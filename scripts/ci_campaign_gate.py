#!/usr/bin/env python
"""CI multi-tenant campaign gate: batched == sequential, faults evict,
the compile cache serves, the ledger judges.

The executable acceptance proof of stencil_tpu/campaign/ on the
8-virtual-device CPU mesh (no TPU needed), B=4 tenants of 16^3:

1. parity + win: ``campaign --mode ab --check-parity`` must exit 0 with
   every tenant's batched final field bit-identical to its sequential
   run AND ``campaign_batched_over_sequential`` > 1.0 — the batched
   program earns its complexity on the smallest CI mesh, not just at
   B=64;
2. fault eviction: a clean campaign and one with
   ``nan@3:tenant=t1:repeat=always`` + ``--max-rollbacks 1``; the
   injected tenant must be EVICTED with the rc-43 evidence bundle under
   ``tenants/t1/`` while every surviving tenant's final snapshot is
   bit-identical to the clean campaign's (``ckpt_tool diff --data``
   per tenant dir) — eviction never stalls or corrupts the slot;
3. compile cache: two same-shape campaigns through ONE CompileCache —
   the second must run with ZERO new ``compile.build`` spans and every
   ``compile.cache_hit`` gauge pinned at 1 (the one-compiled-program-
   serves-every-slot claim, measured not asserted);
4. schema: every produced metrics file passes ``report --validate``
   (the campaign.*/compile.* vocabulary is NAME_FIELDS-gated) and the
   span table renders with the new ``--p99`` column;
5. ledger: two ab runs ingest under run1/run2 labels into a fresh
   ledger and ``perf_tool gate`` judges run2's
   ``campaign.batched_mcells_per_s`` (throughput leg: trips LOW) inside
   run1's band — the bench leg's cross-run regression sentinel, proven
   live.

Exit code 0 only if every stage holds. Run from the repo root:

  python scripts/ci_campaign_gate.py [--size 16] [--steps 6]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def run(cmd, env=None, expect_rc=0, name=""):
    print(f"[campaign-gate] {name}: {' '.join(cmd)}", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    p = subprocess.run(cmd, env=e, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[campaign-gate] {name}: rc={p.returncode}, expected {expect_rc}")
    return p


def campaign(args, extra, name="", tenants=4):
    cmd = [
        PY, "-m", "stencil_tpu.apps.campaign", "--cpu", "8",
        "--tenants", str(tenants), "--slot", "4", "--size",
        str(args.size), "--steps", str(args.steps), "--chunk", "2",
    ] + extra
    p = run(cmd, name=name)
    return json.loads(p.stdout.strip().splitlines()[-1])


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=16)
    p.add_argument("--steps", type=int, default=6)
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="campaign-gate-")
    metrics = []
    try:
        # 1. parity + the batched win at B=4
        m1 = os.path.join(work, "m1.jsonl")
        metrics.append(m1)
        out = campaign(args, ["--mode", "ab", "--check-parity",
                              "--campaign-dir", os.path.join(work, "ab"),
                              "--metrics-out", m1], name="ab-parity")
        if out.get("parity") != "ok":
            raise SystemExit(f"[campaign-gate] parity: {out}")
        ratio = out["batched_over_sequential"]
        if not ratio > 1.0:
            raise SystemExit(
                f"[campaign-gate] batched did not beat sequential: "
                f"ratio={ratio} (batched {out['batched_mcells_per_s']} vs "
                f"sequential {out['sequential_mcells_per_s']} Mcells/s)")
        print(f"[campaign-gate] batched_over_sequential = {ratio}")

        # 2. fault eviction: evidence + survivors bit-identical; a 5th
        # tenant waits in the queue so the evicted lane is BACKFILLED
        clean_dir = os.path.join(work, "clean")
        inj_dir = os.path.join(work, "inj")
        campaign(args, ["--mode", "batched", "--campaign-dir", clean_dir,
                        "--ckpt-every", "2", "--max-rollbacks", "1"],
                 name="clean", tenants=5)
        m2 = os.path.join(work, "m2.jsonl")
        metrics.append(m2)
        out = campaign(args, ["--mode", "batched", "--campaign-dir",
                              inj_dir, "--ckpt-every", "2",
                              "--max-rollbacks", "1",
                              "--rollback-backoff", "0.01",
                              "--inject", "nan@3:tenant=t1:repeat=always",
                              "--metrics-out", m2], name="evict",
                       tenants=5)
        if out.get("evicted") != ["t1"]:
            raise SystemExit(f"[campaign-gate] expected t1 evicted: {out}")
        evidence = os.path.join(inj_dir, "tenants", "t1",
                                "fault-evidence.json")
        with open(evidence) as f:
            ev = json.load(f)
        if ev["rc"] != 43 or "max rollbacks" not in ev["reason"]:
            raise SystemExit(f"[campaign-gate] bad evidence bundle: {ev}")
        recs = [json.loads(l) for l in open(m2) if l.strip()]
        need = {"fault.injected", "health.fault", "recover.rollback",
                "campaign.evict", "campaign.backfill"}
        have = {r["name"] for r in recs}
        if not need <= have:
            raise SystemExit(
                f"[campaign-gate] metrics lack {sorted(need - have)}")
        for tid in ("t0", "t2", "t3", "t4"):
            run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "diff",
                 os.path.join(clean_dir, "tenants", tid),
                 os.path.join(inj_dir, "tenants", tid), "--data"],
                name=f"diff-{tid}")

        # 3. compile cache: the second same-shape campaign is a pure hit
        m3 = os.path.join(work, "m3.jsonl")
        metrics.append(m3)
        code = f"""
import json
import stencil_tpu  # noqa: F401 - installs the jax-version compat shims
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
from stencil_tpu.obs import telemetry
from stencil_tpu.campaign import CampaignDriver, CompileCache, TenantJob
telemetry.configure(metrics_out={m3!r}, app="campaign-gate")
cache = CompileCache()
def jobs(s0):
    return [TenantJob(f"w{{s0}}-{{i}}", ({args.size},) * 3, {args.steps},
                      seed=s0 + i) for i in range(4)]
CampaignDriver(jobs(0), 4, {os.path.join(work, 'wave1')!r}, chunk=2,
               cache=cache).run()
first = dict(cache.stats())
CampaignDriver(jobs(50), 4, {os.path.join(work, 'wave2')!r}, chunk=2,
               cache=cache).run()
print(json.dumps({{"first": first, "second": cache.stats()}}))
"""
        p3 = run([PY, "-c", code], name="cache-waves")
        st = json.loads(p3.stdout.strip().splitlines()[-1])
        if st["second"]["misses"] != st["first"]["misses"]:
            raise SystemExit(
                f"[campaign-gate] second same-shape campaign recompiled: "
                f"{st}")
        recs = [json.loads(l) for l in open(m3) if l.strip()]
        builds = [r for r in recs if r["name"] == "compile.build"]
        hits = [r for r in recs if r["name"] == "compile.cache_hit"]
        if len(builds) != st["first"]["misses"]:
            raise SystemExit(f"[campaign-gate] {len(builds)} compile.build "
                             f"spans, expected {st['first']['misses']}")
        tail = [r["value"] for r in hits[st["first"]["misses"]
                                         + st["first"]["hits"]:]]
        if not tail or any(v != 1 for v in tail):
            raise SystemExit(
                f"[campaign-gate] second wave's compile.cache_hit gauges "
                f"not pinned at 1: {tail}")

        # 4. schema gate + the p99 span column renders
        run([PY, "-m", "stencil_tpu.apps.report"] + metrics + ["--validate"],
            name="report-validate")
        p99 = run([PY, "-m", "stencil_tpu.apps.report", m1, "--p99"],
                  name="report-p99")
        if "p99_s" not in p99.stdout:
            raise SystemExit("[campaign-gate] report --p99 lacks the "
                             "p99_s span column")

        # 5. the bench leg's sentinel, live: ingest two runs, judge run2
        m4 = os.path.join(work, "m4.jsonl")
        campaign(args, ["--mode", "ab", "--check-parity", "--campaign-dir",
                        os.path.join(work, "ab2"), "--metrics-out", m4],
                 name="ab-run2")
        ledger = os.path.join(work, "ledger.jsonl")
        for label, mfile in (("run1", m1), ("run2", m4)):
            run([PY, "-m", "stencil_tpu.apps.perf_tool", "ingest",
                 "--ledger", ledger, "--label", label, "--platform", "cpu",
                 mfile], name=f"ingest-{label}")
        g = run([PY, "-m", "stencil_tpu.apps.perf_tool", "gate",
                 "--ledger", ledger, "--label", "run2",
                 "--metric", "campaign.batched_mcells_per_s",
                 "--min-history", "1", "--rel-tol", "2.0"],
                name="perf-gate")
        if "PASS" not in g.stdout:
            raise SystemExit(f"[campaign-gate] sentinel did not PASS:\n"
                             f"{g.stdout}")

        print("[campaign-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
