"""Probe: astaroth 256^3 with the 2x2x2 partition fully RESIDENT on one
chip — the first hardware number for the flagship MHD workload under
oversubscription, now that resident shards keep the fused Pallas substep
(round 5; the reference's same-GPU fast path under oversubscription,
tx_cuda.cuh:41-113). Mirrors apps/astaroth.py's iteration discipline.

Usage: python scripts/probe_resident_astaroth.py [n] [iters]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import numpy as np

from stencil_tpu.astaroth import config as ac_config
from stencil_tpu.astaroth.integrate import FIELDS, make_astaroth_step, uses_pallas
from stencil_tpu.apps.astaroth import DEFAULT_CONF
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
on_accel = jax.devices()[0].platform != "cpu"
iters = int(sys.argv[2]) if len(sys.argv) > 2 else (30 if on_accel else 2)

info = ac_config.AcMeshInfo()
with open(DEFAULT_CONF) as f:
    ac_config.parse_config(f.read(), info)
info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
info.update_builtin_params()

spec = GridSpec(Dim3(n, n, n), Dim3(2, 2, 2), Radius.constant(3))
mesh = grid_mesh(Dim3(1, 1, 1), jax.devices()[:1])
ex = HaloExchange(spec, mesh)
assert tuple(ex.resident) == (2, 2, 2), ex.resident
pallas = uses_pallas(ex, None)
print(f"resident astaroth {n}^3 2x2x2 on 1 device: pallas={pallas}", flush=True)

step = make_astaroth_step(ex, info, dt=1e-8, overlap=True, iters=iters)
rng = np.random.RandomState(17)
curr = {
    k: shard_blocks((rng.randn(n, n, n) * 0.05).astype(np.float32), spec, mesh)
    for k in FIELDS
}
nxt = {
    k: shard_blocks(np.zeros((n, n, n), np.float32), spec, mesh)
    for k in FIELDS
}
t0 = time.time()
curr, nxt = step(curr, nxt)
hard_sync(curr)
print(f"compile+first {time.time()-t0:.0f}s", flush=True)
st = Statistics()
for _ in range(3):
    t0 = time.perf_counter()
    curr, nxt = step(curr, nxt)
    hard_sync(curr)
    st.insert((time.perf_counter() - t0) / iters)
finite = all(
    bool(np.isfinite(np.asarray(jax.device_get(curr[k]))).all()) for k in FIELDS
)
print(
    f"astaroth-resident {n}^3 2x2x2 on 1 chip: {st.trimean()*1e3:.2f} ms/iter "
    f"(pallas={pallas}, finite={finite}, {iters} iters/dispatch)",
    flush=True,
)
