#!/usr/bin/env python
"""CI plan gate: tune -> persist -> replay with ZERO probes -> bit parity.

The executable acceptance proof of the plan/ subsystem on the 8-virtual-
device CPU mesh (no TPU needed):

1. tune: ``plan_tool autotune`` at 24^3 for Q in {1, 4} (uniform radius
   2, 8 CPU devices) — each first run must MISS the DB (``cache_hit: 0``
   gauge) and execute measured probes, persisting its winner;
2. replay: the same two invocations again — each must be a pure DB hit:
   ``plan.cache_hit`` gauge 1, ``plan.probes_run`` counter 0, and NOT A
   SINGLE ``plan.probe`` span in the metrics JSONL;
3. app wiring: ``jacobi3d --autotune --plan-db`` tunes its own config on
   the first run and replays it probe-free on the second (same gauges,
   via the DistributedDomain knob);
4. bit parity: one exchange under the tuned Q=4 plan must equal the
   ``Method.AXIS_COMPOSED`` default program field-for-field on
   coordinate data (the plan changes the program, never the physics);
5. schema: every produced metrics file passes the telemetry validate
   gate, and ``plan_tool show`` lists exactly the tuned entries.

Exit code 0 only if every stage holds. Run from the repo root:

  python scripts/ci_plan_gate.py [--size 24] [--quantities 1 4]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

PARITY_CHILD = r"""
import sys
import stencil_tpu  # first: applies the jax-compat shims (old-jax containers)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np
from stencil_tpu.apps._bench_common import coord_state, time_exchange
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import Method
from stencil_tpu.plan import db as plandb
from stencil_tpu.plan.ir import PlanChoice, PlanConfig

db_path, size, q = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
db = plandb.load_db(db_path)
cfg = PlanConfig.make(Dim3(size, size, size), Radius.constant(2),
                      ["float32"] * q, 8, "cpu")
entry = plandb.lookup(db, cfg)
assert entry is not None, f"no DB entry for {cfg.key()}"
choice = PlanChoice.from_json(entry["choice"])
# both legs run on the TUNED partition so the stacked layouts (and thus
# every halo cell) are directly comparable; the default leg is the
# AXIS_COMPOSED + batched program realize() would build plan-less
outs = {}
for label, method, batched in (
    ("tuned", Method(choice.method), choice.batch_quantities),
    ("default", Method.AXIS_COMPOSED, True),
):
    r = time_exchange(Dim3(size, size, size), Radius.constant(2), 2,
                      method=method, quantities=q, batch_quantities=batched,
                      partition=choice.partition)
    dd = r["domain"]
    out = dd.halo_exchange(coord_state(dd, q))
    outs[label] = np.stack(
        [np.asarray(jax.device_get(out[i])) for i in sorted(out)]
    )
assert np.array_equal(outs["tuned"], outs["default"]), \
    "tuned plan's exchange disagrees with the AXIS_COMPOSED default"
print("PARITY_OK")
"""


def run(cmd, env=None, expect_rc=0, name=""):
    print(f"[plan-gate] {name}: {' '.join(cmd)}", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    p = subprocess.run(cmd, env=e, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[plan-gate] {name}: rc={p.returncode}, expected {expect_rc}"
        )
    return p


def metrics_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def check_metrics(path, expect_hit: bool, name: str) -> None:
    """The telemetry proof: cache_hit gauge, probes_run counter, and (on
    a hit) the absence of any probe span."""
    recs = metrics_records(path)
    hits = [r["value"] for r in recs
            if r["kind"] == "gauge" and r["name"] == "plan.cache_hit"]
    probes = [r["value"] for r in recs
              if r["kind"] == "counter" and r["name"] == "plan.probes_run"]
    probe_spans = [r for r in recs
                   if r["kind"] == "span" and r["name"] == "plan.probe"]
    if not hits or not probes:
        raise SystemExit(f"[plan-gate] {name}: metrics lack plan.cache_hit/"
                         "plan.probes_run")
    if expect_hit:
        if hits[-1] != 1 or probes[-1] != 0 or probe_spans:
            raise SystemExit(
                f"[plan-gate] {name}: expected a pure DB hit, got "
                f"cache_hit={hits[-1]} probes_run={probes[-1]} "
                f"probe_spans={len(probe_spans)}"
            )
    else:
        if hits[-1] != 0 or probes[-1] < 1:
            raise SystemExit(
                f"[plan-gate] {name}: expected a tuning run with probes, "
                f"got cache_hit={hits[-1]} probes_run={probes[-1]}"
            )


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--quantities", type=int, nargs="+", default=[1, 4])
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="plan-gate-")
    db = os.path.join(work, "plans.json")
    try:
        def tool(q, metrics, name):
            return run(
                [PY, "-m", "stencil_tpu.apps.plan_tool", "autotune",
                 "--cpu", "8", "--db", db,
                 "--x", str(args.size), "--y", str(args.size),
                 "--z", str(args.size), "--radius", "2",
                 "--quantities", str(q), "--probe-iters", "2",
                 "--top-n", "2", "--metrics-out", metrics],
                name=name,
            )

        # 1. tune (DB miss, probes run) / 2. replay (pure hit, zero probes)
        for q in args.quantities:
            m1 = os.path.join(work, f"tune_q{q}.jsonl")
            tool(q, m1, f"tune-q{q}")
            check_metrics(m1, expect_hit=False, name=f"tune-q{q}")
            m2 = os.path.join(work, f"replay_q{q}.jsonl")
            r = tool(q, m2, f"replay-q{q}")
            check_metrics(m2, expect_hit=True, name=f"replay-q{q}")
            if "cache_hit: True" not in r.stdout or "probes_run: 0" not in r.stdout:
                raise SystemExit(f"[plan-gate] replay-q{q} stdout does not "
                                 "report the DB hit")
            run([PY, "-m", "stencil_tpu.apps.report", m1, m2, "--validate"],
                name=f"schema-q{q}")

        # 3. app wiring: jacobi3d --autotune tunes, then replays probe-free
        jm1 = os.path.join(work, "jacobi_tune.jsonl")
        jm2 = os.path.join(work, "jacobi_replay.jsonl")
        jcmd = [PY, "-m", "stencil_tpu.apps.jacobi3d", "--cpu", "8",
                "--x", str(args.size), "--y", str(args.size),
                "--z", str(args.size), "--iters", "2", "--no-weak",
                "--autotune", "--plan-db", db]
        run(jcmd + ["--metrics-out", jm1], name="jacobi-tune")
        check_metrics(jm1, expect_hit=False, name="jacobi-tune")
        run(jcmd + ["--metrics-out", jm2], name="jacobi-replay")
        check_metrics(jm2, expect_hit=True, name="jacobi-replay")
        run([PY, "-m", "stencil_tpu.apps.report", jm1, jm2, "--validate"],
            name="schema-jacobi")

        # 4. bit parity: tuned plan vs the AXIS_COMPOSED default program
        q = max(args.quantities)
        r = run([PY, "-c", PARITY_CHILD, db, str(args.size), str(q)],
                name="parity")
        if "PARITY_OK" not in r.stdout:
            raise SystemExit("[plan-gate] parity child produced no verdict")

        # 5. the DB lists exactly the tuned entries
        r = run([PY, "-m", "stencil_tpu.apps.plan_tool", "show", "--db", db],
                name="show")
        want = len(args.quantities) + 1  # + jacobi's own config
        if f"# {want} entries" not in r.stdout:
            print(r.stdout)
            raise SystemExit(f"[plan-gate] expected {want} DB entries")
        print("[plan-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
