"""Pure substep timing under the tight-x layout (no exchange in the loop):
the round-3 per-substep number for BASELINE.md."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from stencil_tpu.astaroth.config import load_config
from stencil_tpu.astaroth.equations import Constants
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.ops.pallas_astaroth import FIELDS, make_pallas_substep, pick_tiles
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
info, _ = load_config("stencil_tpu/astaroth/astaroth.conf")
c = Constants.from_info(info)
inv_ds = tuple(info.real_params[k] for k in ("AC_inv_dsx", "AC_inv_dsy", "AC_inv_dsz"))
chunk = 60 if n <= 256 else 12
for label, radius in (("tight-x", Radius.constant(3).without_x()),
                      ("inline-x", Radius.constant(3))):
    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), radius)
    p = spec.padded()
    rng = np.random.RandomState(7)
    curr = tuple(jnp.asarray(rng.rand(p.z, p.y, p.x) * 0.1, jnp.float32)
                 for _ in FIELDS)
    out = tuple(jnp.asarray(rng.rand(p.z, p.y, p.x) * 0.1, jnp.float32)
                for _ in FIELDS)
    sub = make_pallas_substep(spec, c, inv_ds, 1, 1e-8)
    fn = jax.jit(lambda cu, ou: jax.lax.fori_loop(
        0, chunk, lambda _, o: sub(cu, o), ou), donate_argnums=(1,))
    t0 = time.time(); out2 = fn(curr, out); hard_sync(out2)
    cs = time.time() - t0
    st = Statistics()
    for _ in range(3):
        t0 = time.perf_counter(); out2 = fn(curr, out2); hard_sync(out2)
        st.insert((time.perf_counter() - t0) / chunk)
    print(f"{label} {n}^3 tiles={pick_tiles(spec)}: "
          f"{st.trimean()*1e3:.2f} ms/substep (compile {cs:.0f}s)", flush=True)
