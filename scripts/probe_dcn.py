"""On-host probe: the hierarchical ICI+DCN exchange A/B — flat vs
two-level at the same config — plus the raw DCN link measurement that
recalibrates ``plan/cost.DEFAULT_CALIBRATION["dcn"]``.

The ISSUE-17 hardware half (ROADMAP #3): the hierarchical plan
dimension (outer DCN-axis split across hosts, inner per-host ICI mesh,
cross-host boundary slabs overlapped behind intra-host work —
parallel/hierarchy.py) is parity-pinned on the STENCIL_VIRTUAL_HOSTS
emulation, but the claim it was built for — DCN latency/bandwidth are
orders of magnitude worse than ICI, and boundary-first overlap hides
them — needs a real multi-host fabric. This probe is the decisive
measurement, staged for ONE multi-host TPU session
(``scripts/launch_multiprocess.sh`` on >= 2 workers):

1. raw DCN link: time ``jax.device_put`` round-trips of exchange-sized
   slabs between a local and a remote-process device, at three sizes —
   the intercept is ``transfer_latency_s``, the slope
   ``wire_bytes_per_s`` (the two modeled constants of the "dcn"
   calibration row; printing them here flips its provenance
   modeled -> measured);
2. hierarchical vs flat composed exchange at the probe config (one
   block per chip, hosts = jax.process_count()): trimean ms/exchange +
   GB/s, with the executed DCN copy census
   (``ex._compiled.last_transfer_count``) printed per leg — the same
   counters analysis/verify_plan.py audits;
3. numbers feed ``DEFAULT_CALIBRATION["dcn"]`` and the plan DB via
   ``plan_tool autotune`` on the multi-host fabric (item-1
   recalibration session).

Needs >= 2 hosts (a single process has no DCN; the hierarchy would be
flat-equivalent). Exits early with one line when run single-host
without ``--cpu-smoke``; ``--cpu-smoke`` runs the full A/B against the
STENCIL_VIRTUAL_HOSTS=2 emulation at a tiny size instead (the
CI-covered path; "DCN" copies there are in-process device_puts, so the
measured constants price host orchestration, not a real network — the
printed calibration is labeled accordingly and must NOT be persisted).

Usage: python scripts/probe_dcn.py [n] [iters]
       python scripts/probe_dcn.py --cpu-smoke
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

cpu_smoke = "--cpu-smoke" in sys.argv
args = [a for a in sys.argv[1:] if a != "--cpu-smoke"]

if cpu_smoke:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["STENCIL_VIRTUAL_HOSTS"] = "2"

import stencil_tpu  # noqa: F401  (jax-compat shims first)
import jax

if cpu_smoke:
    jax.config.update("jax_platforms", "cpu")

from stencil_tpu.parallel.device_topo import host_assignment, virtual_hosts

nhosts = (2 if cpu_smoke and virtual_hosts() else jax.process_count())
if nhosts < 2:
    print("probe_dcn: single host — the DCN level needs >= 2 processes "
          "(scripts/launch_multiprocess.sh), or --cpu-smoke for the "
          "virtual-host emulation path")
    raise SystemExit(0)

import jax.numpy as jnp
import numpy as np

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, NodePartition, Radius
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(args[0]) if args else (32 if cpu_smoke else 256)
iters = int(args[1]) if len(args) > 1 else (4 if cpu_smoke else 20)
ndev = min(8, len(jax.devices()))
if ndev < nhosts:
    print(f"probe_dcn: {ndev} device(s) over {nhosts} hosts — need at "
          "least one device per host")
    raise SystemExit(0)

devs = jax.devices()[:ndev]
assign = host_assignment(devs)

# -- 1. raw DCN link: latency + bandwidth of cross-host device_put ------------
remote = next((d for d, h in zip(devs, assign) if h != assign[0]), None)
print(f"dcn probe: {nhosts} hosts, {ndev} devices, "
      f"{'virtual-host emulation' if cpu_smoke else 'real fabric'}",
      flush=True)
points = []
for mb in (1, 4, 16):
    buf = jnp.zeros((mb * 1024 * 1024 // 4,), jnp.float32)
    buf = jax.device_put(buf, devs[0])
    jax.block_until_ready(buf)
    st = Statistics()
    for _ in range(8):
        t0 = time.perf_counter()
        out = jax.device_put(buf, remote)
        jax.block_until_ready(out)
        st.insert(time.perf_counter() - t0)
    points.append((mb * 1024 * 1024, st.trimean()))
    print(f"  device_put {mb:3d} MiB cross-host: {st.trimean()*1e3:8.3f} ms"
          f"  ({mb * 1024 * 1024 / st.trimean() / 1e9:6.2f} GB/s)",
          flush=True)
# two-point fit: latency intercept + bandwidth slope (the two constants
# of DEFAULT_CALIBRATION["dcn"])
(b0, t0_), (b1, t1_) = points[0], points[-1]
bw = (b1 - b0) / max(t1_ - t0_, 1e-9)
lat = max(t0_ - b0 / bw, 0.0)
tag = ("CPU-emulation figure — do NOT persist; prices host "
       "orchestration, not a network" if cpu_smoke
       else "measured — flips DEFAULT_CALIBRATION['dcn'] provenance")
print(f"  transfer_latency_s ~= {lat:.2e}  wire_bytes_per_s ~= {bw:.3e}"
      f"  ({tag})", flush=True)

# -- 2. hierarchical vs flat composed exchange --------------------------------
part = NodePartition(Dim3(n, n, n), Radius.constant(3), 1, ndev).dim()
axis = "z" if part.z % nhosts == 0 else \
       "y" if part.y % nhosts == 0 else \
       "x" if part.x % nhosts == 0 else None
if axis is None:
    print(f"probe_dcn: no axis of partition {part} divides into "
          f"{nhosts} hosts — pick n/ndev so one does")
    raise SystemExit(0)


def leg(tag, hierarchy):
    spec = GridSpec(Dim3(n, n, n), part, Radius.constant(3))
    mesh = grid_mesh(part, devs)
    ex = HaloExchange(spec, mesh, Method.AXIS_COMPOSED,
                      hierarchy=hierarchy)
    loop = ex.make_loop(iters)
    state = {i: shard_blocks(np.zeros((n,) * 3, np.float32), spec, mesh)
             for i in range(4)}
    state = loop(state)  # compile + warm
    hard_sync(state)
    st = Statistics()
    for _ in range(3):
        t1 = time.perf_counter()
        state = loop(state)
        hard_sync(state)
        st.insert((time.perf_counter() - t1) / iters)
    dcn = (ex._compiled.last_transfer_count if hierarchy else 0)
    gb = ex.bytes_logical([4] * 4) / st.trimean() / 1e9
    print(f"{tag:28s} {st.trimean()*1e3:9.3f} ms/exchange  {gb:8.2f} GB/s"
          f"  dcn_copies={dcn}", flush=True)
    return st.trimean()


print(f"exchange A/B: {n}^3, partition {part}, hierarchy {axis} x "
      f"{nhosts} hosts, fp32 Q=4, {iters} iters/call", flush=True)
t_flat = leg("flat (single-level)", None)
t_hier = leg(f"hierarchical ({axis}{nhosts})", (axis, nhosts))
kind = ("real DCN — the ROADMAP-3 overlap claim" if not cpu_smoke
        else "CPU emulation — host orchestration, not a network")
print(f"hierarchical_over_flat: {t_flat / t_hier:.3f}x ({kind})",
      flush=True)
