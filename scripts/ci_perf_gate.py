#!/usr/bin/env python
"""CI performance-ledger gate: ingest -> trend -> sentinel both directions.

The executable acceptance proof of the cross-run observability layer
(obs/ledger.py + apps/perf_tool.py + obs/trace_export.py) on the
8-virtual-device CPU mesh — no TPU needed:

1. baseline pair: jacobi3d 24^3 runs TWICE with ``--metrics-out``; each
   run's gauge trimeans are ingested into a fresh ledger under labels
   run1/run2, and the sentinel must PASS run2 against run1's band for
   the tracked wall-clock leg (``jacobi.loop_wall_s``);
2. regression trip: a third run is synthetically slowed with the
   fault-injection registry's ``slow:`` kind (``--inject
   slow@3:seconds=S`` — the sleep lands inside the guarded loop, so the
   wall-clock leg inflates while the per-chunk step spans stay clean);
   the sentinel must exit NONZERO and name the tripped leg;
3. ledger schema: the committed LEDGER.jsonl passes ``report --validate
   --ledger`` and ``perf_tool trend`` over it renders the real r01->r05
   trajectory (the 83.1 Gcells/s r05 flagship with its round label);
   a deliberately corrupted copy is REJECTED;
4. trace timeline: a ci_fault_gate-style run (``--inject nan@3`` +
   checkpoints) is exported via ``report --trace-out`` and must validate
   as Chrome-trace JSON with per-(run, proc) lanes and
   ``fault.injected``/``recover.rollback``/``ckpt.save`` instant events;
5. artifacts: the rendered markdown dashboard + trace JSON land in
   ``--out-dir`` for CI upload.

Exit code 0 only if every stage holds. Run from the repo root:

  python scripts/ci_perf_gate.py [--size 24] [--iters 6] [--out-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable
TRACKED_LEG = "jacobi.loop_wall_s"


def run(cmd, expect_rc=0, name=""):
    print(f"[perf-gate] {name}: {' '.join(cmd)}", flush=True)
    p = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[perf-gate] {name}: rc={p.returncode}, expected {expect_rc}")
    return p


def jacobi(args, metrics, extra=(), name=""):
    cmd = [
        PY, "-m", "stencil_tpu.apps.jacobi3d", "--cpu", "8",
        "--x", str(args.size), "--y", str(args.size), "--z", str(args.size),
        "--iters", str(args.iters), "--metrics-out", metrics,
    ] + list(extra)
    return run(cmd, name=name)


def ingest(ledger, metrics, label):
    run([PY, "-m", "stencil_tpu.apps.perf_tool", "ingest",
         "--ledger", ledger, "--label", label, "--platform", "cpu", metrics],
        name=f"ingest-{label}")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--iters", type=int, default=6)
    p.add_argument("--slow-s", type=float, default=8.0,
                   help="injected slowdown (must dwarf CPU-mesh noise)")
    # the tracked leg is a ~0.1 s wall clock on a loaded CI box: single
    # measurements swing several-fold, so the stable band must be wide.
    # The injected 8 s sleep is >50x the baseline — the trip margin stays
    # enormous even at rel_tol 2 (band hi = 3x center).
    p.add_argument("--rel-tol", type=float, default=2.0,
                   help="band floor for the stable pair (CPU timing is "
                        "noisy; the injected slowdown is far larger)")
    p.add_argument("--out-dir", default="",
                   help="keep dashboard + trace here for CI artifacts "
                        "(default: a temp dir, removed)")
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="perf-gate-")
    out_dir = os.path.abspath(args.out_dir) if args.out_dir else work
    os.makedirs(out_dir, exist_ok=True)
    ledger = os.path.join(out_dir, "ledger.jsonl")
    # a stale ledger from a previous invocation would dedup this run's
    # entries away (same metric/config/rev/label keys) and the gate would
    # judge the OLD measurements — every invocation starts fresh
    if os.path.exists(ledger):
        os.remove(ledger)
    try:
        # 1. stable pair -> sentinel PASS
        for i in (1, 2):
            m = os.path.join(work, f"m{i}.jsonl")
            jacobi(args, m, name=f"stable-run{i}")
            ingest(ledger, m, f"run{i}")
        g = run([PY, "-m", "stencil_tpu.apps.perf_tool", "gate",
                 "--ledger", ledger, "--metric", TRACKED_LEG,
                 "--label", "run2", "--rel-tol", str(args.rel_tol)],
                name="gate-stable")
        if f"GATE PASS {TRACKED_LEG}" not in g.stdout:
            raise SystemExit(f"[perf-gate] stable pair did not PASS the "
                             f"sentinel:\n{g.stdout}")

        # 2. injected slowdown -> sentinel TRIPS with the leg named.
        # slow@K sleeps inside the guarded loop (fault/inject.py), so the
        # wall-clock leg inflates while per-chunk step spans stay honest.
        m3 = os.path.join(work, "m3.jsonl")
        jacobi(args, m3,
               extra=["--inject", f"slow@3:seconds={args.slow_s}"],
               name="slowed-run")
        ingest(ledger, m3, "run3")
        g = run([PY, "-m", "stencil_tpu.apps.perf_tool", "gate",
                 "--ledger", ledger, "--metric", TRACKED_LEG,
                 "--label", "run3", "--rel-tol", str(args.rel_tol)],
                expect_rc=1, name="gate-slowed")
        if f"GATE FAIL {TRACKED_LEG}" not in g.stdout:
            raise SystemExit(f"[perf-gate] slowed run did not trip the "
                             f"sentinel by name:\n{g.stdout}")

        # 3. committed ledger: schema-valid, renders the real trajectory
        run([PY, "-m", "stencil_tpu.apps.report", os.path.join(work, "m1.jsonl"),
             "--validate", "--ledger", os.path.join(REPO, "LEDGER.jsonl")],
            name="ledger-schema")
        t = run([PY, "-m", "stencil_tpu.apps.perf_tool", "trend",
                 "--ledger", os.path.join(REPO, "LEDGER.jsonl"),
                 "--metric", "jacobi3d_512_mcells_per_s_per_chip"],
                name="trend-committed")
        if "r05" not in t.stdout or "83059.7" not in t.stdout:
            raise SystemExit(f"[perf-gate] committed LEDGER.jsonl does not "
                             f"render the r05 flagship:\n{t.stdout}")
        # the serve capacity engine's A/B claim (ISSUE 20): the r25 rows
        # must record the elastic engine >= 1.3x the fixed-slot baseline
        # on the mixed-tenant leg
        t = run([PY, "-m", "stencil_tpu.apps.perf_tool", "trend",
                 "--ledger", os.path.join(REPO, "LEDGER.jsonl"),
                 "--metric", "serve_mixed_over_fixed"],
                name="trend-serve-mixed")
        if "r25" not in t.stdout:
            raise SystemExit(f"[perf-gate] committed LEDGER.jsonl lacks the "
                             f"r25 serve_mixed_over_fixed row:\n{t.stdout}")
        ratios = [float(e["value"]) for e in
                  (json.loads(ln) for ln in
                   open(os.path.join(REPO, "LEDGER.jsonl")))
                  if e.get("metric") == "serve_mixed_over_fixed"]
        if not ratios or min(ratios) < 1.3:
            raise SystemExit(f"[perf-gate] serve_mixed_over_fixed must stay "
                             f">= 1.3 (the capacity engine's acceptance "
                             f"floor); ledger has {ratios}")
        # the committed leg-config must drive the sentinel over the two
        # serve legs: every verdict present and direction-aware, rc 0
        # (judged within band) or 2 (all SKIP while history < min_history
        # — the first rounds); rc 1 is a regression trip and fails CI
        cmd = [PY, "-m", "stencil_tpu.apps.perf_tool", "gate",
               "--ledger", os.path.join(REPO, "LEDGER.jsonl"),
               "--metric", "serve_mixed_tenants_per_hour",
               "--metric", "serve_mixed_high_p99_ms",
               "--leg-config", os.path.join(REPO, "perf-legs.json")]
        print(f"[perf-gate] gate-serve-legs: {' '.join(cmd)}", flush=True)
        g = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
        if g.returncode not in (0, 2):
            print(g.stdout)
            print(g.stderr, file=sys.stderr)
            raise SystemExit(f"[perf-gate] serve-leg sentinel tripped "
                             f"(rc={g.returncode})")
        for leg in ("serve_mixed_tenants_per_hour",
                    "serve_mixed_high_p99_ms"):
            if f"GATE FAIL {leg}" in g.stdout or leg not in g.stdout:
                raise SystemExit(f"[perf-gate] serve-leg sentinel verdict "
                                 f"wrong for {leg}:\n{g.stdout}")
        # corruption must be rejected loudly, not aggregated
        bad = os.path.join(work, "bad-ledger.jsonl")
        shutil.copyfile(os.path.join(REPO, "LEDGER.jsonl"), bad)
        with open(bad, "a") as f:
            f.write('{"v": 1, "kind": "perf-ledger", "metric": ""}\n')
        run([PY, "-m", "stencil_tpu.apps.report", os.path.join(work, "m1.jsonl"),
             "--validate", "--ledger", bad], expect_rc=1,
            name="ledger-corruption-rejected")

        # 4. trace timeline from a fault-gate-style self-healing run
        m4 = os.path.join(work, "m4.jsonl")
        jacobi(args, m4,
               extra=["--ckpt-dir", os.path.join(work, "ck"),
                      "--ckpt-every", "2", "--health-every", "2",
                      "--rollback-backoff", "0.05", "--inject", "nan@3"],
               name="fault-run")
        trace = os.path.join(out_dir, "trace.json")
        run([PY, "-m", "stencil_tpu.apps.report", m4, "--trace-out", trace],
            name="trace-export")
        with open(trace) as f:
            tr = json.load(f)
        sys.path.insert(0, REPO)
        from stencil_tpu.obs import trace_export

        errs = trace_export.validate_trace(tr)
        if errs:
            raise SystemExit(f"[perf-gate] invalid trace: {errs[:3]}")
        inst = {e["name"] for e in tr["traceEvents"] if e.get("ph") == "i"}
        need = {"fault.injected", "recover.rollback", "ckpt.save"}
        if not need <= inst:
            raise SystemExit(f"[perf-gate] trace lacks instant markers "
                             f"{sorted(need - inst)} (has {sorted(inst)})")
        lanes = {(e.get("pid"), e.get("tid"))
                 for e in tr["traceEvents"] if e.get("ph") == "X"}
        if not lanes:
            raise SystemExit("[perf-gate] trace has no (run, proc) span lanes")

        # 5. dashboard artifact
        run([PY, "-m", "stencil_tpu.apps.perf_tool", "render",
             "--ledger", ledger,
             "--out", os.path.join(out_dir, "dashboard.md")],
            name="render-dashboard")

        print(f"[perf-gate] PASS (artifacts: {out_dir})")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
