"""Measure the row-tiled multistep at the flagship 768^3 size on the chip.

The full-plane multistep self-capped temporal depth at k=4 at 768^3 (VMEM
staging holds full (py, px) planes — 55.3 Gcells/s vs 79-83 at 512^3,
VERDICT r5 weak #2, scripts/r05_logs/jacobi_768.log). Row-tiled staging
(ops/pallas_stencil.py, plan_multistep_staging) unchains depth from plane
size; this probe A/Bs:

- default plan (row-tiled, k up to the 12 cap) — the new production path;
- temporal_k=4 pin (what the old full-plane kernel could reach).

Done-bar from VERDICT r5 Next #2: >= 70 Gcells/s at 768^3.

  python scripts/probe_rowtile768.py [n] [iters]
  python scripts/probe_rowtile768.py --cpu-smoke   # tiny CPU run
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

cpu_smoke = "--cpu-smoke" in sys.argv
args = [a for a in sys.argv[1:] if a != "--cpu-smoke"]

import jax  # noqa: E402

from stencil_tpu.apps.jacobi3d import run  # noqa: E402
from stencil_tpu.domain.grid import GridSpec  # noqa: E402
from stencil_tpu.geometry import Dim3, Radius  # noqa: E402
from stencil_tpu.ops.pallas_stencil import plan_multistep_staging  # noqa: E402

n = int(args[0]) if len(args) > 0 else 768
iters = int(args[1]) if len(args) > 1 else 60

spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(1).without_x())
k, rows = plan_multistep_staging(spec, 12, 46 * 1024 * 1024)
print(f"{n}^3 staging plan: k={k} rows={rows} "
      f"({'row-tiled' if rows else 'full-plane'})", flush=True)

if jax.devices()[0].platform != "tpu":
    if not cpu_smoke:
        # fail fast and actionably: the probe settles a chip wall-clock
        # question (ROADMAP #2); a CPU run at 768^3 would just churn
        sys.exit("probe_rowtile768: no TPU visible (platform="
                 f"{jax.devices()[0].platform}) — run on the TPU bench host,"
                 " or pass --cpu-smoke for a tiny CPU sanity run")
    print("WARNING: --cpu-smoke — running a tiny CPU smoke instead", flush=True)
    n, iters = 128, 4

for label, cap in (
    ("default plan (row-tiled depth)", None),
    ("k=4 cap (what full-plane staging reached)", "4"),
):
    if cap is None:
        os.environ.pop("STENCIL_TEMPORAL_K_CAP", None)
    else:
        os.environ["STENCIL_TEMPORAL_K_CAP"] = cap
    r = run(n, n, n, iters=iters, weak=False, devices=jax.devices()[:1],
            warmup=1, chunk=min(iters, 30))
    print(f"{label}: {r['iter_trimean_s']*1e3:.3f} ms/iter, "
          f"{r['mcells_per_s_per_dev']:.0f} Mcells/s", flush=True)
os.environ.pop("STENCIL_TEMPORAL_K_CAP", None)
