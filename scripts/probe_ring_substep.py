"""A/B the astaroth sliding-window variants at 512^3 on the chip.

Settles the round-5 floor contradiction (VERDICT r5 weak #1): the closure
summed a 12.7 ms *standalone* window-shift leg into the 70.5 ms substep
floor, but the round-3 in-situ probe measured only 0.4 ms for removing the
shifts inside the kernel — both cannot be additive truths. The ring
variant (ops/pallas_astaroth.py, ``variant="ring"``) removes the shift ops
entirely with CORRECT results, so this probe is the decisive in-situ
measurement:

- delta ~ 12 ms/substep  -> the shifts really serialized at 512^3; the
  ring window recovers more than the 10.5 ms gap to the 60 ms/substep
  target (the 180 ms/iter flagship target reopens and likely falls);
- delta <~ 1 ms/substep -> the shifts hide under DMA/VPU contention; the
  12.7 ms standalone leg was never a floor term and BASELINE.md's closure
  must carry this delta instead.

Bench discipline as bench.py's astaroth legs: fused chunks, untimed
warmup chunk, trimean over chunk means, hard_sync. Run on the TPU host:

  python scripts/probe_ring_substep.py [n] [iters] [chunk]
  python scripts/probe_ring_substep.py --cpu-smoke   # tiny interpret run
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

cpu_smoke = "--cpu-smoke" in sys.argv
args = [a for a in sys.argv[1:] if a != "--cpu-smoke"]

import jax  # noqa: E402

from stencil_tpu.apps.astaroth import run  # noqa: E402

n = int(args[0]) if len(args) > 0 else 512
iters = int(args[1]) if len(args) > 1 else 12
chunk = int(args[2]) if len(args) > 2 else 6

if jax.devices()[0].platform != "tpu":
    if not cpu_smoke:
        # fail fast and actionably: an interpret-mode "measurement" at this
        # size would grind for hours and answer nothing (the probe exists
        # to settle a chip-timing question, ROADMAP #1)
        sys.exit("probe_ring_substep: no TPU visible (platform="
                 f"{jax.devices()[0].platform}) — run on the TPU bench host,"
                 " or pass --cpu-smoke for a tiny interpret-mode sanity run")
    print("WARNING: --cpu-smoke — numbers below are CPU-interpret smoke only",
          flush=True)
    n, iters, chunk = 32, 4, 2

results = {}
for variant in ("shift", "ring"):
    r = run(iters=iters, devices=jax.devices()[:1], dtype="float32",
            nx=n, chunk=chunk, kernel_variant=variant)
    ms = r["iter_trimean_s"] * 1e3
    results[variant] = ms
    print(f"{variant}: {ms:.2f} ms/iter = {ms/3:.2f} ms/substep "
          f"({n}^3, {r['iters_run']} iters)", flush=True)

delta = (results["shift"] - results["ring"]) / 3
print(f"ring saves {delta:.2f} ms/substep "
      f"({'the shifts serialized — floor leg stands' if delta > 6 else 'the shifts hid under DMA/VPU — retire the 12.7 ms leg'})",
      flush=True)
