"""A/B the astaroth sliding-window variants at 512^3 on the chip.

Settles the round-5 floor contradiction (VERDICT r5 weak #1): the closure
summed a 12.7 ms *standalone* window-shift leg into the 70.5 ms substep
floor, but the round-3 in-situ probe measured only 0.4 ms for removing the
shifts inside the kernel — both cannot be additive truths. The ring
variant (ops/pallas_astaroth.py, ``variant="ring"``) removes the shift ops
entirely with CORRECT results, so this probe is the decisive in-situ
measurement:

- delta ~ 12 ms/substep  -> the shifts really serialized at 512^3; the
  ring window recovers more than the 10.5 ms gap to the 60 ms/substep
  target (the 180 ms/iter flagship target reopens and likely falls);
- delta <~ 1 ms/substep -> the shifts hide under DMA/VPU contention; the
  12.7 ms standalone leg was never a floor term and BASELINE.md's closure
  must carry this delta instead.

Bench discipline as bench.py's astaroth legs: fused chunks, untimed
warmup chunk, trimean over chunk means, hard_sync. Run on the TPU host:

  python scripts/probe_ring_substep.py [n] [iters] [chunk]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax  # noqa: E402

from stencil_tpu.apps.astaroth import run  # noqa: E402

n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
iters = int(sys.argv[2]) if len(sys.argv) > 2 else 12
chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 6

if jax.devices()[0].platform != "tpu":
    print("WARNING: no TPU — numbers below are CPU-interpret smoke only",
          flush=True)
    n, iters, chunk = 32, 4, 2

results = {}
for variant in ("shift", "ring"):
    r = run(iters=iters, devices=jax.devices()[:1], dtype="float32",
            nx=n, chunk=chunk, kernel_variant=variant)
    ms = r["iter_trimean_s"] * 1e3
    results[variant] = ms
    print(f"{variant}: {ms:.2f} ms/iter = {ms/3:.2f} ms/substep "
          f"({n}^3, {r['iters_run']} iters)", flush=True)

delta = (results["shift"] - results["ring"]) / 3
print(f"ring saves {delta:.2f} ms/substep "
      f"({'the shifts serialized — floor leg stands' if delta > 6 else 'the shifts hid under DMA/VPU — retire the 12.7 ms leg'})",
      flush=True)
