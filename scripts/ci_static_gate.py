#!/usr/bin/env python
"""CI static-analysis gate: the executable acceptance proof of
stencil_tpu/analysis/ (no TPU needed — 8 virtual CPU devices).

1. clean tree: ``lint_tool lint`` exits 0 against the committed tree
   and its baseline;
2. every shipped rule FIRES: each rule's deliberately-bad fixture must
   produce exactly that rule's finding with exit 1 (a gate that cannot
   detect anything proves nothing) — and the inline
   ``# lint: disable=<rule>`` suppression silences it again;
3. plan conformance: ``lint_tool verify-plan`` agrees for all four
   exchange methods on the CPU mesh (exit 0), and TRIPS (exit 1) when
   an IR prediction is perturbed via ``--perturb-collectives``;
   an infeasible sweep (27-block partition on 8 devices) degrades
   loudly with exit 2 and no traceback;
4. jit audit: the clean jacobi chunk loop PASSES; the injected-
   recompile and injected-host-sync fixtures both FAIL with exit 1;
5. schema: every metrics file the auditors produced passes
   ``report --validate`` (the ``analysis.*`` vocabulary is gated like
   every other subsystem's).

Artifacts (``--out-dir``): the lint/sweep/audit JSON documents + the
metrics JSONL.

Run from the repo root:  python scripts/ci_static_gate.py
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

# -- per-rule bad fixtures (each must fire EXACTLY its rule) ------------------

FIXTURES = {
    # nested + aliased import in a file-path-loaded module
    "pure-stdlib": ("obs/watchdog.py", """\
import os

def beat():
    import numpy as np  # nested: still forbidden at any depth
    return np.zeros(3)
"""),
    "telemetry-vocab": ("lib/metrics_site.py", """\
def emit(rec):
    rec.gauge("recover.rollbck", 1.0)  # typo'd vocabulary name
"""),
    "atomic-write": ("lib/writer.py", """\
import json

def save(path, doc):
    with open(path, "w") as f:
        json.dump(doc, f)
"""),
    "no-bare-assert": ("lib/api_mod.py", """\
def realize(n):
    assert n >= 1, "need at least one device"
    return n
"""),
    "fstring-placeholder": ("lib/errors.py", """\
def fail(name):
    raise ValueError("unknown method {name}")
"""),
    "host-sync-in-hot-loop": ("lib/hot.py", """\
import time
import jax

def make_step():
    def body(x):
        t = time.time()  # trace-time constant burial
        return x + t
    return jax.jit(body)
"""),
}

SUPPRESSED_SUFFIX = {
    # the same bad line with an inline disable pragma: must be clean
    "no-bare-assert": ("lib/api_ok.py", """\
def realize(n):
    assert n >= 1  # lint: disable=no-bare-assert
    return n
"""),
}


def run(args, **kw):
    print(f"+ {' '.join(args)}", flush=True)
    return subprocess.run(args, cwd=REPO, capture_output=True, text=True,
                          **kw)


def must(cond, what, proc=None):
    if cond:
        print(f"  ok: {what}")
        return
    print(f"FAILED: {what}", file=sys.stderr)
    if proc is not None:
        print(proc.stdout[-4000:], file=sys.stderr)
        print(proc.stderr[-4000:], file=sys.stderr)
    sys.exit(1)


def save_artifact(out_dir, name, text):
    if not out_dir:
        return
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, name), "w") as f:
        f.write(text)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="",
                    help="write the JSON documents + metrics here "
                         "(CI artifact dir)")
    args = ap.parse_args()
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    # 1. the committed tree lints clean
    p = run([PY, "-m", "stencil_tpu.apps.lint_tool", "lint", "--json"],
            env=env)
    save_artifact(args.out_dir, "lint.json", p.stdout)
    must(p.returncode == 0, "tree lints clean (rc 0)", p)
    doc = json.loads(p.stdout)
    must(doc["new"] == 0 and not doc["errors"],
         "zero new findings, zero engine errors", p)

    # 2. every rule fires on its bad fixture, and the pragma silences it
    tmp = tempfile.mkdtemp(prefix="static-gate-")
    try:
        for rule, (relpath, src) in FIXTURES.items():
            fpath = os.path.join(tmp, relpath)
            os.makedirs(os.path.dirname(fpath), exist_ok=True)
            with open(fpath, "w") as f:
                f.write(src)
            p = run([PY, "-m", "stencil_tpu.apps.lint_tool", "lint",
                     fpath, "--json", "--baseline",
                     os.path.join(tmp, "empty-baseline.json")], env=env)
            must(p.returncode == 1, f"rule {rule} fixture exits 1", p)
            got = json.loads(p.stdout)
            fired = {f["rule"] for f in got["findings"]}
            must(fired == {rule},
                 f"rule {rule} fires exactly (got {sorted(fired)})", p)
        for rule, (relpath, src) in SUPPRESSED_SUFFIX.items():
            fpath = os.path.join(tmp, relpath)
            with open(fpath, "w") as f:
                f.write(src)
            p = run([PY, "-m", "stencil_tpu.apps.lint_tool", "lint",
                     fpath, "--json"], env=env)
            must(p.returncode == 0,
                 f"inline disable silences {rule} (rc 0)", p)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # 3. plan conformance: agree, trip when perturbed, degrade loudly
    metrics = os.path.join(args.out_dir or tempfile.gettempdir(),
                           "static-gate-metrics.jsonl")
    if os.path.exists(metrics):
        os.remove(metrics)
    p = run([PY, "-m", "stencil_tpu.apps.lint_tool", "verify-plan",
             "--cpu", "8", "--json", "--metrics-out", metrics], env=env)
    save_artifact(args.out_dir, "plan-sweep.json", p.stdout)
    must(p.returncode == 0, "verify-plan agrees on the CPU mesh (rc 0)", p)
    doc = json.loads(p.stdout)
    methods = {v["method"] for v in doc["verdicts"] if not v["skipped"]}
    must(methods == {"axis-composed", "direct26", "auto-spmd",
                     "remote-dma"},
         f"all four methods checked (got {sorted(methods)})", p)
    must(doc["failed"] == 0 and doc["checked"] > 0,
         f"{doc['checked']} configs agree", p)

    p = run([PY, "-m", "stencil_tpu.apps.lint_tool", "verify-plan",
             "--cpu", "8", "--partitions", "2x2x2", "--quantities", "f32",
             "--methods", "axis-composed", "--perturb-collectives", "1"],
            env=env)
    must(p.returncode == 1, "perturbed IR prediction TRIPS (rc 1)", p)

    p = run([PY, "-m", "stencil_tpu.apps.lint_tool", "verify-plan",
             "--cpu", "8", "--partitions", "3x3x3", "--quantities", "f32"],
            env=env)
    must(p.returncode == 2, "infeasible sweep degrades to rc 2", p)
    must("Traceback" not in p.stderr, "…with a message, not a traceback", p)

    # 4. jit audit: clean pass, injected fixtures fail
    p = run([PY, "-m", "stencil_tpu.apps.lint_tool", "jit-audit",
             "--cpu", "8", "--json", "--metrics-out", metrics], env=env)
    save_artifact(args.out_dir, "jit-audit.json", p.stdout)
    must(p.returncode == 0, "clean jacobi chunk loop PASSES", p)
    doc = json.loads(p.stdout)
    must(doc["recompiles"] == 0 and not doc["transfer_trips"],
         "zero post-warmup recompiles, zero transfers", p)
    for inject in ("recompile", "host-sync"):
        p = run([PY, "-m", "stencil_tpu.apps.lint_tool", "jit-audit",
                 "--cpu", "8", "--inject", inject], env=env)
        must(p.returncode == 1, f"injected {inject} FAILS the audit", p)

    # 5. the analysis.* records pass the telemetry schema gate
    p = run([PY, "-m", "stencil_tpu.apps.report", metrics, "--validate"],
            env=env)
    must(p.returncode == 0, "analysis.* metrics pass report --validate", p)
    if args.out_dir and os.path.dirname(metrics) != args.out_dir:
        shutil.copy(metrics, os.path.join(args.out_dir,
                                          "static-gate-metrics.jsonl"))

    print("static gate: all stages passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
