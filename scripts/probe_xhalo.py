"""Out-of-line x-halo layout experiment (VERDICT r2 item 4).

The aligned layout pads a 512-wide radius-1 row to 640 lanes (off.x=1 plus
round-up), so every slab DMA moves 1.25x the logical bytes — the one-step
sweep's x-amplification. This probe benchmarks a TIGHT-x variant: blocks
stored (pz, py, nx) with NO inline x halos (px == nx), the periodic x
neighborhood formed by in-VMEM lane rolls (the single-chip limit of the
reference's out-of-line pack buffers, src/packer.cu:66-107). Both variants
run the same pipelined double-buffered DMA structure, no sphere sel, so
the delta isolates the layout.

Usage: python scripts/probe_xhalo.py [n]
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
TZ, TY = 2, 128


def make_sweep(px, xo, tight):
    """One radius-1 jacobi sweep over (pz, py, px); z/y/x periodic.
    ``tight``: px == nx, x wrap via lane rolls; else inline x halo at
    [xo-1, xo+nx] with in-VMEM edge-column copies (the production layout).
    z wrap via wrapped plane DMAs is replaced by torus indexing on the
    grid (identical traffic); y wrap by row copies as in the production
    kernel (full-row slabs not used: ty=128 tiling, wrap rows staged)."""
    nz = ny = nx = n
    pz, py = n + 2, ((8 + n + 1 + 7) // 8) * 8
    yo, zo = 8, 1
    n_tz, n_ty = nz // TZ, ny // TY
    n_tiles = n_tz * n_ty
    rows_in = TY + 16

    def kernel(curr, out_hbm, in_v, out_v, wy_v, s_in, s_out, s_w):
        t = pl.program_id(0)
        slot, nslot = t % 2, (t + 1) % 2

        def tile_zy(ti):
            return zo + (ti // n_ty) * TZ, yo + (ti % n_ty) * TY

        def in_dma(s, ti):
            z0, y0 = tile_zy(ti)
            return pltpu.make_async_copy(
                curr.at[pl.ds(z0 - 1, TZ + 2), pl.ds(y0 - 8, rows_in)],
                in_v.at[s], s_in.at[s])

        def out_dma(s, ti):
            z0, y0 = tile_zy(ti)
            return pltpu.make_async_copy(
                out_v.at[s], out_hbm.at[pl.ds(z0, TZ), pl.ds(y0, TY)],
                s_out.at[s])

        @pl.when(t == 0)
        def _():
            in_dma(slot, t).start()

        @pl.when(t + 1 < n_tiles)
        def _():
            in_dma(nslot, t + 1).start()

        in_dma(slot, t).wait()

        z0, y0 = tile_zy(t)
        zi, yi = t // n_ty, t % n_ty
        # z wrap: edge tiles refetch the opposite face plane
        @pl.when(zi == 0)
        def _():
            cp = pltpu.make_async_copy(
                curr.at[pl.ds(zo + nz - 1, 1), pl.ds(y0 - 8, rows_in)],
                in_v.at[slot, pl.ds(0, 1)], s_w)
            cp.start(); cp.wait()

        @pl.when(zi == n_tz - 1)
        def _():
            cp = pltpu.make_async_copy(
                curr.at[pl.ds(zo, 1), pl.ds(y0 - 8, rows_in)],
                in_v.at[slot, pl.ds(TZ + 1, 1)], s_w)
            cp.start(); cp.wait()

        # y wrap rows staged through scratch
        @pl.when(yi == 0)
        def _():
            cp = pltpu.make_async_copy(
                curr.at[pl.ds(z0, TZ), pl.ds(yo + ny - 8, 8)], wy_v, s_w)
            cp.start(); cp.wait()
            in_v[slot, 1:TZ + 1, 7, :] = wy_v[:, 7, :]

        @pl.when(yi == n_ty - 1)
        def _():
            cp = pltpu.make_async_copy(
                curr.at[pl.ds(z0, TZ), pl.ds(yo, 8)], wy_v, s_w)
            cp.start(); cp.wait()
            in_v[slot, 1:TZ + 1, 8 + TY, :] = wy_v[:, 0, :]

        ctr = slice(8, 8 + TY)
        c = in_v[slot, 1:TZ + 1]
        if tight:
            mid = c[:, ctr, :]
            xm = pltpu.roll(mid, 1, 2)        # col j reads j-1 (wraps)
            xp = pltpu.roll(mid, nx - 1, 2)   # col j reads j+1 (wraps)
            avg = (xm + xp
                   + c[:, 7:7 + TY, :] + c[:, 9:9 + TY, :]
                   + in_v[slot, 0:TZ, ctr, :] + in_v[slot, 2:TZ + 2, ctr, :]
                   ) / 6.0
            out_v[slot] = avg
        else:
            in_v[slot, :, :, xo - 1] = in_v[slot, :, :, xo + nx - 1]
            in_v[slot, :, :, xo + nx] = in_v[slot, :, :, xo]
            xs = slice(xo, xo + nx)
            avg = (c[:, ctr, xo - 1:xo + nx - 1] + c[:, ctr, xo + 1:xo + nx + 1]
                   + c[:, 7:7 + TY, xs] + c[:, 9:9 + TY, xs]
                   + in_v[slot, 0:TZ, ctr, xs] + in_v[slot, 2:TZ + 2, ctr, xs]
                   ) / 6.0
            out_v[slot] = c[:, ctr, :]
            out_v[slot, :, :, xs] = avg

        @pl.when(t >= 2)
        def _():
            out_dma(slot, t - 2).wait()
        out_dma(slot, t).start()

        @pl.when(t == n_tiles - 1)
        def _():
            if n_tiles >= 2:
                out_dma(nslot, t - 1).wait()
            out_dma(slot, t).wait()

    return pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        out_shape=jax.ShapeDtypeStruct((pz, py, px), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, TZ + 2, rows_in, px), jnp.float32),
            pltpu.VMEM((2, TZ, TY, px), jnp.float32),
            pltpu.VMEM((TZ, 8, px), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), has_side_effects=True,
            vmem_limit_bytes=100 * 1024 * 1024),
    )


def bench(label, px, xo, tight):
    pz, py = n + 2, ((8 + n + 1 + 7) // 8) * 8
    rng = np.random.RandomState(0)
    x0 = jnp.asarray(rng.rand(pz, py, px), jnp.float32)
    fn = make_sweep(px, xo, tight)
    chunk = 120

    def many(a):
        def body(_, cn):
            c, nxt_ = cn
            return (fn(c), c)
        return jax.lax.fori_loop(0, chunk, body, (a, a))[0]

    g = jax.jit(many)
    t0 = time.time(); r = g(x0); hard_sync(r)
    cs = time.time() - t0
    st = Statistics()
    for _ in range(3):
        t0 = time.perf_counter(); r = g(r); hard_sync(r)
        st.insert((time.perf_counter() - t0) / chunk)
    ms = st.trimean() * 1e3
    print(f"{label}: {ms:.3f} ms/step = {n**3/st.trimean()/1e6:.0f} Mcells/s "
          f"(compile {cs:.0f}s)", flush=True)


print("devices:", jax.devices(), flush=True)
bench("inline-x (px=640)", ((1 + n + 1 + 127) // 128) * 128, 1, False)
bench(f"tight-x (px={n})", n, 0, True)
