"""fp64 + overlap compile experiment — thin wrapper.

Round 3 this script built the per-substep interior/exterior overlap
structure at 32^3 and recorded the bounded negative (compile > 25 min:
7 regions x 3 substeps x ~10x f64 emulation expansion). Round 4 replaced
that structure with the hoisted-exchange overlap iteration (9 integrate
bodies — astaroth/integrate.py hoisted_overlap_iteration), and the
experiment lives in probe_f64.py behind STENCIL_PROBE_F64_OVERLAP=1.
This wrapper just sets the flag so the historical entry point keeps
working:

    python scripts/probe_f64_overlap.py [sizes...]
"""
import os
import runpy
import sys

os.environ["STENCIL_PROBE_F64_OVERLAP"] = "1"
sys.argv = [sys.argv[0]] + (sys.argv[1:] or ["32"])
runpy.run_path(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "probe_f64.py"),
    run_name="__main__",
)
