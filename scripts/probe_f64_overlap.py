"""Is the interior/exterior overlap structure the fp64 compile-time
explosion? (round-2 negative result said 32^3 fp64 didn't compile in 25
min; the plain serialized path compiles in ~2 min)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np
from stencil_tpu.astaroth import config as ac_config
from stencil_tpu.astaroth.integrate import FIELDS, make_astaroth_step
from stencil_tpu.apps.astaroth import DEFAULT_CONF
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.utils.sync import hard_sync

n = 32
info = ac_config.AcMeshInfo()
with open(DEFAULT_CONF) as f:
    ac_config.parse_config(f.read(), info)
info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
info.update_builtin_params()
spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3))
mesh = grid_mesh(spec.dim, jax.devices()[:1])
ex = HaloExchange(spec, mesh)
rng = np.random.RandomState(0)
fields = {k: rng.randn(n, n, n) * 0.05 for k in FIELDS}
fields["lnrho"] = fields["lnrho"] + 0.5
step = make_astaroth_step(ex, info, dt=1e-8, overlap=True,
                          use_pallas=False, dtype="float64")
curr = {k: shard_blocks(fields[k], spec, mesh, dtype=np.float64) for k in FIELDS}
nxt = {k: shard_blocks(np.zeros((n, n, n)), spec, mesh, dtype=np.float64)
       for k in FIELDS}
t0 = time.time()
curr, nxt = step(curr, nxt)
hard_sync(curr)
print(f"f64 {n}^3 overlap=True: compile+run {time.time()-t0:.0f}s", flush=True)
