"""Round-3 astaroth numbers for BASELINE.md: 256^3 and 512^3 iteration
times with the sliding-window substep kernel (fused chunks)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
from stencil_tpu.apps.astaroth import run as asta_run

for nx, iters, chunk in ((256, 60, 30), (512, 12, 6)):
    r = asta_run(iters=iters, devices=jax.devices()[:1], dtype="float32",
                 nx=nx, chunk=chunk)
    ms = r["iter_trimean_s"] * 1e3
    mc = nx ** 3 / r["iter_trimean_s"] / 1e6
    print(f"astaroth {nx}^3 fp32: {ms:.1f} ms/iter trimean "
          f"({mc:.0f} Mcells/s), iters_run={r['iters_run']}", flush=True)
