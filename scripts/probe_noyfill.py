"""Timing probe: the 512^3 tight-x multistep with the per-stage y-ring
fill copies REMOVED (results wrong) — sizes the payoff of a tight-y
(zero-y-radius, sublane-roll) layout before building it."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
import stencil_tpu.ops.pallas_stencil as ps
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
k = 10
spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(1).without_x())
p = spec.padded()
rng = np.random.RandomState(0)
x0 = jnp.asarray(rng.rand(p.z, p.y, p.x), jnp.float32)

for label, patch in (("with-yfill", False), ("no-yfill", True)):
    # _skip_yfill is an explicit kernel-builder parameter (not module
    # state, which would silently corrupt kernels built later — ADVICE r3)
    fn = ps.make_pallas_jacobi_multistep(spec, k, _skip_yfill=patch)
    chunk = 12

    def many(a):
        def body(_, cn):
            c, x = cn
            return (fn(c, x), c)
        return jax.lax.fori_loop(0, chunk, body, (a, a))[0]

    g = jax.jit(many)
    t0 = time.time(); r = g(x0); hard_sync(r)
    cs = time.time() - t0
    st = Statistics()
    for _ in range(3):
        t0 = time.perf_counter(); r = g(r); hard_sync(r)
        st.insert((time.perf_counter() - t0) / chunk / k)
    print(f"{label} {n}^3 k={k}: {st.trimean()*1e3:.3f} ms/step "
          f"({n**3/ (st.trimean())/1e6:.0f} Mcells/s) compile {cs:.0f}s",
          flush=True)
