#!/usr/bin/env python
"""CI attribution gate: predict -> measure -> refit -> drift sentinel.

The executable acceptance proof of the plan observatory
(obs/attribution.py + plan/calibrate.py + the drift sentinel) on the
8-virtual-device CPU mesh — no TPU needed:

1. evidence run: jacobi3d 24^3 ``--autotune`` against a FRESH plan DB
   emits schema-valid ``plan.attrib.phase`` records (the probe sweep
   contributes multi-method points; the epilogue exchange loop
   contributes the ``jacobi.exchange`` phase) plus the run's
   ``plan.fingerprint`` stamp;
2. refit: ``plan_tool calibrate --from-metrics --phase jacobi.exchange``
   fits a cpu calibration row with ``fitted(n=…, r2=…)`` provenance and
   installs it in the DB; ``calibration show`` round-trips it and the
   static ranking (``plan_tool explain``) repriced under the fitted
   constants still picks an axis-composed plan;
3. healthy judge: a second jacobi run auto-installs the fitted row
   (DB -> autotune -> prediction), and ``perf_tool drift`` PASSES its
   measured exchange phase against the fitted prediction;
4. drift trip: a third run with ``--inject slow@{iters+2}:seconds=S``
   lands the sleep inside the timed epilogue window, and ``perf_tool
   drift`` exits NONZERO naming ``jacobi.exchange``;
5. timed audit: ``verify_plan --time`` passes the fitted axis-composed
   band healthy and trips under ``--time-slow``;
6. timeline: the drifted run's trace renders the paired
   predicted/measured counter tracks and the ``calibration.drift``
   instant marker, and validates as Chrome-trace JSON;
7. artifacts: metrics, the fitted plan DB, and the trace land in
   ``--out-dir`` for CI upload.

Exit code 0 only if every stage holds. Run from the repo root:

  python scripts/ci_attrib_gate.py [--size 24] [--iters 10] [--out-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable
PHASE = "jacobi.exchange"


def run(cmd, expect_rc=0, name=""):
    print(f"[attrib-gate] {name}: {' '.join(cmd)}", flush=True)
    p = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[attrib-gate] {name}: rc={p.returncode}, expected {expect_rc}")
    return p


def jacobi(args, metrics, run_id, db, extra=(), name=""):
    cmd = [
        PY, "-m", "stencil_tpu.apps.jacobi3d", "--cpu", "8",
        "--x", str(args.size), "--y", str(args.size), "--z", str(args.size),
        "--iters", str(args.iters), "--no-weak",
        "--autotune", "--plan-db", db,
        "--metrics-out", metrics, "--run-id", run_id,
    ] + list(extra)
    return run(cmd, name=name)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--slow-s", type=float, default=6.0,
                   help="injected epilogue slowdown; spread over the "
                        "~10-iter timed window it must still dwarf the "
                        "millisecond-scale exchange")
    # the fitted prediction and the next run's measured exchange sit on
    # the same fabric minutes apart, but a loaded CI box still swings
    # single measurements; 0.75 ([0.25x, 1.75x] of measured) absorbs
    # that while an under-prediction can still trip (rel_tol must stay
    # < 1 — at 1 the band's low edge hits zero; see obs/attribution.py)
    p.add_argument("--rel-tol", type=float, default=0.75)
    p.add_argument("--out-dir", default="",
                   help="keep metrics + fitted DB + trace here for CI "
                        "artifacts (default: a temp dir, removed)")
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="attrib-gate-")
    out_dir = os.path.abspath(args.out_dir) if args.out_dir else work
    os.makedirs(out_dir, exist_ok=True)
    db = os.path.join(out_dir, "plan.json")
    # a stale DB would replay a previous invocation's plan AND its
    # calibration — every invocation fits fresh evidence
    if os.path.exists(db):
        os.remove(db)
    try:
        # 1. evidence run: attribution records validate, fingerprint lands
        m_a = os.path.join(out_dir, "runA.jsonl")
        jacobi(args, m_a, "attrib-runA", db, name="evidence-run")
        run([PY, "-m", "stencil_tpu.apps.report", m_a, "--validate"],
            name="evidence-schema")
        recs = [json.loads(ln) for ln in open(m_a)]
        names = {r["name"] for r in recs}
        if "plan.attrib.phase" not in names:
            raise SystemExit("[attrib-gate] run A emitted no "
                             "plan.attrib.phase records")
        if "plan.fingerprint" not in names:
            raise SystemExit("[attrib-gate] run A carries no "
                             "plan.fingerprint stamp")
        phases = {r.get("phase") for r in recs
                  if r["name"] == "plan.attrib.phase"}
        if PHASE not in phases:
            raise SystemExit(f"[attrib-gate] no {PHASE} attribution in "
                             f"run A (has {sorted(phases)})")

        # 2. refit + round-trip + ranking sanity
        c = run([PY, "-m", "stencil_tpu.apps.plan_tool", "calibrate",
                 "--db", db, "--from-metrics", m_a, "--platform", "cpu",
                 "--phase", PHASE,
                 "--metrics-out", os.path.join(out_dir, "calibrate.jsonl")],
                name="calibrate")
        if "fitted(n=" not in c.stdout:
            raise SystemExit(f"[attrib-gate] calibrate printed no fitted "
                             f"provenance:\n{c.stdout}")
        s = run([PY, "-m", "stencil_tpu.apps.plan_tool", "calibration",
                 "show", "--db", db], name="calibration-show")
        if "cpu,fitted(n=" not in s.stdout:
            raise SystemExit(f"[attrib-gate] fitted row did not round-trip "
                             f"through the DB:\n{s.stdout}")
        e = run([PY, "-m", "stencil_tpu.apps.plan_tool", "explain",
                 "--db", db, "--x", str(args.size), "--y", str(args.size),
                 "--z", str(args.size), "--ndev", "8", "--radius", "1",
                 "--quantities", "1", "--platform", "cpu"],
                name="explain-repriced")
        ranking = [ln for ln in e.stdout.splitlines()
                   if "ms/step" in ln]
        if not ranking or "axis-composed" not in ranking[0]:
            raise SystemExit(f"[attrib-gate] repriced static ranking no "
                             f"longer picks composed:\n{e.stdout}")
        if "calibration: fitted(n=" not in e.stdout:
            raise SystemExit(f"[attrib-gate] explain did not price with "
                             f"the fitted calibration:\n{e.stdout}")

        # 3. healthy run under the fitted calibration -> drift PASS
        m_b = os.path.join(out_dir, "runB.jsonl")
        jacobi(args, m_b, "attrib-runB", db, name="healthy-run")
        g = run([PY, "-m", "stencil_tpu.apps.perf_tool", "drift",
                 "--metrics", m_b, "--phase", PHASE,
                 "--rel-tol", str(args.rel_tol)], name="drift-healthy")
        if f"DRIFT PASS" not in g.stdout or "fitted(n=" not in g.stdout:
            raise SystemExit(f"[attrib-gate] healthy run did not PASS "
                             f"under the fitted calibration:\n{g.stdout}")

        # 4. seeded slowdown in the timed epilogue window -> drift TRIPS.
        # slow@ steps past --iters fire inside the attribution loop
        # (apps/jacobi3d.py epilogue), inflating one measured sample.
        m_c = os.path.join(out_dir, "runC.jsonl")
        jacobi(args, m_c, "attrib-runC", db,
               extra=["--inject",
                      f"slow@{args.iters + 2}:seconds={args.slow_s}"],
               name="drifted-run")
        g = run([PY, "-m", "stencil_tpu.apps.perf_tool", "drift",
                 "--metrics", m_c, "--phase", PHASE,
                 "--rel-tol", str(args.rel_tol)],
                expect_rc=1, name="drift-tripped")
        if f"DRIFT FAIL" not in g.stdout or PHASE not in g.stdout:
            raise SystemExit(f"[attrib-gate] drifted run did not trip the "
                             f"sentinel by phase name:\n{g.stdout}")
        if f"CALIBRATION DRIFT: {PHASE}" not in g.stderr:
            raise SystemExit(f"[attrib-gate] drift trip did not name the "
                             f"phase on stderr:\n{g.stderr}")

        # 5. the timed structural audit: fitted band healthy, trips under
        # the --time-slow proof knob (verify_plan.audit_time)
        vp = [PY, "-m", "stencil_tpu.apps.lint_tool", "verify-plan",
              "--cpu", "8", "--size", "16", "--time", "4",
              "--time-db", db, "--methods", "axis-composed"]
        run(vp, name="verify-time-healthy")
        run(vp + ["--time-slow", "2"], expect_rc=1,
            name="verify-time-tripped")

        # 6. timeline: paired counters + the drift instant marker
        trace = os.path.join(out_dir, "attrib-trace.json")
        run([PY, "-m", "stencil_tpu.apps.report", m_c,
             "--trace-out", trace], name="trace-export")
        with open(trace) as f:
            tr = json.load(f)
        sys.path.insert(0, REPO)
        from stencil_tpu.obs import trace_export

        errs = trace_export.validate_trace(tr)
        if errs:
            raise SystemExit(f"[attrib-gate] invalid trace: {errs[:3]}")
        counters = {e["name"] for e in tr["traceEvents"]
                    if e.get("ph") == "C"}
        need = {f"plan.attrib.{PHASE}.predicted_s",
                f"plan.attrib.{PHASE}.measured_s"}
        if not need <= counters:
            raise SystemExit(f"[attrib-gate] trace lacks paired counters "
                             f"{sorted(need - counters)}")
        markers = {e["name"] for e in tr["traceEvents"]
                   if e.get("ph") == "i"}
        if "calibration.drift" not in markers:
            raise SystemExit(f"[attrib-gate] trace lacks the "
                             f"calibration.drift marker (has "
                             f"{sorted(markers)})")

        print(f"[attrib-gate] PASS (artifacts: {out_dir})")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
