#!/usr/bin/env python
"""CI persistent whole-chunk gate: the ISSUE-16 acceptance proof on the
CPU mesh.

Four stages, exit 0 only if every one holds:

1. **parity + launch census**: at 24^3 on the 2x2x2 8-virtual-device
   mesh, the PERSISTENT chunk loop (``HaloExchange(Method.REMOTE_DMA,
   persistent=True)`` — ONE deep radius*k exchange + ONE k-substep chunk
   program per chunk) lands bit-identical to the AXIS_COMPOSED baseline
   AND to the per-step plain REMOTE_DMA loop at k in {2, 4}, uniform AND
   uneven partitions, with the measured ``last_launches_per_chunk``
   pinned at 2 (O(chunks), not O(steps)) and recorded as the
   ``exchange.launches_per_chunk`` gauge (source=measured);
2. **conformance**: ``analysis/verify_plan`` audits the
   ``remote-dma+persistent`` label — zero-collective census, predicted
   DMA count, and measured-vs-predicted launches_per_chunk — and trips
   when the DMA prediction is perturbed;
3. **autotuner round-trip**: ``plan_tool autotune --methods remote-dma
   --variants persistent --ks 1,2`` tunes (probes run against the
   deep-halo emulation), persists a kernel_variant=persistent entry,
   and a second invocation replays it as a pure DB hit with zero
   probes; all metrics pass ``report --validate``;
4. **lint**: ``lint_tool lint`` stays green over the new modules
   (0 new findings against the committed baseline).

Run from the repo root:  python scripts/ci_persistent_gate.py [--size 24]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

PARITY_CHILD = r"""
import sys
import stencil_tpu  # first: applies the jax-compat shims (old-jax containers)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np
import jax.numpy as jnp
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.obs import telemetry
from stencil_tpu.ops.jacobi import INIT_TEMP, make_jacobi_loop, sphere_sel
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

size, metrics = int(sys.argv[1]), sys.argv[2]
rec = telemetry.configure(metrics_out=metrics, app="ci_persistent_gate")

def run_loop(sz, dim, k, iters, mode):
    spec = GridSpec(Dim3(*sz), Dim3(*dim), Radius.constant(k))
    mesh = grid_mesh(spec.dim, jax.devices()[: spec.dim.flatten()])
    if mode == "persistent":
        ex = HaloExchange(spec, mesh, Method.REMOTE_DMA, persistent=True)
        loop = make_jacobi_loop(ex, iters, temporal_k=k)
    elif mode == "plain":
        ex = HaloExchange(spec, mesh, Method.REMOTE_DMA)
        loop = make_jacobi_loop(ex, iters, temporal_k=k)
    else:
        ex = HaloExchange(spec, mesh, Method.AXIS_COMPOSED)
        loop = make_jacobi_loop(ex, iters)
    g = spec.global_size
    c = shard_blocks(np.full((g.z, g.y, g.x), INIT_TEMP, np.float32),
                     spec, mesh)
    n = jax.device_put(jnp.zeros_like(c), ex.sharding())
    sel = shard_blocks(sphere_sel((g.x, g.y, g.z)), spec, mesh)
    c, _ = loop(c, n, sel)
    if mode == "persistent":
        lpc = ex.last_launches_per_chunk
        assert lpc == 2, f"measured launches/chunk {lpc} != 2 (O(chunks))"
        telemetry.record_exchange_truth(
            ex, {0: c}, [4], variant="persistent")
    return unshard_blocks(c, spec)

# k in {2, 4} on the uniform 2x2x2 partition (tail chunk at k=4), plus
# an UNEVEN anisotropic split — all bit-identical to composed AND to the
# per-step plain remote-dma loop at the same deep-halo config
cases = [
    ((size, size, size), (2, 2, 2), 2, 8),
    ((size, size, size), (2, 2, 2), 4, 10),
    ((size - 6, size - 4, size - 2), (1, 2, 4), 2, 6),
]
for sz, dim, k, iters in cases:
    ref = run_loop(sz, dim, k, iters, "composed")
    plain = run_loop(sz, dim, k, iters, "plain")
    pers = run_loop(sz, dim, k, iters, "persistent")
    tag = f"{sz}/{dim}/k{k}"
    assert np.array_equal(ref, pers), f"PERSISTENT differs from COMPOSED {tag}"
    assert np.array_equal(plain, pers), f"PERSISTENT differs from PLAIN {tag}"

# conformance sweep: the remote-dma+persistent label audits clean and
# the perturbed sweep trips (the gate proves the auditor has teeth)
from stencil_tpu.analysis import verify_plan as vp

cfgs = vp.sweep_configs(size=16, radius=2, partitions=[(2, 2, 2)],
                        methods=[vp.PERSISTENT_METHOD_LABEL],
                        qsets=[("float32",)])
res = vp.run_sweep(cfgs)
assert res["checked"] == 1 and res["failed"] == 0, res
checks = {c["name"]: c for c in res["verdicts"][0].checks}
assert checks["launches_per_chunk"]["predicted"] == 2, checks
assert checks["launches_per_chunk"]["ok"], checks
res = vp.run_sweep(cfgs, perturb_dmas=1)
assert res["failed"] == 1, "perturbed persistent sweep did not trip"
rec.close()
print("PERSISTENT_PARITY_OK")
"""


def run(cmd, env=None, expect_rc=0, name=""):
    shown = " ".join(a if len(a) < 200 else "<inline child>" for a in cmd)
    print(f"[persistent-gate] {name}: {shown}", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    p = subprocess.run(cmd, env=e, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[persistent-gate] {name}: rc={p.returncode}, "
            f"expected {expect_rc}"
        )
    return p


def metrics_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="persistent-gate-")
    db = os.path.join(work, "plans.json")
    try:
        # 1 + 2. parity at k in {2,4} / uneven split, measured launch
        # census == 2, conformance auditor green and trippable
        pm = os.path.join(work, "parity.jsonl")
        r = run([PY, "-c", PARITY_CHILD, str(args.size), pm], name="parity")
        if "PERSISTENT_PARITY_OK" not in r.stdout:
            raise SystemExit("[persistent-gate] parity child gave no verdict")
        recs = metrics_records(pm)
        gauges = [rec for rec in recs if rec["kind"] == "gauge"
                  and rec["name"] == "exchange.launches_per_chunk"]
        measured = [g for g in gauges if g.get("source") == "measured"]
        if not measured or any(g["value"] != 2 for g in measured):
            raise SystemExit(
                f"[persistent-gate] measured launches_per_chunk gauges "
                f"not pinned at 2: {[g.get('value') for g in gauges]}"
            )

        # 3. autotuner DB round-trip with a persistent-variant entry
        def tune(metrics, name):
            return run(
                [PY, "-m", "stencil_tpu.apps.plan_tool", "autotune",
                 "--cpu", "8", "--db", db, "--methods", "remote-dma",
                 "--variants", "persistent", "--ks", "1,2",
                 "--x", str(args.size), "--y", str(args.size),
                 "--z", str(args.size), "--radius", "1",
                 "--quantities", "1", "--probe-iters", "2", "--top-n", "1",
                 "--metrics-out", metrics],
                name=name,
            )

        t1 = os.path.join(work, "tune.jsonl")
        r = tune(t1, "tune-persistent")
        if "persistent" not in r.stdout:
            raise SystemExit(
                f"[persistent-gate] tuner did not pick the persistent "
                f"variant:\n{r.stdout}")
        t2 = os.path.join(work, "replay.jsonl")
        r = tune(t2, "replay-persistent")
        if "cache_hit: True" not in r.stdout or "probes_run: 0" not in r.stdout:
            raise SystemExit(
                f"[persistent-gate] replay was not a pure DB hit:\n"
                f"{r.stdout}")
        with open(db) as f:
            dbobj = json.load(f)
        variants = [e["choice"].get("kernel_variant")
                    for e in dbobj["entries"].values()]
        if variants != ["persistent"]:
            raise SystemExit(
                f"[persistent-gate] DB entries carry variants {variants}, "
                "expected exactly one 'persistent' entry")

        # every metrics file passes the schema gate
        run([PY, "-m", "stencil_tpu.apps.report", pm, t1, t2,
             "--validate"], name="schema")

        # 4. the repo lint stays green over the new modules
        run([PY, "-m", "stencil_tpu.apps.lint_tool", "lint"], name="lint")
        print("[persistent-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
