"""Probe: y-direction FD taps as banded MATMULS on the MXU vs shifted
slice sums on the VPU (task: astaroth 512^3 arithmetic is the recorded
floor binder — tap arithmetic runs on the VPU at ~2.1 Tflop/s while the
MXU idles; a 6th-order y-derivative over a (rows_in -> ty) window is
exactly a banded [ty, rows_in] matmul, contraction along sublanes).

Two kernels over the substep's (tz, rows_in, px) window shape:
- vpu: dy and d2y of NF fields by shifted sublane slices + weighted sums
  (the production fd.py structure);
- mxu: the same 2*NF pencils as one [2*ty, rows_in] x [rows_in, px]
  dot_general per field-plane at Precision.HIGHEST (the multi-pass f32
  decomposition — the only Mosaic-supported precision that passes FD
  parity; the bf16-truncating DEFAULT fails it, and HIGH is
  NotImplementedError in the in-kernel dot lowering), no sublane
  realignment at all.

Outputs are cross-checked (rtol 1e-4: matmul reassociates the 7-term sum)
and both are timed per substep-equivalent tile count at 512^3.

Usage: python scripts/probe_mxu_taps.py [n]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from stencil_tpu.astaroth.fd import FIRST_COEFFS, SECOND_CENTER, SECOND_COEFFS
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.ops.pallas_astaroth import NF, pick_tiles
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync
from stencil_tpu.utils.timer import chained_calls

n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
H = 3


def _interp():
    return jax.devices()[0].platform != "tpu"


def band_matrix(ty: int, rows_in: int, yo: int, inv: float) -> np.ndarray:
    """[2*ty, rows_in] banded operator: rows 0..ty-1 produce dy, rows
    ty..2ty-1 produce d2y, for output rows yo..yo+ty-1 of the window."""
    M = np.zeros((2 * ty, rows_in), np.float32)
    for j in range(ty):
        r = yo + j
        for i, cc in enumerate(FIRST_COEFFS, start=1):
            M[j, r + i] += cc * inv
            M[j, r - i] -= cc * inv
        M[ty + j, r] += SECOND_CENTER * inv * inv
        for i, cc in enumerate(SECOND_COEFFS, start=1):
            M[ty + j, r + i] += cc * inv * inv
            M[ty + j, r - i] += cc * inv * inv
    return M


def main():
    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3).without_x())
    tz, ty = pick_tiles(spec)
    px = spec.padded().x
    rows_in = ty + 16
    yo = 8
    inv = 1.7
    n_tiles = (spec.base.z // tz) * (spec.base.y // ty)
    c1 = [float(c) for c in FIRST_COEFFS]
    c2 = [float(c) for c in SECOND_COEFFS]

    def vpu_kernel(win_ref, out_ref):
        for f in range(NF):
            for z in range(tz):
                w = win_ref[f, z]
                dy = jnp.zeros((ty, px), jnp.float32)
                d2 = jnp.full((ty, px), 0.0, jnp.float32) + (
                    float(SECOND_CENTER) * inv * inv
                ) * w[yo : yo + ty, :]
                for i in range(1, 4):
                    hi = w[yo + i : yo + ty + i, :]
                    lo = w[yo - i : yo + ty - i, :]
                    dy = dy + (c1[i - 1] * inv) * (hi - lo)
                    d2 = d2 + (c2[i - 1] * inv * inv) * (hi + lo)
                out_ref[f, z, 0] = dy
                out_ref[f, z, 1] = d2

    M_np = band_matrix(ty, rows_in, yo, inv)

    def make_mxu_kernel(precision):
        # precision is REQUIRED for parity: the TPU default truncates f32
        # inputs to bf16 (one MXU pass), a ~2^-8 per-product error that
        # fails any useful FD tolerance (measured: 98% of elements out at
        # rtol 1e-4, abs ~5e-3). Only HIGHEST (multi-pass f32
        # decomposition) both parity-passes and lowers in Mosaic.
        def mxu_kernel(win_ref, m_ref, out_ref):
            m = m_ref[...]
            for f in range(NF):
                for z in range(tz):
                    w = win_ref[f, z]
                    both = jax.lax.dot_general(
                        m, w, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=precision,
                    )
                    out_ref[f, z, 0] = both[0:ty, :]
                    out_ref[f, z, 1] = both[ty : 2 * ty, :]

        return mxu_kernel

    win_shape = (NF, tz, rows_in, px)
    out_shape = jax.ShapeDtypeStruct((NF, tz, 2, ty, px), jnp.float32)
    vpu = pl.pallas_call(
        vpu_kernel,
        grid=(n_tiles,),
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)
        ),
        interpret=_interp(),
    )
    def make_mxu(precision):
        return pl.pallas_call(
            make_mxu_kernel(precision),
            grid=(n_tiles,),
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)
            ),
            interpret=_interp(),
        )

    # Mosaic's in-kernel dot lowering supports DEFAULT and HIGHEST only
    # (HIGH raises NotImplementedError, measured round 5); DEFAULT fails
    # FD parity (bf16 truncation), so HIGHEST is the one usable variant.
    mxu_highest = make_mxu(jax.lax.Precision.HIGHEST)
    rng = np.random.RandomState(11)
    win = jnp.asarray(rng.rand(*win_shape) * 0.1, jnp.float32)
    M = jnp.asarray(M_np)

    a = np.asarray(jax.jit(vpu)(win))
    b = np.asarray(jax.jit(mxu_highest)(win, M))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)
    print(f"parity ok at HIGHEST: vpu vs mxu pencils agree (tz,ty)=({tz},{ty}), "
          f"{n_tiles} tiles", flush=True)

    loops = {
        "vpu": chained_calls(lambda w: vpu(w)),
        "mxu-highest": chained_calls(lambda w: mxu_highest(w, M)),
    }
    for label, (g, calls) in loops.items():
        t0 = time.time()
        out = g(win)
        hard_sync(out)
        cs = time.time() - t0
        st = Statistics()
        for _ in range(3):
            t0 = time.perf_counter()
            out = g(win)
            hard_sync(out)
            st.insert((time.perf_counter() - t0) / calls)
        print(f"{label}: {st.trimean()*1e3:.3f} ms per substep-equivalent "
              f"({NF} fields x {tz} planes x (dy+d2y) x {n_tiles} tiles; "
              f"compile {cs:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
