#!/usr/bin/env python
"""CI fused compute+exchange gate: the ISSUE-14 acceptance proof on the
CPU mesh.

Five stages, exit 0 only if every one holds:

1. **parity + census**: at 24^3 on the 2x2x2 8-virtual-device mesh, the
   FUSED exchange (``HaloExchange(Method.REMOTE_DMA, fused=True)`` — the
   concurrent per-direction schedule) is bit-identical to AXIS_COMPOSED
   on coordinate fields (fp32 AND a mixed fp32/fp64 dict), its census
   over every compiled piece contains ZERO collective-permutes, the
   recorded ``exchange.permutes_per_quantity`` gauge reads 0, AND the
   full fused jacobi step loop (pack -> start -> interior -> wait ->
   boundary, 4 iterations) lands bit-identical to the composed step;
2. **overlap telemetry**: the parity run's metrics carry the
   ``fused.interior`` / ``fused.dma_wait`` / ``fused.boundary`` spans
   and a ``fused.overlap_fraction`` gauge in [0, 1], all schema-valid
   under ``report --validate``;
3. **fp8 wire A/B**: ``bench_exchange --wire-ab --wire-dtype
   float8_e4m3fn`` must gate >= 3.8x on-wire byte reduction vs fp32 at
   an unchanged permute/DMA count with max error inside the e4m3
   half-ulp bound (the app exits 1 itself otherwise);
4. **autotuner round-trip**: ``plan_tool autotune --methods remote-dma
   --variants fused`` tunes (probes run against the fused emulation),
   persists a kernel_variant=fused entry, and a second invocation
   replays it as a pure DB hit with zero probes;
5. **lint**: ``lint_tool lint`` stays green over the new modules
   (0 new findings against the committed baseline).

Run from the repo root:  python scripts/ci_fused_gate.py [--size 24]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

PARITY_CHILD = r"""
import sys
import stencil_tpu  # first: applies the jax-compat shims (old-jax containers)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", True)
import numpy as np
import jax.numpy as jnp
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.obs import telemetry
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks

size, metrics = int(sys.argv[1]), sys.argv[2]
rec = telemetry.configure(metrics_out=metrics, app="ci_fused_gate")
spec = GridSpec(Dim3(size, size, size), Dim3(2, 2, 2), Radius.constant(2))
mesh = grid_mesh(spec.dim, jax.devices()[:8])
g = spec.global_size
coord = (np.arange(g.z)[:, None, None] * 1e6
         + np.arange(g.y)[None, :, None] * 1e3
         + np.arange(g.x)[None, None, :])

def state(dtypes):
    return {i: shard_blocks((coord + i).astype(dt), spec, mesh)
            for i, dt in enumerate(dtypes)}

# exchange-level parity + census, fp32 and mixed-dtype
for dtypes in ([np.float32] * 4, [np.float32, np.float64, np.float32]):
    outs = {}
    for method, fused in ((Method.AXIS_COMPOSED, False),
                          (Method.REMOTE_DMA, True)):
        ex = HaloExchange(spec, mesh, method, fused=fused)
        out = ex(state(dtypes))
        outs[fused] = [np.asarray(jax.device_get(out[i]))
                       for i in sorted(out)]
        if fused:
            census = ex.collective_census(state(dtypes))
            assert census.get("collective-permute", (0, 0))[0] == 0, census
            assert sum(c for c, _b in census.values()) == 0, census
            itemsizes = [np.dtype(dt).itemsize for dt in dtypes]
            telemetry.record_exchange_truth(ex, state(dtypes), itemsizes,
                                            variant="fused")
    for a, b in zip(outs[False], outs[True]):
        assert np.array_equal(a, b), "FUSED exchange differs from COMPOSED"

# full fused jacobi step-loop parity (the overlap schedule end to end)
from stencil_tpu.ops.jacobi import INIT_TEMP, make_jacobi_loop, sphere_sel

sel = shard_blocks(sphere_sel((size, size, size)), spec, mesh)
results = {}
for method, fused in ((Method.AXIS_COMPOSED, False),
                      (Method.REMOTE_DMA, True)):
    ex = HaloExchange(spec, mesh, method, fused=fused)
    loop = make_jacobi_loop(ex, 4)
    # per-leg field: the composed loop donates its input buffers
    c = shard_blocks(np.full((size,) * 3, INIT_TEMP, np.float32),
                     spec, mesh)
    n = jax.device_put(jnp.zeros_like(c), ex.sharding())
    c, _n = loop(c, n, sel)
    results[fused] = np.asarray(jax.device_get(c))
assert np.array_equal(results[False], results[True]), \
    "fused jacobi step loop differs from the composed step"
rec.close()
print("FUSED_PARITY_OK")
"""


def run(cmd, env=None, expect_rc=0, name=""):
    shown = " ".join(a if len(a) < 200 else "<inline child>" for a in cmd)
    print(f"[fused-gate] {name}: {shown}", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    p = subprocess.run(cmd, env=e, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[fused-gate] {name}: rc={p.returncode}, expected {expect_rc}"
        )
    return p


def metrics_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="fused-gate-")
    db = os.path.join(work, "plans.json")
    try:
        # 1. parity + 0-ppermute census + step-loop parity
        pm = os.path.join(work, "parity.jsonl")
        r = run([PY, "-c", PARITY_CHILD, str(args.size), pm], name="parity")
        if "FUSED_PARITY_OK" not in r.stdout:
            raise SystemExit("[fused-gate] parity child gave no verdict")
        recs = metrics_records(pm)
        gauges = [rec for rec in recs if rec["kind"] == "gauge"
                  and rec["name"] == "exchange.permutes_per_quantity"]
        if not gauges or any(g["value"] != 0 for g in gauges):
            raise SystemExit(
                f"[fused-gate] permutes_per_quantity gauge not 0: "
                f"{[g.get('value') for g in gauges]}"
            )

        # 2. overlap telemetry: the fused spans + overlap_fraction gauge
        spans = {rec["name"] for rec in recs if rec["kind"] == "span"}
        for want in ("fused.interior", "fused.dma_wait", "fused.boundary"):
            if want not in spans:
                raise SystemExit(
                    f"[fused-gate] span {want!r} missing from the fused "
                    f"run's metrics (saw {sorted(spans)})"
                )
        overlaps = [rec["value"] for rec in recs if rec["kind"] == "gauge"
                    and rec["name"] == "fused.overlap_fraction"]
        if not overlaps or any(not (0.0 <= v <= 1.0) for v in overlaps):
            raise SystemExit(
                f"[fused-gate] fused.overlap_fraction missing or out of "
                f"[0, 1]: {overlaps}"
            )

        # 3. fp8 wire A/B (the app's own gate: >=3.8x bytes, e4m3 bound,
        # unchanged count)
        wm = os.path.join(work, "wire.jsonl")
        run([PY, "-m", "stencil_tpu.apps.bench_exchange", "--wire-ab",
             "--x", str(args.size), "--y", str(args.size),
             "--z", str(args.size), "--iters", "3", "--quantities", "4",
             "--partition", "2x2x2", "--wire-dtype", "float8_e4m3fn",
             "--metrics-out", wm],
            env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
            name="wire-ab-fp8")
        ratios = [rec["value"] for rec in metrics_records(wm)
                  if rec["kind"] == "gauge"
                  and rec["name"] == "wire_ab.bytes_ratio"]
        if not ratios or ratios[-1] < 3.8:
            raise SystemExit(
                f"[fused-gate] fp8 wire bytes ratio {ratios} < 3.8")

        # 4. autotuner DB round-trip with a fused-variant entry
        def tune(metrics, name):
            return run(
                [PY, "-m", "stencil_tpu.apps.plan_tool", "autotune",
                 "--cpu", "8", "--db", db, "--methods", "remote-dma",
                 "--variants", "fused",
                 "--x", str(args.size), "--y", str(args.size),
                 "--z", str(args.size), "--radius", "2",
                 "--quantities", "1", "--probe-iters", "2", "--top-n", "1",
                 "--metrics-out", metrics],
                name=name,
            )

        t1 = os.path.join(work, "tune.jsonl")
        r = tune(t1, "tune-fused")
        if "/fused" not in r.stdout:
            raise SystemExit(
                f"[fused-gate] tuner did not pick the fused variant:\n"
                f"{r.stdout}")
        t2 = os.path.join(work, "replay.jsonl")
        r = tune(t2, "replay-fused")
        if "cache_hit: True" not in r.stdout or "probes_run: 0" not in r.stdout:
            raise SystemExit(
                f"[fused-gate] replay was not a pure DB hit:\n{r.stdout}")
        with open(db) as f:
            dbobj = json.load(f)
        variants = [e["choice"].get("kernel_variant")
                    for e in dbobj["entries"].values()]
        if variants != ["fused"]:
            raise SystemExit(
                f"[fused-gate] DB entries carry variants {variants}, "
                "expected exactly one 'fused' entry")

        # every metrics file passes the schema gate
        run([PY, "-m", "stencil_tpu.apps.report", pm, wm, t1, t2,
             "--validate"], name="schema")

        # 5. the repo lint stays green over the new modules
        run([PY, "-m", "stencil_tpu.apps.lint_tool", "lint"], name="lint")
        print("[fused-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
