#!/usr/bin/env bash
# Launch an N-process run of a stencil_tpu app on ONE machine, each process
# with its own virtual CPU devices — the no-cluster multi-host idiom
# (reference launch scripts: scripts/summit/*.sh via jsrun, README.md:131-168;
# here jax.distributed over Gloo replaces mpiexec).
#
# Usage:
#   scripts/launch_multiprocess.sh <nprocs> <devices-per-proc> <module> [args...]
# Example (2 hosts x 4 devices, jacobi3d):
#   scripts/launch_multiprocess.sh 2 4 stencil_tpu.apps.jacobi3d --x 64 --iters 3
#
# On a real TPU pod slice none of this is needed: every host runs the same
# command and `stencil_tpu.parallel.distributed.init_distributed()` picks up
# the cluster automatically.
set -euo pipefail
NPROCS=${1:?nprocs}
LOCAL=${2:?devices per process}
MODULE=${3:?python module}
shift 3
PORT=${STENCIL_PORT:-$((20000 + RANDOM % 20000))}

pids=()
for ((rank = 0; rank < NPROCS; rank++)); do
  STENCIL_COORDINATOR="localhost:${PORT}" \
  STENCIL_NUM_PROCESSES="${NPROCS}" \
  STENCIL_PROCESS_ID="${rank}" \
  STENCIL_LOCAL_CPU_DEVICES="${LOCAL}" \
  python -m "${MODULE}" "$@" &
  pids+=($!)
done
rc=0
for pid in "${pids[@]}"; do
  wait "${pid}" || rc=$?
done
exit "${rc}"
