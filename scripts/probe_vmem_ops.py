"""Correct-math microbenchmarks for the substep window-shift and the
multistep y-ring fills at 512^3 shapes (VERDICT r3 item 2: the round-3
floor accounting leaned on wrong-results probes of the production kernels;
these standalone kernels measure the SAME VMEM operations in isolation and
verify their outputs, so the numbers carry no corrupted-kernel caveat).

- window-shift: per tile, the astaroth substep copies NF x 2H halo planes
  down the sliding window (`win[f, 0:2H] = win[f, tz:tz+2H]`). The
  microbenchmark kernel performs exactly those copies per grid step over
  the 512^3 tile schedule, then drains a checksum plane so the stores are
  live; the output is verified against the expected roll of the seeded
  window.
- y-ring: the jacobi multistep copies 2 rows per stage per grid step
  (`ref[slot, yo-1, :] = ref[slot, yo+ny-1, :]`); same treatment at k=10.

Usage: python scripts/probe_vmem_ops.py [n]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.ops.pallas_astaroth import NF, pick_tiles
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.timer import chained_calls
from stencil_tpu.utils.sync import hard_sync

n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
H = 3
# CPU smoke (logic validation at tiny n): interpret mode off-TPU
INTERP = None  # resolved after backend selection in main


def _interp():
    import jax
    return jax.devices()[0].platform != "tpu"


def window_shift_bench():
    """The substep's per-tile window shift, alone, on the 512^3 schedule."""
    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3).without_x())
    tz, ty = pick_tiles(spec)
    px = spec.padded().x
    rows_in = ty + 16
    W = tz + 2 * H
    n_tiles = (spec.base.z // tz) * (spec.base.y // ty)
    shifts_per_call = n_tiles  # the substep shifts on every non-strip-start
    # tile; we shift on every tile (upper bound by < (1 + n_strips/n_tiles))

    def kernel(seed_ref, out_ref, win, s):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _():
            cp = pltpu.make_async_copy(seed_ref, win.at[0], s)
            cp.start()
            cp.wait()

        for f in range(NF):
            win[f, 0 : 2 * H] = win[f, tz : tz + 2 * H]

        @pl.when(t == n_tiles - 1)
        def _():
            cp = pltpu.make_async_copy(win.at[0, pl.ds(0, 1)], out_ref, s)
            cp.start()
            cp.wait()

    fn = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        out_shape=jax.ShapeDtypeStruct((1, rows_in, px), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((NF, W, rows_in, px), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), has_side_effects=True
        ),
        interpret=_interp(),
    )
    rng = np.random.RandomState(3)
    seed = jnp.asarray(rng.rand(W, rows_in, px), jnp.float32)
    g, calls = chained_calls(fn)
    t0 = time.time()
    out = g(seed)
    hard_sync(out)
    cs = time.time() - t0
    # correctness: verify plane 0 against a numpy emulation of the same
    # n_tiles-long overlapping-copy sequence
    w = np.array(seed)
    for _ in range(n_tiles):
        w[0 : 2 * H] = w[tz : tz + 2 * H]
    np.testing.assert_allclose(np.asarray(out)[0], w[0], rtol=0, atol=0)
    st = Statistics()
    for _ in range(3):
        t0 = time.perf_counter()
        out = g(seed)
        hard_sync(out)
        st.insert((time.perf_counter() - t0) / calls)
    per_call = st.trimean()
    print(
        f"window-shift {n}^3 (tz,ty)=({tz},{ty}): {per_call*1e3:.3f} ms per "
        f"substep-equivalent ({shifts_per_call} shifts of {NF}x{2*H} planes "
        f"x {rows_in}x{px}; compile {cs:.0f}s)",
        flush=True,
    )


def y_ring_bench():
    """The multistep's per-stage y-ring row copies, alone, at k=10."""
    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(1).without_x())
    from stencil_tpu.ops.pallas_stencil import _pick_tiles

    p = spec.padded()
    off = spec.compute_offset()
    tz, ty = _pick_tiles(spec.base.z, spec.base.y, off.y, p.y, p.x)
    k = 10
    px = p.x
    rows = ty + 16 if ty != spec.base.y else p.y
    yo = 8 if ty != spec.base.y else off.y
    ny = ty
    n_tiles = (spec.base.z // tz) * (spec.base.y // ty)
    copies = 2 * k  # per grid step in the k=10 multistep

    def kernel(seed_ref, out_ref, buf, s):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _():
            cp = pltpu.make_async_copy(seed_ref, buf, s)
            cp.start()
            cp.wait()

        for _ in range(k):
            buf[0, yo - 1, :] = buf[0, yo + ny - 1, :]
            buf[0, yo + ny, :] = buf[0, yo, :]

        @pl.when(t == n_tiles - 1)
        def _():
            cp = pltpu.make_async_copy(buf, out_ref, s)
            cp.start()
            cp.wait()

    fn = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        out_shape=jax.ShapeDtypeStruct((tz + 2, rows, px), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((tz + 2, rows, px), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), has_side_effects=True
        ),
        interpret=_interp(),
    )
    rng = np.random.RandomState(5)
    seed = jnp.asarray(rng.rand(tz + 2, rows, px), jnp.float32)
    g, calls = chained_calls(fn)
    t0 = time.time()
    out = g(seed)
    hard_sync(out)
    cs = time.time() - t0
    w = np.array(seed)
    w[0, yo - 1, :] = w[0, yo + ny - 1, :]
    w[0, yo + ny, :] = w[0, yo, :]  # fixpoint after the first pair
    np.testing.assert_allclose(np.asarray(out), w, rtol=0, atol=0)
    st = Statistics()
    for _ in range(3):
        t0 = time.perf_counter()
        out = g(seed)
        hard_sync(out)
        st.insert((time.perf_counter() - t0) / calls)
    print(
        f"y-ring {n}^3 (tz,ty)=({tz},{ty}) k={k}: {st.trimean()*1e3:.3f} ms "
        f"per multistep call ({copies} row copies x {n_tiles} tiles of "
        f"{px} lanes; compile {cs:.0f}s)",
        flush=True,
    )


if __name__ == "__main__":
    window_shift_bench()
    y_ring_bench()
