#!/usr/bin/env python
"""CI serving gate: continuous batching, drain, revival, SLO replan.

The executable acceptance proof of ISSUE 19 (stencil_tpu/serve/ — the
always-on campaign serving daemon) on the 8-virtual-device CPU mesh,
no TPU needed:

1. **continuous batching**: 8 pre-dropped jobs overflow a ``--slot 4``
   daemon; the gate polls the atomic status snapshot, and the moment a
   slot is observed RUNNING it drops a 9th job into the live intake —
   the final summary must show exactly ONE slot, every job retired,
   and >= 5 backfills (jobs entered mid-slot, no slot-wide barrier);
   the metric stream must show the late job's ``serve.admitted``
   AFTER ``campaign.slot``, and a mid-run status poll must see the
   queue's ``admitted`` count reach 9 while the slot is still going;
2. **SIGTERM drain**: a daemon mid-slot on 3 long jobs receives
   SIGTERM and must exit 0 with outcome ``drained``, every trajectory
   parked mid-flight (``serve.parked`` with 0 < step < steps, zero
   retirements), and a restarted daemon revives all 3 from
   ``serve-state.json`` and finishes them — each job retires exactly
   once across both runs;
3. **kill -> revive bit-identical**: the daemon runs under the PR 3
   watchdog (``obs/watchdog.supervise``) with the injected kill hook
   (``STENCIL_SERVE_KILL_AFTER_RETIRE=2`` -> ``os._exit(17)``); the
   watchdog classifies the death as a CRASH, the revival attempt
   finishes the queue, no retired job is ever re-run, and EVERY
   tenant's final snapshot is bit-identical to an uninterrupted
   reference serve of the same seeded load (``ckpt_tool diff --data``);
4. **SLO-pressure replan**: deadline-doomed jobs (no admission ledger,
   so they are admitted and the pressure builds online) must emit
   ``replan.requested`` with reason ``slo-pressure`` and hot-swap a
   plan between slots (``replan.applied``, trigger ``slo-pressure``)
   persisted into ``--plan-db``;
5. **priced preemption, bit-identical** (ISSUE 20): a high-priority
   deadline job dropped mid-slot against a seeded pricing ledger must
   preempt the running slot at a chunk boundary (``serve.preempted``
   with ``gain_ms > resume_cost_ms``, both victims ``serve.parked``
   with reason ``preempt`` mid-flight), and every tenant's final
   snapshot — victims included — must be bit-identical to an
   undisturbed ``--no-preempt`` reference serve of the same seeded
   load (``ckpt_tool diff --data``);
6. **elastic slot width**: a ``--slot-min 2 --slot-max 8`` daemon
   grows a running width-2 slot when 6 same-bucket jobs land mid-slot
   (``serve.resized`` reason ``grow``, lanes parked with reason
   ``resize``), and a later wave revisiting the grown width compiles
   NOTHING new — every ``compile.build`` key (which carries the slot
   width as ``batch``) is built exactly once across the daemon's life;
7. every metrics file passes ``report --validate``.

Exit 0 only if every stage holds. Run from the repo root:

  python scripts/ci_serve_gate.py [--out-dir DIR]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable
KILL_ENV = "STENCIL_SERVE_KILL_AFTER_RETIRE"

# one compiled bucket for stage 1: the late drop must be backfillable
# into the already-running slot's program
SIZE = 14


def run(cmd, expect_rc=0, name="", env=None):
    print(f"[serve-gate] {name}: {' '.join(cmd)}", flush=True)
    p = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                       env=env)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[serve-gate] {name}: rc={p.returncode}, expected {expect_rc}")
    return p


def load_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def by_name(records, name):
    return [r for r in records if r["name"] == name]


def summary_of(stdout_text, name):
    """The daemon's one-line JSON summary (the last JSON line printed)."""
    for line in reversed(stdout_text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"[serve-gate] {name}: no JSON summary in stdout")


def loadgen(serve_dir, *, jobs, steps, seed, tenants=2, size=SIZE,
            rate=0.0, prefix="j", deadline_ms=0.0):
    cmd = [PY, os.path.join(REPO, "scripts", "serve_loadgen.py"),
           "--serve-dir", serve_dir, "--jobs", str(jobs),
           "--steps", str(steps), "--seed", str(seed),
           "--tenants", str(tenants), "--size", str(size),
           "--rate", str(rate), "--prefix", prefix]
    if deadline_ms > 0:
        cmd += ["--deadline-ms", str(deadline_ms)]
    return run(cmd, name=f"loadgen-{prefix}{seed}")


def serve_cmd(serve_dir, metrics, status, *, slot=4, max_idle_s=2.0,
              extra=()):
    return [PY, "-m", "stencil_tpu.apps.serve", "--serve-dir", serve_dir,
            "--cpu", "8", "--slot", str(slot), "--chunk", "2",
            "--poll-s", "0.05", "--max-idle-s", str(max_idle_s),
            "--metrics-out", metrics, "--status-file", status,
            *extra]


def read_status(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # not written yet / mid-rename on exotic FS


def newest_snapshot(serve_dir, tid):
    d = os.path.join(serve_dir, "campaign", "tenants", tid)
    steps = [s for s in os.listdir(d) if s.startswith("step-")]
    if not steps:
        raise SystemExit(f"[serve-gate] no snapshots under {d}")
    return os.path.join(
        d, max(steps, key=lambda s: int(s.split("-", 1)[1])))


def retired_jobs(*metric_paths):
    out = []
    for path in metric_paths:
        out.extend(r["job"] for r in by_name(load_records(path),
                                             "serve.retired"))
    return out


def drop_doc(serve_dir, doc):
    """Atomically drop one job document (the loadgen write contract;
    used directly when a stage needs a field loadgen has no flag for,
    e.g. an explicit priority)."""
    incoming = os.path.join(serve_dir, "jobs", "incoming")
    os.makedirs(incoming, exist_ok=True)
    tmp = os.path.join(incoming, f".tmp-{doc['job']}-{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(incoming, f"{doc['job']}.json"))


def seed_pricing_ledger(path, prices):
    """Seed ``serve.step_p99_ms`` bucket priors WITHOUT importing
    stencil_tpu (the gate process never pays the jax import): plain v1
    rows in the obs/ledger.py schema, keyed by ``detail.bucket`` —
    exactly what BucketPricer loads."""
    with open(path, "w") as f:
        for i, (bucket, ms) in enumerate(sorted(prices.items())):
            f.write(json.dumps({
                "v": 1, "kind": "perf-ledger",
                "metric": "serve.step_p99_ms", "value": float(ms),
                "unit": "ms", "platform": "cpu",
                "config": f"seed-{bucket}", "rev": None, "label": "seed",
                "source": "serve", "t": float(i + 1), "run": None,
                "detail": {"bucket": bucket, "samples": 8},
            }, sort_keys=True) + "\n")


def poll_daemon(cmd, status_path, out_path, err_path, on_status):
    """Run a daemon to completion, feeding every status snapshot to
    ``on_status`` (output to FILES, not pipes — the stage-1 deadlock
    rule). Returns the daemon's JSON summary."""
    print(f"[serve-gate] daemon (polled): {' '.join(cmd)}", flush=True)
    with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=out_f, stderr=err_f,
                                text=True)
        while proc.poll() is None:
            doc = read_status(status_path)
            if doc:
                on_status(doc)
            time.sleep(0.05)
        proc.wait()
    if proc.returncode != 0:
        with open(err_path) as f:
            print(f.read()[-8000:], file=sys.stderr)
        raise SystemExit(f"[serve-gate] polled daemon rc={proc.returncode}")
    with open(out_path) as f:
        return summary_of(f.read(), os.path.basename(out_path))


def stage1_continuous_batching(work):
    sdir = os.path.join(work, "s1")
    m1 = os.path.join(work, "m1.jsonl")
    st1 = os.path.join(work, "status1.json")
    loadgen(sdir, jobs=8, steps=12, seed=7, tenants=3)
    cmd = serve_cmd(sdir, m1, st1)
    print(f"[serve-gate] daemon (polled): {' '.join(cmd)}", flush=True)
    # child output goes to FILES, not pipes: the poll loop never drains
    # a pipe, so a chatty child would fill the OS buffer and deadlock
    # the gate (the round-4 bench.py lesson watchdog.supervise encodes)
    out_path = os.path.join(work, "daemon1.out")
    err_path = os.path.join(work, "daemon1.err")
    polls, dropped_late, seen_nine = [], False, False
    with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=out_f, stderr=err_f,
                                text=True)
        while proc.poll() is None:
            doc = read_status(st1)
            if doc and doc.get("queue"):
                q = doc["queue"]
                polls.append({"step": doc.get("step"),
                              "admitted": q.get("admitted"),
                              "depth": q.get("depth")})
                mid_run = not doc.get("outcome")
                if (not dropped_late and mid_run
                        and (doc.get("step") or 0) >= 2):
                    # the slot is observably RUNNING: drop job 9 into
                    # the live intake — it must be admitted and
                    # backfilled into THIS slot, not a second one
                    loadgen(sdir, jobs=1, steps=4, seed=1, tenants=1,
                            prefix="late")
                    dropped_late = True
                if dropped_late and mid_run and q.get("admitted") == 9:
                    seen_nine = True
            time.sleep(0.05)
        proc.wait()
    if proc.returncode != 0:
        with open(err_path) as f:
            print(f.read()[-8000:], file=sys.stderr)
        raise SystemExit(f"[serve-gate] daemon1 rc={proc.returncode}")
    if not dropped_late:
        raise SystemExit(
            f"[serve-gate] the status snapshot never showed a running "
            f"slot, so the late job was never dropped ({len(polls)} polls)")
    if not seen_nine:
        raise SystemExit(
            "[serve-gate] no mid-run status poll observed the late job "
            f"admitted (queue.admitted == 9): {polls[-6:]}")
    with open(out_path) as f:
        summary = summary_of(f.read(), "daemon1")
    if summary.get("slots") != 1:
        raise SystemExit(f"[serve-gate] 9 jobs through a B=4 slot must "
                         f"run as ONE slot (continuous batching), got "
                         f"slots={summary.get('slots')}")
    if summary.get("retired") != 9 or summary.get("rejected"):
        raise SystemExit(f"[serve-gate] want 9 retired / 0 rejected: "
                         f"{summary}")
    if summary.get("backfills", 0) < 5:
        raise SystemExit(f"[serve-gate] 9 jobs minus 4 lanes means >= 5 "
                         f"backfills, got {summary.get('backfills')}")
    results = os.listdir(os.path.join(sdir, "results"))
    if len(results) != 9:
        raise SystemExit(f"[serve-gate] want 9 streamed results, got "
                         f"{sorted(results)}")
    recs = load_records(m1)
    slot_idx = min(i for i, r in enumerate(recs)
                   if r["name"] == "campaign.slot")
    late_idx = [i for i, r in enumerate(recs)
                if r["name"] == "serve.admitted"
                and r["job"].startswith("late-")]
    if not late_idx or late_idx[0] <= slot_idx:
        raise SystemExit(
            f"[serve-gate] the late job's serve.admitted must land AFTER "
            f"campaign.slot (admitted mid-slot): slot at {slot_idx}, "
            f"late at {late_idx}")
    run([PY, "-m", "stencil_tpu.apps.report", m1, "--validate"],
        name="validate-1")
    print(f"[serve-gate] stage 1: 1 slot, {summary['backfills']} "
          f"backfills, late job admitted mid-slot (status poll saw "
          f"admitted=9 live; {len(polls)} polls)")


def stage2_sigterm_drain(work):
    sdir = os.path.join(work, "s2")
    m2a = os.path.join(work, "m2a.jsonl")
    m2b = os.path.join(work, "m2b.jsonl")
    st2 = os.path.join(work, "status2.json")
    steps = 16
    loadgen(sdir, jobs=3, steps=steps, seed=5, tenants=3, size=12)
    cmd = serve_cmd(sdir, m2a, st2, slot=4)
    print(f"[serve-gate] daemon (SIGTERM pending): {' '.join(cmd)}",
          flush=True)
    out_path = os.path.join(work, "daemon2.out")
    err_path = os.path.join(work, "daemon2.err")
    with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=out_f, stderr=err_f,
                                text=True)
        while proc.poll() is None:
            doc = read_status(st2)
            if doc and (doc.get("step") or 0) >= 2 and not doc.get("outcome"):
                proc.send_signal(signal.SIGTERM)
                break
            time.sleep(0.05)
        rc = proc.wait(timeout=120)
    if rc != 0:
        with open(err_path) as f:
            print(f.read()[-8000:], file=sys.stderr)
        raise SystemExit(f"[serve-gate] SIGTERM must drain to exit 0, "
                         f"got rc={rc}")
    with open(out_path) as f:
        summary = summary_of(f.read(), "daemon2")
    if summary.get("outcome") != "drained" or summary.get("retired") != 0:
        raise SystemExit(f"[serve-gate] want outcome=drained with 0 "
                         f"retired (parked mid-flight): {summary}")
    if summary.get("queued_remaining") != 3:
        raise SystemExit(f"[serve-gate] all 3 jobs must survive the drain "
                         f"in the queue: {summary}")
    recs = load_records(m2a)
    parked = by_name(recs, "serve.parked")
    if len(parked) != 3 or not all(0 < r["step"] < steps for r in parked):
        raise SystemExit(f"[serve-gate] want 3 mid-flight parks "
                         f"(0 < step < {steps}): "
                         f"{[(r.get('job'), r.get('step')) for r in parked]}")
    drains = by_name(recs, "serve.drain")
    if not drains or drains[0].get("reason") != "sigterm":
        raise SystemExit(f"[serve-gate] serve.drain must name sigterm: "
                         f"{drains}")
    if not os.path.exists(os.path.join(sdir, "serve-state.json")):
        raise SystemExit("[serve-gate] drain left no serve-state.json")

    g = run(serve_cmd(sdir, m2b, st2, slot=4), name="drain-revival")
    summary = summary_of(g.stdout, "drain-revival")
    if summary.get("revived") != 3 or summary.get("retired") != 3:
        raise SystemExit(f"[serve-gate] the restart must revive and "
                         f"finish all 3: {summary}")
    jobs = retired_jobs(m2a, m2b)
    if sorted(jobs) != sorted(set(jobs)) or len(set(jobs)) != 3:
        raise SystemExit(f"[serve-gate] each job must retire exactly "
                         f"once across drain+revival: {sorted(jobs)}")
    for path, name in ((m2a, "validate-2a"), (m2b, "validate-2b")):
        run([PY, "-m", "stencil_tpu.apps.report", path, "--validate"],
            name=name)
    print("[serve-gate] stage 2: SIGTERM drained (3 mid-flight parks), "
          "restart revived and finished all 3, nobody re-ran")


def stage3_kill_revive_bit_identical(work):
    spec = importlib.util.spec_from_file_location(
        "stencil_watchdog",
        os.path.join(REPO, "stencil_tpu", "obs", "watchdog.py"))
    watchdog = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = watchdog  # dataclass resolves __module__
    spec.loader.exec_module(watchdog)

    ref = os.path.join(work, "s3-ref")
    killed = os.path.join(work, "s3-killed")
    for d in (ref, killed):
        loadgen(d, jobs=5, steps=6, seed=11, tenants=2, size=12)
    m_ref = os.path.join(work, "m3ref.jsonl")
    g = run(serve_cmd(ref, m_ref, os.path.join(work, "status3r.json")),
            name="reference-serve")
    if summary_of(g.stdout, "reference-serve").get("retired") != 5:
        raise SystemExit("[serve-gate] reference serve must retire all 5")

    m3a = os.path.join(work, "m3a.jsonl")
    m3b = os.path.join(work, "m3b.jsonl")
    st3 = os.path.join(work, "status3.json")
    env = dict(os.environ)
    env[KILL_ENV] = "2"
    att = watchdog.supervise(
        serve_cmd(killed, m3a, st3), timeout_s=300, env=env, cwd=REPO,
        name="serve-killed")
    if att.outcome != watchdog.CRASH or att.rc != 17:
        raise SystemExit(f"[serve-gate] the kill hook must die as a "
                         f"watchdog CRASH with rc 17: outcome="
                         f"{att.outcome} rc={att.rc}")
    att = watchdog.supervise(
        serve_cmd(killed, m3b, st3), timeout_s=300, cwd=REPO,
        name="serve-revived")
    if att.outcome != watchdog.OK:
        raise SystemExit(f"[serve-gate] revival attempt: outcome="
                         f"{att.outcome} rc={att.rc}\n{att.stderr_tail}")
    summary = summary_of(att.stdout, "serve-revived")
    if summary.get("retired") != 3 or not summary.get("revived"):
        raise SystemExit(f"[serve-gate] revival must pick up the 3 "
                         f"unserved jobs (2 retired pre-kill): {summary}")
    jobs = retired_jobs(m3a, m3b)
    if sorted(jobs) != sorted(set(jobs)) or len(set(jobs)) != 5:
        raise SystemExit(f"[serve-gate] kill+revival must retire each of "
                         f"the 5 jobs exactly once: {sorted(jobs)}")
    for tid in sorted(set(jobs)):
        a = newest_snapshot(killed, tid)
        b = newest_snapshot(ref, tid)
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "diff", a, b,
             "--data"], name=f"diff-{tid}")
    for path, name in ((m3a, "validate-3a"), (m3b, "validate-3b")):
        run([PY, "-m", "stencil_tpu.apps.report", path, "--validate"],
            name=name)
    print("[serve-gate] stage 3: watchdog CRASH rc=17 at 2 retirements, "
          "revival finished 3, all 5 finals bit-identical to the "
          "uninterrupted reference")


def stage4_slo_pressure_replan(work):
    sdir = os.path.join(work, "s4")
    m4 = os.path.join(work, "m4.jsonl")
    plan_db = os.path.join(work, "plans4.json")
    # no admission ledger: the doomed deadline cannot be priced at
    # admission, so the jobs run and the ONLINE p99 builds the pressure
    loadgen(sdir, jobs=4, steps=8, seed=3, tenants=2, size=12,
            deadline_ms=0.001)
    g = run(serve_cmd(sdir, m4, os.path.join(work, "status4.json"),
                      extra=("--replan", "--plan-db", plan_db)),
            name="slo-pressure-serve")
    summary = summary_of(g.stdout, "slo-pressure-serve")
    if summary.get("retired") != 4:
        raise SystemExit(f"[serve-gate] a deadline breach is evidence, "
                         f"not an eviction — all 4 must finish: {summary}")
    recs = load_records(m4)
    req = [r for r in by_name(recs, "replan.requested")
           if r.get("reason") == "slo-pressure"]
    if not req:
        raise SystemExit("[serve-gate] no slo-pressure replan.requested")
    app = [r for r in by_name(recs, "replan.applied")
           if r.get("trigger") == "slo-pressure"]
    if not app:
        raise SystemExit(f"[serve-gate] the latched pressure must "
                         f"hot-swap between slots (replan.applied): "
                         f"{by_name(recs, 'replan.rejected')}")
    if not os.path.exists(plan_db) or not os.path.getsize(plan_db):
        raise SystemExit("[serve-gate] the re-tuned plan must persist "
                         "into --plan-db")
    run([PY, "-m", "stencil_tpu.apps.report", m4, "--validate"],
        name="validate-4")
    print(f"[serve-gate] stage 4: slo-pressure requested at step "
          f"{req[0].get('step')}, plan {app[0].get('old')} -> "
          f"{app[0].get('new')} persisted")


def stage5_preemption_bit_identical(work):
    """A rush high-deadline arrival preempts the running slot — priced
    against the victims' resume cost off a SEEDED ledger — and the
    parked victims resume to finals bit-identical to an undisturbed
    ``--no-preempt`` reference of the same seeded load."""
    lpath = os.path.join(work, "prices5.jsonl")
    # victims' bucket priced slow, the rush bucket fast: waiting in
    # queue provably breaks the rush budget, and the priced gain dwarfs
    # two victims' resume cost
    seed_pricing_ledger(lpath, {
        f"{SIZE}x{SIZE}x{SIZE}/float32/jacobi": 100.0,
        "10x10x10/float32/jacobi": 1.0,
    })
    rush = {"job": "rush", "size": 10, "steps": 2, "dtype": "float32",
            "workload": "jacobi", "seed": 77, "tenant": "tenant-hi",
            "priority": "high", "deadline_ms": 2.0}
    steps = 12
    extra = ("--admission-ledger", lpath, "--preempt-cost-chunks", "0.05")

    ref = os.path.join(work, "s5-ref")
    loadgen(ref, jobs=2, steps=steps, seed=21, tenants=2, prefix="vic")
    drop_doc(ref, rush)
    m_ref = os.path.join(work, "m5ref.jsonl")
    g = run(serve_cmd(ref, m_ref, os.path.join(work, "status5r.json"),
                      extra=("--no-preempt",) + extra),
            name="preempt-reference")
    if summary_of(g.stdout, "preempt-reference").get("retired") != 3:
        raise SystemExit("[serve-gate] preempt reference must retire all 3")

    live = os.path.join(work, "s5")
    loadgen(live, jobs=2, steps=steps, seed=21, tenants=2, prefix="vic")
    m5 = os.path.join(work, "m5.jsonl")
    st5 = os.path.join(work, "status5.json")
    state = {"dropped": False}

    def on_status(doc):
        if (not state["dropped"] and not doc.get("outcome")
                and (doc.get("step") or 0) >= 2):
            # the victim slot is observably RUNNING: now the rush job
            # arrives — preemption must fire at a chunk boundary
            drop_doc(live, rush)
            state["dropped"] = True

    summary = poll_daemon(
        serve_cmd(live, m5, st5, extra=extra), st5,
        os.path.join(work, "daemon5.out"), os.path.join(work, "daemon5.err"),
        on_status)
    if not state["dropped"]:
        raise SystemExit("[serve-gate] stage 5 never saw a running slot "
                         "to drop the rush job into")
    if summary.get("retired") != 3 or summary.get("preemptions") != 1:
        raise SystemExit(f"[serve-gate] want 3 retired / 1 preemption: "
                         f"{summary}")
    recs = load_records(m5)
    pre = by_name(recs, "serve.preempted")
    if len(pre) != 1 or pre[0].get("job") != "rush":
        raise SystemExit(f"[serve-gate] want ONE serve.preempted for the "
                         f"rush job: {pre}")
    if not pre[0]["gain_ms"] > pre[0]["resume_cost_ms"]:
        raise SystemExit(f"[serve-gate] preemption must only fire when "
                         f"the priced gain exceeds the victims' resume "
                         f"cost: {pre[0]}")
    if sorted(pre[0].get("victims", [])) != ["vic-21-0000", "vic-21-0001"]:
        raise SystemExit(f"[serve-gate] both victims must be named: "
                         f"{pre[0]}")
    parked = [r for r in by_name(recs, "serve.parked")
              if r.get("reason") == "preempt"]
    if len(parked) != 2 or not all(0 < r["step"] < steps for r in parked):
        raise SystemExit(f"[serve-gate] want both victims parked "
                         f"mid-flight (0 < step < {steps}): "
                         f"{[(r.get('job'), r.get('step')) for r in parked]}")
    for tid in ("vic-21-0000", "vic-21-0001", "rush"):
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "diff",
             newest_snapshot(live, tid), newest_snapshot(ref, tid),
             "--data"], name=f"diff5-{tid}")
    run([PY, "-m", "stencil_tpu.apps.report", m5, "--validate"],
        name="validate-5")
    run([PY, "-m", "stencil_tpu.apps.report", m_ref, "--validate"],
        name="validate-5ref")
    print(f"[serve-gate] stage 5: rush preempted the slot (gain "
          f"{pre[0]['gain_ms']:.4g} ms > resume cost "
          f"{pre[0]['resume_cost_ms']:.4g} ms), both victims parked and "
          f"resumed, all 3 finals bit-identical to the no-preempt "
          f"reference")


def stage6_elastic_resize(work):
    """A width-2 slot grows to the queue's width mid-flight, and a
    second wave revisiting the grown width recompiles NOTHING — one
    ``compile.build`` per (bucket, width) for the daemon's whole life."""
    lpath = os.path.join(work, "prices6.jsonl")
    seed_pricing_ledger(lpath, {"12x12x12/float32/jacobi": 50.0})
    sdir = os.path.join(work, "s6")
    steps1 = 16
    loadgen(sdir, jobs=2, steps=steps1, seed=31, tenants=2, size=12,
            prefix="w1")
    m6 = os.path.join(work, "m6.jsonl")
    st6 = os.path.join(work, "status6.json")
    state = {"wave2": False, "wave3": False, "wave4": False}

    def on_status(doc):
        q = doc.get("queue") or {}
        mid_run = not doc.get("outcome")
        if (not state["wave2"] and mid_run
                and (doc.get("step") or 0) >= 2):
            # the width-2 slot is RUNNING: 6 more same-bucket jobs make
            # the queue wider than the slot — it must grow, not crawl.
            # Dropped in-process (not via the loadgen subprocess): the
            # whole wave must land while THIS slot is still mid-flight
            for i in range(6):
                drop_doc(sdir, {"job": f"w2-32-{i:04d}", "size": 12,
                                "steps": 8, "dtype": "float32",
                                "workload": "jacobi", "seed": 320 + i,
                                "tenant": f"tenant-{i % 2}",
                                "priority": "normal"})
            state["wave2"] = True
        if (state["wave2"] and not state["wave3"] and mid_run
                and q.get("retired") == 8):
            # everything retired, daemon idling: a second wave at the
            # SAME depth revisits the grown width — a compile-cache hit
            # by construction
            loadgen(sdir, jobs=8, steps=8, seed=33, tenants=2, size=12,
                    prefix="w3")
            state["wave3"] = True
        if (state["wave3"] and not state["wave4"] and mid_run
                and q.get("retired") == 16):
            # the surge is over: a 2-deep trickle must SHRINK the next
            # slot back down the ladder (and hit the width-2 program)
            loadgen(sdir, jobs=2, steps=8, seed=34, tenants=2, size=12,
                    prefix="w4")
            state["wave4"] = True

    summary = poll_daemon(
        serve_cmd(sdir, m6, st6, slot=2,
                  extra=("--slot-min", "2", "--slot-max", "8",
                         "--no-preempt", "--preempt-cost-chunks", "0.25",
                         "--admission-ledger", lpath)),
        st6, os.path.join(work, "daemon6.out"),
        os.path.join(work, "daemon6.err"), on_status)
    if not state["wave4"]:
        raise SystemExit(f"[serve-gate] stage 6 never reached the later "
                         f"waves: {state}")
    if summary.get("retired") != 18 or not summary.get("resizes"):
        raise SystemExit(f"[serve-gate] want 18 retired with >= 1 resize: "
                         f"{summary}")
    recs = load_records(m6)
    grew = [r for r in by_name(recs, "serve.resized")
            if r.get("reason") == "grow" and r.get("from_width") == 2]
    if not grew:
        raise SystemExit(f"[serve-gate] want a grow from width 2: "
                         f"{by_name(recs, 'serve.resized')}")
    shrank = [r for r in by_name(recs, "serve.resized")
              if r.get("reason") == "shrink"]
    if not shrank:
        raise SystemExit(f"[serve-gate] the post-surge trickle must "
                         f"shrink the slot back down the ladder: "
                         f"{by_name(recs, 'serve.resized')}")
    parked = [r for r in by_name(recs, "serve.parked")
              if r.get("reason") == "resize"]
    if not parked or not all(0 < r["step"] < steps1 for r in parked):
        raise SystemExit(f"[serve-gate] the grow must park the running "
                         f"lanes mid-flight: "
                         f"{[(r.get('job'), r.get('step')) for r in parked]}")
    builds = [r["key"] for r in by_name(recs, "compile.build")]
    if len(builds) != len(set(builds)):
        raise SystemExit(f"[serve-gate] a width revisit must be a cache "
                         f"HIT — some program compiled twice: {builds}")
    widths = {json.loads(k).get("batch") for k in builds} - {None}
    slot_widths = {r.get("width") for r in by_name(recs, "campaign.slot")}
    if len(widths) < 2 or 2 not in slot_widths or not (slot_widths - {2}):
        raise SystemExit(f"[serve-gate] want slots at width 2 AND a grown "
                         f"width, one program each: builds={sorted(widths)} "
                         f"slots={sorted(slot_widths)}")
    run([PY, "-m", "stencil_tpu.apps.report", m6, "--validate"],
        name="validate-6")
    print(f"[serve-gate] stage 6: grew 2 -> {grew[0].get('to_width')} "
          f"mid-slot ({len(parked)} resize parks), second wave at the "
          f"grown width recompiled nothing ({len(builds)} builds for "
          f"widths {sorted(widths)}), post-surge trickle shrank back to "
          f"{shrank[0].get('to_width')}")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out-dir", default="",
                   help="keep status/metrics artifacts here for CI upload "
                        "(default: a temp dir, removed)")
    args = p.parse_args()
    work = tempfile.mkdtemp(prefix="serve-gate-")
    try:
        stage1_continuous_batching(work)
        stage2_sigterm_drain(work)
        stage3_kill_revive_bit_identical(work)
        stage4_slo_pressure_replan(work)
        stage5_preemption_bit_identical(work)
        stage6_elastic_resize(work)
        if args.out_dir:
            out = os.path.abspath(args.out_dir)
            os.makedirs(out, exist_ok=True)
            for name in os.listdir(work):
                if name.endswith((".jsonl", ".json", ".out", ".err")):
                    shutil.copy2(os.path.join(work, name),
                                 os.path.join(out, name))
            print(f"[serve-gate] artifacts: {out}")
        print("[serve-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
