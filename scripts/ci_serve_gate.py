#!/usr/bin/env python
"""CI serving gate: continuous batching, drain, revival, SLO replan.

The executable acceptance proof of ISSUE 19 (stencil_tpu/serve/ — the
always-on campaign serving daemon) on the 8-virtual-device CPU mesh,
no TPU needed:

1. **continuous batching**: 8 pre-dropped jobs overflow a ``--slot 4``
   daemon; the gate polls the atomic status snapshot, and the moment a
   slot is observed RUNNING it drops a 9th job into the live intake —
   the final summary must show exactly ONE slot, every job retired,
   and >= 5 backfills (jobs entered mid-slot, no slot-wide barrier);
   the metric stream must show the late job's ``serve.admitted``
   AFTER ``campaign.slot``, and a mid-run status poll must see the
   queue's ``admitted`` count reach 9 while the slot is still going;
2. **SIGTERM drain**: a daemon mid-slot on 3 long jobs receives
   SIGTERM and must exit 0 with outcome ``drained``, every trajectory
   parked mid-flight (``serve.parked`` with 0 < step < steps, zero
   retirements), and a restarted daemon revives all 3 from
   ``serve-state.json`` and finishes them — each job retires exactly
   once across both runs;
3. **kill -> revive bit-identical**: the daemon runs under the PR 3
   watchdog (``obs/watchdog.supervise``) with the injected kill hook
   (``STENCIL_SERVE_KILL_AFTER_RETIRE=2`` -> ``os._exit(17)``); the
   watchdog classifies the death as a CRASH, the revival attempt
   finishes the queue, no retired job is ever re-run, and EVERY
   tenant's final snapshot is bit-identical to an uninterrupted
   reference serve of the same seeded load (``ckpt_tool diff --data``);
4. **SLO-pressure replan**: deadline-doomed jobs (no admission ledger,
   so they are admitted and the pressure builds online) must emit
   ``replan.requested`` with reason ``slo-pressure`` and hot-swap a
   plan between slots (``replan.applied``, trigger ``slo-pressure``)
   persisted into ``--plan-db``;
5. every metrics file passes ``report --validate``.

Exit 0 only if every stage holds. Run from the repo root:

  python scripts/ci_serve_gate.py [--out-dir DIR]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable
KILL_ENV = "STENCIL_SERVE_KILL_AFTER_RETIRE"

# one compiled bucket for stage 1: the late drop must be backfillable
# into the already-running slot's program
SIZE = 14


def run(cmd, expect_rc=0, name="", env=None):
    print(f"[serve-gate] {name}: {' '.join(cmd)}", flush=True)
    p = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                       env=env)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[serve-gate] {name}: rc={p.returncode}, expected {expect_rc}")
    return p


def load_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def by_name(records, name):
    return [r for r in records if r["name"] == name]


def summary_of(stdout_text, name):
    """The daemon's one-line JSON summary (the last JSON line printed)."""
    for line in reversed(stdout_text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"[serve-gate] {name}: no JSON summary in stdout")


def loadgen(serve_dir, *, jobs, steps, seed, tenants=2, size=SIZE,
            rate=0.0, prefix="j", deadline_ms=0.0):
    cmd = [PY, os.path.join(REPO, "scripts", "serve_loadgen.py"),
           "--serve-dir", serve_dir, "--jobs", str(jobs),
           "--steps", str(steps), "--seed", str(seed),
           "--tenants", str(tenants), "--size", str(size),
           "--rate", str(rate), "--prefix", prefix]
    if deadline_ms > 0:
        cmd += ["--deadline-ms", str(deadline_ms)]
    return run(cmd, name=f"loadgen-{prefix}{seed}")


def serve_cmd(serve_dir, metrics, status, *, slot=4, max_idle_s=2.0,
              extra=()):
    return [PY, "-m", "stencil_tpu.apps.serve", "--serve-dir", serve_dir,
            "--cpu", "8", "--slot", str(slot), "--chunk", "2",
            "--poll-s", "0.05", "--max-idle-s", str(max_idle_s),
            "--metrics-out", metrics, "--status-file", status,
            *extra]


def read_status(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # not written yet / mid-rename on exotic FS


def newest_snapshot(serve_dir, tid):
    d = os.path.join(serve_dir, "campaign", "tenants", tid)
    steps = [s for s in os.listdir(d) if s.startswith("step-")]
    if not steps:
        raise SystemExit(f"[serve-gate] no snapshots under {d}")
    return os.path.join(
        d, max(steps, key=lambda s: int(s.split("-", 1)[1])))


def retired_jobs(*metric_paths):
    out = []
    for path in metric_paths:
        out.extend(r["job"] for r in by_name(load_records(path),
                                             "serve.retired"))
    return out


def stage1_continuous_batching(work):
    sdir = os.path.join(work, "s1")
    m1 = os.path.join(work, "m1.jsonl")
    st1 = os.path.join(work, "status1.json")
    loadgen(sdir, jobs=8, steps=12, seed=7, tenants=3)
    cmd = serve_cmd(sdir, m1, st1)
    print(f"[serve-gate] daemon (polled): {' '.join(cmd)}", flush=True)
    # child output goes to FILES, not pipes: the poll loop never drains
    # a pipe, so a chatty child would fill the OS buffer and deadlock
    # the gate (the round-4 bench.py lesson watchdog.supervise encodes)
    out_path = os.path.join(work, "daemon1.out")
    err_path = os.path.join(work, "daemon1.err")
    polls, dropped_late, seen_nine = [], False, False
    with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=out_f, stderr=err_f,
                                text=True)
        while proc.poll() is None:
            doc = read_status(st1)
            if doc and doc.get("queue"):
                q = doc["queue"]
                polls.append({"step": doc.get("step"),
                              "admitted": q.get("admitted"),
                              "depth": q.get("depth")})
                mid_run = not doc.get("outcome")
                if (not dropped_late and mid_run
                        and (doc.get("step") or 0) >= 2):
                    # the slot is observably RUNNING: drop job 9 into
                    # the live intake — it must be admitted and
                    # backfilled into THIS slot, not a second one
                    loadgen(sdir, jobs=1, steps=4, seed=1, tenants=1,
                            prefix="late")
                    dropped_late = True
                if dropped_late and mid_run and q.get("admitted") == 9:
                    seen_nine = True
            time.sleep(0.05)
        proc.wait()
    if proc.returncode != 0:
        with open(err_path) as f:
            print(f.read()[-8000:], file=sys.stderr)
        raise SystemExit(f"[serve-gate] daemon1 rc={proc.returncode}")
    if not dropped_late:
        raise SystemExit(
            f"[serve-gate] the status snapshot never showed a running "
            f"slot, so the late job was never dropped ({len(polls)} polls)")
    if not seen_nine:
        raise SystemExit(
            "[serve-gate] no mid-run status poll observed the late job "
            f"admitted (queue.admitted == 9): {polls[-6:]}")
    with open(out_path) as f:
        summary = summary_of(f.read(), "daemon1")
    if summary.get("slots") != 1:
        raise SystemExit(f"[serve-gate] 9 jobs through a B=4 slot must "
                         f"run as ONE slot (continuous batching), got "
                         f"slots={summary.get('slots')}")
    if summary.get("retired") != 9 or summary.get("rejected"):
        raise SystemExit(f"[serve-gate] want 9 retired / 0 rejected: "
                         f"{summary}")
    if summary.get("backfills", 0) < 5:
        raise SystemExit(f"[serve-gate] 9 jobs minus 4 lanes means >= 5 "
                         f"backfills, got {summary.get('backfills')}")
    results = os.listdir(os.path.join(sdir, "results"))
    if len(results) != 9:
        raise SystemExit(f"[serve-gate] want 9 streamed results, got "
                         f"{sorted(results)}")
    recs = load_records(m1)
    slot_idx = min(i for i, r in enumerate(recs)
                   if r["name"] == "campaign.slot")
    late_idx = [i for i, r in enumerate(recs)
                if r["name"] == "serve.admitted"
                and r["job"].startswith("late-")]
    if not late_idx or late_idx[0] <= slot_idx:
        raise SystemExit(
            f"[serve-gate] the late job's serve.admitted must land AFTER "
            f"campaign.slot (admitted mid-slot): slot at {slot_idx}, "
            f"late at {late_idx}")
    run([PY, "-m", "stencil_tpu.apps.report", m1, "--validate"],
        name="validate-1")
    print(f"[serve-gate] stage 1: 1 slot, {summary['backfills']} "
          f"backfills, late job admitted mid-slot (status poll saw "
          f"admitted=9 live; {len(polls)} polls)")


def stage2_sigterm_drain(work):
    sdir = os.path.join(work, "s2")
    m2a = os.path.join(work, "m2a.jsonl")
    m2b = os.path.join(work, "m2b.jsonl")
    st2 = os.path.join(work, "status2.json")
    steps = 16
    loadgen(sdir, jobs=3, steps=steps, seed=5, tenants=3, size=12)
    cmd = serve_cmd(sdir, m2a, st2, slot=4)
    print(f"[serve-gate] daemon (SIGTERM pending): {' '.join(cmd)}",
          flush=True)
    out_path = os.path.join(work, "daemon2.out")
    err_path = os.path.join(work, "daemon2.err")
    with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=out_f, stderr=err_f,
                                text=True)
        while proc.poll() is None:
            doc = read_status(st2)
            if doc and (doc.get("step") or 0) >= 2 and not doc.get("outcome"):
                proc.send_signal(signal.SIGTERM)
                break
            time.sleep(0.05)
        rc = proc.wait(timeout=120)
    if rc != 0:
        with open(err_path) as f:
            print(f.read()[-8000:], file=sys.stderr)
        raise SystemExit(f"[serve-gate] SIGTERM must drain to exit 0, "
                         f"got rc={rc}")
    with open(out_path) as f:
        summary = summary_of(f.read(), "daemon2")
    if summary.get("outcome") != "drained" or summary.get("retired") != 0:
        raise SystemExit(f"[serve-gate] want outcome=drained with 0 "
                         f"retired (parked mid-flight): {summary}")
    if summary.get("queued_remaining") != 3:
        raise SystemExit(f"[serve-gate] all 3 jobs must survive the drain "
                         f"in the queue: {summary}")
    recs = load_records(m2a)
    parked = by_name(recs, "serve.parked")
    if len(parked) != 3 or not all(0 < r["step"] < steps for r in parked):
        raise SystemExit(f"[serve-gate] want 3 mid-flight parks "
                         f"(0 < step < {steps}): "
                         f"{[(r.get('job'), r.get('step')) for r in parked]}")
    drains = by_name(recs, "serve.drain")
    if not drains or drains[0].get("reason") != "sigterm":
        raise SystemExit(f"[serve-gate] serve.drain must name sigterm: "
                         f"{drains}")
    if not os.path.exists(os.path.join(sdir, "serve-state.json")):
        raise SystemExit("[serve-gate] drain left no serve-state.json")

    g = run(serve_cmd(sdir, m2b, st2, slot=4), name="drain-revival")
    summary = summary_of(g.stdout, "drain-revival")
    if summary.get("revived") != 3 or summary.get("retired") != 3:
        raise SystemExit(f"[serve-gate] the restart must revive and "
                         f"finish all 3: {summary}")
    jobs = retired_jobs(m2a, m2b)
    if sorted(jobs) != sorted(set(jobs)) or len(set(jobs)) != 3:
        raise SystemExit(f"[serve-gate] each job must retire exactly "
                         f"once across drain+revival: {sorted(jobs)}")
    for path, name in ((m2a, "validate-2a"), (m2b, "validate-2b")):
        run([PY, "-m", "stencil_tpu.apps.report", path, "--validate"],
            name=name)
    print("[serve-gate] stage 2: SIGTERM drained (3 mid-flight parks), "
          "restart revived and finished all 3, nobody re-ran")


def stage3_kill_revive_bit_identical(work):
    spec = importlib.util.spec_from_file_location(
        "stencil_watchdog",
        os.path.join(REPO, "stencil_tpu", "obs", "watchdog.py"))
    watchdog = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = watchdog  # dataclass resolves __module__
    spec.loader.exec_module(watchdog)

    ref = os.path.join(work, "s3-ref")
    killed = os.path.join(work, "s3-killed")
    for d in (ref, killed):
        loadgen(d, jobs=5, steps=6, seed=11, tenants=2, size=12)
    m_ref = os.path.join(work, "m3ref.jsonl")
    g = run(serve_cmd(ref, m_ref, os.path.join(work, "status3r.json")),
            name="reference-serve")
    if summary_of(g.stdout, "reference-serve").get("retired") != 5:
        raise SystemExit("[serve-gate] reference serve must retire all 5")

    m3a = os.path.join(work, "m3a.jsonl")
    m3b = os.path.join(work, "m3b.jsonl")
    st3 = os.path.join(work, "status3.json")
    env = dict(os.environ)
    env[KILL_ENV] = "2"
    att = watchdog.supervise(
        serve_cmd(killed, m3a, st3), timeout_s=300, env=env, cwd=REPO,
        name="serve-killed")
    if att.outcome != watchdog.CRASH or att.rc != 17:
        raise SystemExit(f"[serve-gate] the kill hook must die as a "
                         f"watchdog CRASH with rc 17: outcome="
                         f"{att.outcome} rc={att.rc}")
    att = watchdog.supervise(
        serve_cmd(killed, m3b, st3), timeout_s=300, cwd=REPO,
        name="serve-revived")
    if att.outcome != watchdog.OK:
        raise SystemExit(f"[serve-gate] revival attempt: outcome="
                         f"{att.outcome} rc={att.rc}\n{att.stderr_tail}")
    summary = summary_of(att.stdout, "serve-revived")
    if summary.get("retired") != 3 or not summary.get("revived"):
        raise SystemExit(f"[serve-gate] revival must pick up the 3 "
                         f"unserved jobs (2 retired pre-kill): {summary}")
    jobs = retired_jobs(m3a, m3b)
    if sorted(jobs) != sorted(set(jobs)) or len(set(jobs)) != 5:
        raise SystemExit(f"[serve-gate] kill+revival must retire each of "
                         f"the 5 jobs exactly once: {sorted(jobs)}")
    for tid in sorted(set(jobs)):
        a = newest_snapshot(killed, tid)
        b = newest_snapshot(ref, tid)
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "diff", a, b,
             "--data"], name=f"diff-{tid}")
    for path, name in ((m3a, "validate-3a"), (m3b, "validate-3b")):
        run([PY, "-m", "stencil_tpu.apps.report", path, "--validate"],
            name=name)
    print("[serve-gate] stage 3: watchdog CRASH rc=17 at 2 retirements, "
          "revival finished 3, all 5 finals bit-identical to the "
          "uninterrupted reference")


def stage4_slo_pressure_replan(work):
    sdir = os.path.join(work, "s4")
    m4 = os.path.join(work, "m4.jsonl")
    plan_db = os.path.join(work, "plans4.json")
    # no admission ledger: the doomed deadline cannot be priced at
    # admission, so the jobs run and the ONLINE p99 builds the pressure
    loadgen(sdir, jobs=4, steps=8, seed=3, tenants=2, size=12,
            deadline_ms=0.001)
    g = run(serve_cmd(sdir, m4, os.path.join(work, "status4.json"),
                      extra=("--replan", "--plan-db", plan_db)),
            name="slo-pressure-serve")
    summary = summary_of(g.stdout, "slo-pressure-serve")
    if summary.get("retired") != 4:
        raise SystemExit(f"[serve-gate] a deadline breach is evidence, "
                         f"not an eviction — all 4 must finish: {summary}")
    recs = load_records(m4)
    req = [r for r in by_name(recs, "replan.requested")
           if r.get("reason") == "slo-pressure"]
    if not req:
        raise SystemExit("[serve-gate] no slo-pressure replan.requested")
    app = [r for r in by_name(recs, "replan.applied")
           if r.get("trigger") == "slo-pressure"]
    if not app:
        raise SystemExit(f"[serve-gate] the latched pressure must "
                         f"hot-swap between slots (replan.applied): "
                         f"{by_name(recs, 'replan.rejected')}")
    if not os.path.exists(plan_db) or not os.path.getsize(plan_db):
        raise SystemExit("[serve-gate] the re-tuned plan must persist "
                         "into --plan-db")
    run([PY, "-m", "stencil_tpu.apps.report", m4, "--validate"],
        name="validate-4")
    print(f"[serve-gate] stage 4: slo-pressure requested at step "
          f"{req[0].get('step')}, plan {app[0].get('old')} -> "
          f"{app[0].get('new')} persisted")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out-dir", default="",
                   help="keep status/metrics artifacts here for CI upload "
                        "(default: a temp dir, removed)")
    args = p.parse_args()
    work = tempfile.mkdtemp(prefix="serve-gate-")
    try:
        stage1_continuous_batching(work)
        stage2_sigterm_drain(work)
        stage3_kill_revive_bit_identical(work)
        stage4_slo_pressure_replan(work)
        if args.out_dir:
            out = os.path.abspath(args.out_dir)
            os.makedirs(out, exist_ok=True)
            for name in os.listdir(work):
                if name.endswith((".jsonl", ".json", ".out", ".err")):
                    shutil.copy2(os.path.join(work, name),
                                 os.path.join(out, name))
            print(f"[serve-gate] artifacts: {out}")
        print("[serve-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
