#!/bin/bash
# Round-5 TPU revival watcher (VERDICT r4 item 1: "automate the firing").
# Probes the tunneled chip at low cadence; on a successful probe it fires
# the serialized measurement queue (scripts/r04_measure.sh) with logs under
# scripts/r05_logs. If the queue aborts at its own alive gate (tunnel flap:
# one probe answers, then it re-wedges), the watch loop CONTINUES so a
# later real revival is not missed. Exit codes: 0 = queue ran and every
# step completed; 3 = queue ran (gate passed) but some steps failed or
# timed out (see session.log); 2 = deadline reached with no gate-passed
# queue run.
#
# One TPU job at a time — the probe is the only TPU contact until the
# queue runs.
#
# Usage: bash scripts/r05_watch.sh [max_hours]
cd "$(dirname "$0")/.." || exit 1
LOG=scripts/r05_logs
mkdir -p "$LOG"
MAX_HOURS=${1:-11}
DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
QUEUE_RUNS=0

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  timeout 300 python scripts/tpu_alive_probe.py > "$LOG/probe_last.log" 2>&1
  probe_rc=$?
  ts=$(date +%FT%T)   # stamp AFTER the probe: these logs are outage evidence
  if grep -q '^alive' "$LOG/probe_last.log"; then
    echo "$ts ALIVE — firing measurement queue (run $((QUEUE_RUNS + 1)))" >> "$LOG/watch.log"
    MEASURE_LOG_DIR=$LOG bash scripts/r04_measure.sh >> "$LOG/watch.log" 2>&1
    rc=$?
    QUEUE_RUNS=$((QUEUE_RUNS + 1))
    echo "$(date +%FT%T) queue run $QUEUE_RUNS done rc=$rc (0 = all steps completed, >=10 = nothing ran)" >> "$LOG/watch.log"
    if [ "$rc" -lt 10 ]; then
      # The gate passed, so the queue genuinely ran (rc = failed-step
      # count; the gate abort has its own code). Do NOT re-fire the
      # multi-hour queue automatically — partial logs are valid and
      # resuming a specific step is an operator decision
      # (bash scripts/r04_measure.sh <step>).
      [ "$rc" -eq 0 ] && exit 0 || exit 3
    fi
    # rc=10 gate abort: the probe answered but the tunnel re-wedged
    # before the queue's own gate (a flap). Keep watching for a real
    # revival.
  else
    echo "$ts dead (probe rc=$probe_rc)" >> "$LOG/watch.log"
  fi
  sleep 600
done
echo "$(date +%FT%T) deadline reached after $QUEUE_RUNS flap-aborted queue run(s)" >> "$LOG/watch.log"
exit 2
