"""Round-3 probes on the real chip.

1. VMEM scratch compile ceiling: at what explicit-scratch size does a
   trivial kernel stop compiling? (pins _SCRATCH_BUDGET headroom)
2. Astaroth substep tile ablation: same tile count at different shapes vs
   half the tile count — separates HBM-traffic cost from per-tile
   (DMA-descriptor / scalar-core) cost.

Usage: python scripts/probe_r03.py [vmem|tiles]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def probe_vmem():
    for mb in (24, 28, 32, 36, 40, 44):
        n_planes = mb * 1024 * 1024 // (4 * 128 * 512)

        def kernel(x_hbm, o_hbm, scratch, sem):
            cp = pltpu.make_async_copy(x_hbm, scratch.at[0], sem)
            cp.start()
            cp.wait()
            scratch[1] = scratch[0] * 2.0
            cp2 = pltpu.make_async_copy(scratch.at[1], o_hbm, sem)
            cp2.start()
            cp2.wait()

        fn = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((128, 512), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((n_planes, 128, 512), jnp.float32),
                pltpu.SemaphoreType.DMA(()),
            ],
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=128 * 1024 * 1024,
            ),
        )
        x = jnp.ones((128, 512), jnp.float32)
        t0 = time.time()
        try:
            out = jax.jit(fn)(x)
            out.block_until_ready()
            print(f"vmem {mb} MB ({n_planes} planes): OK "
                  f"(compile+run {time.time()-t0:.1f}s)", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"vmem {mb} MB: FAIL {type(e).__name__}: {str(e)[:200]}",
                  flush=True)
            break


def probe_tiles():
    from stencil_tpu.astaroth.config import load_config
    from stencil_tpu.astaroth.equations import Constants
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.ops.pallas_astaroth import FIELDS, make_pallas_substep
    from stencil_tpu.utils.statistics import Statistics
    from stencil_tpu.utils.sync import hard_sync

    n = 256
    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3))
    info, _ = load_config("stencil_tpu/astaroth/astaroth.conf")
    c = Constants.from_info(info)
    inv_ds = (
        info.real_params["AC_inv_dsx"],
        info.real_params["AC_inv_dsy"],
        info.real_params["AC_inv_dsz"],
    )
    p = spec.padded()
    rng = np.random.RandomState(7)
    curr = tuple(
        jnp.asarray(rng.rand(p.z, p.y, p.x) * 0.1, jnp.float32) for _ in FIELDS
    )
    out_np = rng.rand(p.z, p.y, p.x) * 0.1

    chunk = 60
    # sliding-window scratch at 256^3 (px=384): (2,64) 16.5 MB [pick];
    # (4,32) 15.3 MB; (4,64)/(8,32) 27.1 MB; (2,128)/(16,16) 30.7 MB
    for tiles in ((4, 32), (4, 64), (8, 32), (2, 128), (16, 16)):
        # fresh out buffers each variant: the timing loop donates them
        out = tuple(jnp.asarray(out_np, jnp.float32) for _ in FIELDS)
        try:
            sub = make_pallas_substep(spec, c, inv_ds, 1, 1e-8, tiles=tiles)

            def many(cu, ou):
                def body(_, o):
                    return sub(cu, o)
                return jax.lax.fori_loop(0, chunk, body, ou)

            fn = jax.jit(many, donate_argnums=(1,))
            t0 = time.time()
            out2 = fn(curr, out)
            hard_sync(out2)
            compile_s = time.time() - t0
            st = Statistics()
            for _ in range(3):
                t0 = time.perf_counter()
                out2 = fn(curr, out2)
                hard_sync(out2)
                st.insert((time.perf_counter() - t0) / chunk)
            print(
                f"tiles {tiles}: {st.trimean()*1e3:.2f} ms/substep "
                f"(compile {compile_s:.0f}s)", flush=True,
            )
        except Exception as e:  # noqa: BLE001
            print(f"tiles {tiles}: FAIL {type(e).__name__}: {str(e)[:300]}",
                  flush=True)




def probe_decomp():
    """Decompose substep cost: full vs trivial-physics (taps kept) vs
    trivial-derivatives (physics kept) at the best tile shape."""
    import stencil_tpu.ops.pallas_astaroth as pa
    from stencil_tpu.astaroth.config import load_config
    from stencil_tpu.astaroth.equations import Constants
    from stencil_tpu.astaroth.fd import FieldData, field_data
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.utils.statistics import Statistics
    from stencil_tpu.utils.sync import hard_sync

    n = 256
    # round-3 tight-x layout (the production single-chip path)
    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3).without_x())
    info, _ = load_config("stencil_tpu/astaroth/astaroth.conf")
    c = Constants.from_info(info)
    inv_ds = (
        info.real_params["AC_inv_dsx"],
        info.real_params["AC_inv_dsy"],
        info.real_params["AC_inv_dsz"],
    )
    p = spec.padded()
    rng = np.random.RandomState(7)
    curr = tuple(
        jnp.asarray(rng.rand(p.z, p.y, p.x) * 0.1, jnp.float32) for _ in pa.FIELDS
    )
    out_np = rng.rand(p.z, p.y, p.x) * 0.1

    orig = dict(
        continuity=pa.continuity, momentum=pa.momentum,
        induction=pa.induction, entropy=pa.entropy, field_data=pa.field_data,
    )

    def trivial_physics():
        pa.continuity = lambda uu, l: l.laplace()
        pa.momentum = lambda c, uu, l, s, aa: tuple(u.laplace() for u in uu)
        pa.induction = lambda c, uu, aa: tuple(
            a.laplace() + a.hxy + a.hxz + a.hyz + a.gx + a.gy + a.gz
            for a in aa
        )
        pa.entropy = lambda c, s, uu, l, aa: s.laplace()

    def trivial_derivs():
        def fake(arr, rect, ids):
            val = arr[...,
                      slice(rect.lo.z, rect.hi.z),
                      slice(rect.lo.y, rect.hi.y),
                      slice(rect.lo.x, rect.hi.x)]
            k = [val * (1.0 + 0.01 * i) for i in range(10)]
            return FieldData(*k)
        pa.field_data = fake

    chunk = 60
    for label, setup in (("full", None), ("triv-phys", trivial_physics),
                         ("triv-derivs", trivial_derivs)):
        for k, v in orig.items():
            setattr(pa, k, v)
        if setup:
            setup()
        try:
            sub = pa.make_pallas_substep(spec, c, inv_ds, 1, 1e-8)
            out = tuple(jnp.asarray(out_np, jnp.float32) for _ in pa.FIELDS)

            def many(cu, ou):
                return jax.lax.fori_loop(0, chunk, lambda _, o: sub(cu, o), ou)

            fn = jax.jit(many, donate_argnums=(1,))
            t0 = time.time()
            out2 = fn(curr, out)
            hard_sync(out2)
            cs = time.time() - t0
            st = Statistics()
            for _ in range(3):
                t0 = time.perf_counter()
                out2 = fn(curr, out2)
                hard_sync(out2)
                st.insert((time.perf_counter() - t0) / chunk)
            print(f"decomp {label}: {st.trimean()*1e3:.2f} ms/substep "
                  f"(compile {cs:.0f}s)", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"decomp {label}: FAIL {type(e).__name__}: {str(e)[:300]}",
                  flush=True)
    for k, v in orig.items():
        setattr(pa, k, v)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    print("devices:", jax.devices(), flush=True)
    if which in ("vmem", "all"):
        probe_vmem()
    if which in ("tiles", "all"):
        probe_tiles()
    if which == "decomp":
        probe_decomp()
