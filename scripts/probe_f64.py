"""fp64-on-TPU diagnosis (VERDICT r2 item 3).

Measures XLA-path fp64 astaroth compile+run time vs grid size, with the
iteration jitted whole vs substep-chunked, to locate the compile-time
explosion and find a shippable (slow-but-working) fp64 configuration.

Usage: python scripts/probe_f64.py [sizes...]
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_ENABLE_X64", "1")
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np

from stencil_tpu.astaroth import config as ac_config
from stencil_tpu.astaroth.integrate import FIELDS, make_astaroth_step
from stencil_tpu.apps.astaroth import DEFAULT_CONF
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.utils.sync import hard_sync

sizes = [int(s) for s in sys.argv[1:]] or [16, 32, 64]
# STENCIL_PROBE_F64_OVERLAP=1: build the round-4 hoisted-exchange overlap
# iteration (9 integrate bodies) instead of the serialized step — the
# fp64+overlap compile experiment (VERDICT r3 item 3)
OV = os.environ.get("STENCIL_PROBE_F64_OVERLAP") == "1"
print("devices:", jax.devices(), flush=True)

for n in sizes:
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    size = Dim3(n, n, n)
    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:1])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(0)
    fields = {k: rng.randn(n, n, n) * 0.05 for k in FIELDS}
    fields["lnrho"] = fields["lnrho"] + 0.5
    try:
        step = make_astaroth_step(ex, info, dt=1e-8, overlap=OV,
                                  use_pallas=False, dtype="float64")
        curr = {k: shard_blocks(fields[k], spec, mesh, dtype=np.float64)
                for k in FIELDS}
        nxt = {k: shard_blocks(np.zeros((n, n, n)), spec, mesh,
                               dtype=np.float64) for k in FIELDS}
        t0 = time.time()
        curr, nxt = step(curr, nxt)
        hard_sync(curr)
        compile_s = time.time() - t0
        t0 = time.perf_counter()
        for _ in range(3):
            curr, nxt = step(curr, nxt)
        hard_sync(curr)
        run_ms = (time.perf_counter() - t0) / 3 * 1e3
        finite = bool(np.isfinite(np.asarray(jax.device_get(curr["lnrho"]))).all())
        print(f"f64 {n}^3 XLA-path overlap={OV}: compile {compile_s:.0f}s, "
              f"{run_ms:.1f} ms/iter, finite={finite}", flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"f64 {n}^3 XLA-path overlap={OV}: FAIL {type(e).__name__}: {str(e)[:300]}",
              flush=True)
