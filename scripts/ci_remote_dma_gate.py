#!/usr/bin/env python
"""CI remote-DMA gate: the ISSUE-10 acceptance proof on the CPU mesh.

Four stages, exit 0 only if every one holds:

1. **parity + census**: a 24^3 REMOTE_DMA exchange on the 2x2x2
   8-virtual-device mesh is bit-identical to AXIS_COMPOSED on coordinate
   fields (fp32 AND a mixed fp32/fp64 dict), its census over every
   compiled piece of the emulation contains ZERO collective-permutes,
   and the recorded ``exchange.permutes_per_quantity`` gauge reads 0;
2. **wire A/B**: ``bench_exchange --wire-ab`` at the same config must
   report >= 1.9x on-wire byte reduction for bfloat16 with the measured
   max error inside the bf16 rounding bound (the app exits 1 itself
   otherwise) and schema-valid metrics;
3. **autotuner round-trip**: ``plan_tool autotune --methods remote-dma``
   tunes (measured probes run against the emulation), persists a
   remote-dma-keyed entry, and a second invocation replays it as a pure
   DB hit with zero probes;
4. **schema**: every metrics file passes ``report --validate``.

Run from the repo root:  python scripts/ci_remote_dma_gate.py [--size 24]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

PARITY_CHILD = r"""
import sys
import stencil_tpu  # first: applies the jax-compat shims (old-jax containers)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", True)
import numpy as np
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.obs import telemetry
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks

size, metrics = int(sys.argv[1]), sys.argv[2]
rec = telemetry.configure(metrics_out=metrics, app="ci_remote_dma_gate")
spec = GridSpec(Dim3(size, size, size), Dim3(2, 2, 2), Radius.constant(2))
mesh = grid_mesh(spec.dim, jax.devices()[:8])
g = spec.global_size
coord = (np.arange(g.z)[:, None, None] * 1e6
         + np.arange(g.y)[None, :, None] * 1e3
         + np.arange(g.x)[None, None, :])

def state(dtypes):
    return {i: shard_blocks((coord + i).astype(dt), spec, mesh)
            for i, dt in enumerate(dtypes)}

for dtypes in ([np.float32] * 4, [np.float32, np.float64, np.float32]):
    outs = {}
    for method in (Method.AXIS_COMPOSED, Method.REMOTE_DMA):
        ex = HaloExchange(spec, mesh, method)
        out = ex(state(dtypes))
        outs[method] = [np.asarray(jax.device_get(out[i]))
                        for i in sorted(out)]
        if method == Method.REMOTE_DMA:
            census = ex.collective_census(state(dtypes))
            assert census.get("collective-permute", (0, 0))[0] == 0, census
            assert sum(c for c, _b in census.values()) == 0, census
            itemsizes = [np.dtype(dt).itemsize for dt in dtypes]
            telemetry.record_exchange_truth(ex, state(dtypes), itemsizes)
    for a, b in zip(outs[Method.AXIS_COMPOSED], outs[Method.REMOTE_DMA]):
        assert np.array_equal(a, b), "REMOTE_DMA differs from AXIS_COMPOSED"
rec.close()
print("REMOTE_DMA_PARITY_OK")
"""


def run(cmd, env=None, expect_rc=0, name=""):
    print(f"[remote-dma-gate] {name}: {' '.join(cmd)}", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    p = subprocess.run(cmd, env=e, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[remote-dma-gate] {name}: rc={p.returncode}, "
            f"expected {expect_rc}"
        )
    return p


def metrics_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="remote-dma-gate-")
    db = os.path.join(work, "plans.json")
    try:
        # 1. parity + 0-ppermute census + gauge
        pm = os.path.join(work, "parity.jsonl")
        r = run([PY, "-c", PARITY_CHILD, str(args.size), pm], name="parity")
        if "REMOTE_DMA_PARITY_OK" not in r.stdout:
            raise SystemExit("[remote-dma-gate] parity child gave no verdict")
        gauges = [rec for rec in metrics_records(pm)
                  if rec["kind"] == "gauge"
                  and rec["name"] == "exchange.permutes_per_quantity"]
        if not gauges or any(g["value"] != 0 for g in gauges):
            raise SystemExit(
                f"[remote-dma-gate] permutes_per_quantity gauge not 0: "
                f"{[g.get('value') for g in gauges]}"
            )

        # 2. bf16 wire A/B (the app's own gate: >=1.9x bytes + error bound)
        wm = os.path.join(work, "wire.jsonl")
        run([PY, "-m", "stencil_tpu.apps.bench_exchange", "--wire-ab",
             "--x", str(args.size), "--y", str(args.size),
             "--z", str(args.size), "--iters", "3", "--quantities", "4",
             "--partition", "2x2x2", "--metrics-out", wm],
            env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
            name="wire-ab")
        ratios = [rec["value"] for rec in metrics_records(wm)
                  if rec["kind"] == "gauge"
                  and rec["name"] == "wire_ab.bytes_ratio"]
        if not ratios or ratios[-1] < 1.9:
            raise SystemExit(
                f"[remote-dma-gate] wire bytes ratio {ratios} < 1.9")

        # 3. autotuner DB round-trip with a remote-dma-keyed entry
        def tune(metrics, name):
            return run(
                [PY, "-m", "stencil_tpu.apps.plan_tool", "autotune",
                 "--cpu", "8", "--db", db, "--methods", "remote-dma",
                 "--x", str(args.size), "--y", str(args.size),
                 "--z", str(args.size), "--radius", "2",
                 "--quantities", "1", "--probe-iters", "2", "--top-n", "1",
                 "--metrics-out", metrics],
                name=name,
            )

        t1 = os.path.join(work, "tune.jsonl")
        r = tune(t1, "tune-remote")
        if "remote-dma" not in r.stdout:
            raise SystemExit("[remote-dma-gate] tuner did not pick "
                             f"remote-dma:\n{r.stdout}")
        t2 = os.path.join(work, "replay.jsonl")
        r = tune(t2, "replay-remote")
        if "cache_hit: True" not in r.stdout or "probes_run: 0" not in r.stdout:
            raise SystemExit("[remote-dma-gate] replay was not a pure DB "
                             f"hit:\n{r.stdout}")
        with open(db) as f:
            dbobj = json.load(f)
        methods = [e["choice"]["method"] for e in dbobj["entries"].values()]
        if methods != ["remote-dma"]:
            raise SystemExit(
                f"[remote-dma-gate] DB entries carry {methods}, expected "
                "exactly one remote-dma entry")

        # 4. every metrics file passes the schema gate
        run([PY, "-m", "stencil_tpu.apps.report", pm, wm, t1, t2,
             "--validate"], name="schema")
        print("[remote-dma-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
