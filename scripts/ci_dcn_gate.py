#!/usr/bin/env python
"""CI hierarchical ICI+DCN gate: the ISSUE-17 acceptance proof on the
CPU mesh (STENCIL_VIRTUAL_HOSTS virtual-host fabric).

Five stages, exit 0 only if every one holds:

1. **step-loop bit parity**: at 16^3 on the 2x2x2 8-virtual-device mesh
   split z x 2 hosts, the hierarchical exchange (cross-host DCN slabs
   started before the inner per-host programs, ``parallel/hierarchy.py``)
   lands the 5-iteration jacobi loop bit-identical to the flat plan
   through EVERY inner transport — axis-composed (overlap on and off),
   remote-dma, fused, persistent;
2. **DCN conformance**: ``lint_tool verify-plan --hierarchy 2`` audits
   predicted-vs-executed DCN transfers and wire bytes, unchanged inner
   census pins, zero stray collectives, and flat bit parity across
   partitions x inner methods x dtype sets — and ``--perturb-dcn 1``
   must TRIP it (rc 1: the auditor has teeth);
3. **two-level NodeAware**: on the anisotropic 16x16x64 grid with an
   interleaved 2-host device map (the scrambled fabric), the blocks->
   hosts + blocks->chips QAP composes a placement STRICTLY cheaper than
   identity (pinned cost values), while the uniform fabric solves to
   identity by design (``(None, None)`` — flat-equivalent);
4. **autotuner round-trip**: with the virtual-host fabric open, the
   ranked candidate space contains hierarchical plans, the winner
   persists, a second invocation replays it as a pure DB hit with zero
   probes, the DB validates, and a hierarchical choice realizes
   end-to-end through ``DistributedDomain`` (executed DCN transfers
   nonzero); all metrics pass ``report --validate``;
5. **lint**: the repo lint stays green over the new modules.

Run from the repo root:  python scripts/ci_dcn_gate.py
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

CHILD_PRELUDE = r"""
import os
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["STENCIL_VIRTUAL_HOSTS"] = "2"
import stencil_tpu  # first: applies the jax-compat shims
import jax
import numpy as np
"""

PARITY_CHILD = CHILD_PRELUDE + r"""
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_masks
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks

spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
g = spec.global_size
rng = np.random.default_rng(0)
CURR = rng.standard_normal((g.z, g.y, g.x)).astype(np.float32)
hot, cold = sphere_masks(g)
SEL = np.zeros((g.z, g.y, g.x), np.float32)
SEL[hot] = 1
SEL[cold] = 2

def run(method, hierarchy, iters=5, overlap=True, **kw):
    mesh = grid_mesh(spec.dim)
    ex = HaloExchange(spec, mesh, method=method, hierarchy=hierarchy, **kw)
    c = shard_blocks(CURR, spec, mesh)
    n = shard_blocks(np.zeros_like(CURR), spec, mesh)
    s = shard_blocks(SEL, spec, mesh)
    loop = make_jacobi_loop(ex, iters, overlap=overlap)
    out, _ = loop(c, n, s)
    return np.asarray(jax.device_get(out))

def check(tag, a, b):
    assert np.array_equal(a, b), f"HIERARCHICAL differs from FLAT: {tag}"

flat = run(Method.AXIS_COMPOSED, None)
check("composed", flat, run(Method.AXIS_COMPOSED, ("z", 2)))
check("composed/overlap-off", flat,
      run(Method.AXIS_COMPOSED, ("z", 2), overlap=False))
check("remote-dma", run(Method.REMOTE_DMA, None),
      run(Method.REMOTE_DMA, ("z", 2)))
check("fused", run(Method.REMOTE_DMA, None, fused=True),
      run(Method.REMOTE_DMA, ("z", 2), fused=True))
check("persistent", run(Method.REMOTE_DMA, None, persistent=True),
      run(Method.REMOTE_DMA, ("z", 2), persistent=True))
check("remote==composed", flat, run(Method.REMOTE_DMA, None))
print("DCN_PARITY_OK")
"""

QAP_CHILD = r"""
import numpy as np
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.plan.cost import (placement_cost, placement_wire_matrix,
                                   solve_two_level_placement)

# the anisotropic grid: a 2x2x2 partition of 16x16x64 wires far more
# bytes across z faces than x/y, so host grouping MATTERS (a cubic grid
# ties by symmetry and proves nothing)
spec = GridSpec(Dim3(16, 16, 64), Dim3(2, 2, 2), Radius.constant(2))
md = spec.dim
w = placement_wire_matrix(spec, md)

# scrambled 2-host fabric: devices interleaved across hosts, cross-host
# links 7x the intra-host cost (the PR-15 process-boundary ladder)
host_map = [0, 1, 0, 1, 0, 1, 0, 1]
same = np.equal.outer(host_map, host_map)
link = np.where(np.eye(8, dtype=bool), 0.0, np.where(same, 1.0, 7.0))
hp, perm = solve_two_level_placement(w, link, md, ("z", 2), host_map)
assert perm is not None, "scrambled fabric solved to identity"
placed = placement_cost(w, link, perm)
ident = placement_cost(w, link, None)
print(f"two-level QAP: placed {placed:.0f} identity {ident:.0f} "
      f"perm {list(perm)}")
assert placed < ident, f"two-level placement not cheaper: {placed} >= {ident}"
assert (placed, ident) == (52736.0, 108032.0), (placed, ident)

# uniform fabric: identity by design — flat-equivalent
uni = np.where(np.eye(8, dtype=bool), 0.0, 1.0)
hp2, perm2 = solve_two_level_placement(w, uni, md, ("z", 2), None)
assert hp2 is None and perm2 is None, (hp2, perm2)
print("DCN_QAP_OK")
"""

TUNE_CHILD = CHILD_PRELUDE + r"""
import sys
from stencil_tpu.api import DistributedDomain
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.plan import db as plandb
from stencil_tpu.plan.autotune import autotune

dbp = sys.argv[1]
res = autotune(Dim3(32, 32, 32), Radius.constant(2), ["float32"],
               devices=jax.devices(), db_path=dbp, probe=True, top_n=3,
               probe_iters=2)
nhier = sum(1 for _c, ch in res.ranked if ch.is_hierarchical)
assert nhier > 0, "no hierarchical candidates in the ranked space"
res2 = autotune(Dim3(32, 32, 32), Radius.constant(2), ["float32"],
                devices=jax.devices(), db_path=dbp, probe=True)
assert res2.cache_hit and res2.probes_run == 0, (res2.cache_hit,
                                                 res2.probes_run)
assert res2.choice == res.choice
errs = plandb.validate_db(plandb.load_db(dbp))
assert not errs, errs[:3]

# a hierarchical choice realizes end-to-end and actually moves DCN slabs
ch = next(ch for _c, ch in res.ranked
          if ch.is_hierarchical and ch.method == "axis-composed")
dd = DistributedDomain(32, 32, 32, plan=ch)
dd.set_radius(2)
h = dd.add_data("u", "float32")
dd.realize()
assert dd.halo_exchange.hierarchical
assert dd.plan_meta()["choice"]["hierarchy"] is not None
dd.set_curr_global(h, np.random.default_rng(1)
                   .standard_normal((32, 32, 32)).astype(np.float32))
dd.exchange()
n = dd.halo_exchange._compiled.last_transfer_count
assert n > 0, "hierarchical exchange executed zero DCN transfers"
print(f"tuned {res.choice.label()} hier_candidates {nhier} dcn_copies {n}")
print("DCN_TUNE_OK")
"""


def run(cmd, env=None, expect_rc=0, name=""):
    shown = " ".join(a if len(a) < 200 else "<inline child>" for a in cmd)
    print(f"[dcn-gate] {name}: {shown}", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    p = subprocess.run(cmd, env=e, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(f"[dcn-gate] {name}: rc={p.returncode}, "
                         f"expected {expect_rc}")
    return p


def main() -> int:
    work = tempfile.mkdtemp(prefix="dcn-gate-")
    try:
        # 1. flat == hierarchical through every inner transport
        r = run([PY, "-c", PARITY_CHILD], name="parity")
        if "DCN_PARITY_OK" not in r.stdout:
            raise SystemExit("[dcn-gate] parity child gave no verdict")

        # 2. the DCN conformance sweep is green, and the perturb knob
        # proves the auditor trips on IR drift
        vm = os.path.join(work, "verify.jsonl")
        run([PY, "-m", "stencil_tpu.apps.lint_tool", "verify-plan",
             "--cpu", "8", "--hierarchy", "2", "--metrics-out", vm],
            name="verify-plan")
        run([PY, "-m", "stencil_tpu.apps.lint_tool", "verify-plan",
             "--cpu", "8", "--hierarchy", "2", "--perturb-dcn", "1"],
            expect_rc=1, name="verify-plan-perturbed")

        # 3. two-level NodeAware: strictly cheaper on the scrambled
        # fabric, identity (flat-equivalent) on the uniform one
        r = run([PY, "-c", QAP_CHILD], name="two-level-qap")
        if "DCN_QAP_OK" not in r.stdout:
            raise SystemExit("[dcn-gate] QAP child gave no verdict")
        print("[dcn-gate] " + r.stdout.splitlines()[0])

        # 4. tune -> persist -> zero-probe replay -> realize
        db = os.path.join(work, "plans.json")
        r = run([PY, "-c", TUNE_CHILD, db], name="tune-roundtrip")
        if "DCN_TUNE_OK" not in r.stdout:
            raise SystemExit("[dcn-gate] tune child gave no verdict")

        # every metrics record passes the schema gate
        run([PY, "-m", "stencil_tpu.apps.report", vm, "--validate"],
            name="schema")

        # 5. the repo lint stays green over the new modules
        run([PY, "-m", "stencil_tpu.apps.lint_tool", "lint"], name="lint")
        print("[dcn-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
