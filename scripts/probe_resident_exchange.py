"""Config-2 geometry fully RESIDENT on one chip: 256^3 global, 2x2x2
partition, radius 2, 4 fp32 quantities — all 8 blocks stacked on a single
device (mixed (2,2,2) residency), exchanged by local slab shifts.

Until now config 2 was only measurable on 8 *virtual CPU* devices (81.2
ms/exchange, round 2 — a number that says nothing about TPU). Resident
stacking runs the REAL multi-block exchange machinery (per-axis slab
shifts + boundary self-wraps, the same code path that feeds ICI permutes
on a pod) on the actual chip's HBM. Also times the jacobi3d workload on
the same resident partition — the first hardware number for the
multi-block compute paths.

Usage: python scripts/probe_resident_exchange.py [n]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import numpy as np

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
on_accel = jax.devices()[0].platform != "cpu"
chunk = 120 if on_accel else 3

# -- exchange: config 2 resident ---------------------------------------------
spec = GridSpec(Dim3(n, n, n), Dim3(2, 2, 2), Radius.constant(2))
mesh = grid_mesh(Dim3(1, 1, 1), jax.devices()[:1])
ex = HaloExchange(spec, mesh)
assert tuple(ex.resident) == (2, 2, 2), ex.resident
loop = ex.make_loop(chunk)
state = {
    i: shard_blocks(np.zeros((n, n, n), np.float32), spec, mesh)
    for i in range(4)
}
t0 = time.time()
state = loop(state)
hard_sync(state)
print(f"exchange compile {time.time()-t0:.0f}s", flush=True)
st = Statistics()
for _ in range(3):
    t0 = time.perf_counter()
    state = loop(state)
    hard_sync(state)
    st.insert((time.perf_counter() - t0) / chunk)
gb = ex.bytes_logical([4] * 4) / st.trimean() / 1e9
print(f"config2-resident {n}^3 2x2x2 on 1 chip, r2, 4q: "
      f"{st.trimean()*1e3:.2f} ms/exchange ({gb:.2f} GB/s logical, "
      f"chunk {chunk})", flush=True)
del state

# -- jacobi3d workload on the resident partition ------------------------------
from stencil_tpu.apps.jacobi3d import run

r = run(n, n, n, iters=3 * chunk, weak=False, devices=jax.devices()[:1],
        warmup=1, chunk=chunk, partition=(2, 2, 2))
print(f"jacobi3d-resident {n}^3 2x2x2 on 1 chip: "
      f"{r['iter_trimean_s']*1e3:.2f} ms/iter "
      f"({r['mcells_per_s_per_dev']:.0f} Mcells/s)", flush=True)
