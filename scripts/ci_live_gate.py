#!/usr/bin/env python
"""CI live-observability gate: mid-run detection, status snapshots, SLO.

The executable acceptance proof of ISSUE 12 (obs/live.py + obs/status.py
wired through the guarded loop and the campaign driver) on the
8-virtual-device CPU mesh — no TPU needed:

1. **mid-run anomaly**: jacobi3d 24^3 with two injected ``slow@N``
   faults and the live sentinel ON must emit ``anomaly.detected``
   *during* the run — the gate polls the atomic status snapshot while
   the child runs and must observe the ACTIVE anomaly (not just the
   post-mortem), detection must land within 3 chunks of the injection
   step, the anomaly must CLEAR once latencies normalize (final
   snapshot: 1 detected, 1 cleared, none active), ``replan.requested``
   must accompany the detection, and the exported trace must render the
   anomaly instant markers;
2. **clean-run silence**: the same config without the injection emits
   ZERO anomaly/replan records and a zero-anomaly final snapshot;
3. **SLO tracking**: a campaign with one deadline-doomed tenant
   (``--deadline-ms t1=0.0001``) must emit ``slo.violation`` for t1
   ONLY, finish every tenant (a breach is evidence, not an eviction),
   show t1 as violated in the status lane table, and render the
   ``slo.violation`` instant marker in its trace;
4. **schema + ledger**: every record passes ``report --validate``; both
   jacobi runs ingest into a fresh ledger where ``live.anomaly_count``
   trends 1 -> 0 and ``perf_tool trend --json`` archives the
   machine-readable trajectory.

Exit 0 only if every stage holds. Run from the repo root:

  python scripts/ci_live_gate.py [--size 24] [--out-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

# injections land AFTER the sentinel's min_history warmup (chunks end at
# 2,4,6,8 with --health-every 2, default min_history 4) so detection is
# judged at the first slow chunk; the second slow keeps the anomaly
# ACTIVE long enough for the status poll to observe it mid-run
ITERS = 14
HEALTH_EVERY = 2
SLOW_STEPS = (9, 10)
SLOW_SECONDS = (12.0, 8.0)
# "within 3 chunks of injection": chunks here are <= HEALTH_EVERY steps
DETECT_WINDOW_STEPS = 3 * HEALTH_EVERY


def run(cmd, expect_rc=0, name="", **kw):
    print(f"[live-gate] {name}: {' '.join(cmd)}", flush=True)
    p = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True, **kw)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[live-gate] {name}: rc={p.returncode}, expected {expect_rc}")
    return p


def load_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def by_name(records, name):
    return [r for r in records if r["name"] == name]


def instant_markers(trace_path):
    with open(trace_path) as f:
        tr = json.load(f)
    return {e["name"] for e in tr["traceEvents"] if e.get("ph") == "i"}


def jacobi_cmd(args, metrics, status, inject=""):
    cmd = [
        PY, "-m", "stencil_tpu.apps.jacobi3d", "--cpu", "8",
        "--x", str(args.size), "--y", str(args.size), "--z", str(args.size),
        "--iters", str(ITERS), "--health-every", str(HEALTH_EVERY),
        "--metrics-out", metrics, "--status-file", status,
        "--live-sentinel",
    ]
    if inject:
        cmd += ["--inject", inject]
    return cmd


def poll_status_while(proc, status_path, observed):
    """Collect status snapshots while ``proc`` runs (the LIVE half of the
    proof: the anomaly must be visible before the run ends)."""
    while proc.poll() is None:
        try:
            with open(status_path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            doc = None  # not written yet / mid-rename on exotic FS
        if doc:
            a = doc.get("anomalies") or {}
            observed.append({
                "step": doc.get("step"),
                "active": [ev.get("metric") for ev in a.get("active") or []],
                "detected": a.get("detected", 0),
                "cleared": a.get("cleared", 0),
            })
        time.sleep(0.1)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--out-dir", default="",
                   help="keep traces + trend artifact here for CI upload "
                        "(default: a temp dir, removed)")
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="live-gate-")
    out_dir = os.path.abspath(args.out_dir) if args.out_dir else work
    os.makedirs(out_dir, exist_ok=True)
    try:
        # ---- 1. mid-run anomaly detection ------------------------------------
        m_live = os.path.join(work, "m_live.jsonl")
        st_live = os.path.join(out_dir, "status-live.json")
        inject = ",".join(f"slow@{s}:seconds={sec:g}"
                          for s, sec in zip(SLOW_STEPS, SLOW_SECONDS))
        cmd = jacobi_cmd(args, m_live, st_live, inject=inject)
        print(f"[live-gate] anomaly-run (polled): {' '.join(cmd)}",
              flush=True)
        # child output goes to FILES, not pipes: the poll loop never
        # drains a pipe, so a chatty child (debug logging, jax warnings)
        # would fill the OS buffer, block on write, and deadlock the
        # gate — the round-4 bench.py lesson watchdog.supervise encodes
        out_path = os.path.join(work, "anomaly-run.log")
        with open(out_path, "w") as log_f:
            proc = subprocess.Popen(cmd, cwd=REPO, stdout=log_f,
                                    stderr=subprocess.STDOUT, text=True)
            observed = []
            poll_status_while(proc, st_live, observed)
            proc.wait()
        if proc.returncode != 0:
            with open(out_path) as f:
                print(f.read()[-8000:], file=sys.stderr)
            raise SystemExit(f"[live-gate] anomaly-run rc={proc.returncode}")
        live_polls = [o for o in observed if o["active"]]
        if not live_polls:
            raise SystemExit(
                "[live-gate] the status snapshot NEVER showed an active "
                f"anomaly while the run executed (polled {len(observed)} "
                "snapshots) — detection was not live")
        if not any("step.latency_s" in m for o in live_polls
                   for m in o["active"]):
            raise SystemExit(f"[live-gate] active anomalies never named "
                             f"step.latency_s: {live_polls[:3]}")
        print(f"[live-gate] observed the ACTIVE anomaly in "
              f"{len(live_polls)}/{len(observed)} mid-run polls")

        with open(st_live) as f:
            final = json.load(f)
        a = final.get("anomalies") or {}
        if (a.get("detected") != 1 or a.get("cleared") != 1
                or a.get("active")):
            raise SystemExit(f"[live-gate] final snapshot must show the "
                             f"detect AND the clear: {a}")
        if final.get("outcome") != "done":
            raise SystemExit(f"[live-gate] final outcome: {final.get('outcome')}")

        recs = load_records(m_live)
        det = by_name(recs, "anomaly.detected")
        clr = by_name(recs, "anomaly.cleared")
        rep = by_name(recs, "replan.requested")
        inj = [r for r in by_name(recs, "fault.injected")
               if r.get("fault_kind") == "slow"]
        if len(det) != 1 or len(clr) != 1 or not rep:
            raise SystemExit(f"[live-gate] want 1 detect / 1 clear / >=1 "
                             f"replan, got {len(det)}/{len(clr)}/{len(rep)}")
        first_inject = min(r["step"] for r in inj)
        delta = det[0]["step"] - first_inject
        if not 0 <= delta <= DETECT_WINDOW_STEPS:
            raise SystemExit(
                f"[live-gate] detection at step {det[0]['step']} is not "
                f"within {DETECT_WINDOW_STEPS} steps (3 chunks) of the "
                f"injection at {first_inject}")
        if clr[0]["step"] <= det[0]["step"]:
            raise SystemExit("[live-gate] clear must follow the detect")
        print(f"[live-gate] detected at step {det[0]['step']} "
              f"(injection {first_inject}, +{delta} steps), cleared at "
              f"{clr[0]['step']}")

        run([PY, "-m", "stencil_tpu.apps.report", m_live, "--validate"],
            name="validate-live")
        trace_live = os.path.join(out_dir, "trace-live.json")
        run([PY, "-m", "stencil_tpu.apps.report", m_live,
             "--trace-out", trace_live], name="trace-live")
        need = {"anomaly.detected", "anomaly.cleared", "replan.requested",
                "fault.injected"}
        inst = instant_markers(trace_live)
        if not need <= inst:
            raise SystemExit(f"[live-gate] trace lacks instant markers "
                             f"{sorted(need - inst)} (has {sorted(inst)})")

        # ---- 2. clean-run silence --------------------------------------------
        m_clean = os.path.join(work, "m_clean.jsonl")
        st_clean = os.path.join(work, "status-clean.json")
        run(jacobi_cmd(args, m_clean, st_clean), name="clean-run")
        recs = load_records(m_clean)
        noisy = [r["name"] for r in recs
                 if r["name"].startswith(("anomaly.", "replan.", "slo."))]
        if noisy:
            raise SystemExit(f"[live-gate] the clean run emitted anomaly "
                             f"records: {noisy}")
        with open(st_clean) as f:
            a = json.load(f).get("anomalies") or {}
        if a.get("detected") != 0 or a.get("active"):
            raise SystemExit(f"[live-gate] clean snapshot not clean: {a}")
        run([PY, "-m", "stencil_tpu.apps.report", m_clean, "--validate"],
            name="validate-clean")
        print("[live-gate] clean run: zero anomaly records, clean snapshot")

        # ---- 3. campaign SLO -------------------------------------------------
        m_camp = os.path.join(work, "m_camp.jsonl")
        st_camp = os.path.join(out_dir, "status-campaign.json")
        g = run([PY, "-m", "stencil_tpu.apps.campaign", "--cpu", "8",
                 "--tenants", "4", "--slot", "4", "--size", "16",
                 "--steps", "8", "--chunk", "2", "--mode", "batched",
                 "--metrics-out", m_camp, "--status-file", st_camp,
                 "--live-sentinel", "--deadline-ms", "t1=0.0001"],
                name="campaign-slo")
        summary = json.loads(g.stdout.strip().splitlines()[-1])
        if summary.get("slo_violations") != ["t1"]:
            raise SystemExit(f"[live-gate] want slo_violations == ['t1'], "
                             f"got {summary.get('slo_violations')}")
        if summary.get("evicted"):
            raise SystemExit("[live-gate] an SLO breach must not evict: "
                             f"{summary['evicted']}")
        recs = load_records(m_camp)
        viol = by_name(recs, "slo.violation")
        if not viol or {r["tenant"] for r in viol} != {"t1"}:
            raise SystemExit(f"[live-gate] slo.violation must name t1 and "
                             f"ONLY t1: {[r.get('tenant') for r in viol]}")
        with open(st_camp) as f:
            camp = json.load(f)
        lanes = {ln.get("tenant"): ln for ln in camp.get("lanes") or []}
        if lanes.get("t1", {}).get("slo") != "violated":
            raise SystemExit(f"[live-gate] status lanes must show t1 "
                             f"violated: {camp.get('lanes')}")
        clean_lanes = [t for t, ln in lanes.items()
                       if t not in (None, "t1") and ln.get("slo") == "violated"]
        if clean_lanes:
            raise SystemExit(f"[live-gate] survivors must stay clean, but "
                             f"{clean_lanes} read violated")
        run([PY, "-m", "stencil_tpu.apps.report", m_camp, "--validate"],
            name="validate-campaign")
        trace_camp = os.path.join(out_dir, "trace-campaign.json")
        run([PY, "-m", "stencil_tpu.apps.report", m_camp,
             "--trace-out", trace_camp], name="trace-campaign")
        if "slo.violation" not in instant_markers(trace_camp):
            raise SystemExit("[live-gate] campaign trace lacks the "
                             "slo.violation instant marker")
        print("[live-gate] campaign: t1 violated, survivors clean, "
              "marker rendered")

        # ---- 4. ledger + trend --json ---------------------------------------
        ledger = os.path.join(work, "ledger.jsonl")
        for metrics, label in ((m_live, "live1"), (m_clean, "clean1")):
            run([PY, "-m", "stencil_tpu.apps.perf_tool", "ingest",
                 "--ledger", ledger, "--label", label, "--platform", "cpu",
                 metrics], name=f"ingest-{label}")
        trend = os.path.join(out_dir, "trend.json")
        g = run([PY, "-m", "stencil_tpu.apps.perf_tool", "trend",
                 "--ledger", ledger, "--json", "--out", trend,
                 "--metric", "live.anomaly_count"], name="trend-json")
        doc = json.loads(g.stdout)
        legs = [leg for leg in doc["legs"]
                if leg["metric"] == "live.anomaly_count"]
        if len(legs) != 1:
            raise SystemExit(f"[live-gate] live.anomaly_count must trend as "
                             f"ONE leg (both runs share a config "
                             f"fingerprint): {[(leg['metric'], leg['config']) for leg in doc['legs']]}")
        traj = {pt["label"]: pt["value"] for pt in legs[0]["points"]}
        if traj != {"live1": 1.0, "clean1": 0.0}:
            raise SystemExit(f"[live-gate] anomaly count must trend "
                             f"1 -> 0 across the runs: {traj}")
        print("[live-gate] ledger trends live.anomaly_count 1 -> 0; "
              "trend --json archived")

        print(f"[live-gate] PASS (artifacts: {out_dir})")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
