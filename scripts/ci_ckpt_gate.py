#!/usr/bin/env python
"""CI checkpoint gate: save -> kill -> --resume == uninterrupted.

The executable acceptance proof of the ckpt/ subsystem on the 8-virtual-
device CPU mesh (no TPU needed):

1. reference: jacobi3d 24^3 runs 6 iterations uninterrupted, writing its
   final-state snapshot;
2. crash: the same config checkpoints every 2 iterations and is killed by
   the injected-kill hook (STENCIL_CKPT_KILL_AFTER_SAVE) right after the
   step-2 snapshot is durable;
3. revival: the run is restarted with --resume and must continue from
   step 2 to completion;
4. ``ckpt_tool validate --all`` passes on the produced checkpoint dir and
   ``ckpt_tool diff --data`` proves the revived final field is
   bit-identical to the uninterrupted one;
5. corruption: truncating a payload must fail validation AND make
   auto-resume fall back to the previous good snapshot — LATEST never
   names a partial snapshot.

Exit code 0 only if every stage holds. Run from the repo root:

  python scripts/ci_ckpt_gate.py [--size 24] [--iters 6]
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def run(cmd, env=None, expect_rc=0, name=""):
    print(f"[ckpt-gate] {name}: {' '.join(cmd)}", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    p = subprocess.run(cmd, env=e, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[ckpt-gate] {name}: rc={p.returncode}, expected {expect_rc}"
        )
    return p


def jacobi(args, extra, env=None, expect_rc=0, name=""):
    cmd = [
        PY, "-m", "stencil_tpu.apps.jacobi3d", "--cpu", "8",
        "--x", str(args.size), "--y", str(args.size), "--z", str(args.size),
        "--iters", str(args.iters),
    ] + extra
    return run(cmd, env=env, expect_rc=expect_rc, name=name)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--iters", type=int, default=6)
    p.add_argument("--kill-at", type=int, default=2)
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="ckpt-gate-")
    ref, ck = os.path.join(work, "ref"), os.path.join(work, "ck")
    metrics = os.path.join(work, "metrics.jsonl")
    try:
        jacobi(args, ["--ckpt-dir", ref], name="reference")
        jacobi(
            args,
            ["--ckpt-dir", ck, "--ckpt-every", str(args.kill_at)],
            env={"STENCIL_CKPT_KILL_AFTER_SAVE": str(args.kill_at)},
            expect_rc=17, name="killed",
        )
        r = jacobi(
            args,
            ["--ckpt-dir", ck, "--ckpt-every", str(args.kill_at),
             "--resume", "--metrics-out", metrics],
            name="revived",
        )
        if "resuming from checkpointed step" not in r.stdout + r.stderr:
            raise SystemExit("[ckpt-gate] revival did not resume from a "
                             "checkpoint")
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "validate", ck, "--all"],
            name="validate")
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "diff", ref, ck,
             "--data"], name="diff")
        # the metrics file must carry the resumed-from-step evidence and
        # still satisfy the telemetry schema gate
        run([PY, "-m", "stencil_tpu.apps.report", metrics, "--validate"],
            name="report-validate")
        with open(metrics) as f:
            if '"ckpt.resumed_from_step"' not in f.read():
                raise SystemExit("[ckpt-gate] metrics JSONL lacks "
                                 "ckpt.resumed_from_step")

        # corruption: truncate the newest payload; validate must reject it
        # and auto-resume must fall back to the previous good snapshot
        sys.path.insert(0, REPO)
        from stencil_tpu.ckpt import find_resume, read_latest

        latest = read_latest(ck)
        victim = os.path.join(ck, latest, "block_0_0_0.npz")
        with open(victim, "r+b") as f:
            f.truncate(16)
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "validate",
             os.path.join(ck, latest)], expect_rc=1, name="validate-corrupt")
        found = find_resume(ck)
        if found is None or os.path.basename(found[0]) == latest:
            raise SystemExit("[ckpt-gate] auto-resume did not fall back "
                             "past the corrupted snapshot")
        print(f"[ckpt-gate] fallback to {os.path.basename(found[0])} ok")
        print("[ckpt-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
