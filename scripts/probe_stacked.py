"""Pure stacked-substep timing (tight-x layout): the DMA-descriptor
batching result for BASELINE.md. Usage: probe_stacked.py [n]

NOTE: the stacked kernel variant was REVERTED after the negative result was
recorded (commit a558ae8: marginal at 256^3, HBM-OOM at 512^3) —
``make_pallas_substep`` on the current tree has no ``stacked=`` parameter.
Reproducing the stacked leg requires checking out that commit; here the
stacked leg is SKIPPED with a notice and only the per-field leg runs
(ADVICE r3)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from stencil_tpu.astaroth.config import load_config
from stencil_tpu.astaroth.equations import Constants
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.ops.pallas_astaroth import FIELDS, NF, make_pallas_substep, pick_tiles
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
info, _ = load_config("stencil_tpu/astaroth/astaroth.conf")
c = Constants.from_info(info)
inv_ds = tuple(info.real_params[k] for k in ("AC_inv_dsx", "AC_inv_dsy", "AC_inv_dsz"))
spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3).without_x())
p = spec.padded()
rng = np.random.RandomState(7)
chunk = 60 if n <= 256 else 12
import inspect

HAVE_STACKED = "stacked" in inspect.signature(make_pallas_substep).parameters
for label, stacked in (("stacked", True), ("per-field", False)):
    if stacked and not HAVE_STACKED:
        print("stacked: SKIPPED — kernel variant reverted (a558ae8); check "
              "out that commit to reproduce the BASELINE.md negative result",
              flush=True)
        continue
    sub = make_pallas_substep(spec, c, inv_ds, 1, 1e-8,
                              **({"stacked": True} if stacked else {}))
    if stacked:
        curr = jnp.asarray(rng.rand(NF, p.z, p.y, p.x) * 0.1, jnp.float32)
        out = jnp.asarray(rng.rand(NF, p.z, p.y, p.x) * 0.1, jnp.float32)
        fn = jax.jit(lambda cu, ou: jax.lax.fori_loop(
            0, chunk, lambda _, o: sub(cu, o), ou), donate_argnums=(1,))
    else:
        curr = tuple(jnp.asarray(rng.rand(p.z, p.y, p.x) * 0.1, jnp.float32)
                     for _ in FIELDS)
        out = tuple(jnp.asarray(rng.rand(p.z, p.y, p.x) * 0.1, jnp.float32)
                    for _ in FIELDS)
        fn = jax.jit(lambda cu, ou: jax.lax.fori_loop(
            0, chunk, lambda _, o: sub(cu, o), ou), donate_argnums=(1,))
    t0 = time.time(); out2 = fn(curr, out); hard_sync(out2)
    cs = time.time() - t0
    st = Statistics()
    for _ in range(3):
        t0 = time.perf_counter(); out2 = fn(curr, out2); hard_sync(out2)
        st.insert((time.perf_counter() - t0) / chunk)
    print(f"{label} {n}^3 tiles={pick_tiles(spec)}: {st.trimean()*1e3:.2f} "
          f"ms/substep (compile {cs:.0f}s)", flush=True)
