#!/usr/bin/env python
"""CI replan gate: topology-aware placement + the mid-run plan hot-swap.

The executable acceptance proof of ISSUE 15 on the 8-virtual-device CPU
mesh — no TPU needed:

1. **placement conformance**: ``lint_tool verify-plan --placements 3``
   audits >= 3 non-identity block->device permutations on the 2x2x2
   mesh — the realized mesh's device order IS the permuted assignment,
   the compiled ``source_target_pairs`` match the plan's logical
   schedule (so each pair rides exactly the permuted physical link),
   and the exchanged field is bit-identical to identity;
2. **QAP never worse than identity**: on the DERIVED matrices (GridSpec
   wire volumes x live-device link costs — uniform on this mesh, so
   identity must be recognized as optimal) AND on a synthetic
   non-uniform fabric where the solved placement must be STRICTLY
   cheaper, with the static cost model ranking the placed candidate
   below its identity sibling;
3. **hot-swap e2e**: jacobi3d 24^3 starting on direct26 with an injected
   ``slow@N`` and the live sentinel + ``--replan`` ON must emit
   ``replan.requested`` then ``replan.applied`` within 2 chunks, finish
   rc 0, and the final checkpointed field must be BIT-IDENTICAL to an
   unswapped direct26 run (``ckpt_tool diff --data`` — elastic across
   the swap's partition change); a clean replan-armed run emits ZERO
   replan records;
4. **schema**: every record — the new ``replan.applied``/``rejected``
   and the ``qap.placement_cost``/``qap.improvement`` gauges of
   ``bench_qap --derived`` included — passes ``report --validate``.

Exit 0 only if every stage holds. Run from the repo root:

  python scripts/ci_replan_gate.py [--size 24] [--out-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

ITERS = 12
CHUNK = 2
SLOW_STEP = 6
# "within 2 chunks" of the request, in steps
SWAP_WINDOW_STEPS = 2 * CHUNK
# the sentinel must be armed before the injected slow chunk: two healthy
# chunks of history, a tight band, immediate clear
LIVE_CONFIG = json.dumps(
    {"*": {"min_history": 2, "window": 8, "rel_tol": 0.5,
           "clear_after": 1}})

QAP_SNIPPET = r"""
import numpy as np
import stencil_tpu  # installs the jax_num_cpu_devices compat shim
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel.topology import link_cost_matrix
from stencil_tpu.plan import cost as C
from stencil_tpu.plan.ir import PlanConfig

# derived matrices: the real inputs (uniform links on this mesh ->
# identity must be recognized as optimal, not "improved" by noise)
spec = GridSpec(Dim3(24, 24, 24), Dim3(2, 2, 2), Radius.constant(2))
w = C.placement_wire_matrix(spec, Dim3(2, 2, 2))
link = link_cost_matrix(jax.devices()[:8])
assert C.uniform_link_costs(link), "single-process CPU links must be uniform"
assert C.solve_placement(w, link) is None, \
    "uniform links must solve to identity"

# synthetic non-uniform fabric (scrambled ring: cheap links 3 apart):
# the QAP-placed cost must be <= identity, here STRICTLY cheaper
spec_r = GridSpec(Dim3(24, 24, 24), Dim3(1, 1, 8), Radius.constant(1))
w_r = C.placement_wire_matrix(spec_r, Dim3(1, 1, 8))
link_r = np.full((8, 8), 7.0)
for i in range(8):
    link_r[i, (i + 3) % 8] = link_r[(i + 3) % 8, i] = 1.0
np.fill_diagonal(link_r, 0.1)
f = C.solve_placement(w_r, link_r)
assert f is not None, "scrambled ring must admit a better-than-identity placement"
ident = C.placement_cost(w_r, link_r)
placed = C.placement_cost(w_r, link_r, f)
assert placed < ident, (placed, ident)

# the static model must rank the placed candidate below identity
cfg = PlanConfig.make((24, 24, 24), Radius.constant(1), ["float32"], 8, "cpu")
ranked = C.rank(cfg, C.enumerate_candidates(cfg, link_costs=link_r),
                link_costs=link_r)
comp = [(c, ch) for c, ch in ranked
        if ch.method == "axis-composed" and ch.partition == (1, 1, 8)]
ident_c = next(t for t in comp if not t[1].is_placed)
placed_c = next(t for t in comp if t[1].is_placed)
assert placed_c[0].total_s < ident_c[0].total_s, \
    (placed_c[0].total_s, ident_c[0].total_s)
print(f"qap-model: placed {placed:.0f} < identity {ident:.0f} "
      f"({ident / placed:.2f}x); model {placed_c[0].total_s:.3g} < "
      f"{ident_c[0].total_s:.3g}")
"""


def run(cmd, expect_rc=0, name="", **kw):
    print(f"[replan-gate] {name}: {' '.join(cmd)}", flush=True)
    p = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True, **kw)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[replan-gate] {name}: rc={p.returncode}, expected {expect_rc}")
    return p


def load_records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def by_name(records, name):
    return [r for r in records if r["name"] == name]


def jacobi_cmd(args, ckpt, metrics=None, swap=False, inject=""):
    cmd = [
        PY, "-m", "stencil_tpu.apps.jacobi3d", "--cpu", "8",
        "--x", str(args.size), "--y", str(args.size), "--z", str(args.size),
        "--iters", str(ITERS), "--method", "direct26",
        # health boundaries force CHUNK-step fused chunks, so the
        # sentinel sees per-chunk samples (two healthy warmup chunks
        # before the injected slow at SLOW_STEP)
        "--health-every", str(CHUNK),
        "--ckpt-dir", ckpt,
    ]
    if metrics:
        cmd += ["--metrics-out", metrics]
    if swap:
        cmd += ["--live-sentinel", "--live-config", LIVE_CONFIG, "--replan"]
    if inject:
        cmd += ["--inject", inject]
    return cmd


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--out-dir", default="",
                   help="keep metrics artifacts here for CI upload "
                        "(default: a temp dir, removed)")
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="replan-gate-")
    out_dir = os.path.abspath(args.out_dir) if args.out_dir else work
    os.makedirs(out_dir, exist_ok=True)
    try:
        # ---- 1. placement conformance (>= 3 non-identity permutations) ------
        run([PY, "-m", "stencil_tpu.apps.lint_tool", "verify-plan",
             "--cpu", "8", "--methods", "axis-composed",
             "--quantities", "f32", "--placements", "3"],
            name="placement-conformance")
        print("[replan-gate] 3 non-identity placements: mesh order, "
              "source_target_pairs, and bit parity all conform")

        # ---- 2. QAP cost vs identity (derived + synthetic + model) ----------
        g = run([PY, "-c", QAP_SNIPPET], name="qap-vs-identity")
        print("[replan-gate] " + g.stdout.strip().splitlines()[-1])

        # ---- 3. hot-swap e2e -------------------------------------------------
        ck_swap = os.path.join(work, "ck-swap")
        m_swap = os.path.join(out_dir, "m_swap.jsonl")
        run(jacobi_cmd(args, ck_swap, metrics=m_swap, swap=True,
                       inject=f"slow@{SLOW_STEP}:seconds=0.6"),
            name="swap-run")
        recs = load_records(m_swap)
        req = by_name(recs, "replan.requested")
        app = by_name(recs, "replan.applied")
        rej = by_name(recs, "replan.rejected")
        if not req:
            raise SystemExit("[replan-gate] the sentinel never requested "
                             "a replan (injection missed the band?)")
        if not app:
            raise SystemExit(f"[replan-gate] replan requested but never "
                             f"APPLIED (rejected: "
                             f"{[r.get('reason') for r in rej]})")
        delta = app[0]["step"] - req[0]["step"]
        if not 0 <= delta <= SWAP_WINDOW_STEPS:
            raise SystemExit(
                f"[replan-gate] swap at step {app[0]['step']} is not "
                f"within 2 chunks ({SWAP_WINDOW_STEPS} steps) of the "
                f"request at {req[0]['step']}")
        if app[0]["old"] == app[0]["new"]:
            raise SystemExit(f"[replan-gate] the swap must install a "
                             f"DIFFERENT plan: {app[0]}")
        print(f"[replan-gate] swap applied at step {app[0]['step']} "
              f"(+{delta} steps): {app[0]['old']} -> {app[0]['new']}")

        ck_ref = os.path.join(work, "ck-ref")
        run(jacobi_cmd(args, ck_ref), name="unswapped-reference")
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "diff", ck_ref,
             ck_swap, "--data", "--elastic"],
            name="diff-swap-vs-unswapped")
        print("[replan-gate] swapped run bit-identical to the unswapped "
              "reference (elastic across the partition change)")

        # a clean replan-armed run must stay silent
        ck_clean = os.path.join(work, "ck-clean")
        m_clean = os.path.join(work, "m_clean.jsonl")
        run(jacobi_cmd(args, ck_clean, metrics=m_clean, swap=True),
            name="clean-armed-run")
        noisy = [r["name"] for r in load_records(m_clean)
                 if r["name"].startswith("replan.")]
        if noisy:
            raise SystemExit(f"[replan-gate] clean armed run emitted "
                             f"replan records: {noisy}")
        print("[replan-gate] clean armed run: zero replan records")

        # ---- 4. vocabulary schema (replan.* + qap.*) -------------------------
        m_qap = os.path.join(out_dir, "m_qap.jsonl")
        run([PY, "-m", "stencil_tpu.apps.bench_qap", "--derived",
             "--cpu", "8", "--x", "32", "--sizes", "4",
             "--catch-sizes", "16", "--metrics-out", m_qap],
            name="bench-qap-derived")
        qrecs = load_records(m_qap)
        for need in ("qap.placement_cost", "qap.improvement"):
            if not by_name(qrecs, need):
                raise SystemExit(f"[replan-gate] bench_qap --derived "
                                 f"recorded no {need} gauge")
        for metrics, name in ((m_swap, "swap"), (m_clean, "clean"),
                              (m_qap, "qap")):
            run([PY, "-m", "stencil_tpu.apps.report", metrics,
                 "--validate"], name=f"validate-{name}")
        print("[replan-gate] replan.*/qap.* vocabulary schema-valid")

        print(f"[replan-gate] PASS (artifacts: {out_dir})")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
