#!/usr/bin/env python3
"""serve_loadgen — seeded Poisson open-loop load for the serving daemon.

The reference producer for the file-drop intake protocol
(stencil_tpu/serve/intake.py): one JSON document per job, written
ATOMICALLY (tmp file in the same directory, then rename — the daemon
must never see a half-written job), dropped into
``<serve-dir>/jobs/incoming/`` with exponential inter-arrival gaps
(open loop: the generator never waits for the daemon, which is what
makes the daemon's admission control the thing under test, not the
producer's backpressure).

Everything is seeded: job ids, owners, priorities, deadlines and the
arrival gaps all come from one ``random.Random(seed)``, so a gate or
bench leg replays the exact same offered load every run. ``--rate 0``
drops the whole batch immediately (the pre-loaded-queue mode the bench
leg uses).

PURE STDLIB — load generation must not wait on a jax import.

Usage: python scripts/serve_loadgen.py --serve-dir /srv/stencil \
           --jobs 16 --rate 4 --seed 7 --tenants 3 --quota-stress
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

PRIORITIES = ("high", "normal", "low")


def drop_job(incoming: str, doc: dict) -> str:
    """Atomically drop one job document (the intake write contract)."""
    name = f"{doc['job']}.json"
    tmp = os.path.join(incoming, f".tmp-{name}-{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    dst = os.path.join(incoming, name)
    os.replace(tmp, dst)
    return dst


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="seeded Poisson open-loop job generator for the "
                    "serving daemon")
    p.add_argument("--serve-dir", required=True,
                   help="the daemon's service root (jobs land in "
                        "<serve-dir>/jobs/incoming/)")
    p.add_argument("--jobs", type=int, default=8,
                   help="number of jobs to drop")
    p.add_argument("--rate", type=float, default=4.0,
                   help="mean arrival rate in jobs/s (Poisson: "
                        "exponential gaps); 0 = drop everything at once")
    p.add_argument("--seed", type=int, default=0,
                   help="seeds ids, owners, priorities AND arrival gaps "
                        "— the same seed replays the same offered load")
    p.add_argument("--tenants", type=int, default=2,
                   help="owners drawn uniformly from tenant-0..N-1")
    p.add_argument("--size", type=int, default=12,
                   help="per-job cubic domain edge")
    p.add_argument("--steps", type=int, default=4,
                   help="steps per job")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    p.add_argument("--workload", default="jacobi",
                   choices=["jacobi", "astaroth"])
    p.add_argument("--deadline-ms", type=float, default=0.0,
                   help="per-step p99 SLO stamped on every job "
                        "(0 = no deadline)")
    p.add_argument("--mixed-priority", action="store_true",
                   help="draw priorities high/normal/low (seeded) instead "
                        "of all-normal")
    p.add_argument("--prefix", default="j",
                   help="job id prefix (ids are <prefix>-<seed>-<i>; two "
                        "generators with different seeds never collide)")
    args = p.parse_args(argv)
    if args.jobs < 1:
        p.error(f"--jobs must be >= 1, got {args.jobs}")
    if args.rate < 0:
        p.error(f"--rate must be >= 0, got {args.rate}")

    incoming = os.path.join(args.serve_dir, "jobs", "incoming")
    os.makedirs(incoming, exist_ok=True)
    rng = random.Random(args.seed)
    t0 = time.perf_counter()
    dropped = []
    for i in range(args.jobs):
        if args.rate > 0 and i > 0:
            time.sleep(rng.expovariate(args.rate))
        doc = {
            "job": f"{args.prefix}-{args.seed}-{i:04d}",
            "size": args.size,
            "steps": args.steps,
            "dtype": args.dtype,
            "workload": args.workload,
            "seed": rng.randrange(1 << 20),
            "tenant": f"tenant-{rng.randrange(args.tenants)}",
            "priority": (rng.choice(PRIORITIES) if args.mixed_priority
                         else "normal"),
        }
        if args.deadline_ms > 0:
            doc["deadline_ms"] = args.deadline_ms
        path = drop_job(incoming, doc)
        print(f"[loadgen] dropped {os.path.basename(path)} "
              f"(tenant={doc['tenant']}, priority={doc['priority']})",
              file=sys.stderr, flush=True)
        dropped.append(doc["job"])
    print(json.dumps({
        "app": "serve_loadgen", "dropped": len(dropped), "seed": args.seed,
        "rate_per_s": args.rate, "wall_s": round(time.perf_counter() - t0, 3),
        "first": dropped[0], "last": dropped[-1],
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
