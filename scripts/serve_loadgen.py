#!/usr/bin/env python3
"""serve_loadgen — seeded Poisson open-loop load for the serving daemon.

The reference producer for the file-drop intake protocol
(stencil_tpu/serve/intake.py): one JSON document per job, written
ATOMICALLY (tmp file in the same directory, then rename — the daemon
must never see a half-written job), dropped into
``<serve-dir>/jobs/incoming/`` with exponential inter-arrival gaps
(open loop: the generator never waits for the daemon, which is what
makes the daemon's admission control the thing under test, not the
producer's backpressure).

Everything is seeded: job ids, owners, priorities, deadlines and the
arrival gaps all come from one ``random.Random(seed)``, so a gate or
bench leg replays the exact same offered load every run. ``--rate 0``
drops the whole batch immediately (the pre-loaded-queue mode the bench
leg uses).

PURE STDLIB — load generation must not wait on a jax import.

Usage: python scripts/serve_loadgen.py --serve-dir /srv/stencil \
           --jobs 16 --rate 4 --seed 7 --tenants 3 --quota-stress
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

PRIORITIES = ("high", "normal", "low")


def parse_mix(spec: str):
    """``--mix`` entries: comma-separated ``SIZE[/DTYPE[/WORKLOAD]]``
    where SIZE is ``N`` (cubic) or ``XxYxZ``. Each job draws one entry
    from the seeded rng, so a mixed-shape/dtype offered load replays
    exactly. Returns ``[(size, dtype, workload), ...]``."""
    out = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split("/")
        if len(parts) > 3:
            raise ValueError(f"bad --mix entry {entry!r} "
                             "(want SIZE[/DTYPE[/WORKLOAD]])")
        dims = parts[0].lower().split("x")
        if len(dims) not in (1, 3) or not all(
                d.isdigit() and int(d) >= 1 for d in dims):
            raise ValueError(f"bad --mix size {parts[0]!r} "
                             "(want N or XxYxZ)")
        size = ([int(dims[0])] * 3 if len(dims) == 1
                else [int(d) for d in dims])
        dtype = parts[1] if len(parts) > 1 else "float32"
        if dtype not in ("float32", "float64"):
            raise ValueError(f"bad --mix dtype {dtype!r}")
        workload = parts[2] if len(parts) > 2 else "jacobi"
        if workload not in ("jacobi", "astaroth"):
            raise ValueError(f"bad --mix workload {workload!r}")
        out.append((size, dtype, workload))
    if not out:
        raise ValueError("--mix named no entries")
    return out


def burst_gaps(gaps, on_s: float, off_s: float):
    """Reshape Poisson arrival gaps into an on/off duty cycle: arrivals
    keep their seeded order and in-burst spacing, but any arrival that
    would land in an OFF window slides to the start of the next ON
    window — a deterministic transform of the same seeded gap list."""
    period = on_s + off_s
    out = []
    t = 0.0
    prev = 0.0
    for g in gaps:
        t += g
        phase = t % period
        if phase >= on_s:  # lands in the quiet half: slide to next burst
            t += period - phase
        out.append(t - prev)
        prev = t
    return out


def drop_job(incoming: str, doc: dict) -> str:
    """Atomically drop one job document (the intake write contract)."""
    name = f"{doc['job']}.json"
    tmp = os.path.join(incoming, f".tmp-{name}-{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    dst = os.path.join(incoming, name)
    os.replace(tmp, dst)
    return dst


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="seeded Poisson open-loop job generator for the "
                    "serving daemon")
    p.add_argument("--serve-dir", required=True,
                   help="the daemon's service root (jobs land in "
                        "<serve-dir>/jobs/incoming/)")
    p.add_argument("--jobs", type=int, default=8,
                   help="number of jobs to drop")
    p.add_argument("--rate", type=float, default=4.0,
                   help="mean arrival rate in jobs/s (Poisson: "
                        "exponential gaps); 0 = drop everything at once")
    p.add_argument("--seed", type=int, default=0,
                   help="seeds ids, owners, priorities AND arrival gaps "
                        "— the same seed replays the same offered load")
    p.add_argument("--tenants", type=int, default=2,
                   help="owners drawn uniformly from tenant-0..N-1")
    p.add_argument("--size", type=int, default=12,
                   help="per-job cubic domain edge")
    p.add_argument("--steps", type=int, default=4,
                   help="steps per job")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    p.add_argument("--workload", default="jacobi",
                   choices=["jacobi", "astaroth"])
    p.add_argument("--deadline-ms", type=float, default=0.0,
                   help="per-step p99 SLO stamped on every job "
                        "(0 = no deadline)")
    p.add_argument("--mixed-priority", action="store_true",
                   help="draw priorities high/normal/low (seeded) instead "
                        "of all-normal")
    p.add_argument("--mix", default="",
                   help="multi-shape/dtype job mix: comma-separated "
                        "SIZE[/DTYPE[/WORKLOAD]] entries (SIZE = N or "
                        "XxYxZ), e.g. '12,16/float64'; each job draws "
                        "one entry (seeded) — overrides --size/--dtype/"
                        "--workload")
    p.add_argument("--burst", default="",
                   help="on/off duty-cycle arrivals as ON_S,OFF_S "
                        "seconds, e.g. '1,2': the seeded Poisson gaps "
                        "are reshaped so every arrival lands in an ON "
                        "window — bursty offered load, same determinism "
                        "(needs --rate > 0)")
    p.add_argument("--prefix", default="j",
                   help="job id prefix (ids are <prefix>-<seed>-<i>; two "
                        "generators with different seeds never collide)")
    args = p.parse_args(argv)
    if args.jobs < 1:
        p.error(f"--jobs must be >= 1, got {args.jobs}")
    if args.rate < 0:
        p.error(f"--rate must be >= 0, got {args.rate}")
    mix = None
    if args.mix:
        try:
            mix = parse_mix(args.mix)
        except ValueError as e:
            p.error(str(e))
    burst = None
    if args.burst:
        parts = args.burst.split(",")
        try:
            on_s, off_s = (float(parts[0]), float(parts[1]))
        except (IndexError, ValueError):
            p.error(f"bad --burst {args.burst!r} (want ON_S,OFF_S)")
        if on_s <= 0 or off_s < 0:
            p.error(f"--burst needs ON_S > 0 and OFF_S >= 0, "
                    f"got {args.burst!r}")
        if args.rate <= 0:
            p.error("--burst shapes arrival times; it needs --rate > 0")
        burst = (on_s, off_s)

    incoming = os.path.join(args.serve_dir, "jobs", "incoming")
    os.makedirs(incoming, exist_ok=True)
    rng = random.Random(args.seed)
    # draw EVERY gap up front so --mix/--burst never perturb the seeded
    # per-job draws (ids, owners, priorities stay replay-identical)
    gaps = [0.0 if i == 0 else rng.expovariate(args.rate)
            if args.rate > 0 else 0.0 for i in range(args.jobs)]
    if burst is not None:
        gaps = burst_gaps(gaps, burst[0], burst[1])
    t0 = time.perf_counter()
    dropped = []
    for i in range(args.jobs):
        if args.rate > 0 and gaps[i] > 0:
            time.sleep(gaps[i])
        size, dtype, workload = (
            rng.choice(mix) if mix is not None
            else ([args.size] * 3, args.dtype, args.workload))
        doc = {
            "job": f"{args.prefix}-{args.seed}-{i:04d}",
            "size": size,
            "steps": args.steps,
            "dtype": dtype,
            "workload": workload,
            "seed": rng.randrange(1 << 20),
            "tenant": f"tenant-{rng.randrange(args.tenants)}",
            "priority": (rng.choice(PRIORITIES) if args.mixed_priority
                         else "normal"),
        }
        if args.deadline_ms > 0:
            doc["deadline_ms"] = args.deadline_ms
        path = drop_job(incoming, doc)
        print(f"[loadgen] dropped {os.path.basename(path)} "
              f"(tenant={doc['tenant']}, priority={doc['priority']})",
              file=sys.stderr, flush=True)
        dropped.append(doc["job"])
    print(json.dumps({
        "app": "serve_loadgen", "dropped": len(dropped), "seed": args.seed,
        "rate_per_s": args.rate, "wall_s": round(time.perf_counter() - t0, 3),
        "first": dropped[0], "last": dropped[-1],
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
