"""Round-2 profiling: isolate where the jacobi step's time goes.

Times, each as a fused 10-iter loop on the real chip:
  1. pallas sweep alone (double-buffered kernel)
  2. pallas sweep with wrap=(1,1,1) (self-wrap, no exchange needed)
  3. exchange_block alone (r=1, 1 quantity)
  4. full jacobi step (current bench path)
  5. exchange r=3 x 4 quantities (the exchange bench path)
Also numerics: TPU pallas vs XLA path on 128^3.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius, Rect3
from stencil_tpu.parallel import HaloExchange, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_sel, INIT_TEMP, jacobi_sweep
from stencil_tpu.ops.pallas_stencil import make_pallas_jacobi_sweep, sel_z_range, _pick_tiles
from stencil_tpu.utils.sync import hard_sync

N = 512
ITERS = 10
dev = jax.devices()[:1]
print("platform:", dev[0].platform, flush=True)

spec = GridSpec(Dim3(N, N, N), Dim3(1, 1, 1), Radius.constant(1))
p = spec.padded()
print("padded:", p, "tiles:",
      _pick_tiles(spec.base.z, spec.base.y, spec.compute_offset().y, p.y, p.x),
      flush=True)


def timeit(name, fn, *args, rebind=None):
    """rebind: fn's outputs that replace args (for donated buffers)."""
    t0 = time.perf_counter()
    out = fn(*args)
    hard_sync(out)
    compile_s = time.perf_counter() - t0
    best = 1e9
    for _ in range(3):
        a = rebind(out, args) if rebind else args
        t0 = time.perf_counter()
        out = fn(*a)
        hard_sync(out)
        best = min(best, time.perf_counter() - t0)
        args = a
    print(f"{name}: {best/ITERS*1000:.3f} ms/iter  (compile {compile_s:.1f}s)", flush=True)
    return out


# ---- numerics first: TPU pallas vs XLA on 128^3
ns = 128
spec_s = GridSpec(Dim3(ns, ns, ns), Dim3(1, 1, 1), Radius.constant(1))
ps = spec_s.padded()
rng = np.random.RandomState(0)
cs = jnp.asarray(rng.rand(ps.z, ps.y, ps.x).astype(np.float32))
nsx = jnp.zeros((ps.z, ps.y, ps.x), jnp.float32)
off = spec_s.compute_offset()
sl = (slice(off.z, off.z+ns), slice(off.y, off.y+ns), slice(off.x, off.x+ns))
sel_s = np.zeros((ps.z, ps.y, ps.x), np.int32)
sel_s[sl] = sphere_sel(Dim3(ns, ns, ns))
sel_s = jnp.asarray(sel_s)
sweep_s = make_pallas_jacobi_sweep(spec_s, sel_z_range(spec_s))
got = np.asarray(jax.device_get(sweep_s(cs, nsx, sel_s)))
rect = Rect3(off, off + spec_s.base)
want = np.asarray(jax.device_get(
    jacobi_sweep(cs, jnp.zeros_like(nsx), rect, (sel_s == 1, sel_s == 2))))
err = np.abs(got[sl] - want[sl]).max()
print("pallas-vs-xla max err (tpu, 128^3):", err, flush=True)
assert err < 1e-6

# wrap numerics: wrap=(1,1,1) vs np periodic reference
sweep_w = make_pallas_jacobi_sweep(spec_s, sel_z_range(spec_s), wrap=(True, True, True))
got_w = np.asarray(jax.device_get(sweep_w(cs, nsx, sel_s)))
f = np.asarray(jax.device_get(cs))[sl].astype(np.float64)
avg = (np.roll(f, 1, 2) + np.roll(f, -1, 2) + np.roll(f, 1, 1) + np.roll(f, -1, 1)
       + np.roll(f, 1, 0) + np.roll(f, -1, 0)) / 6
selc = np.asarray(sel_s[sl])
avg = np.where(selc == 1, 1.0, np.where(selc == 2, 0.0, avg))
err_w = np.abs(got_w[sl] - avg).max()
print("pallas-wrap-vs-np max err:", err_w, flush=True)
assert err_w < 1e-6

# ---- 1. pallas sweep alone
sweep = make_pallas_jacobi_sweep(spec, sel_z_range(spec))
curr = jnp.full((p.z, p.y, p.x), INIT_TEMP, jnp.float32)
nxt = jnp.zeros((p.z, p.y, p.x), jnp.float32)
sel3 = jnp.zeros((p.z, p.y, p.x), jnp.int32)


def make_sweep_loop(sw):
    @jax.jit
    def sweep_loop(c, x, s):
        def body(_, cn):
            c1, n1 = cn
            return (sw(c1, n1, s), c1)
        return lax.fori_loop(0, ITERS, body, (c, x))
    return sweep_loop


timeit("pallas_sweep_512", make_sweep_loop(sweep), curr, nxt, sel3)

# ---- 2. pallas sweep with full self-wrap
sweep_wrap = make_pallas_jacobi_sweep(spec, sel_z_range(spec), wrap=(True, True, True))
timeit("pallas_sweep_512_wrap", make_sweep_loop(sweep_wrap), curr, nxt, sel3)

# ---- 3. exchange alone r=1 1q
mesh = grid_mesh(spec.dim, dev)
ex1 = HaloExchange(spec, mesh)
loop1 = ex1.make_loop(ITERS)
st = {0: shard_blocks(np.zeros((N, N, N), np.float32), spec, mesh)}
st = timeit("exchange_r1_1q", loop1, st, rebind=lambda out, a: (out,))

# ---- 4. full jacobi loop (bench path)
jl = make_jacobi_loop(ex1, ITERS, overlap=True)
sharding = ex1.sharding()
shape = spec.stacked_shape_zyx()
c6 = jax.device_put(jnp.full(shape, INIT_TEMP, jnp.float32), sharding)
n6 = jax.device_put(jnp.zeros(shape, jnp.float32), sharding)
selb = shard_blocks(sphere_sel(Dim3(N, N, N)), spec, mesh)
timeit("jacobi_full_step", jl, c6, n6, selb,
       rebind=lambda out, a: (out[0], out[1], a[2]))

# ---- 5. exchange r=3 4q (bench exchange path)
spec3 = GridSpec(Dim3(N, N, N), Dim3(1, 1, 1), Radius.constant(3))
ex3 = HaloExchange(spec3, mesh)
loop3 = ex3.make_loop(ITERS)
st3 = {i: shard_blocks(np.zeros((N, N, N), np.float32), spec3, mesh) for i in range(4)}
st3 = timeit("exchange_r3_4q", loop3, st3, rebind=lambda out, a: (out,))
print("logical GB per exchange r3 4q:", ex3.bytes_logical([4] * 4) / 1e9, flush=True)
