"""On-chip probe: the 5-way exchange A/B — composed / auto-spmd /
direct26 / remote-dma / FUSED — plus the wire-compression tiers.

The ISSUE-10 hardware half grown by ISSUE 14 (ROADMAP #5 -> #1): the
kernel-initiated exchange (ops/remote_dma.py) and its FUSED
compute+exchange variant (ops/fused_stencil.py — every per-direction
copy started boundary-first so interior compute hides the wire) are
parity-pinned on the CPU emulation, but the claims they were built
for — per-collective DISPATCH overhead dominates (rounds 7/10), and
wire time can hide behind interior FLOPs — need real ICI. This probe is
the decisive A/B, staged for ONE multi-chip TPU session:

1. composed / direct26 / auto-spmd / remote-dma / fused back-to-back at
   the probe config (radius 2, 4 fp32 quantities, one block per chip),
   trimean ms/exchange + GB/s logical, with the 0-ppermute census
   verified on both kernel-initiated programs;
2. wire-compression rows: remote-dma and fused under
   ``wire_dtype=bfloat16`` (2x bytes) and the fp8 tier
   ``float8_e4m3fn`` (4x bytes) — on TPU the carriers really ship the
   narrow dtype, so this measures what the byte reduction buys on real
   links at each overlap level;
3. numbers feed ``plan/cost.py DEFAULT_CALIBRATION`` ("remote_dma" and
   "fused" provenance flip modeled -> measured) and the plan DB via
   ``plan_tool autotune`` (item-1 recalibration session).

Needs >= 2 TPU chips (a single chip self-wraps every phase and issues no
remote DMA). Exits early with one line when no TPU is present;
``--cpu-smoke`` runs the full 5-way + wire rows against the emulation at
a tiny size instead (the CI-covered path; ratios there are correctness
vehicles, not claims).

Usage: python scripts/probe_remote_dma.py [n] [chunk]
       python scripts/probe_remote_dma.py --cpu-smoke
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

cpu_smoke = "--cpu-smoke" in sys.argv
args = [a for a in sys.argv[1:] if a != "--cpu-smoke"]

if cpu_smoke:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import stencil_tpu  # noqa: F401  (jax-compat shims first)
import jax

if cpu_smoke:
    jax.config.update("jax_platforms", "cpu")

if not cpu_smoke and jax.devices()[0].platform != "tpu":
    print("probe_remote_dma: no TPU on this host — run on the bench host "
          "(or --cpu-smoke for the emulation path)")
    raise SystemExit(0)

import numpy as np

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(args[0]) if args else (16 if cpu_smoke else 256)
chunk = int(args[1]) if len(args) > 1 else (2 if cpu_smoke else 60)
ndev = min(8, len(jax.devices()))
if ndev < 2:
    print(f"probe_remote_dma: {ndev} device(s) — remote DMA needs a "
          "multi-chip ring (single chip self-wraps every phase)")
    raise SystemExit(0)

# the largest 3-factor split of ndev, z-major (grid_mesh handles ICI layout)
from stencil_tpu.geometry import NodePartition

part = NodePartition(Dim3(n, n, n), Radius.constant(2), 1, ndev).dim()
spec = GridSpec(Dim3(n, n, n), part, Radius.constant(2))
mesh = grid_mesh(part, jax.devices()[:ndev])
NQ = 4


def leg(method, wire_dtype=None, fused=False):
    ex = HaloExchange(spec, mesh, method, wire_dtype=wire_dtype,
                      fused=fused)
    loop = ex.make_loop(chunk)
    state = {
        i: shard_blocks(np.zeros((n, n, n), np.float32), spec, mesh)
        for i in range(NQ)
    }
    t0 = time.time()
    state = loop(state)
    hard_sync(state)
    build_s = time.time() - t0
    st = Statistics()
    for _ in range(3):
        t0 = time.perf_counter()
        state = loop(state)
        hard_sync(state)
        st.insert((time.perf_counter() - t0) / chunk)
    census = ex.collective_census(
        {i: shard_blocks(np.zeros((n, n, n), np.float32), spec, mesh)
         for i in range(NQ)})
    cp = census.get("collective-permute", (0, 0))
    gb = ex.bytes_logical([4] * NQ) / st.trimean() / 1e9
    tag = (method.value + ("+fused" if fused else "")
           + (f"+wire={wire_dtype}" if wire_dtype else ""))
    print(f"{tag:40s} {st.trimean()*1e3:9.3f} ms/exchange  {gb:7.2f} GB/s  "
          f"permutes={cp[0]:3d} cp_bytes={cp[1]}  (compile {build_s:.0f}s)",
          flush=True)
    return st.trimean(), cp


print(f"remote-dma/fused probe: {n}^3, partition {part}, {ndev} devices, "
      f"r2, {NQ} fp32 quantities, chunk {chunk}", flush=True)
# the 5-way A/B: every transport at the same config
t_comp, _ = leg(Method.AXIS_COMPOSED)
leg(Method.DIRECT26)
leg(Method.AUTO_SPMD)
t_rd, cp_rd = leg(Method.REMOTE_DMA)
assert cp_rd[0] == 0, f"REMOTE_DMA census shows {cp_rd[0]} ppermutes"
t_fu, cp_fu = leg(Method.REMOTE_DMA, fused=True)
assert cp_fu[0] == 0, f"FUSED census shows {cp_fu[0]} ppermutes"
# wire tiers on both kernel-initiated transports: bf16 (2x) + fp8 (4x)
for wd in ("bfloat16", "float8_e4m3fn"):
    leg(Method.REMOTE_DMA, wire_dtype=wd)
    leg(Method.REMOTE_DMA, wire_dtype=wd, fused=True)
kind = ("TPU carrier kernels" if not cpu_smoke
        else "CPU emulation — correctness vehicle, ratios not claims")
print(f"remote_dma_over_composed: {t_comp / t_rd:.3f}x ({kind})", flush=True)
print(f"fused_over_remote_dma:    {t_rd / t_fu:.3f}x ({kind})", flush=True)
