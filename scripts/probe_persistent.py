"""On-chip probe: the persistent whole-chunk mega-kernel A/B — per-step
remote-dma / fused / PERSISTENT at k in {2, 4} — the launch-economics
measurement.

The ISSUE-16 hardware half (ROADMAP #7 -> #1): the persistent variant
(ops/persistent_stencil.py — one deep radius*k exchange + one k-substep
chunk program, 2 dispatches per chunk instead of 2k) is parity-pinned on
the CPU emulation, but the claim it was built for — per-LAUNCH overhead
dominates small-block stencil chunks, and temporal fusion amortizes it —
needs real silicon. This probe is the decisive A/B, staged for ONE
multi-chip TPU session:

1. per-step remote-dma / fused / persistent@k2 / persistent@k4
   back-to-back at the probe config (fp32 jacobi, one block per chip),
   trimean ms/ITERATION + Mcells/s/chip, with the measured
   ``launches_per_chunk`` census printed per leg (the plan predicts 2
   for persistent vs 2k per-step; the TPU mega-kernel path should
   measure 1 — that number is what flips ir.launches_per_chunk's
   conservative 2 and prices DEFAULT_CALIBRATION["persistent"]
   provenance modeled -> measured);
2. numbers feed ``plan/cost.py DEFAULT_CALIBRATION["persistent"]``
   (launch_overhead_s) and the plan DB via ``plan_tool autotune --ks``
   (item-1 recalibration session).

Needs >= 2 TPU chips (a single chip self-wraps every direction and the
deep exchange issues no remote DMA). Exits early with one line when no
TPU is present; ``--cpu-smoke`` runs the full A/B against the
host-orchestrated emulation at a tiny size instead (the CI-covered
path; ratios there price host dispatch, not ICI).

Usage: python scripts/probe_persistent.py [n] [iters]
       python scripts/probe_persistent.py --cpu-smoke
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

cpu_smoke = "--cpu-smoke" in sys.argv
args = [a for a in sys.argv[1:] if a != "--cpu-smoke"]

if cpu_smoke:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import stencil_tpu  # noqa: F401  (jax-compat shims first)
import jax

if cpu_smoke:
    jax.config.update("jax_platforms", "cpu")

if not cpu_smoke and jax.devices()[0].platform != "tpu":
    print("probe_persistent: no TPU on this host — run on the bench host "
          "(or --cpu-smoke for the emulation path)")
    raise SystemExit(0)

import jax.numpy as jnp
import numpy as np

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, NodePartition, Radius
from stencil_tpu.ops.jacobi import INIT_TEMP, make_jacobi_loop, sphere_sel
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(args[0]) if args else (24 if cpu_smoke else 256)
iters = int(args[1]) if len(args) > 1 else (4 if cpu_smoke else 40)
ndev = min(8, len(jax.devices()))
if ndev < 2:
    print(f"probe_persistent: {ndev} device(s) — the deep exchange needs a "
          "multi-chip ring (single chip self-wraps every direction)")
    raise SystemExit(0)

part = NodePartition(Dim3(n, n, n), Radius.constant(4), 1, ndev).dim()


def leg(tag, radius, k=None, fused=False, persistent=False):
    spec = GridSpec(Dim3(n, n, n), part, Radius.constant(radius))
    mesh = grid_mesh(part, jax.devices()[:ndev])
    ex = HaloExchange(spec, mesh, Method.REMOTE_DMA, fused=fused,
                      persistent=persistent)
    loop = make_jacobi_loop(ex, iters, temporal_k=k)
    sel = shard_blocks(sphere_sel((n, n, n)), spec, mesh)
    c = shard_blocks(np.full((n,) * 3, INIT_TEMP, np.float32), spec, mesh)
    nx = jax.device_put(jnp.zeros_like(c), ex.sharding())
    t0 = time.time()
    c, nx = loop(c, nx, sel)  # compile + warm
    hard_sync((c, nx))
    build_s = time.time() - t0
    st = Statistics()
    for _ in range(3):
        t0 = time.perf_counter()
        c, nx = loop(c, nx, sel)
        hard_sync((c, nx))
        st.insert((time.perf_counter() - t0) / iters)
    lpc = getattr(ex, "last_launches_per_chunk", 0)
    mc = n ** 3 / st.trimean() / 1e6 / ndev
    print(f"{tag:28s} {st.trimean()*1e3:9.3f} ms/iter  {mc:9.2f} "
          f"Mcells/s/chip  launches/chunk={lpc}  (compile {build_s:.0f}s)",
          flush=True)
    return st.trimean(), lpc


print(f"persistent probe: {n}^3, partition {part}, {ndev} devices, "
      f"fp32 jacobi, {iters} iters/call", flush=True)
t_rd, _ = leg("remote-dma per-step", radius=1)
t_fu, _ = leg("remote-dma fused", radius=1, fused=True)
t_p2, lpc2 = leg("persistent k=2", radius=2, k=2, persistent=True)
t_p4, lpc4 = leg("persistent k=4", radius=4, k=4, persistent=True)
# the host-orchestrated schedule pays exactly 2 dispatches per chunk
# (deep exchange + chunk program); the TPU mega-kernel path measures 1
assert lpc2 in (1, 2), f"persistent k=2 census {lpc2} not O(chunks)"
assert lpc4 in (1, 2), f"persistent k=4 census {lpc4} not O(chunks)"
kind = ("TPU mega-kernel" if not cpu_smoke
        else "CPU emulation — dispatch amortization, not ICI")
print(f"persistent_k2_over_fused:  {t_fu / t_p2:.3f}x ({kind})", flush=True)
print(f"persistent_k4_over_fused:  {t_fu / t_p4:.3f}x ({kind})", flush=True)
print(f"persistent_k4_over_perstep: {t_rd / t_p4:.3f}x ({kind})", flush=True)
