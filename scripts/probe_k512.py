"""Probe: jacobi 512^3 temporal depth beyond the k=10 cap.

The cap was measured before the tight-x kernels (k=2 5.69 / k=6 3.88 /
k=10 3.20 ms/step, BASELINE round 2); the current multistep runs 1.77
ms/step at k=10, so the wavefront floor moved and the diminishing-returns
point needs re-measuring. The VMEM staging budget allows k~13 at 512^3.
Uses the same iteration/chunk discipline as bench.py's headline leg.

Usage: python scripts/probe_k512.py [n] [k ...]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax

n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
ks = [int(a) for a in sys.argv[2:]] or [10, 12, 13]
on_accel = jax.devices()[0].platform != "cpu"
chunk = 360 if on_accel else 3

from stencil_tpu.apps.jacobi3d import run  # noqa: E402

for k in ks:
    os.environ["STENCIL_TEMPORAL_K_CAP"] = str(k)
    r = run(n, n, n, iters=3 * chunk, weak=False, devices=jax.devices()[:1],
            warmup=1, chunk=chunk)
    print(
        f"k_cap={k}: {r['iter_trimean_s']*1e3:.3f} ms/iter "
        f"({r['mcells_per_s_per_dev']:.0f} Mcells/s/dev)",
        flush=True,
    )
