"""Single shared TPU alive probe (used by r05_watch.sh and r04_measure.sh).

Prints the device list and an ``alive <sum>`` line on success; any hang is
the caller's problem (wrap in ``timeout``). Kept as one file so the watcher
and the measurement queue's alive gate can never drift apart.
"""

import jax
import jax.numpy as jnp

print(jax.devices())
x = jnp.ones((256, 256))
print("alive", float((x @ x).sum()))
