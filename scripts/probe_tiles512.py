"""512^3 astaroth substep tile/budget retune under the tight-x layout:
is the 22 MB scratch budget leaving tile-shape performance on the table?
(the VMEM compile ceiling probe said ~34 MB still compiles)"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
import stencil_tpu.ops.pallas_astaroth as pa
from stencil_tpu.astaroth.config import load_config
from stencil_tpu.astaroth.equations import Constants
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = 512
spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3).without_x())
info, _ = load_config("stencil_tpu/astaroth/astaroth.conf")
c = Constants.from_info(info)
inv_ds = tuple(info.real_params[k] for k in ("AC_inv_dsx", "AC_inv_dsy", "AC_inv_dsz"))
p = spec.padded()
rng = np.random.RandomState(7)
curr = tuple(jnp.asarray(rng.rand(p.z, p.y, p.x) * 0.1, jnp.float32) for _ in pa.FIELDS)
out_np = rng.rand(p.z, p.y, p.x) * 0.1
chunk = 12
print(f"auto pick: {pa.pick_tiles(spec)}", flush=True)
for tiles in (None, (2, 64), (2, 128), (4, 64), (1, 256)):
    out = tuple(jnp.asarray(out_np, jnp.float32) for _ in pa.FIELDS)
    label = tiles or pa.pick_tiles(spec)
    try:
        mb = pa.scratch_bytes(spec, *(tiles or pa.pick_tiles(spec))) / 2**20
        sub = pa.make_pallas_substep(spec, c, inv_ds, 1, 1e-8, tiles=tiles)
        fn = jax.jit(lambda cu, ou: jax.lax.fori_loop(
            0, chunk, lambda _, o: sub(cu, o), ou), donate_argnums=(1,))
        t0 = time.time(); out2 = fn(curr, out); hard_sync(out2)
        cs = time.time() - t0
        st = Statistics()
        for _ in range(3):
            t0 = time.perf_counter(); out2 = fn(curr, out2); hard_sync(out2)
            st.insert((time.perf_counter() - t0) / chunk)
        print(f"tiles {label} ({mb:.1f} MB): {st.trimean()*1e3:.2f} ms/substep "
              f"(compile {cs:.0f}s)", flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"tiles {label}: FAIL {type(e).__name__}: {str(e)[:200]}", flush=True)
