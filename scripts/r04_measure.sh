#!/bin/bash
# Round-4 serialized TPU measurement session. ONE TPU job at a time (the
# tunneled platform cross-pollutes timings and compiles under contention).
# Each step logs to scripts/r04_logs/<name>.log with its own wall budget;
# a wedged step is killed and the queue continues.
#
# Usage: bash scripts/r04_measure.sh [start_step]
# Exit codes: 0 = every step completed; 1..8 = number of failed/timed-out
# steps; 10 = aborted at the alive gate (tunnel dead, nothing ran);
# 11 = setup failure before the gate (nothing ran).
cd "$(dirname "$0")/.." || exit 11
LOG=${MEASURE_LOG_DIR:-scripts/r04_logs}
mkdir -p "$LOG"
START=${1:-1}

FAILED=0
step() {
  local num=$1 name=$2 budget=$3
  shift 3
  if [ "$num" -lt "$START" ]; then return; fi
  echo "=== step $num $name ($(date +%H:%M:%S), budget ${budget}s)" | tee -a "$LOG/session.log"
  timeout "$budget" "$@" > "$LOG/$name.log" 2>&1
  local rc=$?
  [ "$rc" -ne 0 ] && FAILED=$((FAILED + 1))
  echo "=== step $num $name rc=$rc ($(date +%H:%M:%S))" | tee -a "$LOG/session.log"
}

# step 1 (implicit) — alive gate: ALWAYS probed (even when resuming
# mid-queue) — do not burn budgets against a wedged tunnel or trust a
# stale alive.log
timeout 300 python scripts/tpu_alive_probe.py > "$LOG/alive.log" 2>&1
# exit 10 = aborted at the alive gate (nothing ran) — distinct from the
# failed-step count (max 8) so callers can branch on rc alone
grep -q "^alive" "$LOG/alive.log" || { echo "TPU not alive; aborting" | tee -a "$LOG/session.log"; exit 10; }
echo "=== alive gate passed ($(date +%H:%M:%S))" | tee -a "$LOG/session.log"

# 2. 512^3 substep autotune table (VERDICT item 2)
step 2 tiles512 2700 python scripts/probe_tiles512.py

# 3. correct-math microbenchmarks: window-shift + y-ring at 512^3
step 3 vmem_ops 1800 python scripts/probe_vmem_ops.py 512

# 4. MXU banded-matmul taps vs VPU slices at 512^3 shapes
step 4 mxu_taps 1800 python scripts/probe_mxu_taps.py 512

# 5. fp64 astaroth at the reference's own 256^3 config (serialized path)
step 5 f64_256 3600 python scripts/probe_f64.py 256

# 6. fp64 + hoisted-exchange overlap (round-4 structure): compile budget
#    2x the serialized path's; 32^3 then 64^3
step 6 f64_overlap 3600 env STENCIL_PROBE_F64_OVERLAP=1 python scripts/probe_f64.py 32 64

# 7. weak-scaling single-chip anchors at the pinned temporal depth k=4
step 7 record_base 2700 python -m stencil_tpu.apps.weak_scaling --record-base

# 8. config-2 geometry fully resident on the one chip: the first REAL
#    multi-block exchange + jacobi numbers (previously virtual-CPU only)
step 8 resident_exchange 1800 python scripts/probe_resident_exchange.py

# 9. the full bench (green-artifact rehearsal: headline + exchange +
#    astaroth 256 + budget-gated astaroth 512)
step 9 bench 1500 env STENCIL_BENCH_BUDGET_S=1200 python bench.py

echo "=== session done, failed_steps=$FAILED ($(date +%H:%M:%S))" | tee -a "$LOG/session.log"
exit "$FAILED"
