#!/bin/bash
# Judge-runnable slow tier (VERDICT r4 item 5): the @pytest.mark.slow tests
# (multi-minute interpret-mode Pallas parity + subprocess robustness) split
# into deterministic shards, each small enough for a ~10-minute window —
# the analogue of the reference's tag-filtered ctest slices
# (reference: README.md:81-88).
#
# Usage: bash scripts/run_slow.sh <shard 1..N> <nshards>
#   e.g. bash scripts/run_slow.sh 1 3; bash scripts/run_slow.sh 2 3; ...
# A recorded full local run lives in scripts/slow_logs/ (see the *.log
# files' trailing summary lines).
set -u
cd "$(dirname "$0")/.." || exit 1
SHARD=${1:-1}
NSHARDS=${2:-3}
# integer validation BEFORE the range checks: a non-numeric arg must hit
# the usage message, not an arithmetic error inside [ -lt ] (ADVICE r5 #2)
case "$SHARD" in
  ''|*[!0-9]*) echo "usage: run_slow.sh <shard 1..N> <nshards> (SHARD must be an integer, got '$SHARD')" >&2; exit 2 ;;
esac
case "$NSHARDS" in
  ''|*[!0-9]*) echo "usage: run_slow.sh <shard 1..N> <nshards> (NSHARDS must be an integer, got '$NSHARDS')" >&2; exit 2 ;;
esac
if [ "$NSHARDS" -lt 1 ] || [ "$SHARD" -lt 1 ] || [ "$SHARD" -gt "$NSHARDS" ]; then
  echo "shard must be in 1..$NSHARDS (nshards >= 1)" >&2
  exit 2
fi

# stable shard assignment: sorted node ids, round-robin by index (clustered
# same-file parametrizations spread across shards). Collection stderr goes
# to a temp file so an import error is distinguishable from a genuinely
# empty tier (ADVICE r5 #2).
COLLECT_ERR=$(mktemp)
trap 'rm -f "$COLLECT_ERR"' EXIT
mapfile -t ALL < <(python -m pytest tests/ -q --collect-only -m slow 2>"$COLLECT_ERR" | grep '::' | sort)
if [ "${#ALL[@]}" -eq 0 ]; then
  echo "collected no slow tests; collect-only stderr follows:" >&2
  cat "$COLLECT_ERR" >&2
  exit 2
fi
SEL=()
for i in "${!ALL[@]}"; do
  if [ $((i % NSHARDS)) -eq $((SHARD - 1)) ]; then SEL+=("${ALL[$i]}"); fi
done
echo "slow shard $SHARD/$NSHARDS: ${#SEL[@]} of ${#ALL[@]} tests"
if [ "${#SEL[@]}" -eq 0 ]; then
  # bare `pytest -m slow` would run the WHOLE tier — an empty shard must
  # run nothing
  echo "empty shard"
  exit 0
fi
exec python -m pytest -m slow -q "${SEL[@]}"
