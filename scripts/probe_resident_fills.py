"""Probe: the z-stacked resident self-fill path on REAL TPU hardware.

A (cz,1,1) z-stack keeps the in-place Pallas x/y halo fills by folding
the shard into one (cz*pz, py, px) view (round 5, halo_fill.py z_stack).
The interpret-mode tests pin parity; this probe runs the production
wiring on the chip: verifies every resident block's halos against the
position-coded pattern, and times the exchange with fills vs the XLA
slab fallback (use the env knob STENCIL_PROBE_NO_FILLS=1 to compare).

Usage: python scripts/probe_resident_fills.py [n] [cz]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import numpy as np

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.utils.statistics import Statistics
from stencil_tpu.utils.sync import hard_sync

n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
cz = int(sys.argv[2]) if len(sys.argv) > 2 else 2
on_accel = jax.devices()[0].platform != "cpu"
chunk = 120 if on_accel else 3

assert n % cz == 0, "the halo check assumes a uniform z split"
spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, cz), Radius.constant(2))
mesh = grid_mesh(Dim3(1, 1, 1), jax.devices()[:1])
ex = HaloExchange(spec, mesh)
assert tuple(ex.resident) == (1, 1, cz), ex.resident
if os.environ.get("STENCIL_PROBE_NO_FILLS"):
    ex.__dict__["_self_fills"] = {}
fills = sorted(ex._self_fills)
print(f"resident fills {n}^3 z-stack cz={cz}: active fills = {fills}", flush=True)

# position-coded pattern: value = z*65536 + y*256 + x — for n <= 256
# every packed value is an integer < 2^24, exactly representable in fp32
g = spec.global_size
assert g.x <= 256 and g.y <= 256 and g.z <= 256
coords = (
    np.arange(g.z)[:, None, None] * 65536.0
    + np.arange(g.y)[None, :, None] * 256.0
    + np.arange(g.x)[None, None, :]
).astype(np.float32)
state = {0: shard_blocks(coords, spec, mesh)}
t0 = time.time()
state = ex(state)
hard_sync(state)
print(f"compile+first {time.time()-t0:.0f}s", flush=True)

# verify every resident block's FULL halo ring (vectorized: every cell
# of the padded block whose local coord falls outside the compute region
# and inside the halo reach)
arr = np.asarray(jax.device_get(state[0]))
off = spec.compute_offset()
r = spec.radius
bz = g.z // cz
p3 = spec.padded()
lz = np.arange(p3.z) - off.z  # block-local compute coords
ly = np.arange(p3.y) - off.y
lx = np.arange(p3.x) - off.x
in_z = (lz >= -r.z(-1)) & (lz < bz + r.z(1))
in_y = (ly >= -r.y(-1)) & (ly < g.y + r.y(1))
in_x = (lx >= -r.x(-1)) & (lx < g.x + r.x(1))
core_z = (lz >= 0) & (lz < bz)
core_y = (ly >= 0) & (ly < g.y)
core_x = (lx >= 0) & (lx < g.x)
reach = in_z[:, None, None] & in_y[None, :, None] & in_x[None, None, :]
core = core_z[:, None, None] & core_y[None, :, None] & core_x[None, None, :]
halo = reach & ~core
bad = checked = 0
for j in range(cz):
    want = (
        ((j * bz + lz[:, None, None]) % g.z) * 65536.0
        + (ly[None, :, None] % g.y) * 256.0
        + (lx[None, None, :] % g.x)
    ).astype(np.float32)
    mism = (arr[j, 0, 0] != want) & halo
    checked += int(halo.sum())
    bad += int(mism.sum())
print(f"halo check: {checked} cells, {bad} bad", flush=True)
assert bad == 0

loop = ex.make_loop(chunk)
state = loop(state)
hard_sync(state)
st = Statistics()
for _ in range(3):
    t0 = time.perf_counter()
    state = loop(state)
    hard_sync(state)
    st.insert((time.perf_counter() - t0) / chunk)
print(
    f"resident-fills exchange {n}^3 cz={cz} r2 1q: "
    f"{st.trimean()*1e3:.3f} ms/exchange (fills={bool(fills)})",
    flush=True,
)
