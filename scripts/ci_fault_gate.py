#!/usr/bin/env python
"""CI fault-tolerance gate: inject -> detect -> roll back -> bit-identical.

The executable acceptance proof of the fault/ self-healing layer on the
8-virtual-device CPU mesh (no TPU needed), jacobi3d 24^3, 6 iterations,
checkpoint + health cadence of 2:

1. reference: a clean run (guard ON — also proves no false positives)
   writes its final-state snapshot;
2. detect + rollback: ``--inject nan@3`` bursts NaN into one block at
   step 3; the guard must detect within ``--health-every`` steps
   (metrics pin: health.fault step - fault.injected step <= 2), roll
   back to the step-2 snapshot, complete with rc 0, and the final field
   must be bit-identical to the reference (``ckpt_tool diff --data``);
3. newest-corrupt fallback: ``ckpt-truncate@5`` truncates the newest
   (step-4) snapshot before the ``nan@5`` fault; the rollback must skip
   it to the prior good step-2 snapshot (metrics pin:
   recover.rollback to_step == 2) and still finish bit-identical;
4. quarantine: a hand-truncated snapshot fails ``ckpt_tool validate``,
   ``validate --all --quarantine`` renames it aside, and a re-validate
   of the remaining snapshots passes — auto-resume stops rescanning it;
5. exhaustion: ``nan@3:repeat=always`` with ``--max-rollbacks 2`` must
   abort with the DISTINCT fault rc (43) under the watchdog, which
   classifies the outcome as ``fault`` (not crash/stall), archives the
   child's metrics JSONL as evidence, and leaves a fault-evidence.json
   bundle in the checkpoint dir;
6. schema: every produced metrics file passes ``report --validate``
   (the telemetry gate extended to the fault.*/health.*/recover.*
   vocabulary) and carries health.check spans (the guard's measured
   per-check overhead).

Exit code 0 only if every stage holds. Run from the repo root:

  python scripts/ci_fault_gate.py [--size 24] [--iters 6]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def run(cmd, env=None, expect_rc=0, name=""):
    print(f"[fault-gate] {name}: {' '.join(cmd)}", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    p = subprocess.run(cmd, env=e, cwd=REPO, capture_output=True, text=True)
    if p.returncode != expect_rc:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(
            f"[fault-gate] {name}: rc={p.returncode}, expected {expect_rc}"
        )
    return p


def jacobi(args, extra, env=None, expect_rc=0, name=""):
    cmd = [
        PY, "-m", "stencil_tpu.apps.jacobi3d", "--cpu", "8",
        "--x", str(args.size), "--y", str(args.size), "--z", str(args.size),
        "--iters", str(args.iters), "--ckpt-every", "2", "--health-every",
        "2", "--rollback-backoff", "0.05",
    ] + extra
    return run(cmd, env=env, expect_rc=expect_rc, name=name)


def records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def named(recs, name):
    return [r for r in recs if r.get("name") == name]


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--iters", type=int, default=6)
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="fault-gate-")
    ref = os.path.join(work, "ref")
    metrics = []
    try:
        # 1. clean reference, guard ON: no false positives, rc 0
        jacobi(args, ["--ckpt-dir", ref], name="reference")

        # 2. inject -> detect within --health-every -> roll back -> finish
        ck = os.path.join(work, "ck")
        m1 = os.path.join(work, "m1.jsonl")
        metrics.append(m1)
        jacobi(args, ["--ckpt-dir", ck, "--inject", "nan@3",
                      "--metrics-out", m1], name="nan-rollback")
        recs = records(m1)
        inj = named(recs, "fault.injected")
        flt = named(recs, "health.fault")
        rb = named(recs, "recover.rollback")
        if not (inj and flt and rb):
            raise SystemExit("[fault-gate] metrics lack fault.injected/"
                             "health.fault/recover.rollback records")
        if flt[0]["step"] - inj[0]["step"] > 2:
            raise SystemExit(
                f"[fault-gate] detection at step {flt[0]['step']} is more "
                f"than --health-every after injection at {inj[0]['step']}")
        if not named(recs, "health.check"):
            raise SystemExit("[fault-gate] no health.check spans recorded")
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "diff", ref, ck,
             "--data"], name="diff-rollback")

        # 3. newest snapshot corrupted -> fall back to the prior good one
        ck2 = os.path.join(work, "ck2")
        m2 = os.path.join(work, "m2.jsonl")
        metrics.append(m2)
        jacobi(args, ["--ckpt-dir", ck2, "--inject", "ckpt-truncate@5,nan@5",
                      "--metrics-out", m2], name="corrupt-fallback")
        rb2 = named(records(m2), "recover.rollback")
        if not rb2 or rb2[0]["to_step"] != 2:
            raise SystemExit(f"[fault-gate] fallback rolled to "
                             f"{rb2 and rb2[0]['to_step']}, expected the "
                             "prior good snapshot at step 2")
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "diff", ref, ck2,
             "--data"], name="diff-fallback")

        # 4. quarantine: a truncated snapshot is renamed aside and stays out
        # of every later scan (the run above left ck2's step-4 truncated
        # only transiently — it was re-saved clean — so truncate one here)
        sys.path.insert(0, REPO)
        from stencil_tpu.ckpt import find_resume, list_snapshots

        victim = os.path.join(ck2, list_snapshots(ck2)[0], "block_0_0_0.npz")
        with open(victim, "r+b") as f:
            f.truncate(16)
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "validate", ck2,
             "--all"], expect_rc=1, name="validate-corrupt")
        q = run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "validate", ck2,
                 "--all", "--quarantine"], expect_rc=1, name="quarantine")
        if "quarantined ->" not in q.stdout:
            raise SystemExit("[fault-gate] --quarantine did not rename the "
                             "invalid snapshot")
        run([PY, "-m", "stencil_tpu.apps.ckpt_tool", "validate", ck2,
             "--all"], name="validate-post-quarantine")
        found = find_resume(ck2)
        if found is None or "quarantine" in found[0]:
            raise SystemExit("[fault-gate] find_resume still sees the "
                             "quarantined snapshot")

        # 5. exhaustion under the watchdog: distinct rc, fault outcome,
        # archived metrics evidence, evidence bundle on disk
        from stencil_tpu.obs import watchdog

        ck3 = os.path.join(work, "ck3")
        m3 = os.path.join(work, "m3.jsonl")
        metrics.append(m3)
        env = dict(os.environ)
        env["STENCIL_METRICS_OUT"] = m3
        cmd = [
            PY, "-m", "stencil_tpu.apps.jacobi3d", "--cpu", "8",
            "--x", str(args.size), "--y", str(args.size),
            "--z", str(args.size), "--iters", str(args.iters),
            "--ckpt-every", "2", "--health-every", "2",
            "--rollback-backoff", "0.05", "--ckpt-dir", ck3,
            "--max-rollbacks", "2", "--inject", "nan@3:repeat=always",
            "--metrics-out", m3,
        ]
        print(f"[fault-gate] exhaustion: {' '.join(cmd)}", flush=True)
        att = watchdog.supervise(
            cmd, timeout_s=600, env=env, name="exhaustion", cwd=REPO,
            archive_dir=os.path.join(work, "logs"),
        )
        if att.outcome != watchdog.FAULT or att.rc != watchdog.FAULT_RC:
            raise SystemExit(
                f"[fault-gate] exhaustion outcome={att.outcome} rc={att.rc}, "
                f"expected {watchdog.FAULT}/{watchdog.FAULT_RC}")
        if not (att.metrics_log_path and os.path.isfile(att.metrics_log_path)):
            raise SystemExit("[fault-gate] watchdog did not archive the "
                             "metrics JSONL evidence")
        evidence = os.path.join(ck3, "fault-evidence.json")
        with open(evidence) as f:
            ev = json.load(f)
        if sum(ev["rollbacks"].values()) <= 2 or "max rollbacks" not in ev["reason"]:
            raise SystemExit(f"[fault-gate] unexpected evidence bundle: {ev}")
        ab = named(records(m3), "recover.aborted")
        if not ab:
            raise SystemExit("[fault-gate] metrics lack recover.aborted")

        # 6. every metrics file passes the (extended) telemetry schema gate
        run([PY, "-m", "stencil_tpu.apps.report"] + metrics + ["--validate"],
            name="report-validate")

        print("[fault-gate] PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
