"""Export the multi-block fast-path steps for the TPU platform and report
their overlap dataflow (the machine check of tests/test_overlap_hlo.py).

Runs the full Mosaic kernel lowering without TPU hardware via jax.export.
Executed as a subprocess by the test suite because jax.export's deep
lowering recursion is incompatible with pytest's stack/rewriting; also
usable standalone:

    python scripts/export_overlap_hlo.py jacobi-overlap
    python scripts/export_overlap_hlo.py jacobi-serial
    python scripts/export_overlap_hlo.py astaroth-overlap

Prints one JSON line: the overlap_report() dict.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import stencil_tpu  # noqa: F401 - older-jax shims must precede config use
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.utils.hlo_check import overlap_report


def jacobi_export(overlap: bool) -> str:
    from stencil_tpu.ops.jacobi import make_jacobi_step, sphere_sel

    size = Dim3(32, 32, 32)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    step = make_jacobi_step(ex, overlap=overlap, use_pallas=True, interpret=False)
    z = np.zeros((32, 32, 32), np.float32)
    curr = shard_blocks(z, spec, mesh)
    nxt = shard_blocks(z, spec, mesh)
    sel = shard_blocks(sphere_sel(size), spec, mesh)
    return jax.export.export(step, platforms=["tpu"])(curr, nxt, sel).mlir_module()


def jacobi_sidebuf_export() -> str:
    """Multi-block tight-x (out-of-line side buffers, VERDICT r3 item 5):
    dim 2x2x1, zero x radius — the full sweep must stay independent of the
    y permutes AND the x side-buffer permutes."""
    from stencil_tpu.ops.jacobi import make_jacobi_step, sphere_sel

    size = Dim3(256, 16, 12)
    spec = GridSpec(size, Dim3(2, 2, 1), Radius.constant(1).without_x())
    mesh = grid_mesh(spec.dim, jax.devices()[:4])
    ex = HaloExchange(spec, mesh)
    step = make_jacobi_step(ex, overlap=True, use_pallas=True, interpret=False)
    z = np.zeros((size.z, size.y, size.x), np.float32)
    curr = shard_blocks(z, spec, mesh)
    nxt = shard_blocks(z, spec, mesh)
    sel = shard_blocks(sphere_sel(size), spec, mesh)
    return jax.export.export(step, platforms=["tpu"])(curr, nxt, sel).mlir_module()


def astaroth_export() -> str:
    from stencil_tpu.astaroth import config as ac_config
    from stencil_tpu.astaroth.integrate import FIELDS, make_astaroth_step
    from stencil_tpu.apps.astaroth import DEFAULT_CONF

    n = 32
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    size = Dim3(n, n, n)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    step = make_astaroth_step(
        ex, info, dt=1e-3, overlap=True, dtype="float32",
        use_pallas=True, interpret=False,
    )
    z = np.zeros((n, n, n), np.float32)
    curr = {k: shard_blocks(z, spec, mesh) for k in FIELDS}
    nxt = {k: shard_blocks(z, spec, mesh) for k in FIELDS}
    return jax.export.export(step, platforms=["tpu"])(curr, nxt).mlir_module()


def main(which: str) -> int:
    if which == "jacobi-overlap":
        txt = jacobi_export(True)
    elif which == "jacobi-serial":
        txt = jacobi_export(False)
    elif which == "jacobi-sidebuf":
        txt = jacobi_sidebuf_export()
    elif which == "astaroth-overlap":
        txt = astaroth_export()
    else:
        raise SystemExit(f"unknown target {which!r}")
    print(json.dumps(overlap_report(txt)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1] if len(sys.argv) > 1 else "jacobi-overlap"))
