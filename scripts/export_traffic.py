"""Export production Pallas kernels for the TPU platform and print their
static DMA-traffic inventory (stencil_tpu.utils.mosaic_traffic) as JSON.

Run as a subprocess by tests/test_traffic_accounting.py (jax.export's deep
lowering recursion is incompatible with pytest's rewritten frames — same
trick as export_overlap_hlo.py); also usable standalone:

    python scripts/export_traffic.py multistep 4
    python scripts/export_traffic.py substep [n] [inline|tight]
    python scripts/export_traffic.py fill-x|fill-y|fill-z

Prints one JSON line: {"kernels": [KernelTraffic.report(), ...], ...extras}.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.utils.mosaic_traffic import capture_traffic


def multistep(k: int) -> dict:
    """Temporal-blocked jacobi at a single-block 256x128x32: the 1/k-HBM
    claim (BASELINE.md; ops/pallas_stencil.make_pallas_jacobi_multistep)."""
    from stencil_tpu.ops.pallas_stencil import make_pallas_jacobi_multistep

    spec = GridSpec(Dim3(256, 128, 32), Dim3(1, 1, 1), Radius.constant(1))
    p = spec.padded()

    def build():
        fn = make_pallas_jacobi_multistep(spec, k)
        z = jnp.zeros((p.z, p.y, p.x), jnp.float32)
        return fn, (z, z)

    kernels = capture_traffic(build)
    return {
        "kernels": [kt.report() for kt in kernels],
        "padded": [p.z, p.y, p.x],
        "base": [spec.base.z, spec.base.y, spec.base.x],
        "k": k,
    }


def substep(n: int = 64, tight_x: bool = False) -> dict:
    """Astaroth fused RK3 substep (8 fp32 fields): the (ty+16)/ty x px/nx
    input-amplification claim. ``tight_x`` builds the Radius.without_x
    layout (px == nx — the x amplification factor the tight layout
    removes); ``n`` picks the config (256 = the production tiling)."""
    from stencil_tpu.astaroth import config as ac_config
    from stencil_tpu.astaroth.equations import Constants
    from stencil_tpu.ops.pallas_astaroth import make_pallas_substep, pick_tiles

    info = ac_config.AcMeshInfo()
    from stencil_tpu.apps.astaroth import DEFAULT_CONF

    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    c = Constants.from_info(info)
    inv_ds = (
        info.real_params["AC_inv_dsx"],
        info.real_params["AC_inv_dsy"],
        info.real_params["AC_inv_dsz"],
    )
    r = Radius.constant(3).without_x() if tight_x else Radius.constant(3)
    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), r)
    p = spec.padded()
    tz, ty = pick_tiles(spec)

    def build():
        fn = make_pallas_substep(spec, c, inv_ds, substep=1, dt=1e-3)
        z = tuple(jnp.zeros((p.z, p.y, p.x), jnp.float32) for _ in range(8))
        return (lambda cu, ou: fn(cu, ou)), (z, z)

    kernels = capture_traffic(build)
    return {
        "kernels": [kt.report() for kt in kernels],
        "padded": [p.z, p.y, p.x],
        "base": [spec.base.z, spec.base.y, spec.base.x],
        "tiles": [tz, ty],
    }


def fill(axis: str) -> dict:
    """In-place halo fill at 256^3 r=3 for one self-wrap axis: x pins the
    edge-lane-tile RMW amplification (any inline-x-halo layout pays
    128-lane writes), y the 8-row-tile RMW windows, z the staged whole
    plane copies."""
    from stencil_tpu.ops.halo_fill import _x_tzb, make_self_fill

    spec = GridSpec(Dim3(256, 256, 256), Dim3(1, 1, 1), Radius.constant(3))
    p = spec.padded()

    def build():
        fn = make_self_fill(spec, axis)
        z = jnp.zeros((p.z, p.y, p.x), jnp.float32)
        return fn, (z,)

    kernels = capture_traffic(build)
    rep = {
        "kernels": [kt.report() for kt in kernels],
        "padded": [p.z, p.y, p.x],
        "radius": 3,
        "offset": [spec.compute_offset().z, spec.compute_offset().y,
                   spec.compute_offset().x],
        "base": [spec.base.z, spec.base.y, spec.base.x],
    }
    if axis == "x":
        rep["tzb"] = _x_tzb(spec)
    return rep


def main(argv) -> int:
    which = argv[1] if len(argv) > 1 else "multistep"
    if which == "multistep":
        rep = multistep(int(argv[2]) if len(argv) > 2 else 4)
    elif which == "substep":
        mode = argv[3] if len(argv) > 3 else "inline"
        if mode not in ("inline", "tight"):
            raise SystemExit(f"unknown substep layout {mode!r} (inline|tight)")
        try:
            n = int(argv[2]) if len(argv) > 2 else 64
        except ValueError:
            raise SystemExit(
                f"substep size must be an integer, got {argv[2]!r} "
                "(usage: substep [n] [inline|tight])"
            )
        rep = substep(n, tight_x=mode == "tight")
    elif which in ("fill-x", "fill-y", "fill-z"):
        rep = fill(which[-1])
    else:
        raise SystemExit(f"unknown target {which!r}")
    print(json.dumps(rep))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
