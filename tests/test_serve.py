"""Always-on serving (stencil_tpu/serve/).

The ISSUE-19 acceptance pins:

- file-drop intake feeds a LIVE queue: jobs dropped while a slot runs
  are admitted and backfilled into freed lanes MID-SLOT — no slot-wide
  barrier (one slot serves them all);
- malformed / duplicate job files never kill the daemon: truncated
  JSON, an unknown workload, and a replayed job id are quarantined to
  ``jobs/bad/`` with a reason file and a schema-valid ``serve.rejected``
  record;
- admission edge cases: quota exhaustion DEFERS (and promotes when the
  tenant's job retires) rather than rejects; priority classes reorder
  only queued jobs, never a running lane; a deadline infeasible against
  the ledger's p99 is rejected AT ADMISSION with the pricing named;
- SLO pressure (online p99 over a running job's deadline) emits a
  first-class ``replan.requested``;
- graceful drain parks live lanes as revivable snapshots, and a
  revived daemon finishes them bit-identical to an uninterrupted serve
  while never re-running retired jobs;
- the status schema's ``queue`` section validates and renders.
"""

from __future__ import annotations

import json
import os

import pytest
import jax

from stencil_tpu.obs import ledger as ledger_mod
from stencil_tpu.obs import telemetry
from stencil_tpu.obs.status import render_status, validate_status
from stencil_tpu.obs.telemetry import validate_record
from stencil_tpu.serve import (
    BucketPricer,
    ServeJob,
    ServeQueue,
    ServeScheduler,
    make_state,
    pick_serve_slot,
    validate_state,
    write_state,
    read_state,
)
from stencil_tpu.serve.admission import LEDGER_METRIC, bucket_label

N = 10
STEPS = 4


def job_doc(jid, *, size=N, steps=STEPS, tenant=None, priority="normal",
            deadline_ms=None, workload="jacobi", seed=None, dtype="float32"):
    doc = {"job": jid, "size": size, "steps": steps, "workload": workload,
           "priority": priority, "dtype": dtype,
           "seed": seed if seed is not None else abs(hash(jid)) % 1000}
    if tenant:
        doc["tenant"] = tenant
    if deadline_ms is not None:
        doc["deadline_ms"] = deadline_ms
    return doc


def drop(serve_dir, doc=None, *, name=None, text=None):
    """The loadgen write contract: tmp + rename into jobs/incoming/."""
    inc = os.path.join(serve_dir, "jobs", "incoming")
    os.makedirs(inc, exist_ok=True)
    name = name or f"{doc['job']}.json"
    tmp = os.path.join(inc, f".tmp-{name}")
    with open(tmp, "w") as f:
        f.write(text if text is not None else json.dumps(doc))
    os.replace(tmp, os.path.join(inc, name))


def sched_for(serve_dir, slot=2, **kw):
    kw.setdefault("devices", jax.devices()[:4])
    kw.setdefault("chunk", 2)
    kw.setdefault("max_idle_s", 0.3)
    kw.setdefault("poll_s", 0.02)
    return ServeScheduler(str(serve_dir), slot, **kw)


def recs_of(path):
    recs = [json.loads(l) for l in open(path) if l.strip()]
    bad = [validate_record(r) for r in recs]
    assert not any(bad), [b for b in bad if b]
    return recs


# -- queue policy (pure units) ------------------------------------------------


def test_queue_orders_priority_deadline_arrival():
    def j(tid, pri, dl, seq):
        return ServeJob(tid, (N, N, N), STEPS, "float32", seed=0,
                        deadline_ms=dl, owner=tid, priority=pri, seq=seq)

    q = ServeQueue()
    for job in (j("low-first", "low", None, 0),
                j("norm-late", "normal", None, 3),
                j("norm-tight", "normal", 1.0, 2),
                j("high", "high", None, 1)):
        q.admit(job)
    # priority class first, then deadline (tightest first), then arrival
    assert [x.tid for x in q] == ["high", "norm-tight", "norm-late",
                                  "low-first"]

    bucket, picked = pick_serve_slot(q, 3)
    assert bucket == ((N, N, N), "float32", "jacobi")
    assert [x.tid for x in picked] == ["high", "norm-tight", "norm-late"]
    assert [x.tid for x in q] == ["low-first"]  # stays live for backfill


def test_state_roundtrip_and_validation(tmp_path):
    doc = make_state()
    doc["jobs"]["j1"] = {"state": "queued", "steps_done": 0, "owner": "a",
                         "priority": "normal", "seq": 0,
                         "spec": job_doc("j1")}
    path = str(tmp_path / "serve-state.json")
    write_state(path, doc)
    back = read_state(path)
    assert back is not None and validate_state(back) == []
    assert back["jobs"]["j1"]["state"] == "queued"

    assert validate_state([]) == ["not an object: list"]
    bad = make_state()
    bad["counters"]["admitted"] = True  # bool is not an int here
    bad["jobs"]["x"] = {"state": "sleeping", "steps_done": 0, "owner": "a",
                        "priority": "normal", "seq": 0, "spec": {}}
    errs = validate_state(bad)
    assert any("counters.admitted" in e for e in errs)
    assert any("'sleeping'" in e for e in errs)


# -- continuous batching: mid-slot admission, no slot-wide barrier ------------


class LateDropScheduler(ServeScheduler):
    """Drops extra job files at the FIRST chunk boundary — the in-process
    stand-in for a producer writing while the slot is mid-flight."""

    def __init__(self, *a, late=(), **kw):
        super().__init__(*a, **kw)
        self._late = list(late)

    def _observe_chunk(self, bucket, per, done_now):
        super()._observe_chunk(bucket, per, done_now)
        while self._late:
            drop(self.serve_dir, self._late.pop())


def test_mid_slot_admission_backfills_without_barrier(tmp_path):
    sdir = str(tmp_path / "s")
    for i in range(2):
        drop(sdir, job_doc(f"early{i}"))
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        out = LateDropScheduler(
            sdir, 2, late=[job_doc("late0"), job_doc("late1")],
            devices=jax.devices()[:4], chunk=2,
            max_idle_s=0.3, poll_s=0.02).serve()
    finally:
        telemetry.get().close()
    # all four retired inside ONE slot: the late pair was admitted while
    # the slot ran and landed in freed lanes — zero slot-wide barriers
    assert out["retired"] == 4 and out["slots"] == 1
    assert out["backfills"] >= 2
    recs = recs_of(m)
    names = [r["name"] for r in recs]
    slot0 = names.index("campaign.slot")
    late_admits = [i for i, r in enumerate(recs)
                   if r["name"] == "serve.admitted"
                   and r["job"].startswith("late")]
    assert late_admits and all(i > slot0 for i in late_admits)
    backfilled = {r["tenant"] for r in recs
                  if r["name"] == "campaign.backfill"}
    assert {"late0", "late1"} <= backfilled
    for jid in ("early0", "early1", "late0", "late1"):
        res = json.load(open(os.path.join(sdir, "results", f"{jid}.json")))
        assert res["outcome"] == "done" and res["steps"] == STEPS


# -- quarantine: malformed and duplicate jobs never kill the daemon -----------


def test_malformed_and_duplicate_jobs_quarantined(tmp_path):
    sdir = str(tmp_path / "s")
    drop(sdir, job_doc("good"))
    drop(sdir, None, name="torn.json", text='{"job": "torn", "size": 8')
    drop(sdir, job_doc("weird", workload="jacobi") | {"workload": "brew"},
         name="weird.json")
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        out = sched_for(sdir).serve()
        # the daemon survived both bad drops and served the good job
        assert out["retired"] == 1 and out["rejected"] == 2

        # replay the RETIRED job id: a revived-or-running daemon must
        # quarantine it as a duplicate, never re-run it
        drop(sdir, job_doc("good"))
        out2 = sched_for(sdir).serve()
        assert out2["retired"] == 0 and out2["rejected"] == 1
        assert out2["revived"] == 0
    finally:
        telemetry.get().close()

    bad_dir = os.path.join(sdir, "jobs", "bad")
    quarantined = sorted(os.listdir(bad_dir))
    reasons = {}
    for n in quarantined:
        if n.endswith(".reason.txt"):
            reasons[n] = open(os.path.join(bad_dir, n)).read()
    assert any("not valid JSON" in v for v in reasons.values())
    assert any("unknown workload 'brew'" in v for v in reasons.values())
    assert any("duplicate job id 'good'" in v for v in reasons.values())

    rejected = [r for r in recs_of(m) if r["name"] == "serve.rejected"]
    assert len(rejected) == 3
    by_job = {r["job"]: r["reason"] for r in rejected}
    assert "not valid JSON" in by_job["torn"]
    assert "unknown workload" in by_job["weird"]
    assert "duplicate" in by_job["good"]


# -- admission edge cases -----------------------------------------------------


def test_quota_exhaustion_defers_then_promotes(tmp_path):
    sdir = str(tmp_path / "s")
    for i in range(3):
        drop(sdir, job_doc(f"q{i}", tenant="alice", steps=3))
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        out = sched_for(sdir, slot=1, quota=1).serve()
    finally:
        telemetry.get().close()
    # over-quota jobs queued (deferred), never rejected — and every one
    # was eventually promoted and served
    assert out["rejected"] == 0
    assert out["retired"] == 3
    assert out["deferred"] == 2
    recs = recs_of(m)
    deferred = [r for r in recs if r["name"] == "serve.deferred"]
    assert {r["job"] for r in deferred} == {"q1", "q2"}
    assert all("quota" in r["reason"] for r in deferred)
    # promotion happens at retirement: each deferred job's (promoted)
    # admission comes after some retirement record
    names = [r["name"] for r in recs]
    first_retire = names.index("serve.retired")
    promoted = [i for i, r in enumerate(recs)
                if r["name"] == "serve.admitted" and r.get("promoted")]
    assert len(promoted) == 2 and all(i > first_retire for i in promoted)


def test_priority_reorders_queued_never_running(tmp_path):
    sdir = str(tmp_path / "s")
    # a low-priority job is already RUNNING when a high-priority one
    # arrives mid-slot: the running lane is never preempted — the high
    # job waits for the lane to free, then backfills
    drop(sdir, job_doc("slowpoke", priority="low", steps=6))
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        out = LateDropScheduler(
            sdir, 1, late=[job_doc("urgent", priority="high", steps=2)],
            devices=jax.devices()[:4], chunk=2,
            max_idle_s=0.3, poll_s=0.02).serve()
    finally:
        telemetry.get().close()
    assert out["retired"] == 2
    recs = recs_of(m)
    retire_order = [r["job"] for r in recs if r["name"] == "serve.retired"]
    # the running low-priority tenant finished first, at its FULL step
    # count — priority reordered only the queue, never the lane
    assert retire_order == ["slowpoke", "urgent"]
    slow = [r for r in recs if r["name"] == "serve.retired"
            and r["job"] == "slowpoke"][0]
    assert slow["steps"] == 6
    assert not any(r["name"] == "serve.parked" for r in recs)


def test_infeasible_deadline_rejected_with_pricing_named(tmp_path):
    sdir = str(tmp_path / "s")
    ledger_path = str(tmp_path / "ledger.jsonl")
    label = bucket_label(((N, N, N), "float32", "jacobi"))
    ledger_mod.append_entries(ledger_path, [ledger_mod.make_entry(
        LEDGER_METRIC, 250.0, label="seed", unit="ms", platform="cpu",
        source="serve", config={"bucket": label},
        detail={"bucket": label, "samples": 64})])

    # the pricer itself: ledger prior until online evidence exists
    pricer = BucketPricer(ledger_path)
    p99, source = pricer.price(((N, N, N), "float32", "jacobi"))
    assert p99 == 250.0 and "ledger" in source and "[seed]" in source

    drop(sdir, job_doc("doomed", deadline_ms=1.0))  # 1 ms vs p99 250 ms
    # 6 steps / chunk 2 = 3 chunks: enough online samples (min 3) for
    # the drain-time ledger writeback asserted below
    drop(sdir, job_doc("fine", deadline_ms=5000.0, steps=6))  # feasible
    drop(sdir, job_doc("nosla", steps=6))           # no deadline at all
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        out = sched_for(sdir, admission_ledger=ledger_path).serve()
    finally:
        telemetry.get().close()
    assert out["rejected"] == 1 and out["retired"] == 2
    rej = [r for r in recs_of(m) if r["name"] == "serve.rejected"]
    assert len(rej) == 1 and rej[0]["job"] == "doomed"
    # the rejection NAMES its price and where it came from
    assert "deadline 1 ms infeasible" in rej[0]["reason"]
    assert "p99 is 250 ms" in rej[0]["reason"]
    assert "ledger" in rej[0]["reason"]
    st = read_state(os.path.join(sdir, "serve-state.json"))
    assert st["jobs"]["doomed"]["state"] == "rejected"
    assert not os.path.exists(os.path.join(sdir, "results", "doomed.json"))
    # drain-time writeback: the daemon's own online p99 joined the ledger
    entries = [e for e in ledger_mod.load_ledger(ledger_path)
               if e["metric"] == LEDGER_METRIC]
    assert any(e["source"] == "serve" and e["label"] != "seed"
               for e in entries)


# -- SLO pressure -> replan.requested -----------------------------------------


def test_slo_pressure_emits_replan_requested(tmp_path):
    sdir = str(tmp_path / "s")
    # unpriceable at admission (no ledger), but the online p99 will dwarf
    # a microsecond deadline within the first slot
    drop(sdir, job_doc("pressed", deadline_ms=0.001, steps=8))
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        out = sched_for(sdir).serve()
    finally:
        telemetry.get().close()
    assert out["retired"] == 1  # pressure reschedules, it never kills
    req = [r for r in recs_of(m) if r["name"] == "replan.requested"]
    assert req, "SLO pressure must fire a first-class replan.requested"
    assert req[0]["reason"] == "slo-pressure"
    assert req[0]["bucket"] == bucket_label(((N, N, N), "float32", "jacobi"))
    assert req[0]["p99_ms"] > 0.001
    assert req[0]["jobs"] == ["pressed"]


# -- graceful drain + revival -------------------------------------------------


class DrainingScheduler(ServeScheduler):
    """Requests a drain at the first chunk boundary — the in-process
    stand-in for SIGTERM arriving mid-slot."""

    def _observe_chunk(self, bucket, per, done_now):
        super()._observe_chunk(bucket, per, done_now)
        self.request_drain("test-sigterm")


def test_drain_parks_and_revival_finishes_bit_identical(tmp_path):
    jobs = [job_doc(f"d{i}", steps=6, seed=40 + i) for i in range(3)]

    ref_dir = str(tmp_path / "ref")
    for d in jobs:
        drop(ref_dir, d)
    ref = sched_for(ref_dir, slot=2, ckpt_every=2).serve()
    assert ref["retired"] == 3

    sdir = str(tmp_path / "s")
    for d in jobs:
        drop(sdir, d)
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        out1 = DrainingScheduler(sdir, 2, devices=jax.devices()[:4],
                                 chunk=2, ckpt_every=2,
                                 max_idle_s=0.3, poll_s=0.02).serve()
        out2 = sched_for(sdir, slot=2, ckpt_every=2).serve()
    finally:
        telemetry.get().close()

    # the drained daemon parked mid-trajectory and persisted the queue
    assert out1["outcome"] == "drained"
    assert out1["retired"] == 0 and out1["queued_remaining"] == 3
    recs = recs_of(m)
    parked = [r for r in recs if r["name"] == "serve.parked"]
    assert parked and all(0 < r["step"] < 6 for r in parked)
    assert any(r["name"] == "serve.drain"
               and r["reason"] == "test-sigterm" for r in recs)

    # the revived daemon owed exactly those jobs and finished them
    # bit-identical to the uninterrupted serve
    assert out2["revived"] == 3 and out2["retired"] == 3
    assert any(r["name"] == "serve.revived" and r["jobs"] == 3
               for r in recs)
    for jid in ("d0", "d1", "d2"):
        a = out2["results"][jid]
        b = ref["results"][jid]
        assert a.outcome == b.outcome == "done"
        assert a.final.tobytes() == b.final.tobytes(), jid
    st = read_state(os.path.join(sdir, "serve-state.json"))
    assert validate_state(st) == []
    assert all(j["state"] == "done" for j in st["jobs"].values())


# -- status schema: the queue section -----------------------------------------


def test_status_queue_section_validates_and_renders(tmp_path):
    sdir = str(tmp_path / "s")
    drop(sdir, job_doc("one"))
    status_path = str(tmp_path / "status.json")
    from stencil_tpu.obs.status import StatusWriter

    out = sched_for(sdir, status=StatusWriter(status_path, app="serve",
                                              run="r1")).serve()
    assert out["retired"] == 1
    doc = json.load(open(status_path))
    assert validate_status(doc) == []
    q = doc["queue"]
    assert q["depth"] == 0 and q["admitted"] == 1
    assert q["rejected"] == 0 and q["backfills"] == 0
    text = render_status(doc)
    assert "queue: depth=0 admitted=1 rejected=0 backfills=0" in text

    # the schema authority rejects a malformed queue section
    doc["queue"]["depth"] = True
    assert any("queue.depth" in e for e in validate_status(doc))
    doc.pop("queue")
    assert validate_status(doc) == []  # queue stays optional (additive)


@pytest.mark.parametrize("bad,msg", [
    ({"job": "a/b", "size": 8, "steps": 1}, "path-safe"),
    ({"job": "a", "size": 0, "steps": 1}, "size"),
    ({"job": "a", "size": 8, "steps": 1, "deadline_ms": -2}, "deadline_ms"),
    ({"job": "a", "size": 8, "steps": 1, "priority": "urgent"}, "priority"),
    ({"job": "a", "size": 8, "steps": 1, "shape": 3}, "unknown fields"),
])
def test_job_schema_rejects(bad, msg):
    from stencil_tpu.serve import validate_job

    errs = validate_job(bad)
    assert errs and any(msg in e for e in errs), errs
