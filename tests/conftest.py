"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU build's analogue of the reference's trick of running 2 MPI
ranks / multiple subdomains per GPU on one node to exercise distributed
paths without a cluster (reference: test/CMakeLists.txt:49,
test/test_exchange.cu:52). ``xla_force_host_platform_device_count=8`` gives
8 virtual devices so 2x2x2 meshes run anywhere.

Must set the env vars before JAX initializes.
"""

import os

os.environ.pop("JAX_PLATFORMS", None)  # the TPU-tunnel env pins this to its plugin
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

# float64 quantities are first-class in the reference (astaroth uses double);
# the env-var spelling of this flag is ignored once the TPU plugin loads, so
# set it through the config API.
jax.config.update("jax_enable_x64", True)

# Initialize the CPU backend eagerly: dryrun_multichip's parent-side probe
# (_live_cpu_device_count) only trusts an ALREADY-initialized CPU backend, so
# without this a standalone test_graft_entry run would fall to the (slower)
# subprocess path.
jax.devices()
