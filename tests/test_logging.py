"""Unit tests for utils/logging.py: level parsing from STENCIL_LOG_LEVEL,
set_level, fatal raising FatalError, and the lazy process-index prefix
never importing jax / initializing a backend."""

import importlib
import os
import subprocess
import sys

import pytest

from stencil_tpu.utils import logging as slog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _reload():
    importlib.reload(slog)


def test_level_parsed_from_env(monkeypatch):
    monkeypatch.setenv("STENCIL_LOG_LEVEL", "DEBUG")
    _reload()
    assert slog.get_level() == slog.DEBUG
    monkeypatch.setenv("STENCIL_LOG_LEVEL", "error")  # case-insensitive
    _reload()
    assert slog.get_level() == slog.ERROR
    monkeypatch.setenv("STENCIL_LOG_LEVEL", "bogus")  # unknown -> INFO
    _reload()
    assert slog.get_level() == slog.INFO
    monkeypatch.delenv("STENCIL_LOG_LEVEL")
    _reload()
    assert slog.get_level() == slog.INFO


def test_set_level_string_and_int_gate_emission(capfd):
    slog.set_level("ERROR")
    try:
        slog.info("hidden-line")
        slog.error("shown-line")
        err = capfd.readouterr().err
        assert "hidden-line" not in err
        assert "shown-line" in err and "[ERROR]" in err
        slog.set_level(slog.DEBUG)
        slog.debug("debug-line")
        assert "debug-line" in capfd.readouterr().err
    finally:
        slog.set_level(slog.INFO)


def test_fatal_raises_fatal_error_and_logs(capfd):
    with pytest.raises(slog.FatalError, match="doom"):
        slog.fatal("doom")
    err = capfd.readouterr().err
    assert "[FATAL]" in err and "doom" in err


def test_prefix_carries_process_index(capfd):
    # conftest initialized the single-process CPU backend: the lazy prefix
    # must resolve to p0 once jax is importable
    slog.set_level("INFO")
    slog.info("hello-prefix")
    assert "p0: hello-prefix" in capfd.readouterr().err


def test_lazy_prefix_never_imports_jax():
    """Loading utils/logging.py standalone and logging a line must neither
    import jax nor initialize a backend (the first log line pinning the
    platform was the failure mode the lazy prefix exists to avoid)."""
    path = os.path.join(REPO, "stencil_tpu", "utils", "logging.py")
    code = (
        "import importlib.util, sys\n"
        f"spec = importlib.util.spec_from_file_location('slog', {path!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "sys.modules['slog'] = m\n"
        "spec.loader.exec_module(m)\n"
        "m.info('standalone-line')\n"
        "assert 'jax' not in sys.modules, 'logging pulled in jax'\n"
        "print('LAZY_OK')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "LAZY_OK" in proc.stdout
    # the line itself went out, with the p0 default prefix
    assert "p0: standalone-line" in proc.stderr
