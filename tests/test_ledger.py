"""Performance-ledger tests: schema round-trip, atomic append + dedup,
corruption rejection, the three ingest shapes (bench payload, committed
legacy BENCH/MULTICHIP docs, metrics-JSONL gauge trimeans), and the
bench.py parent hook (STENCIL_BENCH_LEDGER) through the same file-path
loading the parent uses."""

import io
import json
import math
import os

import pytest

from stencil_tpu.obs import ledger, telemetry
from stencil_tpu.utils.statistics import Statistics


def _entry(metric="leg", value=1.0, label="r01", **kw):
    kw.setdefault("platform", "cpu")
    kw.setdefault("config", {"size": 24})
    return ledger.make_entry(metric, value, label=label, **kw)


# -- schema + file round-trip -------------------------------------------------


def test_round_trip_and_dedup(tmp_path):
    path = str(tmp_path / "L.jsonl")
    assert ledger.load_ledger(path) == []  # missing file is an empty ledger
    e1 = _entry(value=10.0, label="r01")
    e2 = _entry(value=12.0, label="r02")
    assert ledger.append_entries(path, [e1, e2]) == 2
    back = ledger.load_ledger(path)
    assert [b["value"] for b in back] == [10.0, 12.0]
    assert all(ledger.validate_entry(b) == [] for b in back)
    # idempotent: same keys (metric/platform/config/rev/label) are skipped
    assert ledger.append_entries(path, [_entry(value=99.0, label="r01")]) == 0
    assert [b["value"] for b in ledger.load_ledger(path)] == [10.0, 12.0]
    # a NEW label appends without rewriting history lines
    assert ledger.append_entries(path, [_entry(value=14.0, label="r03")]) == 1
    assert len(ledger.load_ledger(path)) == 3


def test_validate_entry_catches_violations():
    ok = _entry()
    assert ledger.validate_entry(ok) == []
    assert ledger.validate_entry("not a dict")
    assert ledger.validate_entry({})
    assert ledger.validate_entry(dict(ok, value="fast"))
    assert ledger.validate_entry(dict(ok, value=float("nan")))
    assert ledger.validate_entry(dict(ok, metric=""))
    assert ledger.validate_entry(dict(ok, source="wishful"))
    assert ledger.validate_entry(dict(ok, kind="plan-db"))
    # future schema refused outright (a downgrade must not reinterpret)
    errs = ledger.validate_entry(dict(ok, v=ledger.SCHEMA_VERSION + 1))
    assert errs and "newer" in errs[0]


def test_corruption_rejected_not_clobbered(tmp_path):
    path = str(tmp_path / "L.jsonl")
    ledger.append_entries(path, [_entry()])
    with open(path, "a") as f:
        f.write("{torn line\n")
    with pytest.raises(ledger.LedgerError, match="unparseable"):
        ledger.load_ledger(path)
    # appending to a corrupt ledger must raise, and the file must be
    # byte-identical afterwards (never silently rewritten/shrunk)
    before = open(path).read()
    with pytest.raises(ledger.LedgerError):
        ledger.append_entries(path, [_entry(label="r09")])
    assert open(path).read() == before


def test_invalid_entry_refused_on_append(tmp_path):
    path = str(tmp_path / "L.jsonl")
    bad = _entry()
    bad["value"] = float("inf")
    with pytest.raises(ledger.LedgerError, match="refusing"):
        ledger.append_entries(path, [bad])
    assert not os.path.exists(path)


def test_config_fingerprint_ignores_volatile_keys():
    a = ledger.config_fingerprint({"x": 24, "metrics_out": "/tmp/a.jsonl",
                                   "inject": "slow@3", "run_id": "r1"})
    b = ledger.config_fingerprint({"x": 24, "metrics_out": "/tmp/b.jsonl",
                                   "run_id": "r2"})
    c = ledger.config_fingerprint({"x": 32})
    assert a == b != c
    # key order and None values do not matter
    assert ledger.config_fingerprint({"a": 1, "b": None}) == \
        ledger.config_fingerprint({"b": None, "a": 1}) == \
        ledger.config_fingerprint({"a": 1})


def test_trimean_and_mad_match_statistics():
    vals = [3.0, 1.0, 4.0, 1.5, 9.0, 2.6, 5.3]
    assert ledger.trimean(vals) == pytest.approx(Statistics(vals).trimean())
    assert ledger.mad([1.0, 1.0, 1.0]) == 0.0
    assert ledger.mad([1.0, 2.0, 9.0]) == 1.0
    with pytest.raises(ValueError):
        ledger.trimean([])


# -- ingest shapes ------------------------------------------------------------


def test_entries_from_bench_payload():
    payload = {
        "metric": "jacobi3d_512_mcells_per_s_per_chip",
        "value": 83059.7, "unit": "Mcells/s", "vs_baseline": 24.467,
        "detail": {
            "iter_trimean_s": 0.001616, "exchange_gb_per_s_r3_4q": 15.92,
            "astaroth_256_iter_ms": None,  # absent leg: no entry, not 0
            "plan_choice": "2x2x2",        # string: not a measurement
            "leg_errors": {"x": "boom"},   # diagnostics: skipped
            "platform": "tpu", "size": 512,
        },
    }
    es = ledger.entries_from_bench_payload(payload, label="r05", rev="abc123")
    by = {e["metric"]: e for e in es}
    assert by["jacobi3d_512_mcells_per_s_per_chip"]["value"] == 83059.7
    assert by["jacobi3d_512_mcells_per_s_per_chip"]["unit"] == "Mcells/s"
    assert by["jacobi3d_512_mcells_per_s_per_chip.vs_baseline"]["value"] == \
        pytest.approx(24.467)
    assert by["exchange_gb_per_s_r3_4q"]["value"] == pytest.approx(15.92)
    assert "astaroth_256_iter_ms" not in by
    assert "plan_choice" not in by and "leg_errors" not in by
    assert all(e["platform"] == "tpu" and e["label"] == "r05"
               and e["rev"] == "abc123" for e in es)
    # same payload -> same config fingerprint across entries
    assert len({e["config"] for e in es}) == 1


def test_entries_from_legacy_bench_failed_round():
    # BENCH_r03-shaped: rc=1, no parsed payload — the outage still lands
    # as a bench.rc entry so the trend shows the round
    doc = {"n": 3, "cmd": "python bench.py", "rc": 1, "tail": "Traceback..."}
    es = ledger.entries_from_legacy_bench(doc)
    assert len(es) == 1
    assert es[0]["metric"] == "bench.rc" and es[0]["value"] == 1.0
    assert es[0]["label"] == "r03" and es[0]["source"] == "legacy-bench"


def test_entries_from_legacy_multichip():
    doc = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False, "tail": ""}
    es = ledger.entries_from_legacy_multichip(doc, label="r04")
    assert es[0]["metric"] == "multichip_dryrun_ok" and es[0]["value"] == 1.0
    assert es[0]["detail"]["rc"] == 0


def test_entries_from_metrics_records_gauge_trimeans():
    buf = io.StringIO()
    rec = telemetry.Recorder(sink=buf, app="t", run_id="RUN1")
    rec.meta("config", config={"x": 24, "metrics_out": "/tmp/m.jsonl"})
    for v in (1.0, 2.0, 9.0):
        rec.gauge("leg.speed", v, unit="GB/s")
    rec.gauge("leg.speed", 5.0, method="direct26")  # tag splits the key
    rec.gauge("bad.inf", float("inf"))              # non-finite: skipped
    with rec.span("work", phase="step"):
        pass
    records = [json.loads(l) for l in buf.getvalue().splitlines()]
    es = ledger.entries_from_metrics_records(records, label="run1",
                                             platform="cpu")
    by = {e["metric"]: e for e in es}
    assert by["leg.speed"]["value"] == pytest.approx(
        Statistics([1.0, 2.0, 9.0]).trimean())
    assert by["leg.speed"]["unit"] == "GB/s"
    assert by["leg.speed"]["detail"]["samples"] == 3
    assert by["leg.speed[direct26]"]["value"] == 5.0
    assert "bad.inf" not in by
    assert "work.trimean_s" not in by  # spans only with spans=True
    assert all(e["run"] == "RUN1" and e["label"] == "run1" for e in es)
    # the volatile metrics_out key must not split the config fingerprint
    es2 = ledger.entries_from_metrics_records(
        [dict(r, **({"config": {"x": 24, "metrics_out": "/ELSEWHERE"}}
                    if r.get("name") == "config" else {}))
         for r in records], label="run2", platform="cpu")
    assert es2[0]["config"] == es[0]["config"]
    # spans=True ingests per-span trimeans under <name>.trimean_s
    es3 = ledger.entries_from_metrics_records(records, label="run1",
                                              spans=True)
    assert any(e["metric"] == "work.trimean_s" for e in es3)


# -- the bench.py parent hook -------------------------------------------------


def test_bench_parent_ledger_hook(tmp_path, monkeypatch):
    """The parent-side append: loaded by file path (never importing the
    package), labeled from STENCIL_BENCH_LABEL, best-effort on failure."""
    import importlib.util
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(repo, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    _sys.modules["bench_under_test"] = bench
    spec.loader.exec_module(bench)

    path = str(tmp_path / "L.jsonl")
    payload = {"metric": "m", "value": 2.0, "unit": "u", "vs_baseline": 1.1,
               "detail": {"platform": "cpu", "size": 128, "leg_s": 0.5}}
    monkeypatch.setenv("STENCIL_BENCH_LEDGER", path)
    monkeypatch.setenv("STENCIL_BENCH_LABEL", "r99")
    bench._append_ledger(payload)
    es = ledger.load_ledger(path)
    assert {e["metric"] for e in es} == {"m", "m.vs_baseline", "leg_s"}
    assert all(e["label"] == "r99" and e["source"] == "bench" for e in es)
    # unset -> no-op; corrupt ledger -> warn, never raise
    monkeypatch.delenv("STENCIL_BENCH_LEDGER")
    bench._append_ledger(payload)
    monkeypatch.setenv("STENCIL_BENCH_LEDGER", path)
    with open(path, "a") as f:
        f.write("garbage\n")
    bench._append_ledger(payload)  # must not raise (rc=0 contract)


def test_git_rev_best_effort(tmp_path):
    # inside this repo: a short rev (or None if git is unavailable);
    # outside: None — never an exception
    assert ledger.git_rev(str(tmp_path)) is None
    rev = ledger.git_rev(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    assert rev is None or (isinstance(rev, str) and len(rev) >= 7)


def test_concurrent_appends_serialize_under_the_lock(tmp_path):
    """Two processes appending disjoint entries must both land: the
    flock around the read-modify-write forbids the lost-update rewrite
    of 'append-only' history."""
    import subprocess
    import sys

    path = str(tmp_path / "L.jsonl")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = (
        "import sys; sys.path.insert(0, {repo!r})\n"
        "from stencil_tpu.obs import ledger\n"
        "es = [ledger.make_entry(f'leg{{i}}', float(i), label=sys.argv[2],\n"
        "                        platform='cpu', config={{'c': 1}})\n"
        "      for i in range(20)]\n"
        "ledger.append_entries(sys.argv[1], es)\n"
    ).format(repo=repo)
    procs = [subprocess.Popen([sys.executable, "-c", prog, path, lbl])
             for lbl in ("a", "b", "c")]
    assert all(p.wait() == 0 for p in procs)
    es = ledger.load_ledger(path)
    assert len(es) == 60  # 3 labels x 20 legs, nothing lost
    assert {e["label"] for e in es} == {"a", "b", "c"}


def test_metrics_ingest_drops_nonfinite_samples():
    """One NaN gauge sample must not poison the trimean of the good
    samples (NaN breaks sorted(), yielding a silently WRONG finite
    value, not NaN) — non-finite samples are dropped at collection like
    the bench-payload path does."""
    base = {"v": 1, "run": "R", "proc": 0, "t": 0.0}
    recs = [dict(base, kind="gauge", name="g", value=v)
            for v in (float("nan"), 1.0, 2.0, 3.0, 4.0, 5.0)]
    recs.append(dict(base, kind="span", name="s", seconds=float("inf")))
    recs.append(dict(base, kind="span", name="s", seconds=2.0))
    es = ledger.entries_from_metrics_records(recs, label="L", spans=True)
    by = {e["metric"]: e for e in es}
    assert by["g"]["value"] == 3.0  # true trimean of 1..5, NaN dropped
    assert by["g"]["detail"]["samples"] == 5
    assert by["s.trimean_s"]["value"] == 2.0
    # a gauge with ONLY non-finite samples produces no entry at all
    only_bad = [dict(base, kind="gauge", name="bad", value=float("nan"))]
    assert ledger.entries_from_metrics_records(only_bad, label="L") == []
