"""Plan DB — round-trip, corruption rejection, stale-schema migration.

The DB is the production artifact (tuned plans replayed with zero
probes), so its failure modes must be LOUD: a corrupt or
future-versioned file raises PlanDBError instead of silently emptying,
the known v0 legacy layout migrates forward, and writes are atomic
(tmp + rename — no torn DB on a crash). No jax anywhere in this file.
"""

import json
import os

import pytest

from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.plan import db as plandb
from stencil_tpu.plan.ir import PlanChoice, PlanConfig


def _config(q=4, grid=(64, 64, 64), platform="cpu"):
    return PlanConfig.make(Dim3.of(grid), Radius.constant(2),
                           ["float32"] * q, 8, platform)


def _choice():
    return PlanChoice(partition=(2, 2, 2), method="axis-composed")


def test_roundtrip(tmp_path):
    path = str(tmp_path / "plans.json")
    db = plandb.empty_db()
    cfg = _config()
    entry = plandb.make_entry(cfg, _choice(), "probe", measured_s=0.0262,
                              probes=[{"label": "x", "trimean_s": 0.03}])
    plandb.record(db, entry)
    plandb.save_db(path, db)
    assert not [e for e in os.listdir(tmp_path) if e.startswith(".tmp-")]
    loaded = plandb.load_db(path)
    got = plandb.lookup(loaded, cfg)
    assert got is not None
    assert PlanChoice.from_json(got["choice"]) == _choice()
    assert got["measured_s"] == pytest.approx(0.0262)
    # a permuted-dtype config resolves to the same entry (multiset key)
    assert plandb.lookup(loaded, _config()) is got


def test_missing_file_is_empty():
    db = plandb.load_db("/nonexistent/plans.json")
    assert db == plandb.empty_db()


def test_corruption_rejected(tmp_path):
    path = str(tmp_path / "plans.json")
    plandb.save_db(path, plandb.empty_db())
    with open(path, "r+") as f:
        f.truncate(10)  # torn JSON
    with pytest.raises(plandb.PlanDBError, match="unreadable"):
        plandb.load_db(path)


def test_wrong_kind_rejected(tmp_path):
    path = str(tmp_path / "plans.json")
    with open(path, "w") as f:
        json.dump({"v": 1, "kind": "not-a-plan-db", "entries": {}}, f)
    with pytest.raises(plandb.PlanDBError):
        plandb.load_db(path)


def test_future_version_rejected(tmp_path):
    path = str(tmp_path / "plans.json")
    with open(path, "w") as f:
        json.dump({"v": 99, "kind": plandb.DB_KIND, "entries": {}}, f)
    with pytest.raises(plandb.PlanDBError, match="newer"):
        plandb.load_db(path)


def test_tampered_entry_rejected(tmp_path):
    path = str(tmp_path / "plans.json")
    db = plandb.empty_db()
    plandb.record(db, plandb.make_entry(_config(), _choice(), "probe"))
    plandb.save_db(path, db)
    raw = json.load(open(path))
    key = next(iter(raw["entries"]))
    raw["entries"][key]["choice"]["method"] = "warp-drive"
    with open(path, "w") as f:
        json.dump(raw, f)
    with pytest.raises(plandb.PlanDBError, match="method"):
        plandb.load_db(path)


def test_entry_key_mismatch_rejected(tmp_path):
    path = str(tmp_path / "plans.json")
    db = plandb.empty_db()
    plandb.record(db, plandb.make_entry(_config(), _choice(), "probe"))
    plandb.save_db(path, db)
    raw = json.load(open(path))
    key = next(iter(raw["entries"]))
    raw["entries"]["{}"] = raw["entries"].pop(key)  # moved under a bogus key
    with open(path, "w") as f:
        json.dump(raw, f)
    with pytest.raises(plandb.PlanDBError):
        plandb.load_db(path)


def test_v0_flat_layout_migrates(tmp_path):
    # the pre-schema prototype: a flat {config-key: choice-json} mapping
    path = str(tmp_path / "plans.json")
    cfg = _config()
    with open(path, "w") as f:
        json.dump({cfg.key(): _choice().to_json()}, f)
    db = plandb.load_db(path)
    assert db["v"] == plandb.DB_VERSION
    entry = plandb.lookup(db, cfg)
    assert entry is not None and entry["source"] == "legacy"
    assert PlanChoice.from_json(entry["choice"]) == _choice()
    # migrated DBs re-save as v1 and reload cleanly
    plandb.save_db(path, db)
    assert plandb.load_db(path)["v"] == plandb.DB_VERSION


def test_v0_garbage_rejected(tmp_path):
    path = str(tmp_path / "plans.json")
    with open(path, "w") as f:
        json.dump({"some": "junk"}, f)
    with pytest.raises(plandb.PlanDBError):
        plandb.load_db(path)


def test_save_refuses_invalid():
    with pytest.raises(plandb.PlanDBError, match="refusing"):
        plandb.save_db("/tmp/never-written.json",
                       {"v": 1, "kind": "nope", "entries": {}})


def test_prune_filters_and_guard(tmp_path):
    db = plandb.empty_db()
    plandb.record(db, plandb.make_entry(_config(q=1), _choice(), "seed"))
    plandb.record(db, plandb.make_entry(_config(q=2), _choice(), "probe"))
    plandb.record(db, plandb.make_entry(
        _config(q=2, platform="tpu"), _choice(), "probe"))
    with pytest.raises(ValueError, match="filter"):
        plandb.prune_db(db)
    assert plandb.prune_db(db, source="seed") == 1
    assert plandb.prune_db(db, platform="tpu") == 1
    assert len(db["entries"]) == 1
    assert plandb.prune_db(db, older_than_s=3600.0) == 0  # all fresh
