"""Run-status snapshots (obs/status.py), the report --status reader,
the watchdog heartbeat JSON payload, perf_tool trend --json, and the
campaign driver's deadline/SLO tracking."""

import io
import json
import os
import sys
import textwrap
import time

import pytest

from stencil_tpu.obs import telemetry, watchdog
from stencil_tpu.obs.status import (
    StatusWriter,
    read_status,
    render_status,
    validate_status,
    write_status,
)

PY = sys.executable


# -- the atomic snapshot file -------------------------------------------------


def test_status_round_trip_and_validation(tmp_path):
    path = str(tmp_path / "status.json")
    w = StatusWriter(path, app="jacobi3d", run="r-1")
    doc = w.update(step=4, iters=10, per_step_s=0.01,
                   health={"checks": 2, "faults": 0, "rollbacks": 0},
                   anomalies={"active": [], "detected": 0, "cleared": 0})
    assert validate_status(doc) == []
    got = read_status(path)
    assert got["step"] == 4 and got["iters"] == 10
    assert validate_status(got) == []
    # updates MERGE: a later partial update keeps earlier sections
    w.update(step=6)
    got = read_status(path)
    assert got["step"] == 6 and got["health"]["checks"] == 2


def test_status_write_is_atomic_no_tmp_left_behind(tmp_path):
    path = str(tmp_path / "s.json")
    for i in range(5):
        write_status(path, {"v": 1, "kind": "run-status", "t": time.time(),
                            "step": i})
    assert read_status(path)["step"] == 4
    leftovers = [f for f in os.listdir(tmp_path) if f.startswith(".tmp-")]
    assert leftovers == []


def test_read_status_tolerates_missing_and_garbage(tmp_path):
    assert read_status(str(tmp_path / "nope.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{torn")
    assert read_status(str(bad)) is None
    notdict = tmp_path / "list.json"
    notdict.write_text("[1, 2]")
    assert read_status(str(notdict)) is None


def test_validate_status_catalogue():
    base = {"v": 1, "kind": "run-status", "t": 0.0}
    assert validate_status(base) == []
    assert validate_status("x")
    assert validate_status({**base, "v": 2})
    assert validate_status({**base, "kind": "other"})
    assert validate_status({**base, "step": "four"})
    assert validate_status({**base, "health": {"checks": 1}})  # missing keys
    assert validate_status({**base, "anomalies": {"active": {}}})
    assert validate_status({**base, "lanes": [{"tenant": "t0"}]})  # no lane
    assert validate_status(
        {**base, "lanes": [{"lane": 0, "slo": "maybe"}]})
    assert validate_status({**base, "slo": {"violations": "t1"}})
    ok = {**base, "step": 3, "iters": 9, "per_step_s": 0.1,
          "health": {"checks": 1, "faults": 0, "rollbacks": 0},
          "anomalies": {"active": [{"metric": "k", "step": 2}],
                        "detected": 1, "cleared": 0},
          "lanes": [{"lane": 0, "tenant": "t0", "slo": "ok"},
                    {"lane": 1, "tenant": None, "slo": None}],
          "slo": {"violations": ["t1"]}}
    assert validate_status(ok) == []


def test_render_status_reads_like_top():
    doc = {"v": 1, "kind": "run-status", "run": "r-9", "app": "jacobi3d",
           "t": time.time(), "step": 412, "iters": 1000,
           "per_step_s": 0.0123, "outcome": None,
           "health": {"checks": 12, "faults": 1, "rollbacks": 1},
           "anomalies": {"active": [
               {"metric": "step.latency_s", "step": 400, "value": 8.0,
                "lo": 0.0, "hi": 0.3, "direction": "lower"}],
               "detected": 1, "cleared": 0},
           "lanes": [{"lane": 0, "tenant": "t0", "step": 4, "steps": 8,
                      "p50_ms": 3.0, "p99_ms": 165.0, "deadline_ms": 0.5,
                      "slo": "violated"},
                     {"lane": 1, "tenant": None}],
           "slo": {"violations": ["t0"]}}
    text = render_status(doc)
    assert "step 412/1000 (41%)" in text
    assert "ANOMALY step.latency_s since step 400" in text
    assert "SLO violations: t0" in text
    assert "violated" in text and "(dead)" in text
    assert "faults=1" in text and "1 active" in text


def test_report_status_cli_once_and_follow(tmp_path, capsys):
    from stencil_tpu.apps import report

    path = str(tmp_path / "status.json")
    # missing snapshot: one-shot mode says waiting, exits 1
    assert report.main(["--status", path]) == 1
    assert "waiting for a status snapshot" in capsys.readouterr().out
    StatusWriter(path, app="jacobi3d", run="r-1").update(step=2, iters=4)
    assert report.main(["--status", path]) == 0
    assert "step 2/4" in capsys.readouterr().out
    # follow mode redraws (bounded by --follow-count)
    assert report.main(["--status", path, "--follow", "--follow-count", "2",
                        "--interval", "0.01"]) == 0
    out = capsys.readouterr().out
    assert out.count("-- status #") == 2


def test_report_without_paths_or_status_errors():
    from stencil_tpu.apps import report

    with pytest.raises(SystemExit) as e:
        report.main([])
    assert e.value.code == 2


# -- watchdog heartbeat payload -----------------------------------------------


def test_heartbeat_payload_carries_step_and_span(tmp_path, monkeypatch):
    hb = str(tmp_path / "beat")
    monkeypatch.setenv(watchdog.HEARTBEAT_FILE_ENV, hb)
    rec = telemetry.Recorder(sink=None)
    rec.note_step(412)
    with rec.span("exchange"):
        rec.heartbeat()
        note = watchdog.read_heartbeat_note(hb)
        assert note["step"] == 412 and note["span"] == "exchange"
        assert isinstance(note["t"], float)
    rec.heartbeat()  # span closed: payload drops the span name
    note = watchdog.read_heartbeat_note(hb)
    assert note["step"] == 412 and "span" not in note
    assert watchdog.format_heartbeat_note(note) == "at step 412"
    assert watchdog.format_heartbeat_note(
        {"step": 3, "span": "exchange"}) == "at step 3 in exchange"
    assert watchdog.format_heartbeat_note(None) == ""


def test_heartbeat_mtime_contract_survives_plain_touch(tmp_path):
    # the PURE-STDLIB contract: a beat body that is not JSON is still a
    # beat (liveness is mtime-only); the note reader just returns None
    hb = tmp_path / "beat"
    hb.write_text(str(time.time()))
    assert watchdog.read_heartbeat_note(str(hb)) is None


def test_supervise_stall_report_quotes_the_payload(tmp_path, capfd):
    """The satellite's acceptance line: "stalled at step 412 in
    exchange", not a bare stale-mtime age."""
    child = textwrap.dedent(
        """
        import json, os, time
        hb = os.environ["STENCIL_HEARTBEAT_FILE"]
        with open(hb, "w") as f:
            json.dump({"t": time.time(), "step": 412, "span": "exchange"}, f)
        time.sleep(300)
        """
    )
    att = watchdog.supervise(
        [PY, "-c", child], timeout_s=120, heartbeat_timeout_s=1.5,
        first_beat_grace_s=60, poll_s=0.1, name="stall-note")
    assert att.outcome == watchdog.STALL
    assert att.heartbeat_note == {"t": pytest.approx(
        att.heartbeat_note["t"]), "step": 412, "span": "exchange"}
    err = capfd.readouterr().err
    assert "stalled at step 412 in exchange" in err


# -- perf_tool trend --json ---------------------------------------------------


def _seed_ledger(path):
    from stencil_tpu.obs import ledger

    entries = [
        ledger.make_entry("leg_a_s", v, label=f"r{i + 1:02d}", unit="s",
                          platform="cpu", config="cfg0", source="manual",
                          t=1000.0 + i)
        for i, v in enumerate([1.0, 1.1, 0.9, 5.0])
    ]
    ledger.append_entries(path, entries)
    return entries


def test_trend_json_trajectory_and_verdicts(tmp_path, capsys):
    from stencil_tpu.apps import perf_tool

    path = str(tmp_path / "ledger.jsonl")
    _seed_ledger(path)
    out_file = str(tmp_path / "trend.json")
    rc = perf_tool.main(["trend", "--ledger", path, "--json",
                         "--out", out_file])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc == json.load(open(out_file))
    assert doc["kind"] == "perf-trend" and doc["v"] == 1
    (leg,) = doc["legs"]
    assert leg["metric"] == "leg_a_s" and leg["platform"] == "cpu"
    labels = [pt["label"] for pt in leg["points"]]
    assert labels == ["r01", "r02", "r03", "r04"]
    assert leg["points"][0]["vs_prev"] is None
    assert leg["points"][1]["vs_prev"] == pytest.approx(1.1)
    # the newest label (r04: 5.0 s on a seconds leg) trips the verdict
    assert leg["verdict"]["status"] == "fail"
    assert leg["verdict"]["label"] == "r04"


def test_trend_json_is_machine_parseable_with_filters(tmp_path, capsys):
    from stencil_tpu.apps import perf_tool

    path = str(tmp_path / "ledger.jsonl")
    _seed_ledger(path)
    rc = perf_tool.main(["trend", "--ledger", path, "--json",
                         "--metric", "no_such_leg"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["legs"] == []


# -- campaign deadlines / SLO -------------------------------------------------


def test_parse_deadlines_grammar():
    from stencil_tpu.apps.campaign import parse_deadlines

    assert parse_deadlines("") == {}
    assert parse_deadlines("50") == {"*": 50.0}
    assert parse_deadlines("t1=0.5,t3=100") == {"t1": 0.5, "t3": 100.0}
    assert parse_deadlines("*=10,t1=0.5") == {"*": 10.0, "t1": 0.5}
    with pytest.raises(ValueError):
        parse_deadlines("t1=fast")
    # nan/inf/zero parse as floats but can never be judged (p99 > nan is
    # always False) — rejected loudly instead of running un-judged
    for bad in ("t1=nan", "t1=inf", "0", "t1=-5"):
        with pytest.raises(ValueError):
            parse_deadlines(bad)


def test_campaign_cli_rejects_unjudgeable_configs():
    from stencil_tpu.apps import campaign

    # a mistyped tenant id must not run the campaign un-judged
    with pytest.raises(SystemExit) as e:
        campaign.main(["--tenants", "4", "--deadline-ms", "t9=5"])
    assert e.value.code == 2
    # the live layer rides the guarded batched driver: sequential mode
    # would silently observe nothing
    with pytest.raises(SystemExit) as e:
        campaign.main(["--mode", "sequential", "--live-sentinel"])
    assert e.value.code == 2


def test_campaign_sequential_ignores_env_status_file(tmp_path, capsys):
    """--status-file may come from the globally-exported
    STENCIL_STATUS_FILE the user never typed: sequential mode must warn
    and ignore it, not break every invocation in that environment."""
    from stencil_tpu.apps import campaign

    status = tmp_path / "status.json"
    rc = campaign.main(["--mode", "sequential", "--tenants", "1",
                        "--size", "8", "--steps", "2",
                        "--status-file", str(status)])
    assert rc == 0
    assert not status.exists()  # ignored, loudly (log.warn), not half-used


def test_live_config_errors_are_clean(tmp_path):
    from stencil_tpu.apps import jacobi3d
    from stencil_tpu.apps._bench_common import load_live_config

    assert load_live_config("") == {}
    assert load_live_config('{"*": {"rel_tol": 1.0}}') == {
        "*": {"rel_tol": 1.0}}
    cfg = tmp_path / "live.json"
    cfg.write_text('{"step.latency_s": {"mad_k": 5}}')
    assert load_live_config(str(cfg)) == {"step.latency_s": {"mad_k": 5}}
    with pytest.raises(ValueError):
        load_live_config("[1]")
    # a mistyped path/JSON is an argparse error at the CLI, not a
    # traceback after backend init
    with pytest.raises(SystemExit) as e:
        jacobi3d.main(["--live-sentinel", "--live-config", "no-such.json"])
    assert e.value.code == 2


def test_status_set_stages_without_flushing(tmp_path):
    path = str(tmp_path / "s.json")
    w = StatusWriter(path, app="campaign", run="r-1")
    w.set(lanes=[{"lane": 0, "tenant": "t0"}])
    assert not os.path.exists(path)  # staged only — no write yet
    w.update(step=3)
    got = read_status(path)
    # the staged section rode the one atomic write
    assert got["step"] == 3 and got["lanes"][0]["tenant"] == "t0"


def test_campaign_driver_slo_violation_and_lanes(tmp_path):
    """A deadline-doomed tenant emits exactly one slo.violation while
    its slot siblings stay clean, and the status lanes carry the online
    p50/p99 + verdict."""
    from stencil_tpu.campaign import CampaignDriver, TenantJob

    sink = io.StringIO()
    rec = telemetry.Recorder(sink=sink)
    old = telemetry._recorder
    telemetry._recorder = rec
    try:
        jobs = [
            TenantJob("t0", (8, 8, 8), 8, seed=1),
            TenantJob("t1", (8, 8, 8), 8, seed=2, deadline_ms=1e-4),
            TenantJob("t2", (8, 8, 8), 8, seed=3, deadline_ms=1e9),
        ]
        status = StatusWriter(str(tmp_path / "status.json"), app="campaign",
                              run=rec.run_id)
        drv = CampaignDriver(jobs, 4, str(tmp_path / "c"), chunk=2,
                             status=status, slo_min_samples=2)
        summary = drv.run()
        assert summary["slo_violations"] == ["t1"]
        recs = [json.loads(line) for line in sink.getvalue().splitlines()]
        viol = [r for r in recs if r["name"] == "slo.violation"]
        assert len(viol) == 1  # latched: one evidence record, not a siren
        v = viol[0]
        assert telemetry.validate_record(v) == []
        assert v["tenant"] == "t1" and v["p99_ms"] > v["deadline_ms"]
        doc = read_status(str(tmp_path / "status.json"))
        assert validate_status(doc) == []
        by_tenant = {ln["tenant"]: ln for ln in doc["lanes"]}
        assert by_tenant["t1"]["slo"] == "violated"
        assert by_tenant["t2"]["slo"] == "ok"       # generous deadline holds
        assert by_tenant["t0"]["slo"] is None       # no deadline, no verdict
        assert by_tenant["t1"]["p99_ms"] > 0
        assert doc["slo"] == {"violations": ["t1"]}
        # every tenant still completes (an SLO breach is evidence, not
        # an eviction)
        assert sorted(summary["results"]) == ["t0", "t1", "t2"]
        assert all(r.outcome == "done" for r in summary["results"].values())
    finally:
        telemetry._recorder = old


def test_deadline_never_joins_the_bucket():
    from stencil_tpu.campaign import TenantJob

    a = TenantJob("a", (8, 8, 8), 4, deadline_ms=1.0)
    b = TenantJob("b", (8, 8, 8), 4, deadline_ms=None)
    assert a.bucket() == b.bucket()
