"""jacobi3d correctness: distributed overlap step vs numpy periodic
reference (BASELINE.json config 1 idiom: vs CPU reference)."""

import jax
import numpy as np
import pytest

from stencil_tpu.apps.jacobi3d import run, weak_scale, csv_row
from stencil_tpu.geometry import Dim3
from stencil_tpu.ops.jacobi import INIT_TEMP, jacobi_reference, sphere_masks
from stencil_tpu.parallel import Method


def test_weak_scale_matches_reference_rule():
    # prime factors of 8 = [2,2,2] multiplied into smallest axis each time
    assert weak_scale(4, 4, 4, 8) == Dim3(8, 8, 8)
    assert weak_scale(2, 3, 5, 6) == Dim3(6, 6, 5)  # pf [3,2]: x*3=6 then y*2=6
    assert weak_scale(5, 5, 5, 1) == Dim3(5, 5, 5)


@pytest.mark.parametrize("overlap", [True, False])
def test_jacobi_matches_numpy(overlap):
    iters = 4
    r = run(20, 16, 12, iters=iters, overlap=overlap, weak=False,
            devices=jax.devices()[:8], warmup=0)
    size = Dim3(r["x"], r["y"], r["z"])
    dd, h = r["domain"], r["handle"]
    got = dd.get_curr_global(h)

    masks = sphere_masks(size)
    field = np.full((size.z, size.y, size.x), INIT_TEMP, dtype=np.float32)
    want = jacobi_reference(field, masks, iters)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_overlap_equals_no_overlap():
    ra = run(20, 16, 12, iters=3, overlap=True, weak=False,
             devices=jax.devices()[:8], warmup=0)
    rb = run(20, 16, 12, iters=3, overlap=False, weak=False,
             devices=jax.devices()[:8], warmup=0)
    a = ra["domain"].get_curr_global(ra["handle"])
    b = rb["domain"].get_curr_global(rb["handle"])
    np.testing.assert_array_equal(a, b)


def test_direct26_method_agrees():
    ra = run(16, 16, 16, iters=2, weak=False, devices=jax.devices()[:8], warmup=0)
    rb = run(16, 16, 16, iters=2, weak=False, devices=jax.devices()[:8],
             method=Method.DIRECT26, warmup=0)
    a = ra["domain"].get_curr_global(ra["handle"])
    b = rb["domain"].get_curr_global(rb["handle"])
    np.testing.assert_array_equal(a, b)


def test_uneven_distributed_jacobi():
    """Uneven partition falls back to non-overlap but must stay correct."""
    iters = 3
    r = run(18, 14, 10, iters=iters, weak=False, devices=jax.devices()[:8], warmup=0)
    size = Dim3(r["x"], r["y"], r["z"])
    masks = sphere_masks(size)
    field = np.full((size.z, size.y, size.x), INIT_TEMP, dtype=np.float32)
    want = jacobi_reference(field, masks, iters)
    got = r["domain"].get_curr_global(r["handle"])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_csv_row_format():
    r = run(8, 8, 8, iters=1, weak=False, devices=jax.devices()[:1], warmup=0)
    row = csv_row(r)
    assert row.startswith("jacobi3d,axis-composed,1,1,8,8,8,")
    assert len(row.split(",")) == 10


def test_run_executes_exact_iteration_count():
    """iters not a multiple of the fused chunk must not overshoot."""
    iters = 7
    r = run(16, 12, 10, iters=iters, weak=False, devices=jax.devices()[:8],
            warmup=0, chunk=5)
    size = Dim3(r["x"], r["y"], r["z"])
    masks = sphere_masks(size)
    field = np.full((size.z, size.y, size.x), INIT_TEMP, dtype=np.float32)
    want = jacobi_reference(field, masks, iters)
    got = r["domain"].get_curr_global(r["handle"])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_distributed_pallas_step_matches_xla_path():
    """Full distributed jacobi step (wrap/exchange + pallas sweep inside
    shard_map) on a 2x2x1 mesh in interpret mode vs the XLA path — pins
    the integration wiring (axis subsetting, in-kernel wrap on the
    single-block axis), not just the standalone kernel."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_step, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(16, 16, 16)
    spec = GridSpec(size, Dim3(2, 2, 1), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:4])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(4)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        step = make_jacobi_step(ex, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = step(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_allclose(outs["pallas"], outs["xla"], rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("k", [1, 2, 3, 5, 12])
def test_pallas_multistep_matches_reference(k):
    """Temporal-blocked kernel (interpret mode): k fused steps must equal
    k applications of the numpy periodic reference, spheres included.
    k=12 pins the default cap depth (re-measured round 5;
    STENCIL_TEMPORAL_K_CAP probes others; pipeline needs nz >= 2k+1)."""
    import jax.numpy as jnp
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.pallas_stencil import make_pallas_jacobi_multistep

    size = Dim3(20, 16, 12) if k <= 5 else Dim3(20, 16, 28)
    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(1))
    p = spec.padded()
    off = spec.compute_offset()
    fn = make_pallas_jacobi_multistep(spec, k, interpret=True)
    rng = np.random.RandomState(0)
    curr = np.zeros((p.z, p.y, p.x), np.float32)
    sl = (
        slice(off.z, off.z + size.z),
        slice(off.y, off.y + size.y),
        slice(off.x, off.x + size.x),
    )
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    curr[sl] = field
    got = np.asarray(
        fn(jnp.asarray(curr), jnp.zeros((p.z, p.y, p.x), jnp.float32))
    )
    want = jacobi_reference(field, sphere_masks(size), k)
    # fp32 rounding accumulates ~linearly in fused steps (the reference
    # runs in float64)
    np.testing.assert_allclose(
        got[sl], want, rtol=1e-7 * (2 + k), atol=5e-8 * (1 + k)
    )


@pytest.mark.parametrize(
    "k,size,ty",
    [
        # ny=40 NOT divisible by ty=16: the final strip re-anchors to
        # yo + ny - ty and recomputes its overlap with the previous strip
        (3, Dim3(20, 40, 12), 16),
        # the target depth regime the row tiling exists for (k >= 8)
        (8, Dim3(20, 32, 18), 16),
    ],
)
def test_pallas_multistep_row_tiled_matches_reference(k, size, ty):
    """Row-tiled staging (strips instead of full (py, px) planes): k fused
    wavefront steps must equal k applications of the numpy periodic
    reference, spheres included, edge strips' periodic y rows delivered by
    the wrap-row DMAs (VERDICT r5 weak #2 — 768^3 depth regime)."""
    import jax.numpy as jnp
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.pallas_stencil import make_pallas_jacobi_multistep

    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(1))
    p = spec.padded()
    off = spec.compute_offset()
    fn = make_pallas_jacobi_multistep(spec, k, interpret=True, rows=ty)
    rng = np.random.RandomState(0)
    curr = np.zeros((p.z, p.y, p.x), np.float32)
    sl = (
        slice(off.z, off.z + size.z),
        slice(off.y, off.y + size.y),
        slice(off.x, off.x + size.x),
    )
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    curr[sl] = field
    got = np.asarray(
        fn(jnp.asarray(curr), jnp.zeros((p.z, p.y, p.x), jnp.float32))
    )
    want = jacobi_reference(field, sphere_masks(size), k)
    np.testing.assert_allclose(
        got[sl], want, rtol=1e-7 * (2 + k), atol=5e-8 * (1 + k)
    )


def test_pallas_multistep_row_tiled_tight_x():
    """Row strips compose with the zero-x-radius tight layout (the 768^3
    production combination: x wrap by lane rolls, y wrap by strip DMAs)."""
    import jax.numpy as jnp
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.pallas_stencil import make_pallas_jacobi_multistep

    k, ty = 4, 16
    size = Dim3(128, 32, 14)
    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(1).without_x())
    assert spec.padded().x == 128 and spec.compute_offset().x == 0
    p = spec.padded()
    off = spec.compute_offset()
    fn = make_pallas_jacobi_multistep(spec, k, interpret=True, rows=ty)
    rng = np.random.RandomState(3)
    curr = np.zeros((p.z, p.y, p.x), np.float32)
    sl = (
        slice(off.z, off.z + size.z),
        slice(off.y, off.y + size.y),
        slice(off.x, off.x + size.x),
    )
    curr[sl] = rng.rand(size.z, size.y, size.x)
    got = np.asarray(fn(jnp.asarray(curr), jnp.zeros_like(curr)))[sl]
    want = jacobi_reference(curr[sl], sphere_masks(size), k).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_deep_halo_multistep_row_tiled_z_split_matches_xla():
    """Row-tiled staging under a deep-halo z split (dim 1x1x2, radius 2):
    strips stage the y wrap while z rides the radius-k exchange — the
    768^3-per-chip-on-a-z-mesh configuration. Forced via multistep_rows;
    must match the XLA loop bit-for-bit."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(16, 32, 20)
    iters = 4
    spec = GridSpec(size, Dim3(1, 1, 2), Radius.constant(2))  # k caps at 2
    mesh = grid_mesh(spec.dim, jax.devices()[:2])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(31)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas-rows", dict(use_pallas=True, interpret=True,
                             multistep_rows=16)),
        ("xla", dict(use_pallas=False)),
    ):
        loop = make_jacobi_loop(ex, iters, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = loop(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["pallas-rows"], outs["xla"])


def test_plan_multistep_staging_regimes():
    """The staging planner: full planes while they reach the cap (512^3
    regime — byte-identical to the round-5 layout), row strips when the
    plane size would self-cap the depth (the 768^3 regime that measured
    k=4 / 55.3 Gcells/s on full planes), and a graceful full-plane
    fallback for multi-block y."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.pallas_stencil import (
        plan_multistep_staging, valid_strip_rows,
    )

    budget = 46 * 1024 * 1024
    tight = Radius.constant(1).without_x()
    s512 = GridSpec(Dim3(512, 512, 512), Dim3(1, 1, 1), tight)
    k, rows = plan_multistep_staging(s512, 12, budget)
    assert (k, rows) == (12, None)  # full planes still reach the cap

    s768 = GridSpec(Dim3(768, 768, 768), Dim3(1, 1, 1), tight)
    k, rows = plan_multistep_staging(s768, 12, budget)
    assert k >= 8 and rows is not None  # the depth the full planes lost
    assert valid_strip_rows(s768, k, rows)

    # multi-block y: strips are unsupported — depth degrades, never crashes
    my = GridSpec(Dim3(768, 768, 768), Dim3(1, 2, 1), Radius.constant(12))
    k, rows = plan_multistep_staging(my, 12, budget)
    assert rows is None and k >= 2


def test_temporal_k_cap_env(monkeypatch):
    """STENCIL_TEMPORAL_K_CAP overrides the default depth cap (the probe
    knob that re-measures the diminishing-returns point on hardware —
    k=12 won at 512^3 round 5); the requested depth must reach the
    multistep builder."""
    import stencil_tpu.ops.pallas_stencil as ps
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_loop
    from stencil_tpu.parallel import HaloExchange, grid_mesh

    recorded = []
    orig = ps.make_pallas_jacobi_multistep

    def rec(spec, k, **kw):
        recorded.append(k)
        return orig(spec, k, **kw)

    monkeypatch.setattr(ps, "make_pallas_jacobi_multistep", rec)
    size = Dim3(20, 16, 28)  # nz >= 2k+1 for k=12
    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:1])
    ex = HaloExchange(spec, mesh)
    for env, want in ((None, 12), ("10", 10)):
        recorded.clear()
        if env is None:
            monkeypatch.delenv("STENCIL_TEMPORAL_K_CAP", raising=False)
        else:
            monkeypatch.setenv("STENCIL_TEMPORAL_K_CAP", env)
        make_jacobi_loop(ex, iters=24, use_pallas=True, interpret=True)
        assert recorded == [want], (env, recorded)


@pytest.mark.parametrize("tiles", [None, (5, 16)])
def test_pallas_wrap_matches_periodic_reference(tiles, monkeypatch):
    """Self-wrap mode (kernel fills periodic halos itself) vs np.roll
    reference; tiles=(5,16) forces the row-tiled slab path with the
    staged y-wrap DMA."""
    import jax.numpy as jnp
    import stencil_tpu.ops.pallas_stencil as ps
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius

    size = Dim3(24, 64, 10)
    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(1))
    if tiles is not None:
        monkeypatch.setattr(ps, "_pick_tiles", lambda *a: tiles)
    sweep = ps.make_pallas_jacobi_sweep(
        spec, (0, 0), interpret=True, wrap=(True, True, True)
    )
    p = spec.padded()
    off = spec.compute_offset()
    rng = np.random.RandomState(1)
    curr = jnp.asarray(rng.rand(p.z, p.y, p.x).astype(np.float32))
    got = np.asarray(
        sweep(curr, jnp.zeros((p.z, p.y, p.x), jnp.float32),
              jnp.zeros((p.z, p.y, p.x), np.int32))
    )
    sl = (
        slice(off.z, off.z + size.z),
        slice(off.y, off.y + size.y),
        slice(off.x, off.x + size.x),
    )
    f = np.asarray(curr)[sl].astype(np.float64)
    want = (
        np.roll(f, 1, 2) + np.roll(f, -1, 2) + np.roll(f, 1, 1)
        + np.roll(f, -1, 1) + np.roll(f, 1, 0) + np.roll(f, -1, 0)
    ) / 6
    np.testing.assert_allclose(got[sl], want, rtol=3e-7, atol=1e-7)


def test_pallas_sweep_matches_xla_interpret():
    """Pallas kernel (interpret mode) computes exactly what the XLA path
    computes over the compute region, including sphere overrides."""
    import jax.numpy as jnp
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius, Rect3
    from stencil_tpu.ops.jacobi import jacobi_sweep, sphere_sel
    from stencil_tpu.ops.pallas_stencil import make_pallas_jacobi_sweep, sel_z_range

    size = Dim3(40, 16, 8)
    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(1))
    sweep = make_pallas_jacobi_sweep(spec, sel_z_range(spec), interpret=True)
    p = spec.padded()
    off = spec.compute_offset()
    rng = np.random.RandomState(0)
    curr = jnp.asarray(rng.rand(p.z, p.y, p.x).astype(np.float32))
    nxt = jnp.zeros((p.z, p.y, p.x), jnp.float32)
    selg = sphere_sel(size)
    sel = np.zeros((p.z, p.y, p.x), np.int32)
    cz = slice(off.z, off.z + size.z)
    cy = slice(off.y, off.y + size.y)
    cx = slice(off.x, off.x + size.x)
    sel[cz, cy, cx] = selg
    got = np.asarray(sweep(curr, nxt, jnp.asarray(sel)))

    rect = Rect3(off, off + spec.base)
    sel_j = jnp.asarray(sel)
    want = np.asarray(
        jacobi_sweep(curr, jnp.zeros_like(nxt), rect, (sel_j == 1, sel_j == 2))
    )
    # the two lowerings may reassociate differently -> ULP-level tolerance
    np.testing.assert_allclose(got[cz, cy, cx], want[cz, cy, cx], rtol=3e-7, atol=1e-7)
    assert (sel[cz, cy, cx] == 1).any()  # spheres actually exercised


def test_distributed_pallas_overlap_2x2x2_matches_xla():
    """Overlapped Pallas fast path on a full 2x2x2 mesh (every axis
    multi-block, interpret mode), three fused iterations: the full-region
    sweep reads pre-exchange data and the multi-block-axis shells are
    re-swept from exchanged halos — must equal the XLA overlap path
    (VERDICT r2 item 2a)."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(16, 16, 16)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(11)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        loop = make_jacobi_loop(ex, iters=3, overlap=True, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = loop(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_allclose(outs["pallas"], outs["xla"], rtol=1e-6, atol=1e-7)


def test_uneven_overlap_equals_no_overlap():
    """Uneven partitions keep the interior/exterior overlap via dynamic
    shells (ops/shells.py, VERDICT r2 item 8): the overlapped step must be
    bit-exact vs the serialized step on a genuinely uneven 2x2x2 split
    (x blocks 10 and 9) and match the global reference."""
    iters = 3
    kw = dict(iters=iters, weak=False, devices=jax.devices()[:8], warmup=0,
              partition=(2, 2, 2))
    ra = run(19, 14, 10, overlap=True, **kw)
    rb = run(19, 14, 10, overlap=False, **kw)
    a = ra["domain"].get_curr_global(ra["handle"])
    b = rb["domain"].get_curr_global(rb["handle"])
    np.testing.assert_array_equal(a, b)
    size = Dim3(ra["x"], ra["y"], ra["z"])
    masks = sphere_masks(size)
    field = np.full((size.z, size.y, size.x), INIT_TEMP, dtype=np.float32)
    want = jacobi_reference(field, masks, iters)
    np.testing.assert_allclose(a, want, rtol=1e-5, atol=1e-6)


def test_distributed_pallas_uneven_overlap_matches_xla():
    """Pallas fast path with dynamic-shell overlap on an uneven 2x2x1 mesh
    (x blocks 10 and 9; z self-wraps in-kernel), interpret mode, vs the
    serialized XLA step."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_step, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(19, 16, 12)
    spec = GridSpec(size, Dim3(2, 2, 1), Radius.constant(1))
    assert not spec.is_uniform()
    mesh = grid_mesh(spec.dim, jax.devices()[:4])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(11)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas-overlap", dict(use_pallas=True, interpret=True, overlap=True)),
        ("xla-overlap", dict(use_pallas=False, overlap=True)),
        ("xla-serial", dict(use_pallas=False, overlap=False)),
    ):
        step = make_jacobi_step(ex, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        for _ in range(2):
            curr, nxt = step(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["xla-overlap"], outs["xla-serial"])
    np.testing.assert_allclose(
        outs["pallas-overlap"], outs["xla-serial"], rtol=1e-6, atol=1e-7
    )


def test_deep_halo_multistep_2x2x2_matches_xla():
    """Multi-chip temporal blocking (VERDICT r2 item 7): with radius-2
    halos on a full 2x2x2 mesh, the fused loop takes the deep-halo
    multistep path — ONE radius-2 exchange feeding k=2 fused wavefront
    steps — and must match the per-step XLA overlap loop bit-for-bit on
    the gathered field (integer sphere math, same operand order)."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(24, 24, 24)
    iters = 4
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(2))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(6)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas-deep", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        loop = make_jacobi_loop(ex, iters, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = loop(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["pallas-deep"], outs["xla"])


def test_deep_halo_multistep_mixed_mesh_matches_xla():
    """Deep-halo multistep on a mesh mixing a multi-block z axis with
    self-wrap y/x axes (2x1x1): z halos exchanged at depth k, y/x wrapped
    in-kernel per stage."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(20, 16, 24)
    iters = 6
    spec = GridSpec(size, Dim3(1, 1, 2), Radius.constant(3))  # k caps at 3
    mesh = grid_mesh(spec.dim, jax.devices()[:2])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(8)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas-deep", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        loop = make_jacobi_loop(ex, iters, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = loop(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["pallas-deep"], outs["xla"])


def test_deep_halo_app_flag_stays_correct():
    """--deep-halo K realizes radius-K halos (XLA path on the CPU mesh);
    results must be unchanged."""
    iters = 3
    r = run(16, 16, 16, iters=iters, weak=False, devices=jax.devices()[:8],
            warmup=0, deep_halo=2)
    size = Dim3(r["x"], r["y"], r["z"])
    masks = sphere_masks(size)
    field = np.full((size.z, size.y, size.x), INIT_TEMP, dtype=np.float32)
    want = jacobi_reference(field, masks, iters)
    got = r["domain"].get_curr_global(r["handle"])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_deep_halo_sphere_crossing_periodic_boundary():
    """Non-cubic domain where the hot/cold spheres (radius g.x//10) cross
    the periodic z boundary of a z-split mesh: the deep-halo multistep must
    clamp halo-extended cells at their WRAPPED global coordinates, exactly
    as the owning block does (review r3 finding)."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(128, 16, 20)  # R = 12 > g.z/2 - ... : spheres wrap in z
    iters = 4
    spec = GridSpec(size, Dim3(1, 1, 2), Radius.constant(2))
    mesh = grid_mesh(spec.dim, jax.devices()[:2])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(9)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas-deep", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        loop = make_jacobi_loop(ex, iters, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = loop(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["pallas-deep"], outs["xla"])


def test_oversubscribed_jacobi_matches_reference():
    """2x2x2 partition on 4 devices (2 z-blocks resident per device,
    reference: dd.set_gpus({0,0})): the full distributed iteration must
    match the global reference and the 8-device run bit-for-bit."""
    iters = 3
    ra = run(16, 16, 16, iters=iters, weak=False, devices=jax.devices()[:4],
             warmup=0, partition=(2, 2, 2))
    rb = run(16, 16, 16, iters=iters, weak=False, devices=jax.devices()[:8],
             warmup=0, partition=(2, 2, 2))
    a = ra["domain"].get_curr_global(ra["handle"])
    b = rb["domain"].get_curr_global(rb["handle"])
    np.testing.assert_array_equal(a, b)
    size = Dim3(16, 16, 16)
    masks = sphere_masks(size)
    field = np.full((size.z, size.y, size.x), INIT_TEMP, dtype=np.float32)
    want = jacobi_reference(field, masks, iters)
    np.testing.assert_allclose(a, want, rtol=1e-5, atol=1e-6)


def test_oversubscribed_jacobi_two_devices_matches_reference():
    """2x2x2 partition on TWO devices — mixed (cz, cy) = (2, 2) stacking
    (VERDICT r3 item 4 'done' bar): must match the 8-device run bit-for-bit
    and the global reference."""
    iters = 3
    ra = run(16, 16, 16, iters=iters, weak=False, devices=jax.devices()[:2],
             warmup=0, partition=(2, 2, 2))
    rb = run(16, 16, 16, iters=iters, weak=False, devices=jax.devices()[:8],
             warmup=0, partition=(2, 2, 2))
    assert ra["domain"].halo_exchange.oversubscribed
    a = ra["domain"].get_curr_global(ra["handle"])
    b = rb["domain"].get_curr_global(rb["handle"])
    np.testing.assert_array_equal(a, b)
    size = Dim3(16, 16, 16)
    masks = sphere_masks(size)
    field = np.full((size.z, size.y, size.x), INIT_TEMP, dtype=np.float32)
    want = jacobi_reference(field, masks, iters)
    np.testing.assert_allclose(a, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("overlap", [True, False])
def test_resident_pallas_step_matches_xla(overlap):
    """Resident z-stack (2x2x2 partition on 4 devices) on the Pallas fast
    path (interpret): the per-block kernel loops over the stacked residents
    and must match the XLA slab path bit-for-bit (VERDICT r4 item 7 —
    oversubscription no longer forfeits the Pallas sweep)."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_step, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(16, 16, 16)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(Dim3(2, 2, 1), jax.devices()[:4])
    ex = HaloExchange(spec, mesh)
    assert ex.oversubscribed and ex.resident.z == 2
    rng = np.random.RandomState(21)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        step = make_jacobi_step(ex, overlap=overlap, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = step(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["pallas"], outs["xla"])


def test_resident_mixed_pallas_step_matches_xla():
    """Mixed (cy, cx) residency (2x2x2 on 2 devices, mesh z=2): the sweep
    loop flattens ALL leading block dims, not just z-stacks."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_step, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(16, 16, 16)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(Dim3(1, 1, 2), jax.devices()[:2])
    ex = HaloExchange(spec, mesh)
    assert ex.resident.x == 2 and ex.resident.y == 2 and ex.resident.z == 1
    rng = np.random.RandomState(22)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        step = make_jacobi_step(ex, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = step(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["pallas"], outs["xla"])


@pytest.mark.parametrize("mesh_z,ndev", [(1, 1), (2, 2)])
def test_resident_deep_halo_multistep_matches_xla(mesh_z, ndev):
    """Deep-halo temporal multistep under z residency: each resident block
    gets its own multistep call at its own global origin (the config-2
    fully-resident-on-one-chip geometry, and its 2-device split)."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(20, 16, 24)
    iters = 4
    nz = 2 * mesh_z
    spec = GridSpec(size, Dim3(1, 1, nz), Radius.constant(2))
    mesh = grid_mesh(Dim3(1, 1, mesh_z), jax.devices()[:ndev])
    ex = HaloExchange(spec, mesh)
    assert ex.resident.z == 2
    rng = np.random.RandomState(23)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas-deep", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        loop = make_jacobi_loop(ex, iters, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = loop(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["pallas-deep"], outs["xla"])


def test_oversubscribed_uneven_xy_overlap_falls_back():
    """Resident z-stacking + an uneven x/y split + overlap=True used to
    crash at trace time in _patch_shells_dyn's (pz,py,px) reshape (ADVICE
    r3); it must fall back to the serialized exchange-then-sweep path and
    still match the global reference."""
    iters = 2
    # x = 10+9 (uneven), y = 9+9, z = 8+8 (uniform, required for residency)
    ra = run(19, 18, 16, iters=iters, weak=False, devices=jax.devices()[:4],
             warmup=0, partition=(2, 2, 2), overlap=True)
    assert ra["domain"].halo_exchange.resident_z == 2
    a = ra["domain"].get_curr_global(ra["handle"])
    size = Dim3(19, 18, 16)
    masks = sphere_masks(size)
    field = np.full((size.z, size.y, size.x), INIT_TEMP, dtype=np.float32)
    want = jacobi_reference(field, masks, iters)
    np.testing.assert_allclose(a, want, rtol=1e-5, atol=1e-6)


def test_pallas_sweep_lane_aligned_inline_matches_xla():
    """Lane-aligned nx (128) with INLINE halos (radius 1, xo == 1): the
    tight-x gate must stay off (DMA slice offsets must be 128-divisible,
    ops/pallas_stencil._tight_x_layout) and the inline path must match the
    XLA step bit-for-bit. The engaged tight path is pinned separately by
    test_zero_x_radius_tight_layout_matches_reference."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_step, sphere_sel
    from stencil_tpu.ops.pallas_stencil import make_pallas_jacobi_sweep
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(128, 16, 12)  # x self-wraps and is lane-aligned
    spec = GridSpec(size, Dim3(1, 2, 1), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:2])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(12)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        step = make_jacobi_step(ex, **kwargs)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        for _ in range(2):
            curr, nxt = step(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["pallas"], outs["xla"])


def test_pallas_multistep_lane_aligned_inline_matches_reference():
    """Lane-aligned x (nx % 128 == 0) with INLINE halos (radius 1,
    xo == 1): the multistep's tight-x gate stays off and the inline path
    must equal k applications of the numpy periodic reference. The
    engaged tight multistep (zero-x-radius layout) is pinned by
    test_zero_x_radius_tight_layout_matches_reference (k=4) and
    test_zero_x_radius_tight_multistep_deep_k below."""
    import jax.numpy as jnp
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.pallas_stencil import make_pallas_jacobi_multistep

    k = 3
    size = Dim3(128, 16, 12)
    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(1))
    p = spec.padded()
    off = spec.compute_offset()
    fn = make_pallas_jacobi_multistep(spec, k, interpret=True)
    rng = np.random.RandomState(0)
    curr = np.zeros((p.z, p.y, p.x), np.float32)
    sl = (
        slice(off.z, off.z + size.z),
        slice(off.y, off.y + size.y),
        slice(off.x, off.x + size.x),
    )
    curr[sl] = rng.rand(size.z, size.y, size.x)
    got = np.asarray(fn(jnp.asarray(curr), jnp.zeros_like(curr)))[sl]
    want = jacobi_reference(curr[sl], sphere_masks(size), k).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_zero_x_radius_tight_layout_matches_reference():
    """Radius.without_x on a single block (no x halo columns allocated,
    px == nx): both the one-step sweep and the fused multistep must match
    the periodic numpy reference in interpret mode."""
    import jax.numpy as jnp
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import (
        make_jacobi_loop, make_jacobi_step, sphere_sel,
    )
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(128, 16, 12)
    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(1).without_x())
    assert spec.padded().x == 128 and spec.compute_offset().x == 0
    mesh = grid_mesh(spec.dim, jax.devices()[:1])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(13)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)
    masks = sphere_masks(size)

    for iters, maker in ((1, lambda: make_jacobi_step(
            ex, use_pallas=True, interpret=True)),
                         (4, lambda: make_jacobi_loop(
            ex, 4, use_pallas=True, interpret=True))):
        step = maker()
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = step(curr, nxt, sel)
        got = unshard_blocks(curr, spec)
        want = jacobi_reference(field, masks, iters).astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7,
                                   err_msg=f"iters={iters}")


def test_tight_x_multiblock_yz_matches_reference():
    """Tight-x with MULTI-BLOCK y/z axes (dim 1x2x2, radius-2 inline y/z
    halos, zero x radius): the kernel wraps x by lane rolls while y/z ride
    the exchange; the overlap step (roll-aware shells) and the deep-halo
    fused loop must match the periodic reference in interpret mode
    (VERDICT r3 item 5: tight-x beyond the all-single-block case)."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_loop, make_jacobi_step, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(128, 16, 12)
    spec = GridSpec(size, Dim3(1, 2, 2), Radius.constant(2).without_x())
    assert spec.padded().x == 128 and spec.compute_offset().x == 0
    mesh = grid_mesh(spec.dim, jax.devices()[:4])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(17)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)
    masks = sphere_masks(size)

    for iters, maker in (
        (1, lambda: make_jacobi_step(ex, use_pallas=True, interpret=True)),
        # radius 2 on the multi-block axes engages the deep-halo multistep
        # at k=2 (one exchange per 2 fused steps)
        (4, lambda: make_jacobi_loop(ex, 4, use_pallas=True, interpret=True)),
    ):
        step = maker()
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        curr, nxt = step(curr, nxt, sel)
        got = unshard_blocks(curr, spec)
        want = jacobi_reference(field, masks, iters).astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7,
                                   err_msg=f"iters={iters}")


def test_tight_x_sidebuf_multiblock_x_matches_reference():
    """Tight-x on a MULTI-BLOCK x axis (out-of-line halo side buffers,
    VERDICT r3 item 5): the kernel rolls x block-locally, the exchange
    delivers neighbor columns as side buffers, and the x-edge columns are
    patched from them. dim 2x1x1 (pure x split) and 2x2x1 (x+y split),
    overlap and serialized, must match the periodic reference."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_step, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    size = Dim3(256, 16, 12)  # x blocks of 128 (lane-aligned per block)
    rng = np.random.RandomState(29)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    masks = sphere_masks(size)
    want = jacobi_reference(field, masks, 1).astype(np.float32)

    for dim, ndev in ((Dim3(2, 1, 1), 2), (Dim3(2, 2, 1), 4)):
        spec = GridSpec(size, dim, Radius.constant(1).without_x())
        assert spec.padded().x == 128 and spec.compute_offset().x == 0
        mesh = grid_mesh(spec.dim, jax.devices()[:ndev])
        ex = HaloExchange(spec, mesh)
        sel = shard_blocks(sphere_sel(size), spec, mesh)
        for overlap in (True, False):
            step = make_jacobi_step(ex, overlap=overlap, use_pallas=True,
                                    interpret=True)
            curr = shard_blocks(field, spec, mesh)
            nxt = shard_blocks(np.zeros_like(field), spec, mesh)
            curr, nxt = step(curr, nxt, sel)
            got = unshard_blocks(curr, spec)
            np.testing.assert_allclose(
                got, want, rtol=1e-6, atol=1e-7,
                err_msg=f"dim={tuple(dim)} overlap={overlap}",
            )


def test_zero_x_radius_tight_multistep_deep_k():
    """The engaged tight-x multistep at k=5, called directly: k fused
    wavefront steps over a zero-x-radius block (x wrap via lane rolls)
    must equal k applications of the numpy periodic reference."""
    import jax.numpy as jnp
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.pallas_stencil import make_pallas_jacobi_multistep

    k = 5
    size = Dim3(128, 16, 12)
    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(1).without_x())
    assert spec.padded().x == 128 and spec.compute_offset().x == 0
    p = spec.padded()
    off = spec.compute_offset()
    fn = make_pallas_jacobi_multistep(spec, k, interpret=True)
    rng = np.random.RandomState(0)
    curr = np.zeros((p.z, p.y, p.x), np.float32)
    sl = (
        slice(off.z, off.z + size.z),
        slice(off.y, off.y + size.y),
        slice(off.x, off.x + size.x),
    )
    curr[sl] = rng.rand(size.z, size.y, size.x)
    got = np.asarray(fn(jnp.asarray(curr), jnp.zeros_like(curr)))[sl]
    want = jacobi_reference(curr[sl], sphere_masks(size), k).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_uneven_overlap_asymmetric_radius():
    """Dynamic shells honor per-side radii: asymmetric halos (x-: 2, x+: 1,
    y: 1, z-: 1, z+: 2) on an uneven 2x2x2 split, overlap vs serialized
    bit-exact."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Radius
    from stencil_tpu.ops.jacobi import make_jacobi_step, sphere_sel
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    r = Radius.constant(1)
    r.set_dir((-1, 0, 0), 2)
    r.set_dir((0, 0, 1), 2)
    size = Dim3(19, 14, 10)  # x blocks (10, 9): uneven
    spec = GridSpec(size, Dim3(2, 2, 2), r)
    assert not spec.is_uniform()
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(21)
    field = rng.rand(size.z, size.y, size.x).astype(np.float32)
    sel = shard_blocks(sphere_sel(size), spec, mesh)

    outs = {}
    for label, ov in (("overlap", True), ("serial", False)):
        step = make_jacobi_step(ex, overlap=ov, use_pallas=False)
        curr = shard_blocks(field, spec, mesh)
        nxt = shard_blocks(np.zeros_like(field), spec, mesh)
        for _ in range(2):
            curr, nxt = step(curr, nxt, sel)
        outs[label] = unshard_blocks(curr, spec)
    np.testing.assert_array_equal(outs["overlap"], outs["serial"])
