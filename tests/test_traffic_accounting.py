"""Machine check of BASELINE.md's HBM-traffic claims from the compiled
Mosaic kernels (VERDICT r4 item 6).

scripts/export_traffic.py lowers the production Pallas kernels for the TPU
platform (jax.export — full Mosaic pipeline, no hardware) and reports every
``tpu.enqueue_dma``'s direction, extent, and conditionality. These tests
assert the byte movement that the performance story rests on:

- the temporal-blocked jacobi multistep moves ONE plane in and one out per
  grid step regardless of k (the ~1/k HBM-traffic claim);
- the astaroth substep's steady-state fetch is exactly (tz, ty+16, px) per
  field — input amplification (ty+16)/ty x px/nx, the documented
  1.125 x lane-pad factor (~1.12 at the 256^3 production ty=128);
- the x self-fill rewrites exactly the two edge lane-tiles per z batch
  (the ~42x RMW amplification any inline-x-halo layout pays).

Subprocess pattern as in test_overlap_hlo.py: jax.export's lowering
recursion is incompatible with pytest's rewritten frames.
"""

import json
import os
import subprocess
import sys
from collections import Counter

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "scripts", "export_traffic.py")


def _report(*args) -> dict:
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    last = None
    for attempt in range(2):  # lowering is host-heavy; retry once under load
        try:
            proc = subprocess.run(
                [sys.executable, _SCRIPT, *args],
                capture_output=True,
                text=True,
                timeout=600,
                env=env,
                cwd=_REPO,
            )
        except subprocess.TimeoutExpired:
            if attempt == 0:
                continue
            raise
        last = proc
        if proc.returncode == 0:
            return json.loads(proc.stdout.strip().splitlines()[-1])
    assert last.returncode == 0, f"{args}: {last.stderr[-3000:]}"


def _groups(kernel) -> Counter:
    return Counter((d["dir"], tuple(d["shape"])) for d in kernel["dmas"])


def test_multistep_traffic_is_k_independent():
    r4 = _report("multistep", "4")
    r8 = _report("multistep", "8")
    for rep in (r4, r8):
        (k,) = rep["kernels"]
        pz, py, px = rep["padded"]
        plane = (1, py, px)
        ins = [d for d in k["dmas"] if d["dir"] == "in"]
        outs = [d for d in k["dmas"] if d["dir"] == "out"]
        # every HBM transfer is exactly ONE padded plane — no k-scaled
        # extent exists anywhere in the kernel
        assert ins and all(tuple(d["shape"]) == plane for d in ins + outs)
        assert len(ins) <= 2 and len(outs) == 1
        assert all(d["loop_depth"] == 0 for d in k["dmas"])
        # z-wavefront pipeline: fill + drain extend the plane sweep by
        # 2(k-1) steps
        assert k["grid"] == [pz + 2 * rep["k"] - 2]
    # identical DMA inventory at k=4 and k=8: per-step HBM bytes do not
    # scale with k, so traffic per advanced step falls ~1/k
    def inventory(rep):
        return sorted(_groups(rep["kernels"][0]).items())

    assert inventory(r4) == inventory(r8)
    # static upper bound: k fused steps enqueue <= 3 planes/step over
    # pz + 2k - 2 steps, vs the serialized path's k * (1 read + 1 write)
    # full-array sweeps
    for rep in (r4, r8):
        k = rep["k"]
        pz = rep["padded"][0]
        fused_planes = 3 * (pz + 2 * k - 2)
        serial_planes = 2 * k * pz
        assert fused_planes / serial_planes < 2.2 / k


@pytest.mark.parametrize(
    "n,tight",
    [(64, False), (128, True), (256, True), (256, False)],
    ids=["64-inline", "128-tight-x", "256-production-tight", "256-inline"],
)
def test_substep_steady_state_amplification(n, tight):
    rep = _report("substep", str(n), *(["tight"] if tight else []))
    (k,) = rep["kernels"]
    tz, ty = rep["tiles"]
    pz, py, px = rep["padded"]
    nz, ny, nx = rep["base"]
    g = _groups(k)
    # strip-start window: (tz + 2*3, ty + 16, px) once per field
    assert g[("in", (tz + 6, ty + 16, px))] == 8
    # steady per-tile fetch: (tz, ty+16, px) per field (one prefetch site;
    # a strip's first tile is covered by the window DMA instead)
    assert g[("in", (tz, ty + 16, px))] == 8
    # out-buffer read (substep > 0 consumes the previous stage's out):
    # full-row tiles, both branches
    assert g[("in", (tz, ty, px))] == 16
    # write-back: one full-row tile per field, unconditional
    assert g[("out", (tz, ty, px))] == 8
    assert all(
        d["if_depth"] == 0 for d in k["dmas"] if d["dir"] == "out"
    )
    assert k["grid"] == [ny // ty, nz // tz]
    # steady-state input amplification: PARSED bytes of the per-field
    # stage fetch vs the compulsory (tz, ty, nx) fp32 tile. Must equal the
    # documented (ty+16)/ty x px/nx model exactly — at the 256^3
    # production pick ty=128 the y factor is 144/128 = 1.125 ("~1.12")
    stage_bytes = [
        d["bytes"] for d in k["dmas"]
        if d["dir"] == "in" and tuple(d["shape"]) == (tz, ty + 16, px)
    ]
    compulsory = tz * ty * nx * 4
    amp = stage_bytes[0] / compulsory
    assert amp == pytest.approx((1 + 16 / ty) * (px / nx), rel=1e-12)
    if tight:
        # tight-x (Radius.without_x): px == nx — the lane-pad x factor the
        # layout exists to remove is exactly 1 in the compiled artifact
        assert px == nx
    if n == 256:
        # the production pick's documented y window: ty=128 -> 1.125
        assert ty == 128


def test_fill_y_rmw_row_tiles_only():
    rep = _report("fill-y")
    (k,) = rep["kernels"]
    pz, py, px = rep["padded"]
    r = rep["radius"]
    g = _groups(k)
    tile = (8, 8, px)
    # per z batch: 4 row-tile reads (dest + wrap-source windows, both
    # sides) and 2 writes, all unconditional — the 8-row-tile RMW
    # economics of ops/halo_fill.py:15 ("RMW of 4 row-tiles")
    assert g[("in", tile)] == 4 and g[("out", tile)] == 2
    assert len(k["dmas"]) == 6
    assert all(d["if_depth"] == 0 and d["loop_depth"] == 0 for d in k["dmas"])
    assert k["grid"] == [-(-pz // 8)]
    # written rows per batch vs the 2r logical halo rows: the 8-row
    # minimum write granularity
    written = sum(d["bytes"] for d in k["dmas"] if d["dir"] == "out")
    logical = 2 * r * 8 * px * 4
    assert written / logical == pytest.approx(16 / 6, rel=1e-12)


def test_fill_z_stages_whole_planes():
    rep = _report("fill-z")
    (k,) = rep["kernels"]
    pz, py, px = rep["padded"]
    r = rep["radius"]
    g = _groups(k)
    plane = (r, py, px)
    # one grid step, two staged copies (top r planes -> lo halo, first r
    # planes -> hi halo), each a read + write of exactly r whole planes:
    # z halos have NO write amplification (the untiled dim)
    assert g[("in", plane)] == 2 and g[("out", plane)] == 2
    assert len(k["dmas"]) == 4
    assert all(d["if_depth"] == 0 and d["loop_depth"] == 0 for d in k["dmas"])
    assert k["grid"] == [1]


def test_fill_x_rewrites_edge_lane_tiles_only():
    rep = _report("fill-x")
    (k,) = rep["kernels"]
    tzb = rep["tzb"]
    pz, py, px = rep["padded"]
    tile = (tzb, py, 128)
    ins = [d for d in k["dmas"] if d["dir"] == "in"]
    outs = [d for d in k["dmas"] if d["dir"] == "out"]
    # every transfer is one (TZB, py, 128) edge lane-tile; exactly the two
    # edge tiles are written per batch, nothing else of the array is touched
    assert ins and all(tuple(d["shape"]) == tile for d in ins + outs)
    assert len(outs) == 2 and all(d["if_depth"] == 0 for d in outs)
    assert k["grid"] == [-(-pz // tzb)]
    # PARSED write-back bytes per batch against the logical halo columns
    # actually filled (2r per side pair at r=3 symmetric): the documented
    # ~42x RMW amplification any inline-x-halo layout pays
    # (ops/halo_fill.py:14-19)
    r = rep["radius"]
    written = sum(d["bytes"] for d in outs)
    logical = 2 * r * tzb * py * 4
    assert written / logical == pytest.approx(256 / 6, rel=1e-12)
