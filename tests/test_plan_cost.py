"""Static cost model — ranking properties the autotuner relies on.

The central property: the ranking is a function of the quantity-dtype
MULTISET, so permuting a domain's quantity declaration order can never
change which plan wins (the DB key is the same multiset — a permuted
config must also HIT the same cache entry). Plus the recorded-economics
sanity pins: batching beats per-quantity at Q>1, direct26 ranks below
composed at the recorded config, infeasible partitions never rank.

Pure geometry — no jax compilation anywhere in this file.
"""

import random

import pytest

from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.plan.autotune import default_choice
from stencil_tpu.plan.cost import (
    enumerate_candidates,
    feasible,
    rank,
    scale_radius,
    score,
)
from stencil_tpu.plan.ir import PlanChoice, PlanConfig


def _config(dtypes, grid=(64, 64, 64), r=2, ndev=8):
    return PlanConfig.make(Dim3.of(grid), Radius.constant(r), dtypes,
                           ndev, "cpu")


def _ranking_labels(cfg):
    return [ch.label() for _c, ch in rank(cfg, enumerate_candidates(cfg))]


@pytest.mark.parametrize("dtypes", [
    ["float32"] * 3 + ["float64"] * 2,
    ["float32", "float64", "float32", "float64", "float32"],
    ["float64", "float32", "int32", "float32"],
])
def test_ranking_invariant_under_quantity_dtype_permutation(dtypes):
    base = _ranking_labels(_config(dtypes))
    rng = random.Random(1234)
    for _ in range(5):
        shuffled = list(dtypes)
        rng.shuffle(shuffled)
        cfg = _config(shuffled)
        # same canonical key -> same cache entry -> same ranking
        assert cfg.key() == _config(dtypes).key()
        assert _ranking_labels(cfg) == base


def test_batched_beats_per_quantity_at_q4():
    cfg = _config(["float32"] * 4, grid=(128, 128, 128))
    ch = dict(partition=(2, 2, 2), method="axis-composed")
    b = score(cfg, PlanChoice(batch_quantities=True, **ch))
    pq = score(cfg, PlanChoice(batch_quantities=False, **ch))
    assert b.total_s < pq.total_s
    assert b.collectives == 6 and pq.collectives == 24
    assert b.wire_bytes == pq.wire_bytes  # same payload, fewer launches


def test_direct26_ranks_below_composed_at_recorded_config():
    # round 7's verdict: exact extents lose to fewer messages here
    cfg = _config(["float32"] * 4, grid=(128, 128, 128))
    ch = dict(partition=(2, 2, 2), batch_quantities=True)
    composed = score(cfg, PlanChoice(method="axis-composed", **ch))
    direct = score(cfg, PlanChoice(method="direct26", **ch))
    assert composed.total_s < direct.total_s
    assert direct.wire_bytes < composed.wire_bytes  # it DOES move less


def test_manual_beats_auto_spmd_at_q_above_1():
    # auto cannot batch (it emits per-quantity permutes today), so the
    # packed manual plan wins on collective count
    cfg = _config(["float32"] * 4, grid=(128, 128, 128))
    ch = dict(partition=(2, 2, 2), batch_quantities=True)
    manual = score(cfg, PlanChoice(method="axis-composed", **ch))
    auto = score(cfg, PlanChoice(method="auto-spmd", **ch))
    assert manual.collectives == 6 and auto.collectives == 24
    assert manual.total_s < auto.total_s


def test_multistep_k_amortizes_collective_overhead():
    cfg = _config(["float32"] * 2, grid=(64, 64, 64), r=1)
    k1 = score(cfg, PlanChoice(partition=(2, 2, 2), method="axis-composed",
                               multistep_k=1))
    k2 = score(cfg, PlanChoice(partition=(2, 2, 2), method="axis-composed",
                               multistep_k=2))
    # same collective count per exchange, but k=2 pays it every other step
    assert k1.collectives == k2.collectives == 6
    assert k2.exchange_s / 2 < k1.exchange_s
    assert k2.compute_overhead_s > 0  # the redundant-compute price is real


def test_infeasible_partitions_are_filtered():
    # 8^3 grid, radius 2: an 8-way split along one axis leaves 1-cell
    # blocks (< radius) — must not rank; 2x2x2 (4-cell blocks) must
    cfg = _config(["float32"], grid=(8, 8, 8), r=2)
    assert score(cfg, PlanChoice(partition=(8, 1, 1),
                                 method="axis-composed")) is None
    assert score(cfg, PlanChoice(partition=(2, 2, 2),
                                 method="axis-composed")) is not None
    labels = _ranking_labels(cfg)
    assert labels and all("8x1x1" not in l for l in labels)


def test_block_count_must_be_device_multiple():
    cfg = _config(["float32"], ndev=8)
    assert feasible(cfg, PlanChoice(partition=(3, 1, 1),
                                    method="axis-composed")) is None
    # 16 blocks on 8 devices: legal oversubscription (2 residents)
    feas = feasible(cfg, PlanChoice(partition=(2, 2, 4),
                                    method="axis-composed"))
    assert feas is not None
    _spec, mesh_dim, resident = feas
    assert mesh_dim.flatten() == 8 and resident.flatten() == 2


def test_partial_calibration_override_merges_per_method():
    # a probe session may recalibrate ONE method's overhead; the others
    # must fall back to the defaults instead of raising
    cfg = _config(["float32"] * 4, grid=(128, 128, 128))
    cal = {"permute_overhead_s": {"axis-composed": 5e-4}}
    ch = dict(partition=(2, 2, 2), batch_quantities=True)
    composed = score(cfg, PlanChoice(method="axis-composed", **ch), cal)
    direct = score(cfg, PlanChoice(method="direct26", **ch), cal)
    assert composed is not None and direct is not None
    baseline = score(cfg, PlanChoice(method="axis-composed", **ch))
    assert composed.total_s < baseline.total_s  # the override took effect


def test_scale_radius():
    r = Radius.constant(2)
    r3 = scale_radius(r, 3)
    assert r3.x(-1) == 6 and r3.dir((1, 1, 1)) == 6
    assert scale_radius(r, 1) is r


def test_default_choice_is_nodepartition_composed():
    from stencil_tpu.geometry import NodePartition

    cfg = _config(["float32"] * 2, grid=(64, 64, 64))
    ch = default_choice(cfg)
    want = NodePartition(Dim3(64, 64, 64), Radius.constant(2), 1, 8).dim()
    assert Dim3.of(ch.partition) == want
    assert ch.method == "axis-composed" and ch.batch_quantities
