"""Watchdog x checkpoint integration: kill mid-ladder, resume on revival.

The ISSUE-4 acceptance proof, mirroring tests/test_watchdog.py's injected
stall: a jacobi3d measurement child checkpoints every 2 steps and is
killed (hard, os._exit) by the STENCIL_CKPT_KILL_AFTER_SAVE hook right
after its step-2 snapshot is durable. The Revival ladder's next rung
passes ``--resume``; the revived child must continue from step 2 (not
step 0), finish, and leave telemetry JSONL recording resumed-from-step
plus checkpoint write spans/bytes that apps/report.py aggregates.

(Bit-exactness of the continued run is pinned in-process by
tests/test_ckpt.py and end-to-end by scripts/ci_ckpt_gate.py — this test
pins the supervision + revival + telemetry wiring.)
"""

import json
import os
import sys

from stencil_tpu.obs import watchdog

PY = sys.executable
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _jacobi_cmd(ckpt_dir, metrics, resume):
    cmd = [
        PY, "-m", "stencil_tpu.apps.jacobi3d",
        "--cpu", "2", "--x", "16", "--y", "12", "--z", "12", "--no-weak",
        "--iters", "4", "--ckpt-dir", ckpt_dir, "--ckpt-every", "2",
        "--metrics-out", metrics,
    ]
    if resume:
        cmd.append("--resume")
    return cmd


def _parse_csv(stdout):
    for line in stdout.splitlines():
        if line.startswith("jacobi3d,"):
            return line
    return None


def test_killed_child_resumes_from_checkpoint(tmp_path):
    ckpt_dir = str(tmp_path / "ck")
    metrics = str(tmp_path / "metrics.jsonl")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)

    rev = watchdog.Revival(budget_s=600, parse=_parse_csv,
                           archive_dir=str(tmp_path / "logs"),
                           min_attempt_s=1.0)
    # rung 1: dies hard right after the step-2 snapshot is durable
    env1 = dict(env)
    env1["STENCIL_CKPT_KILL_AFTER_SAVE"] = "2"
    p1 = rev.attempt(
        "kill-rung", _jacobi_cmd(ckpt_dir, metrics, resume=False),
        timeout_s=280, env=env1, cwd=REPO,
    )
    assert p1 is None
    assert rev.attempts[0].outcome == watchdog.CRASH
    assert rev.attempts[0].rc == 17
    # the kill left a durable, valid step-2 snapshot behind — and LATEST
    # names a COMPLETE snapshot (the pointer only ever moves after the
    # payloads + manifest landed), never a partial one
    from stencil_tpu.ckpt import find_resume, read_latest, validate_snapshot

    latest = read_latest(ckpt_dir)
    assert latest is not None
    assert validate_snapshot(os.path.join(ckpt_dir, latest)) == []
    found = find_resume(ckpt_dir)
    assert found is not None and found[1]["step"] == 2

    # rung 2: the revival passes --resume; the child must continue from
    # step 2 to completion and produce the result row
    p2 = rev.attempt(
        "resume-rung", _jacobi_cmd(ckpt_dir, metrics, resume=True),
        timeout_s=280, env=env, cwd=REPO,
    )
    assert p2 is not None, rev.attempts[-1].stderr_tail
    assert rev.attempts[1].outcome == watchdog.OK
    assert "resuming from checkpointed step 2" in (
        rev.attempts[1].stdout + rev.attempts[1].stderr_tail
    )
    # final state is durable at the target step
    found = find_resume(ckpt_dir)
    assert found[1]["step"] == 4

    # telemetry: resumed-from-step + checkpoint write spans/bytes, all
    # schema-valid and aggregatable by apps/report.py
    records = [json.loads(l) for l in open(metrics) if l.strip()]
    resumed = [r for r in records if r["name"] == "ckpt.resumed_from_step"]
    assert resumed and resumed[0]["value"] == 2
    writes = [r for r in records if r["name"] == "ckpt.write"]
    assert writes and all(r["seconds"] >= 0 for r in writes)
    wbytes = [r for r in records if r["name"] == "ckpt.bytes_written"]
    assert wbytes and all(r["bytes"] > 0 for r in wbytes)

    from stencil_tpu.apps.report import aggregate, load

    recs, errors = load([metrics])
    assert not errors
    agg = aggregate(recs)
    assert any("ckpt" in name for name in agg["spans"])
