"""Dim3 / Radius / halo geometry / interior-exterior tests.

Ports the semantics pinned by the reference's unit tests
(test/test_cpu_radius.cpp, test/test_cuda_local_domain.cu) and the region
math of src/stencil.cu:878-977.
"""

from stencil_tpu.geometry import (
    DIRECTIONS_26,
    Dim3,
    Radius,
    Rect3,
    compute_offset,
    exterior_regions,
    halo_extent,
    halo_pos,
    interior_region,
    raw_size,
)


def test_dim3_wrap():
    # reference: dim3.hpp:208-230
    assert Dim3(10, -1, 5).wrap(Dim3(5, 5, 5)) == Dim3(0, 4, 0)
    assert Dim3(-6, 7, 4).wrap(Dim3(5, 5, 5)) == Dim3(4, 2, 4)


def test_directions_26():
    assert len(DIRECTIONS_26) == 26
    assert Dim3(0, 0, 0) not in DIRECTIONS_26


def test_radius_constant_and_fec():
    r = Radius.constant(3)
    assert r.x(1) == 3 and r.y(-1) == 3 and r.dir(1, 1, 1) == 3
    r2 = Radius.face_edge_corner(2, 1, 0)
    assert r2.x(1) == 2
    assert r2.dir(1, 1, 0) == 1
    assert r2.dir(1, 1, 1) == 0
    assert r2.dir(0, 0, 0) == 0


def test_halo_extent_uses_face_radii():
    # reference: local_domain.cuh:212-222 — extents use face radii even for
    # edge/corner directions
    r = Radius.face_edge_corner(2, 1, 1)
    sz = Dim3(10, 20, 30)
    assert halo_extent((1, 0, 0), sz, r) == Dim3(2, 20, 30)
    assert halo_extent((1, 1, 0), sz, r) == Dim3(2, 2, 30)
    assert halo_extent((1, 1, 1), sz, r) == Dim3(2, 2, 2)
    assert halo_extent((0, 0, 0), sz, r) == sz


def test_halo_pos_asymmetric():
    # reference: src/local_domain.cu:86-129
    r = Radius.constant(0)
    r.set_dir((1, 0, 0), 2)   # +x face radius 2
    r.set_dir((-1, 0, 0), 1)  # -x face radius 1
    sz = Dim3(10, 10, 10)
    # +x halo sits past the left pad + interior
    assert halo_pos((1, 0, 0), sz, r, halo=True) == Dim3(10 + 1, 0, 0)
    # +x exterior (boundary interior) starts at left pad + interior - nothing:
    assert halo_pos((1, 0, 0), sz, r, halo=False) == Dim3(10, 0, 0)
    # -x halo is at the very edge; -x exterior just inside the pad
    assert halo_pos((-1, 0, 0), sz, r, halo=True) == Dim3(0, 0, 0)
    assert halo_pos((-1, 0, 0), sz, r, halo=False) == Dim3(1, 0, 0)
    assert raw_size(sz, r) == Dim3(13, 10, 10)
    assert compute_offset(r) == Dim3(1, 0, 0)


def test_interior_exterior_partition_compute_region():
    # interior + exteriors exactly tile the compute region, disjointly
    # (reference: src/stencil.cu:878-977)
    r = Radius.constant(2)
    compute = Rect3.of((0, 0, 0), (10, 12, 8))
    interior = interior_region(compute, r)
    assert interior == Rect3.of((2, 2, 2), (8, 10, 6))
    exts = exterior_regions(compute, interior)
    assert len(exts) == 6
    total = interior.num_points() + sum(e.num_points() for e in exts)
    assert total == compute.num_points()
    # disjointness via point sampling
    seen = set()
    for reg in [interior] + exts:
        for z in range(reg.lo.z, reg.hi.z):
            for y in range(reg.lo.y, reg.hi.y):
                for x in range(reg.lo.x, reg.hi.x):
                    assert (x, y, z) not in seen
                    seen.add((x, y, z))
    assert len(seen) == compute.num_points()


def test_interior_asymmetric_radius():
    r = Radius.constant(0)
    r.set_dir((1, 0, 0), 3)
    compute = Rect3.of((0, 0, 0), (10, 10, 10))
    interior = interior_region(compute, r)
    # only the +x side pulls in
    assert interior == Rect3.of((0, 0, 0), (7, 10, 10))
    exts = exterior_regions(compute, interior)
    assert len(exts) == 1
    assert exts[0] == Rect3.of((7, 0, 0), (10, 10, 10))


def test_zero_radius_interior_is_compute():
    r = Radius.constant(0)
    compute = Rect3.of((0, 0, 0), (5, 5, 5))
    assert interior_region(compute, r) == compute
    assert exterior_regions(compute, compute) == []


def test_halo_rect_exterior_asymmetric():
    """The owned boundary region sent toward +x is sized by the receiver's
    -x halo (radius.x(-1)), not by radius.x(+1) — regression for the
    asymmetric-radius send-extent rule (reference: src/packer.cu:80-81)."""
    from stencil_tpu.geometry import Dim3, Radius, halo_rect

    r = Radius.constant(0)
    r.set_dir((1, 0, 0), 2)
    r.set_dir((-1, 0, 0), 1)
    size = (10, 4, 4)
    send_px = halo_rect((1, 0, 0), size, r, halo=False)
    # allocation: [0,1) -x halo, [1,11) compute, [11,13) +x halo
    assert send_px.lo == Dim3(10, 0, 0)
    assert send_px.hi == Dim3(11, 4, 4)  # width 1 = radius.x(-1)
    send_mx = halo_rect((-1, 0, 0), size, r, halo=False)
    assert send_mx.lo == Dim3(1, 0, 0)
    assert send_mx.hi == Dim3(3, 4, 4)  # width 2 = radius.x(+1)
