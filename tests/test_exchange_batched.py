"""Quantity-batched halo exchange — bit parity and collective census.

The tentpole claim (ISSUE 5): with ``batch_quantities`` (the default) every
collective carries ONE packed ``(Q, ...slab)`` carrier of a same-dtype
group's boundary slabs, so the collective count per exchange is independent
of the quantity count — 6 composed permutes (or ≤26 direct ones) total, not
per quantity — while the result stays bit-identical to the per-quantity
program (the exchange is pure data movement). Parity is pinned for
fp32/fp64/mixed dicts on uniform, remainder, and oversubscribed partitions;
the census pin (batched Q=8 emits the Q=1 permute count) is what the CI
gate (`bench_exchange --batched-ab`) re-checks on every push.

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks

FP32 = ("float32",) * 3
FP64 = ("float64",) * 3
MIXED = ("float32", "float64", "float32", "float64")


def _coord(g: Dim3) -> np.ndarray:
    return (
        np.arange(g.z)[:, None, None] * 1_000_000.0
        + np.arange(g.y)[None, :, None] * 1_000.0
        + np.arange(g.x)[None, None, :]
    )


def _state(spec, mesh, dtypes):
    c = _coord(spec.global_size)
    return {
        i: shard_blocks((c + i).astype(dt), spec, mesh)
        for i, dt in enumerate(dtypes)
    }


def _ab_outputs(spec, mesh, dtypes, method=Method.AXIS_COMPOSED):
    """One exchange through the batched and the per-quantity program (fresh
    states each — the exchange donates its buffers); host-side results."""
    outs = {}
    for batched in (True, False):
        ex = HaloExchange(spec, mesh, method, batch_quantities=batched)
        out = ex(_state(spec, mesh, dtypes))
        outs[batched] = {
            k: np.asarray(jax.device_get(v)) for k, v in out.items()
        }
    return outs


def _assert_parity(outs, dtypes):
    for k in range(len(dtypes)):
        assert outs[True][k].dtype == outs[False][k].dtype == np.dtype(dtypes[k])
        np.testing.assert_array_equal(outs[True][k], outs[False][k])


@pytest.mark.parametrize("dtypes", [FP32, FP64, MIXED],
                         ids=["fp32", "fp64", "mixed"])
def test_batched_parity_uniform(dtypes):
    spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 2, 2), Radius.constant(2))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    _assert_parity(_ab_outputs(spec, mesh, dtypes), dtypes)


@pytest.mark.parametrize("dtypes", [FP32, FP64, MIXED],
                         ids=["fp32", "fp64", "mixed"])
def test_batched_parity_remainder(dtypes):
    """Uneven split on every axis: the packed carrier's slab starts are
    traced size-table lookups, exactly like the per-quantity phases."""
    spec = GridSpec(Dim3(11, 9, 13), Dim3(2, 2, 2), Radius.constant(2))
    assert not spec.is_uniform()
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    _assert_parity(_ab_outputs(spec, mesh, dtypes), dtypes)


def test_batched_parity_oversubscribed_uneven():
    """Resident z-stacking with an uneven resident axis (z = 7+6 on 4
    devices, mixed dtypes): only the boundary slabs ride the (packed)
    permute; the resident-neighbor shifts stay per-quantity local copies."""
    spec = GridSpec(Dim3(12, 12, 13), Dim3(2, 2, 2), Radius.constant(2))
    mesh = grid_mesh(Dim3(2, 2, 1), jax.devices()[:4])
    _assert_parity(_ab_outputs(spec, mesh, MIXED), MIXED)


def test_batched_parity_direct26():
    """DIRECT26 batching: one packed carrier per active direction (uniform
    and remainder partitions, incl. the face→edge→corner layering of the
    uneven path)."""
    for size in (Dim3(8, 8, 8), Dim3(11, 9, 13)):
        spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(2))
        mesh = grid_mesh(spec.dim, jax.devices()[:8])
        _assert_parity(_ab_outputs(spec, mesh, MIXED, Method.DIRECT26), MIXED)


def test_batched_census_q_independent():
    """The tentpole pin: batched AXIS_COMPOSED at Q=8 emits the SAME
    ppermute count as Q=1 (6 on the 2x2x2 mesh) with Q× the carrier
    bytes; the per-quantity program emits 6·Q. census_per_quantity
    attributes the packed bytes back to the logical per-quantity figure."""
    spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 2, 2), Radius.constant(2))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])

    def census(ex, q, dtypes=None):
        dtypes = dtypes or ("float32",) * q
        return ex.collective_census(_state(spec, mesh, dtypes))

    exb = HaloExchange(spec, mesh)
    assert exb.batch_quantities  # default on
    c1 = census(exb, 1)
    c8 = census(exb, 8)
    assert c1["collective-permute"][0] == c8["collective-permute"][0] == 6
    assert c8["collective-permute"][1] == 8 * c1["collective-permute"][1]

    exp = HaloExchange(spec, mesh, batch_quantities=False)
    assert census(exp, 8)["collective-permute"][0] == 6 * 8

    from stencil_tpu.utils.hlo_check import census_per_quantity

    per_q = census_per_quantity(c8, 8)
    assert per_q["collective-permute"] == c1["collective-permute"]

    # mixed dtypes never share a carrier (no bitcast): one packed pair per
    # phase per dtype group -> 12 permutes for a 2-group dict at any Q
    cm = census(exb, 4, MIXED)
    assert cm["collective-permute"][0] == 12


def test_batched_census_direct26_q_independent():
    spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 2, 2), Radius.constant(2))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    exd = HaloExchange(spec, mesh, Method.DIRECT26)

    def census(q):
        return exd.collective_census(_state(spec, mesh, ("float32",) * q))

    c1, c4 = census(1), census(4)
    assert c1["collective-permute"][0] == c4["collective-permute"][0] == 26
    assert c4["collective-permute"][1] == 4 * c1["collective-permute"][1]


def test_domain_quantity_batching_knob():
    """api.py wiring: set_quantity_batching reaches the realized
    HaloExchange; default is on."""
    from stencil_tpu.api import DistributedDomain

    for enabled in (True, False):
        dd = DistributedDomain(8, 8, 8)
        dd.set_radius(1)
        dd.set_partition((2, 2, 2))
        dd.set_devices(jax.devices()[:8])
        if not enabled:
            dd.set_quantity_batching(False)
        dd.add_data("a")
        dd.add_data("b", "float64")
        dd.realize()
        assert dd.halo_exchange.batch_quantities is enabled
