"""2-process Gloo execution of the day-1 weak-scaling harness.

apps/weak_scaling.py is the script the first multi-chip hardware session
depends on, yet until round 6 it had only virtual-mesh and single-chip
runs — a refactor could rot it unexecuted (VERDICT r5 "Next" #4). This
drives it through the REAL launcher (scripts/launch_multiprocess.sh: two
processes x four virtual CPU devices, jax.distributed over Gloo loopback)
at smoke sizes, the same invocation archived in
scripts/r06_logs/weak_scaling_gloo.log."""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LAUNCHER = os.path.join(_REPO, "scripts", "launch_multiprocess.sh")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_weak_scaling_two_process_gloo_smoke():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers configure their own device counts
    env["STENCIL_PORT"] = str(_free_port())
    proc = subprocess.run(
        ["bash", _LAUNCHER, "2", "4", "stencil_tpu.apps.weak_scaling",
         "--smoke"],
        capture_output=True,
        text=True,
        timeout=900,
        env=env,
        cwd=_REPO,
    )
    out = proc.stdout + proc.stderr
    if "Multiprocess computations aren't implemented on the CPU backend" in out:
        # some jaxlib builds ship without Gloo CPU collectives (this is the
        # same wall tests/test_multiprocess.py hits there); the harness
        # wiring is still exercised up to backend init
        pytest.skip("jaxlib built without CPU multiprocess collectives")
    assert proc.returncode == 0, out[-4000:]
    # both ranks print the full CSV: the four config rows must be present
    # and every efficiency field must have parsed as a number
    for row in ("config2_exchange", "config3_exchange_weak",
                "config5_jacobi_overlap", "config5_hidden_frac"):
        assert row in out, (row, out[-4000:])
    rows = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("config") and ",8," in ln]
    assert len(rows) >= 4, proc.stdout[-4000:]
    for ln in rows:
        float(ln.rsplit(",", 1)[1])  # efficiency column parses
