"""The jax API surface the package is written against must exist after
``import stencil_tpu`` — natively on a current jax, via utils/jax_compat
shims on older releases (where the seed suite failed 121 tests on these
exact spellings). Green on both."""

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu

import stencil_tpu  # noqa: F401 - applies the shims


def test_shard_map_spelling_exists():
    assert callable(jax.shard_map)


def test_shape_dtype_struct_accepts_vma():
    s = jax.ShapeDtypeStruct((4, 8), jnp.float32, vma=frozenset({"x"}))
    assert s.shape == (4, 8) and s.dtype == jnp.float32


def test_compiler_params_spelling_exists():
    p = pltpu.CompilerParams(
        dimension_semantics=("arbitrary",),
        has_side_effects=True,
        vmem_limit_bytes=1 << 20,
    )
    assert p is not None
