"""QAP solver tests (ported from reference test/test_cpu_qap.cpp), topology,
and placement strategies through the DistributedDomain API."""

import math

import jax
import numpy as np
import pytest

from stencil_tpu.api import DistributedDomain
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import (
    Boundary,
    IntraNodeRandom,
    NodeAware,
    Topology,
    Trivial,
    comm_matrix,
)
from stencil_tpu.parallel import qap

INF = float("inf")


@pytest.mark.parametrize("use_native", [True, False])
class TestQap:
    def test_unbalanced_triangle(self, use_native):
        # high bw between 0-2, high comm between 0-1 -> put 1 on slot 2
        bw = np.array([[INF, 1, 10], [1, INF, 1], [10, 1, INF]], float)
        comm = np.array([[0, 10, 1], [10, 0, 1], [1, 1, 0]], float)
        dist = qap.make_reciprocal(bw)
        f, cost = qap.solve(comm, dist, use_native=use_native)
        assert f == [0, 2, 1]
        assert math.isclose(cost, qap.cost(comm, dist, f))

    def test_p9_exact(self, use_native):
        bw = np.array(
            [[900, 75, 64, 64], [75, 900, 64, 64], [64, 64, 900, 75], [64, 64, 75, 900]],
            float,
        )
        comm = np.array(
            [[7, 5, 10, 1], [5, 7, 1, 10], [10, 1, 7, 5], [1, 10, 5, 7]], float
        )
        dist = qap.make_reciprocal(bw)
        f, _ = qap.solve(comm, dist, use_native=use_native)
        assert f == [0, 2, 1, 3]

    def test_p9_catch(self, use_native):
        bw = np.array(
            [[900, 75, 64, 64], [75, 900, 64, 64], [64, 64, 900, 75], [64, 64, 75, 900]],
            float,
        )
        comm = np.array(
            [[7, 5, 10, 1], [5, 7, 1, 10], [10, 1, 7, 5], [1, 10, 5, 7]], float
        )
        dist = qap.make_reciprocal(bw)
        f, _ = qap.solve_catch(comm, dist, use_native=use_native)
        # greedy lands in the reference's exact local optimum
        assert f == [3, 1, 2, 0]

    def test_big_catch_improves(self, use_native):
        rng = np.random.RandomState(42)
        n = 32
        bw = rng.rand(n, n) + 0.01
        comm = rng.rand(n, n)
        dist = qap.make_reciprocal(bw)
        identity_cost = qap.cost(comm, dist, list(range(n)))
        f, cost = qap.solve_catch(comm, dist, use_native=use_native)
        assert sorted(f) == list(range(n))
        assert cost <= identity_cost


def test_native_matches_python_on_random():
    rng = np.random.RandomState(7)
    for n in (3, 5, 6):
        w = rng.rand(n, n)
        d = rng.rand(n, n)
        fn, cn = qap.solve(w, d)
        fp, cp = qap.solve(w, d, use_native=False)
        assert fn == fp and math.isclose(cn, cp)
        gn, gcn = qap.solve_catch(w, d)
        gp, gcp = qap.solve_catch(w, d, use_native=False)
        assert gn == gp and math.isclose(gcn, gcp)


class TestTopology:
    def test_periodic_wrap(self):
        t = Topology((3, 3, 3))
        n = t.get_neighbor((0, 0, 0), (-1, -1, -1))
        assert n.exists and n.index == Dim3(2, 2, 2)
        n = t.get_neighbor((2, 1, 0), (1, 0, 1))
        assert n.index == Dim3(0, 1, 1)

    def test_rejects_non_periodic(self):
        with pytest.raises(ValueError):
            Topology((2, 2, 2), Boundary.NONE)


class TestCommMatrix:
    def test_symmetric_face_volumes(self):
        spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 2, 1), Radius.constant(1))
        m = comm_matrix(spec)
        assert m.shape == (4, 4)
        # neighbors in x: blocks 0-1, 2-3; in y: 0-2, 1-3
        assert m[0, 1] > 0 and m[0, 2] > 0
        np.testing.assert_allclose(m, m.T)

    def test_self_wrap_excluded(self):
        spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 1, 1), Radius.constant(1))
        m = comm_matrix(spec)
        assert np.all(np.diag(m) == 0)

    def test_gated_direction_excluded(self):
        r = Radius.constant(0)
        r.set_dir((1, 0, 0), 1)
        r.set_dir((-1, 0, 0), 1)
        spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 2, 1), r)
        m = comm_matrix(spec)
        assert m[0, 1] > 0  # x neighbors communicate
        assert m[0, 2] == 0  # y gated off


@pytest.mark.parametrize(
    "placement", [Trivial(), IntraNodeRandom(), NodeAware(timeout_s=2.0)]
)
def test_placements_through_api(placement):
    """Every placement yields a correct exchange (values don't depend on
    which device hosts which block)."""
    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    dd.set_placement(placement)
    h = dd.add_data("q", "float32")
    dd.realize()
    g = dd.size
    z, y, x = np.meshgrid(np.arange(g.z), np.arange(g.y), np.arange(g.x), indexing="ij")
    field = (x + 100 * y + 10000 * z).astype(np.float32)
    dd.set_curr_global(h, field)
    dd.exchange()
    np.testing.assert_array_equal(dd.get_curr_global(h), field)
    # spot-check one wrapped halo cell on block (0,0,0)
    arr = np.asarray(jax.device_get(dd.get_curr(h)))[0, 0, 0]
    off = dd.spec.compute_offset()
    # -x halo at the compute origin row/plane maps to global x=7 wrap
    assert arr[off.z, off.y, off.x - 1] == field[0, 0, 7]


def test_intranode_random_deterministic():
    devs = jax.devices()[:8]
    spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 2, 2), Radius.constant(1))
    a = IntraNodeRandom(seed=0).arrange(devs, spec)
    b = IntraNodeRandom(seed=0).arrange(devs, spec)
    assert a == b
    assert sorted(d.id for d in a) == sorted(d.id for d in devs)


@pytest.mark.parametrize("use_native", [True, False])
def test_catch_terminates_on_symmetric_block_matrix(use_native):
    """Symmetric inputs create many equal-cost assignments; float drift in
    the incremental update must not read as an improvement (regression for
    an infinite loop; latent in the reference algorithm too)."""
    w = np.kron(np.eye(2), np.ones((4, 4))) + 0.01
    np.fill_diagonal(w, 0)
    rng = np.random.RandomState(3)
    d = rng.rand(8, 8)
    np.fill_diagonal(d, 0)
    f, cost = qap.solve_catch(w, d, use_native=use_native)
    assert sorted(f) == list(range(8))
    assert cost <= qap.cost(w, d, list(range(8))) + 1e-9
