"""Telemetry recorder + metrics schema + report aggregation tests.

The schema assertions here are the same authority CI's JSONL gate uses
(telemetry.validate_record via apps/report.py --validate): run id,
process index, span name + seconds, bytes where applicable.
"""

import io
import json

import jax
import pytest

from stencil_tpu.apps import report
from stencil_tpu.obs import telemetry
from stencil_tpu.utils import timer


def _records(buf: io.StringIO):
    return [json.loads(l) for l in buf.getvalue().splitlines() if l.strip()]


def test_recorder_emits_schema_valid_records():
    buf = io.StringIO()
    rec = telemetry.Recorder(sink=buf, app="t", run_id="RUN")
    with rec.span("work", phase="step", iters=3):
        pass
    rec.counter("census.collective-permute", value=6, bytes=123,
                phase="exchange")
    rec.counter("only.bytes", bytes=7)
    rec.gauge("speed", 1.5, unit="GB/s")
    rec.meta("config", config={"x": 1})
    rec.heartbeat()
    recs = _records(buf)
    assert [r["kind"] for r in recs] == [
        "span", "counter", "counter", "gauge", "meta", "heartbeat",
    ]
    for r in recs:
        assert telemetry.validate_record(r) == [], r
        assert r["run"] == "RUN" and r["proc"] == 0 and r["app"] == "t"
    span = recs[0]
    assert span["seconds"] >= 0 and span["phase"] == "step"
    assert span["iters"] == 3
    assert recs[1]["value"] == 6 and recs[1]["bytes"] == 123


def test_span_rides_timer_buckets_and_survives_exceptions():
    timer.reset()
    buf = io.StringIO()
    rec = telemetry.Recorder(sink=buf)
    with pytest.raises(ValueError, match="boom"):
        with rec.span("failing"):
            raise ValueError("boom")
    recs = _records(buf)
    assert recs[-1]["kind"] == "span" and recs[-1]["name"] == "failing"
    # the shared bucket accumulated too (timed + trace_range underneath)
    assert "failing" in timer.buckets


def test_disabled_recorder_still_times():
    timer.reset()
    rec = telemetry.Recorder(sink=None)
    assert not rec.enabled
    with rec.span("quiet"):
        pass
    assert "quiet" in timer.buckets


def test_validate_record_catches_violations():
    ok = {"v": 1, "run": "r", "proc": 0, "kind": "span", "name": "s",
          "t": 0.0, "seconds": 0.1}
    assert telemetry.validate_record(ok) == []
    assert telemetry.validate_record({})  # missing everything
    assert telemetry.validate_record("not a dict")
    bad = dict(ok)
    del bad["seconds"]
    assert telemetry.validate_record(bad)  # span without seconds
    assert telemetry.validate_record(dict(ok, kind="bogus"))
    ctr = {"v": 1, "run": "r", "proc": 0, "kind": "counter", "name": "c",
           "t": 0.0}
    assert telemetry.validate_record(ctr)  # counter with no value/bytes
    assert telemetry.validate_record(dict(ctr, bytes=5)) == []
    assert telemetry.validate_record(dict(ctr, value=5)) == []
    assert telemetry.validate_record(dict(ctr, bytes=1.5))  # non-int bytes
    gauge = {"v": 1, "run": "r", "proc": 0, "kind": "gauge", "name": "g",
             "t": 0.0}
    assert telemetry.validate_record(gauge)
    assert telemetry.validate_record(dict(gauge, value=2.5)) == []


def test_exchange_truth_lands_in_metrics_file(tmp_path):
    """Integration: time_exchange with the recorder enabled emits phase
    spans AND the census/byte counters, all schema-valid."""
    from stencil_tpu.apps._bench_common import time_exchange
    from stencil_tpu.geometry import Dim3, Radius

    path = str(tmp_path / "m.jsonl")
    telemetry.configure(metrics_out=path, app="test")
    try:
        time_exchange(Dim3(16, 16, 16), Radius.constant(1), iters=2,
                      devices=jax.devices()[:8], quantities=2, chunk=2)
    finally:
        telemetry.configure(metrics_out=None)  # back to disabled
    records, errors = report.load([path])
    assert errors == []
    names = {r["name"] for r in records}
    assert {"exchange.warmup", "exchange.iter",
            "census.collective-permute", "exchange.bytes_logical",
            "exchange.bytes_moved", "exchange.trimean_s",
            "exchange.gb_per_s"} <= names
    cp = next(r for r in records if r["name"] == "census.collective-permute")
    # composed method with quantity batching (the default): 6 packed
    # carriers total, independent of the 2 quantities
    assert cp["value"] == 6
    assert cp["bytes"] > 0
    ppq = next(r for r in records
               if r["name"] == "exchange.permutes_per_quantity")
    assert ppq["value"] == 6 / 2 and ppq["quantities"] == 2
    wire = next(r for r in records if r["name"] == "exchange.bytes_on_wire")
    wire_q = next(r for r in records
                  if r["name"] == "exchange.bytes_on_wire_per_quantity")
    assert wire["bytes"] == 2 * wire_q["bytes"] > 0
    bl = next(r for r in records if r["name"] == "exchange.bytes_logical")
    assert bl["bytes"] > 0


def test_record_dma_traffic_failure_is_evidence_not_crash():
    # a capture failure must record a meta line, never raise: the DMA
    # truth is evidence attached to the run, not the measurement itself
    buf = io.StringIO()
    rec = telemetry.Recorder(sink=buf)

    def exploding_build():
        raise RuntimeError("no kernels here")

    assert telemetry.record_dma_traffic(exploding_build, rec) == []
    recs = _records(buf)
    assert recs[-1]["name"] == "dma.capture_error"
    assert "no kernels here" in recs[-1]["error"]


def test_report_aggregation_tables_and_baseline(tmp_path):
    path = tmp_path / "m.jsonl"
    base = {"v": 1, "run": "r1", "proc": 0, "t": 0.0}
    rows = [
        dict(base, kind="span", name="s", phase="step", seconds=1.0),
        dict(base, kind="span", name="s", phase="step", seconds=2.0),
        dict(base, kind="span", name="s", phase="step", seconds=3.0, run="r2",
             proc=1),
        dict(base, kind="counter", name="c", bytes=10),
        dict(base, kind="counter", name="c", bytes=11),  # disagreement
        dict(base, kind="gauge", name="speed", value=2.0),
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    records, errors = report.load([str(path)])
    assert errors == []
    agg = report.aggregate(records)
    assert agg["spans"]["s"].count() == 3
    assert agg["spans"]["s"].trimean() == 2.0
    assert agg["runs"] == ["r1", "r2"] and agg["procs"] == [0, 1]
    text = report.tables(agg)
    assert "s,step,3," in text and "10..11 (2 distinct)" in text
    md = report.tables(agg, markdown=True)
    assert "| s | step | 3 |" in md
    # baseline delta: nested numeric leaves AND bench-payload form match
    delta = report.baseline_delta(agg, {"published": {"speed": 1.0}})
    assert "2.000" in delta
    delta2 = report.baseline_delta(agg, {"metric": "speed", "value": 4.0})
    assert "0.500" in delta2
    assert "no gauge matches" in report.baseline_delta(agg, {"other": 1.0})


def test_report_validate_cli(tmp_path, capsys):
    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps(
        {"v": 1, "run": "r", "proc": 0, "kind": "gauge", "name": "g",
         "t": 0.0, "value": 1.0}) + "\n")
    bad = tmp_path / "bad.jsonl"
    bad.write_text("not json at all\n" + json.dumps(
        {"v": 1, "run": "r", "kind": "span", "name": "s"}) + "\n")
    assert report.main([str(good), "--validate"]) == 0
    assert report.main([str(bad), "--validate"]) == 1
    assert report.main([str(good)]) == 0
    out = capsys.readouterr().out
    assert "g" in out


def test_machine_info_json_records():
    from stencil_tpu.apps import machine_info

    r = machine_info.run(devices=jax.devices()[:8], size=64)
    buf = io.StringIO()
    rec = telemetry.Recorder(sink=buf, app="machine_info")
    out = machine_info.emit_records(r, rec)
    recs = _records(buf)
    # machine + 8 devices + fabric fingerprint + partition + 2 matrices
    assert len(recs) == len(out) == 1 + 8 + 1 + 1 + 2
    for rr in recs:
        assert telemetry.validate_record(rr) == [], rr
    devs = [rr for rr in recs if rr["name"] == "machine.device"]
    assert len(devs) == 8
    assert all(rr["platform"] == "cpu" for rr in devs)
    m = next(rr for rr in recs if rr["name"] == "machine")
    assert m["devices"] == 8
    fab = next(rr for rr in recs if rr["name"] == "machine.fabric")
    assert fab["devices"] == 8 and fab["platform"] == "cpu"
    assert fab["processes"] >= 1 and fab["hosts"] >= 1
    dm = next(rr for rr in recs if rr["name"] == "machine.distance_matrix")
    assert len(dm["matrix"]) == 8 and len(dm["matrix"][0]) == 8
    part = next(rr for rr in recs if rr["name"] == "machine.partition")
    assert len(part["dim"]) == 3


def test_validate_record_fault_vocabulary():
    """The fault.*/health.*/recover.* records carry typed payload fields
    (schema NAME_FIELDS) — the CI fault gate greps these, so an untyped
    or missing field must fail validation, not a post-mortem."""
    base = {"v": 1, "run": "r", "proc": 0, "t": 0.0}
    ok = dict(base, kind="meta", name="fault.injected",
              fault_kind="nan", step=3)
    assert telemetry.validate_record(ok) == []
    missing = dict(base, kind="meta", name="fault.injected", fault_kind="nan")
    assert any("step" in e for e in telemetry.validate_record(missing))
    badtype = dict(base, kind="meta", name="health.fault",
                   fault_kind="nonfinite", quantity=7, step=1)
    assert any("quantity" in e for e in telemetry.validate_record(badtype))
    # bools are not ints for step-typed fields
    booly = dict(base, kind="meta", name="recover.fault",
                 fault_kind="nonfinite", step=True)
    assert any("step" in e for e in telemetry.validate_record(booly))
    rb = dict(base, kind="counter", name="recover.rollback", value=1,
              from_step=4, to_step=2, fault_step=4)
    assert telemetry.validate_record(rb) == []
    rb_bad = dict(rb)
    del rb_bad["to_step"]
    assert any("to_step" in e for e in telemetry.validate_record(rb_bad))
    span = dict(base, kind="span", name="health.check", seconds=0.01, step=2)
    assert telemetry.validate_record(span) == []
    skip = dict(base, kind="counter", name="ckpt.save_skipped", value=1,
                reason="multi-process writes unsupported")
    assert telemetry.validate_record(skip) == []
    skip_bad = dict(skip)
    del skip_bad["reason"]
    assert any("reason" in e for e in telemetry.validate_record(skip_bad))


def test_baseline_delta_flags_leaf_collisions(tmp_path):
    """Two baseline keys sharing a leaf name make the leaf match
    AMBIGUOUS: the row is flagged with both candidate keys instead of
    silently ratio-ing against whichever flattened first; an exact
    full-name match stays unambiguous."""
    path = tmp_path / "m.jsonl"
    base = {"v": 1, "run": "r1", "proc": 0, "t": 0.0}
    rows = [
        dict(base, kind="gauge", name="speed", value=2.0),
        dict(base, kind="gauge", name="tpu.speed", value=3.0),
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    records, _ = report.load([str(path)])
    agg = report.aggregate(records)
    baseline = {"tpu": {"speed": 1.0}, "cpu": {"speed": 4.0}}
    delta = report.baseline_delta(agg, baseline)
    line = next(l for l in delta.splitlines() if l.startswith("speed,"))
    assert "AMBIGUOUS" in line
    assert "cpu.speed" in line and "tpu.speed" in line
    # "tpu.speed" matches its full baseline key exactly: a clean ratio
    exact = next(l for l in delta.splitlines() if l.startswith("tpu.speed"))
    assert "AMBIGUOUS" not in exact and "3.000" in exact
    # with one candidate the leaf match still resolves
    single = report.baseline_delta(agg, {"cpu": {"speed": 4.0}})
    assert "AMBIGUOUS" not in single and "0.500" in single


def test_report_follow_single_pass(tmp_path, capsys):
    """--follow smoke: one redraw renders the tables, reports heartbeat
    freshness from the beat file's mtime, and waits politely for files
    that do not exist yet."""
    import io

    path = tmp_path / "m.jsonl"
    path.write_text(json.dumps(
        {"v": 1, "run": "r", "proc": 0, "kind": "span", "name": "s",
         "phase": "step", "t": 0.0, "seconds": 1.0}) + "\n")
    hb = tmp_path / "beat"
    hb.write_text("1\n")
    out = io.StringIO()
    rc = report.follow([str(path)], count=1, heartbeat=str(hb), out=out)
    text = out.getvalue()
    assert rc == 0
    assert "follow #1" in text and "1/1 file(s)" in text
    assert "s,step,1," in text  # the span table rendered
    assert "heartbeat:" in text and "s ago" in text
    # a not-yet-existing file is waited for, not an error
    out2 = io.StringIO()
    rc = report.follow([str(tmp_path / "later.jsonl")], count=1, out=out2)
    assert rc == 0
    assert "waiting for records" in out2.getvalue()
    assert "no heartbeat file" in out2.getvalue()
    # the CLI path: --follow --follow-count 1
    assert report.main([str(path), "--follow", "--follow-count", "1"]) == 0
    assert "follow #1" in capsys.readouterr().out


def test_follow_survives_vanishing_file(tmp_path, monkeypatch):
    """A metrics file can vanish between follow()'s exists() filter and
    load()'s open() (watchdog ladders rotate child logs) — the live view
    must render a waiting line, not die with a traceback."""
    import io

    path = tmp_path / "m.jsonl"
    path.write_text("")
    real_load = report.load

    def racy_load(paths):
        raise FileNotFoundError(f"[Errno 2] No such file: {paths}")

    monkeypatch.setattr(report, "load", racy_load)
    out = io.StringIO()
    assert report.follow([str(path)], count=1, out=out) == 0
    text = out.getvalue()
    assert "waiting for records" in text and "1 schema error(s)" in text
    monkeypatch.setattr(report, "load", real_load)


def test_report_warns_ledger_without_validate(tmp_path, capsys):
    """--ledger is a --validate-mode input; default report mode must say
    it is ignoring the flag instead of skipping the ledger check with
    rc 0 and no hint."""
    path = tmp_path / "m.jsonl"
    path.write_text(json.dumps(
        {"v": 1, "run": "r", "proc": 0, "kind": "gauge", "name": "g",
         "t": 0.0, "value": 1.0}) + "\n")
    led = tmp_path / "L.jsonl"
    led.write_text("")
    assert report.main([str(path), "--ledger", str(led)]) == 0
    err = capsys.readouterr().err
    assert "ignores --ledger" in err
