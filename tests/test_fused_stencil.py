"""The fused compute+exchange mega-kernel stack (ISSUE 14 / ROADMAP #5),
pinned on the CPU emulation.

The claims under test:

- **fused plan IR**: the per-direction FusedPhaseIR set predicts 0
  collectives, the exact direct-geometry wire bytes, and the concurrent
  DMA count; fused is REMOTE_DMA-only and single-resident-only (loud).
- **bit parity**: the emulated fused schedule (pack → start every
  per-direction copy → wait → unpack) is bit-identical to AXIS_COMPOSED
  across uniform/uneven/fp64/mixed-dict configs, INCLUDING under bf16
  and fp8 wire compression — a carrier rounds exactly once either way.
- **overlap step parity**: the full fused jacobi loop (interior compute
  slotted between start and wait) and the fused astaroth loop (8-field
  MHD, diagonal pencils) land bit-identical to composed programs.
- **interpret-mode kernel**: the all-self-wrap form of the jacobi
  mega-kernel (in-kernel wrap fills + interior/boundary sweep) equals
  the XLA step on any host.
- **fp8 wire tier**: float8_e4m3fn quarters on-wire bytes at an
  unchanged permute/DMA count within the e4m3 half-ulp bound.
- **plan plumbing**: the autotuner searches the fused variant, persists
  it, replays it probe-free; verify_plan audits the fused lowering's
  census/byte/DMA predictions like the other four methods.

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks
from stencil_tpu.plan.ir import (FUSED_VARIANT, REMOTE_DMA, PlanChoice,
                                 PlanConfig, build_plan, wire_itemsize)


def _state(spec, mesh, nq, dtypes=None, scale=1.0):
    g = spec.global_size
    base = (
        np.arange(g.z)[:, None, None] * 1_000_000.0
        + np.arange(g.y)[None, :, None] * 1_000.0
        + np.arange(g.x)[None, None, :]
    ) * scale
    out = {}
    for i in range(nq):
        dt = dtypes[i] if dtypes else np.float32
        out[i] = shard_blocks((base + i * scale).astype(dt), spec, mesh)
    return out


def _gather(state):
    return [np.asarray(jax.device_get(state[i])) for i in sorted(state)]


# -- plan IR -------------------------------------------------------------------


def test_fused_plan_predicts_zero_permutes_and_concurrent_dmas():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    plan = build_plan(spec, Dim3(2, 2, 2), REMOTE_DMA, fused=True)
    assert plan.collectives_per_exchange(1, 1) == 0
    assert plan.collectives_per_exchange(8, 2) == 0
    # one concurrent copy per active direction (constant radius: all 26),
    # Q-independent per dtype group
    assert plan.dmas_per_exchange(1, 1) == 26
    assert plan.dmas_per_exchange(8, 1) == 26
    assert plan.dmas_per_exchange(8, 2) == 52
    # exact direct-geometry wire model (not the composed full-extent one)
    direct = build_plan(spec, Dim3(2, 2, 2), "direct26")
    assert plan.wire_bytes([4, 4]) == direct.wire_bytes([4, 4])
    assert "(fused compute+exchange kernel)" in plan.describe()
    assert "dmas=1" in plan.describe()


def test_fused_plan_self_wrap_directions_are_local():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 1, 1), Radius.constant(1))
    plan = build_plan(spec, Dim3(2, 1, 1), REMOTE_DMA, fused=True)
    # only x-crossing directions pay a DMA (2 x 9 of the 26)
    assert plan.dmas_per_exchange(1, 1) == 18
    local = [p for p in plan.fused_phases if not p.crossing]
    assert len(local) == 8 and all(p.wire_cells == 0 for p in local)


def test_fused_plan_validation_is_loud():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    with pytest.raises(ValueError, match="REMOTE_DMA"):
        build_plan(spec, Dim3(2, 2, 2), "axis-composed", fused=True)
    with pytest.raises(ValueError, match="single-resident"):
        build_plan(spec, Dim3(2, 2, 1), REMOTE_DMA, fused=True)


def test_fp8_wire_itemsize_in_byte_model():
    assert wire_itemsize("float8_e4m3fn") == 1
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    native = build_plan(spec, Dim3(2, 2, 2), REMOTE_DMA, fused=True)
    fp8 = build_plan(spec, Dim3(2, 2, 2), REMOTE_DMA, fused=True,
                     wire_dtype="float8_e4m3fn")
    assert native.wire_bytes([4]) == 4 * fp8.wire_bytes([4])
    # local hand-offs never compress
    assert native.local_bytes([4]) == fp8.local_bytes([4])


# -- cost model + search space -------------------------------------------------


def test_fused_cost_overlap_aware_and_platform_split():
    from stencil_tpu.plan.cost import enumerate_candidates, rank, score

    mk = lambda platform: PlanConfig.make(
        Dim3(24, 24, 24), Radius.constant(2), ["float32"] * 4, 8, platform)
    # the search space carries fused candidates for remote-dma
    cands = enumerate_candidates(mk("cpu"))
    assert any(c.is_fused for c in cands)
    assert all(c.method == REMOTE_DMA for c in cands if c.is_fused)
    # tpu: hiding wire behind interior compute can only help — the fused
    # exchange cost never exceeds the serialized remote-dma cost
    part = (2, 2, 2)
    plain = score(mk("tpu"), PlanChoice(partition=part, method=REMOTE_DMA))
    fused = score(mk("tpu"), PlanChoice(partition=part, method=REMOTE_DMA,
                                        kernel_variant=FUSED_VARIANT))
    assert fused is not None and plain is not None
    assert fused.collectives == 0 and fused.dmas > 0
    assert fused.exchange_s <= plain.exchange_s
    # cpu: the emulation penalty keeps the composed winner on top
    ranked_cpu = rank(mk("cpu"), enumerate_candidates(mk("cpu")))
    assert ranked_cpu[0][1].method == "axis-composed"


def test_fused_choice_infeasible_outside_its_scope():
    from stencil_tpu.plan.cost import score

    cfg = PlanConfig.make(Dim3(24, 24, 24), Radius.constant(2),
                          ["float32"], 8, "cpu")
    # fused is a REMOTE_DMA lowering
    assert score(cfg, PlanChoice(partition=(2, 2, 2),
                                 method="axis-composed",
                                 kernel_variant=FUSED_VARIANT)) is None
    # and single-resident only (16 blocks on 8 devices oversubscribes)
    assert score(cfg, PlanChoice(partition=(2, 2, 4), method=REMOTE_DMA,
                                 kernel_variant=FUSED_VARIANT)) is None


# -- emulated fused schedule: census + parity ---------------------------------


def test_fused_census_has_zero_ppermutes():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, Method.REMOTE_DMA, fused=True)
    census = ex.collective_census(_state(spec, mesh, 2))
    assert census.get("collective-permute", (0, 0))[0] == 0
    assert sum(c for c, _b in census.values()) == 0, census


def test_fused_transfer_count_q_independent_and_predicted():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    counts = {}
    for nq in (1, 4):
        ex = HaloExchange(spec, mesh, Method.REMOTE_DMA, fused=True)
        ex(_state(spec, mesh, nq))
        counts[nq] = ex._remote.last_transfer_count
    # 8 devices x 26 concurrent copies — independent of Q, and exactly
    # what the plan predicts
    assert counts[1] == counts[4] == 8 * 26
    ex = HaloExchange(spec, mesh, Method.REMOTE_DMA, fused=True)
    assert counts[1] == ex.plan.dmas_per_exchange(1, 1) * 8


@pytest.mark.parametrize("name,size,dim,ndev,dtypes,wire", [
    ("uniform", (16, 16, 16), (2, 2, 2), 8, None, None),
    ("uneven", (17, 19, 16), (2, 2, 2), 8, None, None),
    ("fp64", (16, 16, 16), (2, 2, 2), 8, [np.float64, np.float64], None),
    ("mixed-dtype", (16, 16, 16), (2, 2, 2), 8,
     [np.float32, np.float64, np.float32], None),
    ("bf16-wire", (16, 16, 16), (2, 2, 2), 8, None, "bfloat16"),
    ("fp8-wire", (16, 16, 16), (2, 2, 2), 8, None, "float8_e4m3fn"),
    ("uneven-bf16", (17, 16, 16), (2, 2, 2), 8, None, "bfloat16"),
    ("anisotropic", (16, 16, 16), (1, 2, 4), 8, None, None),
])
def test_fused_bit_parity_vs_composed(name, size, dim, ndev, dtypes, wire):
    spec = GridSpec(Dim3(*size), Dim3(*dim), Radius.constant(2))
    mesh = grid_mesh(Dim3(*dim), jax.devices()[:ndev])
    nq = len(dtypes) if dtypes else 2
    # fp8's finite range tops out at 448: scale the coordinate fixture
    # into range (out-of-range values map to NaN — the policy user data
    # must follow)
    scale = 2e-5 if wire == "float8_e4m3fn" else 1.0
    outs = {}
    for method, fused in ((Method.AXIS_COMPOSED, False),
                          (Method.REMOTE_DMA, True)):
        ex = HaloExchange(spec, mesh, method, wire_dtype=wire, fused=fused)
        out = ex(_state(spec, mesh, nq, dtypes, scale=scale))
        outs[fused] = _gather(out)
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b, err_msg=name)


def test_fused_make_loop_matches_repeated_composed():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    exf = HaloExchange(spec, mesh, Method.REMOTE_DMA, fused=True)
    exc = HaloExchange(spec, mesh, Method.AXIS_COMPOSED)
    sf = exf.make_loop(3)(_state(spec, mesh, 2))
    sc = exc.make_loop(3)(_state(spec, mesh, 2))
    for a, b in zip(_gather(sc), _gather(sf)):
        np.testing.assert_array_equal(a, b)


def test_fused_ctor_validation_is_loud():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    with pytest.raises(ValueError, match="REMOTE_DMA"):
        HaloExchange(spec, mesh, Method.AXIS_COMPOSED, fused=True)
    spec2 = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh2 = grid_mesh(Dim3(2, 2, 1), jax.devices()[:4])  # oversubscribed
    with pytest.raises(ValueError, match="single-resident"):
        HaloExchange(spec2, mesh2, Method.REMOTE_DMA, fused=True)


# -- the fused jacobi step loop ------------------------------------------------


def _run_jacobi(method, fused, size, iters=4):
    from stencil_tpu.api import DistributedDomain
    from stencil_tpu.ops.jacobi import (INIT_TEMP, make_jacobi_loop,
                                        sphere_sel)

    dd = DistributedDomain(*size)
    dd.set_radius(1)
    dd.set_methods(method)
    if fused:
        dd.set_fused_exchange(True)
    dd.set_devices(jax.devices()[:8])
    h = dd.add_data("t", "float32")
    dd.realize()
    dd.set_curr_global(h, np.full(size[::-1], INIT_TEMP, np.float32))
    sel = shard_blocks(sphere_sel(size), dd.spec, dd.mesh)
    loop = make_jacobi_loop(dd.halo_exchange, iters)
    c = dd.get_curr(h)
    n = jax.device_put(jnp.zeros_like(c), dd.sharding())
    c, _n = loop(c, n, sel)
    dd.set_curr(h, c)
    return dd.get_curr_global(h)


@pytest.mark.parametrize("size", [(16, 16, 16), (17, 19, 16)])
def test_fused_jacobi_step_parity(size):
    a = _run_jacobi(Method.AXIS_COMPOSED, False, size)
    b = _run_jacobi(Method.REMOTE_DMA, True, size)
    np.testing.assert_array_equal(a, b)


def test_fused_jacobi_emits_overlap_telemetry(tmp_path):
    from stencil_tpu.obs import telemetry

    sink = str(tmp_path / "m.jsonl")
    rec = telemetry.configure(metrics_out=sink, app="test",
                              heartbeat_thread=False)
    try:
        _run_jacobi(Method.REMOTE_DMA, True, (16, 16, 16), iters=2)
    finally:
        rec.close()
        telemetry._recorder = None
    import json

    recs = [json.loads(ln) for ln in open(sink) if ln.strip()]
    assert not any(telemetry.validate_record(r) for r in recs)
    spans = {r["name"] for r in recs if r["kind"] == "span"}
    for want in ("fused.pack", "fused.interior", "fused.dma_wait",
                 "fused.boundary"):
        assert want in spans, (want, sorted(spans))
    fracs = [r["value"] for r in recs if r["kind"] == "gauge"
             and r["name"] == "fused.overlap_fraction"]
    assert fracs and all(0.0 <= v <= 1.0 for v in fracs)
    # the variant tag splits aggregation (report._agg_key)
    from stencil_tpu.apps.report import _agg_key

    span_rec = next(r for r in recs if r["name"] == "fused.interior")
    assert _agg_key(span_rec) == "fused.interior[fused]"


# -- the interpret-mode mega-kernel --------------------------------------------


def test_fused_kernel_interpret_parity_vs_xla_step():
    """The all-self-wrap (single device) form of the mega-kernel — wrap
    fills + interior/boundary sweep — is bit-identical to the XLA jacobi
    step over two substeps of the double buffer."""
    from stencil_tpu.ops.fused_stencil import make_fused_jacobi_kernel
    from stencil_tpu.ops.jacobi import INIT_TEMP, sphere_sel

    size = (16, 16, 16)
    spec = GridSpec(Dim3(*size), Dim3(1, 1, 1), Radius.constant(1))
    plan = build_plan(spec, Dim3(1, 1, 1), REMOTE_DMA, fused=True)
    kern = make_fused_jacobi_kernel(spec, plan, interpret=True)
    p = spec.padded()
    off = spec.compute_offset()
    sl = (slice(off.z, off.z + 16), slice(off.y, off.y + 16),
          slice(off.x, off.x + 16))
    curr = np.zeros((p.z, p.y, p.x), np.float32)
    curr[sl] = INIT_TEMP
    sel = np.zeros((p.z, p.y, p.x), np.int32)
    sel[sl] = sphere_sel(size)
    nxt = np.zeros_like(curr)
    c, n = jnp.asarray(curr), jnp.asarray(nxt)
    for _ in range(2):  # two substeps through the double buffer
        c2, out = kern(c, n, jnp.asarray(sel))
        c, n = out, c2
    # the XLA step on the same single-device domain (fp32 throughout —
    # the fixture the other kernels' parity is pinned against)
    ref = _run_jacobi_single_device(size, iters=2)
    np.testing.assert_array_equal(np.asarray(c)[sl], ref)


def _run_jacobi_single_device(size, iters):
    from stencil_tpu.api import DistributedDomain
    from stencil_tpu.ops.jacobi import (INIT_TEMP, make_jacobi_loop,
                                        sphere_sel)

    dd = DistributedDomain(*size)
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:1])
    h = dd.add_data("t", "float32")
    dd.realize()
    dd.set_curr_global(h, np.full(size[::-1], INIT_TEMP, np.float32))
    sel = shard_blocks(sphere_sel(size), dd.spec, dd.mesh)
    loop = make_jacobi_loop(dd.halo_exchange, iters)
    c = dd.get_curr(h)
    n = jax.device_put(jnp.zeros_like(c), dd.sharding())
    c, _n = loop(c, n, sel)
    dd.set_curr(h, c)
    return dd.get_curr_global(h)


def test_fused_kernel_interpret_rejects_multi_device_form():
    from stencil_tpu.ops.fused_stencil import make_fused_jacobi_kernel

    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    plan = build_plan(spec, Dim3(2, 2, 2), REMOTE_DMA, fused=True)
    with pytest.raises(ValueError, match="interpret"):
        make_fused_jacobi_kernel(spec, plan, interpret=True)


# -- the fused astaroth loop (8-field MHD fold-in) ----------------------------


def _astaroth_fixture(n=16):
    from stencil_tpu.apps.astaroth import DEFAULT_CONF
    from stencil_tpu.astaroth import config as ac_config
    from stencil_tpu.astaroth.integrate import FIELDS

    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = n
    info.int_params["AC_ny"] = n
    info.int_params["AC_nz"] = n
    info.update_builtin_params()
    rng = np.random.RandomState(7)
    fields = {k: rng.randn(n, n, n) * 0.05 for k in FIELDS}
    fields["lnrho"] = fields["lnrho"] + 0.5
    return info, fields


def test_fused_astaroth_loop_matches_composed():
    """8-field MHD through the fused schedule: diagonal cross-derivative
    pencils ride the concurrent per-direction copies. Bit-identical to
    an AXIS_COMPOSED program with the same compute split; within float
    ulps of the monolithic composed step (whose single XLA program fuses
    across the pieces' boundaries)."""
    from stencil_tpu.astaroth.integrate import (FIELDS, make_astaroth_step,
                                                make_fused_astaroth_loop)

    n = 16
    info, fields = _astaroth_fixture(n)
    dt = 1e-3
    spec = GridSpec(Dim3(n, n, n), Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])

    def start():
        curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
        out = {k: shard_blocks(np.zeros((n, n, n)), spec, mesh)
               for k in FIELDS}
        return curr, out

    exf = HaloExchange(spec, mesh, Method.REMOTE_DMA, fused=True)
    loop = make_fused_astaroth_loop(exf, info, iters=2, dt=dt)
    curr, out = loop(*start())
    got = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    exc = HaloExchange(spec, mesh)
    step = make_astaroth_step(exc, info, dt=dt, overlap=True, iters=2)
    curr, out = step(*start())
    ref = {k: unshard_blocks(curr[k], spec) for k in FIELDS}
    for k in FIELDS:
        np.testing.assert_allclose(got[k], ref[k], rtol=1e-12, atol=1e-14,
                                   err_msg=k)


def test_fused_astaroth_rejects_unsupported_configs():
    from stencil_tpu.astaroth.integrate import make_fused_astaroth_loop

    info, _ = _astaroth_fixture(16)
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, Method.AXIS_COMPOSED)
    with pytest.raises(ValueError, match="fused=True"):
        make_fused_astaroth_loop(ex, info)


# -- fp8 wire tier -------------------------------------------------------------


def test_fp8_wire_ab_gates_bytes_and_e4m3_bound():
    from stencil_tpu.apps.bench_exchange import wire_ab, wire_gate

    ratio_thr, rel_bound = wire_gate("float8_e4m3fn")
    assert ratio_thr == pytest.approx(3.8)
    assert rel_bound == pytest.approx(2.0 ** -4)
    rows, ratio, err = wire_ab(
        16, 16, 16, iters=2, quantities=2, radius=2,
        wire="float8_e4m3fn", partition=(2, 2, 2),
        devices=jax.devices()[:8],
    )
    assert ratio >= ratio_thr            # >= 3.8x vs fp32
    assert err["max_rel_err"] <= rel_bound   # inside the e4m3 half-ulp
    assert err["max_rel_err"] > 0            # actually rounded
    # unchanged permute count between the native and compressed legs
    assert len({row["cp_count"] for row in rows}) == 1


def test_fp8_wire_ab_fused_transport():
    from stencil_tpu.apps.bench_exchange import wire_ab, wire_gate

    ratio_thr, rel_bound = wire_gate("float8_e4m3fn")
    rows, ratio, err = wire_ab(
        16, 16, 16, iters=2, quantities=2, radius=2,
        wire="float8_e4m3fn", partition=(2, 2, 2),
        devices=jax.devices()[:8], method=Method.REMOTE_DMA, fused=True,
    )
    assert ratio >= ratio_thr
    assert err["max_rel_err"] <= rel_bound
    assert all(row["cp_count"] == 0 for row in rows)  # 0 ppermutes


# -- conformance auditor + autotune round-trip --------------------------------


def test_verify_plan_audits_fused_lowering():
    from stencil_tpu.analysis import verify_plan as vp

    configs = vp.sweep_configs(size=16, radius=2, partitions=[(2, 2, 2)],
                               methods=[vp.FUSED_METHOD_LABEL],
                               qsets=[("float32", "float32")])
    res = vp.run_sweep(configs)
    assert res["checked"] == 1 and res["failed"] == 0
    checks = {c["name"]: c for c in res["verdicts"][0].checks}
    assert checks["collectives_per_exchange"]["actual"] == 0
    assert checks["census_bytes"]["actual"] == 0
    assert checks["dma_transfers"]["ok"]
    # the auditor actually trips when the DMA prediction drifts
    res = vp.run_sweep(configs, perturb_dmas=1)
    assert res["failed"] == 1


def test_verify_plan_default_sweep_includes_fused():
    from stencil_tpu.analysis import verify_plan as vp

    methods = {c["method"] for c in vp.sweep_configs()}
    assert vp.FUSED_METHOD_LABEL in methods


def test_autotune_persists_fused_variant_entry(tmp_path):
    from stencil_tpu.plan import db as plandb
    from stencil_tpu.plan.autotune import autotune

    db_path = str(tmp_path / "plans.json")
    kwargs = dict(ndev=8, platform="cpu", db_path=db_path, probe=False,
                  methods=("remote-dma",), variants=(FUSED_VARIANT,))
    res = autotune(Dim3(16, 16, 16), Radius.constant(1), ["float32"],
                   **kwargs)
    assert res.choice.is_fused and res.choice.method == "remote-dma"
    db = plandb.load_db(db_path)
    entry = plandb.lookup(db, res.config)
    assert PlanChoice.from_json(entry["choice"]).is_fused
    res2 = autotune(Dim3(16, 16, 16), Radius.constant(1), ["float32"],
                    **kwargs)
    assert res2.cache_hit and res2.choice.is_fused


def test_domain_realizes_tuned_fused_plan():
    from stencil_tpu.api import DistributedDomain

    dd = DistributedDomain(16, 16, 16, plan={
        "partition": [2, 2, 2], "method": "remote-dma",
        "batch_quantities": True, "multistep_k": 1,
        "kernel_variant": "fused",
    })
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    dd.add_data("t", "float32")
    dd.realize()
    assert dd.halo_exchange.fused
    assert dd.plan_meta()["choice"]["kernel_variant"] == "fused"
