"""Machine check: the compiled multi-block fast-path step's dataflow
permits comm/compute overlap (VERDICT r2 item 2b).

Each step is exported for the TPU platform (jax.export runs the full
Mosaic kernel lowering without hardware), then the StableHLO SSA graph is
analyzed: the collective_permutes must not transitively consume any
stencil-kernel result, and at least one kernel must be independent of
every permute. A negative control (the non-overlapped step) proves the
checker actually distinguishes the structures.

The export runs in a subprocess (scripts/export_overlap_hlo.py): JAX's
lowering recursion blows the stack when invoked under pytest's
assertion-rewritten frames, and a clean interpreter sidesteps it — the
same self-provisioning trick __graft_entry__.dryrun_multichip uses.
"""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "scripts", "export_overlap_hlo.py")


def _report(which: str) -> dict:
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    last = None
    for _ in range(2):  # lowering is host-heavy; retry once under load
        proc = subprocess.run(
            [sys.executable, _SCRIPT, which],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
            cwd=_REPO,
        )
        last = proc
        if proc.returncode == 0:
            return json.loads(proc.stdout.strip().splitlines()[-1])
    assert last.returncode == 0, f"{which}: {last.stderr[-3000:]}"


def test_jacobi_pallas_overlap_dataflow():
    rep = _report("jacobi-overlap")
    assert rep["n_permutes"] == 6
    assert rep["n_kernels"] == 1
    assert not rep["permutes_consume_kernel"]
    assert rep["n_kernels_independent_of_permutes"] == 1


def test_checker_flags_non_overlapped_step():
    """Negative control: exchange-then-sweep must FAIL the independence
    check (the kernel consumes permute results)."""
    rep = _report("jacobi-serial")
    assert rep["n_permutes"] == 6
    assert rep["n_kernels"] == 1
    assert rep["n_kernels_independent_of_permutes"] == 0


def test_jacobi_sidebuf_overlap_dataflow():
    """Multi-block tight-x (dim 2x2x1, out-of-line x side buffers): the
    full sweep kernel must be independent of the y-phase permutes AND the
    side-buffer permutes — the overlap structure survives the layout
    (VERDICT r3 item 5)."""
    rep = _report("jacobi-sidebuf")
    # 2 y-phase permutes + 2 x side-buffer permutes (x phase itself is a
    # zero-radius no-op; the z self-wrap fill takes the XLA slab path under
    # this layout, so the sweep is the only kernel)
    assert rep["n_permutes"] == 4
    assert rep["n_kernels"] == 1
    assert not rep["permutes_consume_kernel"]
    assert rep["n_kernels_independent_of_permutes"] == 1


def test_astaroth_pallas_overlap_dataflow():
    rep = _report("astaroth-overlap")
    # 6 permutes (2 per axis phase) TOTAL: the 8 fields' slabs ride packed
    # quantity-batched carriers (was 6 x 8 before ISSUE 5), and the packed
    # permutes still consume only pre-exchange data — the overlap
    # structure survives batching
    assert rep["n_permutes"] == 6
    # 3 substep kernels; substep 0 (pre-exchange input) is the free one
    assert rep["n_kernels"] == 3
    assert not rep["permutes_consume_kernel"]
    assert rep["n_kernels_independent_of_permutes"] == 1
