"""In-place self-wrap halo-fill kernels (interpret mode) vs direct numpy
slab placement — the pack/unpack-kernel correctness check (reference idiom:
test_cuda_pack.cu round-trips)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.ops.halo_fill import make_self_fill, self_fill_supported


@pytest.mark.parametrize("size,r", [((256, 136, 24), 1), ((140, 160, 40), 2), ((256, 144, 30), 3)])
@pytest.mark.parametrize("axis", ["x", "y", "z"])
def test_self_fill_matches_numpy(size, r, axis):
    sx, sy, sz = size
    spec = GridSpec(Dim3(sx, sy, sz), Dim3(1, 1, 1), Radius.constant(r))
    assert self_fill_supported(spec, axis, jnp.float32)
    p = spec.padded()
    o = spec.compute_offset()
    rng = np.random.RandomState(0)
    base = rng.rand(p.z, p.y, p.x).astype(np.float32)
    fill = make_self_fill(spec, axis, interpret=True)
    got = np.asarray(fill(jnp.asarray(base)))
    want = base.copy()
    if axis == "z":
        want[o.z - r : o.z] = base[o.z + sz - r : o.z + sz]
        want[o.z + sz : o.z + sz + r] = base[o.z : o.z + r]
    elif axis == "y":
        want[:, o.y - r : o.y, :] = base[:, o.y + sy - r : o.y + sy, :]
        want[:, o.y + sy : o.y + sy + r, :] = base[:, o.y : o.y + r, :]
    else:
        want[:, :, o.x - r : o.x] = base[:, :, o.x + sx - r : o.x + sx]
        want[:, :, o.x + sx : o.x + sx + r] = base[:, :, o.x : o.x + r]
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("axis", ["x", "y", "z"])
def test_self_fill_asymmetric_radius(axis):
    # rm != rp per side (the reference's per-direction Radius semantics)
    r = Radius.constant(0)
    lo = {"x": (-1, 0, 0), "y": (0, -1, 0), "z": (0, 0, -1)}[axis]
    hi = {"x": (1, 0, 0), "y": (0, 1, 0), "z": (0, 0, 1)}[axis]
    r.set_dir(lo, 1)
    r.set_dir(hi, 3)
    spec = GridSpec(Dim3(140, 160, 40), Dim3(1, 1, 1), r)
    assert self_fill_supported(spec, axis, jnp.float32)
    p = spec.padded()
    o = spec.compute_offset()
    rng = np.random.RandomState(3)
    base = rng.rand(p.z, p.y, p.x).astype(np.float32)
    got = np.asarray(make_self_fill(spec, axis, interpret=True)(jnp.asarray(base)))
    want = base.copy()
    # active send dir d fills the receiver's -d halo: radius.dir(-d) gates,
    # so lo-side halo width = r.dir(lo) = 1, hi-side = r.dir(hi) = 3
    sx, sy, sz = 140, 160, 40
    if axis == "z":
        want[o.z - 1 : o.z] = base[o.z + sz - 1 : o.z + sz]
        want[o.z + sz : o.z + sz + 3] = base[o.z : o.z + 3]
    elif axis == "y":
        want[:, o.y - 1 : o.y, :] = base[:, o.y + sy - 1 : o.y + sy, :]
        want[:, o.y + sy : o.y + sy + 3, :] = base[:, o.y : o.y + 3, :]
    else:
        want[:, :, o.x - 1 : o.x] = base[:, :, o.x + sx - 1 : o.x + sx]
        want[:, :, o.x + sx : o.x + sx + 3] = base[:, :, o.x : o.x + 3]
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("axis", ["x", "y", "z"])
def test_multi_quantity_fill_matches_per_quantity(axis):
    # fused nq=3 kernel must equal three independent single-quantity fills
    spec = GridSpec(Dim3(140, 160, 40), Dim3(1, 1, 1), Radius.constant(2))
    p = spec.padded()
    rng = np.random.RandomState(5)
    bases = [rng.rand(p.z, p.y, p.x).astype(np.float32) for _ in range(3)]
    single = make_self_fill(spec, axis, interpret=True)
    multi = make_self_fill(spec, axis, interpret=True, nq=3)
    got = multi(*[jnp.asarray(b) for b in bases])
    for q in range(3):
        want = np.asarray(single(jnp.asarray(bases[q])))
        np.testing.assert_array_equal(np.asarray(got[q]), want)


@pytest.mark.parametrize("axis", ["x", "y"])
@pytest.mark.parametrize("nq", [1, 2])
def test_self_fill_z_stack_matches_per_block(axis, nq):
    """``z_stack=c``: one fill over the (c*pz, py, px) view of a resident
    z-stack must equal the single-block fill applied to each stacked block
    (VERDICT r4 item 7 — the resident Pallas fast path)."""
    c = 3
    spec = GridSpec(Dim3(140, 32, 16), Dim3(1, 1, c), Radius.constant(2))
    assert self_fill_supported(spec, axis, jnp.float32, z_stack=c)
    p = spec.padded()
    rng = np.random.RandomState(7)
    bases = [rng.rand(c, p.z, p.y, p.x).astype(np.float32) for _ in range(nq)]
    single = make_self_fill(spec, axis, interpret=True, nq=nq)
    stacked = make_self_fill(spec, axis, interpret=True, nq=nq, z_stack=c)
    got = stacked(*[jnp.asarray(b.reshape(c * p.z, p.y, p.x)) for b in bases])
    got = (got,) if nq == 1 else got
    want = [
        single(*[jnp.asarray(b[j]) for b in bases]) for j in range(c)
    ]
    want = [(w,) if nq == 1 else w for w in want]
    for q in range(nq):
        w = np.stack([np.asarray(want[j][q]) for j in range(c)])
        np.testing.assert_array_equal(
            np.asarray(got[q]).reshape(c, p.z, p.y, p.x), w
        )


def test_self_fill_z_stack_gates():
    # the z fill copies planes across the stack boundary — unsupported
    spec = GridSpec(Dim3(140, 32, 16), Dim3(1, 1, 2), Radius.constant(2))
    assert not self_fill_supported(spec, "z", jnp.float32, z_stack=2)
    # a stack of thin blocks clears the streamed-batch depth gate
    thin = GridSpec(Dim3(128, 64, 4), Dim3(1, 1, 4), Radius.constant(1))
    assert not self_fill_supported(thin, "y", jnp.float32)
    assert self_fill_supported(thin, "y", jnp.float32, z_stack=4)


def test_exchange_blocks_fused_dispatch(monkeypatch):
    """The fused/rest split, chunking, and reshape wiring of
    HaloExchange.exchange_blocks — forced onto the fused path off-TPU by
    injecting interpret-mode fill kernels, with max_fill_group shrunk to
    exercise chunk boundaries (including a trailing nq=1 chunk)."""
    import jax

    from stencil_tpu.parallel import HaloExchange, grid_mesh
    import stencil_tpu.ops.halo_fill as HF
    from stencil_tpu.parallel.exchange import shard_blocks

    g = Dim3(140, 16, 16)
    spec = GridSpec(g, Dim3(1, 1, 1), Radius.constant(2))
    mesh = grid_mesh(spec.dim, jax.devices()[:1])
    ex = HaloExchange(spec, mesh)
    # inject interpret-mode fills (the TPU gate would otherwise leave
    # _self_fills empty on CPU and the dispatch under test never runs)
    ex.__dict__["_self_fills"] = {
        a: HF.make_self_fill(spec, a, interpret=True) for a in ("x", "y", "z")
    }
    ex.__dict__["_multi_fills"] = {
        (a, n): HF.make_self_fill(spec, a, interpret=True, nq=n)
        for a in ("x", "y", "z")
        for n in (1, 2, 3, 5)
    }
    monkeypatch.setattr(HF, "max_fill_group", lambda _spec: 2)

    rng = np.random.RandomState(9)
    coords = (
        np.arange(g.z)[:, None, None] * 10000
        + np.arange(g.y)[None, :, None] * 100
        + np.arange(g.x)[None, None, :]
    )
    state = {i: shard_blocks(coords.astype(np.float32), spec, mesh) for i in range(5)}
    state["f64"] = shard_blocks(coords.astype(np.float64), spec, mesh)
    out = ex.exchange_blocks(state)

    off = spec.compute_offset()
    r = spec.radius
    for key, arr in out.items():
        blk = np.asarray(jax.device_get(arr))[0, 0, 0]
        bad = checked = 0
        for zz in range(-r.z(-1), g.z + r.z(1)):
            for yy in range(-r.y(-1), g.y + r.y(1)):
                for xx in range(-r.x(-1), g.x + r.x(1)):
                    if 0 <= zz < g.z and 0 <= yy < g.y and 0 <= xx < g.x:
                        continue
                    want = (zz % g.z) * 10000 + (yy % g.y) * 100 + (xx % g.x)
                    checked += 1
                    bad += blk[off.z + zz, off.y + yy, off.x + xx] != want
        assert checked > 0 and bad == 0, (key, bad)


def test_exchange_blocks_fused_dispatch_resident(monkeypatch):
    """The z-stacked fused dispatch (VERDICT r4 item 7): a (cz, 1, 1)
    resident shard must route the x/y self-wrap phases through z_stack
    fill kernels (folded (cz*pz, py, px) view) composed with the resident
    z-shift phase — forced on-path off-TPU by injecting interpret-mode
    z_stack fills, with max_fill_group shrunk to hit the nq chunking."""
    import jax

    from stencil_tpu.parallel import HaloExchange, grid_mesh
    import stencil_tpu.ops.halo_fill as HF
    from stencil_tpu.parallel.exchange import shard_blocks

    g = Dim3(140, 16, 16)
    cz = 2
    spec = GridSpec(g, Dim3(1, 1, cz), Radius.constant(2))
    mesh = grid_mesh(Dim3(1, 1, 1), jax.devices()[:1])
    ex = HaloExchange(spec, mesh)
    assert ex.oversubscribed and ex.resident.z == cz
    assert ex._fill_shape() == (cz * spec.padded().z, spec.padded().y,
                                spec.padded().x)
    # z is multi-block (resident shifts); only x/y self-wrap fills exist
    ex.__dict__["_self_fills"] = {
        a: HF.make_self_fill(spec, a, interpret=True, z_stack=cz)
        for a in ("x", "y")
    }
    ex.__dict__["_multi_fills"] = {
        (a, n): HF.make_self_fill(spec, a, interpret=True, nq=n, z_stack=cz)
        for a in ("x", "y")
        for n in (1, 2, 3, 5)
    }
    monkeypatch.setattr(HF, "max_fill_group", lambda _spec: 2)

    coords = (
        np.arange(g.z)[:, None, None] * 10000
        + np.arange(g.y)[None, :, None] * 100
        + np.arange(g.x)[None, None, :]
    )
    state = {i: shard_blocks(coords.astype(np.float32), spec, mesh) for i in range(5)}
    state["f64"] = shard_blocks(coords.astype(np.float64), spec, mesh)
    out = ex.exchange_blocks(state)

    off = spec.compute_offset()
    r = spec.radius
    bz = g.z // cz
    for key, arr in out.items():
        stacked = np.asarray(jax.device_get(arr))
        for j in range(cz):
            blk = stacked[j, 0, 0]
            z0 = j * bz
            bad = checked = 0
            for zz in range(-r.z(-1), bz + r.z(1)):
                for yy in range(-r.y(-1), g.y + r.y(1)):
                    for xx in range(-r.x(-1), g.x + r.x(1)):
                        if 0 <= zz < bz and 0 <= yy < g.y and 0 <= xx < g.x:
                            continue
                        want = (
                            ((z0 + zz) % g.z) * 10000
                            + (yy % g.y) * 100
                            + (xx % g.x)
                        )
                        checked += 1
                        bad += blk[off.z + zz, off.y + yy, off.x + xx] != want
            assert checked > 0 and bad == 0, (key, j, bad)


def test_max_fill_group_positive():
    from stencil_tpu.ops.halo_fill import max_fill_group

    spec = GridSpec(Dim3(256, 256, 256), Dim3(1, 1, 1), Radius.constant(3))
    assert max_fill_group(spec) >= 4


def test_self_fill_gates():
    # float64 and unaligned layouts must fall back
    spec = GridSpec(Dim3(64, 64, 16), Dim3(1, 1, 1), Radius.constant(1))
    assert not self_fill_supported(spec, "x", jnp.float64)
    spec_u = GridSpec(Dim3(64, 64, 16), Dim3(1, 1, 1), Radius.constant(1), aligned=False)
    assert not self_fill_supported(spec_u, "x", jnp.float32)
    # zero radius on the axis: nothing to fill
    r = Radius.constant(0)
    r.set_dir((-1, 0, 0), 1)
    r.set_dir((1, 0, 0), 1)
    spec_x = GridSpec(Dim3(64, 64, 16), Dim3(1, 1, 1), r)
    assert not self_fill_supported(spec_x, "y", jnp.float32)


def test_self_fill_gates_thin_z():
    # x (TZB=4) and y (TZB=8) kernels stream fixed-depth z batches; blocks
    # thinner than one batch must fall back (z0 would go negative)
    spec = GridSpec(Dim3(128, 64, 4), Dim3(1, 1, 1), Radius.constant(1))
    assert spec.padded().z < 8
    assert not self_fill_supported(spec, "y", jnp.float32)
    thin = GridSpec(Dim3(128, 64, 2), Dim3(1, 1, 1), Radius.constant(1))
    if thin.padded().z < 4:
        assert not self_fill_supported(thin, "x", jnp.float32)
    # z kernel copies whole planes regardless of depth
    assert self_fill_supported(spec, "z", jnp.float32)


def test_self_fill_gates_vmem_budget():
    # huge planes exceed the VMEM scratch budget; must fall back instead of
    # failing Mosaic compilation inside HaloExchange
    spec = GridSpec(Dim3(2048, 2048, 64), Dim3(1, 1, 1), Radius.constant(3))
    assert not self_fill_supported(spec, "z", jnp.float32)  # r*py*px*4 ~ 50 MB
    # x shrinks its batch depth down to 2 and still fits here...
    assert self_fill_supported(spec, "x", jnp.float32)
    # ...but a 4096-row plane exceeds the budget even at depth 2
    huge = GridSpec(Dim3(4096, 4096, 64), Dim3(1, 1, 1), Radius.constant(3))
    assert not self_fill_supported(huge, "x", jnp.float32)
