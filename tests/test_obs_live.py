"""The in-run sentinel (stencil_tpu/obs/live.py): streaming trimean ±
MAD windows, the anomaly state machine, the telemetry vocabulary, and
the run_guarded wiring.

The ISSUE-12 online-window edge cases are pinned here: warmup below
``min_history`` never fires, non-finite samples are dropped at
insertion (the metrics-ingest rule), window eviction keeps the band
anchored on recent history, and an anomaly re-arms after
``anomaly.cleared``.
"""

import io
import json
import time

import jax.numpy as jnp
import pytest

from stencil_tpu.fault import chunk_plan, run_guarded
from stencil_tpu.obs import ledger, telemetry
from stencil_tpu.obs.live import (
    LiveSentinel,
    OnlineWindow,
    base_metric,
    default_direction,
)


def _records(sink: io.StringIO):
    return [json.loads(line) for line in sink.getvalue().splitlines()]


def _rec(sink):
    return telemetry.Recorder(sink=sink)


# -- direction authority (perf_tool re-imports these) -------------------------


def test_direction_authority_is_shared_with_perf_tool():
    from stencil_tpu.apps import perf_tool

    # one authority, two importers: the cross-run and in-run sentinels
    # must never diverge on what "worse" means
    assert perf_tool.default_direction is default_direction
    assert perf_tool.base_metric is base_metric
    assert default_direction("step.latency_s", "s") == "lower"
    assert default_direction("step.latency_s[16x16x16,float32]",
                             "s") == "lower"
    assert default_direction("jacobi.mcells_per_s", None) == "higher"


# -- OnlineWindow edge cases --------------------------------------------------


def test_warmup_below_min_history_never_fires():
    w = OnlineWindow("step.latency_s", min_history=5, rel_tol=0.01,
                     mad_k=0.0, unit="s")
    # wildly varying samples — but below min_history NOTHING is judged
    for i, v in enumerate([0.1, 100.0, 0.001, 50.0]):
        assert w.observe(v, i) is None
    assert w.active is None and w.detected == 0


def test_nonfinite_samples_dropped_at_insertion():
    w = OnlineWindow("step.latency_s", min_history=3, rel_tol=1.0, unit="s")
    for i, v in enumerate([0.1, float("nan"), 0.1, float("inf"), 0.1]):
        assert w.observe(v, i) is None
    # only the three finite samples entered the window
    assert len(w.samples) == 3
    # and a NaN after warmup is dropped too, never judged as anomalous
    assert w.observe(float("nan"), 9) is None
    assert w.detected == 0


def test_band_uses_the_perf_tool_formula():
    w = OnlineWindow("step.latency_s", min_history=4, mad_k=3.0,
                     rel_tol=0.5, abs_tol=0.0, unit="s")
    vals = [1.0, 1.1, 0.9, 1.0]
    for i, v in enumerate(vals):
        w.observe(v, i)
    center, lo, hi = w.band()
    assert center == pytest.approx(ledger.trimean(vals))
    spread = 3.0 * ledger.mad(vals)
    # high edge: the perf_tool formula verbatim
    assert hi == pytest.approx(center + max(spread, 0.5 * abs(center)))
    # low edge: the rel component is ratio-symmetric (lo >= center/1.5
    # at rel_tol 0.5) so a wide band keeps a positive floor
    assert lo == pytest.approx(
        center - max(spread, abs(center) * 0.5 / 1.5))


def test_direction_aware_a_fast_sample_never_trips_a_seconds_key():
    w = OnlineWindow("step.latency_s", min_history=3, rel_tol=0.1, unit="s")
    for i in range(4):
        w.observe(1.0, i)
    # dramatically FASTER is an improvement on a "lower" key, not an anomaly
    assert w.observe(0.001, 5) is None
    assert w.detected == 0
    # on a throughput key the same drop DOES trip (direction "higher")
    t = OnlineWindow("agg.mcells_per_s", min_history=3, rel_tol=0.1)
    for i in range(4):
        t.observe(100.0, i)
    ev = t.observe(1.0, 5)
    assert ev and ev["event"] == "detected"


def test_window_eviction_keeps_band_anchored_on_recent_history():
    # a slow in-band drift walks the window forward: after eviction the
    # band centers on RECENT samples, so a value far from the original
    # regime but near the current one is healthy
    w = OnlineWindow("step.latency_s", window=8, min_history=4,
                     mad_k=3.0, rel_tol=0.3, unit="s")
    v, step = 1.0, 0
    while v < 4.0:
        assert w.observe(v, step) is None, f"in-band drift fired at {v}"
        v *= 1.05  # each step within 30% of the rolling center
        step += 1
    center, _lo, hi = w.band()
    # the original regime (1.0) is long evicted: the band no longer
    # admits it, and 4.0-era values are the new normal
    assert center > 2.5
    assert w.observe(center, step) is None
    # ...while the band still catches a real excursion from the NEW center
    ev = w.observe(center * 10, step + 1)
    assert ev and ev["event"] == "detected"


def test_anomalous_samples_do_not_normalize_the_band():
    w = OnlineWindow("step.latency_s", window=16, min_history=4,
                     rel_tol=0.5, clear_after=2, unit="s")
    for i in range(5):
        w.observe(1.0, i)
    n_before = len(w.samples)
    assert w.observe(50.0, 10)["event"] == "detected"
    for i in range(11, 30):
        assert w.observe(50.0, i) is None  # still anomalous, no re-emit
    # the excursion never entered the window: the band stayed anchored
    assert len(w.samples) == n_before
    assert w.active is not None and w.detected == 1


def test_clear_requires_consecutive_in_band_and_rearms():
    w = OnlineWindow("step.latency_s", min_history=3, rel_tol=0.5,
                     clear_after=2, unit="s")
    for i in range(4):
        w.observe(1.0, i)
    assert w.observe(10.0, 4)["event"] == "detected"
    assert w.observe(1.0, 5) is None          # streak 1: not yet cleared
    assert w.observe(10.0, 6) is None         # excursion resets the streak
    assert w.active is not None
    assert w.observe(1.0, 7) is None
    ev = w.observe(1.0, 8)
    assert ev and ev["event"] == "cleared" and ev["since_step"] == 4
    # re-armed: the next excursion fires a fresh detection
    ev2 = w.observe(10.0, 9)
    assert ev2 and ev2["event"] == "detected"
    assert w.detected == 2 and w.cleared == 1


def test_window_must_hold_min_history():
    # a ValueError, not an assert: -O must not turn this into a window
    # that silently can never fire
    with pytest.raises(ValueError):
        OnlineWindow("k", window=2, min_history=5)


def test_higher_direction_trips_under_the_wide_default_band():
    # the low edge's relative component is ratio-symmetric: with the
    # default rel_tol 3.0 a positive throughput keeps a POSITIVE floor
    # (center/4), so a collapse still trips — the additive form would
    # put lo below zero and the "higher" direction could never fire
    w = OnlineWindow("agg.mcells_per_s", min_history=4)  # default knobs
    for i in range(5):
        w.observe(100.0, i)
    center, lo, hi = w.band()
    assert lo > 0
    assert lo == pytest.approx(center / 4)
    assert w.observe(lo * 0.5, 6)["event"] == "detected"
    # the high edge keeps the perf_tool formula verbatim
    assert hi == pytest.approx(center * 4)


def test_validate_config_catches_bad_knobs():
    from stencil_tpu.obs.live import validate_config

    assert validate_config({}) == []
    assert validate_config({"*": {"rel_tol": 1.0, "window": 8,
                                  "min_history": 4}}) == []
    assert validate_config("x")
    assert validate_config({"k": 3})
    assert validate_config({"k": {"rel_tolerance": 1.0}})  # unknown knob
    assert validate_config({"k": {"min_history": 0}})
    assert validate_config({"k": {"rel_tol": float("nan")}})
    assert validate_config({"k": {"direction": "sideways"}})
    assert validate_config({"k": {"window": 2, "min_history": 8}})
    # the relation check sees the MERGED knobs: "*" defaults cascade
    assert validate_config({"*": {"min_history": 8},
                            "k": {"window": 2}})
    assert validate_config({"*": {"min_history": 8, "window": 16},
                            "k": {"window": 16}}) == []


# -- LiveSentinel: vocabulary, config resolution, replan hook -----------------


def test_sentinel_emits_schema_valid_vocabulary():
    sink = io.StringIO()
    s = LiveSentinel({"*": {"min_history": 3, "rel_tol": 0.5,
                            "clear_after": 1}}, rec=_rec(sink))
    for i in range(4):
        s.observe("step.latency_s", 1.0, step=i, unit="s")
    s.observe("step.latency_s", 10.0, step=4, unit="s")
    s.observe("step.latency_s", 1.0, step=5, unit="s")
    recs = _records(sink)
    names = [r["name"] for r in recs]
    assert names == ["anomaly.detected", "replan.requested",
                     "anomaly.cleared"]
    for r in recs:
        assert telemetry.validate_record(r) == [], r
    det = recs[0]
    assert det["metric"] == "step.latency_s" and det["step"] == 4
    assert det["lo"] < det["hi"] and det["direction"] == "lower"
    assert recs[1]["reason"] == "anomaly:step.latency_s"
    assert recs[2]["since_step"] == 4


def test_sentinel_replan_hook_fires_and_never_raises():
    sink = io.StringIO()
    seen = []

    def hook(ev):
        seen.append(ev)
        raise RuntimeError("a broken hook must not kill the run")

    s = LiveSentinel({"*": {"min_history": 2, "rel_tol": 0.5}},
                     rec=_rec(sink), on_replan=hook)
    for i in range(3):
        s.observe("k_s", 1.0, step=i, unit="s")
    s.observe("k_s", 10.0, step=3, unit="s")  # must not raise
    assert len(seen) == 1 and seen[0]["metric"] == "k_s"


def test_sentinel_replan_disabled():
    sink = io.StringIO()
    s = LiveSentinel({"*": {"min_history": 2, "rel_tol": 0.5}},
                     rec=_rec(sink), replan=False)
    for i in range(3):
        s.observe("k_s", 1.0, step=i, unit="s")
    s.observe("k_s", 10.0, step=3, unit="s")
    names = [r["name"] for r in _records(sink)]
    assert "replan.requested" not in names


def test_sentinel_config_resolution_tagged_key_inherits_base():
    s = LiveSentinel({"*": {"min_history": 9},
                      "step.latency_s": {"min_history": 2, "rel_tol": 0.25}})
    w = s._window("step.latency_s[16x16x16,float32,jacobi]", "s")
    # the tagged campaign key inherits the base metric's overrides,
    # exactly like perf_tool leg config
    assert w.min_history == 2 and w.rel_tol == 0.25
    # a fully-tagged override wins over the base
    s2 = LiveSentinel({"step.latency_s": {"rel_tol": 0.25},
                       "step.latency_s[a]": {"rel_tol": 0.75}})
    assert s2._window("step.latency_s[a]", "s").rel_tol == 0.75


# -- run_guarded wiring -------------------------------------------------------


def test_run_guarded_feeds_sentinel_and_detects_midrun(tmp_path):
    """The tentpole pin: a slow chunk cycle is detected DURING the run
    (the sentinel sees the whole step+inject+health+save cycle, so an
    injected slowdown is visible even though the step span is clean)."""
    sink = io.StringIO()
    rec = telemetry.Recorder(sink=sink)
    old = telemetry._recorder
    telemetry._recorder = rec
    try:
        sent = LiveSentinel({"*": {"min_history": 3, "rel_tol": 1.0,
                                   "clear_after": 2}}, rec=rec)

        def step_fn(st, k):
            # steps 1..5 fast; step 6's chunk sleeps (a stand-in for the
            # slow@N injection, whose sleep also lands inside the cycle)
            time.sleep(0.08 if int(st["q"][0]) + k == 6 else 0.002)
            return {"q": st["q"] + k}

        state, done = run_guarded(
            {"q": jnp.zeros((2,))}, start=0, iters=10,
            plan_fn=lambda s: chunk_plan(s, 10, 1),
            step_fn=step_fn, sentinel=sent)
        assert done == 10
        recs = _records(sink)
        det = [r for r in recs if r["name"] == "anomaly.detected"]
        clr = [r for r in recs if r["name"] == "anomaly.cleared"]
        rep = [r for r in recs if r["name"] == "replan.requested"]
        assert len(det) == 1 and det[0]["step"] == 6
        assert len(rep) == 1
        assert len(clr) == 1 and clr[0]["step"] == 8  # clear_after=2
        assert sent.summary() == {"active": [], "detected": 1, "cleared": 1}
    finally:
        telemetry._recorder = old


def test_status_health_accumulates_across_guarded_segments(tmp_path):
    """A campaign calls run_guarded once per slot segment on one shared
    status writer — the health counters must accumulate, never regress
    mid-campaign."""
    from stencil_tpu.fault import HealthGuard
    from stencil_tpu.obs.status import StatusWriter, read_status

    path = str(tmp_path / "status.json")
    status = StatusWriter(path, app="t", run="r")
    guard = HealthGuard(every=1)

    def step_fn(st, k):
        return {"q": st["q"] + k}

    for seg in range(2):
        run_guarded({"q": jnp.zeros((2,))}, start=0, iters=3,
                    plan_fn=lambda s: chunk_plan(s, 3, 1),
                    step_fn=step_fn, guard=guard, status=status)
    doc = read_status(path)
    # 3 checks per segment; the second segment adds to the first
    assert doc["health"]["checks"] == 6


def test_anomaly_count_gauge_ingests_into_the_ledger(tmp_path):
    """The cross-run hook: live.anomaly_count rides the standard
    metrics-JSONL gauge ingest, so in-run instability shows in trends."""
    sink = io.StringIO()
    rec = telemetry.Recorder(sink=sink)
    rec.meta("config", config={"app": "t"})
    rec.gauge("live.anomaly_count", 2.0, phase="live")
    entries = ledger.entries_from_metrics_records(
        _records(sink), label="runX", platform="cpu")
    by_metric = {e["metric"]: e for e in entries}
    assert by_metric["live.anomaly_count"]["value"] == 2.0
    path = str(tmp_path / "ledger.jsonl")
    assert ledger.append_entries(path, entries) == len(entries)
