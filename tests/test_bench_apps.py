"""Smoke tests for the benchmark apps (small sizes, virtual CPU mesh) —
ensures each produces the reference-format CSV and sane numbers."""

import jax
import numpy as np
import pytest

from stencil_tpu.apps import (
    bench_exchange,
    bench_pack,
    bench_qap,
    exchange_strong,
    exchange_weak,
    measure_overlap,
    pingpong,
)


def test_exchange_weak_csv():
    r = exchange_weak.run(8, 8, 8, iters=4, devices=jax.devices()[:8])
    row = exchange_weak.csv_row(r)
    parts = row.split(",")
    assert parts[0] == "exchange"
    assert len(parts) == 16
    assert r["trimean_s"] > 0
    assert r["bytes_logical"] > 0
    # weak scaling grew the domain for 8 devices
    assert r["x"] * r["y"] * r["z"] == 8 * 8 * 8 * 8


def test_exchange_strong_fixed_domain():
    r = exchange_strong.run(16, 16, 16, iters=2, devices=jax.devices()[:8])
    assert (r["x"], r["y"], r["z"]) == (16, 16, 16)


def test_exchange_weak_placement_flags():
    r = exchange_weak.run(8, 8, 8, iters=2, naive=True, devices=jax.devices()[:8])
    assert r["naive"] == 1


def test_bench_exchange_sweep():
    rows = bench_exchange.run(16, 16, 16, iters=2, devices=jax.devices()[:8])
    assert len(rows) == 5
    names = [r["config"].split("/")[1] for r in rows]
    assert names == ["px", "x", "faces", "face&edge", "uniform"]
    for r in rows:
        assert r["bytes"] > 0 and r["trimean_s"] > 0
    # faces-only moves more halo bytes than x-only
    assert rows[2]["bytes"] > rows[1]["bytes"]


def test_bench_pack_rows():
    rows = bench_pack.run(16, 16, 16, radius=2, iters=3)
    assert len(rows) == 26
    face = next(r for r in rows if r["dir"] == (1, 0, 0))
    corner = next(r for r in rows if r["dir"] == (1, 1, 1))
    assert face["bytes"] == 2 * 16 * 16 * 4
    assert corner["bytes"] == 2 * 2 * 2 * 4


def test_bench_qap_rows():
    rows = bench_qap.run(sizes=(4,), catch_sizes=(8,), timeout_s=1.0)
    assert any(r["solver"] == "exact-native" for r in rows) or any(
        r["solver"] == "exact-py" for r in rows
    )
    for r in rows:
        assert np.isfinite(r["cost"]) and r["s"] >= 0


def test_measure_overlap_row(tmp_path):
    r = measure_overlap.run(
        8, 8, 8, iters=2, rounds=2, devices=jax.devices()[:8],
        trace_dir=str(tmp_path / "trace"),
    )
    row = measure_overlap.csv_row(r)
    assert row.startswith("measure_overlap,8,")
    for k in ("compute_s", "exchange_s", "serial_s", "overlap_s"):
        assert r[k] > 0
    # serial = exchange + full sweep, so it cannot beat the compute floor
    assert r["serial_s"] > r["compute_s"] * 0.5
    # the profiler trace artifact was written
    assert any((tmp_path / "trace").rglob("*")), "no trace files written"


def test_pingpong_rows():
    rows = pingpong.run(min_bytes=8, max_bytes=128, iters=3, devices=jax.devices()[:2])
    assert len(rows) >= 2
    for r in rows:
        assert r["latency_us"] > 0 and r["gb_per_s"] > 0
