"""Smoke tests for the benchmark apps (small sizes, virtual CPU mesh) —
ensures each produces the reference-format CSV and sane numbers."""

import jax
import numpy as np
import pytest

from stencil_tpu.apps import (
    bench_alltoall,
    bench_exchange,
    bench_link,
    bench_pack,
    bench_qap,
    exchange_strong,
    exchange_weak,
    machine_info,
    measure_overlap,
    pingpong,
)


def test_exchange_weak_csv():
    r = exchange_weak.run(8, 8, 8, iters=4, devices=jax.devices()[:8])
    row = exchange_weak.csv_row(r)
    parts = row.split(",")
    assert parts[0] == "exchange"
    assert len(parts) == 16
    assert r["trimean_s"] > 0
    assert r["bytes_logical"] > 0
    # weak scaling grew the domain for 8 devices
    assert r["x"] * r["y"] * r["z"] == 8 * 8 * 8 * 8


def test_exchange_strong_fixed_domain():
    r = exchange_strong.run(16, 16, 16, iters=2, devices=jax.devices()[:8])
    assert (r["x"], r["y"], r["z"]) == (16, 16, 16)


def test_exchange_weak_placement_flags():
    r = exchange_weak.run(8, 8, 8, iters=2, naive=True, devices=jax.devices()[:8])
    assert r["naive"] == 1


def test_bench_exchange_sweep():
    rows = bench_exchange.run(16, 16, 16, iters=2, devices=jax.devices()[:8])
    assert len(rows) == 5
    names = [r["config"].split("/")[1] for r in rows]
    assert names == ["px", "x", "faces", "face&edge", "uniform"]
    for r in rows:
        assert r["bytes"] > 0 and r["trimean_s"] > 0
    # faces-only moves more halo bytes than x-only
    assert rows[2]["bytes"] > rows[1]["bytes"]


def test_bench_exchange_method_ablation():
    rows, agree = bench_exchange.ablate(16, 16, 16, iters=2, devices=jax.devices()[:8])
    assert [r["config"].split("method=")[1] for r in rows] == [
        "axis-composed", "direct26", "auto-spmd", "remote-dma",
    ]
    # identical logical bytes — only the movement strategy differs
    assert len({r["bytes"] for r in rows}) == 1 and rows[0]["bytes"] > 0
    # the CI gate: all four strategies deliver bit-identical halos
    assert agree
    # census columns: with quantity batching (the default) the manual
    # methods' counts are Q-independent — the harness's 4 quantities ride
    # packed carriers: composed 6 total, direct26 one per direction —
    # auto >= 1 synthesized permute and nothing else (the partitioner
    # still emits per-quantity permutes; its schedule is its own).
    # remote-dma bypasses the collective path entirely: 0 ppermutes,
    # 0 bytes anywhere a census can see (the ISSUE-10 pin)
    by = {r["config"].split("method=")[1]: r for r in rows}
    assert by["axis-composed"]["cp_count"] == 6
    assert by["direct26"]["cp_count"] == 26
    assert by["auto-spmd"]["cp_count"] >= 1
    assert by["remote-dma"]["cp_count"] == 0
    assert by["remote-dma"]["cp_bytes"] == 0
    assert all(r["other_collectives"] == 0 for r in rows)
    assert all(r["cp_bytes"] > 0 for r in rows
               if "remote-dma" not in r["config"])
    # the ablation CSV has the census columns
    assert bench_exchange.ablate_row(rows[0]).count(",") == \
        bench_exchange.ablate_header().count(",")


def test_bench_pack_rows():
    rows = bench_pack.run(16, 16, 16, radius=2, iters=3)
    assert len(rows) == 26
    face = next(r for r in rows if r["dir"] == (1, 0, 0))
    corner = next(r for r in rows if r["dir"] == (1, 1, 1))
    assert face["bytes"] == 2 * 16 * 16 * 4
    assert corner["bytes"] == 2 * 2 * 2 * 4


def test_bench_qap_rows():
    rows = bench_qap.run(sizes=(4,), catch_sizes=(8,), timeout_s=1.0)
    assert any(r["solver"] == "exact-native" for r in rows) or any(
        r["solver"] == "exact-py" for r in rows
    )
    for r in rows:
        assert np.isfinite(r["cost"]) and r["s"] >= 0


def test_machine_info_report():
    r = machine_info.run(devices=jax.devices()[:8], size=64)
    text = machine_info.report(r)
    assert "8 device(s)" in text
    assert r["dist"].shape == (8, 8)
    assert r["partition"].flatten() == 8
    # distance diagonal is self-distance, off-diagonal same-process
    assert np.allclose(np.diag(r["dist"]), 0.1)


def test_bench_link_rows():
    rows = bench_link.run(sizes_kb=(16,), devices=jax.devices()[:8], iters=3, rounds=2)
    # 2x2x2 partition: all three axes measured
    assert {r["axis"] for r in rows} == {"x", "y", "z"}
    for r in rows:
        assert r["gb_per_s"] > 0 and r["devices_on_axis"] == 2
        assert csv_ok(bench_link.csv_row(r), "bench_link")


def test_bench_alltoall_rows():
    rows = bench_alltoall.run(sizes_kb=(16,), devices=jax.devices()[:4], iters=2, rounds=2)
    assert {r["strategy"] for r in rows} == {"all_to_all", "ring"}
    for r in rows:
        assert r["gb_per_s"] > 0
        assert csv_ok(bench_alltoall.csv_row(r), "bench_alltoall")


def test_alltoall_strategies_agree():
    # both strategies must implement the same transpose: seed distinct
    # payloads and check all_to_all vs ring deliver identical results
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()[:4]
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("i",))
    x = jnp.arange(n * n * 8, dtype=jnp.float32).reshape(n, n, 8)
    xs = jax.device_put(x, NamedSharding(mesh, P("i", None, None)))
    outs = {}
    for name, make in (("a2a", bench_alltoall._alltoall_body),
                       ("ring", bench_alltoall._ring_body)):
        fn = jax.jit(
            jax.shard_map(make(n), mesh=mesh, in_specs=P("i", None, None),
                          out_specs=P("i", None, None))
        )
        outs[name] = np.asarray(jax.device_get(fn(xs)))
    np.testing.assert_array_equal(outs["a2a"], outs["ring"])
    # and it is the blockwise transpose of the input
    want = np.asarray(x).reshape(n, n, 8).transpose(1, 0, 2)
    np.testing.assert_array_equal(outs["a2a"], want)


def csv_ok(row: str, prefix: str) -> bool:
    return row.startswith(prefix + ",") and len(row.split(",")) >= 5


def test_measure_overlap_row(tmp_path):
    r = measure_overlap.run(
        8, 8, 8, iters=2, rounds=2, devices=jax.devices()[:8],
        trace_dir=str(tmp_path / "trace"),
    )
    row = measure_overlap.csv_row(r)
    assert row.startswith("measure_overlap,8,")
    for k in ("compute_s", "exchange_s", "serial_s", "overlap_s"):
        assert r[k] > 0
    # serial = exchange + full sweep, so it cannot beat the compute floor
    assert r["serial_s"] > r["compute_s"] * 0.5
    # the profiler trace artifact was written
    assert any((tmp_path / "trace").rglob("*")), "no trace files written"


def test_pingpong_rows():
    rows = pingpong.run(min_bytes=8, max_bytes=128, iters=3, devices=jax.devices()[:2])
    assert len(rows) >= 2
    for r in rows:
        assert r["latency_us"] > 0 and r["gb_per_s"] > 0


def test_weak_scaling_harness_smoke():
    from stencil_tpu.apps import weak_scaling

    res = weak_scaling.run(
        devices=jax.devices()[:8],
        iters=2, jacobi_iters=2, overlap_rounds=1,
        per_chip=weak_scaling.Dim3(16, 16, 16),
        exw_per_chip=weak_scaling.Dim3(16, 16, 16),
        config2_global=weak_scaling.Dim3(16, 16, 16),
    )
    lines = weak_scaling.csv_rows(res)
    assert lines[0] == weak_scaling.CSV_HEADER
    assert len(lines) == 5
    names = [l.split(",")[0] for l in lines[1:]]
    assert names == [
        "config2_exchange", "config3_exchange_weak",
        "config5_jacobi_overlap", "config5_hidden_frac",
    ]
    for line in lines[1:]:
        parts = line.split(",")
        assert int(parts[4]) == 8
        assert float(parts[5]) > 0  # seconds
