"""Method.AUTO_SPMD — the XLA-synthesized halo exchange (bench_mpi_pack
ablation, reference: bin/bench_mpi_pack.cu:18-80).

The strategy writes NO collectives: the halo fill is a globally-sharded
shifted-slice program and the SPMD partitioner emits the
collective-permutes. These tests pin the two claims the ablation rests on:

1. bit parity with the manual AXIS_COMPOSED exchange (same send-extent
   rule, periodic wrap, radius shapes, uneven partitions,
   oversubscription) — for the exchange alone and for the full jacobi
   step built on it;
2. the collective census: the auto path really emits collective-permutes
   (>= 1, and nothing else — no partitioner all-gather regressions), while
   the manual composed path emits exactly 6 per exchange and DIRECT26 one
   per active direction.

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

from test_exchange import check_halos, coord_field


def _exchange(size, dim, radius, method, mesh_dim=None, ndev=None, dtype=None):
    spec = GridSpec(Dim3.of(size), Dim3.of(dim), radius)
    n = (Dim3.of(mesh_dim) if mesh_dim else spec.dim).flatten()
    mesh = grid_mesh(mesh_dim or spec.dim, jax.devices()[: ndev or n])
    ex = HaloExchange(spec, mesh, method)
    field = coord_field(spec.global_size)
    if dtype is not None:
        field = field.astype(dtype)
    out = ex(shard_blocks(field, spec, mesh))
    return np.asarray(jax.device_get(out)), spec, ex


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize(
    "size,dim,r",
    [
        ((8, 8, 8), (2, 2, 2), 1),  # all-radius-1, uniform
        ((11, 9, 13), (2, 2, 2), 2),  # remainder partition
    ],
)
def test_parity_with_axis_composed(size, dim, r, dtype):
    """Acceptance: allclose (here: bit-equal) with AXIS_COMPOSED on uniform
    and remainder partitions, fp32 and fp64."""
    auto, spec, _ = _exchange(size, dim, Radius.constant(r), Method.AUTO_SPMD,
                              dtype=dtype)
    manual, _, _ = _exchange(size, dim, Radius.constant(r), Method.AXIS_COMPOSED,
                             dtype=dtype)
    np.testing.assert_allclose(auto, manual, rtol=0, atol=0)


def test_anisotropic_radius_parity_and_halos():
    r = Radius.constant(0)
    r.set_dir((-1, 0, 0), 1)
    r.set_dir((1, 0, 0), 2)
    r.set_dir((0, -1, 0), 3)
    r.set_dir((0, 1, 0), 1)
    r.set_dir((0, 0, -1), 2)
    r.set_dir((0, 0, 1), 0)
    auto, spec, _ = _exchange((10, 12, 8), (2, 2, 2), r, Method.AUTO_SPMD)
    manual, _, _ = _exchange((10, 12, 8), (2, 2, 2), r, Method.AXIS_COMPOSED)
    np.testing.assert_array_equal(auto, manual)
    check_halos(jnp.asarray(auto), spec)


def test_auto_spmd_halos_direct():
    """Independent of any manual method: every halo cell carries its
    periodically wrapped source coordinate (the reference verification
    idiom, test_exchange.cu:126-191)."""
    out, spec, _ = _exchange((12, 8, 10), (2, 2, 2), Radius.constant(3),
                             Method.AUTO_SPMD)
    check_halos(jnp.asarray(out), spec)


def test_oversubscribed_parity():
    """8 blocks on 4 and on 2 devices: the partitioner turns shard-internal
    block shifts into local copies and only the boundaries into permutes —
    results must equal the fully distributed exchange."""
    size, dim, r = (12, 12, 13), (2, 2, 2), Radius.constant(2)  # uneven z
    full, _, _ = _exchange(size, dim, r, Method.AUTO_SPMD)
    for mesh_dim, ndev in ((Dim3(2, 2, 1), 4), (Dim3(2, 1, 1), 2)):
        over, _, _ = _exchange(size, dim, r, Method.AUTO_SPMD,
                               mesh_dim=mesh_dim, ndev=ndev)
        np.testing.assert_array_equal(over, full)


def test_exchange_block_is_rejected():
    spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, Method.AUTO_SPMD)
    with pytest.raises(RuntimeError, match="SPMD partitioner"):
        ex.exchange_block(jnp.zeros((1, 1, 1) + spec.block_shape_zyx()))


def _census(size, dim, radius, method):
    spec = GridSpec(Dim3.of(size), Dim3.of(dim), radius)
    mesh = grid_mesh(spec.dim, jax.devices()[: spec.dim.flatten()])
    ex = HaloExchange(spec, mesh, method)
    state = {0: shard_blocks(coord_field(spec.global_size), spec, mesh)}
    return ex.collective_census(state)


def test_collective_census_counts():
    """The ablation's structural claim: the manual composed path emits
    exactly 6 collective-permutes per exchange (2 per axis phase), DIRECT26
    one per active direction (26 at uniform radius), and the auto path
    emits >= 1 synthesized collective-permute and no other collective
    kinds."""
    size, dim, r = (8, 8, 8), (2, 2, 2), Radius.constant(2)
    composed = _census(size, dim, r, Method.AXIS_COMPOSED)
    assert composed["collective-permute"][0] == 6, composed
    direct = _census(size, dim, r, Method.DIRECT26)
    assert direct["collective-permute"][0] == 26, direct
    auto = _census(size, dim, r, Method.AUTO_SPMD)
    assert auto["collective-permute"][0] >= 1, auto
    assert set(auto) == {"collective-permute"}, auto
    for census in (composed, direct, auto):
        assert census["collective-permute"][1] > 0  # bytes accounted


def test_census_bytes_scale_with_radius():
    """Sanity on the bytes column: tripling the radius must move more
    interconnect bytes under every strategy."""
    size, dim = (12, 12, 12), (2, 2, 2)
    for method in (Method.AXIS_COMPOSED, Method.AUTO_SPMD):
        b1 = _census(size, dim, Radius.constant(1), method)["collective-permute"][1]
        b3 = _census(size, dim, Radius.constant(3), method)["collective-permute"][1]
        assert b3 > b1, (method, b1, b3)


@pytest.mark.parametrize("size,dim", [((16, 16, 16), (2, 2, 2)),
                                      ((13, 11, 10), (2, 2, 2))])
def test_jacobi_step_parity(size, dim):
    """The full jacobi iteration built on AUTO_SPMD (one global jitted
    program, ops/jacobi._compile_jacobi_auto) matches the shard_map'd
    AXIS_COMPOSED iteration bit-for-bit, uniform and remainder partitions,
    overlap on and off."""
    from stencil_tpu.ops.jacobi import INIT_TEMP, make_jacobi_loop, sphere_sel

    results = {}
    for method in (Method.AXIS_COMPOSED, Method.AUTO_SPMD):
        for overlap in (True, False):
            spec = GridSpec(Dim3.of(size), Dim3.of(dim), Radius.constant(1))
            mesh = grid_mesh(spec.dim, jax.devices()[: spec.dim.flatten()])
            ex = HaloExchange(spec, mesh, method)
            sh = ex.sharding()
            shape = spec.stacked_shape_zyx()
            curr = jax.device_put(jnp.full(shape, INIT_TEMP, jnp.float32), sh)
            nxt = jax.device_put(jnp.zeros(shape, jnp.float32), sh)
            sel = shard_blocks(sphere_sel(spec.global_size), spec, mesh)
            loop = make_jacobi_loop(ex, 3, overlap=overlap)
            curr, _ = loop(curr, nxt, sel)
            results[(method, overlap)] = unshard_blocks(curr, spec)
    ref = results[(Method.AXIS_COMPOSED, True)]
    for key, arr in results.items():
        np.testing.assert_array_equal(arr, ref, err_msg=str(key))
