"""The numerical health guard (stencil_tpu/fault/health.py).

Pins the ISSUE-7 detection contract: one fused reduction over the state
dict, typed NumericalFault naming the offending quantity/step/kind, the
health.check span evidence — and the zero-HLO-change guarantee: building
and running a guard leaves the compiled step-loop program byte-identical
(the guard is a separate compiled reduction, pinned here the way
tests/test_overlap_hlo.py pins the overlap structure).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.api import DistributedDomain
from stencil_tpu.fault import DIVERGENCE, NONFINITE, HealthGuard, NumericalFault
from stencil_tpu.obs import telemetry


def test_clean_state_passes():
    g = HealthGuard(every=1)
    g.check({"a": jnp.ones((4, 4)), "b": jnp.zeros((2, 8))}, step=3)
    assert g.checks == 1


def test_nonfinite_detected_with_quantity_and_step():
    g = HealthGuard(every=1)
    bad = jnp.ones((4, 4)).at[1, 2].set(jnp.nan)
    with pytest.raises(NumericalFault) as ei:
        g.check({"a": jnp.ones((4, 4)), "b": bad}, step=7)
    f = ei.value
    assert f.kind == NONFINITE
    assert f.quantity == "b"
    assert f.step == 7


def test_inf_detected():
    g = HealthGuard(every=1)
    with pytest.raises(NumericalFault) as ei:
        g.check({"a": jnp.full((4,), jnp.inf)}, step=1)
    assert ei.value.kind == NONFINITE


def test_divergence_ceiling():
    g = HealthGuard(every=1, max_abs=10.0)
    g.check({"a": jnp.full((4,), 9.5)}, step=1)  # under the ceiling
    with pytest.raises(NumericalFault) as ei:
        g.check({"a": jnp.full((4,), -100.0)}, step=2)
    f = ei.value
    assert f.kind == DIVERGENCE
    assert f.value == pytest.approx(100.0)


def test_integer_quantities_trivially_healthy():
    g = HealthGuard(every=1, max_abs=1.0)
    g.check({"mask": jnp.full((4,), 7, jnp.int32)}, step=1)


def test_due_cadence():
    g = HealthGuard(every=4)
    assert not g.due(0, 3)
    assert g.due(3, 4)
    assert g.due(2, 9)   # crossed 4 and 8
    assert not g.due(4, 7)
    assert g.due(7, 8)


def test_health_check_span_and_fault_record(tmp_path):
    path = str(tmp_path / "m.jsonl")
    telemetry.configure(metrics_out=path, app="test")
    try:
        g = HealthGuard(every=1)
        g.check({"a": jnp.ones((4,))}, step=2)
        with pytest.raises(NumericalFault):
            g.check({"a": jnp.full((4,), jnp.nan)}, step=4)
    finally:
        telemetry.configure(metrics_out=None)
    recs = [json.loads(line) for line in open(path) if line.strip()]
    for r in recs:
        assert telemetry.validate_record(r) == [], r
    checks = [r for r in recs if r["name"] == "health.check"]
    assert len(checks) == 2 and all(r["kind"] == "span" for r in checks)
    assert {r["step"] for r in checks} == {2, 4}
    faults = [r for r in recs if r["name"] == "health.fault"]
    assert len(faults) == 1
    assert faults[0]["fault_kind"] == NONFINITE
    assert faults[0]["quantity"] == "a"
    assert faults[0]["step"] == 4


def _small_domain():
    dd = DistributedDomain(8, 8, 8)
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:1])
    dd.set_partition((1, 1, 1))
    h = dd.add_data("temperature", "float32")
    dd.realize()
    return dd, h


def test_domain_check_health():
    dd, h = _small_domain()
    dd.check_health()  # fresh zeros are healthy
    bad = dd.get_curr(h).at[0, 0, 0, 2, 2, 2].set(jnp.nan)
    dd.set_curr(h, bad)
    with pytest.raises(NumericalFault) as ei:
        dd.check_health(step=5)
    assert ei.value.quantity == "temperature"
    assert ei.value.step == 5


def test_domain_check_health_reuses_one_guard():
    # alternating ceilings must not rebuild (and re-jit) the reduction:
    # max_abs is a host-side comparison, not part of the compiled program
    dd, h = _small_domain()
    dd.set_curr(h, dd.get_curr(h).at[0, 0, 0, 2, 2, 2].set(2.0))
    dd.check_health()
    g = dd._health_guard
    with pytest.raises(NumericalFault) as ei:
        dd.check_health(max_abs=0.5, step=3)
    assert ei.value.kind == "divergence"
    dd.check_health()  # ceiling off again: healthy
    assert dd._health_guard is g


def test_step_loop_hlo_unchanged_by_guard():
    """The zero-HLO-change pin: lowering the fused jacobi step loop
    before and after constructing AND running a HealthGuard on the same
    state yields byte-identical StableHLO — the guard never wraps,
    rewrites, or recompiles the step program."""
    from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_sel
    from stencil_tpu.parallel.exchange import shard_blocks

    dd, h = _small_domain()
    sel = shard_blocks(sphere_sel(dd.size), dd.spec, dd.mesh)
    curr, nxt = dd.get_curr(h), dd.get_next(h)
    loop = make_jacobi_loop(dd.halo_exchange, 2)
    before = loop.lower(curr, nxt, sel).as_text()
    g = HealthGuard(every=1, max_abs=1e6)
    g.check({"temperature": curr}, step=1)
    after = loop.lower(curr, nxt, sel).as_text()
    assert before == after
    # and the guard's own reduction is a different (separate) program
    assert "is_finite" in jax.jit(g._build).lower(
        {"temperature": curr}).as_text()


def test_numpy_state_accepted():
    g = HealthGuard(every=1)
    with pytest.raises(NumericalFault):
        g.check({"q": np.array([1.0, np.nan], np.float32)}, step=0)
