"""Trace timeline export tests: telemetry records -> Chrome-trace JSON
with per-(run, proc) lanes, span duration events, counter tracks, and
fault/checkpoint instant markers; the validator catches unsorted
timestamps, incomplete X events, and unbalanced B/E pairs; the
``report --trace-out`` / ``--validate --ledger`` CLI paths."""

import json
import os

import pytest

from stencil_tpu.apps import report
from stencil_tpu.obs import ledger, trace_export


def _rec(kind, name, t, run="R1", proc=0, **fields):
    r = {"v": 1, "run": run, "proc": proc, "kind": kind, "name": name,
         "t": t}
    r.update(fields)
    return r


def _fault_run_records():
    """A ci_fault_gate-style story: two runs, two procs, step spans,
    an injected fault, the rollback, and checkpoint saves."""
    return [
        _rec("meta", "config", 100.0, app="jacobi3d", config={"x": 24}),
        _rec("span", "jacobi.step", 101.0, seconds=1.0, phase="step",
             app="jacobi3d"),
        _rec("span", "jacobi.step", 101.5, seconds=0.5, phase="step",
             proc=1),
        _rec("counter", "fault.injected", 101.6, value=1, step=3,
             fault_kind="nan"),
        _rec("span", "health.check", 101.7, seconds=0.05, phase="health",
             step=4),
        _rec("counter", "recover.rollback", 102.0, value=1, from_step=4,
             to_step=2, fault_step=3),
        _rec("span", "ckpt.save", 102.5, seconds=0.3, phase="ckpt",
             step=4),
        _rec("gauge", "jacobi.mcells_per_s", 103.0, value=42.0),
        _rec("heartbeat", "hb", 103.5, seq=7),
        # a second run shares the timeline but gets its own pid
        _rec("span", "jacobi.step", 104.0, seconds=0.8, run="R2"),
    ]


def test_to_trace_lanes_markers_and_sorting():
    tr = trace_export.to_trace(_fault_run_records())
    assert trace_export.validate_trace(tr) == []
    ev = tr["traceEvents"]
    # one process lane per run (named), one thread lane per (run, proc)
    pnames = {e["args"]["name"] for e in ev
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert pnames == {"run R1 (jacobi3d)", "run R2"}
    tnames = [(e["pid"], e["tid"]) for e in ev
              if e["ph"] == "M" and e["name"] == "thread_name"]
    assert len(tnames) == 3  # R1/proc0, R1/proc1, R2/proc0
    # spans are complete X events whose start is t - seconds
    steps = [e for e in ev if e["ph"] == "X" and e["name"] == "jacobi.step"]
    assert len(steps) == 3
    first = min(steps, key=lambda e: e["ts"])
    assert first["ts"] == 0.0  # earliest start anchors the timeline
    assert first["dur"] == pytest.approx(1.0e6)
    # fault/rollback/ckpt land as instant markers (ph "i")
    inst = {e["name"] for e in ev if e["ph"] == "i"}
    assert {"fault.injected", "recover.rollback", "ckpt.save"} <= inst
    # the ckpt.save span ALSO keeps its duration event
    assert any(e["ph"] == "X" and e["name"] == "ckpt.save" for e in ev)
    # gauges/counters/heartbeats become counter tracks
    cnames = {e["name"] for e in ev if e["ph"] == "C"}
    assert {"jacobi.mcells_per_s", "heartbeat", "fault.injected"} <= cnames
    # non-meta events are globally ts-sorted with non-negative stamps
    ts = [e["ts"] for e in ev if e["ph"] != "M"]
    assert ts == sorted(ts) and min(ts) >= 0
    # args keep the provenance the timeline needs at hover
    mark = next(e for e in ev if e["ph"] == "i"
                and e["name"] == "fault.injected")
    assert mark["args"]["step"] == 3 and mark["args"]["t"] == 101.6


def test_validate_trace_catches_violations():
    assert trace_export.validate_trace([]) != []
    assert trace_export.validate_trace({"traceEvents": "nope"}) != []
    base = {"pid": 1, "tid": 0, "name": "e"}
    # unsorted timestamps
    errs = trace_export.validate_trace({"traceEvents": [
        dict(base, ph="i", s="p", ts=5.0), dict(base, ph="i", s="p", ts=1.0),
    ]})
    assert any("not sorted" in e for e in errs)
    # X without dur / negative dur
    errs = trace_export.validate_trace(
        {"traceEvents": [dict(base, ph="X", ts=0.0)]})
    assert any("dur" in e for e in errs)
    errs = trace_export.validate_trace(
        {"traceEvents": [dict(base, ph="X", ts=0.0, dur=-1.0)]})
    assert any("dur" in e for e in errs)
    # E without B, and an unclosed B — per lane
    errs = trace_export.validate_trace(
        {"traceEvents": [dict(base, ph="E", ts=0.0)]})
    assert any("E without matching B" in e for e in errs)
    errs = trace_export.validate_trace(
        {"traceEvents": [dict(base, ph="B", ts=0.0)]})
    assert any("unclosed B" in e for e in errs)
    # balanced B/E on one lane is fine even with an X on another
    assert trace_export.validate_trace({"traceEvents": [
        dict(base, ph="B", ts=0.0), dict(base, ph="E", ts=1.0),
        {"pid": 2, "tid": 0, "name": "x", "ph": "X", "ts": 2.0, "dur": 1.0},
    ]}) == []
    # unsupported phase, missing name, negative ts
    assert trace_export.validate_trace(
        {"traceEvents": [dict(base, ph="Z", ts=0.0)]})
    assert trace_export.validate_trace(
        {"traceEvents": [{"pid": 1, "tid": 0, "ph": "i", "ts": 0.0}]})
    assert trace_export.validate_trace(
        {"traceEvents": [dict(base, ph="i", ts=-3.0)]})


def test_write_trace_roundtrip_and_refusal(tmp_path):
    out = str(tmp_path / "trace.json")
    n = trace_export.write_trace(out, _fault_run_records())
    with open(out) as f:
        tr = json.load(f)
    assert len(tr["traceEvents"]) == n
    assert trace_export.validate_trace(tr) == []
    assert tr["displayTimeUnit"] == "ms"
    # a span with negative seconds lowers to a negative-dur X event —
    # the writer must refuse its own invalid output, not persist it
    bad = [_rec("span", "s", 10.0, seconds=-1.0)]
    with pytest.raises(ValueError, match="refusing"):
        trace_export.write_trace(str(tmp_path / "bad.json"), bad)
    assert not (tmp_path / "bad.json").exists()


def test_report_trace_out_cli(tmp_path, capsys):
    m = tmp_path / "m.jsonl"
    m.write_text("\n".join(json.dumps(r) for r in _fault_run_records())
                 + "\n")
    out = str(tmp_path / "trace.json")
    assert report.main([str(m), "--trace-out", out]) == 0
    assert "trace:" in capsys.readouterr().out
    with open(out) as f:
        tr = json.load(f)
    assert trace_export.validate_trace(tr) == []
    assert any(e.get("ph") == "i" and e["name"] == "fault.injected"
               for e in tr["traceEvents"])


def test_report_validate_extends_to_ledger(tmp_path, capsys):
    m = tmp_path / "m.jsonl"
    m.write_text(json.dumps(
        {"v": 1, "run": "r", "proc": 0, "kind": "gauge", "name": "g",
         "t": 0.0, "value": 1.0}) + "\n")
    led = str(tmp_path / "L.jsonl")
    ledger.append_entries(led, [ledger.make_entry(
        "leg", 1.0, label="r01", platform="cpu", config={"c": 1})])
    assert report.main([str(m), "--validate", "--ledger", led]) == 0
    assert "1 valid entries" in capsys.readouterr().out
    with open(led, "a") as f:
        f.write("{torn\n")
    assert report.main([str(m), "--validate", "--ledger", led]) == 1
    assert "LEDGER" in capsys.readouterr().out


def test_report_validate_missing_ledger_fails(tmp_path, capsys):
    """--validate --ledger with a nonexistent path must fail the gate —
    a typo'd ledger path silently validating nothing is how schema
    gates rot."""
    m = tmp_path / "m.jsonl"
    m.write_text(json.dumps(
        {"v": 1, "run": "r", "proc": 0, "kind": "gauge", "name": "g",
         "t": 0.0, "value": 1.0}) + "\n")
    rc = report.main([str(m), "--validate",
                      "--ledger", str(tmp_path / "TYPO.jsonl")])
    assert rc == 1
    assert "no such ledger file" in capsys.readouterr().out


def test_write_trace_refuses_non_strict_json(tmp_path):
    """A NaN gauge value must fail the export, not produce a file
    Perfetto/chrome://tracing cannot parse (strict-JSON contract)."""
    recs = [_rec("gauge", "g", 1.0, value=float("nan"))]
    with pytest.raises(ValueError, match="non-strict-JSON"):
        trace_export.write_trace(str(tmp_path / "nan.json"), recs)
    assert not (tmp_path / "nan.json").exists()


def test_report_mode_flags_warn_when_ignored(tmp_path, capsys):
    """--validate/--follow are single-purpose modes: combining them with
    --trace-out etc. says so on stderr instead of silently producing no
    artifact."""
    m = tmp_path / "m.jsonl"
    m.write_text(json.dumps(
        {"v": 1, "run": "r", "proc": 0, "kind": "gauge", "name": "g",
         "t": 0.0, "value": 1.0}) + "\n")
    t = str(tmp_path / "t.json")
    assert report.main([str(m), "--validate", "--trace-out", t]) == 0
    assert "--validate mode ignores --trace-out" in capsys.readouterr().err
    assert not os.path.exists(t)
    assert report.main([str(m), "--follow", "--follow-count", "1",
                        "--trace-out", t]) == 0
    assert "--follow mode ignores --trace-out" in capsys.readouterr().err
