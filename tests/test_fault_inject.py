"""The deterministic fault-injection registry (stencil_tpu/fault/inject.py).

Spec grammar, once-vs-repeat firing semantics, seed-deterministic
placement (including the same-cells-on-refire rule the rollback paths
depend on), the halo/boundary-slab geometry, checkpoint truncation, and
the fault.injected telemetry evidence."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.fault import FaultPlan, parse_spec, truncate_newest_payload
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.obs import telemetry


# -- spec grammar -------------------------------------------------------------


def test_parse_single_and_defaults():
    (inj,) = parse_spec("nan@3")
    assert inj.kind == "nan" and inj.step == 3
    assert inj.repeat == 1 and inj.fired == 0


def test_parse_multi_with_options():
    injs = parse_spec("nan@3:q=uux:cells=4, crash@5:rc=9; slow@2:seconds=0.5")
    assert [i.kind for i in injs] == ["nan", "crash", "slow"]
    assert injs[0].quantity == "uux" and injs[0].cells == 4
    assert injs[1].rc == 9
    assert injs[2].seconds == 0.5


def test_parse_repeat():
    assert parse_spec("nan@1:repeat=3")[0].repeat == 3
    assert parse_spec("nan@1:repeat=always")[0].repeat == -1


@pytest.mark.parametrize("bad", ["nan", "nan@x", "frob@3", "nan@3:wat=1",
                                 "nan@3 cells=2", "nan@0", "crash@0:rc=9"])
def test_parse_errors_are_loud(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_from_spec_env_fallback(monkeypatch):
    monkeypatch.delenv("STENCIL_FAULT_INJECT", raising=False)
    assert FaultPlan.from_spec(None) is None
    monkeypatch.setenv("STENCIL_FAULT_INJECT", "inf@2")
    plan = FaultPlan.from_spec(None)
    assert plan is not None and plan.injections[0].kind == "inf"
    # explicit spec wins over env
    plan = FaultPlan.from_spec("nan@9")
    assert plan.injections[0].kind == "nan"
    monkeypatch.setenv("STENCIL_FAULT_SEED", "7")
    assert FaultPlan.from_spec("nan@1").seed == 7


def test_steps_and_due():
    plan = FaultPlan(parse_spec("nan@3,crash@7,nan@3:repeat=2"))
    assert plan.steps() == [3, 7]
    inj = plan.injections[0]
    assert not inj.due(3, 5)     # step 3 not in (3, 5]
    assert inj.due(2, 3)
    inj.fired = 1
    assert not inj.due(2, 3)     # fire-once consumed
    rep = plan.injections[2]
    rep.fired = 1
    assert rep.due(2, 4)         # repeat=2 still has one firing left


# -- state corruption ---------------------------------------------------------


def _spec():
    return GridSpec(Dim3(12, 12, 12), Dim3(2, 1, 1), Radius.constant(1))


def _state(spec):
    return {"q": jnp.zeros(spec.stacked_shape_zyx(), jnp.float32)}


def test_nan_burst_is_seed_deterministic_and_refire_stable():
    spec = _spec()
    where = []
    for _ in range(2):
        plan = FaultPlan(parse_spec("nan@3:repeat=always"), seed=1)
        st = plan.fire_due(_state(spec), 2, 3, spec=spec)
        where.append(np.argwhere(np.isnan(np.asarray(st["q"]))))
        # re-fire (as after a rollback): the SAME cells again
        st2 = plan.fire_due(_state(spec), 2, 3, spec=spec)
        assert np.array_equal(where[-1],
                              np.argwhere(np.isnan(np.asarray(st2["q"]))))
    assert np.array_equal(where[0], where[1])
    assert len(where[0]) == 2 ** 3  # default cells=2 cube
    other = FaultPlan(parse_spec("nan@3"), seed=2).fire_due(
        _state(spec), 2, 3, spec=spec)
    assert not np.array_equal(
        where[0], np.argwhere(np.isnan(np.asarray(other["q"]))))


def test_inf_burst_and_quantity_targeting():
    spec = _spec()
    st = {"a": jnp.zeros(spec.stacked_shape_zyx(), jnp.float32),
          "b": jnp.zeros(spec.stacked_shape_zyx(), jnp.float32)}
    plan = FaultPlan(parse_spec("inf@1:q=b"))
    out = plan.fire_due(st, 0, 1, spec=spec)
    assert np.isinf(np.asarray(out["b"])).any()
    assert not np.isinf(np.asarray(out["a"])).any()


def test_burst_lands_inside_compute_interior():
    spec = _spec()
    plan = FaultPlan(parse_spec("nan@1:cells=3"), seed=3)
    out = plan.fire_due(_state(spec), 0, 1, spec=spec)
    idx = np.argwhere(np.isnan(np.asarray(out["q"])))
    off = spec.compute_offset()
    for _bz, _by, bx, z, y, x in idx:
        sz = spec.block_size((int(bx), 0, 0))
        assert off.z <= z < off.z + sz.z
        assert off.y <= y < off.y + sz.y
        assert off.x <= x < off.x + sz.x


def test_halo_corrupts_wire_visible_boundary_slab():
    spec = _spec()
    plan = FaultPlan(parse_spec("halo@1"), seed=0)
    out = plan.fire_due(_state(spec), 0, 1, spec=spec)
    idx = np.argwhere(np.isnan(np.asarray(out["q"])))
    assert len(idx)
    off = spec.compute_offset()
    r = spec.radius.dir(0, 0, 1)
    for _bz, _by, bx, z, _y, _x in idx:
        sz = spec.block_size((int(bx), 0, 0))
        # the high-z interior boundary rows (what the next exchange sends)
        assert off.z + sz.z - r <= z < off.z + sz.z


def test_specless_flat_corruption():
    plan = FaultPlan(parse_spec("nan@1:cells=5"))
    out = plan.fire_due({"q": jnp.zeros((4, 4), jnp.float32)}, 0, 1)
    assert int(np.isnan(np.asarray(out["q"])).sum()) == 5


def test_slow_injection_sleeps_and_continues():
    plan = FaultPlan(parse_spec("slow@1:seconds=0.01"))
    st = {"q": jnp.zeros((2,), jnp.float32)}
    out = plan.fire_due(st, 0, 1)
    assert np.array_equal(np.asarray(out["q"]), np.zeros(2, np.float32))
    assert plan.injections[0].fired == 1


def test_injected_record_is_schema_valid(tmp_path):
    path = str(tmp_path / "m.jsonl")
    telemetry.configure(metrics_out=path, app="test")
    try:
        spec = _spec()
        FaultPlan(parse_spec("nan@4")).fire_due(_state(spec), 3, 4, spec=spec)
    finally:
        telemetry.configure(metrics_out=None)
    recs = [json.loads(line) for line in open(path) if line.strip()]
    inj = [r for r in recs if r["name"] == "fault.injected"]
    assert len(inj) == 1
    assert telemetry.validate_record(inj[0]) == []
    assert inj[0]["fault_kind"] == "nan" and inj[0]["step"] == 4
    assert inj[0]["quantity"] == "q"


# -- checkpoint truncation ----------------------------------------------------


def test_ckpt_truncate_hits_newest_snapshot(tmp_path):
    from stencil_tpu.ckpt import find_resume, write_snapshot

    spec = GridSpec(Dim3(8, 6, 4), Dim3(2, 1, 1), Radius.constant(1))
    st = {"q": np.random.RandomState(0).rand(
        *spec.stacked_shape_zyx()).astype(np.float32)}
    d = str(tmp_path)
    write_snapshot(d, 1, spec, st, keep=5)
    write_snapshot(d, 2, spec, st, keep=5)
    path = truncate_newest_payload(d)
    assert path and snapshot_dir(path) == "step-00000002"
    assert os.path.getsize(path) == 16
    # auto-resume now falls back past it
    snap, manifest = find_resume(d)
    assert manifest["step"] == 1


def snapshot_dir(payload_path):
    return os.path.basename(os.path.dirname(payload_path))


def test_ckpt_truncate_no_snapshots_is_none(tmp_path):
    assert truncate_newest_payload(str(tmp_path)) is None
