"""Unit tests for utils/timer.py: bucket accumulation, reset, and the
exception discipline of trace_range (a body exception must propagate —
the docstring's "generator didn't stop after throw()" hazard)."""

import time

import pytest

from stencil_tpu.utils import timer


def test_bucket_accumulation_and_report():
    timer.reset()
    with timer.timed("a"):
        time.sleep(0.01)
    first = timer.buckets["a"]
    assert first >= 0.01
    with timer.timed("a"):
        time.sleep(0.01)
    # accumulates into the same bucket (reference: timer.hpp:44-47), never
    # overwrites
    assert timer.buckets["a"] > first
    with timer.timed("b"):
        pass
    assert set(timer.buckets) >= {"a", "b"}
    rep = timer.report()
    assert rep.startswith("timers: ") and "a=" in rep and "b=" in rep


def test_reset_clears_buckets():
    with timer.timed("x"):
        pass
    timer.reset()
    assert not timer.buckets
    assert timer.report() == "timers: (empty)"


def test_timed_records_even_when_body_raises():
    timer.reset()
    with pytest.raises(ValueError, match="boom"):
        with timer.timed("failing"):
            raise ValueError("boom")
    # the finally-accumulate: a crashed region still leaves its time
    assert "failing" in timer.buckets


def test_trace_range_propagates_body_exception():
    with pytest.raises(ValueError, match="boom"):
        with timer.trace_range("r"):
            raise ValueError("boom")


def test_trace_range_body_runs():
    ran = []
    with timer.trace_range("r2"):
        ran.append(1)
    assert ran == [1]


def test_time_fn_decorator():
    timer.reset()

    @timer.time_fn("deco")
    def f(x):
        return x + 1

    assert f(1) == 2
    assert f.__name__ == "f"
    assert "deco" in timer.buckets
