"""Worker for the 2-process x 4-virtual-CPU-device exchange test.

Each process runs this SPMD-style: initialize the distributed runtime,
build the same DistributedDomain over the 8 global devices, exchange, and
verify the halos of the blocks THIS process hosts against the bit-packed
coordinate pattern (the reference's multi-rank verification idiom,
test_cuda_mpi_distributed_domain.cu:11-67).

Usage: python _mp_worker.py <rank> <num_processes> <port>
"""

import sys

sys.path.insert(0, sys.path[0] + "/..")  # repo root

rank, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])

from stencil_tpu.parallel.distributed import init_distributed, local_devices

pid, pcount = init_distributed(
    coordinator=f"localhost:{port}",
    num_processes=nprocs,
    process_id=rank,
    local_cpu_devices=4,
)
assert (pid, pcount) == (rank, nprocs), (pid, pcount)

import jax
import numpy as np

from stencil_tpu.api import DistributedDomain

assert len(jax.devices()) == 4 * nprocs
assert len(local_devices()) == 4

dd = DistributedDomain(24, 20, 16)
dd.set_radius(2)
h = dd.add_data("q", np.float32)
dd.realize()

g = dd.size
coords = (
    np.arange(g.z)[:, None, None] * 1000000
    + np.arange(g.y)[None, :, None] * 1000
    + np.arange(g.x)[None, None, :]
).astype(np.float32)
dd.set_curr_global(h, coords)
dd.exchange()

# verify every halo cell of every LOCALLY-hosted block
spec = dd.halo_exchange.spec
arr = dd.get_curr(h)
off = spec.compute_offset()
r = spec.radius
checked = bad = 0
for shard in arr.addressable_shards:
    # shard.index is the global (bz, by, bx, pz, py, px) slice tuple
    iz = shard.index[0].start or 0
    iy = shard.index[1].start or 0
    ix = shard.index[2].start or 0
    blk = np.asarray(shard.data)[0, 0, 0]
    o = spec.block_origin((ix, iy, iz))
    s = spec.block_size((ix, iy, iz))
    for zz in range(-r.z(-1), s.z + r.z(1)):
        for yy in range(-r.y(-1), s.y + r.y(1)):
            for xx in range(-r.x(-1), s.x + r.x(1)):
                if 0 <= zz < s.z and 0 <= yy < s.y and 0 <= xx < s.x:
                    continue
                gz, gy, gx = (o.z + zz) % g.z, (o.y + yy) % g.y, (o.x + xx) % g.x
                want = gz * 1000000 + gy * 1000 + gx
                got = blk[off.z + zz, off.y + yy, off.x + xx]
                checked += 1
                bad += got != want
assert checked > 0 and bad == 0, (rank, checked, bad)
print(f"MP_WORKER_OK rank={rank} blocks={len(arr.addressable_shards)} "
      f"halo_cells={checked}", flush=True)
