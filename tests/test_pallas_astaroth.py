"""Fused Pallas RK3 substep vs the XLA path (interpret mode).

Both paths call the same fd/equations math, so parity is structural; these
tests pin the kernel's tiling, DMA pipeline, and RK3 combine against
_integrate_region over the full compute region. Halo contents are random
but identical for both paths, so results must match regardless of
exchange state (reference idiom: test_cuda_mpi_exchange.cu uses
position-determined values the same way)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.astaroth.config import load_config
from stencil_tpu.astaroth.equations import Constants
from stencil_tpu.astaroth.integrate import FIELDS, _integrate_region
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius, Rect3
from stencil_tpu.ops.pallas_astaroth import (
    make_pallas_substep,
    pick_tiles,
    substep_supported,
)

CONF = "stencil_tpu/astaroth/astaroth.conf"
DT = 0.1  # large enough that updates are visible in fp32


def _setup(size=(16, 16, 16)):
    spec = GridSpec(Dim3(*size), Dim3(1, 1, 1), Radius.constant(3))
    info, _ = load_config(CONF)
    c = Constants.from_info(info)
    inv_ds = (
        info.real_params["AC_inv_dsx"],
        info.real_params["AC_inv_dsy"],
        info.real_params["AC_inv_dsz"],
    )
    p = spec.padded()
    rng = np.random.RandomState(7)
    curr = {k: jnp.asarray(rng.rand(p.z, p.y, p.x) * 0.1, jnp.float32) for k in FIELDS}
    out = {k: jnp.asarray(rng.rand(p.z, p.y, p.x) * 0.1, jnp.float32) for k in FIELDS}
    return spec, c, inv_ds, curr, out


@pytest.mark.parametrize("substep", [0, 1, 2])
@pytest.mark.parametrize("tiles", [None, (4, 8)])
def test_substep_parity(substep, tiles):
    spec, c, inv_ds, curr, out = _setup()
    assert substep_supported(spec, jnp.float32)

    fn = make_pallas_substep(spec, c, inv_ds, substep, DT, interpret=True, tiles=tiles)
    got = fn(tuple(curr[k] for k in FIELDS), tuple(out[k] for k in FIELDS))
    got = {k: np.asarray(v) for k, v in zip(FIELDS, got)}

    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)
    want = _integrate_region(substep, compute, inv_ds, c, DT, curr, out)
    want = {k: np.asarray(v) for k, v in want.items()}

    sl = (
        slice(off.z, off.z + spec.base.z),
        slice(off.y, off.y + spec.base.y),
        slice(off.x, off.x + spec.base.x),
    )
    for k in FIELDS:
        # few-ulp fp32 reassociation between XLA fusion and interpret mode;
        # absolute error stays <1e-5 on fields of magnitude up to ~20
        np.testing.assert_allclose(
            got[k][sl], want[k][sl], rtol=1e-4, atol=1e-5, err_msg=f"field {k}"
        )
        # the update must actually be visible (guards against a dt so small
        # the test would pass vacuously)
        assert not np.array_equal(got[k][sl], np.asarray(curr[k])[sl])


@pytest.mark.slow
@pytest.mark.parametrize(
    "substep,tiles",
    [
        # tz=2: ring offsets cycle 0,2,4,6 over W=8 slots (4 z-tiles)
        (0, (2, 8)),
        (2, (2, 8)),
        # tz=4: W=10 — tz does NOT divide W, so the offset walks 0,4,8,2
        # and the fresh-plane slots wrap mid-window (the uneven z-tiling)
        (1, (4, 8)),
    ],
)
def test_substep_parity_ring(substep, tiles):
    """Ring-indexed (shift-free) window variant vs the XLA path, all 8
    fields at radius 3: the modular-slot rotation must be invisible in the
    results at every substep, including tilings whose ring offset cycles
    through every slot (VERDICT r5 "Next" #1). Slow tier: the per-plane
    dynamic-slot reads trace to a much larger interpret graph than the
    shift variant's static slices."""
    spec, c, inv_ds, curr, out = _setup()
    fn = make_pallas_substep(
        spec, c, inv_ds, substep, DT, interpret=True, tiles=tiles,
        variant="ring",
    )
    got = fn(tuple(curr[k] for k in FIELDS), tuple(out[k] for k in FIELDS))
    got = {k: np.asarray(v) for k, v in zip(FIELDS, got)}

    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)
    want = _integrate_region(substep, compute, inv_ds, c, DT, curr, out)
    sl = (
        slice(off.z, off.z + spec.base.z),
        slice(off.y, off.y + spec.base.y),
        slice(off.x, off.x + spec.base.x),
    )
    for k in FIELDS:
        np.testing.assert_allclose(
            got[k][sl], np.asarray(want[k])[sl], rtol=1e-4, atol=1e-5,
            err_msg=f"field {k}",
        )
        assert not np.array_equal(got[k][sl], np.asarray(curr[k])[sl])


def test_kernel_variant_plumbing(monkeypatch):
    """make_astaroth_step resolves kernel_variant (arg > env > 'shift')
    and passes it to every substep kernel builder."""
    import stencil_tpu.astaroth.integrate as integ
    import stencil_tpu.ops.pallas_astaroth as pa
    from stencil_tpu.parallel import HaloExchange, grid_mesh

    recorded = []
    orig = pa.make_pallas_substep

    def rec(*a, **kw):
        recorded.append(kw.get("variant"))
        return orig(*a, **kw)

    # integrate.py imports the builder inside make_astaroth_step, so patch
    # it at its defining module
    monkeypatch.setattr(pa, "make_pallas_substep", rec)
    from stencil_tpu.astaroth.config import load_config

    info, _ = load_config(CONF)
    n = 16
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:1])
    ex = HaloExchange(spec, mesh)
    for env, arg, want in (
        (None, None, "shift"),
        ("ring", None, "ring"),
        ("ring", "shift", "shift"),
        (None, "ring", "ring"),
    ):
        recorded.clear()
        if env is None:
            monkeypatch.delenv("STENCIL_ASTAROTH_VARIANT", raising=False)
        else:
            monkeypatch.setenv("STENCIL_ASTAROTH_VARIANT", env)
        integ.make_astaroth_step(
            ex, info, use_pallas=True, interpret=True, kernel_variant=arg,
        )
        assert recorded == [want] * 3, (env, arg, recorded)


@pytest.mark.slow
def test_distributed_pallas_step_matches_xla_path():
    """Full distributed step (exchange + fused substeps inside shard_map)
    on a 2x2x2 mesh in interpret mode vs the XLA path — pins the
    integration wiring, not just the standalone kernel."""
    from stencil_tpu.astaroth.config import load_config
    from stencil_tpu.astaroth.integrate import make_astaroth_step
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    n = 16
    info, _ = load_config(CONF)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()

    spec = GridSpec(Dim3(n, n, n), Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(2)
    fields = {k: (rng.randn(n, n, n) * 0.05).astype(np.float32) for k in FIELDS}
    fields["lnrho"] = fields["lnrho"] + 0.5

    outs = {}
    for label, kwargs in (
        ("pallas", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        step = make_astaroth_step(ex, info, dt=1e-3, **kwargs)
        curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
        nxt = {k: shard_blocks(np.zeros((n, n, n), np.float32), spec, mesh)
               for k in FIELDS}
        curr, nxt = step(curr, nxt)
        outs[label] = {k: unshard_blocks(curr[k], spec) for k in FIELDS}
    for k in FIELDS:
        np.testing.assert_allclose(
            outs["pallas"][k], outs["xla"][k], rtol=1e-4, atol=1e-5, err_msg=k
        )


def test_substep_gates():
    spec, *_ = _setup()
    assert substep_supported(spec, jnp.float32)
    assert not substep_supported(spec, jnp.float64)
    # unaligned layout
    u = GridSpec(Dim3(16, 16, 16), Dim3(1, 1, 1), Radius.constant(3), aligned=False)
    assert not substep_supported(u, jnp.float32)
    # radius < 3
    r2 = GridSpec(Dim3(16, 16, 16), Dim3(1, 1, 1), Radius.constant(2))
    assert not substep_supported(r2, jnp.float32)
    # ny not a multiple of 8
    odd = GridSpec(Dim3(16, 12, 16), Dim3(1, 1, 1), Radius.constant(3))
    assert not substep_supported(odd, jnp.float32)


@pytest.mark.slow
def test_pick_tiles_budget():
    spec, *_ = _setup((256, 256, 256))
    tz, ty = pick_tiles(spec)
    assert tz >= 1 and ty % 8 == 0
    assert 256 % tz == 0 and 256 % ty == 0
    from stencil_tpu.ops.pallas_astaroth import _SCRATCH_BUDGET, scratch_bytes

    assert scratch_bytes(spec, tz, ty) <= _SCRATCH_BUDGET
