"""End-to-end self-healing runs through the real apps (ISSUE 7).

In-process jacobi3d/astaroth runs on tiny domains: an injected NaN burst
is detected by the health guard, rolled back to the newest durable
snapshot, and the completed run's final field is BIT-IDENTICAL to an
uninterrupted one; no persisted snapshot ever carries the corruption
(the health check precedes every save); exhaustion raises
RecoveryExhausted with the evidence bundle. The full CLI/rc/watchdog
ladder is ci_fault_gate.py's job — these pin the in-process semantics.
"""

import json
import os

import jax
import numpy as np
import pytest

from stencil_tpu.apps.jacobi3d import run as jacobi_run
from stencil_tpu.ckpt import assemble_global, list_snapshots, load_manifest
from stencil_tpu.fault import FAULT_RC, RecoveryExhausted


def _jacobi(tmp, sub, **kw):
    kw.setdefault("iters", 6)
    kw.setdefault("weak", False)
    kw.setdefault("devices", jax.devices()[:1])
    kw.setdefault("warmup", 1)
    kw.setdefault("ckpt_dir", os.path.join(str(tmp), sub))
    kw.setdefault("ckpt_every", 2)
    kw.setdefault("health_every", 2)
    kw.setdefault("rollback_backoff", 0.01)
    return jacobi_run(12, 12, 12, **kw)


def test_jacobi_rollback_bit_identical_and_snapshots_clean(tmp_path):
    ref = _jacobi(tmp_path, "ref")
    g_ref = ref["domain"].get_curr_global(ref["handle"])
    assert np.isfinite(g_ref).all()

    r = _jacobi(tmp_path, "ck", inject="nan@3")
    g = r["domain"].get_curr_global(r["handle"])
    # detected, rolled back, completed — and the final field is exactly
    # the uninterrupted run's
    assert np.array_equal(g_ref, g)
    # the check-before-save ordering: every durable snapshot is finite
    ck = os.path.join(str(tmp_path), "ck")
    for name in list_snapshots(ck):
        snap = os.path.join(ck, name)
        m = load_manifest(snap)
        arr = assemble_global(snap, m, "temperature")
        assert np.isfinite(arr).all(), f"poisoned snapshot {name}"


def test_jacobi_newest_corrupt_falls_back(tmp_path):
    ref = _jacobi(tmp_path, "ref2")
    g_ref = ref["domain"].get_curr_global(ref["handle"])
    # truncate the newest (step-4) snapshot right before the step-5 fault:
    # the rollback must skip it to the prior good step-2 snapshot
    r = _jacobi(tmp_path, "ck2", inject="ckpt-truncate@5,nan@5")
    g = r["domain"].get_curr_global(r["handle"])
    assert np.array_equal(g_ref, g)


def test_jacobi_exhaustion_evidence_and_rc(tmp_path):
    with pytest.raises(RecoveryExhausted) as ei:
        _jacobi(tmp_path, "ck3", inject="nan@3:repeat=always",
                max_rollbacks=1)
    e = ei.value
    assert "max rollbacks (1) exceeded" in e.reason
    assert e.evidence_path and os.path.isfile(e.evidence_path)
    ev = json.load(open(e.evidence_path))
    assert ev["rc"] == FAULT_RC == 43
    assert ev["app"] == "jacobi3d"
    assert sum(ev["rollbacks"].values()) == 2


def test_jacobi_divergence_ceiling_fires(tmp_path, monkeypatch):
    # jacobi temperatures stay bounded; a ceiling below the initial
    # temperature must fault at the first check — and without
    # checkpoints the run degrades loudly instead of looping
    monkeypatch.setenv("STENCIL_FAULT_EVIDENCE",
                       str(tmp_path / "evidence.json"))
    with pytest.raises(RecoveryExhausted) as ei:
        jacobi_run(12, 12, 12, iters=4, weak=False,
                   devices=jax.devices()[:1], warmup=1,
                   health_every=2, max_abs=1e-3)
    assert ei.value.fault.kind == "divergence"
    assert "cannot roll back" in ei.value.reason
    assert os.path.isfile(str(tmp_path / "evidence.json"))


def test_astaroth_guarded_rollback(tmp_path):
    from stencil_tpu.apps.astaroth import run as asta_run

    ck = str(tmp_path / "asta")
    r = asta_run(iters=3, nx=8, devices=jax.devices()[:1], dtype="float64",
                 chunk=1, ckpt_dir=ck, ckpt_every=1, health_every=1,
                 inject="nan@2:q=lnrho", rollback_backoff=0.01)
    # the step-2 fault rolled back to the step-1 snapshot and the run
    # completed every iteration with finite fields (jacobi pins the
    # bit-exactness contract; this pins the 8-field dict wiring)
    dd = r["domain"]
    for name, h in r["handles"].items():
        assert np.isfinite(dd.get_curr_global(h)).all(), name
    assert r["iters_run"] >= 3
    assert list_snapshots(ck)  # durable campaign state exists
