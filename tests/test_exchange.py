"""Halo-exchange correctness — the reference's verification idiom.

Each grid point is initialized with a value determined by its global
coordinate (bit-packed, reference: test_cuda_mpi_distributed_domain.cu:11-17;
ripple, reference: test_exchange.cu:12-33). After one exchange, every halo
cell must hold the value of its periodically-wrapped source coordinate
(reference: test_exchange.cu:126-191). This exercises the entire
partition/slab/ppermute/update pipeline with no reference simulation.

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import DIRECTIONS_26, Dim3, Radius
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import BLOCK_PSPEC, shard_blocks, unshard_blocks


def coord_field(g: Dim3) -> np.ndarray:
    """value = x | y<<10 | z<<20 (valid for extents < 1024)."""
    z, y, x = np.meshgrid(
        np.arange(g.z), np.arange(g.y), np.arange(g.x), indexing="ij"
    )
    return (x | (y << 10) | (z << 20)).astype(np.int32)


def check_halos(stacked, spec: GridSpec, dirs=None):
    """Verify halo cells for every active direction on every block."""
    arr = np.asarray(jax.device_get(stacked))
    g = spec.global_size
    ref = coord_field(g)
    off = spec.compute_offset()
    checked = 0
    for iz in range(spec.dim.z):
        for iy in range(spec.dim.y):
            for ix in range(spec.dim.x):
                idx = (ix, iy, iz)
                size = spec.block_size(idx)
                origin = spec.block_origin(idx)
                block = arr[iz, iy, ix]
                for d in dirs if dirs is not None else DIRECTIONS_26:
                    if spec.radius.dir(d) == 0:
                        continue
                    rect = spec.halo_rect(d, size, halo=True)
                    ext = rect.extent()
                    if ext.flatten() == 0:
                        continue
                    for az in range(rect.lo.z, rect.hi.z):
                        for ay in range(rect.lo.y, rect.hi.y):
                            for ax in range(rect.lo.x, rect.hi.x):
                                gx = (origin.x + ax - off.x) % g.x
                                gy = (origin.y + ay - off.y) % g.y
                                gz = (origin.z + az - off.z) % g.z
                                got = block[az, ay, ax]
                                want = ref[gz, gy, gx]
                                assert got == want, (
                                    f"block {idx} dir {d} halo cell ({ax},{ay},{az}): "
                                    f"got {got:#x} want {want:#x} (src {gx},{gy},{gz})"
                                )
                                checked += 1
    assert checked > 0


def run_exchange(global_size, dim, radius, method, devices=None):
    spec = GridSpec(Dim3.of(global_size), Dim3.of(dim), radius)
    n = spec.num_blocks()
    devs = devices if devices is not None else jax.devices()[:n]
    mesh = grid_mesh(spec.dim, devs)
    ex = HaloExchange(spec, mesh, method)
    field = coord_field(spec.global_size)
    stacked = shard_blocks(field, spec, mesh)
    out = ex(stacked)
    # compute region must be untouched
    np.testing.assert_array_equal(unshard_blocks(out, spec), field)
    return out, spec


@pytest.mark.parametrize("method", [Method.AXIS_COMPOSED, Method.DIRECT26])
@pytest.mark.parametrize(
    "size,dim,r",
    [
        ((8, 8, 8), (2, 2, 2), 1),
        ((12, 8, 10), (2, 2, 2), 3),
        ((8, 8, 8), (4, 2, 1), 2),
        ((16, 8, 8), (8, 1, 1), 2),
        ((6, 6, 6), (1, 1, 1), 2),  # single device: periodic self-wrap
    ],
)
def test_constant_radius(size, dim, r, method):
    out, spec = run_exchange(size, dim, Radius.constant(r), method)
    check_halos(out, spec)


@pytest.mark.parametrize("method", [Method.AXIS_COMPOSED, Method.DIRECT26])
def test_asymmetric_faces(method):
    r = Radius.constant(0)
    r.set_dir((-1, 0, 0), 1)
    r.set_dir((1, 0, 0), 2)
    r.set_dir((0, -1, 0), 3)
    r.set_dir((0, 1, 0), 1)
    r.set_dir((0, 0, -1), 2)
    r.set_dir((0, 0, 1), 0)
    out, spec = run_exchange((10, 12, 8), (2, 2, 2), r, method)
    check_halos(out, spec)


@pytest.mark.parametrize("method", [Method.AXIS_COMPOSED, Method.DIRECT26])
def test_face_edge_corner_gates(method):
    # corners gated off (radius 0): reference skips those messages; both
    # methods must still deliver faces and edges correctly.
    r = Radius.face_edge_corner(2, 2, 0)
    out, spec = run_exchange((8, 8, 8), (2, 2, 2), r, method)
    check_halos(out, spec)


def test_uneven_partition():
    out, spec = run_exchange((11, 9, 13), (2, 2, 2), Radius.constant(2), Method.AXIS_COMPOSED)
    assert not spec.is_uniform()
    check_halos(out, spec)


def test_uneven_three_way():
    out, spec = run_exchange((13, 7, 5), (2, 2, 2), Radius.constant(1), Method.AXIS_COMPOSED)
    check_halos(out, spec)


def test_direct26_uneven_partition():
    """DIRECT26 on a remainder partition (ROADMAP #4, VERDICT r5 "Next"
    #5): slab extents padded to the base size along orthogonal axes,
    face→edge→corner apply order, traced per-block compute extents — every
    halo cell must still carry its wrapped source coordinate."""
    out, spec = run_exchange((11, 9, 13), (2, 2, 2), Radius.constant(2), Method.DIRECT26)
    assert not spec.is_uniform()
    check_halos(out, spec)


def test_direct26_uneven_parity_with_composed():
    """Pin: at a uniform radius the DIRECT26 result on a remainder
    partition is bit-identical to AXIS_COMPOSED (the ISSUE 2 acceptance
    bar; anisotropic gating is exempt — composed full-extent slabs fill
    cells DIRECT26's skipped directions own)."""
    out_d, spec = run_exchange((13, 7, 5), (2, 2, 2), Radius.constant(1), Method.DIRECT26)
    out_c, _ = run_exchange((13, 7, 5), (2, 2, 2), Radius.constant(1), Method.AXIS_COMPOSED)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(out_d)), np.asarray(jax.device_get(out_c))
    )


def test_direct26_uneven_oversubscribed():
    """Uneven split along a RESIDENT axis under DIRECT26 (z = 7+6 on 4
    devices): per-resident traced starts must match the fully distributed
    exchange."""
    size = Dim3(12, 12, 13)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(2))
    coord = coord_field(size)
    results = {}
    for label, mesh_dim, ndev in (("over", Dim3(2, 2, 1), 4),
                                  ("full", Dim3(2, 2, 2), 8)):
        mesh = grid_mesh(mesh_dim, jax.devices()[:ndev])
        ex = HaloExchange(spec, mesh, Method.DIRECT26)
        state = ex({0: shard_blocks(coord, spec, mesh)})
        results[label] = np.asarray(jax.device_get(state[0]))
    np.testing.assert_array_equal(results["over"], results["full"])


def test_multi_quantity_pytree():
    """Exchange a pytree of quantities with distinct dtypes in one call."""
    spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    field = coord_field(spec.global_size)
    state = {
        "a": shard_blocks(field, spec, mesh),
        "b": shard_blocks(field.astype(np.float64), spec, mesh),
    }
    out = ex(state)
    check_halos(out["a"], spec)
    check_halos(out["b"].astype(np.int64), spec)


def test_bytes_accounting():
    spec = GridSpec(Dim3(8, 8, 8), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    # per block: 6 faces 4*4*1 + 12 edges 4*1*1 + 8 corners 1 = 16*6+4*12+8 = 152
    assert ex.bytes_logical([4]) == 8 * (6 * 16 + 12 * 4 + 8) * 4
    assert ex.bytes_moved([4]) >= ex.bytes_logical([4])


def test_oversubscribed_exchange_halo_parity():
    """8 blocks on 4 devices (2 z-blocks resident per device, reference:
    dd.set_gpus({0,0}), test_exchange.cu:52): every halo cell must carry
    its periodically wrapped source coordinate, and the result must equal
    the same partition realized on 8 devices."""
    import jax

    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks

    size = Dim3(12, 12, 12)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(2))
    coord = (
        np.arange(size.z)[:, None, None] * 1_000_000
        + np.arange(size.y)[None, :, None] * 1_000
        + np.arange(size.x)[None, None, :]
    ).astype(np.float32)

    results = {}
    for label, mesh_dim, ndev in (("over", Dim3(2, 2, 1), 4),
                                  ("full", Dim3(2, 2, 2), 8)):
        mesh = grid_mesh(mesh_dim, jax.devices()[:ndev])
        ex = HaloExchange(spec, mesh)
        assert ex.resident_z == (2 if label == "over" else 1)
        state = ex({0: shard_blocks(coord, spec, mesh)})
        results[label] = np.asarray(jax.device_get(state[0]))
    np.testing.assert_array_equal(results["over"], results["full"])

    # independent halo check on the oversubscribed result, every block
    arr = results["over"]
    off = spec.compute_offset()
    r = spec.radius
    for bz in range(2):
        for by in range(2):
            for bx in range(2):
                blk = arr[bz, by, bx]
                org = spec.block_origin((bx, by, bz))
                bs = spec.block_size((bx, by, bz))
                for z in range(off.z - r.z(-1), off.z + bs.z + r.z(1)):
                    gz = (org.z + z - off.z) % size.z
                    for (y, x) in ((off.y - 1, off.x), (off.y + bs.y, off.x + bs.x - 1)):
                        gy = (org.y + y - off.y) % size.y
                        gx = (org.x + x - off.x) % size.x
                        want = gz * 1_000_000 + gy * 1_000 + gx
                        assert blk[z, y, x] == want, (bz, by, bx, z, y, x)


def _coord_field(size):
    return (
        np.arange(size.z)[:, None, None] * 1_000_000
        + np.arange(size.y)[None, :, None] * 1_000
        + np.arange(size.x)[None, None, :]
    ).astype(np.float32)


def _assert_halos_wrap(arr, spec, size):
    """Every face-halo cell of every block carries its periodically wrapped
    source coordinate (spot rows on each face)."""
    off = spec.compute_offset()
    r = spec.radius
    for bz in range(spec.dim.z):
        for by in range(spec.dim.y):
            for bx in range(spec.dim.x):
                blk = arr[bz, by, bx]
                org = spec.block_origin((bx, by, bz))
                bs = spec.block_size((bx, by, bz))
                for z in range(off.z - r.z(-1), off.z + bs.z + r.z(1)):
                    gz = (org.z + z - off.z) % size.z
                    for (y, x) in ((off.y - 1, off.x),
                                   (off.y + bs.y, off.x + bs.x - 1)):
                        gy = (org.y + y - off.y) % size.y
                        gx = (org.x + x - off.x) % size.x
                        want = gz * 1_000_000 + gy * 1_000 + gx
                        assert blk[z, y, x] == want, (bz, by, bx, z, y, x)


def test_oversubscribed_uneven_z_halo_parity():
    """Uneven split along the RESIDENT axis (z = 7+6): per-resident sizes
    come from traced size-table lookups; the result must equal the same
    partition on 8 devices (round-3 rejected this; VERDICT r3 item 4)."""
    import jax

    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks

    size = Dim3(12, 12, 13)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(2))
    coord = _coord_field(size)
    results = {}
    for label, mesh_dim, ndev in (("over", Dim3(2, 2, 1), 4),
                                  ("full", Dim3(2, 2, 2), 8)):
        mesh = grid_mesh(mesh_dim, jax.devices()[:ndev])
        ex = HaloExchange(spec, mesh)
        state = ex({0: shard_blocks(coord, spec, mesh)})
        results[label] = np.asarray(jax.device_get(state[0]))
    np.testing.assert_array_equal(results["over"], results["full"])
    _assert_halos_wrap(results["over"], spec, size)


def test_oversubscribed_uneven_multidevice_axis_halo_parity():
    """Uneven split (z = 4+4+3+3) with the resident axis spanning MULTIPLE
    devices (4 z-blocks, 2 residents on each of 2 devices): exercises the
    axis_index*c+j size-table lookup at axis_index > 0, which the
    single-device-axis tests never reach."""
    import jax

    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks

    size = Dim3(12, 12, 14)
    spec = GridSpec(size, Dim3(1, 1, 4), Radius.constant(2))
    assert tuple(spec.sizes_z) == (4, 4, 3, 3)
    coord = _coord_field(size)
    results = {}
    for label, mesh_dim, ndev in (("over", Dim3(1, 1, 2), 2),
                                  ("full", Dim3(1, 1, 4), 4)):
        mesh = grid_mesh(mesh_dim, jax.devices()[:ndev])
        ex = HaloExchange(spec, mesh)
        state = ex({0: shard_blocks(coord, spec, mesh)})
        results[label] = np.asarray(jax.device_get(state[0]))
    np.testing.assert_array_equal(results["over"], results["full"])
    _assert_halos_wrap(results["over"], spec, size)


def test_x_side_buffers_carry_neighbor_columns():
    """Tight-x multi-block transport: x_side_buffers must deliver the -x
    neighbor's top r columns as xlo and the +x neighbor's first r columns
    as xhi, periodically wrapped, for r=1 and r=2."""
    size = Dim3(256, 8, 6)  # two 128-wide x blocks
    spec = GridSpec(size, Dim3(2, 1, 1), Radius.constant(1).without_x())
    mesh = grid_mesh(spec.dim, jax.devices()[:2])
    ex = HaloExchange(spec, mesh)
    coord = _coord_field(size)
    state = shard_blocks(coord, spec, mesh)

    for r in (1, 2):
        fn = jax.jit(jax.shard_map(
            lambda b: ex.x_side_buffers(b, r),
            mesh=mesh, in_specs=BLOCK_PSPEC,
            out_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
        ))
        xlo, xhi = fn(state)
        xlo = np.asarray(jax.device_get(xlo))
        xhi = np.asarray(jax.device_get(xhi))
        off = spec.compute_offset()
        for bx in range(2):
            org = spec.block_origin((bx, 0, 0))
            blk_lo = xlo[0, 0, bx]
            blk_hi = xhi[0, 0, bx]
            for j in range(r):
                # xlo[..., j] = global x = org.x - r + j (wrapped)
                gx = (org.x - r + j) % size.x
                np.testing.assert_array_equal(
                    blk_lo[off.z, off.y, j],
                    coord[0, 0, gx], err_msg=f"xlo r={r} bx={bx} j={j}",
                )
                # xhi[..., j] = global x = org.x + nx + j (wrapped)
                gx = (org.x + spec.sizes_x[bx] + j) % size.x
                np.testing.assert_array_equal(
                    blk_hi[off.z, off.y, j],
                    coord[0, 0, gx], err_msg=f"xhi r={r} bx={bx} j={j}",
                )


def test_oversubscribed_mixed_axes_halo_parity():
    """(cz, cy) = (2, 2) mixed stacking — a 2x2x2 partition on TWO devices
    (mesh 1x1x2 on x) — and pure-y stacking on 4: both must equal the fully
    distributed 8-device exchange (VERDICT r3 item 4)."""
    import jax

    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks

    size = Dim3(12, 12, 12)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(2))
    coord = _coord_field(size)
    results = {}
    for label, mesh_dim, ndev in (("mixed2", Dim3(2, 1, 1), 2),
                                  ("ystack", Dim3(2, 1, 2), 4),
                                  ("full", Dim3(2, 2, 2), 8)):
        mesh = grid_mesh(mesh_dim, jax.devices()[:ndev])
        ex = HaloExchange(spec, mesh)
        assert ex.oversubscribed == (label != "full")
        state = ex({0: shard_blocks(coord, spec, mesh)})
        results[label] = np.asarray(jax.device_get(state[0]))
    np.testing.assert_array_equal(results["mixed2"], results["full"])
    np.testing.assert_array_equal(results["ystack"], results["full"])
    _assert_halos_wrap(results["mixed2"], spec, size)


def test_oversubscribed_direct26_halo_parity():
    """DIRECT26 under oversubscription (exclusion lifted, VERDICT r3
    item 4): resident rolls + boundary permutes must match the fully
    distributed DIRECT26 exchange, on z-stacked AND mixed meshes."""
    import jax

    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks

    size = Dim3(12, 12, 12)
    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(2))
    coord = _coord_field(size)
    results = {}
    for label, mesh_dim, ndev in (("zstack", Dim3(2, 2, 1), 4),
                                  ("mixed2", Dim3(2, 1, 1), 2),
                                  ("full", Dim3(2, 2, 2), 8)):
        mesh = grid_mesh(mesh_dim, jax.devices()[:ndev])
        ex = HaloExchange(spec, mesh, method=Method.DIRECT26)
        state = ex({0: shard_blocks(coord, spec, mesh)})
        results[label] = np.asarray(jax.device_get(state[0]))
    np.testing.assert_array_equal(results["zstack"], results["full"])
    np.testing.assert_array_equal(results["mixed2"], results["full"])
    _assert_halos_wrap(results["mixed2"], spec, size)
