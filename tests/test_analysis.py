"""stencil_tpu/analysis: the lint engine, the plan/HLO conformance
auditor, and the jit recompile/host-sync audit (ISSUE 13).

Lint tests run the real engine over per-rule good/bad fixture snippets
in a temp tree (including nested/aliased imports for pure-stdlib and
the suppression-pragma edge cases). The plan auditor is pinned to agree
for all four exchange methods at 16^3 on the 8-virtual-device CPU mesh
(conftest.py) and to TRIP when an IR prediction is perturbed; the jit
audit must pass on the clean jacobi chunk loop and fail on both
injected fixtures.
"""

import json
import os

import pytest

from stencil_tpu.analysis import astlint
from stencil_tpu.analysis.astlint import lint_paths, load_baseline, \
    write_baseline


def _lint_snippet(tmp_path, relpath, src, rules=None):
    fpath = tmp_path / relpath
    fpath.parent.mkdir(parents=True, exist_ok=True)
    fpath.write_text(src)
    findings, errors = lint_paths([str(fpath)], repo_root=str(tmp_path),
                                  rules=rules)
    assert not errors, errors
    return findings


def _rules(findings):
    return sorted({f.rule for f in findings})


# -- pure-stdlib --------------------------------------------------------------


def test_pure_stdlib_flags_nested_and_aliased_imports(tmp_path):
    findings = _lint_snippet(tmp_path, "obs/ledger.py", (
        "import json\n"
        "from numpy import array as arr\n"       # aliased third-party
        "def append(path):\n"
        "    import jax\n"                        # nested: still flagged
        "    return jax, arr\n"
    ))
    mine = [f for f in findings if f.rule == "pure-stdlib"]
    assert len(mine) == 2
    assert {f.line for f in mine} == {2, 4}


def test_pure_stdlib_rejects_relative_imports(tmp_path):
    findings = _lint_snippet(tmp_path, "obs/status.py",
                             "from .telemetry import Recorder\n")
    assert _rules(findings) == ["pure-stdlib"]
    assert "file path" in findings[0].message


def test_pure_stdlib_clean_on_stdlib_only(tmp_path):
    findings = _lint_snippet(tmp_path, "obs/watchdog.py", (
        "import json\nimport os\n"
        "try:\n    import fcntl\nexcept ImportError:\n    fcntl = None\n"
    ))
    assert findings == []


def test_pure_stdlib_bench_parent_is_top_level_only(tmp_path):
    # bench.py: module-level jax is a contract break, function-level is
    # the child code path and allowed
    bad = _lint_snippet(tmp_path, "bench.py", "import jax\n")
    assert _rules(bad) == ["pure-stdlib"]
    good = _lint_snippet(tmp_path, "bench.py", (
        "import json\n"
        "def child():\n    import jax\n    return jax\n"
    ))
    assert good == []


def test_pure_stdlib_does_not_apply_elsewhere(tmp_path):
    findings = _lint_snippet(tmp_path, "lib/other.py", "import jax\n")
    assert [f for f in findings if f.rule == "pure-stdlib"] == []


# -- telemetry-vocab ----------------------------------------------------------


def test_vocab_flags_typo_and_passes_known_and_dynamic(tmp_path):
    bad = _lint_snippet(tmp_path, "lib/site.py", (
        "def emit(rec):\n"
        "    rec.counter('recover.rollbck', value=1)\n"
    ))
    assert _rules(bad) == ["telemetry-vocab"]
    good = _lint_snippet(tmp_path, "lib/site.py", (
        "def emit(rec, kind):\n"
        "    rec.gauge('exchange.trimean_s', 1.0)\n"
        "    rec.counter(f'census.{kind}', value=1)\n"   # generic: exempt
        "    rec.emit('gauge', 'jacobi.mcells_per_s', value=1.0)\n"
    ))
    assert good == []


def test_vocab_includes_name_fields_and_analysis(tmp_path):
    good = _lint_snippet(tmp_path, "lib/site.py", (
        "def emit(rec):\n"
        "    rec.meta('analysis.plan_verdict', method='x', ok=1)\n"
        "    rec.meta('recover.aborted', reason='r', step=1)\n"
    ))
    assert good == []


# -- atomic-write -------------------------------------------------------------


def test_atomic_write_flags_plain_dump(tmp_path):
    bad = _lint_snippet(tmp_path, "lib/w.py", (
        "import json\n"
        "def save(path, doc):\n"
        "    with open(path, 'w') as f:\n"
        "        json.dump(doc, f)\n"
    ))
    assert _rules(bad) == ["atomic-write"]


def test_atomic_write_not_silenced_by_str_replace(tmp_path):
    # a str.replace in scope is NOT the atomic protocol: only
    # os/shutil.replace (or a .rename) counts
    bad = _lint_snippet(tmp_path, "lib/w.py", (
        "import json\n"
        "def save(path, doc):\n"
        "    key = path.replace('-', '_')\n"
        "    with open(path, 'w') as f:\n"
        "        json.dump({key: doc}, f)\n"
    ))
    assert _rules(bad) == ["atomic-write"]


def test_atomic_write_passes_tmp_rename_protocol(tmp_path):
    good = _lint_snippet(tmp_path, "lib/w.py", (
        "import json, os\n"
        "def save(path, doc):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        json.dump(doc, f)\n"
        "        os.fsync(f.fileno())\n"
        "    os.replace(tmp, path)\n"
    ))
    assert good == []


# -- no-bare-assert -----------------------------------------------------------


def test_assert_flagged_only_at_public_api_boundaries(tmp_path):
    src = (
        "class Dom:\n"
        "    def realize(self, n):\n"
        "        assert n >= 1\n"              # public method: flagged
        "    def _inner(self, n):\n"
        "        assert n >= 1\n"              # private: exempt
        "def make_loop(k):\n"
        "    assert k > 0\n"                   # public function: flagged
        "    def body(x):\n"
        "        assert x is not None\n"       # nested: exempt
        "    return body\n"
        "def assert_consistent(a):\n"
        "    assert a\n"                       # assert_* checker: exempt
    )
    findings = _lint_snippet(tmp_path, "lib/api.py", src,
                             rules=["no-bare-assert"])
    assert {f.line for f in findings} == {3, 7}


def test_assert_flagged_under_module_level_conditional(tmp_path):
    # a def under a module-level if/try (feature gates, optional-dep
    # fallbacks) is just as public as one at the top level
    findings = _lint_snippet(tmp_path, "lib/api.py", (
        "import sys\n"
        "if sys.platform == 'linux':\n"
        "    def realize(n):\n"
        "        assert n >= 1\n"
    ), rules=["no-bare-assert"])
    assert {f.line for f in findings} == {4}


def test_assert_rule_skips_tests_and_scripts(tmp_path):
    for rel in ("tests/test_x.py", "scripts/probe.py"):
        findings = _lint_snippet(tmp_path, rel,
                                 "def run(n):\n    assert n\n",
                                 rules=["no-bare-assert"])
        assert findings == [], rel


# -- fstring-placeholder ------------------------------------------------------


def test_placeholder_flagged_at_raise_and_log_sites(tmp_path):
    bad = _lint_snippet(tmp_path, "lib/e.py", (
        "def fail(name, log):\n"
        "    log.warn('method {name} is slow')\n"
        "    raise ValueError('unknown method {name!r}')\n"
    ))
    assert _rules(bad) == ["fstring-placeholder"]
    assert len(bad) == 2


def test_placeholder_passes_fstrings_format_and_escapes(tmp_path):
    good = _lint_snippet(tmp_path, "lib/e.py", (
        "def fail(name):\n"
        "    raise ValueError(f'unknown method {name}')\n"
        "def fail2(name):\n"
        "    raise ValueError('unknown method {}'.format(name))\n"
        "def fail3():\n"
        "    raise ValueError('literal braces {{x}} are fine')\n"
        "def fail4(name):\n"
        "    raise ValueError('config shape: {\"a\": 1} etc')\n"
    ))
    assert good == []


# -- host-sync-in-hot-loop ----------------------------------------------------


def test_host_sync_flagged_in_traced_bodies(tmp_path):
    bad = _lint_snippet(tmp_path, "lib/hot.py", (
        "import time\n"
        "import jax\n"
        "def make_step():\n"
        "    def body(x):\n"
        "        t = time.time()\n"            # trace-time constant
        "        return x + t\n"
        "    return jax.jit(body)\n"
    ))
    assert _rules(bad) == ["host-sync-in-hot-loop"]
    assert "trace-time constant" in bad[0].message


def test_host_sync_propagates_through_called_helpers(tmp_path):
    bad = _lint_snippet(tmp_path, "lib/hot.py", (
        "import jax\n"
        "def helper(x):\n"
        "    return float(x.item())\n"         # reached from traced body
        "def make_step():\n"
        "    def body(x):\n"
        "        return helper(x)\n"
        "    return jax.jit(body)\n"
    ), rules=["host-sync-in-hot-loop"])
    assert bad and all(f.rule == "host-sync-in-hot-loop" for f in bad)


def test_host_sync_ignores_host_code(tmp_path):
    good = _lint_snippet(tmp_path, "lib/host.py", (
        "import time\n"
        "def time_loop(fn, state):\n"
        "    t0 = time.perf_counter()\n"
        "    state = fn(state)\n"
        "    return state, time.perf_counter() - t0\n"
    ), rules=["host-sync-in-hot-loop"])
    assert good == []


# -- suppression pragmas ------------------------------------------------------


def test_inline_disable_honored_same_line_and_line_above(tmp_path):
    good = _lint_snippet(tmp_path, "lib/api.py", (
        "def realize(n):\n"
        "    assert n >= 1  # lint: disable=no-bare-assert\n"
        "    # lint: disable=no-bare-assert (documented: perf-critical)\n"
        "    assert n < 100\n"
    ), rules=["no-bare-assert"])
    assert good == []


def test_unknown_rule_in_disable_rejected_loudly(tmp_path):
    findings = _lint_snippet(tmp_path, "lib/api.py", (
        "def realize(n):\n"
        "    assert n >= 1  # lint: disable=no-bear-assert\n"
    ))
    rules = _rules(findings)
    assert "bad-pragma" in rules            # the typo'd pragma is loud
    assert "no-bare-assert" in rules        # ...and suppresses nothing


def test_unknown_rule_filter_raises():
    with pytest.raises(ValueError, match="unknown rule"):
        lint_paths(["stencil_tpu"], rules=["no-such-rule"])


# -- baseline workflow --------------------------------------------------------


def test_baseline_roundtrip_and_resurface_on_edit(tmp_path):
    src = "def realize(n):\n    assert n >= 1\n"
    f = tmp_path / "lib" / "api.py"
    f.parent.mkdir(parents=True)
    f.write_text(src)
    findings, _ = lint_paths([str(f)], repo_root=str(tmp_path),
                             rules=["no-bare-assert"])
    assert len(findings) == 1
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), findings)
    fps = load_baseline(str(bl))
    assert findings[0].fingerprint in fps

    # line shifts do NOT invalidate the baseline entry...
    f.write_text("import os\n\n" + src)
    again, _ = lint_paths([str(f)], repo_root=str(tmp_path),
                          rules=["no-bare-assert"])
    assert again[0].fingerprint in fps
    # ...but editing the offending line resurfaces the finding
    f.write_text(src.replace("n >= 1", "n >= 2"))
    edited, _ = lint_paths([str(f)], repo_root=str(tmp_path),
                           rules=["no-bare-assert"])
    assert edited[0].fingerprint not in fps


def test_fingerprints_distinct_across_same_basename_files(tmp_path):
    # identical offending lines in a/util.py and b/util.py must NOT
    # collide: baselining one would silently suppress the other
    src = "def realize(n):\n    assert n >= 1\n"
    for d in ("a", "b"):
        f = tmp_path / d / "util.py"
        f.parent.mkdir(parents=True)
        f.write_text(src)
    findings, _ = lint_paths([str(tmp_path / "a"), str(tmp_path / "b")],
                             repo_root=str(tmp_path),
                             rules=["no-bare-assert"])
    assert len(findings) == 2
    assert findings[0].fingerprint != findings[1].fingerprint


def test_malformed_baseline_is_loud(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text('{"not": "a baseline"}')
    with pytest.raises(ValueError, match="v1 lint baseline"):
        load_baseline(str(bl))


def test_committed_tree_lints_clean_against_committed_baseline():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings, errors = lint_paths(astlint.DEFAULT_PATHS, repo_root=root)
    assert not errors, errors
    baseline = load_baseline(os.path.join(root, "lint-baseline.json"))
    new = [f for f in findings if f.fingerprint not in baseline]
    assert new == [], [f.render() for f in new]


# -- plan conformance auditor -------------------------------------------------


@pytest.mark.parametrize("method", ["axis-composed", "direct26",
                                    "auto-spmd", "remote-dma"])
def test_plan_auditor_agrees_per_method(method):
    from stencil_tpu.analysis import verify_plan as vp

    configs = vp.sweep_configs(size=16, radius=2,
                               partitions=[(2, 2, 2)],
                               methods=[method],
                               qsets=[("float32", "float32")])
    res = vp.run_sweep(configs)
    assert res["checked"] == 1 and res["failed"] == 0, [
        v.to_json() for v in res["verdicts"]]


def test_plan_auditor_trips_on_perturbed_prediction():
    from stencil_tpu.analysis import verify_plan as vp

    configs = vp.sweep_configs(size=16, radius=2, partitions=[(2, 2, 2)],
                               methods=["axis-composed"],
                               qsets=[("float32",)])
    res = vp.run_sweep(configs, perturb_collectives=1)
    assert res["failed"] == 1
    v = res["verdicts"][0]
    bad = [c for c in v.checks if not c["ok"]]
    assert bad and bad[0]["name"] == "collectives_per_exchange"
    assert bad[0]["predicted"] == bad[0]["actual"] + 1


def test_plan_auditor_skips_infeasible_loudly():
    from stencil_tpu.analysis import verify_plan as vp

    configs = vp.sweep_configs(size=16, radius=2, partitions=[(3, 3, 3)],
                               methods=["axis-composed"],
                               qsets=[("float32",)])
    res = vp.run_sweep(configs)
    assert res["checked"] == 0 and res["skipped"] == 1
    assert "devices" in res["verdicts"][0].reason


def test_verify_plan_cli_exit2_when_nothing_analyzed(capsys):
    from stencil_tpu.apps import lint_tool

    rc = lint_tool.main(["verify-plan", "--partitions", "3x3x3",
                         "--quantities", "f32"])
    assert rc == 2
    assert "nothing analyzed" in capsys.readouterr().err


def test_verify_plan_emits_schema_valid_records(tmp_path):
    from stencil_tpu.analysis import verify_plan as vp
    from stencil_tpu.obs import telemetry

    out = tmp_path / "m.jsonl"
    rec = telemetry.Recorder(sink=str(out), app="test")
    configs = vp.sweep_configs(size=16, radius=2, partitions=[(2, 2, 2)],
                               methods=["axis-composed"],
                               qsets=[("float32",)])
    vp.run_sweep(configs, rec=rec)
    rec.close()
    n_ok, errors = telemetry.validate_jsonl(
        out.read_text().splitlines())
    assert errors == [] and n_ok >= 2


def test_run_sweep_restores_x64_flag():
    # the fp64 sweep flips jax_enable_x64 for itself and must restore
    # it — a leak would make a following jit-audit certify fp64-sel
    # programs the apps never run (infeasible partition: no compiles)
    import jax

    from stencil_tpu.analysis import verify_plan as vp

    configs = vp.sweep_configs(size=16, radius=2, partitions=[(3, 3, 3)],
                               methods=["axis-composed"],
                               qsets=[("float64",)])
    assert jax.config.jax_enable_x64  # conftest turns it on
    try:
        jax.config.update("jax_enable_x64", False)
        vp.run_sweep(configs)
        assert jax.config.jax_enable_x64 is False
    finally:
        jax.config.update("jax_enable_x64", True)


# -- jit audit ----------------------------------------------------------------


def test_jit_audit_passes_clean_loop():
    from stencil_tpu.analysis.jit_audit import run_audit

    r = run_audit(size=16, iters=10, chunk=4)
    assert r.ok and r.recompiles == 0 and r.transfer_trips == []
    assert r.steps == 10


def test_jit_audit_fails_on_injected_recompile():
    from stencil_tpu.analysis.jit_audit import run_audit

    r = run_audit(size=16, iters=10, chunk=4, inject="recompile")
    assert not r.ok and r.recompiles >= 1


def test_jit_audit_fails_on_injected_host_sync():
    from stencil_tpu.analysis.jit_audit import run_audit

    r = run_audit(size=16, iters=10, chunk=4, inject="host-sync")
    assert not r.ok and len(r.transfer_trips) >= 1
    assert "isallow" in r.transfer_trips[0]


def test_jit_audit_rejects_unknown_inject():
    from stencil_tpu.analysis.jit_audit import run_audit

    with pytest.raises(ValueError, match="unknown inject"):
        run_audit(inject="sleep")
