"""Astaroth MHD tests.

- config parser: values, comments, derived params, poison detection
  (reference: astaroth_utils.cu behavior)
- derivatives: 6th-order stencils against analytic sin/cos fields
  (reference: test/test_derivative.cu idiom)
- full distributed step vs an independent np.roll-based global reference
  (halo mechanics + region decomposition + RK3 wiring)
- reductions, init determinism, app smoke
"""

import numpy as np
import pytest

import jax

from stencil_tpu.astaroth import config as ac_config
from stencil_tpu.astaroth import fd
from stencil_tpu.astaroth import equations as eq
from stencil_tpu.astaroth.init import const_init, hash_init, radial_explosion_init, sin_init
from stencil_tpu.astaroth.integrate import FIELDS, make_astaroth_step, rk3_integrate
from stencil_tpu.astaroth.reductions import Reductions
from stencil_tpu.apps.astaroth import DEFAULT_CONF, decompose_zyx, run as astaroth_run
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius, Rect3
from stencil_tpu.parallel import HaloExchange, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks


# -- config -------------------------------------------------------------------


class TestConfig:
    def test_parse_reference_values(self):
        info, ok = ac_config.load_config(DEFAULT_CONF)
        # like the reference's default conf, AC_dt is intentionally unset
        # (the driver overrides it, astaroth.cu:578) -> poison check fires
        assert not ok and info.uninitialized() == ["AC_dt"]
        assert info.int_params["AC_nx"] == 256
        assert info.real_params["AC_dsx"] == pytest.approx(0.04908738521)
        assert info.real_params["AC_gamma"] == 0.5
        # derived (reference: astaroth_utils.cu:52-88)
        assert info.int_params["AC_mx"] == 256 + 6
        assert info.int_params["AC_nx_min"] == 3
        assert info.int_params["AC_nx_max"] == 259
        assert info.real_params["AC_inv_dsx"] == pytest.approx(1 / 0.04908738521)
        assert info.real_params["AC_cs2_sound"] == pytest.approx(1.0)

    def test_poison_detection(self):
        info = ac_config.AcMeshInfo()
        ac_config.parse_config("AC_nx = 8\nAC_ny = 8\nAC_nz = 8\n", info)
        assert "AC_dsx" in info.uninitialized()
        assert "AC_nx" not in info.uninitialized()

    def test_comments_ignored(self):
        info = ac_config.AcMeshInfo()
        ac_config.parse_config(
            "/* block\ncomment */\nAC_nx = 4 // trailing\n// AC_ny = 9\nAC_ny = 5\nAC_nz=6\n",
            info,
        )
        assert info.int_params["AC_nx"] == 4
        assert info.int_params["AC_ny"] == 5
        assert info.int_params["AC_nz"] == 6


# -- derivatives --------------------------------------------------------------


def periodic_padded(f_global: np.ndarray, r: int = 3) -> np.ndarray:
    """Pad a global [z,y,x] array with its periodic wrap."""
    return np.pad(f_global, r, mode="wrap")


class TestDerivatives:
    def setup_method(self):
        n = 32
        L = 2 * np.pi
        self.ds = L / n
        idx = np.arange(n) * self.ds
        self.z, self.y, self.x = np.meshgrid(idx, idx, idx, indexing="ij", sparse=True)
        self.rect = Rect3(Dim3(3, 3, 3), Dim3(3 + n, 3 + n, 3 + n))
        self.inv = 1.0 / self.ds

    def test_derx_sin(self):
        f = periodic_padded(np.sin(self.x) + 0 * self.z * self.y)
        got = np.asarray(fd.derx(f, self.rect, self.inv))
        want = np.broadcast_to(np.cos(self.x), got.shape)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_derzz_sin(self):
        f = periodic_padded(np.sin(self.z) + 0 * self.x * self.y)
        got = np.asarray(fd.derzz(f, self.rect, self.inv))
        want = np.broadcast_to(-np.sin(self.z), got.shape)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_derxy_product(self):
        f = periodic_padded(np.sin(self.x) * np.sin(self.y) + 0 * self.z)
        got = np.asarray(fd.derxy(f, self.rect, self.inv, self.inv))
        want = np.broadcast_to(np.cos(self.x) * np.cos(self.y), got.shape)
        np.testing.assert_allclose(got, want, atol=5e-5)

    def test_deryz_product(self):
        f = periodic_padded(np.sin(self.y) * np.sin(self.z) + 0 * self.x)
        got = np.asarray(fd.deryz(f, self.rect, self.inv, self.inv))
        want = np.broadcast_to(np.cos(self.y) * np.cos(self.z), got.shape)
        np.testing.assert_allclose(got, want, atol=5e-5)

    def test_laplace_plane_wave(self):
        f3 = np.sin(self.x + self.y + self.z)
        f = periodic_padded(f3)
        data = fd.field_data(f, self.rect, (self.inv, self.inv, self.inv))
        np.testing.assert_allclose(np.asarray(data.laplace()), -3 * f3, atol=2e-4)


# -- equations on trivial fields ---------------------------------------------


def make_constants():
    info, _ = ac_config.load_config(DEFAULT_CONF)
    return eq.Constants.from_info(info)


class TestEquationsTrivial:
    def test_all_rates_zero_on_uniform_fields(self):
        n = 8
        r = Rect3(Dim3(3, 3, 3), Dim3(3 + n, 3 + n, 3 + n))
        inv = (1.0, 1.0, 1.0)
        c = make_constants()
        fields = {
            "lnrho": np.full((n + 6,) * 3, 0.5),
            "entropy": np.full((n + 6,) * 3, 0.25),
        }
        for k in ("uux", "uuy", "uuz", "ax", "ay", "az"):
            fields[k] = np.full((n + 6,) * 3, 0.125)
        lnrho = fd.field_data(fields["lnrho"], r, inv)
        ss = fd.field_data(fields["entropy"], r, inv)
        uu = tuple(fd.field_data(fields[k], r, inv) for k in ("uux", "uuy", "uuz"))
        aa = tuple(fd.field_data(fields[k], r, inv) for k in ("ax", "ay", "az"))
        np.testing.assert_allclose(np.asarray(eq.continuity(uu, lnrho)), 0.0, atol=1e-12)
        for comp in eq.induction(c, uu, aa):
            np.testing.assert_allclose(np.asarray(comp), 0.0, atol=1e-12)
        for comp in eq.momentum(c, uu, lnrho, ss, aa):
            np.testing.assert_allclose(np.asarray(comp), 0.0, atol=1e-12)
        np.testing.assert_allclose(
            np.asarray(eq.entropy(c, ss, uu, lnrho, aa)), 0.0, atol=1e-12
        )


# -- RK3 ----------------------------------------------------------------------


def test_rk3_first_step_euler_third():
    # step 0: u + (1/3) f dt (reference: integration.cuh beta[1] = 1/3)
    got = rk3_integrate(0, 99.0, 2.0, 3.0, 0.5)
    assert got == pytest.approx(2.0 + (1.0 / 3.0) * 3.0 * 0.5)


def test_rk3_scalar_sequence_converges():
    # du/dt = -u with swap-per-substep: one full RK3 iteration should give
    # roughly exp(-dt) decay
    dt = 0.01
    curr, out = 1.0, 0.0
    for s in range(3):
        rate = -curr
        out = rk3_integrate(s, out, curr, rate, dt)
        curr, out = out, curr
    assert curr == pytest.approx(np.exp(-dt), rel=1e-6)


# -- full distributed step vs np.roll global reference ------------------------


def roll_field_data(f: np.ndarray, inv_ds) -> fd.FieldData:
    """Independent derivative implementation: periodic np.roll over the
    global array (no halos, no regions)."""

    def sh(dz, dy, dx):
        return np.roll(f, (-dz, -dy, -dx), (0, 1, 2))

    def first(axis_shift, inv):
        res = 0.0
        for i, cc in enumerate(fd.FIRST_COEFFS, start=1):
            res = res + cc * (sh(*axis_shift(i)) - sh(*axis_shift(-i)))
        return res * inv

    def second(axis_shift, inv):
        res = fd.SECOND_CENTER * f
        for i, cc in enumerate(fd.SECOND_COEFFS, start=1):
            res = res + cc * (sh(*axis_shift(i)) + sh(*axis_shift(-i)))
        return res * inv * inv

    def cross(shift_a, shift_b, inv_a, inv_b):
        res = 0.0
        for i, cc in enumerate(fd.CROSS_COEFFS, start=1):
            res = res + cc * (
                sh(*shift_a(i)) + sh(*shift_a(-i)) - sh(*shift_b(i)) - sh(*shift_b(-i))
            )
        return res * inv_a * inv_b

    ix, iy, iz = inv_ds
    return fd.FieldData(
        value=f,
        gx=first(lambda i: (0, 0, i), ix),
        gy=first(lambda i: (0, i, 0), iy),
        gz=first(lambda i: (i, 0, 0), iz),
        hxx=second(lambda i: (0, 0, i), ix),
        hxy=cross(lambda i: (0, i, i), lambda i: (0, -i, i), ix, iy),
        hxz=cross(lambda i: (i, 0, i), lambda i: (-i, 0, i), ix, iz),
        hyy=second(lambda i: (0, i, 0), iy),
        hyz=cross(lambda i: (i, i, 0), lambda i: (-i, i, 0), iy, iz),
        hzz=second(lambda i: (i, 0, 0), iz),
    )


def global_reference_iteration(fields, out, info, dt):
    """One reference-workload iteration (3 substeps over the same input,
    swap at the end) on global periodic arrays."""
    c = eq.Constants.from_info(info)
    inv = (
        info.real_params["AC_inv_dsx"],
        info.real_params["AC_inv_dsy"],
        info.real_params["AC_inv_dsz"],
    )
    for substep in range(3):
        lnrho = roll_field_data(fields["lnrho"], inv)
        ss = roll_field_data(fields["entropy"], inv)
        uu = tuple(roll_field_data(fields[k], inv) for k in ("uux", "uuy", "uuz"))
        aa = tuple(roll_field_data(fields[k], inv) for k in ("ax", "ay", "az"))
        rates = {"lnrho": np.asarray(eq.continuity(uu, lnrho))}
        for i, k in enumerate(("ax", "ay", "az")):
            rates[k] = np.asarray(eq.induction(c, uu, aa)[i])
        for i, k in enumerate(("uux", "uuy", "uuz")):
            rates[k] = np.asarray(eq.momentum(c, uu, lnrho, ss, aa)[i])
        rates["entropy"] = np.asarray(eq.entropy(c, ss, uu, lnrho, aa))
        for k in FIELDS:
            out[k] = np.asarray(rk3_integrate(substep, out[k], fields[k], rates[k], dt))
    return out, fields  # swap


def global_reference_iteration_swapping(fields, out, info, dt):
    """One TEXTBOOK low-storage RK3 iteration (each stage reads the
    previous stage's output — swap per substep) on global periodic
    arrays."""
    c = eq.Constants.from_info(info)
    inv = (
        info.real_params["AC_inv_dsx"],
        info.real_params["AC_inv_dsy"],
        info.real_params["AC_inv_dsz"],
    )
    for substep in range(3):
        lnrho = roll_field_data(fields["lnrho"], inv)
        ss = roll_field_data(fields["entropy"], inv)
        uu = tuple(roll_field_data(fields[k], inv) for k in ("uux", "uuy", "uuz"))
        aa = tuple(roll_field_data(fields[k], inv) for k in ("ax", "ay", "az"))
        rates = {"lnrho": np.asarray(eq.continuity(uu, lnrho))}
        for i, k in enumerate(("ax", "ay", "az")):
            rates[k] = np.asarray(eq.induction(c, uu, aa)[i])
        for i, k in enumerate(("uux", "uuy", "uuz")):
            rates[k] = np.asarray(eq.momentum(c, uu, lnrho, ss, aa)[i])
        rates["entropy"] = np.asarray(eq.entropy(c, ss, uu, lnrho, aa))
        for k in FIELDS:
            out[k] = np.asarray(
                rk3_integrate(substep, out[k], fields[k], rates[k], dt)
            )
        fields, out = out, fields  # feed each stage forward
    return fields, out


@pytest.mark.slow
@pytest.mark.parametrize("overlap", [True, False])
def test_swap_per_substep_matches_textbook_reference(overlap):
    """swap_per_substep=True (textbook low-storage RK3, each stage
    consuming a fresh exchange) vs the stage-feeding global reference —
    previously untested in either overlap mode."""
    n = 16
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(n, n, n)
    rng = np.random.RandomState(7)
    fields = {k: rng.randn(n, n, n) * 0.05 for k in FIELDS}
    fields["lnrho"] = fields["lnrho"] + 0.5

    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    step = make_astaroth_step(ex, info, dt=dt, overlap=overlap,
                              swap_per_substep=True)
    curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
    nxt = {k: shard_blocks(np.zeros((n, n, n)), spec, mesh) for k in FIELDS}
    curr, nxt = step(curr, nxt)
    got = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    ref_out = {k: np.zeros((n, n, n)) for k in FIELDS}
    ref_curr, _ = global_reference_iteration_swapping(dict(fields), ref_out,
                                                      info, dt)
    for k in FIELDS:
        np.testing.assert_allclose(got[k], ref_curr[k], rtol=1e-10, atol=1e-12,
                                   err_msg=k)


@pytest.mark.parametrize(
    "overlap,size",
    [
        (True, (16, 16, 16)),
        (False, (16, 16, 16)),
        # genuinely uneven 2x2x2 split (x blocks 10 and 9) — exercises the
        # remainder-partition exchange under the full workload
        (False, (19, 18, 14)),
        # uneven + overlap: masked interior write + dynamic-offset shells
        # (ops/shells.py, VERDICT r2 item 8)
        (True, (19, 18, 14)),
    ],
)
@pytest.mark.slow
def test_distributed_step_matches_global_reference(overlap, size):
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = size[0]
    info.int_params["AC_ny"] = size[1]
    info.int_params["AC_nz"] = size[2]
    info.update_builtin_params()
    dt = 1e-3

    size = Dim3(*size)
    n = (size.z, size.y, size.x)
    rng = np.random.RandomState(0)
    fields = {k: rng.randn(*n) * 0.05 for k in FIELDS}
    fields["lnrho"] = fields["lnrho"] + 0.5

    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    step = make_astaroth_step(ex, info, dt=dt, overlap=overlap)

    curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
    nxt = {k: shard_blocks(np.zeros(n), spec, mesh) for k in FIELDS}
    curr, nxt = step(curr, nxt)
    got = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    ref_out = {k: np.zeros(n) for k in FIELDS}
    ref_curr, _ = global_reference_iteration(dict(fields), ref_out, info, dt)

    for k in FIELDS:
        np.testing.assert_allclose(got[k], ref_curr[k], rtol=1e-10, atol=1e-12, err_msg=k)


@pytest.mark.slow
def test_two_iterations_match():
    """Second iteration consumes exchanged halos of RK3 output — catches
    stale-halo bugs that a single iteration can't."""
    n = 16
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(n, n, n)
    rng = np.random.RandomState(1)
    fields = {k: rng.randn(n, n, n) * 0.05 for k in FIELDS}
    fields["lnrho"] = fields["lnrho"] + 0.5

    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    step = make_astaroth_step(ex, info, dt=dt)
    curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
    nxt = {k: shard_blocks(np.zeros((n, n, n)), spec, mesh) for k in FIELDS}
    for _ in range(2):
        curr, nxt = step(curr, nxt)
    got = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    a = dict(fields)
    b = {k: np.zeros((n, n, n)) for k in FIELDS}
    for _ in range(2):
        a, b = global_reference_iteration(a, b, info, dt)
    for k in FIELDS:
        np.testing.assert_allclose(got[k], a[k], rtol=1e-9, atol=1e-11, err_msg=k)


# -- init + reductions + app --------------------------------------------------


def test_init_determinism_and_ranges():
    h = hash_init((8, 8, 8))
    assert h.min() >= -1.0 and h.max() <= 1.0
    np.testing.assert_array_equal(h, hash_init((8, 8, 8)))
    assert const_init((4, 4, 4), 0.5)[0, 0, 0] == 0.5
    s = sin_init((8, 16, 8))
    assert s.shape == (8, 16, 8)
    ux, uy, uz = radial_explosion_init((8, 8, 8))
    assert np.isfinite(ux).all() and np.isfinite(uy).all() and np.isfinite(uz).all()


def test_reductions_match_numpy():
    n = 8
    spec = GridSpec(Dim3(n, n, n), Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)
    rng = np.random.RandomState(2)
    f = rng.randn(n, n, n)
    arr = shard_blocks(f, spec, mesh)
    red = Reductions(ex)
    got = red.scal(arr)
    assert got["max"] == pytest.approx(f.max())
    assert got["min"] == pytest.approx(f.min())
    assert got["sum"] == pytest.approx(f.sum(), rel=1e-12)
    assert got["rms"] == pytest.approx(np.sqrt((f**2).mean()), rel=1e-12)
    # vector magnitude reduction
    g = rng.randn(n, n, n)
    h = rng.randn(n, n, n)
    got = red.vec(arr, shard_blocks(g, spec, mesh), shard_blocks(h, spec, mesh))
    mag = np.sqrt(f**2 + g**2 + h**2)
    assert got["max"] == pytest.approx(mag.max())
    assert got["rms"] == pytest.approx(np.sqrt((mag**2).mean()), rel=1e-12)


def test_decompose_zyx():
    assert decompose_zyx(8) == Dim3(2, 2, 2)
    assert decompose_zyx(2) == Dim3(1, 1, 2)  # z gets the first factor
    assert decompose_zyx(1) == Dim3(1, 1, 1)


@pytest.mark.slow
def test_app_smoke():
    r = astaroth_run(iters=2, nx=8, devices=jax.devices()[:8], reductions=True)
    assert r["iter_trimean_s"] > 0
    assert r["exch_trimean_s"] > 0
    assert r["global"] == Dim3(16, 16, 16)
    for k, v in r["reductions"].items():
        for stat in v.values():
            assert np.isfinite(stat)


def test_load_config_missing_extents_reports(tmp_path):
    """Missing AC_nx must surface in the poison report, not crash the
    derived-param computation."""
    p = tmp_path / "bad.conf"
    p.write_text("AC_dsx = 0.1\nAC_dsy = 0.1\nAC_dsz = 0.1\n")
    info, ok = ac_config.load_config(str(p))
    assert not ok
    assert "AC_nx" in info.uninitialized()


@pytest.mark.slow
def test_distributed_pallas_overlap_2x2x2_matches_xla():
    """Overlapped fused-Pallas path on a full 2x2x2 mesh (interpret mode),
    two iterations: substep 0 runs from pre-exchange data concurrently
    with the iteration's exchange, its multi-block shells re-integrated
    after — must match the fp32 XLA path (VERDICT r2 item 2a). Two
    iterations catch stale-halo reuse of the patched state."""
    n = 32  # per-block 16^3: the smallest y-aligned Pallas-supported split
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(n, n, n)
    rng = np.random.RandomState(3)
    fields = {
        k: (rng.randn(n, n, n) * 0.05).astype(np.float32) for k in FIELDS
    }
    fields["lnrho"] = fields["lnrho"] + np.float32(0.5)

    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        step = make_astaroth_step(
            ex, info, dt=dt, overlap=True, dtype="float32", **kwargs
        )
        curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
        nxt = {
            k: shard_blocks(np.zeros((n, n, n), np.float32), spec, mesh)
            for k in FIELDS
        }
        for _ in range(2):
            curr, nxt = step(curr, nxt)
        outs[label] = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    for k in FIELDS:
        np.testing.assert_allclose(
            outs["pallas"][k], outs["xla"][k], rtol=1e-5, atol=1e-7, err_msg=k
        )


@pytest.mark.slow
def test_distributed_pallas_overlap_mixed_mesh_matches_xla():
    """Regression (r3 review): a mesh with BOTH a multi-block axis and
    self-wrap axes, e.g. z split over 2 devices with y/x periodic onto
    themselves. Substep 0's kernel pass reads pre-exchange halos on every
    axis and this kernel has no in-kernel wrap, so the overlap patch must
    re-integrate shells on ALL sides — covering only multi-block sides
    corrupted the self-wrap boundaries (max err ~0.22 at 32^3)."""
    n = 32
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(n, n, n)
    rng = np.random.RandomState(5)
    fields = {
        k: (rng.randn(n, n, n) * 0.05).astype(np.float32) for k in FIELDS
    }
    fields["lnrho"] = fields["lnrho"] + np.float32(0.5)

    spec = GridSpec(size, Dim3(1, 1, 2), Radius.constant(3))  # z split only
    mesh = grid_mesh(spec.dim, jax.devices()[:2])
    ex = HaloExchange(spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas", dict(use_pallas=True, interpret=True)),
        ("xla", dict(use_pallas=False)),
    ):
        step = make_astaroth_step(
            ex, info, dt=dt, overlap=True, dtype="float32", **kwargs
        )
        curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
        nxt = {
            k: shard_blocks(np.zeros((n, n, n), np.float32), spec, mesh)
            for k in FIELDS
        }
        for _ in range(2):
            curr, nxt = step(curr, nxt)
        outs[label] = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    for k in FIELDS:
        np.testing.assert_allclose(
            outs["pallas"][k], outs["xla"][k], rtol=1e-5, atol=1e-7, err_msg=k
        )


@pytest.mark.slow
def test_distributed_pallas_overlap_uneven_matches_xla():
    """Fused-Pallas overlap on a genuinely uneven 2x2x2 split (x blocks 10
    and 9; interpret mode): substep 0's full kernel pass from pre-exchange
    data, then dynamic-offset shells on every side — must match the
    serialized fp32 XLA path (VERDICT r2 item 8)."""
    nx, ny, nz = 19, 16, 14
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = nx
    info.int_params["AC_ny"] = ny
    info.int_params["AC_nz"] = nz
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(nx, ny, nz)
    rng = np.random.RandomState(7)
    fields = {
        k: (rng.randn(nz, ny, nx) * 0.05).astype(np.float32) for k in FIELDS
    }
    fields["lnrho"] = fields["lnrho"] + np.float32(0.5)

    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    assert not spec.is_uniform()
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh)

    outs = {}
    for label, kwargs in (
        ("pallas-overlap", dict(use_pallas=True, interpret=True, overlap=True)),
        ("xla-serial", dict(use_pallas=False, overlap=False)),
    ):
        step = make_astaroth_step(ex, info, dt=dt, dtype="float32", **kwargs)
        curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
        nxt = {
            k: shard_blocks(np.zeros((nz, ny, nx), np.float32), spec, mesh)
            for k in FIELDS
        }
        for _ in range(2):
            curr, nxt = step(curr, nxt)
        outs[label] = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    for k in FIELDS:
        np.testing.assert_allclose(
            outs["pallas-overlap"][k], outs["xla-serial"][k],
            rtol=1e-5, atol=1e-7, err_msg=k,
        )


@pytest.mark.slow
@pytest.mark.parametrize("mesh_dim,ndev", [((2, 2, 1), 4), ((1, 1, 2), 2)])
def test_resident_pallas_step_matches_xla(mesh_dim, ndev):
    """Resident (oversubscribed) shards on the fused Pallas path (VERDICT
    r4 item 7): the per-block substep kernel runs once per stacked
    resident — z-stack (2,2,1 mesh) and mixed (cy,cx) residency (1,1,2
    mesh) must both match the serialized XLA path."""
    n = 16
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(n, n, n)
    rng = np.random.RandomState(13)
    fields = {
        k: (rng.randn(n, n, n) * 0.05).astype(np.float32) for k in FIELDS
    }
    fields["lnrho"] = fields["lnrho"] + np.float32(0.5)

    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(Dim3(*mesh_dim), jax.devices()[:ndev])
    ex = HaloExchange(spec, mesh)
    assert ex.oversubscribed

    outs = {}
    for label, kwargs in (
        ("pallas-overlap", dict(use_pallas=True, interpret=True, overlap=True)),
        ("xla-serial", dict(use_pallas=False, overlap=False)),
    ):
        step = make_astaroth_step(ex, info, dt=dt, dtype="float32", **kwargs)
        curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
        nxt = {
            k: shard_blocks(np.zeros((n, n, n), np.float32), spec, mesh)
            for k in FIELDS
        }
        for _ in range(2):
            curr, nxt = step(curr, nxt)
        outs[label] = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    for k in FIELDS:
        np.testing.assert_allclose(
            outs["pallas-overlap"][k], outs["xla-serial"][k],
            rtol=1e-5, atol=1e-7, err_msg=k,
        )


@pytest.mark.slow
def test_oversubscribed_distributed_step_matches_reference():
    """2x2x2 split on 4 devices (2 z-blocks resident per device): the full
    RK3 iteration must match the np.roll global reference."""
    n = 16
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(n, n, n)
    rng = np.random.RandomState(1)
    fields = {k: rng.randn(n, n, n) * 0.05 for k in FIELDS}
    fields["lnrho"] = fields["lnrho"] + 0.5

    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(Dim3(2, 2, 1), jax.devices()[:4])
    ex = HaloExchange(spec, mesh)
    assert ex.resident_z == 2
    step = make_astaroth_step(ex, info, dt=dt, overlap=True)
    curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
    nxt = {k: shard_blocks(np.zeros((n, n, n)), spec, mesh) for k in FIELDS}
    curr, nxt = step(curr, nxt)
    got = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    ref_out = {k: np.zeros((n, n, n)) for k in FIELDS}
    ref_curr, _ = global_reference_iteration(dict(fields), ref_out, info, dt)
    for k in FIELDS:
        np.testing.assert_allclose(got[k], ref_curr[k], rtol=1e-10, atol=1e-12,
                                   err_msg=k)


@pytest.mark.slow
def test_oversubscribed_two_devices_matches_reference():
    """2x2x2 split on TWO devices — mixed (cz, cy) = (2, 2) stacking
    (VERDICT r3 item 4 'done' bar): the full RK3 iteration must match the
    np.roll global reference."""
    n = 16
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = info.int_params["AC_nz"] = n
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(n, n, n)
    rng = np.random.RandomState(2)
    fields = {k: rng.randn(n, n, n) * 0.05 for k in FIELDS}
    fields["lnrho"] = fields["lnrho"] + 0.5

    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(Dim3(2, 1, 1), jax.devices()[:2])
    ex = HaloExchange(spec, mesh)
    assert ex.resident == Dim3(1, 2, 2)
    step = make_astaroth_step(ex, info, dt=dt, overlap=True)
    curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
    nxt = {k: shard_blocks(np.zeros((n, n, n)), spec, mesh) for k in FIELDS}
    curr, nxt = step(curr, nxt)
    got = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    ref_out = {k: np.zeros((n, n, n)) for k in FIELDS}
    ref_curr, _ = global_reference_iteration(dict(fields), ref_out, info, dt)
    for k in FIELDS:
        np.testing.assert_allclose(got[k], ref_curr[k], rtol=1e-10, atol=1e-12,
                                   err_msg=k)


def test_oversubscribed_uneven_xy_overlap_falls_back():
    """Resident z-stacking + uneven x/y + overlap=True used to crash at
    trace time in _integrate_region_dyn's reshape (ADVICE r3); it must take
    the serialized path and match the global reference."""
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    # x = 10+9 (uneven), y = 9+9, z = 8+8 (uniform, required for residency)
    info.int_params["AC_nx"] = 19
    info.int_params["AC_ny"] = 18
    info.int_params["AC_nz"] = 16
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(19, 18, 16)
    n = (size.z, size.y, size.x)
    rng = np.random.RandomState(5)
    fields = {k: rng.randn(*n) * 0.05 for k in FIELDS}
    fields["lnrho"] = fields["lnrho"] + 0.5

    spec = GridSpec(size, Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(Dim3(2, 2, 1), jax.devices()[:4])
    ex = HaloExchange(spec, mesh)
    assert ex.resident_z == 2
    step = make_astaroth_step(ex, info, dt=dt, overlap=True)
    curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
    nxt = {k: shard_blocks(np.zeros(n), spec, mesh) for k in FIELDS}
    curr, nxt = step(curr, nxt)
    got = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    ref_out = {k: np.zeros(n) for k in FIELDS}
    ref_curr, _ = global_reference_iteration(dict(fields), ref_out, info, dt)
    for k in FIELDS:
        np.testing.assert_allclose(got[k], ref_curr[k], rtol=1e-10, atol=1e-12,
                                   err_msg=k)


def test_reductions_on_oversubscribed_mesh():
    """Masked reductions with 2 z-blocks resident per device: the local
    reduce spans the residents, the collectives run over the smaller mesh."""
    n = 8
    spec = GridSpec(Dim3(n, n, n), Dim3(2, 2, 2), Radius.constant(3))
    mesh = grid_mesh(Dim3(2, 2, 1), jax.devices()[:4])
    ex = HaloExchange(spec, mesh)
    assert ex.resident_z == 2
    rng = np.random.RandomState(3)
    f = rng.randn(n, n, n)
    red = Reductions(ex)
    got = red.scal(shard_blocks(f, spec, mesh))
    assert got["max"] == pytest.approx(f.max())
    assert got["min"] == pytest.approx(f.min())
    assert got["sum"] == pytest.approx(f.sum(), rel=1e-12)
    assert got["rms"] == pytest.approx(np.sqrt((f**2).mean()), rel=1e-12)


@pytest.mark.slow
def test_tight_x_multiblock_yz_matches_reference():
    """Tight-x with MULTI-BLOCK y/z axes (dim 1x2x2): the fused substep
    wraps x by lane rolls, y/z halos ride the exchange, and the overlap
    shells integrate over x-wrapped slabs (_integrate_shell_wrap_x). Two
    iterations (the second consumes exchanged RK3 output) must match the
    global np.roll reference (VERDICT r3 item 5 beyond single-block)."""
    nx, ny, nz = 128, 16, 16
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = nx
    info.int_params["AC_ny"] = ny
    info.int_params["AC_nz"] = nz
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(nx, ny, nz)
    rng = np.random.RandomState(23)
    fields = {
        k: (rng.randn(nz, ny, nx) * 0.05).astype(np.float32) for k in FIELDS
    }
    fields["lnrho"] = fields["lnrho"] + np.float32(0.5)

    spec = GridSpec(size, Dim3(1, 2, 2), Radius.constant(3).without_x())
    assert spec.padded().x == nx and spec.compute_offset().x == 0
    from stencil_tpu.ops.pallas_astaroth import substep_supported
    import jax.numpy as jnp
    assert substep_supported(spec, jnp.float32)
    mesh = grid_mesh(spec.dim, jax.devices()[:4])
    ex = HaloExchange(spec, mesh)
    step = make_astaroth_step(ex, info, dt=dt, dtype="float32",
                              use_pallas=True, interpret=True, overlap=True)
    curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
    nxt = {
        k: shard_blocks(np.zeros((nz, ny, nx), np.float32), spec, mesh)
        for k in FIELDS
    }
    for _ in range(2):
        curr, nxt = step(curr, nxt)
    got = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    f64 = {k: fields[k].astype(np.float64) for k in FIELDS}
    ref_out = {k: np.zeros((nz, ny, nx)) for k in FIELDS}
    ref_curr, ref_out = global_reference_iteration(dict(f64), ref_out, info, dt)
    ref_curr, _ = global_reference_iteration(ref_curr, ref_out, info, dt)
    for k in FIELDS:
        np.testing.assert_allclose(got[k], ref_curr[k], rtol=2e-4, atol=1e-6,
                                   err_msg=k)


def test_tight_x_rejects_multiblock_x():
    """Documented envelope: the tight-x astaroth substep requires a
    single-BLOCK x axis (an x-split would need r=3 side buffers with
    edge-halo composition; the TPU decomposition never splits x —
    geometry.decompose_zy). The gate must reject loudly, not miscompute."""
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = 256
    info.int_params["AC_ny"] = info.int_params["AC_nz"] = 16
    info.update_builtin_params()
    spec = GridSpec(Dim3(256, 16, 16), Dim3(2, 1, 1),
                    Radius.constant(3).without_x())
    mesh = grid_mesh(spec.dim, jax.devices()[:2])
    ex = HaloExchange(spec, mesh)
    with pytest.raises(ValueError, match="single-block x axis"):
        make_astaroth_step(ex, info, dt=1e-3, dtype="float32",
                           use_pallas=True, interpret=True)


@pytest.mark.slow
def test_tight_x_layout_matches_inline_reference():
    """Radius.without_x on a single block (px == nx, x pencils via lane
    rolls): the fused substep must match the global np.roll reference,
    exactly like the inline-halo layout does."""
    nx, ny, nz = 128, 16, 14
    info = ac_config.AcMeshInfo()
    with open(DEFAULT_CONF) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = nx
    info.int_params["AC_ny"] = ny
    info.int_params["AC_nz"] = nz
    info.update_builtin_params()
    dt = 1e-3
    size = Dim3(nx, ny, nz)
    rng = np.random.RandomState(17)
    fields = {
        k: (rng.randn(nz, ny, nx) * 0.05).astype(np.float32) for k in FIELDS
    }
    fields["lnrho"] = fields["lnrho"] + np.float32(0.5)

    spec = GridSpec(size, Dim3(1, 1, 1), Radius.constant(3).without_x())
    assert spec.padded().x == nx and spec.compute_offset().x == 0
    from stencil_tpu.ops.pallas_astaroth import substep_supported
    import jax.numpy as jnp
    assert substep_supported(spec, jnp.float32)
    mesh = grid_mesh(spec.dim, jax.devices()[:1])
    ex = HaloExchange(spec, mesh)
    step = make_astaroth_step(ex, info, dt=dt, dtype="float32",
                              use_pallas=True, interpret=True)
    curr = {k: shard_blocks(fields[k], spec, mesh) for k in FIELDS}
    nxt = {
        k: shard_blocks(np.zeros((nz, ny, nx), np.float32), spec, mesh)
        for k in FIELDS
    }
    for _ in range(2):
        curr, nxt = step(curr, nxt)
    got = {k: unshard_blocks(curr[k], spec) for k in FIELDS}

    f64 = {k: fields[k].astype(np.float64) for k in FIELDS}
    ref_out = {k: np.zeros((nz, ny, nx)) for k in FIELDS}
    ref_curr, ref_out = global_reference_iteration(dict(f64), ref_out, info, dt)
    ref_curr, _ = global_reference_iteration(ref_curr, ref_out, info, dt)
    for k in FIELDS:
        np.testing.assert_allclose(got[k], ref_curr[k], rtol=2e-4, atol=1e-6,
                                   err_msg=k)
