"""Symmetric/antisymmetric boundary conditions vs a numpy mirror
(reference: astaroth/boundconds.cuh — intended semantics; the reference
kernel's write line is disabled, see boundconds.py docstring)."""

import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.astaroth.boundconds import (
    ANTISYMMETRIC,
    PERIODIC,
    SYMMETRIC,
    antisymmetric,
    apply_boundconds,
    symmetric,
)
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius


def _spec():
    return GridSpec(Dim3(16, 16, 12), Dim3(1, 1, 1), Radius.constant(3))


def _mirror_np(base, spec, axis, sign):
    want = base.copy()
    off = spec.compute_offset()
    o = {"z": off.z, "y": off.y, "x": off.x}[axis]
    sz = {"z": spec.base.z, "y": spec.base.y, "x": spec.base.x}[axis]
    dim = {"z": 0, "y": 1, "x": 2}[axis]
    b0, b1 = o, o + sz - 1
    for g in range(1, 4):
        sl_dst = [slice(None)] * 3
        sl_src = [slice(None)] * 3
        sl_dst[dim], sl_src[dim] = b0 - g, b0 + g
        want[tuple(sl_dst)] = sign * base[tuple(sl_src)]
        sl_dst[dim], sl_src[dim] = b1 + g, b1 - g
        want[tuple(sl_dst)] = sign * base[tuple(sl_src)]
    return want


@pytest.mark.parametrize("axis", ["x", "y", "z"])
@pytest.mark.parametrize("sign,fn", [(1, symmetric), (-1, antisymmetric)])
def test_mirror_matches_numpy(axis, sign, fn):
    spec = _spec()
    p = spec.padded()
    rng = np.random.RandomState(3)
    base = rng.rand(p.z, p.y, p.x).astype(np.float32)
    got = np.asarray(fn(jnp.asarray(base), spec, axis))
    np.testing.assert_array_equal(got, _mirror_np(base, spec, axis, sign))


def test_apply_boundconds_mixed():
    spec = _spec()
    p = spec.padded()
    rng = np.random.RandomState(4)
    base = rng.rand(p.z, p.y, p.x).astype(np.float32)
    got = np.asarray(
        apply_boundconds(
            jnp.asarray(base), spec,
            {"x": SYMMETRIC, "y": ANTISYMMETRIC, "z": PERIODIC},
        )
    )
    want = _mirror_np(base, spec, "x", 1)
    want = _mirror_np(want, spec, "y", -1)
    np.testing.assert_array_equal(got, want)
    # periodic z is left to the exchange: the z ghost planes still hold
    # their ORIGINAL values in the interior x/y region (only the x/y
    # mirrors may touch ghost columns/rows within them)
    off = spec.compute_offset()
    iy = slice(off.y, off.y + spec.base.y)
    ix = slice(off.x, off.x + spec.base.x)
    np.testing.assert_array_equal(got[: off.z, iy, ix], base[: off.z, iy, ix])
    np.testing.assert_array_equal(
        got[off.z + spec.base.z :, iy, ix], base[off.z + spec.base.z :, iy, ix]
    )


def test_mirror_rejects_multiblock_axis():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 1, 1), Radius.constant(3))
    p = spec.padded()
    with pytest.raises(ValueError):
        symmetric(jnp.zeros((p.z, p.y, p.x)), spec, "x")
