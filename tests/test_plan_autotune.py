"""Autotuner + DistributedDomain wiring + ckpt plan provenance.

The production contracts: a tuned config REPLAYS from the DB with zero
probes (the cache-hit telemetry proves it), a corrupt DB degrades loudly
without being clobbered, the domain knobs actually apply the tuned
choice, and a checkpoint written under one plan warns when revived under
another. The probing test compiles small 16^3 exchanges on the virtual
8-device CPU mesh; everything else is backend-free.
"""

import json
import os

import numpy as np

import jax

from stencil_tpu.api import DistributedDomain
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import Method
from stencil_tpu.plan import db as plandb
from stencil_tpu.plan.autotune import autotune
from stencil_tpu.plan.ir import PlanChoice, PlanConfig


def test_autotune_probes_then_pure_db_hit(tmp_path):
    path = str(tmp_path / "plans.json")
    args = dict(size=(16, 16, 16), radius=Radius.constant(1),
                dtypes=["float32"] * 2, ndev=8, db_path=path)
    first = autotune(top_n=2, probe_iters=2, **args)
    assert not first.cache_hit and first.source == "probe"
    assert first.probes_run >= 1 and first.candidates > 10
    assert os.path.exists(path)
    second = autotune(**args)
    assert second.cache_hit and second.probes_run == 0
    assert second.choice == first.choice
    # the persisted entry carries provenance + probe evidence
    entry = plandb.lookup(plandb.load_db(path), first.config)
    assert entry["source"] == "probe"
    assert any("trimean_s" in p for p in entry["probes"])


def test_seeded_entry_replays_without_backend_or_probes(tmp_path):
    # a seed/DB hit never compiles: ndev+platform are explicit, so the
    # whole call is file I/O + dict lookups
    path = str(tmp_path / "plans.json")
    cfg = PlanConfig.make(Dim3(128, 128, 128), Radius.constant(2),
                          ["float32"] * 4, 8, "cpu")
    choice = PlanChoice(partition=(2, 2, 2), method="axis-composed")
    db = plandb.empty_db()
    plandb.record(db, plandb.make_entry(cfg, choice, "seed",
                                        measured_s=0.0262))
    plandb.save_db(path, db)
    res = autotune((128, 128, 128), Radius.constant(2), ["float32"] * 4,
                   ndev=8, platform="cpu", db_path=path)
    assert res.cache_hit and res.probes_run == 0
    assert res.choice == choice and res.entry["source"] == "seed"


def test_static_only_run_needs_no_probe(tmp_path):
    res = autotune((64, 64, 64), Radius.constant(2), ["float32"] * 4,
                   ndev=8, platform="cpu", probe=False,
                   db_path=str(tmp_path / "p.json"))
    assert res.source == "static" and res.probes_run == 0
    assert res.ranked and res.choice == res.ranked[0][1]


def test_corrupt_db_degrades_without_clobbering(tmp_path, capfd):
    path = str(tmp_path / "plans.json")
    with open(path, "w") as f:
        f.write("{ this is not json")
    before = open(path).read()
    res = autotune((64, 64, 64), Radius.constant(2), ["float32"] * 2,
                   ndev=8, platform="cpu", probe=False, db_path=path)
    assert res.source == "static"
    assert open(path).read() == before, "corrupt DB must not be overwritten"
    assert "rejected" in capfd.readouterr().err


def test_domain_set_plan_applies_choice():
    choice = PlanChoice(partition=(2, 2, 2), method="direct26",
                        batch_quantities=False)
    dd = DistributedDomain(16, 16, 16, plan=choice.to_json())
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    dd.add_data("t", "float32")
    dd.realize()
    assert dd._method == Method.DIRECT26
    assert not dd._batch_quantities
    assert dd.spec.dim == Dim3(2, 2, 2)
    assert dd.plan_choice == choice
    meta = dd.plan_meta()
    assert meta["choice"]["method"] == "direct26"
    assert meta["tuned"]


def test_domain_autotune_knob_records_result(tmp_path):
    path = str(tmp_path / "plans.json")
    dd = DistributedDomain(16, 16, 16, autotune=True, plan_db=path)
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    dd.add_data("t", "float32")
    dd.realize()
    assert dd.autotune_result is not None
    assert dd.plan_choice == dd.autotune_result.choice
    assert Dim3.of(dd.plan_choice.partition) == dd.spec.dim
    # a second domain at the same config replays from the DB
    dd2 = DistributedDomain(16, 16, 16, autotune=True, plan_db=path)
    dd2.set_radius(1)
    dd2.set_devices(jax.devices()[:8])
    dd2.add_data("t", "float32")
    dd2.realize()
    assert dd2.autotune_result.cache_hit
    assert dd2.autotune_result.probes_run == 0
    assert dd2.plan_choice == dd.plan_choice


def test_explicit_partition_beats_tuned_plan(capfd):
    choice = PlanChoice(partition=(2, 2, 2), method="direct26")
    dd = DistributedDomain(16, 16, 16, plan=choice.to_json())
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    dd.set_partition((1, 2, 4))
    dd.add_data("t", "float32")
    dd.realize()
    assert dd.spec.dim == Dim3(1, 2, 4)
    assert "overrides" in capfd.readouterr().err
    # the choice was tuned as a unit: overriding its partition must also
    # drop its method/batching, not apply them to a partition they were
    # never measured on
    assert dd._method == Method.AXIS_COMPOSED
    assert dd.plan_choice is None and not dd.plan_meta()["tuned"]


def test_ckpt_manifest_records_plan_and_resume_warns(tmp_path, capfd):
    ck = str(tmp_path / "ck")

    def make(method):
        dd = DistributedDomain(16, 16, 16)
        dd.set_radius(1)
        dd.set_methods(method)
        dd.set_devices(jax.devices()[:8])
        h = dd.add_data("t", "float32")
        dd.realize()
        return dd, h

    dd, h = make(Method.AXIS_COMPOSED)
    field = np.arange(16 ** 3, dtype=np.float32).reshape(16, 16, 16)
    dd.set_curr_global(h, field)
    dd.save_checkpoint(ck, 3, asynchronous=False)
    # the manifest carries the plan provenance
    snaps = [e for e in os.listdir(ck) if e.startswith("step-")]
    manifest = json.load(open(os.path.join(ck, snaps[0], "manifest.json")))
    plan = manifest["meta"]["plan"]
    assert plan["choice"]["method"] == "axis-composed"
    assert plan["key"]["grid"] == [16, 16, 16]
    capfd.readouterr()

    # same plan -> restores silently
    dd2, h2 = make(Method.AXIS_COMPOSED)
    assert dd2.restore_checkpoint(ck) == 3
    assert "exchange plan" not in capfd.readouterr().err
    np.testing.assert_array_equal(dd2.get_curr_global(h2), field)

    # different plan -> bit-exact restore, LOUD provenance warning
    dd3, h3 = make(Method.DIRECT26)
    assert dd3.restore_checkpoint(ck) == 3
    err = capfd.readouterr().err
    assert "exchange plan" in err and "differ" in err
    np.testing.assert_array_equal(dd3.get_curr_global(h3), field)


def test_autotune_without_quantities_warns_and_skips(capfd):
    # a quantity-less realize() is legal; autotune has nothing to key on
    # and must skip with a warning instead of crashing
    dd = DistributedDomain(16, 16, 16, autotune=True)
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    dd.realize()
    assert dd.autotune_result is None
    assert "no quantities" in capfd.readouterr().err
