"""Method.REMOTE_DMA — kernel-initiated halo exchange, pinned on the CPU
emulation (ISSUE 10 / ROADMAP #2).

The claims under test:

- **0 ppermutes**: a lowered REMOTE_DMA exchange contains ZERO
  collective-permutes — ``collective_census`` over EVERY compiled piece
  of the emulation comes back permute-free, and the recorded
  ``exchange.permutes_per_quantity`` gauge reads 0.
- **bit parity**: the emulation (host-initiated per-neighbor
  device-to-device copies of the composed-phase slabs) is bit-identical
  to AXIS_COMPOSED on uniform, uneven, and oversubscribed partitions,
  fp32/fp64/mixed dicts, and the full jacobi step.
- **Q-independent DMA count**: the per-dtype packed carrier keeps the
  emulated transfer count independent of the quantity count (PR-5
  geometry).
- **bf16 on the wire**: the compression knob halves the lowered-module
  wire bytes at an unchanged permute count, within the wire dtype's
  rounding bound, and never touches local/self-wrap movement.

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import os

import jax
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.plan.ir import REMOTE_DMA, PlanChoice, PlanConfig, build_plan


def _state(spec, mesh, nq, dtypes=None):
    g = spec.global_size
    base = (
        np.arange(g.z)[:, None, None] * 1_000_000.0
        + np.arange(g.y)[None, :, None] * 1_000.0
        + np.arange(g.x)[None, None, :]
    )
    out = {}
    for i in range(nq):
        dt = dtypes[i] if dtypes else np.float32
        out[i] = shard_blocks((base + i).astype(dt), spec, mesh)
    return out


def _gather(state):
    return np.stack(
        [np.asarray(jax.device_get(state[i])) for i in sorted(state)]
    )


# -- plan IR -------------------------------------------------------------------


def test_remote_plan_predicts_zero_permutes_and_dma_count():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    plan = build_plan(spec, Dim3(2, 2, 2), REMOTE_DMA)
    assert plan.collectives_per_exchange(1, 1) == 0
    assert plan.collectives_per_exchange(8, 1) == 0
    # 2 async copies per axis phase, Q-independent per dtype group
    assert plan.dmas_per_exchange(1, 1) == 6
    assert plan.dmas_per_exchange(8, 1) == 6
    assert plan.dmas_per_exchange(8, 2) == 12   # two dtype groups
    # the wire model is literally the composed one
    composed = build_plan(spec, Dim3(2, 2, 2), Method.AXIS_COMPOSED)
    assert plan.wire_bytes([4, 4]) == composed.wire_bytes([4, 4])
    assert "dmas=2" in plan.describe()
    assert "0 ppermutes" in plan.describe()


def test_remote_plan_self_wrap_has_no_dmas():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 1, 1), Radius.constant(1))
    plan = build_plan(spec, Dim3(2, 1, 1), REMOTE_DMA)
    x, y, z = plan.remote_phases
    assert x.dmas() == 2 and y.dmas() == 0 and z.dmas() == 0
    assert plan.dmas_per_exchange(4, 1) == 2


def test_wire_dtype_byte_model():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    native = build_plan(spec, Dim3(2, 2, 2), Method.AXIS_COMPOSED)
    bf16 = build_plan(spec, Dim3(2, 2, 2), Method.AXIS_COMPOSED,
                      wire_dtype="bfloat16")
    assert native.wire_bytes([4, 4]) == 2 * bf16.wire_bytes([4, 4])
    # fp64 narrows to 2 bytes on the wire too (4x)
    assert native.wire_bytes([8]) == 4 * bf16.wire_bytes([8])
    # local bytes never compress
    assert native.local_bytes([4]) == bf16.local_bytes([4])
    # integer quantities never narrow (the lowering keeps them native,
    # so the byte model must too): an int32 + fp32 pair compresses only
    # the float half
    assert bf16.wire_bytes([4, 4], floating=[False, True]) == \
        native.wire_bytes([4]) + bf16.wire_bytes([4])
    cfg = PlanConfig.make(Dim3(16, 16, 16), Radius.constant(1),
                          ["int32", "float32"], 8)
    assert cfg.floating_flags() == (True, False) or \
        cfg.floating_flags() == (False, True)
    # aligned with itemsizes(): sorted dtype order puts float32 first
    assert list(zip(cfg.itemsizes(), cfg.floating_flags())) == \
        [(4, True), (4, False)]


def test_wire_narrow_dtype_policy():
    import jax.numpy as jnp

    from stencil_tpu.ops.halo_fill import wire_narrow_dtype

    assert wire_narrow_dtype(jnp.float32, "bfloat16") == jnp.dtype("bfloat16")
    assert wire_narrow_dtype(jnp.float64, "bfloat16") == jnp.dtype("bfloat16")
    assert wire_narrow_dtype(jnp.float32, None) is None
    # never widens, never touches ints
    assert wire_narrow_dtype(jnp.bfloat16, "float32") is None
    assert wire_narrow_dtype(jnp.float32, "float32") is None
    assert wire_narrow_dtype(jnp.int32, "bfloat16") is None


# -- census + parity -----------------------------------------------------------


def test_remote_census_has_zero_ppermutes():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, Method.REMOTE_DMA)
    census = ex.collective_census(_state(spec, mesh, 2))
    assert census.get("collective-permute", (0, 0))[0] == 0
    # nothing else snuck onto the collective path either
    assert sum(c for c, _b in census.values()) == 0, census


def test_remote_permutes_per_quantity_gauge_reads_zero(tmp_path):
    from stencil_tpu.obs import telemetry

    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, Method.REMOTE_DMA)
    state = _state(spec, mesh, 2)
    sink = str(tmp_path / "m.jsonl")
    rec = telemetry.Recorder(sink=sink, run_id="r", app="test")
    telemetry.record_exchange_truth(ex, state, [4, 4], rec=rec)
    rec.close()
    import json

    recs = [json.loads(ln) for ln in open(sink) if ln.strip()]
    gauges = {r["name"]: r for r in recs if r["kind"] == "gauge"}
    assert gauges["exchange.permutes_per_quantity"]["value"] == 0.0
    on_wire = [r for r in recs if r["name"] == "exchange.bytes_on_wire"]
    assert on_wire and on_wire[0]["bytes"] == 0  # nothing on the XLA path


def test_remote_transfer_count_q_independent():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    counts = {}
    for nq in (1, 4):
        ex = HaloExchange(spec, mesh, Method.REMOTE_DMA)
        ex(_state(spec, mesh, nq))
        counts[nq] = ex._remote.last_transfer_count
    # 8 devices x (2 copies per active ring phase) — independent of Q
    assert counts[1] == counts[4] == 8 * 6
    # per-quantity mode scales with Q, like the ppermute baseline
    ex = HaloExchange(spec, mesh, Method.REMOTE_DMA, batch_quantities=False)
    ex(_state(spec, mesh, 4))
    assert ex._remote.last_transfer_count == 4 * 8 * 6


@pytest.mark.parametrize("name,size,dim,mesh_dim,ndev,dtypes", [
    ("uniform", (16, 16, 16), (2, 2, 2), (2, 2, 2), 8, None),
    ("uneven", (17, 19, 16), (2, 2, 2), (2, 2, 2), 8, None),
    ("oversubscribed", (16, 16, 16), (2, 2, 2), (2, 2, 1), 4, None),
    ("mixed-dtype", (16, 16, 16), (2, 2, 2), (2, 2, 2), 8,
     [np.float32, np.float64, np.float32]),
    ("uneven-oversub-f64", (17, 16, 16), (2, 2, 2), (2, 1, 2), 4,
     [np.float64, np.float64]),
])
def test_remote_bit_parity_vs_composed(name, size, dim, mesh_dim, ndev,
                                       dtypes):
    spec = GridSpec(Dim3(*size), Dim3(*dim), Radius.constant(1))
    mesh = grid_mesh(Dim3(*mesh_dim), jax.devices()[:ndev])
    nq = len(dtypes) if dtypes else 2
    outs = {}
    for method in (Method.AXIS_COMPOSED, Method.REMOTE_DMA):
        ex = HaloExchange(spec, mesh, method)
        out = ex(_state(spec, mesh, nq, dtypes))
        outs[method] = [np.asarray(jax.device_get(out[i]))
                        for i in sorted(out)]
    for a, b in zip(outs[Method.AXIS_COMPOSED], outs[Method.REMOTE_DMA]):
        np.testing.assert_array_equal(a, b, err_msg=name)


def test_remote_make_loop_matches_repeated_composed():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    exr = HaloExchange(spec, mesh, Method.REMOTE_DMA)
    exc = HaloExchange(spec, mesh, Method.AXIS_COMPOSED)
    sr = exr.make_loop(3)(_state(spec, mesh, 2))
    sc = exc.make_loop(3)(_state(spec, mesh, 2))
    np.testing.assert_array_equal(_gather(sr), _gather(sc))


def test_remote_full_jacobi_step_parity():
    import jax.numpy as jnp

    from stencil_tpu.api import DistributedDomain
    from stencil_tpu.ops.jacobi import INIT_TEMP, make_jacobi_loop, sphere_sel

    def run(method):
        dd = DistributedDomain(16, 16, 16)
        dd.set_radius(1)
        dd.set_methods(method)
        dd.set_devices(jax.devices()[:8])
        h = dd.add_data("t", "float32")
        dd.realize()
        dd.set_curr_global(h, np.full((16, 16, 16), INIT_TEMP, np.float32))
        sel = shard_blocks(sphere_sel((16, 16, 16)), dd.spec, dd.mesh)
        loop = make_jacobi_loop(dd.halo_exchange, 4)
        c = dd.get_curr(h)
        n = jax.device_put(jnp.zeros_like(c), dd.sharding())
        c, _n = loop(c, n, sel)
        dd.set_curr(h, c)
        return dd.get_curr_global(h)

    np.testing.assert_array_equal(
        run(Method.AXIS_COMPOSED), run(Method.REMOTE_DMA))


def test_remote_has_no_per_block_body():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, Method.REMOTE_DMA)
    with pytest.raises(RuntimeError, match="REMOTE_DMA"):
        ex.exchange_blocks({0: None})


# -- bf16 on the wire ----------------------------------------------------------


def test_wire_compression_halves_lowered_wire_bytes():
    from stencil_tpu.utils.hlo_check import stablehlo_wire_census

    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    st = _state(spec, mesh, 2)
    cens = {}
    for wd in (None, "bfloat16"):
        ex = HaloExchange(spec, mesh, Method.AXIS_COMPOSED, wire_dtype=wd)
        cens[wd] = stablehlo_wire_census(
            ex._compiled.lower(st).as_text())
    cp_n = cens[None]["collective-permute"]
    cp_w = cens["bfloat16"]["collective-permute"]
    assert cp_n[0] == cp_w[0] == 6      # count unchanged (Q=2, batched)
    assert cp_n[1] == 2 * cp_w[1]       # bytes halved
    # and the plan model predicts the same ratio
    exw = HaloExchange(spec, mesh, Method.AXIS_COMPOSED,
                      wire_dtype="bfloat16")
    exn = HaloExchange(spec, mesh, Method.AXIS_COMPOSED)
    assert exn.plan.wire_bytes([4, 4]) == 2 * exw.plan.wire_bytes([4, 4])


def test_wire_compression_error_bounded_and_lossless_locally():
    # one multi-block axis (wire) + two self-wrap axes (local): the wire
    # halos round to bf16, the self-wrap halos stay bit-exact
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 1, 1), Radius.constant(1))
    mesh = grid_mesh(Dim3(2, 1, 1), jax.devices()[:2])
    outs = {}
    for wd in (None, "bfloat16"):
        ex = HaloExchange(spec, mesh, Method.AXIS_COMPOSED, wire_dtype=wd)
        outs[wd] = _gather(ex(_state(spec, mesh, 1)))
    a, b = outs[None], outs["bfloat16"]
    rel = np.abs(a - b) / np.maximum(np.abs(a), 1.0)
    assert 0 < rel.max() <= 2 ** -8    # rounded, within bf16 half-ulp
    # self-wrap y halo rows are pure local copies: bit-identical over the
    # compute-x columns (the x-halo columns they carry crossed the wire
    # in the earlier x phase and legitimately rounded)
    off = spec.compute_offset()
    xs = slice(off.x, off.x + spec.base.x)
    np.testing.assert_array_equal(a[..., off.y - 1, xs],
                                  b[..., off.y - 1, xs])
    np.testing.assert_array_equal(a[..., off.y + spec.base.y, xs],
                                  b[..., off.y + spec.base.y, xs])


def test_wire_compression_parity_remote_vs_composed():
    # the lossy knob must stay CONSISTENT across transports: remote-dma
    # with bf16 wire equals composed with bf16 wire bit-for-bit
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    outs = {}
    for method in (Method.AXIS_COMPOSED, Method.REMOTE_DMA):
        ex = HaloExchange(spec, mesh, method, wire_dtype="bfloat16")
        outs[method] = _gather(ex(_state(spec, mesh, 2)))
    np.testing.assert_array_equal(outs[Method.AXIS_COMPOSED],
                                  outs[Method.REMOTE_DMA])


def test_wire_dtype_ignored_for_auto_spmd(capfd):
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, Method.AUTO_SPMD, wire_dtype="bfloat16")
    assert ex.wire_dtype is None
    assert "ignored" in capfd.readouterr().err


# -- cost model + autotuner + DB ----------------------------------------------


def test_remote_dma_cost_entry_and_platform_split():
    from stencil_tpu.plan.cost import (DEFAULT_CALIBRATION,
                                       enumerate_candidates, rank, score)

    assert "remote_dma" in DEFAULT_CALIBRATION
    assert "modeled" in DEFAULT_CALIBRATION["remote_dma"]["provenance"]
    mk = lambda platform: PlanConfig.make(
        Dim3(24, 24, 24), Radius.constant(2), ["float32"] * 4, 8, platform)
    # cpu: the emulation penalty keeps remote-dma BELOW the recorded
    # composed winner (static-only rankings must not change on this mesh)
    ranked_cpu = rank(mk("cpu"), enumerate_candidates(mk("cpu")))
    assert ranked_cpu[0][1].method == "axis-composed"
    # tpu: the modeled kernel-initiated transport competes (and its cost
    # carries the 0-permute / dma split for plan_tool explain)
    ranked_tpu = rank(mk("tpu"), enumerate_candidates(mk("tpu")))
    best_remote = next(
        (c, ch) for c, ch in ranked_tpu if ch.method == REMOTE_DMA)
    assert best_remote[0].collectives == 0
    assert best_remote[0].dmas > 0
    # remote-dma candidates are scored for every config
    sc = score(mk("cpu"), PlanChoice(partition=(2, 2, 2), method=REMOTE_DMA))
    assert sc is not None and sc.collectives == 0 and sc.dmas == 6


def test_autotune_persists_remote_dma_keyed_entry(tmp_path):
    from stencil_tpu.plan import db as plandb
    from stencil_tpu.plan.autotune import autotune

    db_path = str(tmp_path / "plans.json")
    res = autotune(
        Dim3(16, 16, 16), Radius.constant(1), ["float32"],
        ndev=8, platform="cpu", db_path=db_path, probe=False,
        methods=("remote-dma",),
    )
    assert res.choice.method == "remote-dma"
    db = plandb.load_db(db_path)   # validates: remote-dma is a known method
    entry = plandb.lookup(db, res.config)
    assert entry is not None
    assert PlanChoice.from_json(entry["choice"]).method == "remote-dma"
    # and a second run replays it as a pure DB hit
    res2 = autotune(
        Dim3(16, 16, 16), Radius.constant(1), ["float32"],
        ndev=8, platform="cpu", db_path=db_path, probe=False,
        methods=("remote-dma",),
    )
    assert res2.cache_hit and res2.choice.method == "remote-dma"


# -- ckpt plan-mismatch satellite ---------------------------------------------


def _make_domain(method, wire_dtype=None):
    from stencil_tpu.api import DistributedDomain

    dd = DistributedDomain(16, 16, 16)
    dd.set_radius(1)
    dd.set_methods(method)
    if wire_dtype:
        dd.set_wire_dtype(wire_dtype)
    dd.set_devices(jax.devices()[:8])
    h = dd.add_data("t", "float32")
    dd.realize()
    return dd, h


def test_ckpt_restore_warns_on_remote_dma_plan_mismatch(tmp_path, capfd):
    ck = str(tmp_path / "ck")
    dd, h = _make_domain(Method.REMOTE_DMA)
    field = np.arange(16 ** 3, dtype=np.float32).reshape(16, 16, 16)
    dd.set_curr_global(h, field)
    dd.save_checkpoint(ck, 2, asynchronous=False)
    capfd.readouterr()
    # a snapshot written under REMOTE_DMA restoring under COMPOSED warns
    # (names both methods) and restores bit-exactly — never crashes
    dd2, h2 = _make_domain(Method.AXIS_COMPOSED)
    assert dd2.restore_checkpoint(ck) == 2
    err = capfd.readouterr().err
    assert "exchange plan" in err and "remote-dma" in err
    np.testing.assert_array_equal(dd2.get_curr_global(h2), field)


def test_ckpt_restore_survives_unknown_future_method(tmp_path, capfd):
    import json

    ck = str(tmp_path / "ck")
    dd, h = _make_domain(Method.AXIS_COMPOSED)
    field = np.arange(16 ** 3, dtype=np.float32).reshape(16, 16, 16)
    dd.set_curr_global(h, field)
    dd.save_checkpoint(ck, 2, asynchronous=False)
    # rewrite the manifest's plan with a method this build does not know
    snaps = [e for e in os.listdir(ck) if e.startswith("step-")]
    mpath = os.path.join(ck, snaps[0], "manifest.json")
    manifest = json.load(open(mpath))
    manifest["meta"]["plan"]["choice"]["method"] = "quantum-teleport"
    json.dump(manifest, open(mpath, "w"))
    capfd.readouterr()
    dd2, h2 = _make_domain(Method.AXIS_COMPOSED)
    assert dd2.restore_checkpoint(ck) == 2   # warns, never crashes
    err = capfd.readouterr().err
    assert "unknown to this build" in err
    np.testing.assert_array_equal(dd2.get_curr_global(h2), field)


def test_ckpt_restore_warns_on_wire_dtype_delta(tmp_path, capfd):
    ck = str(tmp_path / "ck")
    dd, h = _make_domain(Method.AXIS_COMPOSED, wire_dtype="bfloat16")
    field = np.arange(16 ** 3, dtype=np.float32).reshape(16, 16, 16)
    dd.set_curr_global(h, field)
    dd.save_checkpoint(ck, 2, asynchronous=False)
    capfd.readouterr()
    dd2, h2 = _make_domain(Method.AXIS_COMPOSED)
    assert dd2.restore_checkpoint(ck) == 2
    err = capfd.readouterr().err
    assert "wire_dtype" in err
