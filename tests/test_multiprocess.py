"""2-process x 4-virtual-CPU-device distributed exchange test.

Exercises the multi-host code path end to end — jax.distributed
initialization, NodePartition's host-level outer split, cross-process
ppermutes over Gloo — without a cluster, the way the reference exercises
its colocated/MPI transports with 2 ranks on one node
(reference: test/CMakeLists.txt:49, mpi_topology.hpp:20-30)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_exchange():
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "_mp_worker.py")
    port = _free_port()
    env = dict(os.environ)
    # the workers configure their own backend (4 CPU devices each); drop the
    # test harness's own virtual-device setting so it cannot interfere
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=here,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    if any("Multiprocess computations aren't implemented on the CPU backend"
           in out for out in outs):
        # some jaxlib builds ship without Gloo CPU collectives; the workers
        # still exercised jax.distributed init + domain construction
        pytest.skip("jaxlib built without CPU multiprocess collectives")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert f"MP_WORKER_OK rank={rank}" in out, out[-2000:]
