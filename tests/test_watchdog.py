"""Unit tests for obs/watchdog.py — the revival watcher.

The injected-stall test is the CI requirement from ISSUE 3: a child that
beats, then sleeps past the heartbeat deadline, must be detected as a
STALL (not a timeout), killed, retried, and the ladder reported — with
the child log archived at every rung.
"""

import json
import os
import sys
import textwrap

from stencil_tpu.obs import watchdog

PY = sys.executable
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Beats the heartbeat file three times, then wedges far past any deadline.
STALL_CHILD = textwrap.dedent(
    """
    import os, time
    hb = os.environ["STENCIL_HEARTBEAT_FILE"]
    for _ in range(3):
        with open(hb, "w") as f:
            f.write(str(time.time()))
        time.sleep(0.2)
    print("beaten; wedging now", flush=True)
    time.sleep(300)
    """
)


def test_supervise_ok_captures_stdout():
    att = watchdog.supervise([PY, "-c", "print('RESULT 42')"],
                             timeout_s=60, name="ok")
    assert att.outcome == watchdog.OK
    assert att.rc == 0
    assert "RESULT 42" in att.stdout


def test_supervise_distinguishes_crash():
    att = watchdog.supervise([PY, "-c", "import sys; sys.exit(3)"],
                             timeout_s=60, name="crash")
    assert att.outcome == watchdog.CRASH
    assert att.rc == 3


def test_supervise_timeout_kills_and_archives(tmp_path):
    att = watchdog.supervise(
        [PY, "-c", "import time; print('partial', flush=True); time.sleep(300)"],
        timeout_s=2.0, poll_s=0.1, name="sleeper",
        archive_dir=str(tmp_path),
    )
    assert att.outcome == watchdog.TIMEOUT
    assert att.rc is None
    # pre-kill output survives (file-backed, not pipe-backed)
    assert "partial" in att.stdout
    assert att.log_path and os.path.exists(att.log_path)
    assert "partial" in open(att.log_path).read()


def test_supervise_detects_stall_before_budget():
    """The injected stall: beats, then silence past the heartbeat deadline
    — killed as STALL long before the 120 s total budget."""
    att = watchdog.supervise(
        [PY, "-c", STALL_CHILD],
        timeout_s=120, heartbeat_timeout_s=1.5, first_beat_grace_s=60,
        poll_s=0.1, name="staller",
    )
    assert att.outcome == watchdog.STALL
    assert att.rc is None
    assert att.seconds < 60  # the heartbeat deadline fired, not the budget
    assert "beaten; wedging now" in att.stdout


def test_supervise_never_beaten_uses_first_beat_grace():
    att = watchdog.supervise(
        [PY, "-c", "import time; time.sleep(300)"],
        timeout_s=120, heartbeat_timeout_s=60, first_beat_grace_s=1.5,
        poll_s=0.1, name="mute",
    )
    assert att.outcome == watchdog.STALL
    assert att.seconds < 60


def test_telemetry_heartbeats_feed_the_watchdog():
    """The integration the bench children rely on: heartbeats emitted by
    stencil_tpu.obs.telemetry (configure() starts the beat thread) keep a
    healthy child alive under a tight between-beats deadline."""
    child = textwrap.dedent(
        """
        import time
        from stencil_tpu.obs import telemetry
        rec = telemetry.configure(app="hb-child")
        for _ in range(4):
            rec.heartbeat()
            time.sleep(0.3)
        print("HB_OK", flush=True)
        """
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env[watchdog.HEARTBEAT_INTERVAL_ENV] = "0.5"
    att = watchdog.supervise(
        [PY, "-c", child],
        timeout_s=180, heartbeat_timeout_s=5.0, first_beat_grace_s=150,
        poll_s=0.1, name="telemetry-child", env=env, cwd=REPO,
    )
    assert att.outcome == watchdog.OK, (att.outcome, att.stderr_tail)
    assert "HB_OK" in att.stdout


def _parse_result(stdout):
    for line in stdout.splitlines():
        if line.startswith("RESULT "):
            try:
                return json.loads(line[len("RESULT "):])
            except json.JSONDecodeError:
                return None
    return None


def test_revival_detect_kill_retry_report(tmp_path):
    """The full ladder: stall detected -> killed -> retried with a healthy
    child -> payload delivered -> both attempts reported + archived."""
    rev = watchdog.Revival(budget_s=120, parse=_parse_result,
                           archive_dir=str(tmp_path), min_attempt_s=1.0)
    p1 = rev.attempt("stall-rung", [PY, "-c", STALL_CHILD], timeout_s=60,
                     heartbeat_timeout_s=1.5, first_beat_grace_s=60)
    assert p1 is None
    p2 = rev.attempt(
        "good-rung", [PY, "-c", "print('RESULT {\"value\": 7}')"],
        timeout_s=30,
    )
    assert p2 == {"value": 7}
    assert [a.outcome for a in rev.attempts] == [watchdog.STALL, watchdog.OK]
    assert all(a.log_path and os.path.exists(a.log_path)
               for a in rev.attempts)
    rep = rev.report()
    assert rep[0]["outcome"] == "stall" and rep[1]["outcome"] == "ok"


def test_revival_no_result_and_budget_refusal():
    rev = watchdog.Revival(budget_s=60, parse=_parse_result,
                           min_attempt_s=1.0)
    assert rev.attempt("empty", [PY, "-c", "print('nothing')"],
                       timeout_s=30) is None
    assert rev.attempts[0].outcome == watchdog.NO_RESULT
    spent = watchdog.Revival(budget_s=0.0, parse=_parse_result)
    assert spent.attempt("refused", [PY, "-c", "print(1)"],
                         timeout_s=30) is None
    assert spent.attempts == []  # refused before spawning
    # the floor overrides an exhausted budget (the last-resort rung)
    assert spent.attempt(
        "floored", [PY, "-c", "print('RESULT {\"v\": 1}')"],
        timeout_s=30, floor_timeout_s=30.0,
    ) == {"v": 1}


def test_supervise_classifies_fault_rc():
    """rc 43 (stencil_tpu.fault.recover's rollback-exhausted abort) is
    the FAULT outcome — distinct from a generic crash — and the contract
    constant matches the fault package's."""
    from stencil_tpu.fault import FAULT_RC

    assert watchdog.FAULT_RC == FAULT_RC == 43
    att = watchdog.supervise([PY, "-c", "import sys; sys.exit(43)"],
                             timeout_s=60, name="faulting")
    assert att.outcome == watchdog.FAULT
    assert att.rc == 43
    # an explicit fault_rc=None turns the classification off
    att = watchdog.supervise([PY, "-c", "import sys; sys.exit(43)"],
                             timeout_s=60, name="plain", fault_rc=None)
    assert att.outcome == watchdog.CRASH


def test_supervise_archives_metrics_evidence(tmp_path):
    """On a bad outcome the child's metrics JSONL is archived next to the
    log (auto-detected from STENCIL_METRICS_OUT in the child's env) —
    post-mortems get telemetry, not just stdout."""
    metrics = str(tmp_path / "child-metrics.jsonl")
    child = (
        "import os, sys\n"
        "open(os.environ['STENCIL_METRICS_OUT'], 'w')"
        ".write('{\"fake\": 1}\\n')\n"
        "sys.exit(43)\n"
    )
    env = dict(os.environ)
    env["STENCIL_METRICS_OUT"] = metrics
    att = watchdog.supervise([PY, "-c", child], timeout_s=60, env=env,
                             name="evidence", archive_dir=str(tmp_path / "a"))
    assert att.outcome == watchdog.FAULT
    assert att.metrics_log_path and os.path.exists(att.metrics_log_path)
    assert att.metrics_log_path.endswith(".metrics.jsonl")
    assert open(att.metrics_log_path).read() == '{"fake": 1}\n'
    assert att.summary()["metrics"] == att.metrics_log_path
    # a healthy child's metrics are NOT archived (evidence is for failures)
    env2 = dict(env)
    att2 = watchdog.supervise([PY, "-c", "print('fine')"], timeout_s=60,
                              env=env2, name="healthy",
                              archive_dir=str(tmp_path / "a"))
    assert att2.outcome == watchdog.OK
    assert att2.metrics_log_path is None
