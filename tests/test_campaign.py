"""Multi-tenant batched campaigns (stencil_tpu/campaign/).

The ISSUE-9 acceptance pins:

- batched-vs-sequential BIT-parity at B in {1, 4}, fp32 and fp64 — every
  tenant served by the batched (B, pz, py, px) program finishes
  bit-identical to the same tenant run through the standard
  single-domain machinery;
- deterministic slot packing / backfill order;
- an injected ``nan@K:tenant=...:repeat=always`` tenant is EVICTED with
  rc-43 evidence while its siblings finish bit-identical to a clean
  campaign (and the evicted tenant is revivable from its snapshot);
- the second same-shape slot is a pure compile-cache hit
  (``compile.cache_hit`` == 1, zero new ``compile.build`` spans);
- the campaign/compile telemetry vocabulary is schema-gated;
- report span tables grow the optional p99 column and split the
  campaign A/B's ``mode`` tag.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest
import jax

from stencil_tpu.campaign import (
    CampaignDriver,
    CompileCache,
    TenantJob,
    plan_slots,
    run_sequential,
    tenant_init_field,
)
from stencil_tpu.obs import telemetry
from stencil_tpu.obs.telemetry import validate_record
from stencil_tpu.obs.watchdog import FAULT_RC

N = 12
STEPS = 4


def jobs_for(n_jobs, dtype="float32", size=N, steps=STEPS, seed0=10):
    return [TenantJob(f"t{i}", (size, size, size), steps, dtype,
                      seed=seed0 + i) for i in range(n_jobs)]


def finals(summary):
    return {t: r.final for t, r in summary["results"].items()
            if r.outcome == "done"}


# -- batched vs sequential bit-parity -----------------------------------------


@pytest.mark.parametrize("dtype", ["float32", "float64"])
@pytest.mark.parametrize("slot", [1, 4])
def test_batched_matches_sequential_bitwise(tmp_path, dtype, slot):
    jobs = jobs_for(3, dtype)  # 3 jobs: B=4 exercises a dead padding lane
    seq = run_sequential(jobs, devices=jax.devices()[:8], chunk=2)
    bat = CampaignDriver(jobs, slot, str(tmp_path / "c"), chunk=2,
                         devices=jax.devices()[:8]).run()
    assert bat["evicted"] == []
    sf, bf = finals(seq), finals(bat)
    assert set(sf) == set(bf) == {j.tid for j in jobs}
    for tid in sf:
        assert bf[tid].dtype == np.dtype(dtype)
        assert bf[tid].tobytes() == sf[tid].tobytes(), (
            f"tenant {tid} diverged between batched (B={slot}) and "
            "sequential")
    # throughput accounting covers every tenant step
    cells = N ** 3
    assert bat["cell_steps"] == len(jobs) * STEPS * cells
    assert np.isfinite(bat["p50_step_s"]) and np.isfinite(bat["p99_step_s"])
    assert bat["p99_step_s"] >= bat["p50_step_s"]


# -- slot packing / backfill determinism --------------------------------------


def test_plan_slots_fifo_bucketed():
    jobs = [
        TenantJob("a0", (12, 12, 12), 4),
        TenantJob("b0", (10, 10, 10), 4),
        TenantJob("a1", (12, 12, 12), 4),
        TenantJob("a2", (12, 12, 12), 4),
        TenantJob("b1", (10, 10, 10), 4),
        TenantJob("a3", (12, 12, 12), 4),
    ]
    got = plan_slots(jobs, 3)
    # bucket of the queue head first; same-bucket jobs pulled forward in
    # FIFO order; the fourth 12^3 job overflows into a later slot
    assert got == [
        (((12, 12, 12), "float32", "jacobi"), ["a0", "a1", "a2"]),
        (((10, 10, 10), "float32", "jacobi"), ["b0", "b1"]),
        (((12, 12, 12), "float32", "jacobi"), ["a3"]),
    ]
    # pure + deterministic
    assert got == plan_slots(jobs, 3)


def test_backfill_order_is_deterministic(tmp_path):
    """6 jobs through B=2 slots: retirement backfills FIFO from the
    queue, so two identical campaigns record identical slot/backfill
    sequences."""
    orders = []
    for run_i in range(2):
        m = tmp_path / f"m{run_i}.jsonl"
        telemetry.configure(metrics_out=str(m), app="t")
        try:
            CampaignDriver(jobs_for(6), 2, str(tmp_path / f"c{run_i}"),
                           chunk=2, devices=jax.devices()[:8]).run()
        finally:
            telemetry.get().close()
        recs = [json.loads(l) for l in open(m) if l.strip()]
        orders.append([
            (r["name"], r.get("tenant") or ",".join(r.get("tenants", [])))
            for r in recs
            if r["name"] in ("campaign.slot", "campaign.backfill",
                             "campaign.retire")
        ])
    assert orders[0] == orders[1]
    # the first slot is t0/t1; backfills arrive in queue order
    backfills = [t for (n, t) in orders[0] if n == "campaign.backfill"]
    assert backfills == ["t2", "t3", "t4", "t5"]


# -- eviction: rc-43 evidence, surviving lanes bit-identical ------------------


def test_injected_tenant_evicted_survivors_bit_identical(tmp_path):
    jobs = jobs_for(5, steps=6)
    clean = CampaignDriver(jobs, 4, str(tmp_path / "clean"), chunk=2,
                           ckpt_every=2, max_rollbacks=1,
                           devices=jax.devices()[:8]).run()
    assert clean["evicted"] == []

    telemetry.configure(metrics_out=str(tmp_path / "m.jsonl"), app="t")
    try:
        inj = CampaignDriver(
            jobs, 4, str(tmp_path / "inj"), chunk=2, ckpt_every=2,
            max_rollbacks=1, rollback_backoff=0.01,
            inject="nan@3:tenant=t1:repeat=always",
            devices=jax.devices()[:8]).run()
    finally:
        telemetry.get().close()

    # the injected tenant is evicted with the rc-43 evidence bundle...
    assert inj["evicted"] == ["t1"]
    r1 = inj["results"]["t1"]
    assert r1.outcome == "fault"
    assert r1.evidence and os.path.isfile(r1.evidence)
    ev = json.load(open(r1.evidence))
    assert ev["rc"] == FAULT_RC
    assert "max rollbacks" in ev["reason"]
    # ...its lane was backfilled and every other tenant completed,
    # bit-identical to the uninjected campaign
    cf, inf_ = finals(clean), finals(inj)
    assert set(inf_) == {j.tid for j in jobs} - {"t1"}
    for tid in inf_:
        assert inf_[tid].tobytes() == cf[tid].tobytes(), tid
    # metrics: injection, per-lane fault, rollback, eviction all recorded
    recs = [json.loads(l) for l in open(tmp_path / "m.jsonl") if l.strip()]
    assert all(not validate_record(r) for r in recs)
    names = {r["name"] for r in recs}
    assert {"fault.injected", "health.fault", "recover.rollback",
            "campaign.evict", "campaign.backfill"} <= names
    evict = [r for r in recs if r["name"] == "campaign.evict"]
    assert evict[0]["tenant"] == "t1" and evict[0]["rc"] == FAULT_RC

    # revivable: the evicted tenant's last healthy state is a snapshot;
    # a resumed single-tenant campaign finishes it bit-identical to clean
    rev = CampaignDriver([jobs[1]], 2, str(tmp_path / "inj"), chunk=2,
                         resume=True, devices=jax.devices()[:8]).run()
    rr = rev["results"]["t1"]
    assert rr.outcome == "done" and rr.steps == jobs[1].steps
    assert rr.final.tobytes() == cf["t1"].tobytes()


# -- compile cache: the second same-shape slot is a pure hit ------------------


def test_second_same_shape_slot_hits_compile_cache(tmp_path):
    cache = CompileCache()
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        CampaignDriver(jobs_for(2, seed0=0), 2, str(tmp_path / "c1"),
                       chunk=2, cache=cache,
                       devices=jax.devices()[:8]).run()
        misses_after_first = cache.misses
        first_lines = [json.loads(l) for l in open(m) if l.strip()]
        builds_after_first = sum(
            1 for r in first_lines if r["name"] == "compile.build")
        lookups_after_first = sum(
            1 for r in first_lines if r["name"] == "compile.cache_hit")
        CampaignDriver(jobs_for(2, seed0=9), 2, str(tmp_path / "c2"),
                       chunk=2, cache=cache,
                       devices=jax.devices()[:8]).run()
    finally:
        telemetry.get().close()
    # zero rebuilds: no new compile.build spans, no new misses
    assert cache.misses == misses_after_first
    assert cache.hits >= 1
    recs = [json.loads(l) for l in open(m) if l.strip()]
    builds = [r for r in recs if r["name"] == "compile.build"]
    assert len(builds) == builds_after_first == misses_after_first
    hits = [r for r in recs if r["name"] == "compile.cache_hit"]
    second = [r["value"] for r in hits[lookups_after_first:]]
    # the second campaign's lookups are all hits (gauge pinned at 1)
    assert second and all(v == 1 for v in second)
    for r in builds + hits:
        assert isinstance(r["key"], str) and '"grid"' in r["key"]


# -- the batched Pallas fast path (interpret mode) ----------------------------


@pytest.mark.slow
def test_batched_pallas_sweep_matches_xla(tmp_path):
    """The leading-batch-grid Pallas kernel (all-axes in-kernel wrap, one
    tile pass per tenant) is bit-identical to the XLA batched path —
    interpret mode, the CI stand-in for TPU hardware."""
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.ops.jacobi import make_batched_jacobi_loop, sphere_sel

    B, nx, ny, nz = 2, 128, 8, 8
    spec = GridSpec(Dim3(nx, ny, nz), Dim3(1, 1, 1), Radius.constant(1))
    p, off = spec.padded(), spec.compute_offset()
    rng = np.random.RandomState(5)
    curr = np.zeros((B, p.z, p.y, p.x), np.float32)
    sel = np.zeros((B, p.z, p.y, p.x), np.int32)
    sel_g = sphere_sel((nx, ny, nz))
    for b in range(B):
        curr[b, off.z:off.z + nz, off.y:off.y + ny, off.x:off.x + nx] = (
            rng.standard_normal((nz, ny, nx)).astype(np.float32))
        sel[b, off.z:off.z + nz, off.y:off.y + ny, off.x:off.x + nx] = sel_g
    nxt = np.zeros_like(curr)

    import jax.numpy as jnp

    xla = make_batched_jacobi_loop(spec, 1)
    pal = make_batched_jacobi_loop(spec, 1, use_pallas=True, batch=B,
                                   interpret=True)
    cx, _ = xla(jnp.asarray(curr), jnp.asarray(nxt), jnp.asarray(sel))
    cp, _ = pal(jnp.asarray(curr), jnp.asarray(nxt), jnp.asarray(sel))
    ix = np.asarray(cx)[:, off.z:off.z + nz, off.y:off.y + ny,
                        off.x:off.x + nx]
    ip = np.asarray(cp)[:, off.z:off.z + nz, off.y:off.y + ny,
                        off.x:off.x + nx]
    assert ix.tobytes() == ip.tobytes()


# -- the batched astaroth XLA path --------------------------------------------


@pytest.mark.slow
def test_batched_astaroth_matches_single_domain():
    """Each lane of make_batched_astaroth_step equals the single-domain
    make_astaroth_step hoisted-overlap iteration — same tolerance
    discipline as the astaroth suite (test_astaroth.py)."""
    import jax.numpy as jnp

    from stencil_tpu.astaroth import config as ac_config
    from stencil_tpu.astaroth.integrate import (
        FIELDS, make_astaroth_step, make_batched_astaroth_step)
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius
    from stencil_tpu.parallel import HaloExchange, grid_mesh
    from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks

    n, B, dt, iters = 16, 2, 1e-3, 2
    info = ac_config.AcMeshInfo()
    conf = os.path.join(os.path.dirname(__file__), "..", "stencil_tpu",
                        "astaroth", "astaroth.conf")
    with open(conf) as f:
        ac_config.parse_config(f.read(), info)
    info.int_params["AC_nx"] = info.int_params["AC_ny"] = n
    info.int_params["AC_nz"] = n
    info.update_builtin_params()
    rng = np.random.RandomState(11)
    tenants = []
    for _ in range(B):
        f = {k: rng.randn(n, n, n) * 0.05 for k in FIELDS}
        f["lnrho"] = f["lnrho"] + 0.5
        tenants.append(f)

    spec1 = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3))
    mesh1 = grid_mesh(spec1.dim, jax.devices()[:1])
    step = make_astaroth_step(HaloExchange(spec1, mesh1), info, dt=dt,
                              iters=iters)
    seq = []
    for b in range(B):
        curr = {k: shard_blocks(tenants[b][k], spec1, mesh1)
                for k in FIELDS}
        nxt = {k: shard_blocks(np.zeros((n, n, n)), spec1, mesh1)
               for k in FIELDS}
        curr, nxt = step(curr, nxt)
        seq.append({k: unshard_blocks(curr[k], spec1) for k in FIELDS})

    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3),
                    aligned=False)
    p, off = spec.padded(), spec.compute_offset()

    def pack(key):
        a = np.zeros((B, p.z, p.y, p.x))
        for b in range(B):
            a[b, off.z:off.z + n, off.y:off.y + n, off.x:off.x + n] = (
                tenants[b][key])
        return jnp.asarray(a)

    curr = {k: pack(k) for k in FIELDS}
    nxt = {k: jnp.zeros((B, p.z, p.y, p.x)) for k in FIELDS}
    bstep = make_batched_astaroth_step(spec, info, dt=dt, iters=iters)
    curr, nxt = bstep(curr, nxt)
    for b in range(B):
        for k in FIELDS:
            got = np.asarray(curr[k])[b, off.z:off.z + n, off.y:off.y + n,
                                      off.x:off.x + n]
            np.testing.assert_allclose(got, seq[b][k], rtol=1e-10,
                                       atol=1e-12, err_msg=f"{b}/{k}")


# -- telemetry vocabulary ------------------------------------------------------


def test_campaign_vocabulary_schema_gated():
    base = {"v": 1, "run": "r", "proc": 0, "t": 0.0}
    ok = dict(base, kind="meta", name="campaign.evict", tenant="t1",
              step=3, rc=43)
    assert validate_record(ok) == []
    for missing in ("tenant", "step", "rc"):
        bad = dict(ok)
        del bad[missing]
        assert any(missing in e for e in validate_record(bad))
    g = dict(base, kind="gauge", name="compile.cache_hit", value=1)
    assert any("key" in e for e in validate_record(g))
    assert validate_record(dict(g, key="k")) == []
    lat = dict(base, kind="gauge", name="campaign.step_latency_s",
               value=0.1)
    assert any("mode" in e for e in validate_record(lat))
    assert validate_record(dict(lat, mode="batched")) == []


# -- report: p99 span column + mode tag split ---------------------------------


def test_report_p99_column_and_mode_split():
    from stencil_tpu.apps.report import aggregate, tables

    def rec(kind, name, **kw):
        return dict({"v": 1, "run": "r", "proc": 0, "kind": kind,
                     "name": name, "t": 0.0}, **kw)

    records = [rec("span", "campaign.chunk", seconds=s, phase="step")
               for s in (0.01,) * 99 + (1.0,)]
    records += [rec("gauge", "campaign.step_latency_s", value=0.1,
                    mode="batched"),
                rec("gauge", "campaign.step_latency_s", value=9.0,
                    mode="sequential")]
    agg = aggregate(records)
    # the A/B modes never fold into one gauge row
    assert "campaign.step_latency_s[batched]" in agg["gauges"]
    assert "campaign.step_latency_s[sequential]" in agg["gauges"]
    out = tables(agg, p99=True)
    header = [l for l in out.splitlines() if l.startswith("span,")][0]
    assert header.endswith("p99_s")
    row = [l for l in out.splitlines() if l.startswith("campaign.chunk")][0]
    # p99 of 99x0.01 + 1x1.0 sits just above 0.01 — far from max
    p99 = float(row.split(",")[-1])
    assert 0.01 < p99 < 0.1
    # default stays the historical table (no new column)
    assert "p99_s" not in tables(agg)


# -- astaroth campaigns through the driver (ISSUE-10 satellite) ----------------


def test_workload_joins_the_bucket():
    jobs = [
        TenantJob("j0", (8, 8, 8), 2, "float64", workload="jacobi"),
        TenantJob("a0", (8, 8, 8), 2, "float64", workload="astaroth"),
        TenantJob("j1", (8, 8, 8), 2, "float64", workload="jacobi"),
        TenantJob("a1", (8, 8, 8), 2, "float64", workload="astaroth"),
    ]
    # jacobi and astaroth tenants never share a slot: their compiled
    # programs (and quantity sets) differ even at identical (size, dtype)
    slots = plan_slots(jobs, 4)
    assert [tids for _b, tids in slots] == [["j0", "j1"], ["a0", "a1"]]
    assert slots[0][0][2] == "jacobi" and slots[1][0][2] == "astaroth"


def test_unknown_workload_rejected(tmp_path):
    with pytest.raises(ValueError, match="workload"):
        CampaignDriver(
            [TenantJob("t0", (8, 8, 8), 1, workload="lbm")], 1,
            str(tmp_path / "c"))


def test_astaroth_sequential_baseline_refused():
    with pytest.raises(NotImplementedError, match="jacobi"):
        run_sequential(
            [TenantJob("a0", (8, 8, 8), 1, "float64",
                       workload="astaroth")])


def test_astaroth_campaign_driver_parity_b2(tmp_path):
    """The ISSUE-10 satellite pin: astaroth tenants served by the
    campaign driver at B=2 finish bit-identical to the SAME batched-step
    program driven directly (the driver adds queueing/packing/guarding/
    retire bookkeeping, never numerics), and every per-tenant snapshot
    carries all 8 fields."""
    import jax.numpy as jnp

    from stencil_tpu.astaroth.integrate import FIELDS
    from stencil_tpu.campaign import WORKLOADS, astaroth_init_state
    from stencil_tpu.domain.grid import GridSpec
    from stencil_tpu.geometry import Dim3, Radius

    n, B, steps, chunk = 8, 2, 2, 2
    jobs = [TenantJob(f"t{i}", (n, n, n), steps, "float64", seed=i,
                      workload="astaroth") for i in range(B)]
    devs = jax.devices()[:2]
    drv = CampaignDriver(jobs, B, str(tmp_path / "c"), devices=devs,
                         chunk=chunk)
    res = drv.run()["results"]
    assert sorted(res) == ["t0", "t1"]
    assert all(r.outcome == "done" for r in res.values())

    # reference: the workload's own compiled program (same sharding, same
    # chunk plan), driven by hand from the same seeded init
    wl = WORKLOADS["astaroth"]
    spec = GridSpec(Dim3(n, n, n), Dim3(1, 1, 1), Radius.constant(3),
                    aligned=False)
    p, off = spec.padded(), spec.compute_offset()
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(devs), ("b",))
    sh = NamedSharding(mesh, P("b"))
    shr = NamedSharding(mesh, P())

    def pack(key):
        a = np.zeros((B, p.z, p.y, p.x), np.float64)
        for b, job in enumerate(jobs):
            a[b, off.z:off.z + n, off.y:off.y + n, off.x:off.x + n] = (
                astaroth_init_state(job)[key])
        return jax.device_put(jnp.asarray(a), sh)

    curr = {k: pack(k) for k in FIELDS}
    scratch = {k: jax.device_put(jnp.zeros((B, p.z, p.y, p.x)), sh)
               for k in FIELDS}
    loop = wl.build_loop(spec, chunk, sh, shr, batch=B, use_pallas=False)
    done = 0
    while done < steps:
        curr = wl.step(loop, curr, scratch, None)
        done += chunk
    for b, job in enumerate(jobs):
        fins = res[job.tid].finals
        assert sorted(fins) == sorted(FIELDS)
        for kf in FIELDS:
            ref = np.asarray(jax.device_get(curr[kf]))[
                b, off.z:off.z + n, off.y:off.y + n, off.x:off.x + n]
            np.testing.assert_array_equal(fins[kf], ref,
                                          err_msg=f"{job.tid}/{kf}")
    # the tenant snapshot dirs are revivable 8-field snapshots
    from stencil_tpu.ckpt import find_resume

    found = find_resume(os.path.join(str(tmp_path / "c"), "tenants", "t0"))
    assert found is not None
    _snap, manifest = found
    assert sorted(q["name"] for q in manifest["quantities"]) == sorted(FIELDS)


def test_astaroth_campaign_b2_matches_b1_lanes(tmp_path):
    """Batching independence at the driver level: each astaroth tenant
    served in a B=2 slot equals the same tenant served alone in a B=1
    slot (same tolerance discipline as the batched-step parity suite)."""
    n, steps = 8, 2
    jobs = [TenantJob(f"t{i}", (n, n, n), steps, "float64", seed=i,
                      workload="astaroth") for i in range(2)]
    devs = jax.devices()[:1]
    r2 = CampaignDriver(jobs, 2, str(tmp_path / "b2"), devices=devs,
                        chunk=2).run()["results"]
    r1 = {}
    for job in jobs:
        r1.update(CampaignDriver([job], 1, str(tmp_path / f"b1-{job.tid}"),
                                 devices=devs, chunk=2).run()["results"])
    from stencil_tpu.astaroth.integrate import FIELDS

    for tid in ("t0", "t1"):
        for kf in FIELDS:
            np.testing.assert_allclose(
                r2[tid].finals[kf], r1[tid].finals[kf],
                rtol=1e-10, atol=1e-12, err_msg=f"{tid}/{kf}")
