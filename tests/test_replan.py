"""The mid-run plan hot-swap (ISSUE 15 / ROADMAP #6's missing half).

Contracts: an injected slow chunk trips the live sentinel, the latched
``replan.requested`` is consumed BETWEEN chunks (the guarded loop
finishes its chunk first), the autotuner's new choice installs via the
in-memory elastic reshard with ``replan.applied`` within 2 chunks, and
the finished run is bit-identical to an unswapped one; a THROWING
autotuner emits ``replan.rejected`` and the run continues on the old
plan to completion; the swap budget and the confirmed-current-choice
paths reject loudly too; the campaign driver performs the same swap at
its slot boundary.
"""

import io
import json
import time

import numpy as np
import pytest

import jax

from stencil_tpu.fault.recover import chunk_plan, run_guarded
from stencil_tpu.obs import telemetry
from stencil_tpu.obs.live import LiveSentinel
from stencil_tpu.parallel import Method
from stencil_tpu.plan.ir import PlanChoice
from stencil_tpu.plan.replan import ReplanController

TRIP_CONFIG = {"*": {"min_history": 2, "window": 8, "rel_tol": 0.5,
                     "clear_after": 1}}


def recording_recorder():
    buf = io.StringIO()
    rec = telemetry.Recorder(sink=buf, app="test")
    return rec, buf


def records(buf, name=None):
    out = [json.loads(line) for line in buf.getvalue().splitlines()
           if line.strip()]
    return [r for r in out if name is None or r["name"] == name]


# -- engine-level paths (no app, no backend work) -----------------------------


def sleepy_step(trip_at):
    """A step_fn with a stable ~10 ms chunk latency whose chunk ending
    at ``trip_at`` runs ~25x slower — far outside the band (a no-op
    step would sit at microsecond noise, where scheduler jitter alone
    trips the relative band and the test flakes)."""

    def step_fn(st, k):
        done = st["i"] + k
        time.sleep(0.25 if done == trip_at else 0.01)
        return dict(st, i=done)  # preserve swap-applied markers

    return step_fn


def guarded(rec, sentinel, controller, iters=10, chunk=2, trip_at=6):
    return run_guarded(
        {"i": 0}, start=0, iters=iters,
        plan_fn=lambda s: chunk_plan(s, iters, chunk),
        step_fn=sleepy_step(trip_at),
        sentinel=sentinel, replan=controller,
    )


def test_throwing_retune_rejected_and_run_continues():
    rec, buf = recording_recorder()
    sent = LiveSentinel(TRIP_CONFIG, rec=rec)

    def retune():
        raise RuntimeError("tuner exploded")

    ctrl = ReplanController(retune, lambda c, st: st, sentinel=sent,
                            rec=rec,
                            current_choice=PlanChoice((2, 2, 2),
                                                      "direct26"))
    sent.on_replan = ctrl.request
    state, done = guarded(rec, sent, ctrl)
    assert done == 10 and state["i"] == 10  # the run FINISHED on the old plan
    rej = records(buf, "replan.rejected")
    assert len(rej) == 1 and "tuner exploded" in rej[0]["reason"]
    assert not records(buf, "replan.applied")
    assert ctrl.rejected == 1 and ctrl.swaps == 0
    from stencil_tpu.obs.telemetry import validate_record

    assert not [e for r in records(buf) for e in validate_record(r)]


def test_applied_swap_transforms_state_and_resets_sentinel():
    rec, buf = recording_recorder()
    sent = LiveSentinel(TRIP_CONFIG, rec=rec)
    new_choice = PlanChoice((8, 1, 1), "axis-composed")

    def apply(choice, st):
        return dict(st, swapped=True)

    ctrl = ReplanController(lambda: new_choice, apply, sentinel=sent,
                            rec=rec,
                            current_choice=PlanChoice((2, 2, 2),
                                                      "direct26"))
    sent.on_replan = ctrl.request
    state, done = guarded(rec, sent, ctrl)
    assert done == 10 and state.get("swapped") is True
    app = records(buf, "replan.applied")
    assert len(app) == 1
    assert app[0]["old"] == "2x2x2/direct26/batched"
    assert app[0]["new"] == "8x1x1/axis-composed/batched"
    req = records(buf, "replan.requested")
    assert app[0]["step"] - req[0]["step"] <= 2 * 2  # within 2 chunks
    assert ctrl.current_choice == new_choice
    # the sentinel windows restarted from warmup (reset), totals kept
    assert not sent.windows or all(
        len(w.samples) <= 2 for w in sent.windows.values())
    assert sent.detected_total == 1


def test_retune_confirming_current_choice_is_a_rejected_noop():
    rec, buf = recording_recorder()
    sent = LiveSentinel(TRIP_CONFIG, rec=rec)
    current = PlanChoice((2, 2, 2), "axis-composed")
    applied = []
    ctrl = ReplanController(lambda: current,
                            lambda c, st: applied.append(c) or st,
                            sentinel=sent, rec=rec, current_choice=current)
    sent.on_replan = ctrl.request
    state, done = guarded(rec, sent, ctrl)
    assert done == 10 and not applied
    rej = records(buf, "replan.rejected")
    assert rej and "confirmed" in rej[0]["reason"]
    assert ctrl.swaps == 0


def test_swap_budget_exhaustion_rejects():
    rec, buf = recording_recorder()
    ctrl = ReplanController(lambda: PlanChoice((1, 1, 8), "axis-composed"),
                            lambda c, st: st, rec=rec, max_swaps=0)
    ctrl.request({"metric": "step.latency_s", "step": 4})
    assert ctrl.pending
    assert ctrl.maybe_swap({"i": 0}, 4) is None
    assert not ctrl.pending
    rej = records(buf, "replan.rejected")
    assert rej and "budget" in rej[0]["reason"]


def test_sentinel_reset_preserves_totals():
    sent = LiveSentinel({"*": {"min_history": 2, "window": 4,
                               "rel_tol": 0.5}})
    for v in (1.0, 1.0, 10.0):
        sent.observe("k_s", v, step=1, unit="s")
    assert sent.detected_total == 1
    sent.reset()
    assert sent.windows == {} and sent.detected_total == 1
    sent.observe("k_s", 1.0, step=2, unit="s")
    assert sent.detected_total == 1  # fresh warmup, nothing judged


# -- the app-level e2e (the satellite's acceptance wording) -------------------


def run_jacobi(replan, inject=None, sentinel=None):
    from stencil_tpu.apps.jacobi3d import run

    return run(24, 24, 24, iters=10, method=Method.DIRECT26,
               devices=jax.devices()[:8], weak=False, chunk=2,
               inject=inject, sentinel=sentinel, replan=replan)


def test_jacobi_hot_swap_bit_identical_to_unswapped():
    rec, buf = recording_recorder()
    prev = telemetry._recorder
    telemetry._recorder = rec
    try:
        sent = LiveSentinel(TRIP_CONFIG, rec=rec)
        r1 = run_jacobi(True, inject="slow@6:seconds=0.5", sentinel=sent)
        f1 = r1["domain"].get_curr_global(r1["handle"])
    finally:
        telemetry._recorder = prev
    req = records(buf, "replan.requested")
    app = records(buf, "replan.applied")
    assert req and app, "slow@6 must trip the sentinel and swap"
    assert 0 <= app[0]["step"] - req[0]["step"] <= 2 * 2  # 2 chunks
    assert app[0]["old"] != app[0]["new"]
    assert r1["method"] != Method.DIRECT26.value  # the CSV names the new plan
    r2 = run_jacobi(False)
    f2 = r2["domain"].get_curr_global(r2["handle"])
    assert f1.tobytes() == f2.tobytes()


def test_jacobi_replan_without_sentinel_warns_and_runs(capfd):
    r = run_jacobi(True)
    assert r["method"] == Method.DIRECT26.value
    assert "--replan needs --live-sentinel" in capfd.readouterr().err


# -- campaign: the same swap between slots ------------------------------------


def test_campaign_swaps_between_slots(tmp_path):
    from stencil_tpu.campaign import CampaignDriver, TenantJob

    rec, buf = recording_recorder()
    prev = telemetry._recorder
    telemetry._recorder = rec
    try:
        new_choice = PlanChoice((1, 1, 8), "axis-composed")
        ctrl = ReplanController(lambda: new_choice, lambda c, st: None,
                                rec=rec)
        # two same-bucket slots of one lane each; the request latches
        # during slot 0 (here: pre-latched — the sentinel pathway is
        # covered by the engine tests) and must be consumed at the
        # FIRST slot boundary, not mid-slot
        ctrl.request({"metric": "step.latency_s[16x16x16,float32,jacobi]",
                      "step": 2})
        # two DIFFERENT shape buckets: same-bucket tenants would be
        # backfilled into slot 0's freed lane and no slot boundary
        # (the campaign's swap point) would ever occur
        jobs = [TenantJob("t0", (16, 16, 16), 4),
                TenantJob("t1", (8, 8, 8), 4)]
        drv = CampaignDriver(jobs, 1, str(tmp_path / "camp"),
                             devices=jax.devices()[:8], chunk=2,
                             replan=ctrl)
        summary = drv.run()
    finally:
        telemetry._recorder = prev
    assert summary["tenants"] == 2 and summary["slots"] == 2
    assert all(r.outcome == "done" for r in summary["results"].values())
    app = records(buf, "replan.applied")
    assert len(app) == 1 and app[0]["new"] == new_choice.label()
    assert ctrl.swaps == 1
