"""DistributedDomain end-to-end tests — the TPU analogue of the reference's
distributed tests (test/test_cuda_mpi_distributed_domain.cu,
test/test_cuda_mpi_exchange.cu): exchange through the top-level API across
methods and radius shapes, verified with coordinate-determined values."""

import os

import jax
import numpy as np
import pytest

from stencil_tpu.api import DistributedDomain
from stencil_tpu.geometry import DIRECTIONS_26, Dim3, Radius
from stencil_tpu.parallel import Method


def coord_field(g: Dim3) -> np.ndarray:
    z, y, x = np.meshgrid(np.arange(g.z), np.arange(g.y), np.arange(g.x), indexing="ij")
    return (x | (y << 10) | (z << 20)).astype(np.float64)


def make_domain(size=(12, 10, 8), radius=1, method=Method.AXIS_COMPOSED, ndev=8):
    dd = DistributedDomain(*size)
    dd.set_radius(radius)
    dd.set_methods(method)
    dd.set_devices(jax.devices()[:ndev])
    h = dd.add_data("q", "float64")
    dd.realize()
    return dd, h


@pytest.mark.parametrize("method", [Method.AXIS_COMPOSED, Method.DIRECT26])
def test_exchange_via_api(method):
    dd, h = make_domain(method=method)
    g = dd.size
    field = coord_field(g)
    dd.set_curr_global(h, field)
    dd.exchange()
    # verify all halo cells of all blocks
    arr = np.asarray(jax.device_get(dd.get_curr(h)))
    spec = dd.spec
    off = spec.compute_offset()
    for i in range(spec.num_blocks()):
        idx = dd._block_idx(i)
        size = spec.block_size(idx)
        origin = spec.block_origin(idx)
        block = arr[idx.z, idx.y, idx.x]
        for d in DIRECTIONS_26:
            if spec.radius.dir(d) == 0:
                continue
            rect = spec.halo_rect(d, size, halo=True)
            for az in range(rect.lo.z, rect.hi.z):
                for ay in range(rect.lo.y, rect.hi.y):
                    for ax in range(rect.lo.x, rect.hi.x):
                        gx = (origin.x + ax - off.x) % g.x
                        gy = (origin.y + ay - off.y) % g.y
                        gz = (origin.z + az - off.z) % g.z
                        assert block[az, ay, ax] == field[gz, gy, gx]
    # round trip
    np.testing.assert_array_equal(dd.get_curr_global(h), field)
    assert dd.num_exchanges == 1
    assert dd.time_exchange > 0


def test_swap_and_double_buffer():
    dd, h = make_domain()
    field = coord_field(dd.size)
    dd.set_curr_global(h, field)
    dd.swap()
    assert float(np.asarray(dd.get_next(h)).sum()) > 0
    assert float(np.asarray(dd.get_curr(h)).sum()) == 0.0
    dd.swap()
    np.testing.assert_array_equal(dd.get_curr_global(h), field)


def test_interior_exterior_cover_compute():
    """interior + exterior slabs exactly tile the compute region
    (reference: src/stencil.cu:878-977 geometry)."""
    dd, _ = make_domain(size=(16, 12, 10), radius=2)
    interiors = dd.get_interior()
    exteriors = dd.get_exterior()
    spec = dd.spec
    off = spec.compute_offset()
    for i in range(spec.num_blocks()):
        sz = spec.block_size(dd._block_idx(i))
        total = sz.flatten()
        vol = interiors[i].extent().flatten() + sum(
            r.extent().flatten() for r in exteriors[i]
        )
        assert vol == total
        # non-overlap: paint cells
        paint = np.zeros((sz.z, sz.y, sz.x), dtype=int)
        regions = [interiors[i]] + exteriors[i]
        for r in regions:
            paint[
                r.lo.z - off.z : r.hi.z - off.z,
                r.lo.y - off.y : r.hi.y - off.y,
                r.lo.x - off.x : r.hi.x - off.x,
            ] += 1
        assert paint.min() == 1 and paint.max() == 1


def test_fused_loop_public_api():
    # exchange_loop / run_exchanges / halo_exchange are the public fused-loop
    # surface (apps must not reach into dd._exchange)
    dd, h = make_domain(radius=1)
    g = dd.size
    dd.set_curr_global(h, coord_field(g))
    dd.run_exchanges(3)
    assert dd.num_exchanges == 3
    # state after fused exchanges equals state after one exchange (the
    # exchange is idempotent once halos are filled)
    want = np.asarray(jax.device_get(dd.get_curr(h)))
    dd2, h2 = make_domain(radius=1)
    dd2.set_curr_global(h2, coord_field(g))
    dd2.exchange()
    got = np.asarray(jax.device_get(dd2.get_curr(h2)))
    np.testing.assert_array_equal(want, got)
    # the loop builder is usable standalone on a state pytree
    state = dd2.curr_state()
    state = dd2.exchange_loop(2)(state)
    np.testing.assert_array_equal(np.asarray(jax.device_get(state[h2.idx])), got)
    assert dd.halo_exchange is dd._exchange


def test_bytes_accounting_api():
    dd, _ = make_domain(radius=1)
    assert dd.exchange_bytes_for_method(Method.AXIS_COMPOSED) > 0
    assert dd.exchange_bytes_for_method(Method.DIRECT26) == 0
    assert dd.exchange_bytes_moved() >= dd.exchange_bytes_for_method(Method.AXIS_COMPOSED)


def test_write_paraview_and_plan(tmp_path):
    dd, h = make_domain(size=(4, 4, 4), radius=1, ndev=8)
    field = coord_field(dd.size)
    dd.set_curr_global(h, field)
    prefix = str(tmp_path / "out")
    dd.write_paraview(prefix)
    files = sorted(p for p in os.listdir(tmp_path) if p.startswith("out_"))
    assert len(files) == dd.spec.num_blocks()
    first = (tmp_path / files[0]).read_text().splitlines()
    assert first[0] == "Z,Y,X,q"
    # row count = interior cells + header
    i0 = dd._block_idx(0)
    assert len(first) == dd.spec.block_size(i0).flatten() + 1
    dd.write_plan(str(tmp_path / "p_"))
    mat = np.loadtxt(tmp_path / "p_mat_npy_loadtxt.txt")
    assert mat.shape == (8, 8)
    assert mat.sum() > 0


def test_paraview_native_writer_matches_python(tmp_path, monkeypatch):
    """The C++ row writer (native/paraview.cpp) must emit byte-identical
    files to the Python fallback (shortest-round-trip floats normalized to
    repr): exercised with values that stress the formatting (integers,
    negatives, tiny exponents, float32-rounded randoms)."""
    import stencil_tpu.api as api_mod
    from stencil_tpu.native import paraview_write  # skip-less: lib builds on import

    dd, h = make_domain(size=(5, 4, 3), radius=1, ndev=8)
    rng = np.random.RandomState(9)
    field = rng.randn(3, 4, 5).astype(np.float32).astype(np.float64)
    # stress exactly the fixed-vs-scientific boundary where a naive
    # shortest-string formatter diverges from Python repr
    field[0, 0, 0] = 2.0
    field[0, 0, 1] = -0.0
    field[0, 1, 0] = 1e-12
    field[1, 0, 0] = -123456789.0
    field[0, 0, 2] = 0.0001      # repr: fixed; shortest-string: 1e-04
    field[0, 0, 3] = 1e10        # repr: 10000000000.0
    field[0, 0, 4] = 5e9         # repr: 5000000000.0
    field[0, 1, 1] = 1e16        # repr: 1e+16 (scientific threshold)
    field[0, 1, 2] = 9.999999e15 # repr: 9999999000000000.0
    field[0, 1, 3] = 1.5e-5      # repr: 1.5e-05
    field[0, 1, 4] = 1e-4 / 3    # repr: 3.3333333333333335e-05
    dd.set_curr_global(h, field)
    dd.write_paraview(str(tmp_path / "nat"))

    # force the Python fallback by making the native import fail
    import builtins
    real_import = builtins.__import__

    def no_native(name, *a, **k):
        if "native" in name:
            raise ImportError("forced fallback")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_native)
    dd.write_paraview(str(tmp_path / "py"))
    monkeypatch.setattr(builtins, "__import__", real_import)

    for i in range(dd.spec.num_blocks()):
        nat = (tmp_path / f"nat_{i}.txt").read_bytes()
        py = (tmp_path / f"py_{i}.txt").read_bytes()
        assert nat == py, f"block {i} differs"


def test_uneven_via_api():
    dd, h = make_domain(size=(11, 9, 13), radius=2)
    field = coord_field(dd.size)
    dd.set_curr_global(h, field)
    dd.exchange()
    np.testing.assert_array_equal(dd.get_curr_global(h), field)


def test_write_paraview_zero_nans(tmp_path):
    """The NaN-scrubbing dump path: zero_nans=True writes 0.0 where the
    field holds NaN (both writers — native and the Python fallback — get
    the already-scrubbed arrays); zero_nans=False keeps the NaN."""
    dd = DistributedDomain(4, 4, 4)
    dd.set_devices(jax.devices()[:1])
    dd.set_partition((1, 1, 1))
    h = dd.add_data("q", "float32")
    dd.realize()
    g = np.arange(64, dtype=np.float32).reshape(4, 4, 4) + 1.0
    g[0, 0, 0] = np.nan
    g[2, 1, 3] = np.nan
    dd.set_curr_global(h, g)

    def read_values(prefix):
        vals = {}
        with open(prefix + "_0.txt") as f:
            next(f)  # header
            for line in f:
                z, y, x, v = line.strip().split(",")
                vals[(int(z), int(y), int(x))] = float(v)
        return vals

    dd.write_paraview(str(tmp_path / "scrub"), zero_nans=True)
    vals = read_values(str(tmp_path / "scrub"))
    assert vals[(0, 0, 0)] == 0.0
    assert vals[(2, 1, 3)] == 0.0
    assert vals[(1, 1, 1)] == g[1, 1, 1]  # untouched cells survive
    assert all(np.isfinite(v) for v in vals.values())

    dd.write_paraview(str(tmp_path / "raw"), zero_nans=False)
    raw = read_values(str(tmp_path / "raw"))
    assert np.isnan(raw[(0, 0, 0)])


def test_multiprocess_ckpt_skip_is_observable(tmp_path, monkeypatch):
    """api.py's multi-process checkpoint skip: every skip emits a
    ckpt.save_skipped counter (so a campaign with zero durable state is
    alertable) and the warning is deduplicated to once per domain."""
    import json as _json

    from stencil_tpu.obs import telemetry as _telemetry

    dd, h = make_domain(size=(8, 8, 8), ndev=1)
    path = str(tmp_path / "m.jsonl")
    _telemetry.configure(metrics_out=path, app="test")
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    try:
        dd.save_checkpoint(str(tmp_path / "ck"), 1)
        dd.save_checkpoint(str(tmp_path / "ck"), 2)
        assert dd.restore_checkpoint(str(tmp_path / "ck")) is None
    finally:
        monkeypatch.setattr(jax, "process_count", lambda: 1)
        _telemetry.configure(metrics_out=None)
    assert not os.path.isdir(str(tmp_path / "ck"))  # nothing was written
    recs = [_json.loads(line) for line in open(path) if line.strip()]
    for r in recs:
        assert _telemetry.validate_record(r) == [], r
    skips = [r for r in recs if r["name"] == "ckpt.save_skipped"]
    assert [r["step"] for r in skips] == [1, 2]
    assert all(r["kind"] == "counter" and r["value"] == 1 for r in skips)
    assert [r["name"] for r in recs].count("ckpt.restore_skipped") == 1
    assert dd._ckpt_skip_warned  # the dedup flag latched after one warning
