"""Elastic checkpoint/restart (stencil_tpu/ckpt/) tests.

Pins the subsystem's acceptance contract (ISSUE 4):

- round-trip bit-exactness: save at step k, restore, continue to step n
  equals an uninterrupted n-step run — fp32 and fp64, uniform and uneven
  partitions, and an oversubscribed (resident-block) config;
- elastic restore parity: a (2,2,2)x8-device snapshot restores
  bit-identically onto (1,2,4)x8, onto 4 devices (oversubscribed), and
  onto 1 device — and CONTINUES identically there;
- crash-safety: truncated/missing payloads are rejected by validation
  and skipped by auto-resume (fallback to the previous good snapshot);
  LATEST never names a partial snapshot; retention keeps the newest N;
- the async double-buffered writer produces the same durable snapshots
  as the synchronous path;
- ckpt_tool inspect/validate/diff exit codes.

The filesystem-protocol tests build snapshots from a bare GridSpec +
numpy state (no domain, no compile) so they stay fast.
"""

import json
import os

import jax
import numpy as np
import pytest

from stencil_tpu.api import DistributedDomain
from stencil_tpu.ckpt import (
    AsyncCheckpointer,
    find_resume,
    list_snapshots,
    load_manifest,
    read_latest,
    snapshot_name,
    step_of,
    validate_snapshot,
    write_snapshot,
)
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.ops.jacobi import INIT_TEMP, make_jacobi_step, sphere_sel
from stencil_tpu.parallel.exchange import shard_blocks


def coord_field(g: Dim3, dtype) -> np.ndarray:
    z, y, x = np.meshgrid(
        np.arange(g.z), np.arange(g.y), np.arange(g.x), indexing="ij"
    )
    return (x + y * 1_000 + z * 1_000_000).astype(dtype)


def make_domain(size, dtype, partition=None, ndev=8, radius=1):
    dd = DistributedDomain(*size)
    dd.set_radius(radius)
    dd.set_devices(jax.devices()[:ndev])
    if partition is not None:
        dd.set_partition(partition)
    h = dd.add_data("temperature", dtype)
    dd.realize()
    return dd, h


def run_steps(dd, h, n: int):
    """Advance the domain's curr state by n jacobi steps (fused per-call,
    like the apps: exchange + sweep + swap inside one jit)."""
    step = make_jacobi_step(dd.halo_exchange, overlap=True)
    sel = shard_blocks(sphere_sel(dd.size), dd.spec, dd.mesh)
    curr, nxt = dd.get_curr(h), dd.get_next(h)
    for _ in range(n):
        curr, nxt = step(curr, nxt, sel)
    dd.set_curr(h, curr)
    dd.set_next(h, nxt)


# -- round-trip bit-exactness (save at k, restore, continue to n) ------------


@pytest.mark.parametrize(
    "dtype,size,partition,ndev",
    [
        ("float32", (12, 12, 8), (2, 2, 2), 8),   # uniform
        ("float64", (13, 11, 9), (2, 2, 2), 8),   # uneven (remainder rule)
        ("float32", (12, 12, 8), (2, 2, 2), 4),   # oversubscribed residents
    ],
    ids=["fp32-uniform", "fp64-uneven", "fp32-oversubscribed"],
)
def test_continue_matches_uninterrupted(tmp_path, dtype, size, partition, ndev):
    k, n = 2, 4
    init = np.full((size[2], size[1], size[0]), INIT_TEMP, dtype)

    dd, h = make_domain(size, dtype, partition, ndev)
    dd.set_curr_global(h, init)
    run_steps(dd, h, n)
    want = dd.get_curr_global(h)

    dd1, h1 = make_domain(size, dtype, partition, ndev)
    dd1.set_curr_global(h1, init)
    run_steps(dd1, h1, k)
    dd1.save_checkpoint(str(tmp_path), k, asynchronous=False)

    dd2, h2 = make_domain(size, dtype, partition, ndev)
    assert dd2.restore_checkpoint(str(tmp_path)) == k
    run_steps(dd2, h2, n - k)
    got = dd2.get_curr_global(h2)
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(got, want)


# -- elastic restore parity ---------------------------------------------------


def test_elastic_restore_across_partitions(tmp_path):
    """A (2,2,2)/8-device snapshot restores bit-identically onto (1,2,4),
    onto 4 devices (oversubscribed), and onto 1 device — and the (1,2,4)
    target CONTINUES bit-identically to the saver's own continuation."""
    size, dtype, k, n = (12, 12, 8), "float32", 2, 4
    init = np.full((size[2], size[1], size[0]), INIT_TEMP, dtype)

    dd, h = make_domain(size, dtype, (2, 2, 2), 8)
    dd.set_curr_global(h, init)
    run_steps(dd, h, k)
    dd.save_checkpoint(str(tmp_path), k, asynchronous=False)
    saved_global = dd.get_curr_global(h)
    run_steps(dd, h, n - k)
    want_final = dd.get_curr_global(h)

    for partition, ndev in [((1, 2, 4), 8), ((2, 2, 2), 4), ((1, 1, 1), 1)]:
        dd2, h2 = make_domain(size, dtype, partition, ndev)
        assert dd2.restore_checkpoint(str(tmp_path)) == k, (partition, ndev)
        np.testing.assert_array_equal(
            dd2.get_curr_global(h2), saved_global
        ), (partition, ndev)

    dd3, h3 = make_domain(size, dtype, (1, 2, 4), 8)
    assert dd3.restore_checkpoint(str(tmp_path)) == k
    run_steps(dd3, h3, n - k)
    np.testing.assert_array_equal(dd3.get_curr_global(h3), want_final)


def test_restore_falls_back_past_incompatible_newer_snapshot(tmp_path):
    """A newer VALID snapshot from a different domain shape (the bench
    CPU-fallback scenario) must not shadow an older compatible one: the
    compatibility check joins the fallback chain."""
    g = coord_field(Dim3(12, 12, 8), "float32")
    dd, h = make_domain((12, 12, 8), "float32", (2, 2, 2), 8)
    dd.set_curr_global(h, g)
    dd.save_checkpoint(str(tmp_path), 5, asynchronous=False)
    # a different campaign writes a newer snapshot into the same dir
    other, _ = make_domain((16, 12, 8), "float32", (2, 2, 2), 8)
    other.save_checkpoint(str(tmp_path), 9, asynchronous=False)
    assert read_latest(str(tmp_path)) == snapshot_name(9)

    dd2, h2 = make_domain((12, 12, 8), "float32", (1, 2, 4), 8)
    assert dd2.restore_checkpoint(str(tmp_path)) == 5
    np.testing.assert_array_equal(dd2.get_curr_global(h2), g)


def test_restore_incompatible_returns_none(tmp_path):
    dd, h = make_domain((12, 12, 8), "float32", (2, 2, 2), 8)
    dd.save_checkpoint(str(tmp_path), 1, asynchronous=False)
    # different global size -> no compatible snapshot, never an exception
    dd2, _ = make_domain((16, 12, 8), "float32", (2, 2, 2), 8)
    assert dd2.restore_checkpoint(str(tmp_path)) is None
    # different dtype -> bit-exact restore impossible, refused
    dd3 = DistributedDomain(12, 12, 8)
    dd3.set_radius(1)
    dd3.set_devices(jax.devices()[:8])
    dd3.set_partition((2, 2, 2))
    dd3.add_data("temperature", "float64")
    dd3.realize()
    assert dd3.restore_checkpoint(str(tmp_path)) is None
    # empty/missing dir -> None
    assert dd2.restore_checkpoint(str(tmp_path / "nope")) is None


# -- filesystem protocol (bare GridSpec + numpy, no compile) ------------------


def small_spec():
    return GridSpec(Dim3(8, 6, 4), Dim3(2, 1, 1), Radius.constant(1))


def host_state(spec, seed=0):
    rng = np.random.RandomState(seed)
    return {"q": rng.rand(*spec.stacked_shape_zyx()).astype(np.float32)}


def test_write_protocol_latest_and_retention(tmp_path):
    spec = small_spec()
    d = str(tmp_path)
    for step in (1, 2, 3, 4, 5):
        write_snapshot(d, step, spec, host_state(spec, step), keep=3)
    assert list_snapshots(d) == [snapshot_name(s) for s in (3, 4, 5)]
    assert read_latest(d) == snapshot_name(5)
    assert step_of(snapshot_name(5)) == 5
    for s in (3, 4, 5):
        assert validate_snapshot(os.path.join(d, snapshot_name(s))) == []


def test_rewrite_same_step_never_deletes_before_publish(tmp_path):
    """Overwriting an existing step moves the old snapshot aside (rename)
    rather than rmtree'ing it first — a crash between the renames leaves
    the old state on disk instead of losing the newest durable step. The
    completed rewrite replaces the content and leaves no leftovers."""
    spec = small_spec()
    d = str(tmp_path)
    write_snapshot(d, 2, spec, host_state(spec, 1), keep=3)
    old = np.load(os.path.join(d, snapshot_name(2), "block_0_0_0.npz"))["q"]
    write_snapshot(d, 2, spec, host_state(spec, 9), keep=3)
    new = np.load(os.path.join(d, snapshot_name(2), "block_0_0_0.npz"))["q"]
    assert not np.array_equal(old, new)
    assert validate_snapshot(os.path.join(d, snapshot_name(2))) == []
    assert list_snapshots(d) == [snapshot_name(2)]
    assert not [e for e in os.listdir(d) if e.startswith(".tmp-")]


def test_resume_past_target_never_relabels(tmp_path):
    """jacobi3d resumed with --iters BELOW the checkpointed step runs
    nothing and must NOT re-label the further-along snapshot as the
    smaller step (campaign step accounting stays truthful)."""
    from stencil_tpu.apps.jacobi3d import run

    d = str(tmp_path)
    run(8, 8, 8, iters=2, weak=False, devices=jax.devices()[:1],
        warmup=0, ckpt_dir=d)
    assert list_snapshots(d) == [snapshot_name(2)]
    r = run(8, 8, 8, iters=1, weak=False, devices=jax.devices()[:1],
            warmup=0, ckpt_dir=d, resume=True)
    assert list_snapshots(d) == [snapshot_name(2)]  # untouched
    assert not np.isfinite(r["iter_trimean_s"])  # nothing was timed


def test_truncated_payload_rejected_and_skipped(tmp_path):
    spec = small_spec()
    d = str(tmp_path)
    write_snapshot(d, 1, spec, host_state(spec, 1), keep=5)
    write_snapshot(d, 2, spec, host_state(spec, 2), keep=5)
    victim = os.path.join(d, snapshot_name(2), "block_0_0_0.npz")
    with open(victim, "r+b") as f:
        f.truncate(10)
    errs = validate_snapshot(os.path.join(d, snapshot_name(2)))
    assert errs and "truncated" in errs[0]
    # auto-resume skips the bad snapshot, falls back to the good one
    snap, manifest = find_resume(d)
    assert manifest["step"] == 1
    # LATEST itself still names the (now bad) newest — the pointer is only
    # ever moved AFTER a complete snapshot landed, so it cannot name a
    # .tmp partial; corruption-after-the-fact is find_resume's job
    assert read_latest(d) == snapshot_name(2)


def test_missing_payload_and_hash_mismatch(tmp_path):
    spec = small_spec()
    d = str(tmp_path)
    snap = write_snapshot(d, 3, spec, host_state(spec), keep=2)
    os.remove(os.path.join(snap, "block_0_0_1.npz"))
    errs = validate_snapshot(snap)
    assert any("missing payload" in e for e in errs)

    snap2 = write_snapshot(d, 4, spec, host_state(spec), keep=2)
    path = os.path.join(snap2, "block_0_0_0.npz")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:  # same size, flipped bytes
        f.seek(size // 2)
        f.write(b"\xff\xff\xff\xff")
    errs = validate_snapshot(snap2)
    assert any("SHA-256 mismatch" in e for e in errs)
    assert validate_snapshot(snap2, deep=False) == []  # shallow skips hashes


def test_partial_tmp_dir_is_invisible(tmp_path):
    spec = small_spec()
    d = str(tmp_path)
    write_snapshot(d, 1, spec, host_state(spec), keep=3)
    # a crashed writer leaves a .tmp- dir: never listed, never resumed
    os.makedirs(os.path.join(d, ".tmp-step-00000099-123"))
    assert list_snapshots(d) == [snapshot_name(1)]
    snap, manifest = find_resume(d)
    assert manifest["step"] == 1


def test_resume_prefers_newest_even_when_latest_lags(tmp_path):
    """A crash between publishing a snapshot and moving LATEST leaves an
    intact step newer than the pointer; resume must take the newest valid
    snapshot, not the pointer's (LATEST is the floor, not the ceiling)."""
    from stencil_tpu.ckpt.snapshot import _write_latest

    spec = small_spec()
    d = str(tmp_path)
    write_snapshot(d, 1, spec, host_state(spec, 1), keep=5)
    write_snapshot(d, 2, spec, host_state(spec, 2), keep=5)
    _write_latest(d, snapshot_name(1))  # simulate the crash window
    snap, manifest = find_resume(d)
    assert manifest["step"] == 2


def test_latest_pointing_at_removed_snapshot_falls_back(tmp_path):
    spec = small_spec()
    d = str(tmp_path)
    write_snapshot(d, 1, spec, host_state(spec, 1), keep=5)
    write_snapshot(d, 2, spec, host_state(spec, 2), keep=5)
    import shutil

    shutil.rmtree(os.path.join(d, snapshot_name(2)))
    snap, manifest = find_resume(d)
    assert manifest["step"] == 1


def test_manifest_contents(tmp_path):
    spec = small_spec()
    snap = write_snapshot(str(tmp_path), 7, spec, host_state(spec), keep=1)
    m = load_manifest(snap)
    assert m["v"] == 1 and m["kind"] == "stencil-ckpt" and m["step"] == 7
    assert m["global"] == {"x": 8, "y": 6, "z": 4}
    assert m["partition"] == {"x": 2, "y": 1, "z": 1}
    assert [q["name"] for q in m["quantities"]] == ["q"]
    assert len(m["files"]) == spec.num_blocks()
    for fe in m["files"]:
        assert fe["bytes"] > 0 and len(fe["sha256"]) == 64
        # interiors only: recorded size is the logical block size
        ix, iy, iz = fe["block"]
        s = spec.block_size((ix, iy, iz))
        assert fe["size"] == [s.x, s.y, s.z]


def test_async_checkpointer_matches_sync(tmp_path):
    spec = small_spec()
    state = host_state(spec, 42)
    sync_dir, async_dir = str(tmp_path / "s"), str(tmp_path / "a")
    write_snapshot(sync_dir, 5, spec, state, keep=2)

    import jax.numpy as jnp

    cp = AsyncCheckpointer(async_dir, keep=2)
    arrays = {"q": jnp.asarray(state["q"])}
    cp.save(spec, arrays, 5)
    cp.save(spec, arrays, 6)  # second save drains the first (double buffer)
    cp.close()
    assert cp.last_step == 6
    assert list_snapshots(async_dir) == [snapshot_name(5), snapshot_name(6)]
    for sdir in list_snapshots(async_dir):
        assert validate_snapshot(os.path.join(async_dir, sdir)) == []
    # payload equality with the synchronous write (npz bytes differ by zip
    # metadata; the arrays must not)
    a = np.load(os.path.join(async_dir, snapshot_name(5), "block_0_0_0.npz"))
    b = np.load(os.path.join(sync_dir, snapshot_name(5), "block_0_0_0.npz"))
    np.testing.assert_array_equal(a["q"], b["q"])


# -- ckpt_tool ----------------------------------------------------------------


def test_ckpt_tool_cli(tmp_path, capsys):
    from stencil_tpu.apps.ckpt_tool import main as tool

    spec = small_spec()
    d = str(tmp_path)
    write_snapshot(d, 1, spec, host_state(spec, 1), keep=5)
    write_snapshot(d, 2, spec, host_state(spec, 1), keep=5)  # same data
    write_snapshot(d, 3, spec, host_state(spec, 3), keep=5)

    assert tool(["inspect", d]) == 0
    out = capsys.readouterr().out
    assert "step      3" in out and "q:float32" in out
    assert tool(["inspect", d, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["step"] == 3

    assert tool(["validate", d, "--all"]) == 0
    capsys.readouterr()

    # metadata diff: steps differ
    s1 = os.path.join(d, snapshot_name(1))
    s2 = os.path.join(d, snapshot_name(2))
    s3 = os.path.join(d, snapshot_name(3))
    assert tool(["diff", s1, s2]) == 1  # step differs
    assert tool(["diff", s1, s2, "--data"]) == 1  # ... even if data equal
    assert tool(["diff", s1, s1, "--data"]) == 0
    assert tool(["diff", s2, s3, "--data"]) == 1
    out = capsys.readouterr().out
    assert "differing cells" in out

    # corrupt one payload: validate CLI must exit nonzero
    with open(os.path.join(s3, "block_0_0_0.npz"), "r+b") as f:
        f.truncate(10)
    assert tool(["validate", d, "--all"]) == 1


def test_quarantine_invalid_snapshot(tmp_path):
    """ckpt_tool validate --quarantine / quarantine_snapshot: an invalid
    (truncated) snapshot is renamed aside so find_resume stops rescanning
    it on every restart; LATEST is repointed at the newest survivor."""
    from stencil_tpu.apps import ckpt_tool
    from stencil_tpu.ckpt import QUARANTINE_PREFIX, quarantine_snapshot

    spec = small_spec()
    d = str(tmp_path)
    write_snapshot(d, 1, spec, host_state(spec, 1), keep=5)
    write_snapshot(d, 2, spec, host_state(spec, 2), keep=5)
    victim = os.path.join(d, snapshot_name(2), "block_0_0_0.npz")
    with open(victim, "r+b") as f:
        f.truncate(10)
    # the CLI path: validate --all --quarantine renames the bad one
    rc = ckpt_tool.main(["validate", d, "--all", "--quarantine"])
    assert rc == 1  # the invalid snapshot still fails THIS run
    assert list_snapshots(d) == [snapshot_name(1)]
    qdirs = [e for e in os.listdir(d) if e.startswith(QUARANTINE_PREFIX)]
    assert len(qdirs) == 1 and snapshot_name(2) in qdirs[0]
    # evidence breadcrumb + LATEST repointed at the survivor
    assert os.path.isfile(os.path.join(d, qdirs[0], "QUARANTINED.txt"))
    assert read_latest(d) == snapshot_name(1)
    # a fresh validate now passes, and resume lands on the survivor
    assert ckpt_tool.main(["validate", d, "--all"]) == 0
    snap, manifest = find_resume(d)
    assert manifest["step"] == 1
    # quarantining the last snapshot removes the dangling LATEST
    assert quarantine_snapshot(d, snapshot_name(1), reason="test") is not None
    assert read_latest(d) is None
    assert find_resume(d) is None
    # and a nonexistent name is a no-op
    assert quarantine_snapshot(d, snapshot_name(9)) is None
