"""utils/sync.py (hard_sync) tests — the scalar-fetch completion barrier.

hard_sync is the timing discipline every bench app rides (fetch one
scalar, forcing completion of everything queued before it — because
block_until_ready lies on the tunneled TPU platform). Pinned here: it
works on bare arrays, on pytrees (first leaf in jax.tree order), and on
0-d leaves, and returns the fetched element as a float.
"""

import jax
import jax.numpy as jnp
import numpy as np

from stencil_tpu.utils.sync import hard_sync


def test_scalar_fetch_returns_first_element():
    x = jnp.arange(12.0).reshape(3, 4) + 5.0
    assert hard_sync(x) == 5.0
    assert isinstance(hard_sync(x), float)


def test_forces_completion_of_queued_work():
    # the fetched value reflects the finished computation, not the input
    x = jnp.ones((8, 8))
    y = jax.jit(lambda a: a * 3 + 1)(x)
    assert hard_sync(y) == 4.0


def test_pytree_dict_uses_first_leaf():
    # jax.tree order for dicts is sorted keys: "a" is the first leaf
    tree = {"b": jnp.full((2, 2), 7.0), "a": jnp.full((3,), 2.0)}
    assert hard_sync(tree) == 2.0


def test_nested_pytree():
    tree = {"x": [jnp.array([[9.0, 1.0]]), jnp.zeros(4)], "y": jnp.ones(2)}
    assert hard_sync(tree) == 9.0


def test_zero_d_leaf():
    # a 0-d leaf has no indexable axes: the empty index tuple must work
    assert hard_sync(jnp.float32(3.5)) == 3.5
    assert hard_sync({"s": jnp.array(2.25)}) == 2.25


def test_sharded_stacked_array():
    # the shape the apps actually sync: a sharded stacked-block array
    from jax.sharding import NamedSharding

    from stencil_tpu.parallel.mesh import BLOCK_PSPEC, grid_mesh
    from stencil_tpu.geometry import Dim3

    mesh = grid_mesh(Dim3(2, 2, 2), jax.devices()[:8])
    arr = jax.device_put(
        jnp.full((2, 2, 2, 4, 4, 4), 1.5, jnp.float32),
        NamedSharding(mesh, BLOCK_PSPEC),
    )
    assert hard_sync(arr) == 1.5
    assert hard_sync({"q": arr}) == 1.5
