"""Driver-artifact hardening: bench.py and dryrun_multichip must survive a
broken or wedged accelerator backend (the round-3 failure: the tunneled TPU
plugin stalled ``jax.devices()`` in the parent → MULTICHIP rc=124, and died
mid-``device_put`` → BENCH rc=1).

These tests break the backend deliberately (a bogus JAX_PLATFORMS makes any
backend init in the subprocess raise) and assert the entry points still
deliver: one JSON line + rc=0 for bench.py, rc=0 for dryrun_multichip.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _broken_env(**extra):
    env = dict(os.environ)
    # any backend init that does not go through the forced-CPU config API
    # now raises instead of silently working
    env["JAX_PLATFORMS"] = "bogus_backend"
    # the tunnel plugin's sitecustomize (on PYTHONPATH) re-pins
    # JAX_PLATFORMS at interpreter startup, so with a HEALTHY tunnel the
    # accel child would ignore the bogus backend and succeed (these tests
    # first ran during a full outage, where the wedge itself broke the
    # child) — drop the plugin site dir so the break is
    # tunnel-state-independent
    parts = [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and not any("axon_site" in c for c in p.split(os.sep))
    ]
    if parts:
        env["PYTHONPATH"] = os.pathsep.join(parts)
    else:
        env.pop("PYTHONPATH", None)
    env.update(extra)
    return env


def _last_json_line(stdout: str) -> dict:
    lines = [l for l in stdout.splitlines() if l.strip().startswith("{")]
    assert lines, f"no JSON line in stdout:\n{stdout[-2000:]}"
    return json.loads(lines[-1])


@pytest.mark.slow
def test_bench_falls_back_to_cpu_on_broken_backend():
    """Accel children fail fast (unknown backend); the CPU fallback child
    must still produce the one JSON line, and bench.py must exit 0."""
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=_broken_env(STENCIL_BENCH_BUDGET_S="240", STENCIL_BENCH_FAST="1"),
        capture_output=True,
        text=True,
        timeout=300,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = _last_json_line(proc.stdout)
    assert "cpu_fallback" in payload["metric"]
    assert payload["value"] > 0
    assert payload["vs_baseline"] == 0.0  # CPU numbers never compare to TPU
    assert payload["detail"]["platform"] == "cpu"


@pytest.mark.slow
def test_bench_times_out_wedged_child_and_falls_back():
    """A child that hangs before even importing JAX (the wedged-tunnel
    analogue) must be killed by the parent's timeout, and the CPU fallback
    must still deliver."""
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=_broken_env(
            STENCIL_BENCH_BUDGET_S="60",
            STENCIL_BENCH_FAST="1",
            STENCIL_BENCH_SELFTEST_HANG_S="600",
        ),
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "timed out" in proc.stderr
    payload = _last_json_line(proc.stdout)
    assert "cpu_fallback" in payload["metric"]
    assert payload["value"] > 0


@pytest.mark.slow
def test_dryrun_parent_never_initializes_backend():
    """dryrun_multichip must reach its CPU subprocess without initializing
    any backend in the parent: with a bogus JAX_PLATFORMS, a parent-side
    ``jax.devices()`` would raise — the run must still succeed."""
    code = (
        f"import sys; sys.path.insert(0, {REPO!r}); "
        "import __graft_entry__ as g; "
        "g.dryrun_multichip(2); "
        "print('hardened-dryrun: ok')"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=_broken_env(),
        capture_output=True,
        text=True,
        timeout=540,
        cwd=REPO,
    )
    assert proc.returncode == 0, f"{proc.stdout[-1000:]}\n{proc.stderr[-2000:]}"
    assert "hardened-dryrun: ok" in proc.stdout
