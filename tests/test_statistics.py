import pytest

from stencil_tpu.utils import Statistics
from stencil_tpu.utils.statistics import percentile


def test_basic_stats():
    s = Statistics([1.0, 2.0, 3.0, 4.0])
    assert s.min() == 1.0
    assert s.max() == 4.0
    assert s.avg() == 2.5
    assert s.med() == 2.5
    assert s.count() == 4


def test_trimean():
    # trimean of 1..5: Q1=2, med=3, Q3=4 -> (2 + 6 + 4)/4 = 3
    s = Statistics([1, 2, 3, 4, 5])
    assert s.trimean() == 3.0


def test_insert_keeps_sorted():
    s = Statistics([3.0])
    s.insert(1.0)
    s.insert(2.0)
    assert s.min() == 1.0 and s.max() == 3.0


def test_percentile_matches_median_and_extremes():
    s = Statistics([1, 2, 3, 4, 5])
    assert s.percentile(50) == s.med() == 3.0
    assert s.percentile(0) == 1.0
    assert s.percentile(100) == 5.0


def test_percentile_interpolates():
    # 99th percentile of 0..100 (101 samples) lands exactly on 99; with
    # 100 samples 0..99 it interpolates: pos = .99*99 = 98.01 -> 98.01
    assert percentile(range(101), 99) == 99.0
    assert percentile(range(100), 99) == pytest.approx(98.01)
    # the tail statistic the campaign legs exist for: one outlier among
    # uniform samples pulls p99 off the median but not to the max
    vals = [0.01] * 99 + [1.0]
    p99 = percentile(vals, 99)
    assert 0.01 < p99 < 1.0
    assert percentile(vals, 50) == 0.01


def test_percentile_rejects_bad_input():
    with pytest.raises(ValueError):
        percentile([], 50)
    with pytest.raises(ValueError):
        percentile([1.0], 101)
    with pytest.raises(ValueError):
        percentile([1.0], -1)


def test_percentile_module_level_equals_method():
    vals = [5.0, 1.0, 4.0, 2.0, 3.0]
    for q in (0, 25, 50, 75, 90, 99, 100):
        assert percentile(vals, q) == Statistics(vals).percentile(q)
