from stencil_tpu.utils import Statistics


def test_basic_stats():
    s = Statistics([1.0, 2.0, 3.0, 4.0])
    assert s.min() == 1.0
    assert s.max() == 4.0
    assert s.avg() == 2.5
    assert s.med() == 2.5
    assert s.count() == 4


def test_trimean():
    # trimean of 1..5: Q1=2, med=3, Q3=4 -> (2 + 6 + 4)/4 = 3
    s = Statistics([1, 2, 3, 4, 5])
    assert s.trimean() == 3.0


def test_insert_keeps_sorted():
    s = Statistics([3.0])
    s.insert(1.0)
    s.insert(2.0)
    assert s.min() == 1.0 and s.max() == 3.0
