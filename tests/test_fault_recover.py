"""The rollback-with-backoff recovery engine (stencil_tpu/fault/recover.py).

Engine-level pins with scripted step/save/restore hooks (tiny jnp state,
no domain, no app): plain-loop degeneration, the step -> inject -> check
-> checkpoint ordering (a poisoned state is never persisted), rollback
to the newest valid snapshot, quarantine of a poisoned restore,
exponential backoff, and the evidence-bundle abort with FAULT_RC."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.fault import (
    FAULT_RC,
    FaultPlan,
    HealthGuard,
    NumericalFault,
    RecoveryExhausted,
    RecoveryPolicy,
    chunk_plan,
    parse_spec,
    run_guarded,
)


# -- chunk_plan ---------------------------------------------------------------


def test_chunk_plan_basic():
    assert chunk_plan(0, 10, 4) == [4, 4, 2]
    assert chunk_plan(3, 10, 4) == [4, 3]
    assert chunk_plan(10, 10, 4) == []


def test_chunk_plan_breaks_at_cadences_and_steps():
    # ckpt every 2 clamps like the historical jacobi plan
    assert chunk_plan(0, 6, 10, every=(2,)) == [2, 2, 2]
    # health cadence 3 + injection at 5: boundaries at 3, 5
    assert chunk_plan(0, 9, 10, every=(3,), at=(5,)) == [3, 2, 1, 3]
    # zero cadences are ignored
    assert chunk_plan(0, 6, 10, every=(0, 0)) == [6]
    # injection at/beyond the end adds no boundary
    assert chunk_plan(0, 6, 10, at=(6, 9)) == [6]


# -- a tiny scripted workload -------------------------------------------------
# state: {"q": scalar-ish array}; step k adds k (so the final value equals
# the step count and bit-exactness is trivially checkable)


def _mk(start=0.0):
    return {"q": jnp.full((4,), float(start), jnp.float32)}


def _step(st, k):
    return {"q": st["q"] + k}


class MemCkpt:
    """In-memory snapshot store standing in for ckpt/ (the real store is
    exercised end-to-end in test_fault_e2e.py / ci_fault_gate.py)."""

    def __init__(self):
        self.snaps = {}
        self.quarantined = []

    def save(self, step, st):
        self.snaps[step] = np.asarray(st["q"]).copy()

    def restore(self):
        if not self.snaps:
            return None
        step = max(self.snaps)
        return step, {"q": jnp.asarray(self.snaps[step])}

    def quarantine(self, step):
        self.quarantined.append(step)
        del self.snaps[step]


def test_plain_loop_degeneration():
    """No guard/injector/restore: the engine IS the historical chunk loop
    — same chunk sequence, same save boundaries, same final state."""
    ck = MemCkpt()
    seen = []

    def on_chunk(st, k, per, done):
        seen.append((k, done))

    state, done = run_guarded(
        _mk(), start=0, iters=10,
        plan_fn=lambda s: chunk_plan(s, 10, 4, every=(2,)),
        step_fn=_step, save_fn=ck.save, ckpt_every=2, on_chunk=on_chunk)
    assert done == 10
    assert np.all(np.asarray(state["q"]) == 10)
    assert seen == [(2, 2), (2, 4), (2, 6), (2, 8), (2, 10)]
    # saves at every interior ckpt boundary, never the final step (the
    # apps own the final save)
    assert sorted(ck.snaps) == [2, 4, 6, 8]


def test_rollback_restores_and_recomputes_bit_identically():
    clean, _ = run_guarded(
        _mk(), start=0, iters=8,
        plan_fn=lambda s: chunk_plan(s, 8, 3, every=(2,)), step_fn=_step)
    ck = MemCkpt()
    plan = FaultPlan(parse_spec("nan@5"))
    state, done = run_guarded(
        _mk(), start=0, iters=8,
        plan_fn=lambda s: chunk_plan(s, 8, 3, every=(2, 2), at=plan.steps()),
        step_fn=_step, guard=HealthGuard(every=2), injector=plan,
        policy=RecoveryPolicy(backoff_s=0.001),
        save_fn=ck.save, ckpt_every=2, restore_fn=ck.restore)
    assert done == 8
    assert np.array_equal(np.asarray(state["q"]), np.asarray(clean["q"]))
    # the check precedes every save: no persisted snapshot carries the NaN
    for step, snap in ck.snaps.items():
        assert np.isfinite(snap).all(), f"poisoned snapshot at {step}"


def test_save_off_health_cadence_is_still_checked():
    """A ckpt boundary that is NOT a health boundary (ckpt_every=2,
    health_every=4, fault at 5 → save due at 6) still health-checks
    first: the poisoned state is never persisted, the rollback lands on
    the clean step-4 snapshot, and no quarantine is ever needed."""
    clean, _ = run_guarded(
        _mk(), start=0, iters=8,
        plan_fn=lambda s: chunk_plan(s, 8, 3, every=(2,)), step_fn=_step)
    ck = MemCkpt()
    plan = FaultPlan(parse_spec("nan@5"))
    state, done = run_guarded(
        _mk(), start=0, iters=8,
        plan_fn=lambda s: chunk_plan(s, 8, 3, every=(2, 4), at=plan.steps()),
        step_fn=_step, guard=HealthGuard(every=4), injector=plan,
        policy=RecoveryPolicy(backoff_s=0.001),
        save_fn=ck.save, ckpt_every=2, restore_fn=ck.restore,
        quarantine_fn=ck.quarantine)
    assert done == 8
    assert np.array_equal(np.asarray(state["q"]), np.asarray(clean["q"]))
    for step, snap in ck.snaps.items():
        assert np.isfinite(snap).all(), f"poisoned snapshot at {step}"
    assert ck.quarantined == []


def test_pre_start_injections_warn_and_never_fire():
    """A resumed run whose injection step already passed completes clean
    (the spec is warned about, not silently vacuous)."""
    plan = FaultPlan(parse_spec("nan@2"))
    state, done = run_guarded(
        _mk(4.0), start=4, iters=8,
        plan_fn=lambda s: chunk_plan(s, 8, 3, at=plan.steps()),
        step_fn=_step, guard=HealthGuard(every=2), injector=plan)
    assert done == 8
    assert np.isfinite(np.asarray(state["q"])).all()
    assert plan.injections[0].fired == 0


def test_detection_within_health_every():
    ck = MemCkpt()
    plan = FaultPlan(parse_spec("nan@3"))
    faults = []
    orig_check = HealthGuard.check

    class Spy(HealthGuard):
        def check(self, state, step):
            try:
                orig_check(self, state, step)
            except NumericalFault as f:
                faults.append(f)
                raise

    state, _ = run_guarded(
        _mk(), start=0, iters=8,
        plan_fn=lambda s: chunk_plan(s, 8, 8, every=(2, 2), at=plan.steps()),
        step_fn=_step, guard=Spy(every=2), injector=plan,
        policy=RecoveryPolicy(backoff_s=0.001),
        save_fn=ck.save, ckpt_every=2, restore_fn=ck.restore)
    assert faults and faults[0].step - 3 <= 2


def test_no_restore_aborts_with_evidence(tmp_path):
    plan = FaultPlan(parse_spec("inf@2"))
    with pytest.raises(RecoveryExhausted) as ei:
        run_guarded(
            _mk(), start=0, iters=4,
            plan_fn=lambda s: chunk_plan(s, 4, 4, every=(2,), at=plan.steps()),
            step_fn=_step, guard=HealthGuard(every=2), injector=plan,
            evidence_dir=str(tmp_path), app="unit")
    e = ei.value
    assert "cannot roll back" in e.reason
    assert e.evidence_path and os.path.isfile(e.evidence_path)
    ev = json.load(open(e.evidence_path))
    assert ev["rc"] == FAULT_RC == 43
    assert ev["app"] == "unit"
    assert ev["faults"][0]["kind"] == "nonfinite"
    assert ev["injections"][0]["kind"] == "inf"


def test_max_rollbacks_exhaustion_and_backoff(tmp_path, monkeypatch):
    sleeps = []
    import stencil_tpu.fault.recover as recover

    monkeypatch.setattr(recover.time, "sleep", lambda s: sleeps.append(s))
    ck = MemCkpt()
    plan = FaultPlan(parse_spec("nan@3:repeat=always"))
    with pytest.raises(RecoveryExhausted) as ei:
        run_guarded(
            _mk(), start=0, iters=8,
            plan_fn=lambda s: chunk_plan(s, 8, 8, every=(2, 2),
                                         at=plan.steps()),
            step_fn=_step, guard=HealthGuard(every=2), injector=plan,
            policy=RecoveryPolicy(max_rollbacks=2, backoff_s=0.5),
            save_fn=ck.save, ckpt_every=2, restore_fn=ck.restore,
            evidence_dir=str(tmp_path))
    assert "max rollbacks (2) exceeded" in ei.value.reason
    assert ei.value.rollbacks == 3
    assert sleeps == [0.5, 1.0]  # exponential backoff per repeat


def test_poisoned_restore_is_quarantined(tmp_path):
    """A snapshot that restores to unhealthy state is quarantined and the
    next candidate is used — rollback never reinstalls the disease."""
    ck = MemCkpt()
    plan = FaultPlan(parse_spec("nan@5"))

    def poisoning_save(step, st):
        ck.save(step, st)
        if step == 4:  # corrupt the stored copy AFTER the healthy save
            ck.snaps[4][0] = np.nan

    state, done = run_guarded(
        _mk(), start=0, iters=8,
        plan_fn=lambda s: chunk_plan(s, 8, 8, every=(2, 2), at=plan.steps()),
        step_fn=_step, guard=HealthGuard(every=2), injector=plan,
        policy=RecoveryPolicy(backoff_s=0.001),
        save_fn=poisoning_save, ckpt_every=2, restore_fn=ck.restore,
        quarantine_fn=ck.quarantine, evidence_dir=str(tmp_path))
    assert done == 8
    assert ck.quarantined == [4]
    assert np.isfinite(np.asarray(state["q"])).all()


def test_rollback_telemetry_records(tmp_path):
    from stencil_tpu.obs import telemetry

    path = str(tmp_path / "m.jsonl")
    telemetry.configure(metrics_out=path, app="unit")
    try:
        ck = MemCkpt()
        plan = FaultPlan(parse_spec("nan@3"))
        run_guarded(
            _mk(), start=0, iters=6,
            plan_fn=lambda s: chunk_plan(s, 6, 6, every=(2, 2),
                                         at=plan.steps()),
            step_fn=_step, guard=HealthGuard(every=2), injector=plan,
            policy=RecoveryPolicy(backoff_s=0.001),
            save_fn=ck.save, ckpt_every=2, restore_fn=ck.restore)
    finally:
        telemetry.configure(metrics_out=None)
    recs = [json.loads(line) for line in open(path) if line.strip()]
    for r in recs:
        assert telemetry.validate_record(r) == [], r
    by_name = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    assert "fault.injected" in by_name
    assert "health.fault" in by_name
    assert "recover.fault" in by_name
    (rb,) = by_name["recover.rollback"]
    assert rb["to_step"] == 2 and rb["fault_step"] == 4
    assert by_name["recover.backoff_s"][0]["value"] == pytest.approx(0.001)


def test_flush_called_before_restore_and_disk_injections():
    calls = []
    ck = MemCkpt()
    plan = FaultPlan(parse_spec("ckpt-truncate@3,nan@3"))
    run_guarded(
        _mk(), start=0, iters=6,
        plan_fn=lambda s: chunk_plan(s, 6, 6, every=(2, 2), at=plan.steps()),
        step_fn=_step, guard=HealthGuard(every=2), injector=plan,
        policy=RecoveryPolicy(backoff_s=0.001),
        save_fn=ck.save, ckpt_every=2, restore_fn=ck.restore,
        flush_fn=lambda: calls.append("flush"))
    # once for the ckpt-truncate injection, once before the rollback read
    assert calls.count("flush") >= 2
