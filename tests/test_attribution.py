"""The plan observatory (obs/attribution + plan/calibrate): attribution
records validate under the v1 schema vocabulary, the least-squares fit
recovers known constants from synthetic residuals (and refuses the
degenerate cases loudly), the drift band is numerically THE SAME band
``perf_tool.evaluate_gate`` applies to ledger history, fitted rows
round-trip through the plan DB, and the trace export renders attribution
as paired counters with the drift marker."""

import json

import pytest

from stencil_tpu.obs import attribution, telemetry
from stencil_tpu.obs.attribution import (DriftVerdict, PhasePrediction,
                                         emit_drift, emit_phase, judge_drift,
                                         phases_from_records,
                                         predict_exchange)
from stencil_tpu.obs.ledger import mad, trimean
from stencil_tpu.plan import calibrate
from stencil_tpu.plan import db as plandb
from stencil_tpu.plan.calibrate import CalibrationError, Sample, fit
from stencil_tpu.plan.cost import DEFAULT_CALIBRATION
from stencil_tpu.plan.ir import (AXIS_COMPOSED, DIRECT26, PlanChoice,
                                 PlanConfig)
from stencil_tpu.geometry import Dim3, Radius


def _config():
    return PlanConfig.make(Dim3(24, 24, 24), Radius.constant(2),
                           ["float32"] * 4, 8, "cpu")


def _choice():
    return PlanChoice(partition=(2, 2, 2), method=AXIS_COMPOSED,
                      batch_quantities=True)


# -- schema vocabulary --------------------------------------------------------


def test_attrib_vocabulary_in_name_fields():
    for name in ("plan.attrib.phase", "plan.fingerprint",
                 "calibration.fitted", "calibration.drift"):
        assert name in telemetry.NAME_FIELDS, name
        assert name in telemetry.KNOWN_NAMES, name


def test_attrib_record_roundtrip_via_schema(tmp_path):
    path = str(tmp_path / "m.jsonl")
    rec = telemetry.Recorder(path, app="t", run_id="r1")
    pred = predict_exchange(_config(), _choice())
    assert pred is not None
    emit_phase(rec, pred, 0.002, phase="stencil.exchange",
               kernel_variant=None,
               fabric={"processes": 1, "platform": "cpu"})
    rec.meta("plan.fingerprint", fingerprint=_choice().fingerprint(),
             choice=_choice().label(), calibration="modeled(default)")
    v = judge_drift("stencil.exchange", pred.predicted_s,
                    [100.0, 101.0, 99.0], rel_tol=0.75)
    assert not v.ok  # prediction is millis, samples are 100 s
    emit_drift(rec, v)
    rec.close()
    with open(path) as f:
        lines = f.readlines()
    n_ok, errs = telemetry.validate_jsonl(lines)
    assert errs == []
    names = {json.loads(ln)["name"] for ln in lines}
    assert {"plan.attrib.phase", "plan.fingerprint",
            "calibration.drift"} <= names
    # fabric scalars ride along as extra fields
    attrib = [json.loads(ln) for ln in lines
              if json.loads(ln)["name"] == "plan.attrib.phase"][0]
    assert attrib["fabric_platform"] == "cpu"
    assert attrib["residual"] == pytest.approx(0.002 - pred.predicted_s)


def test_emit_drift_is_silent_when_healthy(tmp_path):
    path = str(tmp_path / "m.jsonl")
    rec = telemetry.Recorder(path, app="t", run_id="r1")
    v = judge_drift("p", 1.0, [1.0, 1.01, 0.99], rel_tol=0.75)
    assert v.ok
    assert emit_drift(rec, v) is None
    rec.close()
    assert "calibration.drift" not in open(path).read()


# -- the fit ------------------------------------------------------------------


def test_fit_recovers_known_constants():
    # synthetic truth: measured = overhead[m] * collectives + bytes / bw
    truth = {"axis-composed": 5e-4, "direct26": 2e-3}
    bw = 5e8
    samples = []
    for m, oh in truth.items():
        for c, b in ((2, 100_000), (4, 400_000), (6, 1_200_000),
                     (26, 2_400_000)):
            samples.append(Sample(method=m, collectives=c, wire_bytes=b,
                                  measured_s=oh * c + b / bw))
    row = fit(samples, platform="cpu")
    cal = row["calibration"]
    assert row["bandwidth_fit"] is True
    assert cal["permute_overhead_s"]["axis-composed"] == pytest.approx(
        5e-4, rel=1e-6)
    assert cal["permute_overhead_s"]["direct26"] == pytest.approx(
        2e-3, rel=1e-6)
    assert cal["wire_bytes_per_s"] == pytest.approx(bw, rel=1e-6)
    assert row["r2"] == pytest.approx(1.0, abs=1e-9)
    assert row["provenance"].startswith("fitted(n=8")


def test_fit_refuses_degenerate_single_sample():
    with pytest.raises(CalibrationError):
        fit([Sample(method=AXIS_COMPOSED, collectives=2, wire_bytes=1000,
                    measured_s=1e-3)])


def test_fit_pins_bandwidth_on_single_point_population():
    # every sample at ONE (collectives, bytes) point: the bandwidth
    # column is unidentifiable, so the fit pins it at the modeled
    # default and recovers only the per-collective overhead
    base_bw = DEFAULT_CALIBRATION["wire_bytes_per_s"]
    oh = 6.6e-4
    samples = [Sample(method=AXIS_COMPOSED, collectives=2,
                      wire_bytes=200_000,
                      measured_s=oh * 2 + 200_000 / base_bw)
               for _ in range(3)]
    row = fit(samples, platform="cpu")
    assert row["bandwidth_fit"] is False
    # pinned bandwidth stays ABSENT from the override (absent-field
    # discipline: score() falls back to the modeled default, which is
    # exactly the pin), and the overhead is recovered from the residual
    assert "wire_bytes_per_s" not in row["calibration"]
    assert row["calibration"]["permute_overhead_s"][AXIS_COMPOSED] == (
        pytest.approx(oh, rel=1e-6))


def test_samples_from_records_matches_emitted_shape(tmp_path):
    path = str(tmp_path / "m.jsonl")
    rec = telemetry.Recorder(path, app="t", run_id="r1")
    pred = predict_exchange(_config(), _choice())
    for s in (0.002, 0.0021, 0.0019):
        emit_phase(rec, pred, s, phase="exchange.iter")
    rec.close()
    records = [json.loads(ln) for ln in open(path)]
    samples = calibrate.samples_from_records(records)
    assert len(samples) == 3
    assert all(s.method == AXIS_COMPOSED for s in samples)
    assert all(s.collectives == pred.collectives for s in samples)
    assert all(s.phase == "exchange.iter" for s in samples)


# -- the drift band == the perf_tool band -------------------------------------


def test_drift_band_is_the_evaluate_gate_band():
    """judge_drift and perf_tool.evaluate_gate must compute the SAME
    band from the same history — one authority, two entry points."""
    from stencil_tpu.apps import perf_tool
    from stencil_tpu.obs import ledger as L

    hist = [1.0e-3, 1.3e-3, 0.9e-3, 1.1e-3, 1.2e-3]
    predicted = 2.9e-3
    mad_k, rtol = 3.0, 0.75
    v = judge_drift("p", predicted, hist, mad_k=mad_k, rel_tol=rtol)

    entries = [L.make_entry("m_s", h, label=f"h{i}", unit="s",
                            platform="cpu", config={"c": 1})
               for i, h in enumerate(hist)]
    entries.append(L.make_entry("m_s", predicted, label="new", unit="s",
                                platform="cpu", config={"c": 1}))
    [g] = perf_tool.evaluate_gate(
        entries, label="new", mad_k=mad_k, rel_tol=rtol, min_history=2,
        leg_config={"*": {"direction": "both"}})
    assert g["lo"] == pytest.approx(v.lo)
    assert g["hi"] == pytest.approx(v.hi)
    assert g["center"] == pytest.approx(v.center)
    assert (g["status"] == "pass") == v.ok


def test_drift_trips_on_stale_low_prediction():
    """The bug class this sentinel exists for: measured time inflated
    well past a stale (low) prediction MUST trip even at a wide
    rel_tol — the band's low edge stays positive for rel_tol < 1."""
    samples = [0.015, 0.016, 0.017]
    healthy = judge_drift("p", 0.0112, samples, rel_tol=0.75)
    assert healthy.ok
    stale = judge_drift("p", 0.0112, [s * 10 for s in samples],
                        rel_tol=0.75)
    assert not stale.ok
    assert stale.lo > 0.0112  # tripped on the LOW side
    assert "OUTSIDE" in stale.describe()


def test_phases_from_records_splits_methods(tmp_path):
    path = str(tmp_path / "m.jsonl")
    rec = telemetry.Recorder(path, app="t", run_id="r1")
    pa = PhasePrediction(method=AXIS_COMPOSED, predicted_s=1e-3,
                         collectives=2, wire_bytes=1000)
    pd = PhasePrediction(method=DIRECT26, predicted_s=5e-3,
                         collectives=26, wire_bytes=1000)
    emit_phase(rec, pa, 1.1e-3, phase="exchange.iter")
    emit_phase(rec, pd, 5.2e-3, phase="exchange.iter")
    emit_phase(rec, pa, 0.9e-3, phase="jacobi.exchange")
    rec.close()
    records = [json.loads(ln) for ln in open(path)]
    groups = phases_from_records(records)
    # mixed-method phase splits; single-method phase keeps its name
    assert set(groups) == {"exchange.iter[axis-composed]",
                           "exchange.iter[direct26]", "jacobi.exchange"}
    assert groups["exchange.iter[direct26]"]["predicted_s"] == (
        pytest.approx(5e-3))


# -- plan DB round-trip -------------------------------------------------------


def test_calibration_row_roundtrips_through_db(tmp_path):
    samples = [Sample(method=AXIS_COMPOSED, collectives=c, wire_bytes=b,
                      measured_s=7e-4 * c + b / 4e8)
               for c, b in ((2, 100_000), (4, 500_000), (6, 900_000))]
    row = fit(samples, platform="cpu")
    db_path = str(tmp_path / "plan.json")
    db = plandb.load_db(db_path)
    plandb.record_calibration(db, "cpu", row)
    plandb.save_db(db_path, db)
    back = plandb.lookup_calibration(plandb.load_db(db_path), "cpu")
    assert back is not None
    assert back["provenance"] == row["provenance"]
    assert back["provenance"].startswith("fitted(n=3")
    assert back["calibration"]["permute_overhead_s"][AXIS_COMPOSED] == (
        pytest.approx(7e-4, rel=1e-6))
    # a pre-observatory DB (no calibrations section) stays valid and
    # lookups answer None, not KeyError
    assert plandb.validate_db(plandb.empty_db()) == []
    assert plandb.lookup_calibration(plandb.empty_db(), "cpu") is None


def test_db_rejects_malformed_calibration_row():
    errs = plandb.validate_calibration_row(
        "cpu", {"calibration": {}, "provenance": "fitted(n=1, r2=0.0)",
                "n": 1, "r2": 0.0})
    assert errs  # n < 2 is the degenerate fit the CLI refuses too


# -- fingerprint + trace rendering -------------------------------------------


def test_fingerprint_is_stable_and_discriminating():
    a, b = _choice(), _choice()
    assert a.fingerprint() == b.fingerprint()
    assert len(a.fingerprint()) == 12
    assert int(a.fingerprint(), 16) >= 0  # hex
    c = PlanChoice(partition=(1, 2, 4), method=AXIS_COMPOSED,
                   batch_quantities=True)
    assert c.fingerprint() != a.fingerprint()


def test_trace_renders_paired_counters_and_drift_marker(tmp_path):
    from stencil_tpu.obs import trace_export

    path = str(tmp_path / "m.jsonl")
    rec = telemetry.Recorder(path, app="t", run_id="r1")
    pred = PhasePrediction(method=AXIS_COMPOSED, predicted_s=1e-3,
                           collectives=2, wire_bytes=1000)
    for s in (1.1e-3, 0.9e-3):
        emit_phase(rec, pred, s, phase="stencil.exchange")
    emit_drift(rec, DriftVerdict(ok=False, phase="stencil.exchange",
                                 predicted_s=1e-3, center=5e-3,
                                 lo=2e-3, hi=8e-3, n=2))
    rec.close()
    records = [json.loads(ln) for ln in open(path)]
    trace = trace_export.to_trace(records)
    assert trace_export.validate_trace(trace) == []
    counters = {e["name"] for e in trace["traceEvents"]
                if e["ph"] == "C"}
    assert "plan.attrib.stencil.exchange.predicted_s" in counters
    assert "plan.attrib.stencil.exchange.measured_s" in counters
    markers = [e for e in trace["traceEvents"]
               if e["ph"] == "i" and e["name"] == "calibration.drift"]
    assert markers and markers[0]["args"]["band_lo"] == pytest.approx(2e-3)


# -- ledger fold --------------------------------------------------------------


def test_ledger_folds_attribution_to_one_entry_per_phase_method(tmp_path):
    from stencil_tpu.obs import ledger as L

    path = str(tmp_path / "m.jsonl")
    rec = telemetry.Recorder(path, app="t", run_id="r1")
    pred = PhasePrediction(method=AXIS_COMPOSED, predicted_s=1e-3,
                           collectives=2, wire_bytes=64_000,
                           provenance="modeled(default)")
    for s in (1.0e-3, 1.2e-3, 1.1e-3):
        emit_phase(rec, pred, s, phase="jacobi.exchange")
    rec.close()
    records = [json.loads(ln) for ln in open(path)]
    entries = [e for e in L.entries_from_metrics_records(records, label="x")
               if e["metric"].startswith("plan.attrib.")]
    assert len(entries) == 1
    e = entries[0]
    assert e["metric"] == "plan.attrib.jacobi.exchange"
    assert e["value"] == pytest.approx(trimean([1.0e-3, 1.2e-3, 1.1e-3]))
    d = e["detail"]
    assert d["method"] == AXIS_COMPOSED and d["collectives"] == 2
    # ...and calibrate can reconstruct fit samples from that entry
    samples = calibrate.samples_from_ledger(entries)
    assert len(samples) == 1 and samples[0].wire_bytes == 64_000
