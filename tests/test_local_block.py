"""LocalBlock tests, behaviors pinned from reference
test/test_cuda_local_domain.cu (halo extents/positions, curr != next) and
local_domain.cuh raw_size semantics."""

import numpy as np
import pytest

from stencil_tpu.domain import LocalBlock, block_rect_slices
from stencil_tpu.geometry import Dim3, Radius, Rect3


def asym_radius():
    r = Radius.constant(0)
    r.set_dir((1, 0, 0), 2)
    r.set_dir((-1, 0, 0), 1)
    return r


class TestGeometryQueries:
    def test_asymmetric_send_extent(self):
        # reference case1: +x send is sized like the -x side halo
        b = LocalBlock((3, 4, 5), (0, 0, 0), asym_radius())
        ext = b.halo_region(Dim3(-1, 0, 0), halo=True).extent()
        assert ext == Dim3(1, 4, 5)

    def test_raw_size(self):
        b = LocalBlock((3, 4, 5), (0, 0, 0), asym_radius())
        assert b.raw_size() == Dim3(3 + 1 + 2, 4, 5)

    def test_symmetric_face_positions(self):
        b = LocalBlock((30, 40, 50), (0, 0, 0), Radius.constant(4))
        assert b.halo_region((-1, 0, 0), True).lo == Dim3(0, 4, 4)
        assert b.halo_region((1, 0, 0), True).lo == Dim3(34, 4, 4)
        assert b.halo_region((0, 1, 0), True).lo == Dim3(4, 44, 4)
        assert b.halo_region((-1, 0, 0), False).lo == Dim3(4, 4, 4)
        assert b.halo_region((1, 0, 0), False).lo == Dim3(30, 4, 4)
        assert b.halo_region((-1, 0, 0), True).extent() == Dim3(4, 40, 50)
        assert b.halo_region((0, -1, 0), True).extent() == Dim3(30, 4, 50)


class TestData:
    def test_curr_neq_next(self):
        b = LocalBlock((3, 4, 5), (0, 0, 0), asym_radius())
        h = b.add_data("q", "float32")
        b.realize()
        c = b.get_curr(h)
        n = b.get_next(h)
        assert c.shape == (5, 4, 6)  # [z, y, x]
        c2 = c.at[0, 0, 0].set(1.0)
        b.set_curr(h, c2)
        assert float(b.get_curr(h)[0, 0, 0]) == 1.0
        assert float(b.get_next(h)[0, 0, 0]) == 0.0
        assert n is not c2

    def test_swap(self):
        b = LocalBlock((4, 4, 4), (0, 0, 0), Radius.constant(1))
        h = b.add_data()
        b.realize()
        b.set_next(h, b.get_next(h) + 7.0)
        b.swap()
        assert float(b.get_curr(h)[0, 0, 0]) == 7.0
        assert float(b.get_next(h)[0, 0, 0]) == 0.0

    def test_region_to_host(self):
        b = LocalBlock((4, 4, 4), (0, 0, 0), Radius.constant(1))
        h = b.add_data()
        b.realize()
        arr = np.arange(6 * 6 * 6, dtype=np.float32).reshape(6, 6, 6)
        import jax.numpy as jnp

        b.set_curr(h, jnp.asarray(arr))
        rect = Rect3(Dim3(1, 1, 1), Dim3(5, 5, 5))
        got = b.region_to_host(h, rect)
        np.testing.assert_array_equal(got, arr[1:5, 1:5, 1:5])
        np.testing.assert_array_equal(b.interior_to_host(h), arr[1:5, 1:5, 1:5])

    def test_multi_dtype(self):
        b = LocalBlock((4, 4, 4), (0, 0, 0), Radius.constant(1))
        hf = b.add_data("f", "float32")
        hd = b.add_data("d", "float64")
        hi = b.add_data("i", "int32")
        b.realize()
        assert b.get_curr(hf).dtype == np.float32
        assert b.get_curr(hd).dtype == np.float64
        assert b.get_curr(hi).dtype == np.int32
        assert b.num_data() == 3
