"""Topology-aware block placement as a PlanChoice dimension (ISSUE 15).

The contracts: placement is a first-class, persisted, schema-migrated
plan field (absent => identity); the wire-volume matrix is the IR's
halo geometry aggregated to mesh positions; the QAP search only fires
on non-uniform fabrics and never returns something worse than identity;
the cost model prices a placement's wire term through the link matrix;
realize() binds mesh position i to ``devices[placement[i]]`` with
bit-identical results across every method/partition shape; and the ckpt
plan-mismatch warning covers the new field without crying wolf over
pre-placement snapshots.
"""

import json

import numpy as np
import pytest

import jax

from stencil_tpu.api import DistributedDomain
from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import FixedAssignment, Method, link_cost_matrix, qap
from stencil_tpu.plan import cost as plancost
from stencil_tpu.plan import db as plandb
from stencil_tpu.plan.ir import PlanChoice, PlanConfig, validate_placement

PERM8 = (4, 1, 6, 3, 0, 5, 2, 7)


def scrambled_ring_links(n=8, stride=3):
    """A non-uniform fabric where identity is provably suboptimal on a
    1x1xN ring partition: cheap links sit ``stride`` apart."""
    link = np.full((n, n), 7.0)
    for i in range(n):
        link[i, (i + stride) % n] = link[(i + stride) % n, i] = 1.0
    np.fill_diagonal(link, 0.1)
    return link


# -- the PlanChoice field -----------------------------------------------------


def test_choice_placement_roundtrip_and_label():
    ch = PlanChoice(partition=(2, 2, 2), method="axis-composed",
                    placement=PERM8)
    assert PlanChoice.from_json(ch.to_json()) == ch
    assert ch.is_placed
    assert "/p=4-1-6-3-0-5-2-7" in ch.label()
    ident = PlanChoice(partition=(2, 2, 2), method="axis-composed",
                       placement=tuple(range(8)))
    assert not ident.is_placed
    assert "/p=" not in ident.label()


def test_absent_placement_is_identity():
    """Schema migration: every pre-placement JSON choice (DB entries,
    ckpt plan metas) deserializes to placement=None."""
    ch = PlanChoice.from_json({"partition": [2, 2, 2],
                               "method": "axis-composed"})
    assert ch.placement is None and not ch.is_placed


def test_validate_placement():
    assert validate_placement(None, 8) is None
    assert validate_placement(PERM8, 8) is None
    assert "permutation" in validate_placement((0, 0, 1, 2, 3, 4, 5, 6), 8)
    assert "8 mesh" in validate_placement((0, 1, 2), 8)
    assert validate_placement("junk", 8) is not None


# -- the DB (schema v1, migrated) ---------------------------------------------


def test_db_roundtrips_placement(tmp_path):
    path = str(tmp_path / "plans.json")
    cfg = PlanConfig.make((16, 16, 16), Radius.constant(2), ["float32"],
                          8, "cpu")
    ch = PlanChoice(partition=(2, 2, 2), method="axis-composed",
                    placement=PERM8)
    db = plandb.empty_db()
    plandb.record(db, plandb.make_entry(cfg, ch, "static"))
    plandb.save_db(path, db)
    back = plandb.lookup(plandb.load_db(path), cfg)
    assert PlanChoice.from_json(back["choice"]).placement == PERM8


def test_db_rejects_bad_placement(tmp_path):
    cfg = PlanConfig.make((16, 16, 16), Radius.constant(2), ["float32"],
                          8, "cpu")
    ch = PlanChoice(partition=(2, 2, 2), method="axis-composed",
                    placement=PERM8)
    db = plandb.empty_db()
    entry = plandb.record(db, plandb.make_entry(cfg, ch, "static"))
    entry["choice"]["placement"] = [0, 0, 1, 2, 3, 4, 5, 6]
    with pytest.raises(plandb.PlanDBError):
        plandb.save_db(str(tmp_path / "bad.json"), db)


def test_legacy_v0_entry_migrates_to_identity_placement(tmp_path):
    """A v0 flat-layout entry (no placement field anywhere) migrates to
    source='legacy' with identity placement — the plan_tool show
    round-trip the satellite pins."""
    path = str(tmp_path / "v0.json")
    cfg = PlanConfig.make((16, 16, 16), Radius.constant(2), ["float32"],
                          8, "cpu")
    flat = {cfg.key(): {"partition": [2, 2, 2], "method": "axis-composed",
                        "batch_quantities": True}}
    with open(path, "w") as f:
        json.dump(flat, f)
    db = plandb.load_db(path)
    entry = plandb.lookup(db, cfg)
    assert entry["source"] == "legacy"
    ch = PlanChoice.from_json(entry["choice"])
    assert ch.placement is None and not ch.is_placed
    # and show renders it without crashing
    from stencil_tpu.apps.plan_tool import _entry_row

    row = _entry_row(cfg.key(), entry)
    assert "legacy" in row and "/p=" not in row


# -- wire matrix + QAP + pricing ----------------------------------------------


def test_wire_matrix_matches_qap_cost_authority():
    """placement_cost is pinned equal to parallel.qap.cost (the jax-free
    reimplementation must never drift from the solver's objective)."""
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    w = plancost.placement_wire_matrix(spec, Dim3(2, 2, 2))
    link = scrambled_ring_links()
    for f in (list(range(8)), list(PERM8)):
        assert plancost.placement_cost(w, link, tuple(f)) == pytest.approx(
            qap.cost(w, link, f))


def test_wire_matrix_symmetric_and_excludes_local():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    w = plancost.placement_wire_matrix(spec, Dim3(2, 2, 2))
    np.testing.assert_allclose(w, w.T)
    assert np.all(np.diag(w) == 0)
    # oversubscribed: resident (same-slot) traffic never hits the wire —
    # a 2x2x4 partition on a 2x2x2 mesh halves the z-pair count but the
    # self-z traffic is excluded, not attributed
    spec2 = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 4), Radius.constant(1))
    w2 = plancost.placement_wire_matrix(spec2, Dim3(2, 2, 2))
    assert w2.shape == (8, 8)
    assert np.all(np.diag(w2) == 0)


def test_solve_placement_uniform_is_identity():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    w = plancost.placement_wire_matrix(spec, Dim3(2, 2, 2))
    uniform = np.ones((8, 8))
    np.fill_diagonal(uniform, 0.0)
    assert plancost.uniform_link_costs(uniform)
    assert plancost.solve_placement(w, uniform) is None
    # the live CPU mesh derives a uniform matrix too
    assert plancost.uniform_link_costs(link_cost_matrix(jax.devices()[:8]))


def test_solve_placement_beats_identity_on_scrambled_ring():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(1, 1, 8), Radius.constant(1))
    w = plancost.placement_wire_matrix(spec, Dim3(1, 1, 8))
    link = scrambled_ring_links()
    f = plancost.solve_placement(w, link)
    assert f is not None and sorted(f) == list(range(8))
    assert (plancost.placement_cost(w, link, f)
            < plancost.placement_cost(w, link))


def test_score_prices_placement_and_ranks_it_first():
    cfg = PlanConfig.make((16, 16, 16), Radius.constant(1), ["float32"],
                          8, "cpu")
    link = scrambled_ring_links()
    cands = plancost.enumerate_candidates(cfg, link_costs=link)
    placed = [c for c in cands if c.is_placed]
    assert placed, "non-uniform links must grow placed candidates"
    ranked = plancost.rank(cfg, cands, link_costs=link)
    ring = [(c, ch) for c, ch in ranked
            if ch.method == "axis-composed" and ch.partition == (1, 1, 8)
            and ch.multistep_k == 1]
    ident = next(t for t in ring if not t[1].is_placed)
    plc = next(t for t in ring if t[1].is_placed)
    assert plc[0].total_s < ident[0].total_s
    # identical non-wire terms: only the wire term scaled
    assert plc[0].collectives == ident[0].collectives
    assert plc[0].wire_bytes == ident[0].wire_bytes


def test_uniform_links_leave_search_space_unchanged():
    cfg = PlanConfig.make((16, 16, 16), Radius.constant(1), ["float32"],
                          8, "cpu")
    uniform = np.ones((8, 8))
    np.fill_diagonal(uniform, 0.0)
    assert (len(plancost.enumerate_candidates(cfg, link_costs=uniform))
            == len(plancost.enumerate_candidates(cfg)))


def test_feasible_rejects_malformed_placement():
    cfg = PlanConfig.make((16, 16, 16), Radius.constant(1), ["float32"],
                          8, "cpu")
    bad = PlanChoice(partition=(2, 2, 2), method="axis-composed",
                     placement=(0, 0, 1, 2, 3, 4, 5, 6))
    assert plancost.feasible(cfg, bad) is None
    short = PlanChoice(partition=(2, 2, 2), method="axis-composed",
                       placement=(1, 0))
    assert plancost.feasible(cfg, short) is None


# -- realize() binding + bit parity -------------------------------------------


def _exchange_once(method, part, placement, dtype="float32", grid=16):
    dd = DistributedDomain(grid, grid, grid)
    dd.set_radius(2)
    dd.set_devices(jax.devices()[:8])
    dd.set_plan(PlanChoice(partition=part, method=method,
                           placement=placement))
    h = dd.add_data("q", dtype)
    dd.realize()
    g = dd.size
    z, y, x = np.meshgrid(np.arange(g.z), np.arange(g.y), np.arange(g.x),
                          indexing="ij")
    field = (x + 100 * y + 10000 * z).astype(dtype)
    dd.set_curr_global(h, field)
    dd.exchange()
    return dd, np.asarray(jax.device_get(dd.get_curr(h)))


@pytest.mark.parametrize("method", ["axis-composed", "direct26",
                                    "auto-spmd", "remote-dma"])
def test_placed_exchange_bit_identical_all_methods(method):
    _, ident = _exchange_once(method, (2, 2, 2), None)
    dd, placed = _exchange_once(method, (2, 2, 2), PERM8)
    assert ident.tobytes() == placed.tobytes()
    assert [d.id for d in dd.mesh.devices.flatten()] == list(PERM8)


def test_placed_exchange_uneven_and_oversubscribed():
    # uneven (17^3 over 1x2x4) and oversubscribed (16 blocks on 8 devs)
    _, a = _exchange_once("axis-composed", (1, 2, 4), None, grid=17)
    dd, b = _exchange_once("axis-composed", (1, 2, 4), PERM8, grid=17)
    assert a.tobytes() == b.tobytes()
    _, c = _exchange_once("axis-composed", (2, 2, 4), None)
    dd2, d = _exchange_once("axis-composed", (2, 2, 4), PERM8)
    assert c.tobytes() == d.tobytes()
    assert [dv.id for dv in dd2.mesh.devices.flatten()] == list(PERM8)


def test_realize_rejects_bad_placement():
    dd = DistributedDomain(16, 16, 16)
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    dd.set_plan(PlanChoice(partition=(2, 2, 2), method="axis-composed",
                           placement=(0, 1)))
    dd.add_data("q", "float32")
    with pytest.raises(ValueError, match="placement"):
        dd.realize()


def test_explicit_strategy_wins_over_tuned_placement(capfd):
    """set_placement (a strategy) overrides the tuned tuple, loudly —
    the set_partition-over-tuned-plan convention."""
    dd = DistributedDomain(16, 16, 16)
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    dd.set_placement(FixedAssignment(tuple(range(8))))
    dd.set_plan(PlanChoice(partition=(2, 2, 2), method="axis-composed",
                           placement=PERM8))
    dd.add_data("q", "float32")
    dd.realize()
    assert [d.id for d in dd.mesh.devices.flatten()] == list(range(8))
    assert "overrides the tuned" in capfd.readouterr().err


def test_fixed_assignment_validates():
    with pytest.raises(ValueError):
        FixedAssignment((0, 0, 1))
    fa = FixedAssignment((1, 0))
    devs = jax.devices()[:2]
    assert fa.arrange(devs, None) == [devs[1], devs[0]]
    with pytest.raises(ValueError):
        fa.arrange(jax.devices()[:3], None)


def test_plan_meta_records_placement():
    dd, _ = _exchange_once("axis-composed", (2, 2, 2), PERM8)
    meta = dd.plan_meta()
    assert tuple(meta["choice"]["placement"]) == PERM8


# -- ckpt plan-mismatch coverage ----------------------------------------------


def _realized(plan=None, tuned_placement=None):
    dd = DistributedDomain(16, 16, 16)
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    if plan is not None:
        dd.set_plan(plan)
    dd.add_data("q", "float32")
    dd.realize()
    return dd


def test_ckpt_warns_on_placement_delta(capfd):
    tuned = PlanChoice(partition=(2, 2, 2), method="axis-composed",
                       placement=PERM8)
    dd = _realized(plan=tuned)
    manifest = {"meta": {"plan": dd.plan_meta()}}
    other = _realized(plan=PlanChoice(partition=(2, 2, 2),
                                      method="axis-composed"))
    capfd.readouterr()
    other._warn_plan_mismatch(manifest)
    assert "exchange plan" in capfd.readouterr().err


def test_ckpt_quiet_on_pre_placement_snapshot(capfd):
    """A snapshot written BEFORE the placement field existed (no key in
    its choice dict) must not warn against an identity-placement run."""
    dd = _realized()
    manifest = {"meta": {"plan": dd.plan_meta()}}
    del manifest["meta"]["plan"]["choice"]["placement"]  # old-build shape
    capfd.readouterr()
    dd._warn_plan_mismatch(manifest)
    assert "exchange plan" not in capfd.readouterr().err


def test_ckpt_quiet_on_untuned_placement_only_delta(capfd):
    """Between two UNTUNED runs a placement-only delta stays quiet, like
    the partition-only elastic resume."""
    dd = _realized()
    manifest = {"meta": {"plan": dd.plan_meta()}}
    # hand-edit the saved side to carry a placement (an untuned run
    # whose realize() arranged devices via a strategy)
    manifest["meta"]["plan"]["choice"]["placement"] = list(PERM8)
    capfd.readouterr()
    dd._warn_plan_mismatch(manifest)
    assert "exchange plan" not in capfd.readouterr().err


# -- autotune round-trip ------------------------------------------------------


def test_autotune_persists_and_replays_placement(tmp_path):
    """A non-uniform fabric tunes to a PLACED choice, persists it, and
    the DB hit replays it; realize() binds the replayed assignment."""
    path = str(tmp_path / "plans.json")
    from stencil_tpu.plan.autotune import autotune

    link = scrambled_ring_links()
    first = autotune((16, 16, 16), Radius.constant(1), ["float32"],
                     ndev=8, platform="cpu", db_path=path, probe=False,
                     link_costs=link,
                     methods=("axis-composed",))
    assert first.choice.is_placed, first.choice.label()
    second = autotune((16, 16, 16), Radius.constant(1), ["float32"],
                      ndev=8, platform="cpu", db_path=path, probe=False,
                      link_costs=link, methods=("axis-composed",))
    assert second.cache_hit and second.choice == first.choice


def test_placement_audit_sweep():
    """The verify_plan placement sweep (the CI gate's stage 1) passes on
    the live mesh."""
    from stencil_tpu.analysis.verify_plan import (placement_permutations,
                                                  run_placement_sweep)

    perms = placement_permutations(8, 3)
    assert len(perms) == 3
    assert all(p != tuple(range(8)) for p in perms)
    res = run_placement_sweep(count=3, size=16, radius=2,
                              partition=(2, 2, 2))
    assert res["checked"] == 3 and res["failed"] == 0


def test_placement_permutations_valid_for_odd_ndev():
    """Every emitted fixture must be a real permutation — the naive
    pairwise-swap formula mapped odd ndev's last index out of range, so
    the sweep FAILED (IndexError verdicts) on a healthy build."""
    from stencil_tpu.analysis.verify_plan import placement_permutations

    for ndev in (2, 3, 5, 7, 8):
        for p in placement_permutations(ndev, 3):
            assert validate_placement(p, ndev) is None, (ndev, p)
            assert p != tuple(range(ndev))


def test_replan_failure_rolls_back_to_the_old_plan():
    """A choice that cannot realize must leave the domain EXACTLY as it
    was — the ReplanController's 'rejected, continuing on the old plan'
    contract — not torn with its state dropped."""
    dd = _realized(plan=PlanChoice(partition=(2, 2, 2),
                                   method="axis-composed"))
    h_idx = 0
    field = np.arange(16 ** 3, dtype=np.float32).reshape(16, 16, 16)
    from stencil_tpu.domain import DataHandle

    h = DataHandle(h_idx, "q", "float32")
    dd.set_curr_global(h, field)
    before = dd.get_curr_global(h)
    # 27 blocks on 8 devices: realize() must reject it
    bad = PlanChoice(partition=(3, 3, 3), method="axis-composed")
    with pytest.raises(ValueError):
        dd.replan(bad)
    assert dd._realized and dd.spec.dim == Dim3(2, 2, 2)
    assert dd._method == Method.AXIS_COMPOSED
    np.testing.assert_array_equal(dd.get_curr_global(h), before)
    # and the domain still swaps plans normally afterwards
    dd.replan(PlanChoice(partition=(1, 2, 4), method="axis-composed"))
    np.testing.assert_array_equal(dd.get_curr_global(h), before)
