"""ExchangePlan IR — the declarative plan vs the compiled truth.

The refactor contract: every exchange method lowers from the IR
(parallel/exchange.py consumes HaloExchange.plan's phase records), and
the lowering compiles to the SAME programs as the pre-refactor method
branches. Pinned three ways:

- census pins: the IR's predicted collective count must equal the
  compiled program's census for every method / batching / Q (the round-7
  and round-10 recorded counts: 6 composed, <=26 direct26, Q-independent
  when batched, 6*Q per-quantity / auto);
- byte pins: the IR's wire-byte estimate reproduces the RECORDED round-7
  on-wire bytes for the recorded config (pure geometry, no jax);
- parity: the plan-driven lowering still fills every halo correctly on
  uneven + oversubscribed partitions (the test_exchange fixtures, reused
  per the refactor's acceptance).

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks
from stencil_tpu.plan.ir import (
    AxisPhaseIR,
    DirectPhaseIR,
    PlanChoice,
    PlanConfig,
    build_plan,
    radius_dirs,
    radius_from_dirs,
)

from test_exchange import check_halos, coord_field


def _census_permutes(ex, state):
    census = ex.collective_census(state)
    other = sum(c for k, (c, _b) in census.items()
                if k != "collective-permute")
    assert other == 0, f"non-permute collectives snuck in: {census}"
    return census.get("collective-permute", (0, 0))[0]


def _state(spec, mesh, nq, dtype=np.float32):
    g = spec.global_size
    field = np.arange(g.x * g.y * g.z, dtype=dtype).reshape(g.z, g.y, g.x)
    return {i: shard_blocks(field + i, spec, mesh) for i in range(nq)}


@pytest.mark.parametrize("method,batched,nq,expect", [
    (Method.AXIS_COMPOSED, True, 4, 6),    # one carrier pair per phase
    (Method.AXIS_COMPOSED, False, 3, 18),  # 6 per quantity
    (Method.DIRECT26, True, 2, 26),        # one carrier per direction
])
def test_plan_predicts_census(method, batched, nq, expect):
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, method, batch_quantities=batched)
    assert ex.plan.collectives_per_exchange(nq, 1) == expect
    assert _census_permutes(ex, _state(spec, mesh, nq)) == expect


def test_auto_plan_predicts_census():
    # round-7 finding, encoded in the IR: the partitioner reinvents the
    # composed schedule per quantity (6*Q permutes)
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, Method.AUTO_SPMD)
    nq = 2
    assert ex.plan.synthesized
    assert ex.plan.collectives_per_exchange(nq, 1) == 12
    assert _census_permutes(ex, _state(spec, mesh, nq)) == 12


def test_plan_wire_bytes_reproduce_round7_record():
    # BASELINE.md round 7: 128^3, 2x2x2, uniform r2, 4 fp32 quantities ->
    # 12,484,608 on-wire bytes for the composed plan. Pure geometry.
    spec = GridSpec(Dim3(128, 128, 128), Dim3(2, 2, 2), Radius.constant(2))
    plan = build_plan(spec, Dim3(2, 2, 2), Method.AXIS_COMPOSED)
    assert plan.wire_bytes([4, 4, 4, 4]) == 12_484_608


def test_axis_phase_order_and_geometry():
    spec = GridSpec(Dim3(24, 16, 16), Dim3(2, 1, 2), Radius.constant(1))
    plan = build_plan(spec, Dim3(2, 1, 2), Method.AXIS_COMPOSED)
    assert [p.axis for p in plan.axis_phases] == ["x", "y", "z"]
    x, y, z = plan.axis_phases
    assert isinstance(x, AxisPhaseIR)
    assert (x.ring, x.resident) == (2, 1)
    assert (y.ring, y.resident) == (1, 1)   # self-wrap: no permute pairs
    assert y.collectives() == 0 and y.fwd == ()
    assert x.fwd == ((0, 1), (1, 0))
    assert x.sizes == (12, 12)
    # phases carry the per-exchange byte split: self-wrap y moves only
    # locally, split x/z ride the wire
    assert y.wire_cells == 0 and y.local_cells > 0
    assert x.wire_cells > 0


def test_oversubscribed_plan_ring_and_resident():
    # 2x2x2 partition on 4 devices: stack_residents -> z-heavy (cz=2)
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    plan = build_plan(spec, Dim3(2, 2, 1), Method.AXIS_COMPOSED)
    z = plan.axis_phases[2]
    assert (z.ring, z.resident) == (1, 2)
    assert z.collectives() == 0  # single-device ring: boundary wraps locally
    x = plan.axis_phases[0]
    assert (x.ring, x.resident) == (2, 1)


def test_direct26_phases_uniform():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    plan = build_plan(spec, Dim3(2, 2, 2), Method.DIRECT26)
    assert len(plan.direct_phases) == 26
    ph = plan.direct_phases[0]
    assert isinstance(ph, DirectPhaseIR)
    assert ph.src is not None and ph.dst is not None
    assert len(ph.pairs) == 8  # flattened 26-neighbor permutation, 8 devs
    assert all(p.collective_count == 1 for p in plan.direct_phases)


def test_direct26_phases_uneven_sorted_and_padded():
    spec = GridSpec(Dim3(17, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    plan = build_plan(spec, Dim3(2, 2, 2), Method.DIRECT26)
    ranks = [abs(p.direction[0]) + abs(p.direction[1]) + abs(p.direction[2])
             for p in plan.direct_phases]
    assert ranks == sorted(ranks), "uneven apply order must be face->edge->corner"
    # orthogonal extents pad to the base block size
    face_x = next(p for p in plan.direct_phases if p.direction == (1, 0, 0))
    assert face_x.shape == (spec.base.z, spec.base.y, 1)
    assert face_x.src is None  # traced per-block starts at lowering time


def test_plan_lowering_parity_uneven_oversubscribed():
    # the refactor's end-to-end pin: the plan-driven lowering still fills
    # every halo on an uneven partition with resident oversubscription
    spec = GridSpec(Dim3(18, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    mesh = grid_mesh(Dim3(2, 2, 1), jax.devices()[:4])
    ex = HaloExchange(spec, mesh, Method.AXIS_COMPOSED)
    assert ex.plan.resident == (1, 1, 2)
    stacked = shard_blocks(coord_field(spec.global_size), spec, mesh)
    out = ex(stacked)
    check_halos(out, spec)


def test_radius_roundtrip_and_center_excluded():
    r = Radius.constant(2)
    dirs = radius_dirs(r)
    assert all(d[:3] != (0, 0, 0) for d in dirs)
    r2 = radius_from_dirs(dirs)
    for d, v in r._r.items():
        if d != (0, 0, 0):
            assert r2.dir(d) == v


def test_plan_config_key_and_choice_roundtrip():
    cfg = PlanConfig.make(Dim3(24, 24, 24), Radius.constant(2),
                          ["float64", "float32", "float32"], 8, "cpu")
    assert cfg.quantities == (("float32", 2), ("float64", 1))
    assert PlanConfig.from_json(cfg.to_json()) == cfg
    ch = PlanChoice(partition=(2, 2, 2), method="direct26",
                    batch_quantities=False, multistep_k=2,
                    kernel_variant="ring")
    assert PlanChoice.from_json(ch.to_json()) == ch
    assert "k=2" in ch.label() and "ring" in ch.label()
