"""Partition math tests ported from the reference's exact expectations
(reference: test/test_cpu_partition.cpp:7-73) plus NodePartition behavior."""

from stencil_tpu.geometry import Dim3, NodePartition, Radius, RankPartition, prime_factors


def test_prime_factors_sorted_desc():
    assert prime_factors(1) == []
    assert prime_factors(2) == [2]
    assert prime_factors(12) == [3, 2, 2]
    assert prime_factors(9) == [3, 3]
    assert prime_factors(13) == [13]


def test_10x5x5_into_2():
    part = RankPartition((10, 5, 5), 2)
    assert part.dim() == Dim3(2, 1, 1)
    assert part.subdomain_size((0, 0, 0)) == Dim3(5, 5, 5)
    assert part.subdomain_size((1, 0, 0)) == Dim3(5, 5, 5)


def test_10x3x1_into_4():
    part = RankPartition((10, 3, 1), 4)
    assert part.subdomain_size((0, 0, 0)) == Dim3(3, 3, 1)
    assert part.subdomain_size((1, 0, 0)) == Dim3(3, 3, 1)
    assert part.subdomain_size((2, 0, 0)) == Dim3(2, 3, 1)
    assert part.subdomain_size((3, 0, 0)) == Dim3(2, 3, 1)
    assert part.subdomain_origin((0, 0, 0)) == Dim3(0, 0, 0)
    assert part.subdomain_origin((1, 0, 0)) == Dim3(3, 0, 0)
    assert part.subdomain_origin((2, 0, 0)) == Dim3(6, 0, 0)
    assert part.subdomain_origin((3, 0, 0)) == Dim3(8, 0, 0)


def test_10x5x5_into_3():
    part = RankPartition((10, 5, 5), 3)
    assert part.subdomain_size((0, 0, 0)) == Dim3(4, 5, 5)
    assert part.subdomain_size((1, 0, 0)) == Dim3(3, 5, 5)
    assert part.subdomain_size((2, 0, 0)) == Dim3(3, 5, 5)


def test_13x7x7_into_4():
    part = RankPartition((13, 7, 7), 4)
    assert part.subdomain_size((0, 0, 0)) == Dim3(4, 7, 7)
    assert part.subdomain_size((1, 0, 0)) == Dim3(3, 7, 7)
    assert part.subdomain_size((2, 0, 0)) == Dim3(3, 7, 7)
    assert part.subdomain_size((3, 0, 0)) == Dim3(3, 7, 7)


def test_10x14x2_into_9():
    part = RankPartition((10, 14, 2), 9)
    assert part.subdomain_origin((0, 0, 0)) == Dim3(0, 0, 0)
    assert part.subdomain_origin((1, 1, 0)) == Dim3(4, 5, 0)
    assert part.subdomain_origin((2, 2, 0)) == Dim3(7, 10, 0)


def test_linearize_roundtrip():
    part = RankPartition((8, 8, 8), 8)
    n = part.dim().flatten()
    assert n == 8
    for i in range(n):
        assert part.linearize(part.dimensionize(i)) == i


def test_subdomains_tile_global_domain():
    """Every global cell belongs to exactly one subdomain."""
    size = Dim3(13, 7, 5)
    part = RankPartition(size, 6)
    seen = set()
    d = part.dim()
    for z in range(d.z):
        for y in range(d.y):
            for x in range(d.x):
                idx = Dim3(x, y, z)
                o = part.subdomain_origin(idx)
                s = part.subdomain_size(idx)
                for pz in range(o.z, o.z + s.z):
                    for py in range(o.y, o.y + s.y):
                        for px in range(o.x, o.x + s.x):
                            p = (px, py, pz)
                            assert p not in seen
                            seen.add(p)
    assert len(seen) == size.flatten()


def test_node_partition_min_interface():
    # with a uniform radius, NodePartition cuts the axis with the smallest
    # orthogonal area first: for a long-x box that is the x axis
    # (reference: partition.hpp:167-208)
    part = NodePartition((64, 16, 16), Radius.constant(1), 2, 2)
    assert part.sys_dim() == Dim3(2, 1, 1)
    assert part.node_dim() == Dim3(2, 1, 1)
    assert part.base_size() == Dim3(16, 16, 16)


def test_node_partition_radius_weighting():
    # zero radius in x makes the x interface free, so splits prefer x even
    # when x is short
    r = Radius.constant(2)
    for d in ((1, 0, 0), (-1, 0, 0)):
        r.set_dir(d, 0)
    part = NodePartition((8, 64, 64), r, 4, 1)
    assert part.sys_dim() == Dim3(4, 1, 1)


def test_node_partition_uneven():
    part = NodePartition((10, 10, 10), Radius.constant(1), 3, 1)
    sizes = [part.subdomain_size((i, 0, 0)).x for i in range(3)]
    origins = [part.subdomain_origin((i, 0, 0)).x for i in range(3)]
    assert sizes == [4, 3, 3]
    assert origins == [0, 4, 7]
    assert not part.is_uniform()


def test_decompose_zy_keeps_x_whole():
    """TPU-first decomposition: z/y only, z first, x never splits."""
    from stencil_tpu.geometry import decompose_zy

    assert tuple(decompose_zy(1)) == (1, 1, 1)
    assert tuple(decompose_zy(2)) == (1, 1, 2)
    assert tuple(decompose_zy(4)) == (1, 2, 2)
    assert tuple(decompose_zy(8)) == (1, 2, 4)
    assert tuple(decompose_zy(64)) == (1, 8, 8)
    for p in (3, 6, 12, 24, 48):
        d = decompose_zy(p)
        assert d.x == 1 and d.flatten() == p
