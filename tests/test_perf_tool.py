"""perf_tool tests: trend across rounds, the regression sentinel tripping
and passing in BOTH directions (throughput legs trip low, seconds legs
trip high), per-leg threshold config, legacy ingest over the committed
BENCH_r0*/MULTICHIP_r0* shapes, and the committed LEDGER.jsonl
acceptance pin (the r05 flagship renders with its round label)."""

import json
import os

import pytest

from stencil_tpu.apps import perf_tool
from stencil_tpu.obs import ledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _seed(path, metric, values, labels=None, unit=None, direction_cfg=None):
    es = []
    for i, v in enumerate(values):
        lbl = labels[i] if labels else f"h{i:02d}"
        es.append(ledger.make_entry(metric, v, label=lbl, unit=unit,
                                    platform="cpu", config={"c": 1}))
    ledger.append_entries(path, es)


# -- sentinel -----------------------------------------------------------------


def test_gate_trips_low_on_throughput_leg(tmp_path, capsys):
    led = str(tmp_path / "L.jsonl")
    _seed(led, "leg_gb_per_s", [10.0, 10.4, 9.8])
    _seed(led, "leg_gb_per_s", [5.0], labels=["new"])
    rc = perf_tool.main(["gate", "--ledger", led, "--metric", "leg_gb_per_s",
                         "--label", "new", "--rel-tol", "0.2"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "GATE FAIL leg_gb_per_s" in out and "below" in out
    # ...but an IMPROVEMENT on a higher-is-better leg never trips
    _seed(led, "leg_gb_per_s", [20.0], labels=["fast"])
    rc = perf_tool.main(["gate", "--ledger", led, "--metric", "leg_gb_per_s",
                         "--label", "fast", "--rel-tol", "0.2"])
    assert rc == 0


def test_gate_trips_high_on_seconds_leg(tmp_path, capsys):
    led = str(tmp_path / "L.jsonl")
    _seed(led, "loop_wall_s", [1.0, 1.05, 0.97], unit="s")
    _seed(led, "loop_wall_s", [4.0], labels=["new"], unit="s")
    rc = perf_tool.main(["gate", "--ledger", led, "--metric", "loop_wall_s",
                         "--label", "new", "--rel-tol", "0.2"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "GATE FAIL loop_wall_s" in out and "above" in out
    # a faster run on a lower-is-better leg passes
    _seed(led, "loop_wall_s", [0.5], labels=["fast"], unit="s")
    rc = perf_tool.main(["gate", "--ledger", led, "--metric", "loop_wall_s",
                         "--label", "fast", "--rel-tol", "0.2"])
    assert rc == 0


def test_gate_passes_within_band_and_skips_thin_history(tmp_path, capsys):
    led = str(tmp_path / "L.jsonl")
    _seed(led, "leg_gb_per_s", [10.0, 10.4, 9.8])
    _seed(led, "leg_gb_per_s", [10.1], labels=["new"])
    rc = perf_tool.main(["gate", "--ledger", led, "--metric", "leg_gb_per_s",
                         "--label", "new", "--rel-tol", "0.2"])
    assert rc == 0
    assert "GATE PASS leg_gb_per_s" in capsys.readouterr().out
    # a leg with no history is a SKIP, and judging nothing exits 2
    led2 = str(tmp_path / "L2.jsonl")
    _seed(led2, "lonely", [1.0], labels=["only"])
    rc = perf_tool.main(["gate", "--ledger", led2, "--metric", "lonely",
                         "--label", "only"])
    assert rc == 2
    assert "SKIP" in capsys.readouterr().out


def test_gate_mad_band_tighter_than_rel_tol():
    # 3*MAD dominates when history is tight and rel_tol is 0
    es = [ledger.make_entry("m", v, label=f"h{i}", platform="cpu",
                            config={"c": 1})
          for i, v in enumerate([10.0, 10.1, 9.9, 10.05])]
    es.append(ledger.make_entry("m", 9.0, label="new", platform="cpu",
                                config={"c": 1}))
    verdicts = perf_tool.evaluate_gate(es, metrics=["m"], label="new",
                                       rel_tol=0.0, mad_k=3.0)
    assert verdicts[0]["status"] == "fail"
    assert verdicts[0]["tol"] == pytest.approx(3.0 * ledger.mad(
        [10.0, 10.1, 9.9, 10.05]))


def test_gate_per_leg_config_overrides(tmp_path, capsys):
    led = str(tmp_path / "L.jsonl")
    _seed(led, "leg_gb_per_s", [10.0, 10.2], )
    _seed(led, "leg_gb_per_s", [5.0], labels=["new"])
    cfg = str(tmp_path / "legs.json")
    # an explicit wide tolerance + direction=both for this leg
    with open(cfg, "w") as f:
        json.dump({"leg_gb_per_s": {"rel_tol": 0.9}}, f)
    rc = perf_tool.main(["gate", "--ledger", led, "--metric", "leg_gb_per_s",
                         "--label", "new", "--rel-tol", "0.1",
                         "--leg-config", cfg])
    assert rc == 0  # the per-leg override widened the band
    with open(cfg, "w") as f:
        json.dump({"*": {"direction": "both", "rel_tol": 0.05}}, f)
    _seed(led, "leg_gb_per_s", [17.0], labels=["hot"])
    rc = perf_tool.main(["gate", "--ledger", led, "--metric", "leg_gb_per_s",
                         "--label", "hot", "--leg-config", cfg])
    assert rc == 1  # direction=both: even an "improvement" out of band trips
    capsys.readouterr()


def test_default_direction_heuristic():
    assert perf_tool.default_direction("exchange.gb_per_s", None) == "higher"
    assert perf_tool.default_direction("jacobi.mcells_per_s_per_dev",
                                       None) == "higher"
    assert perf_tool.default_direction("jacobi.loop_wall_s", "s") == "lower"
    assert perf_tool.default_direction("jacobi.iter_trimean_s",
                                       None) == "lower"
    assert perf_tool.default_direction("astaroth_512_iter_ms",
                                       None) == "lower"
    assert perf_tool.default_direction("bench.rc", "rc") == "lower"
    # the report-style tag suffix does not confuse the lookup
    assert perf_tool.default_direction("exchange.trimean_s[direct26]",
                                       None) == "lower"


# -- trend / diff / render ----------------------------------------------------


def test_trend_and_diff(tmp_path, capsys):
    led = str(tmp_path / "L.jsonl")
    _seed(led, "leg", [10.0, 20.0], labels=["r01", "r02"], unit="GB/s")
    rc = perf_tool.main(["trend", "--ledger", led])
    out = capsys.readouterr().out
    assert rc == 0
    assert "r01" in out and "r02" in out and "2.000x" in out
    rc = perf_tool.main(["diff", "--ledger", led, "--a", "r01", "--b", "r02"])
    out = capsys.readouterr().out
    assert rc == 0 and "2.000" in out


def test_committed_ledger_renders_r05_flagship(capsys):
    """The acceptance pin: the committed LEDGER.jsonl carries the real
    r01->r05 trajectory, ending at the 83.1 Gcells/s round-5 flagship."""
    led = os.path.join(REPO, "LEDGER.jsonl")
    entries = ledger.load_ledger(led)  # schema-valid by construction
    assert len(entries) >= 30
    rc = perf_tool.main(["trend", "--ledger", led,
                         "--metric", "jacobi3d_512_mcells_per_s_per_chip"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "r05" in out and "83059.7" in out  # = 83.1 Gcells/s
    assert "r01" in out and "5395" in out     # the round-1 start
    # the failed round 3 and the CPU-fallback round 4 are visible too
    rc = perf_tool.main(["trend", "--ledger", led, "--metric", "bench.rc"])
    out = capsys.readouterr().out
    assert "r03" in out
    rc = perf_tool.main(["trend", "--ledger", led,
                         "--metric", "multichip_dryrun_ok"])
    out = capsys.readouterr().out
    assert "r02" in out and "r05" in out


def test_render_dashboard(tmp_path, capsys):
    led = str(tmp_path / "L.jsonl")
    _seed(led, "leg_gb_per_s", [10.0, 10.3], labels=["r01", "r02"])
    out_md = str(tmp_path / "dash.md")
    rc = perf_tool.main(["render", "--ledger", led, "--out", out_md])
    capsys.readouterr()
    assert rc == 0
    text = open(out_md).read()
    assert "# Performance dashboard" in text
    assert "## Latest" in text and "## Trends" in text
    assert "leg_gb_per_s" in text


# -- ingest CLI ---------------------------------------------------------------


def test_ingest_legacy_files_idempotent(tmp_path, capsys):
    led = str(tmp_path / "L.jsonl")
    argv = ["ingest", "--ledger", led, "--legacy",
            os.path.join(REPO, "BENCH_r05.json"),
            os.path.join(REPO, "MULTICHIP_r05.json")]
    assert perf_tool.main(argv) == 0
    n1 = len(ledger.load_ledger(led))
    assert n1 >= 8
    assert perf_tool.main(argv) == 0  # re-ingest: nothing new
    assert len(ledger.load_ledger(led)) == n1
    capsys.readouterr()


def test_ingest_metrics_jsonl(tmp_path, capsys):
    import io

    from stencil_tpu.obs import telemetry

    buf = io.StringIO()
    rec = telemetry.Recorder(sink=buf, app="t", run_id="RUN")
    rec.meta("config", config={"x": 24})
    for v in (1.0, 1.1, 0.9):
        rec.gauge("leg.wall_s", v, unit="s")
    m = tmp_path / "m.jsonl"
    m.write_text(buf.getvalue())
    led = str(tmp_path / "L.jsonl")
    rc = perf_tool.main(["ingest", "--ledger", led, "--label", "run1",
                         "--platform", "cpu", str(m)])
    capsys.readouterr()
    assert rc == 0
    es = ledger.load_ledger(led)
    assert es[0]["metric"] == "leg.wall_s" and es[0]["platform"] == "cpu"
    # a schema-invalid metrics line fails the ingest loudly
    m.write_text(buf.getvalue() + '{"v": 1}\n')
    with pytest.raises(ValueError, match="missing required key"):
        perf_tool.ingest_file(str(m), label="run2")


def test_ingest_rejects_unknown_shape(tmp_path):
    p = tmp_path / "odd.json"
    p.write_text(json.dumps({"what": "is this"}))
    with pytest.raises(ValueError, match="unrecognized payload shape"):
        perf_tool.ingest_file(str(p))


def test_ingest_single_line_metrics_jsonl(tmp_path, capsys):
    """A metrics file with exactly ONE record parses as a single dict —
    it must still route to the telemetry-JSONL path, not be rejected as
    an unrecognized payload."""
    m = tmp_path / "one.jsonl"
    m.write_text(json.dumps(
        {"v": 1, "run": "R", "proc": 0, "kind": "gauge", "name": "leg.s",
         "t": 0.0, "value": 2.5, "unit": "s"}) + "\n")
    es = perf_tool.ingest_file(str(m), label="run1", platform="cpu")
    assert len(es) == 1
    assert es[0]["metric"] == "leg.s" and es[0]["value"] == 2.5


def test_backfilled_round_keeps_its_label_position(tmp_path, capsys):
    """Groups order by (label, t), not ingest time: a round backfilled
    AFTER later rounds (stamped with today's t) must not become the
    trend's 'latest' nor the gate's default judged label."""
    led = str(tmp_path / "L.jsonl")
    _seed(led, "leg", [10.0, 30.0], labels=["r01", "r05"])
    _seed(led, "leg", [20.0], labels=["r03"])  # backfill, newest t
    gs = perf_tool.groups(ledger.load_ledger(led))
    es = next(iter(gs.values()))
    assert [e["label"] for e in es] == ["r01", "r03", "r05"]
    # default gate label is the group's LAST label (r05), not r03
    verdicts = perf_tool.evaluate_gate(ledger.load_ledger(led),
                                       metrics=["leg"], rel_tol=9.0)
    assert verdicts[0]["label"] == "r05"
    rc = perf_tool.main(["trend", "--ledger", led])
    out = capsys.readouterr().out
    assert out.index("r03") < out.index("r05")


def test_ingest_one_label_many_files_warns(tmp_path, capsys):
    """Repeat runs of one config ingested under ONE label dedup to the
    first file's value — the CLI must say so loudly."""
    import io

    from stencil_tpu.obs import telemetry

    paths = []
    for i, v in enumerate((1.0, 9.0)):
        buf = io.StringIO()
        rec = telemetry.Recorder(sink=buf, app="t", run_id=f"R{i}")
        rec.gauge("leg.s", v, unit="s")
        p = tmp_path / f"m{i}.jsonl"
        p.write_text(buf.getvalue())
        paths.append(str(p))
    led = str(tmp_path / "L.jsonl")
    assert perf_tool.main(["ingest", "--ledger", led, "--label", "day1",
                           "--platform", "cpu"] + paths) == 0
    err = capsys.readouterr().err
    assert "WARNING" in err and "day1" in err
    # the dedup the warning describes: only the first file's value landed
    es = [e for e in ledger.load_ledger(led) if e["metric"] == "leg.s"]
    assert [e["value"] for e in es] == [1.0]


def test_live_bench_label_orders_after_round_history(tmp_path, capsys):
    """The documented auto-append flow: a default bench-<timestamp>
    label must order AFTER the rNN prehistory (lexicographically it
    sorts before "r01"), so the no-label gate judges the NEW round —
    and trips on its regression — instead of re-judging r05."""
    led = str(tmp_path / "L.jsonl")
    _seed(led, "leg", [100.0, 110.0, 105.0], labels=["r01", "r02", "r05"])
    _seed(led, "leg", [50.0], labels=["bench-20260803T120000"])
    es = ledger.load_ledger(led)
    ordered = next(iter(perf_tool.groups(es).values()))
    assert [e["label"] for e in ordered][-1] == "bench-20260803T120000"
    verdicts = perf_tool.evaluate_gate(es, metrics=["leg"], rel_tol=0.2)
    assert verdicts[0]["label"] == "bench-20260803T120000"
    assert verdicts[0]["status"] == "fail"  # the regression IS judged
    rc = perf_tool.main(["trend", "--ledger", led])
    out = capsys.readouterr().out
    assert out.index("r05") < out.index("bench-20260803T120000")


def test_read_subcommands_fail_on_missing_ledger(tmp_path, capsys):
    """trend/diff/gate/render on a mistyped --ledger path must exit
    nonzero, not render an empty artifact with rc 0."""
    typo = str(tmp_path / "TYPO.jsonl")
    for argv in (["trend", "--ledger", typo],
                 ["diff", "--ledger", typo, "--a", "x", "--b", "y"],
                 ["gate", "--ledger", typo],
                 ["render", "--ledger", typo]):
        assert perf_tool.main(argv) == 2
        assert "no such ledger" in capsys.readouterr().err


def test_outage_round_joins_platform_trend_group(tmp_path, capsys):
    """The r03 discipline, end to end: an outage payload (no detail, so
    platform 'unknown') must land INSIDE the real trajectory's trend
    group — and trip the gate — not sit in an isolated single-entry
    group nobody reads."""
    led = str(tmp_path / "L.jsonl")
    healthy = {"metric": "leg_mcells_per_s", "value": 100.0,
               "detail": {"platform": "tpu", "size": 512}}
    outage = {"metric": "leg_mcells_per_s", "value": 0.0,
              "vs_baseline": 0.0,
              "detail": {"error": "all bench children failed"}}
    es = []
    for i, p in enumerate((healthy, healthy, healthy)):
        es += ledger.entries_from_bench_payload(p, label=f"r{i + 1:02d}")
    es += ledger.entries_from_bench_payload(outage, label="r04")
    ledger.append_entries(led, es)
    gs = perf_tool.groups(ledger.load_ledger(led),
                          metrics=["leg_mcells_per_s"])
    assert len(gs) == 1, f"outage split the trend group: {list(gs)}"
    (key, group), = gs.items()
    assert key[1] == "tpu"
    assert [e["label"] for e in group] == ["r01", "r02", "r03", "r04"]
    # the trend renders the zero in the trajectory...
    assert perf_tool.main(["trend", "--ledger", led,
                           "--metric", "leg_mcells_per_s"]) == 0
    out = capsys.readouterr().out
    assert out.count("leg_mcells_per_s ·") == 1 and "r04,0," in out
    # ...and the newest-label gate trips on it by name
    rc = perf_tool.main(["gate", "--ledger", led,
                         "--metric", "leg_mcells_per_s"])
    assert rc == 1
    assert "GATE FAIL leg_mcells_per_s" in capsys.readouterr().out
    # all-unknown metrics (the MULTICHIP docs) still stand alone
    led2 = str(tmp_path / "L2.jsonl")
    ledger.append_entries(led2, [
        ledger.make_entry("multichip_dryrun_ok", 1.0, label="r02",
                          platform="unknown", config={"n_devices": 8})])
    gs2 = perf_tool.groups(ledger.load_ledger(led2))
    assert list(gs2)[0][1] == "unknown"


def test_label_from_filename_requires_round_form():
    """Only the committed _rNN form names a round: a loose trailing
    _<digits> (bench_128.json) must NOT become round 'r128' and displace
    the real newest round in order_key's rNN prehistory."""
    assert perf_tool._label_from_filename("BENCH_r03.json") == "r03"
    assert perf_tool._label_from_filename("MULTICHIP_r05.json") == "r05"
    assert perf_tool._label_from_filename("bench_128.json") is None
    assert perf_tool._label_from_filename("payload.json") is None


def test_platform_filter_keeps_all_unknown_metrics(tmp_path, capsys):
    """A --platform filter must not silently un-judge metrics that exist
    ONLY as platform-'unknown' (the MULTICHIP docs): with no platform-
    tagged group to join, the unknown group stands alone even filtered."""
    led = str(tmp_path / "L.jsonl")
    ledger.append_entries(led, [
        ledger.make_entry("multichip_dryrun_ok", float(v), label=f"r{i + 1:02d}",
                          platform="unknown", config={"n_devices": 8})
        for i, v in enumerate((1.0, 1.0, 1.0))])
    gs = perf_tool.groups(ledger.load_ledger(led), platform="tpu")
    (key,) = gs
    assert key[:2] == ("multichip_dryrun_ok", "unknown")
    assert len(next(iter(gs.values()))) == 3
    rc = perf_tool.main(["trend", "--ledger", led, "--platform", "tpu"])
    assert rc == 0
    assert "multichip_dryrun_ok" in capsys.readouterr().out


def test_markdown_flag_only_on_table_subcommands(tmp_path):
    """gate output is line-oriented and render is unconditionally
    markdown — neither accepts a dead --markdown flag."""
    led = str(tmp_path / "L.jsonl")
    _seed(led, "leg", [1.0, 1.0], labels=["r01", "r02"])
    for argv in (["gate", "--ledger", led, "--markdown"],
                 ["render", "--ledger", led, "--markdown"]):
        with pytest.raises(SystemExit) as ei:
            perf_tool.main(argv)
        assert ei.value.code == 2
    assert perf_tool.main(["trend", "--ledger", led, "--markdown"]) == 0
    assert perf_tool.main(["diff", "--ledger", led, "--a", "r01", "--b", "r02",
                           "--markdown"]) == 0


def test_gate_bad_leg_config_is_usage_error_not_trip(tmp_path, capsys):
    """A mistyped or malformed --leg-config must exit 2 with a message,
    not escape as a traceback with rc 1 — CI would read that as a
    regression trip."""
    led = str(tmp_path / "L.jsonl")
    _seed(led, "leg", [1.0, 1.0, 1.0])
    for cfg in (str(tmp_path / "TYPO.json"),):
        rc = perf_tool.main(["gate", "--ledger", led, "--leg-config", cfg])
        assert rc == 2
        assert "bad --leg-config" in capsys.readouterr().err
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    rc = perf_tool.main(["gate", "--ledger", led, "--leg-config", str(bad)])
    assert rc == 2
    assert "bad --leg-config" in capsys.readouterr().err
    # a non-object config is the load_leg_config ValueError path
    bad.write_text("[1, 2]")
    rc = perf_tool.main(["gate", "--ledger", led, "--leg-config", str(bad)])
    assert rc == 2
    assert "bad --leg-config" in capsys.readouterr().err
