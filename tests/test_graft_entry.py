"""Driver-contract tests: entry() compile-checks and dryrun_multichip runs
over the virtual 8-device mesh."""

import sys

import jax
import pytest
import numpy as np


def _load():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__

    return __graft_entry__


def test_entry_single_chip():
    ge = _load()
    fn, args = ge.entry()
    out = fn(*args)
    jax.block_until_ready(out)
    curr, nxt = out
    assert np.isfinite(np.asarray(jax.device_get(curr))).all()


@pytest.mark.slow
def test_dryrun_multichip_8():
    ge = _load()
    ge.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_4():
    ge = _load()
    ge.dryrun_multichip(4)
