"""The persistent whole-chunk mega-kernel stack (ISSUE 16 / ROADMAP #7),
pinned on the CPU emulation.

The claims under test:

- **plan IR**: ``persistent=True`` is REMOTE_DMA-only, single-resident-
  only, k >= 2 only (loud everywhere: build_plan, HaloExchange, cost);
  ``launches_per_chunk(k)`` predicts 2 per chunk for the persistent
  lowering vs 2k for the per-step REMOTE_DMA lowerings and 1 for the
  one-XLA-program methods.
- **depth feasibility**: a chunk depth whose radius*k halo exceeds a
  block interior is refused statically (plan/cost.feasible) AND at the
  driver (check_chunk_depth) — never a silent wrong answer; the VMEM
  staging planner (plan_multistep_staging) self-caps instead of
  overflowing the budget.
- **bit parity**: the host-orchestrated persistent chunk loop — ONE
  deep (radius*k) exchange + ONE k-substep chunk program per chunk —
  lands bit-identical to the composed per-step baseline across uniform
  and UNEVEN partitions, k in {2, 4}, tail chunks included, with the
  measured launch census pinned at 2 dispatches per chunk.
- **interpret-mode kernel**: the single-device all-self-wrap mega-kernel
  (in-kernel deep exchange + k plane-streamed substeps over a mod-3
  plane ring) equals the XLA chunk body bit-for-bit, INCLUDING grown
  z extents that wrap the ring mid-window (nz % 3 != 0).
- **guarded loop**: the persistent step drives fault/recover.run_guarded
  end-to-end — rollback recomputation is bit-identical to a clean run.
- **plan plumbing**: the autotuner searches the persistent variant at
  k >= 2, persists it, replays it probe-free; verify_plan audits the
  persistent lowering's census/DMA/launch predictions.

Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.ops.jacobi import INIT_TEMP, make_jacobi_loop, sphere_sel
from stencil_tpu.ops.persistent_stencil import (
    check_chunk_depth,
    chunk_schedule,
    make_persistent_chunk_body,
    make_persistent_jacobi_kernel,
    persistent_kernel_supported,
    _deep_dir_phases,
)
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.exchange import shard_blocks, unshard_blocks
from stencil_tpu.plan.ir import (PERSISTENT_VARIANT, REMOTE_DMA, PlanChoice,
                                 PlanConfig, build_plan)


# -- plan IR -------------------------------------------------------------------


def test_persistent_plan_launch_prediction():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    pers = build_plan(spec, Dim3(2, 2, 2), REMOTE_DMA, persistent=True)
    plain = build_plan(spec, Dim3(2, 2, 2), REMOTE_DMA)
    composed = build_plan(spec, Dim3(2, 2, 2), "axis-composed")
    # 2 dispatches per CHUNK (deep exchange + chunk program) vs 2 per
    # STEP for the per-step remote-dma lowerings; the ppermute methods
    # compile the whole chunk into one XLA program
    assert pers.launches_per_chunk(4) == 2
    assert pers.launches_per_chunk(1) == 2
    assert plain.launches_per_chunk(4) == 8
    assert composed.launches_per_chunk(4) == 1
    # the deep exchange itself is the plain remote-dma slab schedule:
    # same per-exchange DMA and collective counts
    assert pers.collectives_per_exchange(2, 1) == 0
    assert pers.dmas_per_exchange(1, 1) == plain.dmas_per_exchange(1, 1)
    assert "persistent" in pers.describe()


def test_persistent_plan_validation_is_loud():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    with pytest.raises(ValueError, match="REMOTE_DMA"):
        build_plan(spec, Dim3(2, 2, 2), "axis-composed", persistent=True)
    with pytest.raises(ValueError, match="single-resident"):
        build_plan(spec, Dim3(2, 2, 1), REMOTE_DMA, persistent=True)
    with pytest.raises(ValueError, match="distinct kernel variants"):
        build_plan(spec, Dim3(2, 2, 2), REMOTE_DMA, fused=True,
                   persistent=True)


def test_persistent_ctor_validation_is_loud():
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    with pytest.raises(ValueError, match="REMOTE_DMA"):
        HaloExchange(spec, mesh, Method.AXIS_COMPOSED, persistent=True)
    with pytest.raises(ValueError, match="mutually exclusive"):
        HaloExchange(spec, mesh, Method.REMOTE_DMA, fused=True,
                     persistent=True)


def test_persistent_choice_searched_and_gated():
    from stencil_tpu.plan.cost import enumerate_candidates, score

    cfg = PlanConfig.make(Dim3(24, 24, 24), Radius.constant(1),
                          ["float32"], 8, "cpu")
    # the default variant set grows persistent once ks reaches depth 2
    cands = enumerate_candidates(cfg, ks=(1, 2))
    pers = [c for c in cands if c.is_persistent]
    assert pers and all(c.method == REMOTE_DMA for c in pers)
    # k = 1 points are emitted but fall out at score() (below); the
    # searchable ones carry the real chunk depth
    assert any(c.multistep_k >= 2 for c in pers)
    assert not any(c.is_persistent for c in enumerate_candidates(cfg))
    # k < 2 degenerates to the fused point: infeasible under this label
    assert score(cfg, PlanChoice(partition=(2, 2, 2), method=REMOTE_DMA,
                                 kernel_variant=PERSISTENT_VARIANT)) is None
    # non-REMOTE_DMA and oversubscribed partitions are infeasible
    assert score(cfg, PlanChoice(partition=(2, 2, 2), method="axis-composed",
                                 kernel_variant=PERSISTENT_VARIANT,
                                 multistep_k=2)) is None
    assert score(cfg, PlanChoice(partition=(2, 2, 4), method=REMOTE_DMA,
                                 kernel_variant=PERSISTENT_VARIANT,
                                 multistep_k=2)) is None


def test_persistent_fused_are_mutually_exclusive_choices():
    c = PlanChoice(partition=(2, 2, 2), method=REMOTE_DMA,
                   kernel_variant=PERSISTENT_VARIANT, multistep_k=2)
    assert c.is_persistent and not c.is_fused
    assert PlanChoice.from_json(c.to_json()).is_persistent


# -- depth feasibility: radius*k vs block interior -----------------------------


def test_deep_halo_exceeding_interior_is_refused_statically():
    from stencil_tpu.plan.cost import feasible

    # 16^3 / (1, 2, 4): z blocks are 4 cells; radius 2 at k = 2 realizes
    # a 4-cell halo — exactly feasible; k = 3 (6 cells) is not
    cfg = PlanConfig.make(Dim3(16, 16, 16), Radius.constant(2),
                          ["float32"], 8, "cpu")
    ok = PlanChoice(partition=(1, 2, 4), method=REMOTE_DMA,
                    kernel_variant=PERSISTENT_VARIANT, multistep_k=2)
    bad = PlanChoice(partition=(1, 2, 4), method=REMOTE_DMA,
                     kernel_variant=PERSISTENT_VARIANT, multistep_k=3)
    assert feasible(cfg, ok) is not None
    assert feasible(cfg, bad) is None


def test_check_chunk_depth_refuses_loudly():
    # radius shallower than the chunk depth: substep 0 would read past
    # the staged halo
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    with pytest.raises(ValueError, match="radius >= 3"):
        check_chunk_depth(spec, 3)
    check_chunk_depth(spec, 2)  # feasible: no raise
    # depth deeper than the block interior: the shrinking valid strip
    # would go negative even with the halo staged
    deep = GridSpec(Dim3(16, 16, 16), Dim3(1, 1, 4), Radius.constant(8))
    with pytest.raises(ValueError, match="interior"):
        check_chunk_depth(deep, 8)


def test_multistep_staging_planner_self_caps_never_overflows():
    from stencil_tpu.ops.pallas_stencil import plan_multistep_staging

    spec = GridSpec(Dim3(128, 128, 128), Dim3(1, 1, 8), Radius.constant(1))
    # a generous budget reaches the requested depth with full planes
    k, rows = plan_multistep_staging(spec, 4, budget=64 << 20)
    assert k == 4 and rows is None
    # a starved budget CAPS the depth rather than planning an overflow
    k_small, _rows = plan_multistep_staging(spec, 4, budget=1 << 18)
    assert k_small < 4


def test_chunk_schedule_and_launch_arithmetic():
    assert chunk_schedule(8, 2) == [2, 2, 2, 2]
    assert chunk_schedule(10, 4) == [4, 4, 2]
    assert chunk_schedule(0, 4) == []
    with pytest.raises(ValueError, match=">= 1"):
        chunk_schedule(8, 0)
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    assert persistent_kernel_supported(spec, Dim3(1, 1, 1))
    uneven = GridSpec(Dim3(17, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    assert not persistent_kernel_supported(uneven, Dim3(1, 1, 1))


# -- bit parity vs the composed baseline ---------------------------------------


def _run_jacobi(size, dim, k, iters, persistent):
    spec = GridSpec(Dim3(*size), Dim3(*dim), Radius.constant(k))
    mesh = grid_mesh(spec.dim, jax.devices()[: spec.dim.flatten()])
    if persistent:
        ex = HaloExchange(spec, mesh, Method.REMOTE_DMA, persistent=True)
        loop = make_jacobi_loop(ex, iters, temporal_k=k)
    else:
        ex = HaloExchange(spec, mesh, Method.AXIS_COMPOSED)
        loop = make_jacobi_loop(ex, iters)
    g = spec.global_size
    c = shard_blocks(np.full((g.z, g.y, g.x), INIT_TEMP, np.float32),
                     spec, mesh)
    n = jax.device_put(jnp.zeros_like(c), ex.sharding())
    sel = shard_blocks(sphere_sel((g.x, g.y, g.z)), spec, mesh)
    c, _ = loop(c, n, sel)
    return unshard_blocks(c, spec), getattr(ex, "last_launches_per_chunk", 0)


@pytest.mark.parametrize("name,size,dim,k,iters", [
    ("uniform-k2", (24, 24, 24), (2, 2, 2), 2, 8),
    ("uniform-k4-tail2", (24, 24, 24), (2, 2, 2), 4, 10),
    ("uneven-k2", (18, 20, 22), (1, 2, 4), 2, 6),
    ("uneven-k3-tail1", (18, 20, 22), (1, 2, 4), 3, 7),
])
def test_persistent_bit_parity_vs_composed(name, size, dim, k, iters):
    base, _ = _run_jacobi(size, dim, k, iters, persistent=False)
    pers, lpc = _run_jacobi(size, dim, k, iters, persistent=True)
    np.testing.assert_array_equal(base, pers, err_msg=name)
    # the measured launch census: 2 host dispatches per chunk (deep
    # exchange + chunk program), tail chunks included
    assert lpc == 2, name


# -- the interpret-mode mega-kernel --------------------------------------------


def _self_wrap(spec, arr):
    """The host-side replica of the kernel's deep exchange geometry on a
    single all-self-wrap block (same ``_deep_dir_phases`` records)."""
    out = arr.copy()
    for _d, src, dst, shape, _c in _deep_dir_phases(spec, Dim3(1, 1, 1)):
        s = tuple(slice(a, a + w) for a, w in zip(src, shape))
        d = tuple(slice(a, a + w) for a, w in zip(dst, shape))
        out[d] = arr[s]
    return out


@pytest.mark.parametrize("size,k", [
    ((16, 16, 14), 2),   # grown z extent % 3 != 0: ring wraps mid-window
    ((16, 16, 16), 3),
    ((16, 16, 13), 4),
])
def test_persistent_kernel_interpret_parity_vs_xla_chunk(size, k):
    import types

    gx, gy, gz = size
    spec = GridSpec(Dim3(gx, gy, gz), Dim3(1, 1, 1), Radius.constant(k))
    pz, py, px = spec.block_shape_zyx()
    rng = np.random.default_rng(0)
    curr = rng.standard_normal((pz, py, px)).astype(np.float32)
    sel = _self_wrap(spec, rng.integers(0, 3, size=(pz, py, px))
                     .astype(np.int32))
    nxt = np.zeros_like(curr)

    # baseline: host-exchanged halos + the XLA chunk body
    chunk = jax.jit(make_persistent_chunk_body(spec, k))
    fin, _ = chunk(jnp.asarray(_self_wrap(spec, curr)), jnp.asarray(nxt),
                   jnp.asarray(sel))

    plan = types.SimpleNamespace(mesh_dim=(1, 1, 1))
    kern = make_persistent_jacobi_kernel(spec, plan, k, interpret=True)
    c2, o2, _ = kern(jnp.asarray(curr), jnp.asarray(nxt), jnp.asarray(sel))
    got = np.asarray(o2 if k % 2 else c2)

    off, b = spec.compute_offset(), spec.base
    sl = (slice(off.z, off.z + b.z), slice(off.y, off.y + b.y),
          slice(off.x, off.x + b.x))
    np.testing.assert_array_equal(np.asarray(fin)[sl], got[sl])


def test_persistent_kernel_interpret_rejects_multi_device_form():
    import types

    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    plan = types.SimpleNamespace(mesh_dim=(2, 2, 2))
    with pytest.raises(ValueError, match="interpret"):
        make_persistent_jacobi_kernel(spec, plan, 2, interpret=True)


# -- the guarded loop (fault/recover) ------------------------------------------


def test_persistent_loop_through_guarded_rollback():
    """run_guarded drives the persistent chunk loop: a NaN injection
    rolls back to the newest clean snapshot and the recomputation lands
    bit-identical to a clean guarded run AND to the composed baseline."""
    from stencil_tpu.fault import (FaultPlan, HealthGuard, RecoveryPolicy,
                                   chunk_plan, parse_spec, run_guarded)

    size, dim, k, iters = (24, 24, 24), (2, 2, 2), 2, 8
    spec = GridSpec(Dim3(*size), Dim3(*dim), Radius.constant(k))
    mesh = grid_mesh(spec.dim, jax.devices()[:8])
    ex = HaloExchange(spec, mesh, Method.REMOTE_DMA, persistent=True)
    g = spec.global_size
    sel = shard_blocks(sphere_sel(size), spec, mesh)
    loops = {}

    def step_fn(st, n):
        loop = loops.get(n)
        if loop is None:
            loop = loops[n] = make_jacobi_loop(ex, n, temporal_k=k)
        nxt = jax.device_put(jnp.zeros_like(st["t"]), ex.sharding())
        c, _n = loop(st["t"], nxt, sel)
        return {"t": c}

    def start_state():
        return {"t": shard_blocks(
            np.full((g.z, g.y, g.x), INIT_TEMP, np.float32), spec, mesh)}

    snaps = {}

    def save(step, st):
        snaps[step] = np.asarray(st["t"]).copy()

    def restore():
        if not snaps:
            return None
        s = max(snaps)
        return s, {"t": jax.device_put(jnp.asarray(snaps[s]),
                                       ex.sharding())}

    clean, done = run_guarded(
        start_state(), start=0, iters=iters,
        plan_fn=lambda s: chunk_plan(s, iters, k, every=(k,)),
        step_fn=step_fn)
    assert done == iters

    plan = FaultPlan(parse_spec("nan@5"))
    state, done = run_guarded(
        start_state(), start=0, iters=iters,
        plan_fn=lambda s: chunk_plan(s, iters, k, every=(k, k),
                                     at=plan.steps()),
        step_fn=step_fn, guard=HealthGuard(every=k), injector=plan,
        policy=RecoveryPolicy(backoff_s=0.001),
        save_fn=save, ckpt_every=k, restore_fn=restore)
    assert done == iters
    np.testing.assert_array_equal(np.asarray(state["t"]),
                                  np.asarray(clean["t"]))
    for step, snap in snaps.items():
        assert np.isfinite(snap).all(), f"poisoned snapshot at {step}"

    base, _ = _run_jacobi(size, dim, k, iters, persistent=False)
    np.testing.assert_array_equal(
        base, unshard_blocks(jnp.asarray(clean["t"]), spec))


# -- conformance auditor + autotune round-trip ---------------------------------


def test_verify_plan_audits_persistent_lowering():
    from stencil_tpu.analysis import verify_plan as vp

    configs = vp.sweep_configs(size=16, radius=2, partitions=[(2, 2, 2)],
                               methods=[vp.PERSISTENT_METHOD_LABEL],
                               qsets=[("float32",)])
    res = vp.run_sweep(configs)
    assert res["checked"] == 1 and res["failed"] == 0
    checks = {c["name"]: c for c in res["verdicts"][0].checks}
    assert checks["census_bytes"]["actual"] == 0
    assert checks["dma_transfers"]["ok"]
    # the launch census is a conformance-audited PREDICTION: measured
    # dispatches per chunk == plan.launches_per_chunk(k) == 2
    assert checks["launches_per_chunk"]["predicted"] == 2
    assert checks["launches_per_chunk"]["ok"]
    res = vp.run_sweep(configs, perturb_dmas=1)
    assert res["failed"] == 1


def test_verify_plan_default_sweep_includes_persistent():
    from stencil_tpu.analysis import verify_plan as vp

    assert vp.PERSISTENT_METHOD_LABEL in {
        c["method"] for c in vp.sweep_configs()}


def test_autotune_persists_persistent_variant_entry(tmp_path):
    from stencil_tpu.plan import db as plandb
    from stencil_tpu.plan.autotune import autotune

    db_path = str(tmp_path / "plans.json")
    kwargs = dict(ndev=8, platform="cpu", db_path=db_path, probe=False,
                  methods=("remote-dma",), ks=(2,),
                  variants=(PERSISTENT_VARIANT,))
    res = autotune(Dim3(16, 16, 16), Radius.constant(1), ["float32"],
                   **kwargs)
    assert res.choice.is_persistent and res.choice.method == "remote-dma"
    assert res.choice.multistep_k == 2
    db = plandb.load_db(db_path)
    entry = plandb.lookup(db, res.config)
    assert PlanChoice.from_json(entry["choice"]).is_persistent
    res2 = autotune(Dim3(16, 16, 16), Radius.constant(1), ["float32"],
                    **kwargs)
    assert res2.cache_hit and res2.choice.is_persistent


def test_domain_realizes_tuned_persistent_plan():
    from stencil_tpu.api import DistributedDomain

    dd = DistributedDomain(16, 16, 16, plan={
        "partition": [2, 2, 2], "method": "remote-dma",
        "batch_quantities": True, "multistep_k": 2,
        "kernel_variant": "persistent",
    })
    dd.set_radius(2)  # radius * k as the tuned plan realizes it
    dd.set_devices(jax.devices()[:8])
    dd.add_data("t", "float32")
    dd.realize()
    assert dd.halo_exchange.persistent
    assert dd.plan_meta()["choice"]["kernel_variant"] == "persistent"


def test_jacobi3d_app_rejects_unknown_variant():
    from stencil_tpu.apps.jacobi3d import run

    with pytest.raises(ValueError, match="valid values"):
        run(8, 8, 8, iters=1, kernel_variant="bogus")
    with pytest.raises(ValueError, match="deep-halo"):
        run(8, 8, 8, iters=1, kernel_variant="persistent")


def test_astaroth_variant_checked_at_build_time(monkeypatch):
    from stencil_tpu.astaroth.integrate import _check_variant

    _check_variant(None)
    _check_variant("ring")
    with pytest.raises(ValueError, match="valid values"):
        _check_variant("bogus")
    monkeypatch.setenv("STENCIL_ASTAROTH_VARIANT", "rnig")
    with pytest.raises(ValueError, match="STENCIL_ASTAROTH_VARIANT"):
        _check_variant(None)
