"""The serving capacity engine (ISSUE 20).

Acceptance pins:

- **Weighted fairness is monotone and non-starving**: stride shares
  track weights (doubling a class's weight never lowers its served
  share), and a ``low`` job under sustained ``high`` load is served
  within the aging bound ``aging_s * (rank + 1)`` plus one slot —
  starvation is structurally impossible.
- **Cross-bucket packing** is deterministic, prefers the priced
  fuller/faster bucket within the entitled class, and the deadline-slack
  veto never manufactures an SLO miss it can see.
- **Elastic width** sizes slots on the power-of-two ladder, grows a
  running slot mid-flight against a same-bucket surge, and every
  (bucket, width) program compiles at most once.
- **Chunk-boundary preemption** parks a running lane-set for a queued
  ``high`` deadline job only when the priced gain exceeds the victims'
  resume cost (a veto is a first-class record), and every preempted
  tenant's final state is bit-identical to an undisturbed run.
- **Per-width pricing**: the admission pricer keeps (bucket, width)
  rows, answers most-specific-first, and writes both granularities back
  to the ledger.
"""

from __future__ import annotations

import json
import os

import pytest
import jax

from stencil_tpu.obs import ledger as ledger_mod
from stencil_tpu.obs import telemetry
from stencil_tpu.obs.telemetry import validate_record
from stencil_tpu.serve import (
    BucketPricer,
    FairnessPolicy,
    ServeJob,
    ServeQueue,
    ServeScheduler,
    WidthPolicy,
    pack_serve_slot,
)
from stencil_tpu.serve.admission import LEDGER_METRIC, bucket_label

N = 10
STEPS = 4


def job_doc(jid, *, size=N, steps=STEPS, tenant=None, priority="normal",
            deadline_ms=None, seed=None):
    doc = {"job": jid, "size": size, "steps": steps, "workload": "jacobi",
           "priority": priority, "dtype": "float32",
           "seed": seed if seed is not None else abs(hash(jid)) % 1000}
    if tenant:
        doc["tenant"] = tenant
    if deadline_ms is not None:
        doc["deadline_ms"] = deadline_ms
    return doc


def drop(serve_dir, doc):
    inc = os.path.join(serve_dir, "jobs", "incoming")
    os.makedirs(inc, exist_ok=True)
    name = f"{doc['job']}.json"
    tmp = os.path.join(inc, f".tmp-{name}")
    with open(tmp, "w") as f:
        f.write(json.dumps(doc))
    os.replace(tmp, os.path.join(inc, name))


def mk_job(tid, *, size=N, steps=STEPS, pri="normal", dl=None, seq=0,
           admit_t=None):
    return ServeJob(tid, (size, size, size), steps, "float32", seed=0,
                    deadline_ms=dl, owner=tid, priority=pri, seq=seq,
                    admit_t=admit_t)


def recs_of(path):
    recs = [json.loads(ln) for ln in open(path) if ln.strip()]
    bad = [validate_record(r) for r in recs]
    assert not any(bad), [b for b in bad if b]
    return recs


def seeded_ledger(path, prices):
    """A serve.step_p99_ms prior per bucket label (and per width when
    the key is a (label, width) tuple)."""
    entries = []
    for key, ms in prices.items():
        label, width = key if isinstance(key, tuple) else (key, None)
        det = {"bucket": label, "samples": 8}
        cfg = {"bucket": label}
        if width is not None:
            det["width"] = width
            cfg["width"] = width
        entries.append(ledger_mod.make_entry(
            LEDGER_METRIC, ms, label="seed", unit="ms", platform="cpu",
            source="serve", config=cfg, detail=det))
    ledger_mod.append_entries(path, entries)


# -- WidthPolicy (pure) -------------------------------------------------------


def test_width_ladder_and_choose():
    wp = WidthPolicy(2, 12)
    assert wp.widths == (2, 4, 8, 12)
    assert not wp.fixed
    assert wp.choose(1) == 2 and wp.choose(3) == 4
    assert wp.choose(9) == 12 and wp.choose(64) == 12

    fixed = WidthPolicy(4, 4)
    assert fixed.fixed and fixed.widths == (4,)
    assert fixed.choose(1) == 4 and fixed.choose(99) == 4

    with pytest.raises(ValueError):
        WidthPolicy(0, 4)
    with pytest.raises(ValueError):
        WidthPolicy(8, 4)


# -- FairnessPolicy (pure, fake clock) ----------------------------------------


def run_shares(w_low, slots=60, width=2):
    """Sustained two-class backlog in DISJOINT buckets; count jobs
    served per class over a fixed number of slots."""
    t = [0.0]
    fp = FairnessPolicy({"low": w_low}, aging_s=0.0, clock=lambda: t[0])
    wp = WidthPolicy(width, width)
    q = ServeQueue(policy=fp)
    seq = [0]

    def top_up():
        by_pri = {"high": 0, "low": 0}
        for j in q.jobs(t[0]):
            by_pri[j.priority] += 1
        for pri, size in (("high", 10), ("low", 12)):
            while by_pri[pri] < width:
                q.admit(mk_job(f"{pri}-{seq[0]}", size=size, pri=pri,
                               seq=seq[0], admit_t=t[0]))
                seq[0] += 1
                by_pri[pri] += 1

    for _ in range(slots):
        top_up()
        plan = pack_serve_slot(q, wp, fairness=fp, now=t[0])
        t[0] += 1.0
        assert plan is not None
    return dict(fp.served)


def test_fairness_weights_are_monotone():
    base = run_shares(1.0)
    doubled = run_shares(2.0)
    total_b = sum(base.values())
    total_d = sum(doubled.values())
    # doubling low's weight never lowers its served share (pinned), and
    # for a sustained backlog it strictly raises it
    assert doubled["low"] / total_d >= base["low"] / total_b
    assert doubled["low"] > base["low"]
    # shares track the weights: high:low ~ 8:1 at weight 1
    assert base["high"] > base["low"] * 4


def test_low_served_within_aging_bound_under_sustained_high():
    # rig the stride state so shares alone would starve low for ~250k
    # slots (a huge banked pass debt): the AGING override is the only
    # path to service, and it is the bound under test
    t = [0.0]
    fp = FairnessPolicy({"high": 10000.0, "low": 1.0}, aging_s=1.0,
                        clock=lambda: t[0])
    fp.charge("low", 50)  # pass debt: low never wins the stride pick
    wp = WidthPolicy(2, 2)
    q = ServeQueue(policy=fp)
    q.admit(mk_job("low-0", size=12, pri="low", seq=0, admit_t=0.0))
    seq = [1]
    served_at = None
    for _ in range(30):
        while sum(1 for j in q.jobs(t[0]) if j.priority == "high") < 2:
            q.admit(mk_job(f"h{seq[0]}", size=10, pri="high", seq=seq[0],
                           admit_t=t[0]))
            seq[0] += 1
        plan = pack_serve_slot(q, wp, fairness=fp, now=t[0])
        if any(j.tid == "low-0" for j in plan.picked):
            served_at = t[0]
            assert plan.reason == "aging-override"
            break
        t[0] += 1.0
    # the hard bound: aging_s * (rank + 1) = 1 * 3, plus one slot wall
    assert served_at is not None and served_at <= 4.0


def test_aging_promotes_queue_order():
    t = [0.0]
    fp = FairnessPolicy(aging_s=1.0, clock=lambda: t[0])
    q = ServeQueue(policy=fp)
    q.admit(mk_job("old-low", pri="low", seq=0, admit_t=0.0))
    t[0] = 1.5  # old-low has aged past one class
    q.admit(mk_job("new-normal", pri="normal", seq=1, admit_t=1.5))
    # low rank 2 aged by 1.5 -> 0.5 < normal rank 1: the old job leads
    assert [j.tid for j in q.jobs(t[0])] == ["old-low", "new-normal"]


def test_stride_reentry_cannot_bank_credit():
    fp = FairnessPolicy(clock=lambda: 0.0)
    fp.note_backlog(["high"])
    for _ in range(40):
        fp.charge("high")
    # low was absent the whole time; entering now it gets the floor of
    # the active passes, not an epoch of banked credit
    fp.note_backlog(["high", "low"])
    assert fp._pass["low"] >= fp._pass["high"]


# -- cross-bucket packing (pure) ----------------------------------------------


def priced(prices):
    p = BucketPricer()
    for bucket, per_s in prices.items():
        for _ in range(3):
            p.observe(bucket, per_s)
    return p


def test_packing_prefers_fuller_priced_bucket():
    b_small = ((10, 10, 10), "float32", "jacobi")
    b_big = ((12, 12, 12), "float32", "jacobi")
    pricer = priced({b_small: 0.001, b_big: 0.001})
    wp = WidthPolicy(4, 4)
    q = ServeQueue()
    # head of queue (lowest seq) is the lone b_small job, but b_big
    # holds four same-class jobs: packing fills a slot instead of
    # fragmenting
    q.admit(mk_job("lone", size=10, seq=0))
    for i in range(4):
        q.admit(mk_job(f"b{i}", size=12, seq=1 + i))
    plan = pack_serve_slot(q, wp, pricer=pricer)
    assert plan.bucket == b_big
    assert [j.tid for j in plan.picked] == ["b0", "b1", "b2", "b3"]
    assert plan.reason == "throughput"
    assert len(plan.candidates) == 2
    # deterministic: replay the same queue, same plan
    q2 = ServeQueue()
    q2.admit(mk_job("lone", size=10, seq=0))
    for i in range(4):
        q2.admit(mk_job(f"b{i}", size=12, seq=1 + i))
    plan2 = pack_serve_slot(q2, wp, pricer=pricer)
    assert (plan2.bucket, [j.tid for j in plan2.picked]) == (
        plan.bucket, [j.tid for j in plan.picked])


def test_packing_deadline_slack_veto():
    b_bulk = ((12, 12, 12), "float32", "jacobi")
    b_tight = ((10, 10, 10), "float32", "jacobi")
    pricer = priced({b_bulk: 0.001, b_tight: 0.001})
    wp = WidthPolicy(4, 4)
    q = ServeQueue()
    for i in range(4):
        q.admit(mk_job(f"bulk{i}", size=12, steps=10, seq=i))
    # per-step budget 1.1ms vs p99 ~1ms: feasible NOW, dead if it waits
    # out the bulk slot's ~10ms wall
    q.admit(mk_job("tight", size=10, steps=4, dl=1.1, seq=4))
    plan = pack_serve_slot(q, wp, pricer=pricer)
    assert plan.bucket == b_tight and plan.reason == "deadline-slack"
    # without the deadline the bulk bucket wins on throughput
    q2 = ServeQueue()
    for i in range(4):
        q2.admit(mk_job(f"bulk{i}", size=12, steps=10, seq=i))
    q2.admit(mk_job("tight", size=10, steps=4, seq=4))
    assert pack_serve_slot(q2, wp, pricer=pricer).bucket == b_bulk


# -- per-width pricing (pure) -------------------------------------------------


def test_pricer_per_width_rows_and_fallback(tmp_path):
    b = ((N, N, N), "float32", "jacobi")
    p = BucketPricer()
    for _ in range(3):
        p.observe(b, 0.002, width=4)
    for _ in range(3):
        p.observe(b, 0.016, width=16)
    ms4, src4 = p.price(b, width=4)
    ms16, src16 = p.price(b, width=16)
    assert ms4 == pytest.approx(2.0) and "B=4" in src4
    assert ms16 == pytest.approx(16.0) and "B=16" in src16
    # an unseen width falls back to the bucket aggregate, never None
    ms8, src8 = p.price(b, width=8)
    assert ms8 > 0 and "B=" not in src8
    # writeback carries BOTH granularities, width in detail
    entries = p.ledger_entries(platform="cpu", label="t")
    widths = sorted((e["detail"].get("width") or 0) for e in entries)
    assert widths == [0, 4, 16]

    lpath = str(tmp_path / "ledger.jsonl")
    ledger_mod.append_entries(lpath, entries)
    p2 = BucketPricer(lpath)
    assert p2.price(b, width=4)[0] == pytest.approx(ms4)
    assert p2.price(b, width=16)[0] == pytest.approx(ms16)
    assert p2.price(b)[0] > 0


# -- integration: the capacity engine end to end ------------------------------


def engine_kw(**over):
    kw = dict(devices=jax.devices()[:4], chunk=2, max_idle_s=0.3,
              poll_s=0.02, packing=True, fairness=True, preempt=True,
              aging_s=5.0)
    kw.update(over)
    return kw


class LateDropScheduler(ServeScheduler):
    """Drops extra job files at the FIRST chunk boundary — a producer
    writing while the slot is mid-flight."""

    def __init__(self, *a, late=(), **kw):
        super().__init__(*a, **kw)
        self._late = list(late)

    def _observe_chunk(self, bucket, per, done_now):
        while self._late:
            drop(self.serve_dir, self._late.pop())
        super()._observe_chunk(bucket, per, done_now)


def test_elastic_grow_mid_slot_and_zero_recompile(tmp_path):
    sdir = str(tmp_path / "s")
    lpath = str(tmp_path / "seed-ledger.jsonl")
    label = bucket_label(((N, N, N), "float32", "jacobi"))
    seeded_ledger(lpath, {label: 50.0})
    for i in range(2):
        drop(sdir, job_doc(f"e{i}", steps=8))
    late = [job_doc(f"late{i}", steps=8) for i in range(4)]
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        s = LateDropScheduler(
            sdir, 2, late=late, admission_ledger=lpath,
            **engine_kw(slot_min=2, slot_max=8, preempt=False))
        out = s.serve()
    finally:
        telemetry.get().close()
    assert out["retired"] == 6
    assert out["resizes"] >= 1
    recs = recs_of(m)
    grew = [r for r in recs if r["name"] == "serve.resized"
            and r["reason"] == "grow"]
    assert grew and grew[0]["from_width"] == 2
    assert grew[0]["to_width"] > grew[0]["from_width"]
    # the grow parked the running lanes revivably (capacity park, not
    # a drain: the daemon kept serving)
    parked = [r for r in recs if r["name"] == "serve.parked"
              and r.get("reason") == "resize"]
    assert parked
    assert out["outcome"] == "idle"
    # zero recompiles for cached widths: every (bucket, width, iters)
    # program built at most once
    built = s.cache.built_keys
    assert len(built) == len(set(built))
    widths = {json.loads(k).get("batch") for k in built} - {None}
    assert len(widths) >= 2  # the surge really did run a wider rung


def test_preemption_prices_gain_and_restores_bit_identical(tmp_path):
    small = bucket_label(((N, N, N), "float32", "jacobi"))
    big = bucket_label(((14, 14, 14), "float32", "jacobi"))
    jobs = [job_doc(f"low{i}", size=14, steps=10, priority="low",
                    seed=60 + i) for i in range(2)]
    hi = job_doc("rush", size=N, steps=2, priority="high", deadline_ms=9.0,
                 seed=99)

    # undisturbed reference: same jobs, no preemption
    ref_dir = str(tmp_path / "ref")
    for d in jobs + [hi]:
        drop(ref_dir, d)
    ref = ServeScheduler(ref_dir, 2, **engine_kw(preempt=False)).serve()
    assert ref["retired"] == 3

    lpath = str(tmp_path / "seed-ledger.jsonl")
    # victims price high (long remaining wall), the high job cheap: the
    # priced gain clears the resume cost and preemption fires
    seeded_ledger(lpath, {big: 100.0, small: 1.0})
    sdir = str(tmp_path / "s")
    for d in jobs:
        drop(sdir, d)
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        out = LateDropScheduler(
            sdir, 2, late=[hi], admission_ledger=lpath,
            **engine_kw(preempt_cost_chunks=0.05)).serve()
    finally:
        telemetry.get().close()
    assert out["retired"] == 3
    assert out["preemptions"] == 1
    recs = recs_of(m)
    pre = [r for r in recs if r["name"] == "serve.preempted"]
    assert len(pre) == 1 and pre[0]["job"] == "rush"
    assert pre[0]["gain_ms"] > pre[0]["resume_cost_ms"]
    assert sorted(pre[0]["victims"]) == ["low0", "low1"]
    parked = [r for r in recs if r["name"] == "serve.parked"
              and r.get("reason") == "preempt"]
    assert len(parked) == 2 and all(0 < r["step"] < 10 for r in parked)
    # every preempted-then-revived tenant ends bit-identical to the
    # undisturbed run (the park/revive ckpt contract, priced or not)
    for jid in ("low0", "low1", "rush"):
        a, b = out["results"][jid], ref["results"][jid]
        assert a.outcome == b.outcome == "done"
        assert a.final.tobytes() == b.final.tobytes(), jid


def test_preemption_vetoed_when_gain_below_resume_cost(tmp_path):
    small = bucket_label(((N, N, N), "float32", "jacobi"))
    big = bucket_label(((14, 14, 14), "float32", "jacobi"))
    lpath = str(tmp_path / "seed-ledger.jsonl")
    seeded_ledger(lpath, {big: 100.0, small: 1.0})
    sdir = str(tmp_path / "s")
    for i in range(2):
        drop(sdir, job_doc(f"low{i}", size=14, steps=10, priority="low",
                           seed=70 + i))
    hi = job_doc("rush", size=N, steps=2, priority="high", deadline_ms=9.0)
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")
    try:
        out = LateDropScheduler(
            sdir, 2, late=[hi], admission_ledger=lpath,
            **engine_kw(preempt_cost_chunks=1e6)).serve()
    finally:
        telemetry.get().close()
    # the priced gain can never clear an absurd resume cost: vetoed,
    # recorded, and nothing was parked
    assert out["preemptions"] == 0 and out["retired"] == 3
    recs = recs_of(m)
    veto = [r for r in recs if r["name"] == "serve.preempt_veto"]
    assert veto and veto[0]["job"] == "rush"
    assert veto[0]["gain_ms"] <= veto[0]["resume_cost_ms"]
    assert not any(r["name"] == "serve.preempted" for r in recs)


def test_sustained_high_load_does_not_starve_low(tmp_path):
    sdir = str(tmp_path / "s")
    drop(sdir, job_doc("patient", size=12, steps=2, priority="low"))
    for i in range(2):
        drop(sdir, job_doc(f"h-pre{i}", size=N, steps=2, priority="high",
                           seed=90 + i))
    # a stream of high jobs in a DIFFERENT bucket keeps arriving at
    # every chunk boundary; stride shares + aging still serve the low
    # job before the stream runs dry
    late = [job_doc(f"h{i}", size=N, steps=2, priority="high", seed=i)
            for i in range(4)]
    m = tmp_path / "m.jsonl"
    telemetry.configure(metrics_out=str(m), app="t")

    class Streaming(ServeScheduler):
        def _observe_chunk(self, bucket, per, done_now):
            if late:
                drop(self.serve_dir, late.pop())
            super()._observe_chunk(bucket, per, done_now)

    try:
        out = Streaming(sdir, 2,
                        **engine_kw(aging_s=0.05, preempt=False)).serve()
    finally:
        telemetry.get().close()
    assert out["retired"] == 7
    assert out["results"]["patient"].outcome == "done"
    recs = recs_of(m)
    retire_order = [r["job"] for r in recs if r["name"] == "serve.retired"]
    # the low job did not trail the whole high stream
    assert retire_order.index("patient") < len(retire_order) - 1
    assert out["fairness"]["served"]["low"] >= 1


# -- report: the priority split -----------------------------------------------


def test_report_splits_serve_gauges_on_priority():
    from stencil_tpu.apps.report import _agg_key

    hi = {"name": "serve.p99_ms", "priority": "high"}
    lo = {"name": "serve.p99_ms", "priority": "low"}
    plain = {"name": "serve.p99_ms"}
    assert _agg_key(hi) == "serve.p99_ms[high]"
    assert _agg_key(lo) == "serve.p99_ms[low]"
    assert _agg_key(plain) == "serve.p99_ms"
    assert len({_agg_key(hi), _agg_key(lo), _agg_key(plain)}) == 3


# -- loadgen: --mix / --burst stay seeded and deterministic -------------------


def test_loadgen_mix_and_burst_helpers():
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "serve_loadgen", os.path.join(root, "scripts", "serve_loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)

    mix = lg.parse_mix("12,16x8x8/float64,10/float32/jacobi")
    assert mix == [([12, 12, 12], "float32", "jacobi"),
                   ([16, 8, 8], "float64", "jacobi"),
                   ([10, 10, 10], "float32", "jacobi")]
    with pytest.raises(ValueError):
        lg.parse_mix("12/float16")
    with pytest.raises(ValueError):
        lg.parse_mix("")

    gaps = [0.3, 0.3, 0.3, 0.3, 0.3, 0.3]
    shaped = lg.burst_gaps(gaps, 0.5, 1.0)
    assert shaped == lg.burst_gaps(gaps, 0.5, 1.0)  # deterministic
    # every arrival lands inside an ON window of the 1.5s duty cycle
    t = 0.0
    for g in shaped:
        assert g >= 0
        t += g
        assert t % 1.5 < 0.5 + 1e-9, t
    # arrivals never reorder and never move earlier
    orig = []
    acc = 0.0
    for g in gaps:
        acc += g
        orig.append(acc)
    acc = 0.0
    for g, o in zip(shaped, orig):
        acc += g
        assert acc >= o - 1e-9
