"""Unit tests for the Mosaic-dump parser's region tracking (fast tier).

The byte-level traffic assertions live in test_traffic_accounting.py (slow,
subprocess jax.export); these pin the pure-text parsing rules the whole
accounting rests on: string-literal braces must not skew region depth, and
a drifted stack must refuse instead of silently mis-attributing DMAs
(ADVICE r5 #1)."""

import pytest

from stencil_tpu.utils import mosaic_traffic as mt

_DMA_LINE = (
    '      tpu.enqueue_dma source(%0 : memref<2x8x128xf32, '
    "#tpu.memory_space<any>>) target(%1 : memref<2x8x128xf32, "
    "#tpu.memory_space<vmem>>) target_semaphore(%2)"
)


def _dump(body: str) -> str:
    return mt._MARKER + "/tmp/foo.py:12:\n" + body


def test_string_literal_braces_do_not_skew_depth():
    # the sym_name attr contains an unbalanced '{' inside a string literal;
    # the DMA after it is at top level, NOT inside a region
    body = "\n".join(
        [
            "module @kernel {",
            '  func.func @main() attributes {sym_name = "weird{name"} {',
            _DMA_LINE,
            "  }",
            "}",
        ]
    )
    (k,) = mt.parse_mosaic_dumps(_dump(body))
    assert len(k.dmas) == 1
    assert k.dmas[0].if_depth == 0 and k.dmas[0].loop_depth == 0


def test_scf_if_attribution_still_counts():
    body = "\n".join(
        [
            "module @kernel {",
            "  scf.if %cond {",
            _DMA_LINE,
            "  }",
            _DMA_LINE,
            "}",
        ]
    )
    (k,) = mt.parse_mosaic_dumps(_dump(body))
    assert [d.if_depth for d in k.dmas] == [1, 0]


def test_trailing_text_after_module_close_is_ignored():
    body = "\n".join(
        [
            "module @kernel {",
            _DMA_LINE,
            "}",
            "some later debug output with a stray { brace",
        ]
    )
    (k,) = mt.parse_mosaic_dumps(_dump(body))
    assert len(k.dmas) == 1


_DMA_GENERIC_LINE = (
    '      "tpu.enqueue_dma"(%129, %130, %132) <{operandSegmentSizes = '
    "array<i32: 1, 0, 1, 1, 0, 0>}> : (memref<1x144x384xf32, "
    "#tpu.memory_space<any>>, memref<1x144x384xf32, "
    "#tpu.memory_space<vmem>>, memref<!tpu.dma_semaphore, "
    "#tpu.memory_space<semaphore_mem>>) -> ()"
)


def test_generic_form_dma_parses():
    # older Mosaic prints ops in generic MLIR form; direction and extents
    # come from the trailing type signature (source first, target second)
    body = "\n".join(["module @kernel {", _DMA_GENERIC_LINE, "}"])
    (k,) = mt.parse_mosaic_dumps(mt._MARKER + "/tmp/foo.py:12:\n" + body)
    (d,) = k.dmas
    assert d.is_input and d.shape == (1, 144, 384) and d.nbytes == 221184


def test_unbalanced_module_raises():
    body = "\n".join(["module @kernel {", "  scf.if %cond {", _DMA_LINE])
    with pytest.raises(ValueError, match="unbalanced"):
        mt.parse_mosaic_dumps(_dump(body))


def test_overclosed_module_raises():
    # two closes on one line against a depth-1 stack: refuse loudly
    body = "\n".join(["module @kernel {", "} }"])
    with pytest.raises(ValueError, match="closes against"):
        mt.parse_mosaic_dumps(_dump(body))


def test_capture_traffic_rejects_reentry(monkeypatch):
    monkeypatch.setattr(mt, "_capture_active", True)
    with pytest.raises(RuntimeError, match="not reentrant"):
        mt.capture_traffic(lambda: (None, ()))
