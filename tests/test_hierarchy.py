"""Hierarchical ICI+DCN halo exchange (ISSUE 17 / ROADMAP #3).

The claims under test, all on the in-process virtual-host fabric
(``STENCIL_VIRTUAL_HOSTS`` — id-sorted contiguous device groups, set
per-test via monkeypatch, no env-dependent skips):

- **bit parity**: the two-level lowering (cross-host DCN-axis slabs as
  host-orchestrated carrier copies started before the inner per-host
  programs) is bit-identical to the flat plan on uniform, uneven, and
  oversubscribed partitions, fp32/fp64/mixed dicts, bf16 wire, batch
  off, through axis-composed / remote-dma / fused inner transports,
  and through the full jacobi step loop.
- **census pins unchanged**: the hierarchical census's
  collective-permute (count, bytes) equals the flat plan's, and the
  DCN level contributes zero collectives of any kind.
- **predicted == executed**: ``DcnPhaseIR``'s
  ``dcn_transfers_per_exchange`` / ``dcn_wire_bytes`` match the
  executed ``last_transfer_count`` / ``last_transfer_bytes`` exactly.
- **alignment is validated**: a split whose segments interleave across
  hosts (an x split under identity device order) raises, and the
  composed two-level placement ordering fixes it.
- **the auditor audits**: ``analysis/verify_plan.run_hierarchy_sweep``
  passes clean and trips on a perturbed DCN prediction.
- **ckpt topology delta**: manifests record the host->blocks map; a
  restore under a different host fabric warns, a pre-hierarchy
  snapshot stays quiet.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stencil_tpu.domain.grid import GridSpec
from stencil_tpu.geometry import Dim3, Radius
from stencil_tpu.parallel import HaloExchange, Method, grid_mesh
from stencil_tpu.parallel.device_topo import host_assignment, virtual_hosts
from stencil_tpu.parallel.exchange import shard_blocks

VH = "STENCIL_VIRTUAL_HOSTS"


def _state(spec, mesh, nq=1, dtypes=None):
    g = spec.global_size
    base = (
        np.arange(g.z)[:, None, None] * 1_000_000.0
        + np.arange(g.y)[None, :, None] * 1_000.0
        + np.arange(g.x)[None, None, :]
    )
    return {
        i: shard_blocks(
            (base + i).astype(dtypes[i] if dtypes else np.float32),
            spec, mesh)
        for i in range(nq)
    }


def _gather(state):
    return np.stack(
        [np.asarray(jax.device_get(state[i]), dtype=np.float64)
         for i in sorted(state)]
    )


def _pair(spec, mesh_dim, ndev, hierarchy, method=Method.AXIS_COMPOSED,
          **kw):
    """(flat exchange, hierarchical exchange) on the same device list."""
    devs = jax.devices()[:ndev]
    flat = HaloExchange(spec, grid_mesh(mesh_dim, devs), method, **kw)
    hier = HaloExchange(spec, grid_mesh(mesh_dim, devs), method,
                        hierarchy=hierarchy, **kw)
    return flat, hier


# -- fabric ---------------------------------------------------------------


def test_virtual_hosts_env_partitions_devices(monkeypatch):
    monkeypatch.delenv(VH, raising=False)
    assert virtual_hosts() == 0
    devs = jax.devices()[:8]
    assert set(host_assignment(devs)) == {0}
    monkeypatch.setenv(VH, "2")
    assert virtual_hosts() == 2
    assign = host_assignment(devs)
    assert assign == sorted(assign) and set(assign) == {0, 1}
    assert assign.count(0) == assign.count(1) == 4


# -- bit parity: one exchange ---------------------------------------------


CASES = [
    # (global, partition, mesh_dim, ndev, hierarchy)
    ((16, 16, 16), (2, 2, 2), (2, 2, 2), 8, ("z", 2)),      # uniform
    ((14, 18, 20), (1, 2, 4), (1, 2, 4), 8, ("z", 2)),      # uneven, z4/h2
    ((16, 16, 16), (1, 2, 4), (1, 2, 4), 8, ("z", 4)),      # 4 hosts
    ((12, 12, 16), (2, 2, 4), (1, 2, 2), 4, ("z", 2)),      # oversubscribed
]


@pytest.mark.parametrize("g,part,mdim,ndev,hier", CASES)
@pytest.mark.parametrize("method,kw", [
    (Method.AXIS_COMPOSED, {}),
    (Method.REMOTE_DMA, {}),
    (Method.REMOTE_DMA, {"fused": True}),
])
def test_hierarchical_bit_identical_to_flat(monkeypatch, g, part, mdim,
                                            ndev, hier, method, kw):
    monkeypatch.setenv(VH, str(hier[1]))
    spec = GridSpec(Dim3(*g), Dim3(*part), Radius.constant(2))
    if (method, tuple(kw)) != (Method.AXIS_COMPOSED, ()) and mdim != part:
        pytest.skip("remote-dma/fused emulations are single-resident")
    flat, hx = _pair(spec, Dim3(*mdim), ndev, hier, method, **kw)
    state = _state(spec, flat.mesh, nq=2)
    np.testing.assert_array_equal(
        _gather(flat(jax.tree.map(jnp.copy, state))),
        _gather(hx(jax.tree.map(jnp.copy, state))))


@pytest.mark.parametrize("dtypes", [
    [np.float64, np.float64],
    [np.float32, np.float64, np.float32],   # mixed: two dtype groups
])
def test_hierarchical_parity_fp64_and_mixed(monkeypatch, dtypes):
    monkeypatch.setenv(VH, "2")
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    flat, hx = _pair(spec, Dim3(2, 2, 2), 8, ("z", 2))
    state = _state(spec, flat.mesh, nq=len(dtypes), dtypes=dtypes)
    a = flat(jax.tree.map(jnp.copy, state))
    b = hx(jax.tree.map(jnp.copy, state))
    for i in state:
        ga, gb = jax.device_get(a[i]), jax.device_get(b[i])
        assert ga.dtype == gb.dtype == dtypes[i]
        np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))


@pytest.mark.parametrize("kw", [
    {"wire_dtype": "bfloat16"},
    {"batch_quantities": False},
])
def test_hierarchical_parity_wire_and_batch_knobs(monkeypatch, kw):
    monkeypatch.setenv(VH, "2")
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    flat, hx = _pair(spec, Dim3(2, 2, 2), 8, ("z", 2), **kw)
    state = _state(spec, flat.mesh, nq=2)
    np.testing.assert_array_equal(
        _gather(flat(jax.tree.map(jnp.copy, state))),
        _gather(hx(jax.tree.map(jnp.copy, state))))


def test_hierarchical_step_loop_parity(monkeypatch):
    """5 jacobi iterations land bit-identical to the flat plan (the DCN
    exchange inside the compute loop, overlap path included)."""
    from stencil_tpu.ops.jacobi import make_jacobi_loop, sphere_masks

    monkeypatch.setenv(VH, "2")
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(2))
    g = spec.global_size
    rng = np.random.default_rng(0)
    curr = rng.standard_normal((g.z, g.y, g.x)).astype(np.float32)
    hot, cold = sphere_masks(g)
    sel = np.zeros((g.z, g.y, g.x), np.float32)
    sel[hot] = 1
    sel[cold] = 2

    outs = {}
    for tag, hier in (("flat", None), ("hier", ("z", 2))):
        mesh = grid_mesh(spec.dim, jax.devices()[:8])
        ex = HaloExchange(spec, mesh, hierarchy=hier)
        loop = make_jacobi_loop(ex, 5)
        out, _ = loop(shard_blocks(curr, spec, mesh),
                      shard_blocks(np.zeros_like(curr), spec, mesh),
                      shard_blocks(sel, spec, mesh))
        outs[tag] = np.asarray(jax.device_get(out))
    np.testing.assert_array_equal(outs["flat"], outs["hier"])


# -- census + counters ----------------------------------------------------


def test_inner_census_pins_unchanged_and_dcn_collective_free(monkeypatch):
    monkeypatch.setenv(VH, "2")
    spec = GridSpec(Dim3(16, 16, 16), Dim3(1, 2, 4), Radius.constant(2))
    flat, hx = _pair(spec, Dim3(1, 2, 4), 8, ("z", 2))
    state = _state(spec, flat.mesh, nq=2)
    cf = flat.collective_census(state)
    ch = hx.collective_census(state)
    assert ch.get("collective-permute") == cf.get("collective-permute")
    stray = {k: v for k, v in ch.items()
             if k != "collective-permute" and v[0]}
    assert stray == {}, stray


def test_predicted_dcn_transfers_and_bytes_match_executed(monkeypatch):
    monkeypatch.setenv(VH, "2")
    spec = GridSpec(Dim3(16, 16, 16), Dim3(1, 2, 4), Radius.constant(2))
    _, hx = _pair(spec, Dim3(1, 2, 4), 8, ("z", 2))
    dtypes = [np.float32, np.float64]
    state = _state(spec, hx.mesh, nq=2, dtypes=dtypes)
    hx(jax.tree.map(jnp.copy, state))
    plan = hx.plan
    ngroups = 2  # two dtype groups
    assert plan.dcn_transfers_per_exchange(2, ngroups) > 0
    assert (hx._compiled.last_transfer_count
            == plan.dcn_transfers_per_exchange(2, ngroups))
    assert (hx._compiled.last_transfer_bytes
            == plan.dcn_wire_bytes([4, 8], floating=[True, True]))


def test_dcn_counters_reset_per_exchange(monkeypatch):
    monkeypatch.setenv(VH, "2")
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    _, hx = _pair(spec, Dim3(2, 2, 2), 8, ("z", 2))
    state = _state(spec, hx.mesh)
    out = hx(jax.tree.map(jnp.copy, state))
    c, b = hx._compiled.last_transfer_count, hx._compiled.last_transfer_bytes
    hx(out)
    assert (hx._compiled.last_transfer_count,
            hx._compiled.last_transfer_bytes) == (c, b)


# -- alignment ------------------------------------------------------------


def test_misaligned_split_raises_and_composed_order_fixes_it(monkeypatch):
    """An x split under identity device order interleaves its segments
    across the id-sorted contiguous hosts -> loud ValueError naming the
    fabric; reordering the device list so each segment lives on one
    host (what realize() does with the two-level placement) builds and
    stays bit-identical to flat."""
    monkeypatch.setenv(VH, "2")
    spec = GridSpec(Dim3(16, 16, 16), Dim3(2, 2, 2), Radius.constant(1))
    devs = jax.devices()[:8]
    bad = HaloExchange(spec, grid_mesh(spec.dim, devs), hierarchy=("x", 2))
    state = _state(spec, bad.mesh, nq=1)
    with pytest.raises(ValueError, match="do not align"):
        bad(state)  # the two-level lowering validates at first build

    # mesh flat order is (z, y, x) with x fastest: put host-0 devices on
    # every x=0 slot and host-1 devices on every x=1 slot
    order = [devs[i // 2] if i % 2 == 0 else devs[4 + i // 2]
             for i in range(8)]
    mesh = grid_mesh(spec.dim, order, ordered=True)
    hx = HaloExchange(spec, mesh, hierarchy=("x", 2))
    flat = HaloExchange(spec, mesh)
    state = _state(spec, mesh, nq=1)
    np.testing.assert_array_equal(
        _gather(flat(jax.tree.map(jnp.copy, state))),
        _gather(hx(jax.tree.map(jnp.copy, state))))


def test_hierarchy_validation_rejects_bad_split():
    from stencil_tpu.plan.ir import validate_hierarchy

    assert validate_hierarchy(("z", 2), Dim3(2, 2, 2)) is None
    assert validate_hierarchy(("z", 3), Dim3(2, 2, 2)) is not None
    assert validate_hierarchy(("q", 2), Dim3(2, 2, 2)) is not None


# -- the auditor ----------------------------------------------------------


def test_verify_plan_hierarchy_sweep_clean_and_perturb_trips(monkeypatch):
    from stencil_tpu.analysis import verify_plan as vp

    monkeypatch.delenv(VH, raising=False)
    cfgs = vp.hierarchy_sweep_configs(
        size=16, radius=2, partitions=[(1, 2, 4)],
        methods=["axis-composed"], qsets=[("float32", "float64")])
    res = vp.run_hierarchy_sweep(
        hosts=2, size=16, radius=2, partitions=[(1, 2, 4)],
        methods=["axis-composed"], qsets=[("float32", "float64")])
    assert res["checked"] == len(cfgs) >= 1
    assert res["failed"] == 0, [v.to_json() for v in res["verdicts"]]
    names = {c["name"] for v in res["verdicts"] for c in v.checks}
    assert {"dcn_transfers", "dcn_wire_bytes", "inner_census_pin",
            "bit_identical_to_flat"} <= names
    # the sweep owns the env flip and restores it
    assert VH not in os.environ

    res = vp.run_hierarchy_sweep(
        hosts=2, size=16, radius=2, partitions=[(1, 2, 4)],
        methods=["axis-composed"], qsets=[("float32", "float64")],
        perturb_dcn=1)
    assert res["failed"] == res["checked"] >= 1


# -- ckpt host-topology delta ---------------------------------------------


def _realized_dd(monkeypatch, hosts):
    from stencil_tpu.api import DistributedDomain

    if hosts:
        monkeypatch.setenv(VH, str(hosts))
    else:
        monkeypatch.delenv(VH, raising=False)
    dd = DistributedDomain(16, 16, 16)
    dd.set_radius(1)
    dd.set_devices(jax.devices()[:8])
    dd.add_data("q", "float32")
    dd.realize()
    return dd


def test_manifest_records_host_blocks(monkeypatch):
    dd = _realized_dd(monkeypatch, 2)
    hosts = dd.plan_meta()["host_blocks"]
    assert len(hosts) == 8 and set(hosts) == {0, 1}


def test_ckpt_warns_on_host_topology_delta(monkeypatch, capfd):
    dd = _realized_dd(monkeypatch, 2)
    manifest = {"meta": {"plan": dd.plan_meta()}}
    other = _realized_dd(monkeypatch, 4)
    capfd.readouterr()
    other._warn_plan_mismatch(manifest)
    err = capfd.readouterr().err
    assert "host fabric" in err


def test_ckpt_quiet_on_same_fabric_and_pre_hierarchy_snapshot(monkeypatch,
                                                              capfd):
    dd = _realized_dd(monkeypatch, 2)
    manifest = {"meta": {"plan": dd.plan_meta()}}
    capfd.readouterr()
    dd._warn_plan_mismatch(manifest)
    assert capfd.readouterr().err == ""

    # a pre-hierarchy snapshot (no host_blocks / hierarchy keys at all)
    # must not warn against a flat single-host run
    old = _realized_dd(monkeypatch, 0)
    manifest = {"meta": {"plan": old.plan_meta()}}
    for k in ("host_blocks",):
        del manifest["meta"]["plan"][k]
    for k in ("hierarchy", "host_placement"):
        del manifest["meta"]["plan"]["choice"][k]
    capfd.readouterr()
    old._warn_plan_mismatch(manifest)
    assert capfd.readouterr().err == ""
