"""GridSpec — the static layout of a partitioned, halo-padded 3D grid.

Bundles what the reference scatters across ``DistributedDomain``/
``Placement``/``LocalDomain`` geometry state (reference:
include/stencil/stencil.hpp:33-122, include/stencil/partition.hpp:264-289):
the global extent, the partition grid, per-block logical sizes/origins
(uneven splits follow the reference's remainder rule, partition.hpp:55-86),
the per-direction radius, and the padded block shape.

Because the partition is a tensor product (each axis is split
independently), per-block sizes factor into three per-axis size lists —
this is what makes uneven blocks exchangeable with axis-aligned collective
permutes: blocks in the same ring share the orthogonal-axis sizes.

Array layout convention: JAX arrays are indexed ``[z, y, x]``; all blocks
are padded to the *base* (largest) logical size plus both face radii, and
smaller blocks keep their data at the same compute offset with a dead tail
(the pad-and-mask strategy, SURVEY.md §7 step 4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple

from ..geometry import Dim3, Radius, Rect3, halo_rect


def _axis_sizes(total: int, n: int, base: int) -> Tuple[int, ...]:
    """Per-index sizes along one axis under the reference remainder rule
    (partition.hpp:55-70): trailing indices lose one point."""
    rem = total % n
    # base = ceil(total / n) when rem != 0, else total / n
    return tuple(base - (1 if (rem != 0 and i >= rem) else 0) for i in range(n))


# TPU tiling alignment for the block's minor dims: sublanes (y) and lanes
# (x). Slab DMAs in Pallas kernels require these; the pad tail beyond
# raw_size is dead cells, exactly like the uneven-partition tail.
ALIGN_Y = 8
ALIGN_X = 128


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


@dataclass(frozen=True)
class GridSpec:
    global_size: Dim3
    dim: Dim3  # number of blocks along x, y, z
    radius: Radius
    aligned: bool = True  # pad block planes to (ALIGN_Y, ALIGN_X) multiples
    base: Dim3 = field(init=False)  # largest block size
    sizes_x: Tuple[int, ...] = field(init=False)
    sizes_y: Tuple[int, ...] = field(init=False)
    sizes_z: Tuple[int, ...] = field(init=False)

    def __post_init__(self):
        g, d = self.global_size, self.dim
        if not (d.x >= 1 and d.y >= 1 and d.z >= 1):
            raise ValueError(f"partition {d} needs >= 1 block per axis")
        if not (g.x >= d.x and g.y >= d.y and g.z >= d.z):
            raise ValueError(f"global {g} too small for partition {d}")
        base = Dim3(-(-g.x // d.x), -(-g.y // d.y), -(-g.z // d.z))
        object.__setattr__(self, "base", base)
        object.__setattr__(self, "sizes_x", _axis_sizes(g.x, d.x, base.x))
        object.__setattr__(self, "sizes_y", _axis_sizes(g.y, d.y, base.y))
        object.__setattr__(self, "sizes_z", _axis_sizes(g.z, d.z, base.z))

    # -- factories ----------------------------------------------------------
    @classmethod
    def from_partition(cls, global_size, part, radius: Radius) -> "GridSpec":
        """From a RankPartition/NodePartition (same remainder semantics)."""
        return cls(Dim3.of(global_size), part.dim(), radius)

    # -- per-block queries ---------------------------------------------------
    def block_size(self, idx) -> Dim3:
        i = Dim3.of(idx)
        return Dim3(self.sizes_x[i.x], self.sizes_y[i.y], self.sizes_z[i.z])

    def block_origin(self, idx) -> Dim3:
        i = Dim3.of(idx)
        return Dim3(
            sum(self.sizes_x[: i.x]),
            sum(self.sizes_y[: i.y]),
            sum(self.sizes_z[: i.z]),
        )

    def is_uniform(self) -> bool:
        return self.base * self.dim == self.global_size

    # -- shapes --------------------------------------------------------------
    def padded(self) -> Dim3:
        """Per-block allocation extent (x, y, z); when ``aligned``, the y/x
        plane dims are rounded up to TPU tile multiples (dead tail) and the
        compute region starts at an 8-aligned y row (see compute_offset)."""
        off = self.compute_offset()
        r = self.radius
        p = Dim3(off.x + self.base.x + r.x(1), off.y + self.base.y + r.y(1),
                 off.z + self.base.z + r.z(1))
        if not self.aligned:
            return p
        return Dim3(_round_up(p.x, ALIGN_X), _round_up(p.y, ALIGN_Y), p.z)

    def block_shape_zyx(self) -> Tuple[int, int, int]:
        p = self.padded()
        return (p.z, p.y, p.x)

    def stacked_shape_zyx(self) -> Tuple[int, int, int, int, int, int]:
        """Shape of the stacked-blocks array: (bz, by, bx, pz, py, px)."""
        p = self.padded()
        return (self.dim.z, self.dim.y, self.dim.x, p.z, p.y, p.x)

    def num_blocks(self) -> int:
        return self.dim.flatten()

    def compute_offset(self) -> Dim3:
        """Allocation-local origin of the compute region.

        In ``aligned`` layouts the y (sublane) offset is rounded up to the
        8-row tile so that HBM/VMEM DMA slices of row-tiled slabs start on
        tile boundaries (Mosaic requires tile-aligned slice offsets in the
        minor-two dims; z is untiled and x slabs span full rows). The rows
        between the y halo and the compute region are dead pad."""
        r = self.radius
        yo = r.y(-1)
        if self.aligned and yo > 0:
            yo = _round_up(yo, ALIGN_Y)
        return Dim3(r.x(-1), yo, r.z(-1))

    def halo_rect(self, direction, size=None, halo: bool = True) -> Rect3:
        """Allocation-local halo (or owned boundary) rect in *this* layout:
        the radius-origin geometry rect (geometry.halo_rect) translated by
        the aligned layout's extra compute offset."""
        r = self.radius
        sz = self.base if size is None else Dim3.of(size)
        shift = self.compute_offset() - Dim3(r.x(-1), r.y(-1), r.z(-1))
        rect = halo_rect(direction, sz, r, halo)
        return Rect3(rect.lo + shift, rect.hi + shift)
