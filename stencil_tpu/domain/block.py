"""LocalBlock — one subdomain's quantities as halo-padded JAX arrays.

TPU-native re-design of the reference's ``LocalDomain``
(reference: include/stencil/local_domain.cuh:34-276, src/local_domain.cu).
The reference cudaMallocs a pitched curr/next allocation per quantity and
does byte-offset pointer math; here each quantity is a dense ``jnp`` array of
shape ``raw_size = size + radius⁻ + radius⁺`` (z, y, x fastest-varying last,
so XLA's (8,128) tiling lands on the y/x plane), and the curr/next double
buffer is a pair of pytrees swapped functionally (``swap()`` ≡ exchanging the
dict references; under ``jit`` this becomes input/output buffer aliasing
rather than a device-side pointer-array flip, src/local_domain.cu:67-84).

Array axis order is ``[z, y, x]`` throughout the framework (the reference
indexes ``z*ysize*pitch + y*pitch + x``, pitched_ptr.hpp:52 — same
memory order).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..geometry import Dim3, Radius, Rect3, compute_offset, halo_rect, raw_size
from .handle import DataHandle


def block_rect_slices(rect: Rect3) -> Tuple[slice, slice, slice]:
    """Slices selecting an allocation-local ``Rect3`` from a [z,y,x] array."""
    return (
        slice(rect.lo.z, rect.hi.z),
        slice(rect.lo.y, rect.hi.y),
        slice(rect.lo.x, rect.hi.x),
    )


def block_compute_slices(size, radius: Radius) -> Tuple[slice, slice, slice]:
    """Slices selecting the compute (interior, non-halo) region.

    The accessor-origin math of the reference (`local_domain.cuh:153-173`:
    origin = −negative-face radius) collapses to "offset every coordinate by
    the negative-side radius", i.e. this slice.
    """
    sz = Dim3.of(size)
    off = compute_offset(radius)
    return (
        slice(off.z, off.z + sz.z),
        slice(off.y, off.y + sz.y),
        slice(off.x, off.x + sz.x),
    )


class LocalBlock:
    """All quantities of one subdomain, halo-padded, double-buffered.

    Mirrors the reference ``LocalDomain`` API surface: ``add_data`` →
    ``realize`` → ``get_curr``/``get_next`` → ``swap``; geometry queries
    (``raw_size``, ``halo_rect`` …) delegate to :mod:`stencil_tpu.geometry`.
    """

    def __init__(self, size, origin, radius: Optional[Radius] = None):
        self.size = Dim3.of(size)
        self.origin = Dim3.of(origin)
        self.radius = radius if radius is not None else Radius.constant(0)
        self._handles: List[DataHandle] = []
        self._curr: Dict[int, jnp.ndarray] = {}
        self._next: Dict[int, jnp.ndarray] = {}
        self._realized = False

    # -- setup (reference: local_domain.cuh:85-107) -------------------------
    def set_radius(self, radius: Radius) -> None:
        if self._realized:
            raise RuntimeError("set_radius after realize")
        self.radius = radius

    def add_data(self, name: str = "", dtype="float32") -> DataHandle:
        if self._realized:
            raise RuntimeError("add_data after realize")
        h = DataHandle(len(self._handles), name or f"q{len(self._handles)}", str(jnp.dtype(dtype)))
        self._handles.append(h)
        return h

    def realize(self) -> None:
        """Allocate curr+next zero arrays per quantity
        (reference: src/local_domain.cu:159-220)."""
        shape = self.raw_size().as_tuple()[::-1]  # [z, y, x]
        for h in self._handles:
            self._curr[h.idx] = jnp.zeros(shape, dtype=h.dtype)
            self._next[h.idx] = jnp.zeros(shape, dtype=h.dtype)
        self._realized = True

    # -- geometry -----------------------------------------------------------
    def raw_size(self) -> Dim3:
        return raw_size(self.size, self.radius)

    def num_data(self) -> int:
        return len(self._handles)

    def handles(self) -> Tuple[DataHandle, ...]:
        return tuple(self._handles)

    def compute_slices(self) -> Tuple[slice, slice, slice]:
        return block_compute_slices(self.size, self.radius)

    def halo_region(self, direction, halo: bool) -> Rect3:
        """Allocation-local halo (``halo=True``) or matching interior-edge
        region (reference: src/local_domain.cu:86-129)."""
        return halo_rect(direction, self.size, self.radius, halo)

    # -- data access --------------------------------------------------------
    def get_curr(self, h: DataHandle) -> jnp.ndarray:
        return self._curr[h.idx]

    def get_next(self, h: DataHandle) -> jnp.ndarray:
        return self._next[h.idx]

    def set_curr(self, h: DataHandle, arr) -> None:
        if arr.shape != self.raw_size().as_tuple()[::-1]:
            raise ValueError(
                f"shape {arr.shape} != padded "
                f"{self.raw_size().as_tuple()[::-1]}"
            )
        self._curr[h.idx] = arr

    def set_next(self, h: DataHandle, arr) -> None:
        if arr.shape != self.raw_size().as_tuple()[::-1]:
            raise ValueError(
                f"shape {arr.shape} != padded "
                f"{self.raw_size().as_tuple()[::-1]}"
            )
        self._next[h.idx] = arr

    def curr_tree(self) -> Dict[int, jnp.ndarray]:
        return dict(self._curr)

    def next_tree(self) -> Dict[int, jnp.ndarray]:
        return dict(self._next)

    def swap(self) -> None:
        """Exchange curr/next (reference: src/local_domain.cu:67-84). A pure
        host-side reference swap — no device work."""
        self._curr, self._next = self._next, self._curr

    # -- host transfer (reference: local_domain.cuh:264-273, region_to_host)
    def quantity_to_host(self, h: DataHandle, curr: bool = True) -> np.ndarray:
        """Full padded region including halos, as numpy [z,y,x]."""
        src = self._curr if curr else self._next
        return np.asarray(src[h.idx])

    def region_to_host(self, h: DataHandle, rect: Rect3, curr: bool = True) -> np.ndarray:
        src = self._curr if curr else self._next
        return np.asarray(src[h.idx][block_rect_slices(rect)])

    def interior_to_host(self, h: DataHandle, curr: bool = True) -> np.ndarray:
        src = self._curr if curr else self._next
        return np.asarray(src[h.idx][self.compute_slices()])
