"""Typed handle naming one quantity within a domain.

TPU-native analogue of the reference's ``DataHandle<T>``
(reference: include/stencil/local_domain.cuh:18-26). The reference encodes
the element type in the C++ template parameter and the quantity's slot in an
integer index; here the handle carries the slot index, a human-readable name,
and the JAX dtype.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class DataHandle:
    idx: int
    name: str = ""
    dtype: str = "float32"

    def __repr__(self) -> str:
        return f"DataHandle({self.idx}, {self.name!r}, {self.dtype})"
