from .handle import DataHandle
from .block import LocalBlock, block_compute_slices, block_rect_slices
from .grid import GridSpec

__all__ = [
    "DataHandle",
    "GridSpec",
    "LocalBlock",
    "block_compute_slices",
    "block_rect_slices",
]
