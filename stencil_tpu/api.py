"""DistributedDomain — the top-level user API.

TPU-native re-design of the reference orchestrator
(reference: include/stencil/stencil.hpp:33-225, src/stencil.cu). The surface
is kept: ``set_radius`` → ``add_data`` → ``realize`` → loop
{compute / ``exchange`` / ``swap``} → ``write_paraview``. What changed
underneath:

- Subdomain-per-GPU ``LocalDomain`` allocations become one stacked,
  halo-padded array per quantity, sharded ``P('z','y','x')`` over a 3D
  device mesh (all blocks of all "ranks" in one jit-visible value).
- ``realize``'s transport planning (the 26-direction goto-cascade,
  src/stencil.cu:327-464, and sender/recver construction :651-759) becomes
  the construction + compilation of one :class:`HaloExchange`.
- ``exchange``'s CPU polling engine (src/stencil.cu:1002-1186) is one call
  into the compiled collective program; overlap is XLA's job (SURVEY §7.5).
- Placement (``do_placement``, src/stencil.cu:201-239) becomes device-mesh
  layout; the partition is still the comm-minimizing NodePartition.

Setup/exchange statistics mirror STENCIL_SETUP_STATS / STENCIL_EXCHANGE_STATS
(reference: CMakeLists.txt:17-22) but are always on — they cost one host
timestamp per call.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .domain import DataHandle, GridSpec
from .geometry import (
    DIRECTIONS_26,
    Dim3,
    NodePartition,
    Radius,
    Rect3,
    exterior_regions,
    halo_extent,
    interior_region,
)
from .parallel import HaloExchange, Method, grid_mesh
from .parallel.exchange import direction_bytes, shard_blocks, unshard_blocks
from .utils import logging as log
from .utils import timer
from .utils.sync import hard_sync


# moved to geometry/partition.py so the plan cost model predicts the same
# mesh realize() would build; kept as an alias for callers/tests
from .geometry import stack_residents as _stack_residents


class DistributedDomain:
    """A multi-quantity 3D domain distributed over a TPU device mesh."""

    def __init__(self, x: int, y: int, z: int, plan=None,
                 autotune: bool = False, plan_db: Optional[str] = None):
        self.size = Dim3(x, y, z)
        self.radius = Radius.constant(0)
        self._names: List[str] = []
        self._dtypes: List[str] = []
        self._method = Method.AXIS_COMPOSED
        self._batch_quantities = True
        self._fused = False
        self._persistent = False
        self._hierarchy: Optional[Tuple[str, int]] = None
        self._wire_dtype: Optional[str] = None
        self._devices: Optional[Sequence] = None
        self._partition_dim: Optional[Dim3] = None
        self._placement = None
        # exchange planning (stencil_tpu/plan/): an explicit tuned choice,
        # or realize()-time autotuning against the on-disk plan DB
        self._plan_choice = None
        self._autotune_opts: Optional[dict] = None
        self.autotune_result = None
        if plan is not None:
            self.set_plan(plan)
            if autotune:
                log.warn("explicit plan= suppresses autotune=: the given "
                         "choice is applied as-is (drop plan= to re-tune)")
        if autotune:
            self.enable_autotune(db_path=plan_db)
        self._output_prefix = os.environ.get("STENCIL_OUTPUT_PREFIX", "")
        self._realized = False
        # data (after realize): handle.idx -> stacked array
        self._curr: Dict[int, jax.Array] = {}
        self._next: Dict[int, jax.Array] = {}
        # setup stats (reference: stencil.hpp:103-112)
        self.time_plan = 0.0
        self.time_realize = 0.0
        self.time_create = 0.0
        # exchange stats (reference: stencil.hpp:96-101)
        self.time_exchange = 0.0
        self.time_swap = 0.0
        self.num_exchanges = 0

    # -- configuration (pre-realize) ----------------------------------------
    def set_radius(self, r) -> None:
        """Uniform or per-direction radius (reference: stencil.hpp:124-137)."""
        self.radius = Radius.constant(r) if isinstance(r, int) else r

    def add_data(self, name: str = "", dtype="float32") -> DataHandle:
        """Register a quantity (reference: stencil.hpp:128)."""
        if self._realized:
            raise RuntimeError("add_data after realize()")
        idx = len(self._names)
        self._names.append(name or f"data{idx}")
        self._dtypes.append(str(jnp.dtype(dtype)))
        return DataHandle(idx, self._names[-1], self._dtypes[-1])

    def set_methods(self, method: Method) -> None:
        """Exchange strategy (reference: stencil.hpp:139)."""
        self._method = method

    def set_plan(self, choice) -> None:
        """Apply a tuned exchange plan (a ``plan.ir.PlanChoice`` or its
        JSON dict): partition shape, exchange method, and quantity
        batching are applied at realize(); the choice's ``multistep_k``
        and ``kernel_variant`` ride along for the apps that own those
        knobs (:attr:`plan_choice`). An explicit :meth:`set_partition`
        still wins over the plan's partition (with a warning)."""
        from .plan.ir import PlanChoice

        if isinstance(choice, dict):
            choice = PlanChoice.from_json(choice)
        self._plan_choice = choice

    def enable_autotune(self, db_path: Optional[str] = None,
                        probe: bool = True, top_n: int = 3,
                        probe_iters: int = 4, ks: Sequence[int] = (1,),
                        force: bool = False) -> None:
        """Autotune the exchange plan at realize() time (plan/autotune):
        consult the plan DB first (a hit replays with zero probes), else
        rank the (partition x method x batching x k) space statically and
        refine the top ``top_n`` with measured probes, persisting the
        winner to ``db_path``. The result lands in
        :attr:`autotune_result`; telemetry gets the ``plan.cache_hit``
        gauge + ``plan.probes_run`` counter either way."""
        self._autotune_opts = dict(
            db_path=db_path, probe=probe, top_n=top_n,
            probe_iters=probe_iters, ks=tuple(ks), force=force,
        )

    @property
    def plan_choice(self):
        """The effective tuned choice (None on a plan-less domain)."""
        return self._plan_choice

    def set_fused_exchange(self, enabled: bool) -> None:
        """The FUSED compute+exchange variant of ``Method.REMOTE_DMA``
        (ROADMAP #5): the exchange moves one exact-extent message per
        active direction, all started boundary-first so the step loops
        overlap interior compute behind the wire (the Pallas mega-kernel
        on TPU, the host-orchestrated schedule elsewhere — both zero
        collective-permutes). Applied at realize(); also set
        automatically when a tuned plan carries
        ``kernel_variant == "fused"``. Single-resident partitions only —
        realize() raises loudly otherwise."""
        self._fused = bool(enabled)

    def set_persistent_exchange(self, enabled: bool) -> None:
        """The PERSISTENT whole-chunk variant of ``Method.REMOTE_DMA``
        (ROADMAP #7, ops/persistent_stencil.py): the step driver
        exchanges radius*k-deep halos ONCE per k-step chunk and runs the
        k substeps with no further communication — launch count drops
        from O(steps) to O(chunks). The domain must be realized at the
        DEEPENED radius (radius*k) — the step drivers that own the knob
        (``jacobi3d --kernel-variant persistent``) do this; also set
        automatically when a tuned plan carries
        ``kernel_variant == "persistent"``. Mutually exclusive with
        :meth:`set_fused_exchange`; single-resident REMOTE_DMA only —
        realize() raises loudly otherwise."""
        self._persistent = bool(enabled)

    def set_hierarchy(self, axis, hosts: Optional[int] = None) -> None:
        """Hierarchical (ICI + DCN) domain decomposition (ROADMAP #3):
        split the ``axis`` ('z'/'y'/'x') ring into ``hosts`` contiguous
        segments — the inner per-host exchange stays on the ICI while
        the segment-boundary slabs cross the DCN, overlapped behind the
        intra-host phases (parallel/hierarchy.py owns the schedule and
        the bit-parity argument). Pass ``None`` (or ``hosts=1``) to
        clear. Applied at realize(); also set automatically when a tuned
        plan carries a ``hierarchy``. The realized mesh must group each
        segment onto one host — in-process that is the
        ``STENCIL_VIRTUAL_HOSTS`` fabric plus the two-level placement
        (plan/cost.solve_two_level_placement); HaloExchange validates
        loudly. Composed/remote-dma inner methods only."""
        if axis is None:
            self._hierarchy = None
            return
        if hosts is None:
            axis, hosts = axis  # a ("z", 2) tuple
        self._hierarchy = (str(axis), int(hosts))

    def set_quantity_batching(self, enabled: bool) -> None:
        """Quantity-batched exchange (default on): per collective, all
        same-dtype quantities' boundary slabs ride ONE packed ``(Q, ...)``
        carrier, so the collective count per exchange is independent of
        the quantity count (parallel/exchange.py module docstring). Off =
        the historical one-collective-per-quantity program — the A/B
        baseline of ``bench_exchange --batched-ab``."""
        self._batch_quantities = bool(enabled)

    def set_wire_dtype(self, dtype) -> None:
        """bf16-on-the-wire halo compression (``None`` = off): boundary
        carriers that actually cross the interconnect narrow to this
        dtype before the send and widen on unpack
        (``HaloExchange(wire_dtype=...)``; ops/halo_fill.wire_narrow_dtype
        owns the policy — only floating carriers ever narrow, local
        copies stay lossless). LOSSY by design: the exchanged halos
        round to the wire precision, so checkpoints/parity comparisons
        across the knob differ. ``bench_exchange --wire-ab`` measures
        the error the bandwidth is bought with."""
        self._wire_dtype = None if dtype in (None, "") else str(jnp.dtype(dtype))

    def set_devices(self, devices: Sequence) -> None:
        """Restrict to specific devices (reference ``set_gpus``,
        stencil.hpp:154)."""
        self._devices = list(devices)

    def set_placement(self, placement) -> None:
        """Device-placement strategy (reference: stencil.hpp:146)."""
        self._placement = placement

    def set_partition(self, dim) -> None:
        """Override the automatic partition grid (testing/ablation)."""
        self._partition_dim = Dim3.of(dim)

    def set_output_prefix(self, prefix: str) -> None:
        self._output_prefix = prefix

    # -- realize -------------------------------------------------------------
    def realize(self) -> None:
        """Partition, build the mesh, allocate quantities, compile exchange
        (reference: src/stencil.cu:241-850)."""
        t0 = time.perf_counter()
        with timer.timed("setup.plan"), timer.trace_range("stencil.plan"):
            devices = (
                list(self._devices) if self._devices is not None else jax.devices()
            )
            n = len(devices)
            if self._autotune_opts is not None and self._plan_choice is None:
                if not self._dtypes:
                    log.warn("autotune: no quantities declared; skipping")
                else:
                    from .plan.autotune import autotune as _plan_autotune

                    opts = self._autotune_opts
                    self.autotune_result = _plan_autotune(
                        self.size, self.radius, self._dtypes,
                        devices=devices, db_path=opts["db_path"],
                        probe=opts["probe"], top_n=opts["top_n"],
                        probe_iters=opts["probe_iters"], ks=opts["ks"],
                        force=opts["force"],
                    )
                    self._plan_choice = self.autotune_result.choice
            if self._plan_choice is not None:
                ch = self._plan_choice
                if (self._partition_dim is not None
                        and self._partition_dim != Dim3.of(ch.partition)):
                    # the choice was tuned as a UNIT (its method/batching
                    # were measured on its partition); an explicit
                    # partition overrides the whole plan, not pieces of it
                    log.warn(
                        f"explicit partition {self._partition_dim} overrides "
                        f"the tuned plan {ch.label()}; the plan's method/"
                        "batching are NOT applied (re-tune with the pinned "
                        "partition instead)"
                    )
                    self._plan_choice = None
                else:
                    self._method = Method(ch.method)
                    self._batch_quantities = ch.batch_quantities
                    # the tuned choice owns the variant BOTH ways: a
                    # fused choice realizes the fused transport, and a
                    # non-fused choice clears any prior
                    # set_fused_exchange(True) — the autotune -> DB ->
                    # zero-probe replay round-trip must reproduce the
                    # tuned program exactly (and a composed winner must
                    # not crash realize() on a stale fused flag)
                    self._fused = ch.is_fused
                    self._persistent = ch.is_persistent
                    # same ownership rule for the outer DCN split: a
                    # hierarchical choice realizes the two-level
                    # transport, a flat one clears any prior
                    # set_hierarchy (absent field == flat, the
                    # pre-hierarchy DB/ckpt migration default)
                    self._hierarchy = ch.hierarchy
                    if self._partition_dim is None:
                        self._partition_dim = Dim3.of(ch.partition)
            if self._partition_dim is not None:
                dim = self._partition_dim
            else:
                # comm-minimizing two-level split: hosts x devices-per-host
                # (reference: do_placement -> NodeAware, src/stencil.cu:201-239)
                hosts = max(1, jax.process_count())
                part = NodePartition(self.size, self.radius, hosts, max(1, n // hosts))
                dim = part.dim()
            mesh_dim = dim
            if dim.flatten() != n:
                # oversubscription (reference: dd.set_gpus({0,0}),
                # stencil.hpp:154): run any partition on fewer devices by
                # stacking c = blocks/devices resident blocks per device;
                # the exchange shifts resident-neighbor slabs locally
                # (exchange.py _axis_phase_resident). Stacking may mix
                # axes — prefer z-heavy (the cheapest slab geometry), then
                # y, then x.
                c, rem = divmod(dim.flatten(), n)
                if rem:
                    raise ValueError(
                        f"partition {dim} has {dim.flatten()} blocks, not a "
                        f"multiple of {n} devices"
                    )
                mesh_dim = _stack_residents(dim, c)
            self.spec = GridSpec(self.size, dim, self.radius)
            ordered = False
            if self._placement is not None and mesh_dim != dim:
                log.warn(
                    "placement strategies assume one block per device; "
                    "ignoring set_placement for the oversubscribed partition"
                )
            elif self._placement is not None:
                devices = self._placement.arrange(devices, self.spec)
                ordered = True
            ch = self._plan_choice
            if ch is not None and ch.placement is not None:
                # the tuned topology-aware placement: mesh position i
                # (row-major z, y, x — residents stack WITHIN a
                # position, so oversubscription composes) is hosted by
                # devices[placement[i]]. An explicit set_placement
                # strategy wins, with a warning — like set_partition
                # over the tuned partition.
                if ordered:
                    log.warn(
                        "explicit set_placement overrides the tuned "
                        f"plan's placement {list(ch.placement)}; probes "
                        "measured the tuned assignment, not this one"
                    )
                else:
                    from .plan.ir import validate_placement

                    err = validate_placement(ch.placement, n)
                    if err is not None:
                        raise ValueError(f"tuned plan placement: {err}")
                    devices = [devices[ch.placement[i]] for i in range(n)]
                    ordered = True
            self.mesh = grid_mesh(mesh_dim, devices, ordered=ordered)
        self.time_plan = time.perf_counter() - t0

        t0 = time.perf_counter()
        with timer.timed("setup.realize"), timer.trace_range("stencil.realize"):
            shape = self.spec.stacked_shape_zyx()
            self._exchange = HaloExchange(
                self.spec, self.mesh, self._method,
                batch_quantities=self._batch_quantities,
                wire_dtype=self._wire_dtype,
                fused=self._fused,
                persistent=self._persistent,
                hierarchy=self._hierarchy,
            )
            sharding = self._exchange.sharding()
            for idx, dt in enumerate(self._dtypes):
                self._curr[idx] = jax.device_put(jnp.zeros(shape, dtype=dt), sharding)
                self._next[idx] = jax.device_put(jnp.zeros(shape, dtype=dt), sharding)
        self.time_realize = time.perf_counter() - t0

        t0 = time.perf_counter()
        with timer.timed("setup.create"), timer.trace_range("stencil.create"):
            self._exchange._compiled  # build + trace now, like two-phase prepare
        self.time_create = time.perf_counter() - t0
        self._realized = True
        log.debug(
            f"realized {self.size} over {dim} blocks of {self.spec.base}, "
            f"padded {self.spec.padded()}"
        )
        if self._output_prefix:
            self.write_plan(self._output_prefix)

    # -- data access ---------------------------------------------------------
    def get_curr(self, h: DataHandle) -> jax.Array:
        return self._curr[h.idx]

    def get_next(self, h: DataHandle) -> jax.Array:
        return self._next[h.idx]

    def set_curr(self, h: DataHandle, stacked: jax.Array) -> None:
        self._curr[h.idx] = stacked

    def set_next(self, h: DataHandle, stacked: jax.Array) -> None:
        self._next[h.idx] = stacked

    def curr_state(self) -> Dict[int, jax.Array]:
        return dict(self._curr)

    def next_state(self) -> Dict[int, jax.Array]:
        return dict(self._next)

    def set_curr_global(self, h: DataHandle, global_zyx: np.ndarray) -> None:
        """Scatter a host array [z,y,x] into the sharded layout."""
        self._curr[h.idx] = shard_blocks(
            global_zyx.astype(self._dtypes[h.idx]), self.spec, self.mesh
        )

    def get_curr_global(self, h: DataHandle) -> np.ndarray:
        """Gather the compute region to a host array [z,y,x]."""
        return unshard_blocks(self._curr[h.idx], self.spec)

    def sharding(self):
        return self._exchange.sharding()

    # -- the iteration API (reference: stencil.hpp:182-215) ------------------
    @property
    def halo_exchange(self) -> HaloExchange:
        """The compiled halo-exchange op, for composing into larger jitted
        steps (fused compute/exchange overlap, custom loops). Public: this
        is how apps embed the exchange inside their own shard_map'd step
        (the reference's equivalent is handing its senders the app streams,
        bin/jacobi3d.cu:296-368)."""
        return self._exchange

    def exchange(self) -> None:
        """Fill every halo from the periodic neighbors
        (reference: src/stencil.cu:1002-1186).

        Synchronizes with the device each call, so the per-call overhead is
        a full host round-trip (~0.7 s on a tunneled TPU). For iteration
        loops use :meth:`exchange_loop` / :attr:`halo_exchange` instead."""
        t0 = time.perf_counter()
        with timer.timed("exchange"), timer.trace_range("stencil.exchange"):
            self._curr = self._exchange(self._curr)
            hard_sync(self._curr)  # block_until_ready lies on the tunneled TPU
        self.time_exchange += time.perf_counter() - t0
        self.num_exchanges += 1

    def exchange_loop(self, iters: int):
        """``iters`` fused back-to-back exchanges as one compiled program
        acting on a quantity pytree (see :meth:`curr_state`): amortizes
        dispatch cost the way the reference's timed loops amortize launch
        overhead (reference: bin/exchange_weak.cu:168-177)."""
        return self._exchange.make_loop(iters)

    def run_exchanges(self, iters: int) -> None:
        """Run ``iters`` fused exchanges on the domain's current state."""
        t0 = time.perf_counter()
        with timer.timed("exchange"), timer.trace_range("stencil.exchange_loop"):
            self._curr = self.exchange_loop(iters)(self._curr)
            hard_sync(self._curr)
        self.time_exchange += time.perf_counter() - t0
        self.num_exchanges += iters

    def swap(self) -> None:
        """Swap curr/next (reference: src/stencil.cu:852-872)."""
        t0 = time.perf_counter()
        with timer.timed("swap"):
            self._curr, self._next = self._next, self._curr
        self.time_swap += time.perf_counter() - t0

    def get_interior(self) -> List[Rect3]:
        """Per-block interior compute region, allocation-local coordinates
        (reference: src/stencil.cu:878-921)."""
        out = []
        off = self.spec.compute_offset()
        for i in range(self.spec.num_blocks()):
            idx = self._block_idx(i)
            sz = self.spec.block_size(idx)
            compute = Rect3(off, off + sz)
            out.append(interior_region(compute, self.radius))
        return out

    def get_exterior(self) -> List[List[Rect3]]:
        """Per-block exterior slabs (reference: src/stencil.cu:927-977)."""
        out = []
        off = self.spec.compute_offset()
        interiors = self.get_interior()
        for i in range(self.spec.num_blocks()):
            idx = self._block_idx(i)
            sz = self.spec.block_size(idx)
            compute = Rect3(off, off + sz)
            out.append(exterior_regions(compute, interiors[i]))
        return out

    def _block_idx(self, i: int) -> Dim3:
        d = self.spec.dim
        return Dim3(i % d.x, (i // d.x) % d.y, i // (d.x * d.y))

    # -- accounting (reference: src/stencil.cu:139-161) ----------------------
    def exchange_bytes_for_method(self, method: Method) -> int:
        """Logical halo bytes per exchange attributed to ``method``."""
        if method != self._method:
            return 0
        itemsizes = [jnp.dtype(dt).itemsize for dt in self._dtypes]
        return self._exchange.bytes_logical(itemsizes)

    def exchange_bytes_moved(self) -> int:
        itemsizes = [jnp.dtype(dt).itemsize for dt in self._dtypes]
        return self._exchange.bytes_moved(itemsizes)

    def plan_meta(self) -> dict:
        """The EFFECTIVE exchange plan of this realized domain — what the
        ckpt manifests record so ``--resume`` can warn when a snapshot
        tuned under one plan is revived under another (the state restores
        bit-identically either way — elasticity — but the compiled
        programs, and any recorded performance, differ)."""
        from .plan.ir import PlanChoice, PlanConfig

        if not self._realized:
            raise RuntimeError("plan_meta requires realize()")
        devs = self.mesh.devices.flatten()
        cfg = PlanConfig.make(self.size, self.radius, self._dtypes,
                              len(devs), devs[0].platform)
        from .plan.ir import FUSED_VARIANT, PERSISTENT_VARIANT

        ch = self._plan_choice
        choice = PlanChoice(
            partition=(self.spec.dim.x, self.spec.dim.y, self.spec.dim.z),
            method=self._method.value,
            batch_quantities=self._batch_quantities,
            multistep_k=ch.multistep_k if ch is not None else 1,
            kernel_variant=(ch.kernel_variant if ch is not None
                            else FUSED_VARIANT if self._fused
                            else PERSISTENT_VARIANT if self._persistent
                            else None),
            placement=ch.placement if ch is not None else None,
            hierarchy=self._hierarchy,
            host_placement=ch.host_placement if ch is not None else None,
        )
        # the realized host fabric: host index per mesh position, so a
        # resume on a different host topology (other host count, other
        # segment grouping) is visible in the manifest even when the
        # plan itself is unchanged
        from .parallel.device_topo import host_assignment

        hosts = [int(h) for h in host_assignment(
            list(self.mesh.devices.flat))]
        return {"key": cfg.to_json(), "choice": choice.to_json(),
                "tuned": ch is not None,
                "wire_dtype": self._wire_dtype,
                "host_blocks": hosts}

    def _warn_plan_mismatch(self, manifest: dict) -> None:
        saved = (manifest.get("meta") or {}).get("plan")
        if not saved:
            return  # pre-plan snapshot: nothing to compare
        here = self.plan_meta()
        saved_ch = dict(saved.get("choice") or {})
        here_ch = dict(here["choice"])
        # pre-placement snapshots never wrote the field: an absent
        # placement IS the identity assignment (the plan-DB migration
        # rule), so normalize both sides before comparing — a build
        # upgrade must not make every old snapshot warn
        saved_ch.setdefault("placement", None)
        here_ch.setdefault("placement", None)
        # same migration rule for the outer DCN split: pre-hierarchy
        # snapshots never wrote the fields, and absent IS flat
        for k in ("hierarchy", "host_placement"):
            saved_ch.setdefault(k, None)
            here_ch.setdefault(k, None)
        # host-topology delta: the plan may be unchanged while the host
        # fabric moved under it (other host count / segment grouping) —
        # restoring is still bit-exact, but recorded DCN performance is
        # not comparable. Pre-hierarchy snapshots (no field) stay quiet.
        saved_hosts = saved.get("host_blocks")
        here_hosts = here.get("host_blocks")
        if saved_hosts is not None and saved_hosts != here_hosts:
            log.warn(
                "ckpt: snapshot was written on host fabric "
                f"{saved_hosts} but this run realizes {here_hosts} "
                "(host index per mesh position) — the elastic restore "
                "is bit-exact, but cross-host exchange behavior and any "
                "recorded DCN timings differ"
            )
        if not (saved.get("tuned") or here["tuned"]):
            # neither side went through the tuner: a partition-only delta
            # is the supported elastic mesh-reshape resume (PR 4) and must
            # stay quiet (and so must a placement-only one — both are
            # realize()-time layout facts, not tuned verdicts);
            # method/batching deltas still mix programs
            saved_ch.pop("partition", None)
            here_ch.pop("partition", None)
            saved_ch.pop("placement", None)
            here_ch.pop("placement", None)
        # the comparison is data-driven (plain dicts), so a snapshot
        # written under a method this build does not know — REMOTE_DMA
        # from a newer build, or any future transport — still WARNS
        # instead of crashing on an unknown enum name; name the methods
        # in the message so the operator sees what moved
        saved_m = saved_ch.get("method")
        here_m = here_ch.get("method")
        known = {m.value for m in Method}
        unknown = (f" (method {saved_m!r} is unknown to this build)"
                   if saved_m is not None and saved_m not in known else "")
        wire_delta = saved.get("wire_dtype") != here.get("wire_dtype")
        if saved_ch != here_ch or wire_delta:
            detail = (f" (exchange method {saved_m} -> {here_m})"
                      if saved_m != here_m else "")
            if wire_delta:
                detail += (f" (wire_dtype {saved.get('wire_dtype')} -> "
                           f"{here.get('wire_dtype')}: halos exchanged "
                           "after restore round to the NEW wire precision)")
            log.warn(
                "ckpt: snapshot was written under exchange plan "
                f"{saved.get('choice')} but this run uses {here['choice']}"
                f"{detail}{unknown} — the elastic restore is still "
                "bit-exact, but the compiled programs differ; re-tune "
                "(--autotune) or pass the snapshot's plan to keep "
                "measurements comparable"
            )

    def replan(self, choice) -> None:
        """Hot-swap the exchange plan of a REALIZED domain, in place —
        the mid-run half of ROADMAP #6 (the PR-12 ``replan.requested``
        hook's consumer, driven by :class:`stencil_tpu.plan.replan.
        ReplanController` between guarded-loop chunks).

        ``choice`` (a ``plan.ir.PlanChoice`` or its JSON dict) is applied
        as a UNIT — partition, method, batching, kernel variant, and
        block placement; any explicit ``set_partition`` pin is cleared,
        exactly like a fresh tuned realize. The swap is the elastic
        ckpt restore without the disk: gather every quantity's global
        interior (pure host copies — bit-exact), re-realize under the
        new plan (the compile cache of already-seen programs makes this
        cheap), re-scatter, and rebuild the exteriors with one halo
        exchange. State after the swap is bit-identical to before it."""
        from .plan.ir import PlanChoice

        if not self._realized:
            raise RuntimeError(
                "replan() requires a realized domain (use set_plan "
                "before realize() for the initial choice)")
        if isinstance(choice, dict):
            choice = PlanChoice.from_json(choice)
        with timer.timed("setup.replan"), timer.trace_range("stencil.replan"):
            globs = {
                idx: unshard_blocks(self._curr[idx], self.spec)
                for idx in self._curr
            }
            old_choice = self._plan_choice
            old_partition = self._partition_dim

            def _install(ch):
                self._plan_choice = ch
                self._realized = False
                self._curr = {}
                self._next = {}
                self.realize()
                for idx, g in globs.items():
                    self._curr[idx] = shard_blocks(
                        g.astype(self._dtypes[idx]), self.spec, self.mesh)
                if self.radius.max_radius() > 0:
                    # one exchange rebuilds every exterior on the new
                    # layout (idempotent on exchanged data — the
                    # elastic-restore rule)
                    self.exchange()

            self._partition_dim = None
            try:
                _install(choice)
            except Exception:
                # a choice that cannot realize (bad tuned placement, a
                # partition the live device set no longer divides) must
                # not leave the domain torn: the ReplanController's
                # "rejected — continuing on the old plan" contract is
                # only true if the old plan is actually back. Re-realize
                # the old choice, re-shard the gathered state, and let
                # the original exception propagate as the rejection.
                self._partition_dim = old_partition
                _install(old_choice)
                raise

    # -- checkpoint / restart (ckpt/ subsystem) ------------------------------
    def save_checkpoint(self, ckpt_dir: str, step: int, *, keep: int = 3,
                        asynchronous: bool = True) -> None:
        """Snapshot every quantity's ``curr`` state at ``step`` into
        ``ckpt_dir`` (sharded per-block npz + manifest; crash-safe rename
        protocol — see ckpt/snapshot.py).

        ``asynchronous=True`` (default) fetches the snapshot copy on this
        thread, then hashes/serializes/fsyncs on a writer thread so the
        step loop keeps running; a second save drains the first (double
        buffering). Call :meth:`finish_checkpoints` before exiting."""
        from .ckpt import AsyncCheckpointer, host_snapshot, write_snapshot

        if jax.process_count() > 1:
            # cross-host shards are not addressable from this process;
            # per-host sharded writes + manifest merge are a ROADMAP #7
            # follow-up — degrade loudly ONCE, and count every skip so a
            # campaign with zero durable state is alertable from its
            # metrics (ckpt.save_skipped), never kill the run
            from .obs import telemetry

            telemetry.get().counter(
                "ckpt.save_skipped", value=1, phase="ckpt", step=int(step),
                reason="multi-process writes unsupported")
            if not getattr(self, "_ckpt_skip_warned", False):
                self._ckpt_skip_warned = True
                log.warn("ckpt: multi-process checkpoint writes are not "
                         "supported yet; skipping save (every skip is "
                         "counted as ckpt.save_skipped; this warning is "
                         "not repeated)")
            return
        arrays = {name: self._curr[i] for i, name in enumerate(self._names)}
        dtypes = dict(zip(self._names, self._dtypes))
        extra_meta = {"plan": self.plan_meta()}
        if not asynchronous:
            with timer.timed("ckpt.save"), timer.trace_range("ckpt.save"):
                write_snapshot(ckpt_dir, step, self.spec,
                               host_snapshot(self.spec, arrays),
                               dtypes=dtypes, keep=keep,
                               extra_meta=extra_meta)
            return
        cp = getattr(self, "_checkpointer", None)
        if cp is None or cp.ckpt_dir != ckpt_dir:
            if cp is not None:
                cp.close()
            cp = self._checkpointer = AsyncCheckpointer(
                ckpt_dir, keep=keep, dtypes=dtypes
            )
        cp.keep = keep
        cp.save(self.spec, arrays, step, extra_meta=extra_meta)

    def flush_checkpoints(self) -> None:
        """Block until the in-flight async snapshot (if any) is durable,
        keeping the writer alive — what the fault/recovery engine calls
        before reading the checkpoint dir back (rollback restore, the
        ckpt-truncate injection): disk must reflect every save already
        handed off."""
        cp = getattr(self, "_checkpointer", None)
        if cp is not None:
            cp.flush()

    def finish_checkpoints(self) -> None:
        """Drain the async checkpoint writer (every handed-off snapshot is
        durable when this returns)."""
        cp = getattr(self, "_checkpointer", None)
        if cp is not None:
            cp.close()
            self._checkpointer = None

    def restore_checkpoint(self, ckpt_dir: str) -> Optional[int]:
        """Materialize the newest valid snapshot under ``ckpt_dir`` onto
        THIS domain — elastic: the snapshot's partition/mesh/device count
        may differ from the saver's (global reassembly + re-split + halo
        exchange; ckpt/restore.py). Returns the restored step, or None
        when no compatible snapshot exists (logged, never raised — the
        auto-resume path must degrade to a fresh start)."""
        from .ckpt import assemble_global, check_compatible, find_resume
        from .obs import telemetry

        if not self._realized:
            raise RuntimeError("restore_checkpoint requires realize()")
        if jax.process_count() > 1:
            telemetry.get().counter(
                "ckpt.restore_skipped", value=1, phase="ckpt",
                reason="multi-process restore unsupported")
            log.warn("ckpt: multi-process restore is not supported yet; "
                     "starting fresh")
            return None
        # compatibility joins validity in the fallback: a newer intact
        # snapshot from a DIFFERENT domain shape must not shadow an older
        # compatible one
        found = find_resume(
            ckpt_dir,
            accept=lambda m: check_compatible(
                m, self.size, self._names, self._dtypes),
        )
        if found is None:
            log.info(f"ckpt: no valid compatible snapshot under {ckpt_dir}")
            return None
        snap, manifest = found
        # plan provenance: resuming under a different tuned plan is legal
        # (elastic restore) but must never be silent
        self._warn_plan_mismatch(manifest)
        rec = telemetry.get()
        with rec.span("ckpt.restore", phase="ckpt", step=manifest["step"]):
            nbytes = 0
            for idx, name in enumerate(self._names):
                g = assemble_global(snap, manifest, name,
                                    dtype=self._dtypes[idx])
                nbytes += g.nbytes
                self.set_curr_global(DataHandle(idx, name, self._dtypes[idx]), g)
            if self.radius.max_radius() > 0:
                # rebuild every exterior on the CURRENT partition — after
                # this the restored state is indistinguishable from a live
                # one (halo exchange is idempotent on exchanged data)
                self.exchange()
        rec.counter("ckpt.bytes_read", bytes=nbytes, phase="ckpt",
                    step=manifest["step"])
        rec.meta("ckpt.resumed", step=manifest["step"], snapshot=snap)
        log.info(f"ckpt: restored step {manifest['step']} from {snap}")
        return manifest["step"]

    # -- numerical health (fault/ subsystem) ---------------------------------
    def check_health(self, max_abs: Optional[float] = None,
                     step: Optional[int] = None) -> None:
        """One fused ``isfinite`` reduction (plus an optional ``max|u|``
        divergence ceiling) over every quantity's current state — raises
        :class:`stencil_tpu.fault.NumericalFault` naming the offending
        quantity and records the per-check cost as a ``health.check``
        span. The step program is untouched (the guard is a separate
        compiled reduction): with no check calls there is zero HLO
        change. The loop-integrated version (periodic checks + rollback)
        is :func:`stencil_tpu.fault.run_guarded`, wired as the apps'
        ``--health-every`` / ``--max-rollbacks`` knobs."""
        from .fault.health import HealthGuard

        if not self._realized:
            raise RuntimeError("check_health requires realize()")
        g = getattr(self, "_health_guard", None)
        if g is None:
            g = self._health_guard = HealthGuard(every=1, max_abs=max_abs)
        # the ceiling is a host-side comparison, not part of the compiled
        # reduction — mutate it rather than rebuilding (and re-jitting) the
        # guard when callers alternate ceilings
        g.max_abs = float(max_abs) if max_abs else None
        g.check({self._names[i]: a for i, a in self._curr.items()},
                step=-1 if step is None else int(step))

    # -- observability -------------------------------------------------------
    def write_plan(self, prefix: str) -> None:
        """Dump the exchange plan and the block-comm matrix — the analogue of
        plan_<rank>.txt / mat_npy_loadtxt.txt (reference:
        src/stencil.cu:482-637)."""
        path = f"{prefix}plan_{jax.process_index()}.txt"
        with open(path, "w") as f:
            f.write(f"global {self.size} dim {self.spec.dim} base {self.spec.base}\n")
            f.write(f"radius {self.radius}\n")
            f.write(f"method {self._method.value}\n")
            f.write(f"mesh {dict(self.mesh.shape)}\n")
            itemsizes = [jnp.dtype(dt).itemsize for dt in self._dtypes]
            for d in DIRECTIONS_26:
                b = direction_bytes(self.spec, d, sum(itemsizes))
                f.write(f"dir ({d.x},{d.y},{d.z}) bytes {b}\n")
        # block-to-block byte matrix for numpy loadtxt
        nb = self.spec.num_blocks()
        mat = np.zeros((nb, nb), dtype=np.int64)
        itemsize = sum(jnp.dtype(dt).itemsize for dt in self._dtypes)
        for i in range(nb):
            src = self._block_idx(i)
            for d in DIRECTIONS_26:
                if self.radius.dir(d) == 0:
                    continue
                dst = (src + d).wrap(self.spec.dim)
                j = dst.x + dst.y * self.spec.dim.x + dst.z * self.spec.dim.x * self.spec.dim.y
                ext = halo_extent(d, self.spec.block_size(src), self.radius)
                mat[i, j] += ext.flatten() * itemsize
        np.savetxt(f"{prefix}mat_npy_loadtxt.txt", mat, fmt="%d")

    def write_paraview(self, prefix: str, zero_nans: bool = False) -> None:
        """Per-block CSV dump of the interior — same columns as the reference
        (Z,Y,X,<quantity names>; reference: src/stencil.cu:1188-1264).

        Rows stream from the native writer (native/paraview.cpp — the
        reference's writer is C++ too, and a Python row loop is minutes of
        interpreter time at flagship sizes); the pure-Python loop is the
        byte-identical fallback when the shared library is unavailable."""
        off = self.spec.compute_offset()
        hosts = {
            idx: np.asarray(jax.device_get(arr)) for idx, arr in self._curr.items()
        }
        try:
            from .native import paraview_write
        except Exception:
            paraview_write = None
        for i in range(self.spec.num_blocks()):
            idx3 = self._block_idx(i)
            sz = self.spec.block_size(idx3)
            origin = self.spec.block_origin(idx3)
            path = f"{prefix}_{i}.txt"
            header = ",".join(["Z", "Y", "X"] + list(self._names))
            qs = []
            for qi in range(len(self._names)):
                block = hosts[qi][idx3.z, idx3.y, idx3.x]
                q = block[
                    off.z : off.z + sz.z, off.y : off.y + sz.y, off.x : off.x + sz.x
                ]
                if zero_nans:
                    q = np.nan_to_num(q, nan=0.0)
                qs.append(q)
            if paraview_write is not None:
                try:
                    paraview_write(
                        path, header,
                        (origin.z, origin.y, origin.x), (sz.z, sz.y, sz.x), qs,
                    )
                except OSError:  # stale .so without the symbol: fall back
                    paraview_write = None
            if paraview_write is None:
                with open(path, "w") as f:
                    f.write(header + "\n")
                    for lz in range(sz.z):
                        for ly in range(sz.y):
                            for lx in range(sz.x):
                                pos = origin + Dim3(lx, ly, lz)
                                row = [str(pos.z), str(pos.y), str(pos.x)]
                                row += [repr(float(q[lz, ly, lx])) for q in qs]
                                f.write(",".join(row) + "\n")
            log.info(f"wrote paraview file {path}")
