"""stencil_tpu — TPU-native distributed 3D stencil halo-exchange framework.

A from-scratch JAX/XLA re-design with the capabilities of the reference
MPI/CUDA library socal-ucr/stencil (see SURVEY.md): multi-quantity 3D
domains, per-direction asymmetric radii, communication-minimizing
partitioning, 26-neighbor periodic halo exchange as ``shard_map``-ped
``lax.ppermute`` collectives over a 3D device mesh, and interior/exterior
comm/compute overlap inside a single jitted step.
"""

from .utils import jax_compat as _jax_compat

_jax_compat.apply()  # older-jax shims; no-op on a current release

from .domain import DataHandle, GridSpec, LocalBlock
from .geometry import Dim3, Radius, Rect3
from .parallel import HaloExchange, Method, grid_mesh

__version__ = "0.1.0"

__all__ = [
    "DataHandle",
    "Dim3",
    "GridSpec",
    "HaloExchange",
    "LocalBlock",
    "Method",
    "Radius",
    "Rect3",
    "grid_mesh",
    "__version__",
]
