from . import logging, timer
from .statistics import Statistics

__all__ = ["Statistics", "logging", "timer"]
