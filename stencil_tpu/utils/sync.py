"""Hard device synchronization for timing.

On the tunneled TPU platform ``jax.block_until_ready`` can return before
execution finishes (readiness events are not plumbed through), and a
per-call host round-trip costs ~0.7 s. All timing must therefore (a) fuse
iteration loops into one compiled program and (b) synchronize by fetching a
scalar, which forces completion of everything queued before it."""

from __future__ import annotations

import jax
import numpy as np


def hard_sync(tree) -> float:
    """Force completion of all queued work producing ``tree``; returns one
    element of the first leaf (cheap: a single-scalar transfer)."""
    leaf = jax.tree.leaves(tree)[0]
    idx = tuple(0 for _ in leaf.shape)
    return float(np.asarray(jax.device_get(leaf[idx])))
