"""Leveled stderr logging with a process-index prefix.

TPU-native analogue of the reference's compile-time leveled logging
(reference: include/stencil/logging.hpp:8-53). Instead of a CMake-time
level, the level is read from the ``STENCIL_LOG_LEVEL`` environment variable
(SPEW|DEBUG|INFO|WARN|ERROR|FATAL, default INFO) and may be changed at
runtime with :func:`set_level`. ``fatal`` raises instead of ``exit(1)`` so
library users can handle errors.
"""

from __future__ import annotations

import os
import sys

SPEW, DEBUG, INFO, WARN, ERROR, FATAL = 0, 1, 2, 3, 4, 5
_NAMES = {"SPEW": SPEW, "DEBUG": DEBUG, "INFO": INFO, "WARN": WARN, "ERROR": ERROR, "FATAL": FATAL}
_LEVEL = _NAMES.get(os.environ.get("STENCIL_LOG_LEVEL", "INFO").upper(), INFO)


class FatalError(RuntimeError):
    pass


def set_level(level) -> None:
    global _LEVEL
    _LEVEL = _NAMES[level.upper()] if isinstance(level, str) else int(level)


def get_level() -> int:
    return _LEVEL


_PID: int | None = None


def _prefix(tag: str) -> str:
    # Resolve the process index lazily and only if JAX is already imported —
    # calling jax.process_index() here would otherwise *initialize* the JAX
    # backend as a side effect of the first log line, pinning the platform
    # before user code can configure it.
    global _PID
    if _PID is None:
        pid = 0
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                pid = jax.process_index()
                _PID = pid
            except Exception:
                pass  # backend not up yet; retry on a later log line
        return f"[{tag}] p{pid}: "
    return f"[{tag}] p{_PID}: "


def _emit(level: int, tag: str, msg: str) -> None:
    if level >= _LEVEL:
        print(_prefix(tag) + str(msg), file=sys.stderr)


def spew(msg):
    _emit(SPEW, "SPEW", msg)


def debug(msg):
    _emit(DEBUG, "DEBUG", msg)


def info(msg):
    _emit(INFO, "INFO", msg)


def warn(msg):
    _emit(WARN, "WARN", msg)


def error(msg):
    _emit(ERROR, "ERROR", msg)


def fatal(msg):
    _emit(FATAL, "FATAL", msg)
    raise FatalError(str(msg))
