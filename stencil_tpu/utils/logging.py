"""Leveled stderr logging with a process-index prefix.

TPU-native analogue of the reference's compile-time leveled logging
(reference: include/stencil/logging.hpp:8-53). Instead of a CMake-time
level, the level is read from the ``STENCIL_LOG_LEVEL`` environment variable
(SPEW|DEBUG|INFO|WARN|ERROR|FATAL, default INFO) and may be changed at
runtime with :func:`set_level`. ``fatal`` raises instead of ``exit(1)`` so
library users can handle errors.
"""

from __future__ import annotations

import os
import sys

SPEW, DEBUG, INFO, WARN, ERROR, FATAL = 0, 1, 2, 3, 4, 5
_NAMES = {"SPEW": SPEW, "DEBUG": DEBUG, "INFO": INFO, "WARN": WARN, "ERROR": ERROR, "FATAL": FATAL}
_LEVEL = _NAMES.get(os.environ.get("STENCIL_LOG_LEVEL", "INFO").upper(), INFO)


class FatalError(RuntimeError):
    pass


def set_level(level) -> None:
    global _LEVEL
    _LEVEL = _NAMES[level.upper()] if isinstance(level, str) else int(level)


def get_level() -> int:
    return _LEVEL


def _prefix(tag: str) -> str:
    try:
        import jax

        pid = jax.process_index()
    except Exception:
        pid = 0
    return f"[{tag}] p{pid}: "


def _emit(level: int, tag: str, msg: str) -> None:
    if level >= _LEVEL:
        print(_prefix(tag) + str(msg), file=sys.stderr)


def spew(msg):
    _emit(SPEW, "SPEW", msg)


def debug(msg):
    _emit(DEBUG, "DEBUG", msg)


def info(msg):
    _emit(INFO, "INFO", msg)


def warn(msg):
    _emit(WARN, "WARN", msg)


def error(msg):
    _emit(ERROR, "ERROR", msg)


def fatal(msg):
    _emit(FATAL, "FATAL", msg)
    raise FatalError(str(msg))
