"""Machine check of the comm/compute-overlap dataflow structure.

The TPU scheduler can only run a ``collective-permute`` concurrently with
the interior compute if neither depends on the other — the property the
reference achieves with streams + CPU polling (reference:
src/stencil.cu:1002-1186, bin/jacobi3d.cu:296-368) and this framework
achieves by construction (the fast-path kernel reads pre-exchange data).
No hardware can *demonstrate* the overlap without a real multi-chip slice
(BASELINE.md config 5), but the enabling dataflow property is checkable on
any host: export the ≥2-device step for the TPU platform
(``jax.export``), parse the StableHLO SSA graph, and verify that no
``collective_permute`` transitively consumes the stencil kernel's output
and the kernel consumes no ``collective_permute`` result.

Used by tests/test_overlap_hlo.py (the machine gate) via the subprocess
runner scripts/export_overlap_hlo.py, which is also the standalone entry.
"""

from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

_ID_RE = re.compile(r"%[A-Za-z0-9_]+")


def _func_bodies(mlir_text: str) -> List[Tuple[str, List[str]]]:
    """(header line, body lines) of every ``func.func`` in the module."""
    lines = mlir_text.splitlines()
    out: List[Tuple[str, List[str]]] = []
    header = None
    body: List[str] = []
    depth = 0
    for ln in lines:
        if header is None:
            if re.search(r"func\.func .*@\w+", ln):
                header = ln
                body = []
                depth = ln.count("{") - ln.count("}")
            continue
        depth += ln.count("{") - ln.count("}")
        body.append(ln)
        if depth <= 0:
            out.append((header, body))
            header = None
    return out


def _main_body(mlir_text: str) -> List[str]:
    """Lines of the function body holding the step's dataflow.

    On a current jax the shard_map'd step inlines into ``@main`` (as an
    ``sdy.manual_computation`` region); older releases lower shard_map to
    a CALL of a private callee, leaving ``@main`` without the collectives.
    Analyze ``@main`` when it contains them, otherwise the function with
    the most ``collective_permute`` ops (SSA ids are function-local, so
    the graph must never mix functions)."""
    funcs = _func_bodies(mlir_text)
    main = next(
        (b for h, b in funcs if re.search(r"@main\b", h)), []
    )
    if any("collective_permute" in ln for ln in main):
        return main
    best = max(
        funcs,
        key=lambda hb: sum("collective_permute" in ln for ln in hb[1]),
        default=(None, main),
    )
    if sum("collective_permute" in ln for ln in best[1]):
        return best[1]
    return main


def build_graph(mlir_text: str) -> Dict[str, Tuple[str, List[str]]]:
    """SSA graph of @main (including regions nested in it, e.g. the
    ``sdy.manual_computation`` a shard_map lowers to): result id ->
    (op line, operand ids on that line).

    Parsing is per-line: every op this check cares about
    (collective_permute, the Mosaic custom_call, slices/updates) is a
    single-line op. Multi-result ops (``%a:2 = ...``) are keyed by their
    base id; uses ``%a#1`` are normalized to ``%a``. Block arguments of
    nested regions terminate closures (their binding to outer operands is
    not tracked), which can only MISS dependence edges through region
    boundaries — acceptable because the step under test is a single
    straight-line iteration (no fori_loop), asserted by the caller seeing
    the expected op counts.
    """
    graph: Dict[str, Tuple[str, List[str]]] = {}
    for ln in _main_body(mlir_text):
        m = re.match(r"^\s*(%[A-Za-z0-9_]+)(?::\d+)?\s*=\s*(.*)$", ln)
        if not m:
            continue
        res, rhs = m.group(1), m.group(2)
        operands = [t.split("#")[0] for t in _ID_RE.findall(rhs)]
        graph[res] = (rhs, [o for o in operands if o != res])
    return graph


def _closure(graph, seeds: List[str]) -> Set[str]:
    seen: Set[str] = set()
    stack = list(seeds)
    while stack:
        s = stack.pop()
        if s in seen or s not in graph:
            continue
        seen.add(s)
        stack.extend(graph[s][1])
    return seen


def overlap_report(mlir_text: str, kernel_marker: str = "tpu_custom_call") -> dict:
    """Analyze permute/kernel dataflow in an exported step.

    Returns ``n_permutes``, ``n_kernels``, and the two independence
    violations: ``permutes_consume_kernel`` (a collective transitively
    reads a kernel result — comm serialized behind compute) and
    ``kernels_consume_permutes`` (the kernel reads exchanged data — compute
    serialized behind comm)."""
    graph = build_graph(mlir_text)
    permutes = [k for k, (op, _) in graph.items() if "collective_permute" in op]
    kernels = [k for k, (op, _) in graph.items() if kernel_marker in op]
    perm_inputs = _closure(graph, [o for p in permutes for o in graph[p][1]])
    kernels_indep = [
        k
        for k in kernels
        if not _closure(graph, graph[k][1]).intersection(permutes)
    ]
    return {
        "n_permutes": len(permutes),
        "n_kernels": len(kernels),
        # a collective transitively reading a kernel result would serialize
        # comm behind compute
        "permutes_consume_kernel": bool(perm_inputs.intersection(kernels)),
        # kernels free to run concurrently with the permutes (for RK3 this
        # is substep 0; later substeps legitimately read exchanged data)
        "n_kernels_independent_of_permutes": len(kernels_indep),
    }


# -- collective census (bench_mpi_pack ablation accounting) ------------------

# HLO element sizes in bytes for the dtypes this framework traffics in
# (f8* are the fp8 wire-compression tier's carrier types).
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

COLLECTIVE_KINDS = (
    "collective-permute",
    "all-gather",
    "all-reduce",
    "all-to-all",
    "reduce-scatter",
    "collective-broadcast",
)

# `KIND(` right after the result type(s): matches both sync ops and the
# `-start` half of async pairs (`-done` consumes no extra interconnect).
_COLLECTIVE_OP_RE = re.compile(
    r"=\s*[^=]*?\b(" + "|".join(COLLECTIVE_KINDS) + r")(-start)?\("
)
# dtype token may carry interior digits (f8e4m3fn) — [a-z][a-z0-9]*
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_PAIR_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")
_GROUPS_RE = re.compile(r"replica_groups=\{((?:\{[\d,]*\},?)*)\}")


def _tensor_bytes(dtype: str, dims: str) -> int:
    n = _DTYPE_BYTES.get(dtype, 0)
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n


def collective_census(hlo_text: str) -> Dict[str, Tuple[int, int]]:
    """``{op kind: (count, bytes)}`` over a compiled (post-SPMD-partitioning)
    HLO module — the per-method data-movement accounting of the
    bench_mpi_pack ablation (reference: bin/bench_mpi_pack.cu:18-80).

    Scans every computation in the module (while-loop bodies and called
    computations included — the callee-aware discipline of
    :func:`_main_body`), so shard_map-lowered hand-written ppermutes and
    partitioner-synthesized collectives are counted identically. Counts are
    STATIC op instances: an op inside a fori_loop body counts once, so
    census a single-exchange program, not a fused loop, when comparing
    strategies.

    Bytes are the interconnect payload per op instance, summed per kind:
    the operand buffer is the per-shard payload; for ``collective-permute``
    it is multiplied by the number of ``source_target_pairs`` (each pair
    carries one payload across a link — the exact figure the ablation
    table wants); for gather/reduce/all-to-all kinds it is multiplied by
    the participant count in ``replica_groups`` (a first-order upper bound
    for ring/tree implementations). Async ``-start``/``-done`` pairs count
    once, at the start op."""
    out: Dict[str, Tuple[int, int]] = {}
    for ln in hlo_text.splitlines():
        m = _COLLECTIVE_OP_RE.search(ln)
        if not m:
            continue
        kind = m.group(1)
        # operand types sit between `KIND(` and the first `)` (shapes never
        # contain parens in HLO text)
        args = ln[m.end():].split(")", 1)[0]
        payload = sum(_tensor_bytes(d, dims) for d, dims in _SHAPE_RE.findall(args))
        pm = _PAIR_RE.search(ln)
        if kind == "collective-permute" and pm:
            fanout = pm.group(1).count("{")
        else:
            gm = _GROUPS_RE.search(ln)
            fanout = (
                sum(1 for t in re.split(r"[{},]", gm.group(1)) if t) if gm else 1
            )
        count, nbytes = out.get(kind, (0, 0))
        out[kind] = (count + 1, nbytes + payload * max(1, fanout))
    return out


# Ops in a compiled (post-optimization) HLO module that dispatch a device
# kernel: XLA's fused loops and the custom-call escape hatch (Mosaic
# kernels land as tpu_custom_call custom-calls). Elementwise ops that
# survive unfused still launch, but by the backends' own fusion pass they
# are the noise floor — the census is a LOWER bound used for pinning
# relative O(steps)-vs-O(chunks) shapes, not an absolute dispatch count.
_LAUNCH_OP_RE = re.compile(r"=\s*[^=]*?\b(fusion|custom-call)\(")


def kernel_launch_census(hlo_text: str) -> Dict[str, int]:
    """``{op kind: count}`` of kernel-launch ops (``fusion`` /
    ``custom-call``) over a compiled HLO module — the launch-count
    analogue of :func:`collective_census`, counted the same way: STATIC
    op instances across every computation (a fusion inside a while body
    counts once), so census a single-chunk program when comparing
    kernel variants. The persistent whole-chunk variant's pitch is this
    number's shape — O(chunks) dispatched programs instead of O(steps)
    (ops/persistent_stencil.py) — and the plan's
    ``launches_per_chunk`` prediction is conformance-audited against
    the measured host-dispatch count (analysis/verify_plan,
    scripts/ci_persistent_gate.py); this census is the compiled-module
    side of that evidence."""
    out: Dict[str, int] = {}
    for ln in hlo_text.splitlines():
        m = _LAUNCH_OP_RE.search(ln)
        if not m:
            continue
        out[m.group(1)] = out.get(m.group(1), 0) + 1
    return out


def collective_permute_pairs(hlo_text: str):
    """Every ``collective-permute``'s ``source_target_pairs``, one
    frozenset of (src, tgt) logical-device pairs per op instance, in
    module order — the placement-conformance auditor's raw material
    (analysis/verify_plan): logical ids index the computation's device
    assignment, i.e. the mesh's device order, so mapping a pair through
    ``mesh.devices.flatten()`` yields the physical link it rides."""
    out = []
    for ln in hlo_text.splitlines():
        m = _COLLECTIVE_OP_RE.search(ln)
        if not m or m.group(1) != "collective-permute":
            continue
        pm = _PAIR_RE.search(ln)
        if not pm:
            out.append(frozenset())
            continue
        pairs = re.findall(r"\{(\d+),(\d+)\}", pm.group(1))
        out.append(frozenset((int(a), int(b)) for a, b in pairs))
    return out


_STABLEHLO_OP_RE = re.compile(
    r'"stablehlo\.(collective_permute|all_gather|all_reduce|all_to_all|'
    r'reduce_scatter|collective_broadcast)"'
)
_STABLEHLO_RESULT_RE = re.compile(r"->\s*tensor<([0-9x]+)x([a-zA-Z0-9]+)>")
_STABLEHLO_PAIRS_RE = re.compile(r"source_target_pairs\s*=[^:]*:\s*tensor<(\d+)x2xi64>")
_STABLEHLO_DTYPE_BYTES = {
    "i1": 1, "i8": 1, "ui8": 1, "f8E4M3FN": 1, "f8E5M2": 1,
    "i16": 2, "ui16": 2, "f16": 2, "bf16": 2,
    "i32": 4, "ui32": 4, "f32": 4, "i64": 8, "ui64": 8, "f64": 8,
}


def stablehlo_wire_census(mlir_text: str) -> Dict[str, Tuple[int, int]]:
    """``{op kind: (count, bytes)}`` over a LOWERED (pre-backend-
    optimization) StableHLO module — what the program *asks* the wire to
    carry, counted like :func:`collective_census` (per-shard payload ×
    source_target pairs for permutes).

    Why a second census exists: backend optimization passes may rewrite
    payload dtypes — the CPU backend's float-normalization widens a bf16
    ``collective_permute`` back to f32 (bf16 is not a native CPU type),
    so a compiled-HLO census on the 8-device CPU mesh cannot see the
    bf16-on-the-wire compression that a TPU (native bf16) actually
    ships. This census reads the module BEFORE those passes: the
    wire-dtype the exchange requested, exact for the hand-written
    permute methods whose collectives exist pre-partitioning."""
    out: Dict[str, Tuple[int, int]] = {}
    for ln in mlir_text.splitlines():
        m = _STABLEHLO_OP_RE.search(ln)
        if not m:
            continue
        kind = m.group(1).replace("_", "-")
        rm_ = _STABLEHLO_RESULT_RE.search(ln)
        payload = 0
        if rm_:
            dims, dtype = rm_.group(1), rm_.group(2)
            payload = _STABLEHLO_DTYPE_BYTES.get(dtype, 0)
            for d in dims.split("x"):
                payload *= int(d)
        pm = _STABLEHLO_PAIRS_RE.search(ln)
        fanout = int(pm.group(1)) if pm else 1
        count, nbytes = out.get(kind, (0, 0))
        out[kind] = (count + 1, nbytes + payload * max(1, fanout))
    return out


def census_per_quantity(census: Dict[str, Tuple[int, int]],
                        quantities: int) -> Dict[str, Tuple[int, int]]:
    """Attribute a quantity-batched census back to logical per-quantity
    bytes: ``{kind: (count, bytes // Q)}``.

    With quantity batching (parallel/exchange.py) one collective carries a
    packed ``(Q, ...)`` carrier of every same-dtype quantity's slab, so a
    raw census reports Q quantities' bytes on each op. Dividing by the
    quantity count restores the per-quantity figure the reference's
    Allreduced per-method byte counters speak (src/stencil.cu:139-161) —
    what one quantity's halos cost on the wire — while the COUNT column
    stays the batched truth (the whole point: Q-independent). For an
    unbatched program the two accountings coincide at Q = 1 and differ by
    exactly the op-count factor otherwise."""
    q = max(1, int(quantities))
    return {k: (c, b // q) for k, (c, b) in census.items()}


def assert_overlap_independent(mlir_text: str, expect_permutes: int = None) -> dict:
    """Raise AssertionError unless the permutes and the kernel are mutually
    independent (the overlap-enabling dataflow)."""
    rep = overlap_report(mlir_text)
    assert rep["n_kernels"] >= 1, f"no stencil kernel found: {rep}"
    assert rep["n_permutes"] >= 1, f"no collective_permute found: {rep}"
    if expect_permutes is not None:
        assert rep["n_permutes"] == expect_permutes, rep
    assert not rep["permutes_consume_kernel"], (
        f"collective_permute depends on a stencil kernel: {rep}"
    )
    assert rep["n_kernels_independent_of_permutes"] >= 1, (
        f"every stencil kernel depends on collective_permute results: {rep}"
    )
    return rep
