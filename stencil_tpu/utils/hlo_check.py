"""Machine check of the comm/compute-overlap dataflow structure.

The TPU scheduler can only run a ``collective-permute`` concurrently with
the interior compute if neither depends on the other — the property the
reference achieves with streams + CPU polling (reference:
src/stencil.cu:1002-1186, bin/jacobi3d.cu:296-368) and this framework
achieves by construction (the fast-path kernel reads pre-exchange data).
No hardware can *demonstrate* the overlap without a real multi-chip slice
(BASELINE.md config 5), but the enabling dataflow property is checkable on
any host: export the ≥2-device step for the TPU platform
(``jax.export``), parse the StableHLO SSA graph, and verify that no
``collective_permute`` transitively consumes the stencil kernel's output
and the kernel consumes no ``collective_permute`` result.

Used by tests/test_overlap_hlo.py (the machine gate) via the subprocess
runner scripts/export_overlap_hlo.py, which is also the standalone entry.
"""

from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

_ID_RE = re.compile(r"%[A-Za-z0-9_]+")


def _func_bodies(mlir_text: str) -> List[Tuple[str, List[str]]]:
    """(header line, body lines) of every ``func.func`` in the module."""
    lines = mlir_text.splitlines()
    out: List[Tuple[str, List[str]]] = []
    header = None
    body: List[str] = []
    depth = 0
    for ln in lines:
        if header is None:
            if re.search(r"func\.func .*@\w+", ln):
                header = ln
                body = []
                depth = ln.count("{") - ln.count("}")
            continue
        depth += ln.count("{") - ln.count("}")
        body.append(ln)
        if depth <= 0:
            out.append((header, body))
            header = None
    return out


def _main_body(mlir_text: str) -> List[str]:
    """Lines of the function body holding the step's dataflow.

    On a current jax the shard_map'd step inlines into ``@main`` (as an
    ``sdy.manual_computation`` region); older releases lower shard_map to
    a CALL of a private callee, leaving ``@main`` without the collectives.
    Analyze ``@main`` when it contains them, otherwise the function with
    the most ``collective_permute`` ops (SSA ids are function-local, so
    the graph must never mix functions)."""
    funcs = _func_bodies(mlir_text)
    main = next(
        (b for h, b in funcs if re.search(r"@main\b", h)), []
    )
    if any("collective_permute" in ln for ln in main):
        return main
    best = max(
        funcs,
        key=lambda hb: sum("collective_permute" in ln for ln in hb[1]),
        default=(None, main),
    )
    if sum("collective_permute" in ln for ln in best[1]):
        return best[1]
    return main


def build_graph(mlir_text: str) -> Dict[str, Tuple[str, List[str]]]:
    """SSA graph of @main (including regions nested in it, e.g. the
    ``sdy.manual_computation`` a shard_map lowers to): result id ->
    (op line, operand ids on that line).

    Parsing is per-line: every op this check cares about
    (collective_permute, the Mosaic custom_call, slices/updates) is a
    single-line op. Multi-result ops (``%a:2 = ...``) are keyed by their
    base id; uses ``%a#1`` are normalized to ``%a``. Block arguments of
    nested regions terminate closures (their binding to outer operands is
    not tracked), which can only MISS dependence edges through region
    boundaries — acceptable because the step under test is a single
    straight-line iteration (no fori_loop), asserted by the caller seeing
    the expected op counts.
    """
    graph: Dict[str, Tuple[str, List[str]]] = {}
    for ln in _main_body(mlir_text):
        m = re.match(r"^\s*(%[A-Za-z0-9_]+)(?::\d+)?\s*=\s*(.*)$", ln)
        if not m:
            continue
        res, rhs = m.group(1), m.group(2)
        operands = [t.split("#")[0] for t in _ID_RE.findall(rhs)]
        graph[res] = (rhs, [o for o in operands if o != res])
    return graph


def _closure(graph, seeds: List[str]) -> Set[str]:
    seen: Set[str] = set()
    stack = list(seeds)
    while stack:
        s = stack.pop()
        if s in seen or s not in graph:
            continue
        seen.add(s)
        stack.extend(graph[s][1])
    return seen


def overlap_report(mlir_text: str, kernel_marker: str = "tpu_custom_call") -> dict:
    """Analyze permute/kernel dataflow in an exported step.

    Returns ``n_permutes``, ``n_kernels``, and the two independence
    violations: ``permutes_consume_kernel`` (a collective transitively
    reads a kernel result — comm serialized behind compute) and
    ``kernels_consume_permutes`` (the kernel reads exchanged data — compute
    serialized behind comm)."""
    graph = build_graph(mlir_text)
    permutes = [k for k, (op, _) in graph.items() if "collective_permute" in op]
    kernels = [k for k, (op, _) in graph.items() if kernel_marker in op]
    perm_inputs = _closure(graph, [o for p in permutes for o in graph[p][1]])
    kernels_indep = [
        k
        for k in kernels
        if not _closure(graph, graph[k][1]).intersection(permutes)
    ]
    return {
        "n_permutes": len(permutes),
        "n_kernels": len(kernels),
        # a collective transitively reading a kernel result would serialize
        # comm behind compute
        "permutes_consume_kernel": bool(perm_inputs.intersection(kernels)),
        # kernels free to run concurrently with the permutes (for RK3 this
        # is substep 0; later substeps legitimately read exchanged data)
        "n_kernels_independent_of_permutes": len(kernels_indep),
    }


def assert_overlap_independent(mlir_text: str, expect_permutes: int = None) -> dict:
    """Raise AssertionError unless the permutes and the kernel are mutually
    independent (the overlap-enabling dataflow)."""
    rep = overlap_report(mlir_text)
    assert rep["n_kernels"] >= 1, f"no stencil kernel found: {rep}"
    assert rep["n_permutes"] >= 1, f"no collective_permute found: {rep}"
    if expect_permutes is not None:
        assert rep["n_permutes"] == expect_permutes, rep
    assert not rep["permutes_consume_kernel"], (
        f"collective_permute depends on a stencil kernel: {rep}"
    )
    assert rep["n_kernels_independent_of_permutes"] >= 1, (
        f"every stencil kernel depends on collective_permute results: {rep}"
    )
    return rep
