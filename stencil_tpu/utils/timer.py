"""Phase timers and profiler range annotations.

TPU-native analogue of the reference's pass-through CUDA/MPI timers and
NVTX ranges (reference: include/stencil/rt.hpp:9-36,
include/stencil/timer.hpp:44-47, nvtxRangePush/Pop throughout
src/stencil.cu). On TPU the profiler annotation is
``jax.profiler.TraceAnnotation``; accumulated wall-clock buckets replace the
global ``timers::cudaRuntime``/``timers::mpi`` counters.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

# global accumulated seconds per named bucket (reference: timer.hpp:44-47)
buckets: dict[str, float] = defaultdict(float)


def reset() -> None:
    buckets.clear()


@contextlib.contextmanager
def timed(bucket: str):
    """Accumulate elapsed wall time into ``buckets[bucket]``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        buckets[bucket] += time.perf_counter() - t0


@contextlib.contextmanager
def trace_range(name: str):
    """Named profiler range (NVTX analogue: jax.profiler.TraceAnnotation).

    Only the annotation setup is guarded — a body exception must propagate
    (an ``except`` around the ``yield`` would swallow the throw and
    double-yield: "generator didn't stop after throw()")."""
    try:
        import jax.profiler

        ann = jax.profiler.TraceAnnotation(name)
    except Exception:
        ann = contextlib.nullcontext()
    with ann:
        yield


def report() -> str:
    """One-line bucket summary, the analogue of the reference's exit print
    of timers::cudaRuntime/timers::mpi (reference: bin/jacobi3d.cu:397-398)."""
    if not buckets:
        return "timers: (empty)"
    parts = [f"{k}={v:.3f}s" for k, v in sorted(buckets.items())]
    return "timers: " + " ".join(parts)


def time_fn(bucket: str):
    """Decorator: accumulate a function's wall time into a bucket."""

    def deco(fn):
        def wrapper(*args, **kwargs):
            with timed(bucket):
                return fn(*args, **kwargs)

        wrapper.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapper

    return deco


def chained_calls(call, chunk: int = 8):
    """Build a jitted timing loop of ``chunk + 1`` sequential invocations
    of ``call`` (one array argument -> one array result).

    The fori seed is a real invocation and each body input depends on the
    carry through a zero-scaled scalar, so XLA can neither hoist the
    loop-invariant call nor CSE the chain — every invocation executes, in
    order, even for pure (non-side-effecting) kernels. Returns
    ``(g, calls)``: time ``g(x)`` and divide by ``calls``. (One probe
    divided a 9-call chain by 8 and another relied on side-effect
    ordering alone — this helper is the single corrected idiom.)
    """
    import jax

    def f(x):
        def body(_, o):
            return call(x + o[(0,) * o.ndim] * 0.0)

        return jax.lax.fori_loop(0, chunk, body, call(x))

    return jax.jit(f), chunk + 1
