"""Benchmark statistics: min/max/avg/median/stddev and the trimean.

TPU-native re-implementation of the reference's Statistics helper
(reference: bin/statistics.hpp:6-19, bin/statistics.cpp). The *trimean*
(Tukey's (Q1 + 2*Q2 + Q3) / 4) is the canonical reported statistic for all
benchmarks, as in the reference.
"""

from __future__ import annotations

import math
from typing import Iterable


class Statistics:
    def __init__(self, values: Iterable[float] = ()):  # noqa: D401
        self._v: list[float] = sorted(float(v) for v in values)

    def insert(self, v: float) -> None:
        import bisect

        bisect.insort(self._v, float(v))

    def count(self) -> int:
        return len(self._v)

    def min(self) -> float:
        return self._v[0]

    def max(self) -> float:
        return self._v[-1]

    def avg(self) -> float:
        return sum(self._v) / len(self._v)

    def stddev(self) -> float:
        """Sample standard deviation (n-1 denominator, matching the
        reference; NaN for a single sample, bin/statistics.cpp)."""
        if len(self._v) < 2:
            return float("nan")
        m = self.avg()
        return math.sqrt(sum((v - m) ** 2 for v in self._v) / (len(self._v) - 1))

    def _quantile(self, q: float) -> float:
        """Linear-interpolated quantile over the sorted samples."""
        v = self._v
        if len(v) == 1:
            return v[0]
        pos = q * (len(v) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(v) - 1)
        frac = pos - lo
        return v[lo] * (1 - frac) + v[hi] * frac

    def med(self) -> float:
        return self._quantile(0.5)

    def trimean(self) -> float:
        """Tukey's trimean — the reference's headline statistic
        (reference: bin/statistics.hpp:17)."""
        return (self._quantile(0.25) + 2 * self._quantile(0.5) + self._quantile(0.75)) / 4

    def percentile(self, q: float) -> float:
        """The q-th percentile (0 <= q <= 100), linear-interpolated over
        the sorted samples — p50/p99 for tail-latency reporting (the
        multi-tenant campaign's step-latency legs)."""
        if not self._v:
            raise ValueError("percentile of an empty sample set")
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        return self._quantile(q / 100.0)


def percentile(values: Iterable[float], q: float) -> float:
    """Module-level convenience: ``Statistics(values).percentile(q)`` —
    the p50/p99 authority the campaign driver, apps/report.py's optional
    p99 span column, and bench.py's latency legs share (same linear
    interpolation as the trimean's quartiles)."""
    return Statistics(values).percentile(q)
