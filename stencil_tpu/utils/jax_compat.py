"""Compatibility shims for older jax releases.

The package is written against the current jax spelling of three APIs the
kernels and the distributed step depend on:

- ``jax.shard_map(f, mesh=..., in_specs=..., out_specs=..., check_vma=...)``
  (older releases only have ``jax.experimental.shard_map.shard_map`` with a
  ``check_rep`` argument and no vma machinery);
- ``jax.ShapeDtypeStruct(..., vma=...)`` — the varying-manual-axes
  annotation Pallas outputs need inside ``shard_map`` when vma checking
  exists (older releases have no ``vma`` kwarg, and nothing to annotate);
- ``pltpu.CompilerParams`` (older: ``pltpu.TPUCompilerParams``, without
  the ``has_side_effects`` field).

On an older jax, :func:`apply` installs equivalents at the public names so
every call site keeps the one modern spelling; on a current jax it is a
no-op.  The shims are *degraded* equivalents where the old API has no
counterpart: vma annotations are dropped (there is no vma checker to feed)
and ``shard_map`` runs with ``check_rep=False`` (the old replication
checker has no rules for ``pallas_call``/donated in-place updates, so
leaving it on rejects valid programs the vma checker accepts).

Also translated: ``jax.config.update("jax_num_cpu_devices", n)`` — the
apps' virtual-device flag — becomes the ``xla_force_host_platform_device_
count`` XLA flag when the config option does not exist.  Like the real
option, it only takes effect before the backend initializes.

Applied from ``stencil_tpu/__init__`` so plain ``import stencil_tpu``
(tests, apps, probe scripts, driver children) is enough.
"""

from __future__ import annotations

import inspect
import os


def apply() -> None:
    import jax

    # jax.export is a lazy submodule on some releases; utils/mosaic_traffic
    # relies on attribute access working after `import jax`
    try:
        import jax.export  # noqa: F401
    except ImportError:  # pragma: no cover - very old jax
        pass

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                      check_vma=True, **kwargs):
            # check_rep (the old checker) has no replication rules for
            # pallas_call or donated in-place aliasing, so it rejects valid
            # programs regardless of check_vma — run unchecked instead
            return _shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=False, **kwargs,
            )

        jax.shard_map = shard_map

    if "vma" not in inspect.signature(jax.ShapeDtypeStruct.__init__).parameters:
        _sds = jax.ShapeDtypeStruct

        class ShapeDtypeStruct(_sds):
            """ShapeDtypeStruct accepting (and dropping) the vma kwarg."""

            def __init__(self, shape, dtype, *, sharding=None,
                         weak_type=False, vma=None):
                super().__init__(
                    shape, dtype, sharding=sharding, weak_type=weak_type
                )

        jax.ShapeDtypeStruct = ShapeDtypeStruct

    from jax.experimental.pallas import tpu as pltpu

    if not hasattr(pltpu, "CompilerParams"):
        _params = pltpu.TPUCompilerParams
        _known = set(inspect.signature(_params.__init__).parameters)

        def CompilerParams(**kwargs):
            # drop fields the old dataclass lacks (has_side_effects: kernel
            # liveness is carried by input_output_aliases + used outputs)
            return _params(**{k: v for k, v in kwargs.items() if k in _known})

        pltpu.CompilerParams = CompilerParams

    try:
        jax.config.jax_num_cpu_devices  # noqa: B018 - existence probe
    except AttributeError:
        _update = jax.config.update

        def update(name, value):
            if name == "jax_num_cpu_devices":
                if value and value > 0:
                    flags = os.environ.get("XLA_FLAGS", "")
                    if "xla_force_host_platform_device_count" not in flags:
                        os.environ["XLA_FLAGS"] = (
                            flags
                            + f" --xla_force_host_platform_device_count={value}"
                        ).strip()
                return
            return _update(name, value)

        jax.config.update = update
