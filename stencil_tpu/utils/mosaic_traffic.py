"""Static DMA-traffic accounting from compiled Mosaic kernels.

The outage-proof way to keep the performance story honest (VERDICT r4
item 6): instead of quoting roofline prose, lower the Pallas kernels for
the TPU platform (``jax.export`` runs the full Mosaic pipeline without
hardware), capture the TPU-dialect module each ``pallas_call`` dumps, and
read the ``tpu.enqueue_dma`` ops back — every DMA's direction, extent and
conditionality is statically visible. Tests then assert the per-grid-step
byte movement of the production kernels (the input-amplification and
1/k-traffic claims in BASELINE.md) the same way ``hlo_check.py`` pins the
overlap dataflow.

This is the analogue of the reference's Allreduced per-method byte
counters (reference: src/stencil.cu:139-161,620-627) — except derived
from the compiled artifact rather than incremented at runtime.
"""

from __future__ import annotations

import contextlib
import io
import re
from dataclasses import dataclass
from math import prod
from typing import Callable, List, Sequence, Tuple

_MARKER = "The Mosaic module for pallas_call kernel at "

# string literals must not contribute to region-brace counting (MLIR
# sym_name / location attributes may contain braces)
_STRLIT = re.compile(r'"(?:[^"\\]|\\.)*"')

_ITEMSIZE = {"f32": 4, "f64": 8, "i32": 4, "bf16": 2, "f16": 2, "i8": 1, "i64": 8}

_MEMREF = re.compile(
    r"memref<((?:\d+x)+)(\w+), #tpu\.memory_space<(\w+)>>"
)
_DMA = re.compile(
    r"tpu\.enqueue_dma\s+source\((.*?)\)\s+target\((.*?)\)\s+target_semaphore"
)
# older Mosaic prints the GENERIC MLIR form instead:
#   "tpu.enqueue_dma"(%a, %b, %sem) <{...}> : (memref<src>, memref<dst>, ...)
# operand order is the same (source, then target); types carry the spaces
_DMA_GENERIC = re.compile(r'"tpu\.enqueue_dma"\(.*?\).*?:\s*\((.*)\)')
_BOUNDS = re.compile(r"iteration_bounds = array<i64: ([0-9, ]+)>")


@dataclass(frozen=True)
class DmaOp:
    """One ``tpu.enqueue_dma`` in a kernel body."""

    src_space: str  # 'any' == HBM operand, 'vmem'/'smem' == on-chip
    dst_space: str
    shape: Tuple[int, ...]
    itemsize: int
    if_depth: int  # enclosing scf.if regions; 0 = issued every grid step
    loop_depth: int  # enclosing scf.for/while regions (0 in these kernels)

    @property
    def nbytes(self) -> int:
        return prod(self.shape) * self.itemsize

    @property
    def is_input(self) -> bool:
        """HBM -> VMEM."""
        return self.src_space == "any" and self.dst_space != "any"

    @property
    def is_output(self) -> bool:
        """VMEM -> HBM."""
        return self.dst_space == "any" and self.src_space != "any"


@dataclass
class KernelTraffic:
    """DMA inventory of one compiled Pallas kernel."""

    name: str  # "<basename>:<line>" of the pallas_call site
    grid: Tuple[int, ...]  # iteration_bounds
    dmas: List[DmaOp]

    @property
    def steps(self) -> int:
        return prod(self.grid) if self.grid else 1

    def input_bytes(self, unconditional_only: bool = False) -> int:
        """Sum of HBM->VMEM bytes enqueued in one kernel-body pass."""
        return sum(
            d.nbytes
            for d in self.dmas
            if d.is_input and (d.if_depth == 0 or not unconditional_only)
        )

    def output_bytes(self, unconditional_only: bool = False) -> int:
        return sum(
            d.nbytes
            for d in self.dmas
            if d.is_output and (d.if_depth == 0 or not unconditional_only)
        )

    def inputs(self) -> List[DmaOp]:
        return [d for d in self.dmas if d.is_input]

    def outputs(self) -> List[DmaOp]:
        return [d for d in self.dmas if d.is_output]

    def report(self) -> dict:
        """JSON-friendly summary (what scripts/export_traffic.py prints)."""
        return {
            "name": self.name,
            "grid": list(self.grid),
            "dmas": [
                {
                    "dir": "in" if d.is_input else ("out" if d.is_output else "local"),
                    "shape": list(d.shape),
                    "bytes": d.nbytes,
                    "if_depth": d.if_depth,
                    "loop_depth": d.loop_depth,
                }
                for d in self.dmas
            ],
        }


def _parse_ref(txt: str):
    m = _MEMREF.search(txt)
    if not m:
        return None
    dims = tuple(int(t) for t in m.group(1).split("x") if t)
    dtype = m.group(2)
    return dims, _ITEMSIZE.get(dtype, 4), m.group(3)


def _parse_module(name: str, lines: Sequence[str]) -> KernelTraffic:
    grid: Tuple[int, ...] = ()
    dmas: List[DmaOp] = []
    # region stack: 'if' (scf.if/else region) or 'op' (anything else).
    # Attribute dicts open and close braces on the same line, so only the
    # NET brace delta of a line changes the stack. Braces are counted on
    # the line with its string literals stripped — braces inside MLIR
    # string attrs (sym_name, location strings) would otherwise silently
    # skew the if/loop DMA attribution (ADVICE r5 #1).
    stack: List[str] = []
    opened = False  # the module op's own region has been entered
    for ln in lines:
        b = _BOUNDS.search(ln)
        if b:
            grid = tuple(int(t) for t in b.group(1).replace(" ", "").split(","))
        if "tpu.enqueue_dma" in ln:
            m = _DMA.search(ln)
            if m:
                src = _parse_ref(m.group(1))
                dst = _parse_ref(m.group(2))
            else:
                # generic-form printer (older Mosaic): operand memrefs live
                # in the trailing type signature, source first, target next
                g = _DMA_GENERIC.search(ln)
                refs = _MEMREF.findall(g.group(1)) if g else []
                src = dst = None
                if len(refs) >= 2:
                    src, dst = (
                        (
                            tuple(int(t) for t in r[0].split("x") if t),
                            _ITEMSIZE.get(r[1], 4),
                            r[2],
                        )
                        for r in refs[:2]
                    )
            if src is None or dst is None:
                # an uncounted DMA would make the byte assertions pass
                # vacuously — fail loudly instead (e.g. a future Mosaic
                # printing strided/dynamic memref layouts)
                raise ValueError(f"unparseable enqueue_dma operands: {ln.strip()}")
            dmas.append(
                DmaOp(
                    src_space=src[2],
                    dst_space=dst[2],
                    shape=dst[0],
                    itemsize=dst[1],
                    if_depth=sum(1 for f in stack if f == "if"),
                    loop_depth=sum(1 for f in stack if f == "loop"),
                )
            )
        bare = _STRLIT.sub('""', ln)
        net = bare.count("{") - bare.count("}")
        if net > 0:
            if "scf.if" in bare or "} else {" in bare:
                kind = "if"
            elif "scf.for" in bare or "scf.while" in bare:
                kind = "loop"
            else:
                kind = "op"
            stack.extend([kind] * net)
            opened = True
        elif net < 0:
            if -net > len(stack):
                raise ValueError(
                    f"unbalanced region braces in Mosaic dump of {name}: "
                    f"{-net} closes against a {len(stack)}-deep stack"
                )
            del stack[net:]
        # '} else {' with net == 0: the closed and opened regions are both
        # arms of the same scf.if — the stack is already correct.
        if opened and not stack:
            break  # top-level 'module {' closed; trailing text is not ours
    if not opened or stack:
        # a drifted stack would mis-attribute every subsequent DMA's
        # conditionality — refuse instead of returning skewed counts
        raise ValueError(
            f"Mosaic dump of {name} ended with an unbalanced region stack "
            f"(opened={opened}, depth={len(stack)})"
        )
    return KernelTraffic(name=name, grid=grid, dmas=dmas)


def parse_mosaic_dumps(text: str) -> List[KernelTraffic]:
    """Split a captured debug stream into per-kernel traffic records."""
    out: List[KernelTraffic] = []
    chunks = text.split(_MARKER)[1:]
    for chunk in chunks:
        lines = chunk.splitlines()
        # first line: "<path>:<line>:"
        loc = lines[0].rstrip(":")
        name = "/".join(loc.split("/")[-1:])
        # module body ends when the top-level 'module @kernel {' closes;
        # passing trailing text is harmless (no enqueue_dma outside).
        out.append(_parse_module(name, lines[1:]))
    return out


_capture_active = False


def capture_traffic(build: Callable[[], tuple]) -> List[KernelTraffic]:
    """Lower a Pallas-using function for the TPU platform and return the
    DMA inventory of every kernel it contains.

    ``build()`` must CONSTRUCT the kernels (pallas_call must run under the
    patch so the debug dump is enabled) and return ``(fn, args)``; the
    function is then jitted and exported for ``platforms=["tpu"]``.

    Process-global side effects: for the duration of build() + export this
    patches ``pl.pallas_call`` (forcing ``debug=True`` on every kernel
    constructed anywhere in the process) and redirects ALL of stdout into
    the capture buffer. Nested or concurrent use in one process would
    force debug onto foreign kernels and swallow their output, so reentry
    raises ``RuntimeError`` — run concurrent captures in subprocesses (the
    pattern scripts/export_traffic.py uses).
    """
    import jax
    from jax.experimental import pallas as pl

    global _capture_active
    if _capture_active:
        raise RuntimeError(
            "capture_traffic is not reentrant: it patches the process-global "
            "pl.pallas_call and redirects stdout; run concurrent captures in "
            "subprocesses"
        )
    _capture_active = True
    orig = pl.pallas_call

    def patched(*a, **k):
        k["debug"] = True
        return orig(*a, **k)

    buf = io.StringIO()
    pl.pallas_call = patched
    try:
        with contextlib.redirect_stdout(buf):
            fn, args = build()
            jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
    finally:
        pl.pallas_call = orig
        _capture_active = False
    return parse_mosaic_dumps(buf.getvalue())
