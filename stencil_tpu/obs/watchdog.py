"""The revival watcher: supervise stall-prone measurement children.

The reference keeps its long benchmark campaigns alive with babysitting
shell scripts; this repo's analogue problem is the tunneled TPU platform,
whose plugin can stall ``jax.devices()`` indefinitely or die
mid-``device_put`` (BENCH round-3 artifact, rc=1). ``bench.py`` round 4
grew a bespoke accel/accel-retry/cpu/static ladder of timed-out
subprocesses; this module is that logic made reusable and testable
(ROADMAP item 6's "revival watcher", VERDICT r5 "Next" #8).

Two layers:

- :func:`supervise` — run ONE child under two deadlines: a total wall
  budget (``timeout_s``) and an optional heartbeat deadline
  (``heartbeat_timeout_s``). The supervisor hands the child a heartbeat
  file path via the ``STENCIL_HEARTBEAT_FILE`` env var; the child's
  telemetry recorder (stencil_tpu.obs.telemetry) touches that file on
  every record and from a background thread. A fresh file mtime is a
  beat; staleness beyond the deadline is a STALL (killed early, long
  before the total budget), process exit is ok/crash, budget exhaustion
  is a TIMEOUT. Heartbeats catch hard wedges (a native call that stops
  the interpreter also stops the beat thread); a wedge that keeps the
  interpreter breathing still falls to the total budget — the two
  deadlines are deliberately layered.
- :class:`Revival` — a bounded-budget ladder of such attempts with
  backoff, a result parser, and per-attempt log archiving, so a driver
  entry point is a plan (name, cmd, timeout) list instead of copy-pasted
  subprocess plumbing.

This module is PURE STDLIB and must stay importable without the
``stencil_tpu`` package: ``bench.py``'s parent process loads it by file
path (``importlib``) precisely so the parent never imports jax — the
wedge being supervised lives in JAX backend init.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

# Contract with stencil_tpu.obs.telemetry (the writer side): the child
# process touches the file named by this env var; only the mtime matters.
HEARTBEAT_FILE_ENV = "STENCIL_HEARTBEAT_FILE"
HEARTBEAT_INTERVAL_ENV = "STENCIL_HEARTBEAT_INTERVAL_S"

# Outcomes, in the order the layered deadlines fire.
OK = "ok"
CRASH = "crash"          # child exited nonzero on its own
STALL = "stall"          # heartbeat went stale; child was killed
TIMEOUT = "timeout"      # total budget exhausted; child was killed
NO_RESULT = "no-result"  # exited 0 but the parser found no payload
FAULT = "fault"          # child aborted via the fault/recovery ladder

# Contract with stencil_tpu.fault.recover (which imports THIS constant —
# watchdog.py must stay importable without the package): a child that
# exhausted its rollback budget exits with this rc, distinct from a stall
# kill (rc None), a generic crash, and the ckpt kill hook's 17, so the
# revival ladder can tell "numerics are broken" from "process died".
FAULT_RC = 43


@dataclass
class Attempt:
    """One supervised child run, as archived evidence."""

    name: str
    outcome: str
    rc: Optional[int]  # None when the supervisor killed the child
    seconds: float
    stdout: str
    stderr_tail: str
    log_path: Optional[str] = None  # archived combined log, if archiving
    metrics_log_path: Optional[str] = None  # archived metrics JSONL (evidence)
    heartbeat_note: Optional[dict] = None  # last beat's JSON payload (stalls)

    def summary(self) -> dict:
        return {
            "name": self.name,
            "outcome": self.outcome,
            "rc": self.rc,
            "seconds": round(self.seconds, 1),
            "log": self.log_path,
            "metrics": self.metrics_log_path,
        }


def _mtime(path: str) -> Optional[float]:
    try:
        return os.stat(path).st_mtime
    except OSError:
        return None


def read_heartbeat_note(path: str) -> Optional[dict]:
    """The beat file's optional JSON payload (telemetry writes
    ``{"t", "step"?, "span"?}``) — None for a missing file or a
    non-JSON body (a hand-touched beat is still a valid beat: liveness
    is mtime-only by contract, the payload is a bonus)."""
    try:
        with open(path) as f:
            note = json.loads(f.read(4096))
    except (OSError, ValueError):
        return None
    return note if isinstance(note, dict) else None


def format_heartbeat_note(note: Optional[dict]) -> str:
    """One human phrase from a beat payload: "at step 412 in exchange"."""
    if not note:
        return ""
    parts = []
    if isinstance(note.get("step"), int):
        parts.append(f"at step {note['step']}")
    if isinstance(note.get("span"), str) and note["span"]:
        parts.append(f"in {note['span']}")
    return " ".join(parts)


def _kill(proc: subprocess.Popen, grace_s: float) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=grace_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            pass  # unreapable; the OS keeps the zombie, we keep the budget


def supervise(
    cmd: Sequence[str],
    *,
    timeout_s: float,
    heartbeat_timeout_s: Optional[float] = None,
    first_beat_grace_s: Optional[float] = None,
    env: Optional[dict] = None,
    name: str = "child",
    poll_s: float = 0.25,
    archive_dir: Optional[str] = None,
    kill_grace_s: float = 5.0,
    cwd: Optional[str] = None,
    stderr_tail_bytes: int = 4000,
    fault_rc: Optional[int] = FAULT_RC,
    metrics_path: Optional[str] = None,
) -> Attempt:
    """Run ``cmd`` under the layered deadlines and return the Attempt.

    stdout/stderr go to temp FILES, not pipes: a child killed mid-write
    loses pipe buffers, but file contents survive the kill (the round-4
    bench.py lesson). ``heartbeat_timeout_s=None`` disables stall
    detection (total budget only). ``first_beat_grace_s`` is the deadline
    for the FIRST beat (interpreter + jax import are slow on small
    hosts); it defaults to ``max(heartbeat_timeout_s, 60)``.

    A child exit code equal to ``fault_rc`` is classified as the FAULT
    outcome (the fault/recovery ladder's rollback-exhausted abort) rather
    than a generic CRASH. On any non-OK outcome, when archiving is on and
    the child wrote a metrics JSONL (``metrics_path``, defaulting to the
    ``STENCIL_METRICS_OUT`` / ``STENCIL_BENCH_METRICS_OUT`` entries of
    the child's env), the metrics file is archived next to the log — a
    post-mortem gets telemetry, not just stdout.
    """
    env = dict(env if env is not None else os.environ)
    if metrics_path is None:
        metrics_path = (env.get("STENCIL_METRICS_OUT")
                        or env.get("STENCIL_BENCH_METRICS_OUT"))
    hb_dir = None
    hb_path = None
    if heartbeat_timeout_s is not None:
        hb_dir = tempfile.mkdtemp(prefix="stencil-hb-")
        hb_path = os.path.join(hb_dir, "beat")
        env[HEARTBEAT_FILE_ENV] = hb_path
        # overwrite, never setdefault: a nested supervision must beat at
        # THIS deadline's cadence, not an outer (possibly slower) one's
        env[HEARTBEAT_INTERVAL_ENV] = str(max(0.2, heartbeat_timeout_s / 4))
        if first_beat_grace_s is None:
            first_beat_grace_s = max(heartbeat_timeout_s, 60.0)

    t0 = time.monotonic()
    outcome = OK
    rc: Optional[int] = None
    hb_note: Optional[dict] = None
    with tempfile.TemporaryFile(mode="w+") as out, \
            tempfile.TemporaryFile(mode="w+") as err:
        proc = subprocess.Popen(cmd, stdout=out, stderr=err, env=env, cwd=cwd)
        try:
            while True:
                rc = proc.poll()
                if rc is not None:
                    if rc == 0:
                        outcome = OK
                    elif fault_rc is not None and rc == fault_rc:
                        outcome = FAULT
                    else:
                        outcome = CRASH
                    break
                elapsed = time.monotonic() - t0
                if elapsed > timeout_s:
                    outcome = TIMEOUT
                    print(
                        f"[watchdog] {name} timed out after {elapsed:.0f}s "
                        f"(budget {timeout_s:.0f}s); killing",
                        file=sys.stderr, flush=True,
                    )
                    _kill(proc, kill_grace_s)
                    break
                if hb_path is not None:
                    mt = _mtime(hb_path)
                    now = time.time()
                    stale = (
                        (mt is None and elapsed > first_beat_grace_s)
                        or (mt is not None and now - mt > heartbeat_timeout_s)
                    )
                    if stale:
                        outcome = STALL
                        age = "never beat" if mt is None else f"{now - mt:.0f}s stale"
                        # quote the beat payload's progress note so the
                        # report says WHERE, not just how stale
                        hb_note = read_heartbeat_note(hb_path)
                        where = format_heartbeat_note(hb_note)
                        print(
                            f"[watchdog] {name} stalled"
                            + (f" {where}" if where else "")
                            + f" (heartbeat {age}, "
                            f"deadline {heartbeat_timeout_s:.0f}s) after "
                            f"{elapsed:.0f}s; killing",
                            file=sys.stderr, flush=True,
                        )
                        _kill(proc, kill_grace_s)
                        break
                time.sleep(poll_s)
        finally:
            if proc.poll() is None:
                _kill(proc, kill_grace_s)
        seconds = time.monotonic() - t0
        out.seek(0)
        stdout = out.read()
        err.seek(0)
        stderr = err.read()

    if hb_dir is not None:
        for p in (hb_path, hb_dir):
            try:
                os.remove(p) if p == hb_path else os.rmdir(p)
            except OSError:
                pass

    att = Attempt(
        name=name,
        outcome=outcome,
        rc=rc,
        seconds=seconds,
        stdout=stdout,
        stderr_tail=stderr[-stderr_tail_bytes:],
        log_path=None,
        heartbeat_note=hb_note,
    )
    if archive_dir:
        # sub-second suffix: back-to-back retries of one name must not
        # overwrite each other's archived evidence
        stamp = (time.strftime("%Y%m%dT%H%M%S")
                 + f"-{time.time_ns() % 10**6:06d}")
        try:
            os.makedirs(archive_dir, exist_ok=True)
            att.log_path = os.path.join(archive_dir, f"{name}-{stamp}.log")
            with open(att.log_path, "w") as f:
                f.write(f"# attempt={name} outcome={outcome} rc={rc} "
                        f"seconds={seconds:.1f}\n")
                f.write("# --- stdout ---\n")
                f.write(stdout)
                f.write("\n# --- stderr ---\n")
                f.write(stderr)
        except OSError as e:  # archiving must never eat the measurement
            print(f"[watchdog] log archive failed: {e}", file=sys.stderr)
            att.log_path = None
        # evidence bundle: on a bad outcome, the child's metrics JSONL is
        # archived beside the log (a copy, not a move — a later resumed
        # child may still be appending to the live file)
        if (outcome != OK and metrics_path
                and os.path.isfile(metrics_path)):
            try:
                dest = os.path.join(archive_dir,
                                    f"{name}-{stamp}.metrics.jsonl")
                shutil.copyfile(metrics_path, dest)
                att.metrics_log_path = dest
            except OSError as e:
                print(f"[watchdog] metrics archive failed: {e}",
                      file=sys.stderr)
    return att


@dataclass
class Revival:
    """A bounded-budget retry ladder over supervised children.

    ``parse(stdout) -> payload | None`` extracts the measurement result;
    an attempt that exits 0 without a parseable payload is recorded as
    ``no-result`` (the ladder continues). The overall budget is the
    Revival's, not per-attempt: ``attempt()`` clamps each timeout to the
    time remaining and refuses attempts shorter than ``min_attempt_s``.
    """

    budget_s: float
    parse: Callable[[str], Optional[object]]
    archive_dir: Optional[str] = None
    min_attempt_s: float = 10.0
    attempts: List[Attempt] = field(default_factory=list)
    _t0: float = field(default_factory=time.monotonic)

    def remaining(self) -> float:
        return self.budget_s - (time.monotonic() - self._t0)

    def attempt(
        self,
        name: str,
        cmd: Sequence[str],
        *,
        timeout_s: float,
        heartbeat_timeout_s: Optional[float] = None,
        first_beat_grace_s: Optional[float] = None,
        env: Optional[dict] = None,
        cwd: Optional[str] = None,
        floor_timeout_s: float = 0.0,
    ) -> Optional[object]:
        """Run one rung of the ladder; return the parsed payload or None.

        ``floor_timeout_s`` guarantees a minimal try even when the budget
        is spent (the last-resort fallback must not be starved of its
        shot at producing the result line)."""
        timeout_s = max(floor_timeout_s, min(timeout_s, self.remaining()))
        if timeout_s < self.min_attempt_s:
            return None
        att = supervise(
            cmd,
            timeout_s=timeout_s,
            heartbeat_timeout_s=heartbeat_timeout_s,
            first_beat_grace_s=first_beat_grace_s,
            env=env,
            name=name,
            archive_dir=self.archive_dir,
            cwd=cwd,
        )
        payload = self.parse(att.stdout) if att.stdout else None
        if payload is None and att.outcome == OK:
            att.outcome = NO_RESULT
        self.attempts.append(att)
        if payload is None:
            print(
                f"[watchdog] {name} produced no result "
                f"(outcome={att.outcome}, rc={att.rc}); stderr tail:\n"
                f"{att.stderr_tail[-2000:]}",
                file=sys.stderr, flush=True,
            )
        return payload

    def backoff(self, seconds: float, floor_s: float = 0.0) -> None:
        """Sleep between rungs, never past the budget (keep ``floor_s`` in
        reserve for the remaining rungs)."""
        time.sleep(min(seconds, max(0.0, self.remaining() - floor_s)))

    def report(self) -> List[dict]:
        return [a.summary() for a in self.attempts]
